"""Per-request trace spans: trace ids, pluggable event sinks, JSONL.

A *span record* is a flat JSON-serializable dict describing one unit of
traced work — a served request (``kind="request"``), a dispatched batch
(``kind="batch"``), or a tool-level measurement. The serving engine
mints a :func:`new_trace_id` at ``Engine.submit()`` and threads it
through the request's whole life; the batch record carries the trace ids
of its riders so a JSONL file can be joined both ways
(docs/observability.md has the full schema).

Sinks are deliberately tiny: anything with an ``emit(dict)`` method
works. The two shipped sinks are :class:`JsonlSink` (append one JSON
object per line, the interchange format tools/serving_bench.py and
tools/latency_profile.py consume) and :class:`ListSink` (in-memory, for
tests and ad-hoc notebooks). Telemetry must never take down the
instrumented path, so emitters are expected to call through
:func:`safe_emit` — a sink that raises is silenced (and counted on the
default registry).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Iterator, List, Optional

from raft_tpu.obs import metrics as _metrics

__all__ = [
    "current_trace",
    "new_trace_id",
    "trace_scope",
    "JsonlSink",
    "ListSink",
    "NullSink",
    "RingSink",
    "safe_emit",
    "timed_span",
    "read_jsonl",
]

_SINK_ERRORS = _metrics.REGISTRY.counter(
    "raft_tpu_obs_sink_errors_total",
    "Span records dropped because a sink's emit() raised.")


def new_trace_id() -> str:
    """64-bit random hex id (Dapper-style width; 16 chars). os.urandom is
    one syscall — microseconds, fine at serving request rates."""
    return os.urandom(8).hex()


_CURRENT = threading.local()


def current_trace() -> Optional[str]:
    """The trace id of the work this thread is currently executing, or
    None. Set by the serving engine around the device call so deep
    emitters (the tiered arena's ``tier_fetch`` spans) can tag their
    records with the requesting trace without plumbing an argument
    through every search signature."""
    return getattr(_CURRENT, "trace", None)


@contextlib.contextmanager
def trace_scope(trace_id: Optional[str]) -> Iterator[None]:
    """Bind :func:`current_trace` for the dynamic extent of a block
    (re-entrant: restores the previous binding on exit)."""
    prev = getattr(_CURRENT, "trace", None)
    _CURRENT.trace = trace_id
    try:
        yield
    finally:
        _CURRENT.trace = prev


class NullSink:
    """Discards everything; the disabled-telemetry stand-in."""

    def emit(self, record: dict) -> None:
        pass


class ListSink:
    """Accumulates records in memory (thread-safe). ``records`` returns a
    copy, so tests can reconcile while the engine is still emitting."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._records: List[dict] = []  # guarded_by: _lock

    def emit(self, record: dict) -> None:
        with self._lock:
            self._records.append(record)

    @property
    def records(self) -> List[dict]:
        with self._lock:
            return list(self._records)

    def by_kind(self, kind: str) -> List[dict]:
        return [r for r in self.records if r.get("kind") == kind]

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


class RingSink:
    """Bounded in-memory ring of the last ``capacity`` span records —
    the flight recorder's tape. Unlike :class:`ListSink` it can run
    forever in a serving process: memory is O(capacity) no matter how
    many spans flow through. ``emit`` is a deque append under a lock
    (the deque's own maxlen does the eviction), cheap enough to tee
    every engine span through unconditionally.

    Optionally tees to ``inner`` (the user's configured sink) so
    installing the recorder never displaces existing telemetry; the
    inner emit rides through :func:`safe_emit` and cannot poison the
    ring."""

    def __init__(self, capacity: int = 512, inner=None) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.inner = inner
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._emitted = 0  # guarded_by: _lock

    def emit(self, record: dict) -> None:
        with self._lock:
            self._ring.append(record)
            self._emitted += 1
            # tee under the lock: the inner sink sees records in the
            # same order the ring does, so a frozen bundle's tail is a
            # suffix of the inner sink's stream (two emitters racing
            # outside the lock could cross-order the two sinks)
            if self.inner is not None:
                safe_emit(self.inner, record)

    @property
    def records(self) -> List[dict]:
        """Oldest-first copy of the tape."""
        with self._lock:
            return list(self._ring)

    @property
    def emitted(self) -> int:
        """Total records ever emitted (dropped ones included)."""
        with self._lock:
            return self._emitted

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._emitted - len(self._ring))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class JsonlSink:
    """Appends one JSON object per line to ``path``. Writes are serialized
    under a lock and flushed per record — span rates are batch/request
    scale (hundreds per second), not per-op, so durability wins over
    buffering. Use as a context manager or call ``close()``."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a")  # guarded_by: _lock

    def emit(self, record: dict) -> None:
        line = json.dumps(record, sort_keys=True, default=str)
        with self._lock:
            if self._f is None:
                return
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "JsonlSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def safe_emit(sink, record: dict) -> None:
    """Emit ``record`` on ``sink`` (None is a no-op); a raising sink is
    counted and silenced — telemetry never fails the serving path."""
    if sink is None:
        return
    try:
        sink.emit(record)
    except Exception:
        _SINK_ERRORS.inc()


@contextlib.contextmanager
def timed_span(sink, kind: str, **fields) -> Iterator[dict]:
    """Context manager: time the body and emit one span record with
    ``duration_ms`` (and ``error`` on exception, which propagates). The
    yielded dict is the record-in-progress — add fields freely."""
    rec = {"kind": kind, "trace_id": fields.pop("trace_id", new_trace_id())}
    rec.update(fields)
    t0 = time.perf_counter()
    try:
        yield rec
    except BaseException as e:
        rec["error"] = f"{type(e).__name__}: {e}"
        raise
    finally:
        rec["duration_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        safe_emit(sink, rec)


def read_jsonl(path: str, kind: Optional[str] = None) -> List[dict]:
    """Load span records back from a JSONL file, optionally filtered by
    ``kind``. Tolerates a torn final line (a crashed writer)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out
