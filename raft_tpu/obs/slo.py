"""Declarative SLOs evaluated at scrape time into error-budget burn rates.

The serving stack measures everything an SLO needs — typed request
outcomes (``raft_tpu_serving_requests_total``), latency histograms
(``raft_tpu_serving_total_seconds``), and, with shadow sampling on,
online recall (``raft_tpu_online_recall``). This module closes the last
mile: a declarative :class:`SLO` list on the engine config, evaluated
lazily (every read recomputes from the registry, the same convention as
every derived gauge in this repo) into

- ``raft_tpu_slo_burn_rate{engine,slo}`` — how many times faster than
  "exactly at objective" the error budget is being spent over the
  current window. 1.0 = spending the budget exactly; <1 healthy; the
  Google SRE fast-burn alerting convention (a 14.4x burn exhausts a
  30-day budget in ~2 days).
- ``raft_tpu_slo_budget_remaining{engine,slo}`` — ``max(0, 1 - burn)``,
  the window's remaining budget fraction.
- ``GET /slo`` (obs.httpd) — the :meth:`SLOMonitor.report` JSON doc.

Burn-rate math per kind (docs/observability.md SLO catalog):

- ``availability``: bad = failed + shed_deadline + rejected_* over the
  window; burn = (bad / (good + bad)) / (1 - objective).
- ``latency_p99``: fraction of windowed request latencies over
  ``threshold_ms`` (bucket-interpolated from the histogram), divided by
  the allowed fraction (1 - objective, e.g. 0.01 for a p99 target).
- ``recall_floor``: worst current ``raft_tpu_online_recall`` window
  across (family, k, bucket); burn = (1 - recall) / (1 - objective).
  No shadow samples yet → no data → burn 0 (never alert on silence;
  the shadow shed counters are the guard against silent silence).

Windowing is by baseline snapshot: counters/histograms diff against a
baseline re-taken every ``window_s``. A burn crossing ``fast_burn``
fires ``on_fast_burn(slo_name, burn)`` once per excursion (re-armed
when the burn drops back under) — the Engine wires this to its
rate-limited flight-recorder auto-dump, so the moments that spend the
budget fastest are the ones with a captured span tape.

Layering: registry-only (no serving import); the Engine hands the
monitor its engine label and callbacks.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from raft_tpu.obs import metrics as _metrics

__all__ = ["SLO", "SLOMonitor", "SLO_KINDS"]

SLO_KINDS = ("availability", "latency_p99", "recall_floor")

#: availability's bad-outcome events (requests_total ``event`` labels);
#: ``cancelled`` is excluded — a client abandoning its future is not a
#: serving failure
_BAD_EVENTS = ("failed", "shed_deadline", "rejected_overload",
               "rejected_breaker")


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declarative objective.

    ``objective`` is the good fraction for availability (e.g. 0.999)
    and latency (e.g. 0.99 = a p99 target), and the floor itself for
    ``recall_floor`` (e.g. 0.95). ``threshold_ms`` applies to
    ``latency_p99`` only. ``fast_burn`` is the burn-rate multiple whose
    crossing triggers the flight-recorder dump (14.0 ≈ the SRE
    2-day-budget-exhaustion pace)."""

    name: str
    kind: str
    objective: float
    threshold_ms: float = 0.0
    fast_burn: float = 14.0

    def __post_init__(self):
        if self.kind not in SLO_KINDS:
            raise ValueError(
                f"kind={self.kind!r}: expected one of {SLO_KINDS}")
        if not 0.0 < float(self.objective) < 1.0:
            raise ValueError(
                f"objective={self.objective}: expected a fraction in (0, 1)")
        if self.kind == "latency_p99" and self.threshold_ms <= 0:
            raise ValueError("latency_p99 needs threshold_ms > 0")


def _frac_over(snapshot, threshold_s: float) -> float:
    """Fraction of a HistogramSnapshot's observations above
    ``threshold_s``, linearly interpolated inside the containing bucket
    (the overflow bucket counts whole — no upper bound to interpolate
    against, so the estimate errs toward alerting)."""
    if snapshot.count <= 0:
        return 0.0
    over = 0.0
    lower = 0.0
    for i, upper in enumerate(snapshot.bounds):
        n = snapshot.counts[i]
        if threshold_s <= lower:
            over += n
        elif threshold_s < upper:
            over += n * (upper - threshold_s) / (upper - lower)
        lower = upper
    over += snapshot.counts[-1]  # overflow bucket
    if threshold_s > lower:
        pass  # whole overflow bucket already counted: errs high
    return min(over / snapshot.count, 1.0)


class SLOMonitor:
    """Evaluate ``slos`` for one engine against a registry; exports the
    burn-rate / budget gauges on construction and serves
    :meth:`report` for the ``/slo`` endpoint."""

    def __init__(self, slos: Sequence[SLO], engine_label: str,
                 registry: Optional[_metrics.Registry] = None,
                 on_fast_burn: Optional[Callable[[str, float],
                                                 None]] = None,
                 window_s: float = 300.0,
                 clock: Callable[[], float] = time.monotonic):
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.slos = tuple(slos)
        self.engine_label = str(engine_label)
        self.registry = registry if registry is not None \
            else _metrics.REGISTRY
        self.window_s = float(window_s)
        self.clock = clock
        self._on_fast_burn = on_fast_burn
        self._lock = threading.Lock()
        self._fast_burn_active: Dict[str, bool] = {
            s.name: False for s in self.slos}  # guarded_by: _lock
        self._base = self._take_baseline()  # guarded_by: _lock

        burn = self.registry.gauge(
            "raft_tpu_slo_burn_rate",
            "Error-budget burn-rate multiple over the current window "
            "(1.0 = spending exactly at objective).", ("engine", "slo"))
        budget = self.registry.gauge(
            "raft_tpu_slo_budget_remaining",
            "Remaining error-budget fraction of the current window.",
            ("engine", "slo"))
        for s in self.slos:
            burn.labels(self.engine_label, s.name).set_function(
                lambda s=s: self.burn_rate(s))
            budget.labels(self.engine_label, s.name).set_function(
                lambda s=s: max(0.0, 1.0 - self.burn_rate(s)))

    # ------------------------------------------------------- windowing
    def _take_baseline(self) -> dict:
        return {"t": self.clock(),
                "req": self._request_counts(),
                "latency": self._latency_snapshot()}

    def _maybe_roll(self) -> dict:
        with self._lock:
            if self.clock() - self._base["t"] >= self.window_s:
                self._base = self._take_baseline()
            return self._base

    # --------------------------------------------------- registry reads
    def _request_counts(self) -> Dict[str, int]:
        fam = self.registry.get("raft_tpu_serving_requests_total")
        if fam is None:
            return {}
        return {key[1]: int(c.value) for key, c in fam.collect()
                if key[0] == self.engine_label}

    def _latency_snapshot(self):
        fam = self.registry.get("raft_tpu_serving_total_seconds")
        if fam is None:
            return None
        for key, child in fam.collect():
            if key[0] == self.engine_label:
                return child.snapshot()
        return None

    def _worst_recall(self) -> float:
        fam = self.registry.get("raft_tpu_online_recall")
        if fam is None:
            return math.nan
        worst = math.nan
        for _, child in fam.collect():
            v = float(child.value)
            if not math.isnan(v) and (math.isnan(worst) or v < worst):
                worst = v
        return worst

    # -------------------------------------------------------- burn math
    def burn_rate(self, slo: SLO) -> float:
        """Windowed burn-rate multiple for one SLO (also the gauge
        body); fires the fast-burn callback on upward crossings."""
        base = self._maybe_roll()
        allowed = 1.0 - float(slo.objective)
        if slo.kind == "availability":
            now = self._request_counts()
            bad = sum(max(0, now.get(ev, 0) - base["req"].get(ev, 0))
                      for ev in _BAD_EVENTS)
            good = max(0, now.get("completed", 0)
                       - base["req"].get("completed", 0))
            total = good + bad
            burn = (bad / total / allowed) if total else 0.0
        elif slo.kind == "latency_p99":
            snap = self._latency_snapshot()
            if snap is None:
                burn = 0.0
            else:
                diff = snap - base["latency"] if base["latency"] is not None \
                    else snap
                burn = _frac_over(diff, slo.threshold_ms / 1e3) / allowed \
                    if diff.count else 0.0
        else:  # recall_floor
            recall = self._worst_recall()
            burn = 0.0 if math.isnan(recall) else \
                max(0.0, (1.0 - recall) / allowed)
        self._check_fast_burn(slo, burn)
        return burn

    def _check_fast_burn(self, slo: SLO, burn: float) -> None:
        fire = False
        with self._lock:
            active = self._fast_burn_active[slo.name]
            if burn >= slo.fast_burn and not active:
                self._fast_burn_active[slo.name] = fire = True
            elif burn < slo.fast_burn and active:
                self._fast_burn_active[slo.name] = False
        if fire and self._on_fast_burn is not None:
            try:
                self._on_fast_burn(slo.name, burn)
            except Exception:
                # telemetry never fails the scrape path, but a broken
                # pager hook must not vanish either — count it where
                # the same scrape will surface it
                self.registry.counter(
                    "raft_tpu_slo_callback_errors_total",
                    "fast-burn callbacks that raised.",
                    ("engine", "slo")).labels(
                        self.engine_label, slo.name).inc()

    # ---------------------------------------------------------- report
    def report(self) -> dict:
        """The ``/slo`` JSON doc: every SLO's burn rate, remaining
        budget, and fast-burn state for the current window."""
        base = self._maybe_roll()
        out = {"engine": self.engine_label, "window_s": self.window_s,
               "window_age_s": round(self.clock() - base["t"], 3),
               "slos": []}
        for s in self.slos:
            burn = self.burn_rate(s)
            row = {"name": s.name, "kind": s.kind,
                   "objective": s.objective,
                   "burn_rate": round(burn, 4),
                   "budget_remaining": round(max(0.0, 1.0 - burn), 4),
                   "fast_burn_threshold": s.fast_burn,
                   "fast_burn": burn >= s.fast_burn}
            if s.kind == "latency_p99":
                row["threshold_ms"] = s.threshold_ms
            if s.kind == "recall_floor":
                worst = self._worst_recall()
                if not math.isnan(worst):
                    row["worst_recall"] = round(worst, 6)
            out["slos"].append(row)
        return out
