"""raft_tpu.obs — unified telemetry: metrics, trace spans, exposition.

The TPU-native analog of RAFT's NVTX-everywhere convention, split into
four pieces (docs/observability.md):

- :mod:`~raft_tpu.obs.metrics` — lock-cheap Counter/Gauge/Histogram
  registry with Prometheus text + JSON exposition (stdlib-only);
- :mod:`~raft_tpu.obs.spans` — per-request trace span records and
  pluggable JSONL/in-memory sinks (stdlib-only);
- :mod:`~raft_tpu.obs.device` — jax.monitoring compile counters and
  ``profile_session()`` (imports jax lazily);
- :mod:`~raft_tpu.obs.httpd` — the ``/metrics`` + ``/healthz`` +
  ``/debug/bundle`` server an Engine exposes;
- :mod:`~raft_tpu.obs.diagnostics` — flight-recorder bundles (the span
  tape + registry snapshot + health frozen at a moment of interest);
- :mod:`~raft_tpu.obs.costs` — compiled-cost roofline reports and the
  planner calibration audit (imports jax lazily; AOT only);
- :mod:`~raft_tpu.obs.explain` — per-search execution-plan attribution
  (ExplainRecord + the ``raft_tpu_dispatch_total`` reason counter);
- :mod:`~raft_tpu.obs.quality` — shadow sampling and the online recall
  estimator behind ``raft_tpu_online_recall``;
- :mod:`~raft_tpu.obs.slo` — declarative SLOs → error-budget burn-rate
  gauges and the ``/slo`` report.

Layering: obs sits beside ``core`` — serving/parallel/neighbors import
obs, never the reverse.
"""

from raft_tpu.obs.device import (compile_count, compile_seconds,
                                 install_compile_metrics, profile_session)
from raft_tpu.obs.diagnostics import (build_bundle, load_bundle,
                                      write_bundle)
from raft_tpu.obs.explain import (REASONS, ExplainRecord, capture,
                                  dispatch_counts, record_dispatch)
from raft_tpu.obs.httpd import MetricsServer
from raft_tpu.obs.metrics import (DEFAULT_LATENCY_BUCKETS, REGISTRY, Counter,
                                  Gauge, Histogram, HistogramSnapshot,
                                  Registry, exponential_buckets)
from raft_tpu.obs.quality import (OnlineRecallEstimator, ShadowSampler,
                                  overlap_at_k)
from raft_tpu.obs.slo import SLO, SLOMonitor
from raft_tpu.obs.spans import (JsonlSink, ListSink, NullSink, RingSink,
                                new_trace_id, read_jsonl, safe_emit,
                                timed_span)

__all__ = [
    # metrics
    "Counter", "Gauge", "Histogram", "HistogramSnapshot", "Registry",
    "REGISTRY", "DEFAULT_LATENCY_BUCKETS", "exponential_buckets",
    # spans
    "JsonlSink", "ListSink", "NullSink", "RingSink", "new_trace_id",
    "read_jsonl", "safe_emit", "timed_span",
    # diagnostics (costs is imported explicitly — it compiles)
    "build_bundle", "write_bundle", "load_bundle",
    # device
    "compile_count", "compile_seconds", "install_compile_metrics",
    "profile_session",
    # explain / quality / slo
    "ExplainRecord", "REASONS", "capture", "record_dispatch",
    "dispatch_counts", "OnlineRecallEstimator", "ShadowSampler",
    "overlap_at_k", "SLO", "SLOMonitor",
    # exposition
    "MetricsServer",
]
