"""raft_tpu.obs — unified telemetry: metrics, trace spans, exposition.

The TPU-native analog of RAFT's NVTX-everywhere convention, split into
four pieces (docs/observability.md):

- :mod:`~raft_tpu.obs.metrics` — lock-cheap Counter/Gauge/Histogram
  registry with Prometheus text + JSON exposition (stdlib-only);
- :mod:`~raft_tpu.obs.spans` — per-request trace span records and
  pluggable JSONL/in-memory sinks (stdlib-only);
- :mod:`~raft_tpu.obs.device` — jax.monitoring compile counters and
  ``profile_session()`` (imports jax lazily);
- :mod:`~raft_tpu.obs.httpd` — the ``/metrics`` + ``/healthz`` server
  an Engine exposes.

Layering: obs sits beside ``core`` — serving/parallel/neighbors import
obs, never the reverse.
"""

from raft_tpu.obs.device import (compile_count, compile_seconds,
                                 install_compile_metrics, profile_session)
from raft_tpu.obs.httpd import MetricsServer
from raft_tpu.obs.metrics import (DEFAULT_LATENCY_BUCKETS, REGISTRY, Counter,
                                  Gauge, Histogram, HistogramSnapshot,
                                  Registry, exponential_buckets)
from raft_tpu.obs.spans import (JsonlSink, ListSink, NullSink, new_trace_id,
                                read_jsonl, safe_emit, timed_span)

__all__ = [
    # metrics
    "Counter", "Gauge", "Histogram", "HistogramSnapshot", "Registry",
    "REGISTRY", "DEFAULT_LATENCY_BUCKETS", "exponential_buckets",
    # spans
    "JsonlSink", "ListSink", "NullSink", "new_trace_id", "read_jsonl",
    "safe_emit", "timed_span",
    # device
    "compile_count", "compile_seconds", "install_compile_metrics",
    "profile_session",
    # exposition
    "MetricsServer",
]
