"""Online answer-quality estimation: shadow sampling + windowed recall.

Recall in this repo existed only in offline bench artifacts; serving
traffic carried no quality signal at all. This module closes that gap
the way the SLO layer needs it closed — on a sample, off the hot path,
and with every shed *counted*:

- :class:`ShadowSampler` takes a configurable fraction of completed
  batches (the decision is per batch, seeded and deterministic for a
  given request sequence), re-runs the sampled queries on a background
  *oracle* (brute-force exact, or a high-nprobe sibling config), and
  scores the answer the engine actually served by overlap@k against the
  oracle's. The oracle runs on a single daemon worker behind a bounded
  queue: a full queue sheds new samples (``shed_queue``), a stale item
  past the deadline cap is dropped at dequeue (``shed_deadline``) —
  both typed, both counted, never silent. A hung oracle call therefore
  wedges the worker, the queue fills, and pressure surfaces as
  ``shed_queue`` counts rather than hot-path latency.
- :class:`OnlineRecallEstimator` folds each sample into per
  ``(family, k, bucket)`` sliding windows exported as the
  ``raft_tpu_online_recall{family,k,bucket}`` gauge family (evaluated at
  scrape time, like every derived gauge in this repo).

Each evaluated sample also emits a ``kind="shadow_eval"`` span carrying
the ORIGINAL request's trace id, so a trace shows both the serving
answer and its graded quality, and spans reconcile 1:1 with the
``raft_tpu_serving_shadow_total`` counters (the chaos-suite invariant).

Estimator semantics and caveats (docs/observability.md): overlap@k is
computed against the oracle's ids with served ``-1`` padding excluded
from the numerator but not the denominator (a short answer is a recall
loss, not a smaller problem); sampling is per *batch*, so the estimate
is traffic-weighted, and sheds under pressure bias the window toward
calm periods — the shed counters are published precisely so that bias
is visible.

Layering: numpy + obs only. The serving engine hands this module plain
arrays and callables (``record_event`` routes to ``ServingStats``);
quality.py never imports serving or jax.
"""

from __future__ import annotations

import collections
import contextlib
import math
import queue
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from raft_tpu.obs import metrics as _metrics
from raft_tpu.obs import spans as _spans

__all__ = ["overlap_at_k", "OnlineRecallEstimator", "ShadowSampler",
           "SHADOW_EVENTS"]

#: per-request shadow accounting vocabulary; ``sampled`` counts every
#: request offered into the shadow path and equals evaluated +
#: shed_queue + shed_deadline + shed_close + error + (still queued)
#: at all times
SHADOW_EVENTS = ("sampled", "evaluated", "shed_queue", "shed_deadline",
                 "shed_close", "error")


def overlap_at_k(served_ids, oracle_ids) -> float:
    """|served ∩ oracle| / |oracle|: the recall of a served answer graded
    against the oracle's id set for the same query. ``-1`` markers (the
    families' "fewer than k candidates" padding) never count as hits,
    but the denominator stays the oracle's full set — a padded answer IS
    a recall loss."""
    oracle = [int(x) for x in np.asarray(oracle_ids).ravel() if int(x) >= 0]
    if not oracle:
        return 1.0
    served = {int(x) for x in np.asarray(served_ids).ravel() if int(x) >= 0}
    return len(served.intersection(oracle)) / len(oracle)


class OnlineRecallEstimator:
    """Sliding-window recall per (family, k, bucket), exported as the
    ``raft_tpu_online_recall`` gauge family at scrape time."""

    def __init__(self, registry: Optional[_metrics.Registry] = None,
                 window: int = 256):
        self._registry = registry if registry is not None \
            else _metrics.REGISTRY
        self._gauge = self._registry.gauge(
            "raft_tpu_online_recall",
            "Windowed mean overlap@k of served answers vs the shadow "
            "oracle, per family/k/bucket (NaN until the first sample).",
            ("family", "k", "bucket"))
        self._window = int(window)
        self._lock = threading.Lock()
        self._samples: Dict[Tuple[str, int, int],
                            collections.deque] = {}  # guarded_by: _lock

    def observe(self, family: str, k: int, bucket: int,
                recall: float) -> None:
        key = (str(family), int(k), int(bucket))
        with self._lock:
            dq = self._samples.get(key)
            if dq is None:
                dq = self._samples[key] = collections.deque(
                    maxlen=self._window)
                self._gauge.labels(*key).set_function(
                    lambda dq=dq: self._mean(dq))
            dq.append(float(recall))

    def _mean(self, dq) -> float:
        with self._lock:
            return sum(dq) / len(dq) if dq else math.nan

    def snapshot(self) -> Dict[Tuple[str, int, int], Tuple[int, float]]:
        """``{(family, k, bucket): (n_samples_in_window, mean)}`` — the
        host-side view serving_bench compares against its offline
        oracle."""
        with self._lock:
            return {key: (len(dq), sum(dq) / len(dq))
                    for key, dq in self._samples.items() if dq}


class _Sample:
    """One sampled batch in flight to the oracle."""

    __slots__ = ("queries", "k", "riders", "family", "bucket", "t_enqueue")

    def __init__(self, queries, k, riders, family, bucket, t_enqueue):
        self.queries = queries    # [n, dim] host array
        self.k = k                # oracle k (max rider k)
        self.riders = riders      # [(trace_id, k, served_ids), ...]
        self.family = family
        self.bucket = bucket
        self.t_enqueue = t_enqueue


class ShadowSampler:
    """Samples completed batches onto a background oracle and grades the
    served answers (class docstring: module header).

    ``oracle(queries [n, dim], k) -> (distances, indices)`` runs on the
    worker thread only — typically a brute-force exact search or a
    high-nprobe sibling of the serving config. ``record_event(event, n)``
    receives the :data:`SHADOW_EVENTS` accounting (the Engine routes it
    to ``ServingStats.record_shadow``). Spans go through ``safe_emit``:
    a raising sink is counted and silenced, never propagated."""

    def __init__(self, oracle: Callable, rate: float,
                 deadline_ms: float = 250.0, queue_limit: int = 64,
                 seed: int = 0,
                 estimator: Optional[OnlineRecallEstimator] = None,
                 record_event: Optional[Callable[[str, int], None]] = None,
                 span_sink=None, engine_label: str = "engine",
                 registry: Optional[_metrics.Registry] = None,
                 clock: Optional[Callable[[], float]] = None):
        if not 0.0 <= float(rate) <= 1.0:
            raise ValueError(f"rate={rate}: expected a fraction in [0, 1]")
        self.rate = float(rate)
        self.deadline_ms = float(deadline_ms)
        self.estimator = estimator if estimator is not None \
            else OnlineRecallEstimator(registry)
        self._record_event = record_event or (lambda event, n: None)
        self._span_sink = span_sink
        self._engine_label = str(engine_label)
        self._rng = np.random.default_rng(int(seed))
        self.clock = clock or time.monotonic
        self._queue: "queue.Queue[Optional[_Sample]]" = queue.Queue(
            maxsize=int(queue_limit))
        # single False->True lifecycle transition; racing offers observe
        # it best-effort (a late offer declines or lands pre-sentinel)
        self._closed = False  # guarded_by: atomic
        self._worker = threading.Thread(
            target=self._run, name="raft-tpu-shadow", daemon=True)
        self._worker.start()
        self._oracle = oracle

    # ---- hot-path side -------------------------------------------------
    def offer(self, queries, served_ids: Sequence, trace_ids: Sequence[str],
              ks: Sequence[int], family: str, bucket: int) -> bool:
        """Called by the completion loop after futures resolve: decide
        (per batch) whether to sample, and enqueue without blocking. A
        full queue counts every rider as ``shed_queue``. Returns whether
        the batch was sampled (queued or shed) — False means the coin
        said skip."""
        if self._closed or self._rng.random() >= self.rate:
            return False
        n = len(trace_ids)
        self._record_event("sampled", n)
        riders = [(trace_ids[j], int(ks[j]), np.array(served_ids[j]))
                  for j in range(n)]
        sample = _Sample(np.array(queries), max(r[1] for r in riders),
                         riders, str(family), int(bucket), self.clock())
        try:
            self._queue.put_nowait(sample)
        except queue.Full:
            self._record_event("shed_queue", n)
            self._emit_spans(sample, "shed_queue", [None] * n)
        return True

    # ---- worker side ---------------------------------------------------
    def _run(self) -> None:
        while True:
            sample = self._queue.get()
            if sample is None:
                return
            n = len(sample.riders)
            lag_ms = (self.clock() - sample.t_enqueue) * 1e3
            if lag_ms > self.deadline_ms:
                # stale before the oracle even started: the answer's
                # quality grade would arrive too late to matter (and the
                # backlog behind it would only grow) — typed shed
                self._record_event("shed_deadline", n)
                self._emit_spans(sample, "shed_deadline", [None] * n)
                continue
            try:
                _, oracle_ids = self._oracle(sample.queries, sample.k)
                oracle_ids = np.asarray(oracle_ids)
                recalls = []
                for j, (_, rk, served) in enumerate(sample.riders):
                    recalls.append(overlap_at_k(
                        served[:rk], oracle_ids[j][:rk]))
            except BaseException:  # noqa: B036 — shadow never kills serving
                self._record_event("error", n)
                self._emit_spans(sample, "error", [None] * n)
                continue
            for (_, rk, _), recall in zip(sample.riders, recalls):
                self.estimator.observe(sample.family, rk, sample.bucket,
                                       recall)
            self._record_event("evaluated", n)
            self._emit_spans(sample, "ok", recalls)

    def _emit_spans(self, sample: _Sample, outcome: str, recalls) -> None:
        if self._span_sink is None:
            return
        lag_ms = round((self.clock() - sample.t_enqueue) * 1e3, 3)
        for (trace_id, rk, _), recall in zip(sample.riders, recalls):
            rec = {"kind": "shadow_eval", "trace_id": trace_id,
                   "engine": self._engine_label, "family": sample.family,
                   "k": rk, "bucket": sample.bucket, "outcome": outcome,
                   "lag_ms": lag_ms}
            if recall is not None:
                rec["recall"] = round(float(recall), 6)
            _spans.safe_emit(self._span_sink, rec)

    def close(self, timeout: float = 5.0) -> None:
        """Stop the worker; queued samples drain first (the sentinel
        rides the same FIFO), then the thread exits."""
        if self._closed:
            return
        self._closed = True
        # the sentinel must land even when the queue is momentarily full
        # (bounded queue + racing offers): block briefly, then evict one
        # queued sample to make room — dropping the sentinel instead
        # would leave the worker parked on the queue forever
        try:
            self._queue.put(None, timeout=timeout)
        except queue.Full:
            self._record_event("shed_close", 1)
            with contextlib.suppress(queue.Empty):
                self._queue.get_nowait()
            with contextlib.suppress(queue.Full):
                self._queue.put_nowait(None)
        self._worker.join(timeout)
