"""Device-side attribution: compile counters and profiler sessions.

Replaces the serving engine's one-off ``jax.monitoring`` listener with
registry-backed counters, and wraps ``jax.profiler`` start/stop in
:func:`profile_session` so xprof captures are themselves observable
(how many sessions ran, whether one is live now).

``jax`` is imported lazily inside the functions — the rest of
:mod:`raft_tpu.obs` stays stdlib-only, so the metrics registry and span
sinks are importable in tooling that never touches a device.

Families (all on the default registry — jax.monitoring events are
process-global, so a per-engine registry would be a lie):

- ``raft_tpu_xla_compile_total`` — XLA backend compile events. The
  serving warmup invariant ("the first submit after ``start()`` compiles
  nothing", docs/serving.md) is asserted as a zero delta on this.
- ``raft_tpu_xla_compile_seconds_total`` — cumulative compile seconds.
- ``raft_tpu_profile_sessions_total`` / ``raft_tpu_profile_active`` —
  profiler start/stop accounting around :func:`profile_session`.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional

from raft_tpu.obs import metrics as _metrics

__all__ = ["install_compile_metrics", "compile_count", "compile_seconds",
           "profile_session"]

_install_lock = threading.Lock()
_installed = False

_COMPILES = _metrics.REGISTRY.counter(
    "raft_tpu_xla_compile_total",
    "XLA backend compile events (jax.monitoring duration events matching "
    "'backend_compile'). A nonzero delta across a serving request means "
    "a shape escaped warmup.")
_COMPILE_SECONDS = _metrics.REGISTRY.counter(
    "raft_tpu_xla_compile_seconds_total",
    "Cumulative seconds spent in XLA backend compiles.")
_PROFILE_SESSIONS = _metrics.REGISTRY.counter(
    "raft_tpu_profile_sessions_total",
    "jax.profiler capture sessions opened via obs.profile_session().")
_PROFILE_ACTIVE = _metrics.REGISTRY.gauge(
    "raft_tpu_profile_active",
    "1 while an obs.profile_session() capture is running.")


def _listener(event: str, duration: float, **kwargs) -> None:
    if "backend_compile" in event:
        _COMPILES.inc()
        _COMPILE_SECONDS.inc(max(float(duration), 0.0))


def install_compile_metrics() -> None:
    """Register the jax.monitoring compile listener once (idempotent,
    thread-safe). Events before the first call are not counted — callers
    comparing deltas must install before the baseline read, which
    :func:`compile_count` does implicitly."""
    global _installed
    with _install_lock:
        if _installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_listener)
        _installed = True


def compile_count() -> int:
    """Process-wide count of XLA backend compiles observed since the
    first call. Monotonic; compare deltas, not absolutes. (Kept as the
    serving layer's historical API; re-exported from raft_tpu.serving.)"""
    install_compile_metrics()
    return int(_COMPILES.value)


def compile_seconds() -> float:
    """Cumulative seconds spent compiling since the first call."""
    install_compile_metrics()
    return float(_COMPILE_SECONDS.value)


@contextlib.contextmanager
def profile_session(log_dir: str = "/tmp/raft_tpu_trace",
                    host_tracer_level: int = 2,
                    ) -> Iterator[str]:
    """xprof capture with session accounting: wraps
    :func:`raft_tpu.core.tracing.profile` and ticks the session
    counter/active gauge so a scrape shows whether a capture is live.
    Yields the log dir; open it with xprof/TensorBoard and correlate via
    the ``tracing.range`` names (docs/observability.md)."""
    from raft_tpu.core import tracing

    install_compile_metrics()
    _PROFILE_SESSIONS.inc()
    _PROFILE_ACTIVE.inc()
    try:
        with tracing.profile(log_dir, host_tracer_level) as d:
            yield d
    finally:
        _PROFILE_ACTIVE.dec()
