"""Execution-plan attribution: every search dispatch explains itself.

PR 9's ``scan_mode`` dispatch falls back from the fused Pallas engines
to XLA *silently* (docs/tuning.md fallback matrix) — correct by design,
invisible by accident: production traffic gave no signal whether the
fused hot path was even live. This module makes every dispatch decision
observable, three ways from one emission point:

- a structured :class:`ExplainRecord` — family, requested vs resolved
  engine, a reason code from the closed :data:`REASONS` vocabulary,
  planner tile choices and predicted workspace bytes, probe/bucket
  params;
- the ``raft_tpu_dispatch_total{family,engine,reason}`` counter family
  on the default registry, incremented once per public ``search()``
  call (the scrape-able reason histogram — r06's proof that fused
  routing actually flipped on);
- the thread-local :func:`capture` collector, which the serving engine
  wraps around each batch dispatch so the records ride the batch/request
  spans as ``explain`` breadcrumbs, and which ``search(...,
  explain=True)`` uses to hand the record back to the caller.

Layering: this module is registry-only (no jax, no neighbors import —
obs sits beside core). The neighbor families and ``ops/select_k`` call
:func:`record_dispatch` / :func:`note_select_k` at their dispatch
points; graftcheck rule R007 enforces that no silent-fallback branch
ships without one.

Counter semantics: family dispatch decisions happen in Python per
``search()`` call, so ``raft_tpu_dispatch_total`` reconciles 1:1 with
batch-level span breadcrumbs. ``select_k``'s AUTO resolution runs at
*trace time* inside jitted search bodies (once per compiled shape, not
per call), so it records into the active capture only — counting it
would alias the jit cache, not the traffic.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Dict, Iterator, List, Optional

from raft_tpu.obs import metrics as _metrics

__all__ = [
    "ExplainRecord",
    "REASONS",
    "capture",
    "record_dispatch",
    "note_select_k",
    "dispatch_counts",
]

#: The closed fallback-cause vocabulary (docs/observability.md "Explain
#: records"). Every dispatch emission MUST use one of these — the
#: reconciliation tests assert zero increments outside it (and zero
#: ``unknown``s, which exists only as the schema's escape hatch for
#: forward-compat readers, never as something the repo emits).
REASONS = frozenset({
    # engine chosen positively
    "forced",                  # scan_mode explicitly named this engine
    "auto_fused_wins",         # measured PALLAS_PROBE verdict routed fused
    "interpret",               # RAFT_TPU_PALLAS_INTERPRET=1 parity hook
    "only_engine",             # family has a single engine (kept in the
                               # vocabulary for artifact replay; cagra —
                               # its last emitter — now has the fused
                               # Pallas beam engine and dispatches like
                               # the other fused families)
    # fused considered but routed to XLA
    "tpu_absent",              # pallas/auto on a host with no TPU backend
    "no_fused_wins_verdict",   # auto on TPU, probe artifact has no verdict
    "fused_loses",             # auto on TPU, probe measured XLA winning
    "non_l2",                  # metric outside the fused L2 matrix
    "filtered",                # bitset filter (no in-carry filter epilogue)
    "fast_scan",               # bf16 fast scan requested (fp32-only carry)
    "k_gt_1024",               # k above the VMEM top-k carry bound
    "non_float_dtype",         # integer dataset (no float carry)
    "lut_params_unsupported",  # fused-LUT regime needs pq_bits=8 etc.
    # sharded cross-chip merge dispatch (parallel/sharded.py merge_mode;
    # "forced"/"fused_loses" above are shared with the merge ladder)
    "merge_tree",              # auto: log₂S ppermute tree merge (default)
    "merge_ring",              # auto on TPU: measured merge_ring win
    "merge_allgather",         # auto: non-power-of-two mesh fallback
    "no_ring_verdict",         # auto on TPU, probe has no merge_ring row
    # deadline-aware adaptive planning (planner/adaptive.py choice
    # reasons — emitted with requested="adaptive", engine="planner";
    # also counted in raft_tpu_adaptive_choice_total{family,reason})
    "pareto_default",          # highest-recall frontier point fits
    "deadline_degraded",       # budget forced a lower-recall point
    "floor_clamped",           # recall floor stopped the degradation
    "no_frontier",             # no committed points: static params serve
    # schema escape hatch for readers; never emitted by this repo
    "unknown",
})

_DISPATCH = _metrics.REGISTRY.counter(
    "raft_tpu_dispatch_total",
    "Search dispatch decisions by family, resolved engine, and "
    "reason code (docs/observability.md reason vocabulary).",
    ("family", "engine", "reason"))


@dataclasses.dataclass
class ExplainRecord:
    """One dispatch decision, fully attributed.

    ``params`` carries the query-shape side (k, nq, n_probes, metric,
    bucket…); ``plan`` carries the planner side (tile choices, predicted
    workspace/VMEM bytes). Both are flat JSON-safe dicts so a record
    drops straight into a span or a JSONL line.
    """

    family: str      # "brute_force" | "ivf_flat" | "ivf_pq" | "cagra" | ...
    requested: str   # scan_mode as the caller asked ("auto", "pallas", ...)
    engine: str      # what actually ran: "pallas", "xla", "cache", ...
    reason: str      # a REASONS member: why `engine` was the resolution
    params: Dict[str, object] = dataclasses.field(default_factory=dict)
    plan: Dict[str, object] = dataclasses.field(default_factory=dict)
    #: trace-time sub-decisions (select_k AUTO resolution) observed while
    #: this record's search was the innermost active capture
    notes: List[dict] = dataclasses.field(default_factory=list)

    def brief(self) -> dict:
        """The span breadcrumb: just the attribution triple + request."""
        return {"family": self.family, "requested": self.requested,
                "engine": self.engine, "reason": self.reason}

    def to_dict(self) -> dict:
        return {"family": self.family, "requested": self.requested,
                "engine": self.engine, "reason": self.reason,
                "params": dict(self.params), "plan": dict(self.plan),
                "notes": [dict(n) for n in self.notes]}


class _Capture:
    """Collector for one ``with capture():`` scope (single-thread use —
    the scope lives on the thread that opened it)."""

    def __init__(self) -> None:
        self.records: List[ExplainRecord] = []

    @property
    def last(self) -> Optional[ExplainRecord]:
        return self.records[-1] if self.records else None

    def briefs(self) -> List[dict]:
        return [r.brief() for r in self.records]


_tls = threading.local()


def _stack() -> List[_Capture]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


@contextlib.contextmanager
def capture() -> Iterator[_Capture]:
    """Collect every :class:`ExplainRecord` emitted on THIS thread while
    the scope is open. Scopes nest (each record lands in every open
    scope, so an engine-level capture still sees records a tool-level
    inner capture claims). Never raises into the instrumented path."""
    col = _Capture()
    stack = _stack()
    stack.append(col)
    try:
        yield col
    finally:
        # tolerate a peer popping out of order rather than corrupting
        # the instrumented call (telemetry never fails serving)
        with contextlib.suppress(ValueError):
            stack.remove(col)


def record_dispatch(family: str, requested: str, engine: str, reason: str,
                    params: Optional[dict] = None,
                    plan: Optional[dict] = None) -> ExplainRecord:
    """THE emission point: build the record, bump
    ``raft_tpu_dispatch_total{family,engine,reason}``, and hand the
    record to every open :func:`capture` scope on this thread.

    ``reason`` outside :data:`REASONS` is a programming error and
    raises — the vocabulary is closed so dashboards and the
    reconciliation tests can enumerate it."""
    if reason not in REASONS:
        raise ValueError(f"reason {reason!r} outside the documented "
                         f"vocabulary (docs/observability.md)")
    rec = ExplainRecord(family=family, requested=requested, engine=engine,
                        reason=reason, params=dict(params or {}),
                        plan=dict(plan or {}))
    _DISPATCH.labels(family, engine, reason).inc()
    for col in _stack():
        col.records.append(rec)
    return rec


def note_select_k(n: int, k: int, algo: str, k_pad: int = 0) -> None:
    """Attach a select_k AUTO/pad resolution to the active capture(s).

    Runs at trace time inside jitted search bodies — once per compiled
    shape — so it deliberately does NOT touch the dispatch counter (see
    the module docstring); it exists so ``tools/explain.py`` and
    ``search(..., explain=True)`` show the full plan of a cold query."""
    stack = _stack()
    if not stack:
        return
    note = {"op": "select_k", "n": int(n), "k": int(k), "algo": str(algo),
            "k_pad": int(k_pad)}
    for col in stack:
        if col.records:
            col.records[-1].notes.append(note)
        else:
            # select_k used standalone under a capture: synthesize a
            # record so the decision is still attributable
            col.records.append(ExplainRecord(
                family="select_k", requested="auto", engine=str(algo),
                reason="forced", params={"n": int(n), "k": int(k)},
                plan={"k_pad": int(k_pad)}))


def dispatch_counts(
        registry: Optional[_metrics.Registry] = None) -> Dict[tuple, int]:
    """``{(family, engine, reason): count}`` view of the dispatch
    counter — the explain reason histogram serving_bench / tpu_queue2
    artifacts record next to the pallasgate verdicts."""
    reg = registry if registry is not None else _metrics.REGISTRY
    fam = reg.get("raft_tpu_dispatch_total")
    if fam is None:
        return {}
    return {tuple(key): int(child.value) for key, child in fam.collect()
            if int(child.value)}
