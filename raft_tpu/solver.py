"""Linear assignment (LAP) solver.

Reference: ``raft::solver`` (solver/linear_assignment.cuh — ``LinearAssignment
Problem``, a GPU Hungarian/alternating-tree solver after Date & Nagi 2016;
solver/linear_assignment_types.hpp).

TPU-native design: the auction algorithm — per-round, every unassigned row
bids for its best column (a dense argmin/argtop2 over the cost row, pure
VPU/MXU-friendly vector work), highest bid wins, prices rise. Rounds are a
bounded ``lax.while_loop`` with an epsilon-scaling schedule; dense [n, n]
cost matrices are exactly the reference's input shape. For guaranteed-exact
host-side solves, ``solve_host`` wraps scipy's Jonker-Volgenant.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=("maximize", "max_iters"))
def _auction_jit(cost, eps, maximize: bool, max_iters: int):
    n, m = cost.shape
    benefit = cost if maximize else -cost  # auction maximizes benefit
    big = jnp.float32(jnp.inf)

    def cond(state):
        i, row_of_col, price, unassigned = state
        return (i < max_iters) & jnp.any(unassigned)

    rows = jnp.arange(n, dtype=jnp.int32)

    def body(state):
        i, row_of_col, price, unassigned = state
        value = benefit - price[None, :]  # [n, m]
        # top-2 values per row for the bid increment
        v1, j1 = jax.lax.top_k(value, 2)
        bid_inc = v1[:, 0] - v1[:, 1] + eps
        target = j1[:, 0]
        # only unassigned rows bid; masked scatters use index m (dropped)
        bidder = jnp.where(unassigned, target, m)
        best_bid = jnp.full((m,), -big).at[bidder].max(bid_inc,
                                                       mode="drop")
        is_best = unassigned & (bid_inc >= best_bid[target])
        # tie-break: lowest row id among best bidders per column
        winner_row = jnp.full((m,), n, jnp.int32).at[
            jnp.where(is_best, target, m)].min(rows, mode="drop")
        won = is_best & (winner_row[target] == rows)

        # previous owners of columns won this round become unassigned
        displaced = row_of_col[jnp.where(won, target, 0)]
        displaced = jnp.where(won & (displaced >= 0), displaced, n)
        unassigned = (unassigned & ~won).at[displaced].set(True, mode="drop")
        price = price.at[jnp.where(won, target, m)].add(bid_inc, mode="drop")
        row_of_col = row_of_col.at[jnp.where(won, target, m)].set(
            rows, mode="drop")
        return i + 1, row_of_col, price, unassigned

    row_of_col0 = jnp.full((m,), -1, jnp.int32)
    price0 = jnp.zeros((m,), jnp.float32)
    unassigned0 = jnp.ones((n,), bool)
    _, row_of_col, price, unassigned = jax.lax.while_loop(
        cond, body, (0, row_of_col0, price0, unassigned0))
    # invert to col_of_row
    col_of_row = jnp.full((n,), -1, jnp.int32).at[
        jnp.where(row_of_col >= 0, row_of_col, n)].set(
        jnp.arange(m, dtype=jnp.int32), mode="drop")
    return col_of_row, unassigned


def solve(cost, maximize: bool = False, eps: Optional[float] = None,
          max_iters: int = 0) -> Tuple[jax.Array, jax.Array]:
    """Solve the square dense assignment problem on-device via auction
    (reference entry: LinearAssignmentProblem::solve,
    solver/linear_assignment.cuh). Returns (col_of_row [n], total_cost).

    With ``eps < 1/n`` (default) the auction result is optimal for integer
    costs; for float costs it is within n·eps of optimal.
    """
    cost = jnp.asarray(cost, jnp.float32)
    n, m = cost.shape
    if n != m:
        raise ValueError(f"cost must be square, got {cost.shape}")
    if eps is None:
        eps = 1.0 / (n + 1)
    if max_iters <= 0:
        max_iters = 50 * n + 1000
    assign, unassigned = _auction_jit(cost, jnp.float32(eps), bool(maximize),
                                      int(max_iters))
    total = jnp.sum(jnp.take_along_axis(
        cost, jnp.maximum(assign, 0)[:, None], axis=1)[:, 0]
        * (assign >= 0))
    return assign, total


def solve_host(cost, maximize: bool = False) -> Tuple[np.ndarray, float]:
    """Exact host-side solve (scipy Jonker-Volgenant) — the ``refine``-style
    oracle for tests and small problems."""
    from scipy.optimize import linear_sum_assignment

    cost = np.asarray(cost)
    rows, cols = linear_sum_assignment(cost, maximize=maximize)
    out = np.full(cost.shape[0], -1, np.int64)
    out[rows] = cols
    return out, float(cost[rows, cols].sum())
