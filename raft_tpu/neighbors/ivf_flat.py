"""IVF-Flat — inverted-file index over raw vectors.

Reference: ``raft::neighbors::ivf_flat`` (neighbors/ivf_flat-inl.cuh:65-647;
build detail/ivf_flat_build.cuh; search detail/ivf_flat_search-inl.cuh +
interleaved scan detail/ivf_flat_interleaved_scan-inl.cuh; types
ivf_flat_types.hpp). Build: balanced k-means on a trainset subsample →
predict labels → fill per-list storage in an interleaved group-of-32,
veclen-chunked layout. Search: coarse top-``n_probes`` clusters via pairwise
distance + select_k, then a fused per-cluster scan feeding warpsort queues,
then a final select_k across probes.

TPU-native design:
- **List layout**: padded dense ``[n_lists, list_pad, dim]`` (plus int32 row
  ids), lane-aligned padding instead of the GPU's 32-row interleaving — the
  balanced quantizer keeps max/avg list length near 1, so padding waste is
  small and every probe scan is a dense, MXU/VPU-friendly block.
- **Search**: coarse scores = one queries×centers matmul (+ select_k);
  probed lists are gathered to ``[q_tile, n_probes, list_pad, dim]`` and
  scanned with one einsum; invalid padding rows get ±inf; one select_k over
  ``n_probes·list_pad`` candidates finishes (two-stage selection like the
  reference's per-probe queues + final select_k). Query batches stream
  through ``lax.map`` sized by the workspace budget.
- Optional ``Bitset`` filter masks candidates by source row id (reference:
  bitset_filter, sample_filter_types.hpp:27-82).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import serialize as ser
from raft_tpu.core import tracing
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.bitset import filter_mask as bitset_filter_mask
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.neighbors import list_packing
from raft_tpu.neighbors.brute_force import fused_ineligible_reason
from raft_tpu.obs import explain as obs_explain
from raft_tpu.ops.distance import (DistanceType, gathered_distances,
                                    resolve_metric, row_norms_sq)
from raft_tpu.ops.select_k import (refine_multiplier, select_k,
                                   select_k_maybe_approx)
from raft_tpu.ops import rng as rrng
from raft_tpu.utils.shape import (as_query_array, cdiv, pad_rows,
                                  query_bucket)


@dataclasses.dataclass
class IndexParams:
    """reference: ivf_flat_types.hpp:57-99 index_params."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    adaptive_centers: bool = False
    add_data_on_build: bool = True
    # Padded-storage budget: list capacity is capped so L·pad plus the
    # overflow block stays within this multiple of the raw row count; rows
    # spilled from hot lists land in the overflow block, scanned
    # brute-force by every query (a candidate superset — recall can only
    # improve). The reference pays only group-of-32 padding on ragged
    # lists (ivf_list.hpp); this bounds the dense-layout analog.
    list_pad_expansion: float = 1.5

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.list_pad_expansion < 1.0:
            raise ValueError(
                f"list_pad_expansion must be >= 1.0, got "
                f"{self.list_pad_expansion}")


@dataclasses.dataclass
class SearchParams:
    """reference: ivf_flat_types.hpp search_params.

    ``scan_dtype``: None scans at the data dtype (fp32 data → fp32-accurate
    MXU passes). ``"bfloat16"`` runs the fine scan's matmul as a bf16 MXU
    screen over ~4k candidates followed by an exact fp32 re-rank — the TPU
    analog of the reference's int8/dp4a fast scans
    (ivf_flat_interleaved_scan-inl.cuh:99-251). The re-rank is required:
    an unrefined bf16 expanded-L2 scan cancels catastrophically when
    distance gaps are small next to vector norms (measured recall
    0.9997 → 0.57 on clustered data on v5e). The re-rank recovers most
    but not all of it — bf16 rounding of the *inputs* can push true
    neighbors outside the ``refine_ratio·k`` screen when gaps are far
    below vector norms (near-duplicate regimes measure ~0.95 at the
    default ratio; widen ``refine_ratio`` or use the fp32 scan when
    exactness matters)."""

    n_probes: int = 20
    scan_dtype: Optional[object] = None
    # bf16 screen width as a multiple of k for the exact fp32 re-rank
    # (scan_dtype="bfloat16" only); wider = higher recall, more re-rank
    refine_ratio: float = 4.0
    # "pallas" requests the fused Pallas scan+select (probed slabs DMA'd to
    # VMEM, top-k carried in-kernel — docs/tuning.md); "auto" picks it on
    # TPU where the committed probe artifact shows it winning; unsupported
    # combinations (non-L2 metric, filter, bf16 fast scan, k > 1024) fall
    # back to the XLA engine silently
    scan_mode: str = "auto"
    # <1.0 routes internal top-k through the TPU PartialReduce engine
    # (ops.select_k APPROX) at this per-element recall target — measured
    # 10-40x faster than exact top_k at IVF shapes on v5e; the recall
    # trade is the searcher's, like the reference's lut_dtype dial
    select_recall: float = 1.0


class Index:
    """IVF-Flat index (reference: ivf_flat_types.hpp:142-165 — per-list data
    + indices + sizes, centers, center norms)."""

    def __init__(self, params: IndexParams, centers, list_data, list_indices,
                 list_sizes, n_rows: int, overflow_data=None,
                 overflow_indices=None):
        self.params = params
        self.centers = centers  # [n_lists, dim] fp32
        self.list_data = list_data  # [n_lists, list_pad, dim]
        self.list_indices = list_indices  # [n_lists, list_pad] int32, -1 pad
        self.list_sizes = list_sizes  # [n_lists] int32
        self.n_rows = int(n_rows)
        # rows spilled past the capped list_pad (choose_list_pad): scanned
        # brute-force by every query and merged into the final select_k.
        # [n_over_pad, dim] / [n_over_pad] int32 (-1 = padding); empty in
        # the balanced common case.
        dim = centers.shape[1] if centers is not None else 0
        dt = list_data.dtype if list_data is not None else jnp.float32
        self.overflow_data = (overflow_data if overflow_data is not None
                              else jnp.zeros((0, dim), dt))
        self.overflow_indices = (
            overflow_indices if overflow_indices is not None
            else jnp.zeros((0,), jnp.int32))
        # lazy per-row squared norms for the Pallas fused scan (the
        # reference's center_norms analog at list granularity)
        self._row_norms = None

    def ensure_row_norms(self):
        if self._row_norms is None:
            self._row_norms = jnp.sum(
                self.list_data.astype(jnp.float32) ** 2, -1)
        return self._row_norms

    @property
    def metric(self) -> DistanceType:
        return self.params.metric

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def size(self) -> int:
        return self.n_rows


def _pack_lists(dataset: np.ndarray, labels: np.ndarray, n_lists: int,
                ids: Optional[np.ndarray] = None,
                max_expansion: float = 1.5):
    """Pack rows into padded [n_lists, pad, dim] storage via the native C++
    packer (host-side; analog of build_index_kernel's list fill,
    detail/ivf_flat_build.cuh:123-160). ``pad`` is budget-capped
    (list_packing.choose_list_pad); rows past a hot list's cap spill to
    the returned overflow block.

    Returns (data, idxs, sizes, overflow_rows, overflow_ids)."""
    from raft_tpu import native

    sizes = np.bincount(labels, minlength=n_lists).astype(np.int32)
    pad = list_packing.choose_list_pad(sizes, max_expansion)
    if ids is None:
        ids = np.arange(len(dataset), dtype=np.int32)
    if int(sizes.max(initial=0)) <= pad:
        data, idxs, sizes = native.pack_lists(dataset, labels, n_lists, pad,
                                              ids)
        return data, idxs, sizes, *list_packing.pad_overflow_block(
            dataset[:0], ids[:0])
    keep = list_packing.fit_mask(labels, n_lists, pad)
    data, idxs, sizes = native.pack_lists(
        np.ascontiguousarray(dataset[keep]), labels[keep], n_lists, pad,
        np.ascontiguousarray(ids[keep]))
    over_rows, over_ids = list_packing.pad_overflow_block(
        np.ascontiguousarray(dataset[~keep]),
        np.ascontiguousarray(ids[~keep]))
    return data, idxs, sizes, over_rows, over_ids


@tracing.range("ivf_flat.build")
def build(
    dataset,
    params: Optional[IndexParams] = None,
    res: Optional[Resources] = None,
) -> Index:
    """Build the index (reference: ivf_flat::build, ivf_flat-inl.cuh:65)."""
    params = params or IndexParams()
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    n_rows, dim = dataset.shape
    if params.n_lists > n_rows:
        raise ValueError(f"n_lists={params.n_lists} > n_rows={n_rows}")

    # trainset subsample (reference: detail/ivf_flat_build.cuh build())
    n_train = max(int(n_rows * params.kmeans_trainset_fraction), params.n_lists)
    n_train = min(n_train, n_rows)
    trainset = rrng.subsample_rows(res.next_key(), dataset, n_train)

    km_params = KMeansBalancedParams(
        n_iters=params.kmeans_n_iters, metric=params.metric
    )
    centers = kmeans_balanced.fit(res.next_key(), trainset, params.n_lists,
                                  km_params, res=res)
    index = Index(params, centers, None, None, None, 0)
    if params.add_data_on_build:
        index = extend(index, dataset, res=res)
    return index


@tracing.range("ivf_flat.extend")
def extend(index: Index, new_vectors, new_indices=None,
           res: Optional[Resources] = None) -> Index:
    """Add vectors (reference: ivf_flat::extend, ivf_flat-inl.cuh:195;
    optional adaptive_centers recomputes centroids from list means,
    ivf_flat_types.hpp:57-68)."""
    res = ensure_resources(res)
    new_vectors = jnp.asarray(new_vectors)
    km_params = KMeansBalancedParams(metric=index.metric)
    labels = np.asarray(kmeans_balanced.predict(index.centers, new_vectors,
                                                km_params, res=res))
    new_np = np.asarray(new_vectors)
    if new_indices is None:
        # auto ids start past the row count and any user-supplied id —
        # including ids that spilled to the overflow block
        base = index.n_rows
        if index.list_indices is not None:
            base = max(base, int(np.asarray(index.list_indices).max()) + 1)
        if index.overflow_indices is not None and \
                index.overflow_indices.shape[0]:
            base = max(base,
                       int(np.asarray(index.overflow_indices).max()) + 1)
        new_ids = np.arange(base, base + len(new_np), dtype=np.int32)
    else:
        new_ids = np.asarray(new_indices, np.int32)

    if index.list_data is None:
        data, idxs, sizes, over_rows, over_ids = _pack_lists(
            new_np, labels, index.n_lists, new_ids,
            index.params.list_pad_expansion)
        data, idxs, sizes = (jnp.asarray(data), jnp.asarray(idxs),
                             jnp.asarray(sizes))
        over_rows, over_ids = jnp.asarray(over_rows), jnp.asarray(over_ids)
    else:
        # device-side append: grow the pad (budget-capped) if needed, then
        # segment-scatter the new batch after each list's tail — existing
        # lists stay packed on device (same path as ivf_pq.extend;
        # reference: build_index_kernel's list fill,
        # detail/ivf_flat_build.cuh:123-160). Rows past a hot list's cap
        # spill to the overflow block (the pad never shrinks below the
        # current storage — no repack on extend).
        old_sizes = np.asarray(index.list_sizes)
        counts = np.bincount(labels, minlength=index.n_lists)
        n_over_old = int(jnp.sum(index.overflow_indices >= 0)) \
            if len(index.overflow_indices) else 0
        cap = max(list_packing.choose_list_pad(
            old_sizes + counts, index.params.list_pad_expansion),
            index.list_data.shape[1])
        keep = list_packing.fit_mask(labels, index.n_lists, cap,
                                     sizes=old_sizes)
        data, idxs = list_packing.grow_pad(
            index.list_data, index.list_indices,
            int((old_sizes + np.bincount(
                labels[keep], minlength=index.n_lists)).max()))
        data, idxs, sizes = list_packing.append_lists(
            data, idxs, index.list_sizes,
            jnp.asarray(new_np[keep]).astype(data.dtype),
            jnp.asarray(new_ids[keep]), jnp.asarray(labels[keep]),
            index.n_lists)
        over_rows, over_ids = _merge_overflow(
            index.overflow_data, index.overflow_indices, n_over_old,
            new_np[~keep].astype(data.dtype), new_ids[~keep])
    centers = index.centers
    if index.params.adaptive_centers:
        dsum = data.astype(jnp.float32).sum(axis=1)
        centers = dsum / jnp.maximum(sizes.astype(jnp.float32), 1.0)[:, None]
    return Index(index.params, centers, data, idxs, sizes,
                 index.n_rows + len(new_np), over_rows, over_ids)


def _merge_overflow(old_rows, old_ids, n_old_valid: int, new_rows_np,
                    new_ids_np):
    """Append spilled rows to the overflow block (8-aligned). Valid rows
    are compacted first (padding slots sit only at the tail)."""
    if len(new_rows_np) == 0:
        return old_rows, old_ids
    merged_rows = np.concatenate(
        [np.asarray(old_rows)[:n_old_valid], new_rows_np], axis=0)
    merged_ids = np.concatenate(
        [np.asarray(old_ids)[:n_old_valid],
         np.asarray(new_ids_np, np.int32)])
    rows, ids = list_packing.pad_overflow_block(merged_rows, merged_ids)
    return jnp.asarray(rows), jnp.asarray(ids)


def _coarse_scores(queries, centers, metric: DistanceType):
    dots = jax.lax.dot_general(
        queries.astype(jnp.float32), centers, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    if metric == DistanceType.InnerProduct:
        return dots, False  # maximize
    if metric == DistanceType.CosineExpanded:
        cn = jnp.sqrt(jnp.maximum(row_norms_sq(centers), 1e-20))
        return dots / cn[None, :], False
    qn = row_norms_sq(queries)
    cn = row_norms_sq(centers)
    return qn[:, None] + cn[None, :] - 2.0 * dots, True


def _overflow_scan(qt, qf, o_scan, o_norms, o_ok_base, overflow_indices,
                   filter_words, metric: DistanceType, has_filter: bool,
                   fast_scan: bool, bad_fill):
    """Brute-force distances of one query tile against the overflow block
    (the spilled-rows complement of the probed-list scan): [t, O] distances
    + broadcast ids, ready to concatenate into the final select_k."""
    q_s = qt.astype(jnp.bfloat16) if fast_scan else qf
    dots = jax.lax.dot_general(
        q_s, o_scan, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=(None if fast_scan else jax.lax.Precision.HIGHEST),
    )  # [t, O]
    if metric == DistanceType.InnerProduct:
        od = dots
    elif metric == DistanceType.CosineExpanded:
        on = jnp.sqrt(jnp.maximum(o_norms, 1e-20))
        qn = jnp.sqrt(jnp.maximum(row_norms_sq(qf), 1e-20))
        od = 1.0 - dots / (on[None, :] * qn[:, None])
    else:
        od = jnp.maximum(
            row_norms_sq(qf)[:, None] + o_norms[None, :] - 2.0 * dots, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            od = jnp.sqrt(od)
    ok = o_ok_base
    if has_filter:
        ok = ok & bitset_filter_mask(overflow_indices, filter_words)
    od = jnp.where(ok[None, :], od, bad_fill)
    oi = jnp.broadcast_to(overflow_indices[None, :],
                          (qt.shape[0], overflow_indices.shape[0]))
    o_ok = jnp.broadcast_to(ok[None, :], od.shape)
    return od, oi, o_ok


def _search_core(queries, centers, list_data, list_indices, list_sizes,
                 filter_words, metric: DistanceType, k: int, n_probes: int,
                 q_tile: int, has_filter: bool, row_norms=None,
                 use_pallas: bool = False, pallas_interpret: bool = False,
                 fast_scan: bool = False, overflow_data=None,
                 overflow_indices=None, has_overflow: bool = False,
                 select_recall: float = 1.0, refine_mult: int = 4):
    """Traceable search body — jitted below; also shard_mapped by
    raft_tpu.parallel.sharded for multi-device list-sharded search.

    ``use_pallas`` routes the probe scan through the fused scalar-prefetch
    kernel (ops.pallas_kernels.ivf_scan): probed list slabs are DMA'd
    straight to VMEM instead of materializing the [t, P, pad, dim] gather
    in HBM; requires ``row_norms`` [L, pad].

    ``has_overflow``: rows spilled past the capped list_pad are scanned
    brute-force for every query and merged into the final select_k — a
    strict candidate superset (exact distances), so recall never drops."""
    nq, dim = queries.shape
    n_lists, list_pad, _ = list_data.shape
    minimize = metric != DistanceType.InnerProduct

    def _sel(vals, kk, sel_min):
        return select_k_maybe_approx(vals, kk, sel_min, select_recall)

    n_q_tiles = cdiv(nq, q_tile)
    pad_q = n_q_tiles * q_tile - nq
    qp = jnp.pad(queries, ((0, pad_q), (0, 0)))

    valid_slot = jnp.arange(list_pad)[None, :] < list_sizes[:, None]  # [L, pad]
    if has_overflow:
        o_f32 = overflow_data.astype(jnp.float32)
        o_norms = row_norms_sq(o_f32)  # [O]
        o_ok_base = overflow_indices >= 0
        o_scan = (overflow_data.astype(jnp.bfloat16) if fast_scan else o_f32)

    def q_body(qt):
        # ---- coarse: top-n_probes clusters per query
        scores, coarse_min = _coarse_scores(qt, centers, metric)
        _, probes = _sel(scores, n_probes, coarse_min)  # [t, P]

        g_idx = list_indices[probes]  # [t, P, pad]
        g_valid = valid_slot[probes]  # [t, P, pad]
        qf = qt.astype(jnp.float32)
        if use_pallas:
            from raft_tpu.ops import pallas_kernels as pk

            qv = jnp.broadcast_to(qf[:, None, :],
                                  (qt.shape[0], n_probes, dim))
            part = pk.ivf_scan(probes, qv, list_data, row_norms,
                               interpret=pallas_interpret)  # ||v||²−2q·v
            vn2 = row_norms[probes]
            dots = 0.5 * (vn2 - part)
            if metric == DistanceType.InnerProduct:
                d = dots
            elif metric == DistanceType.CosineExpanded:
                vn = jnp.sqrt(jnp.maximum(vn2, 1e-20))
                qn = jnp.sqrt(jnp.maximum(row_norms_sq(qf), 1e-20))
                d = 1.0 - dots / (vn * qn[:, None, None])
            else:
                qn2 = row_norms_sq(qf)
                d = jnp.maximum(qn2[:, None, None] + part, 0.0)
                if metric == DistanceType.L2SqrtExpanded:
                    d = jnp.sqrt(d)
        else:
            # ---- gather probed lists and scan
            g_data = list_data[probes]  # [t, P, pad, dim]
            if fast_scan:
                # bf16 MXU pass; norms stay exact fp32 (cached per-row)
                q_s, g_s = qt.astype(jnp.bfloat16), g_data.astype(jnp.bfloat16)
            else:
                q_s, g_s = qf, g_data.astype(jnp.float32)
            dots = jnp.einsum(
                "td,tpld->tpl", q_s, g_s,
                # HIGHEST only for true fp32 data on the accurate path;
                # int8/uint8/bf16 values are bf16-exact → single MXU pass
                precision=(jax.lax.Precision.HIGHEST
                           if (not fast_scan
                               and g_data.dtype == jnp.float32) else None),
                preferred_element_type=jnp.float32,
            )
            if metric == DistanceType.InnerProduct:
                d = dots
            else:
                # exact per-row norms: cached [L, pad] gather when available,
                # else recomputed from the gathered tile
                if row_norms is not None:
                    vn2 = row_norms[probes]
                else:
                    gf32 = g_data.astype(jnp.float32)
                    vn2 = jnp.sum(gf32 * gf32, -1)
                if metric == DistanceType.CosineExpanded:
                    vn = jnp.sqrt(jnp.maximum(vn2, 1e-20))
                    qn = jnp.sqrt(jnp.maximum(row_norms_sq(qf), 1e-20))
                    d = 1.0 - dots / (vn * qn[:, None, None])
                else:
                    qn2 = row_norms_sq(qf)
                    d = qn2[:, None, None] + vn2 - 2.0 * dots
                    d = jnp.maximum(d, 0.0)
                    if metric == DistanceType.L2SqrtExpanded:
                        d = jnp.sqrt(d)
        bad_fill = jnp.inf if minimize else -jnp.inf
        ok = g_valid
        if has_filter:
            ok = ok & bitset_filter_mask(g_idx, filter_words)
        d = jnp.where(ok, d, bad_fill)

        # ---- final top-k across all probed candidates (k may exceed the
        # candidate pool for tiny indexes; pad the tail with inf/-1)
        n_cand = n_probes * list_pad
        flat_d = d.reshape(qt.shape[0], n_cand)
        flat_i = g_idx.reshape(qt.shape[0], n_cand)
        flat_ok = ok.reshape(qt.shape[0], n_cand)
        if has_overflow:
            od, oi, o_ok = _overflow_scan(qt, qf, o_scan, o_norms, o_ok_base,
                                          overflow_indices, filter_words,
                                          metric, has_filter, fast_scan,
                                          bad_fill)
            flat_d = jnp.concatenate([flat_d, od], axis=1)
            flat_i = jnp.concatenate([flat_i, oi], axis=1)
            flat_ok = jnp.concatenate([flat_ok, o_ok], axis=1)
            n_cand += od.shape[1]
        kk = min(k, n_cand)
        if fast_scan:
            # bf16 expanded-L2 cancels catastrophically when distance gaps
            # are small next to vector norms (measured on v5e: recall
            # 0.9997 -> 0.57 on clustered data; CPU XLA upcasts bf16
            # matmuls, which is why CPU gates never caught it). Same cure
            # as brute_force's fast path: bf16 screen picks ~4k
            # candidates, exact fp32 re-rank orders them.
            k_ref = min(max(refine_mult * k, k + 8), n_cand)
            _, sel = _sel(flat_d, k_ref, minimize)
            cand_i = jnp.take_along_axis(flat_i, sel, axis=1)
            # re-mask from the real validity bits (pad + filter), the way
            # brute_force's re-rank does — screened-distance isfinite would
            # silently flip if a valid distance were ±inf or bad_fill ever
            # became finite
            cand_ok = jnp.take_along_axis(flat_ok, sel, axis=1)
            n_main = n_probes * list_pad
            sel_p = jnp.minimum(sel // list_pad, n_probes - 1)
            sel_s = sel % list_pad
            cand_list = jnp.take_along_axis(probes, sel_p, axis=1)
            main_vecs = list_data[cand_list, sel_s].astype(jnp.float32)
            if has_overflow:
                o_idx = jnp.clip(sel - n_main, 0, o_f32.shape[0] - 1)
                cand_vecs = jnp.where((sel < n_main)[:, :, None],
                                      main_vecs, o_f32[o_idx])
            else:
                cand_vecs = main_vecs
            exact = gathered_distances(qf, cand_vecs, metric)
            exact = jnp.where(cand_ok, exact, bad_fill)
            v, sel2 = select_k(exact, kk, select_min=minimize)
            i_out = jnp.take_along_axis(cand_i, sel2, axis=1)
        else:
            v, sel = _sel(flat_d, kk, minimize)
            i_out = jnp.take_along_axis(flat_i, sel, axis=1)
        if kk < k:
            v = jnp.pad(v, ((0, 0), (0, k - kk)), constant_values=bad_fill)
            i_out = jnp.pad(i_out, ((0, 0), (0, k - kk)), constant_values=-1)
        return v, i_out

    if n_q_tiles == 1:
        vals, idxs = q_body(qp)
    else:
        vals, idxs = jax.lax.map(
            q_body, qp.reshape(n_q_tiles, q_tile, dim)
        )
        vals = vals.reshape(-1, k)
        idxs = idxs.reshape(-1, k)
    return vals[:nq], idxs[:nq]


_search_jit = jax.jit(
    _search_core,
    static_argnames=("metric", "k", "n_probes", "q_tile", "has_filter",
                     "use_pallas", "pallas_interpret", "fast_scan",
                     "has_overflow", "select_recall", "refine_mult"),
)

#: public traceable-core name — the cross-package contract for the sharded
#: engine (parallel/sharded.py shard_maps this body) and the graftcheck
#: jaxpr audit; the underscore spelling stays package-private (R004)
search_core = _search_core


def _search_fused_core(queries, centers, list_data, list_indices, list_sizes,
                       row_norms, overflow_data, overflow_indices,
                       metric: DistanceType, k: int, n_probes: int,
                       pad_tile: int, has_overflow: bool,
                       interpret: bool = False):
    """Fused-Pallas search body (``scan_mode="pallas"``, L2 metrics only):
    coarse selection stays XLA, then the probed slabs are DMA'd straight
    to VMEM and merged into an in-kernel top-k carry
    (``ops.pallas_kernels.fused_ivf_topk``) — the [nq, P, pad] candidate
    slab never materializes in HBM and no ``select_k``/TOPK_PAD padding
    applies to the fine scan. Overflow rows (spilled past the capped
    list_pad) are scanned by the XLA brute pass in squared space and
    merged with the kernel's survivors through one unpadded ``select_k``."""
    from raft_tpu.ops import pallas_kernels as pk

    nq, dim = queries.shape
    list_pad = list_data.shape[1]
    qf = queries.astype(jnp.float32)

    # ---- coarse: top-n_probes clusters per query (XLA, tiny)
    scores, coarse_min = _coarse_scores(queries, centers, metric)
    _, probes = select_k(scores, n_probes, select_min=coarse_min)

    # unfilled slots must carry the -1 null id the kernel masks on; the
    # class invariant already puts -1 there, this re-derives it from
    # list_sizes so a stale slot can never alias a real row
    valid_slot = jnp.arange(list_pad)[None, :] < list_sizes[:, None]
    safe_ids = jnp.where(valid_slot, list_indices, -1)

    qv = jnp.broadcast_to(qf[:, None, :], (nq, n_probes, dim))
    qn = jnp.broadcast_to(row_norms_sq(qf)[:, None], (nq, n_probes))
    v, i = pk.fused_ivf_topk(probes, qv, qn, list_data, row_norms, safe_ids,
                             k, pad_tile=pad_tile, clamp=True,
                             interpret=interpret)

    if has_overflow:
        o_f32 = overflow_data.astype(jnp.float32)
        od, oi, _ = _overflow_scan(
            queries, qf, o_f32, row_norms_sq(o_f32),
            overflow_indices >= 0, overflow_indices,
            jnp.zeros((0,), jnp.uint32),
            # squared space: the kernel's carry is squared-L2; one sqrt at
            # the end covers both sources
            DistanceType.L2Expanded, False, False, jnp.inf)
        cand_v = jnp.concatenate([v, od], axis=1)
        cand_i = jnp.concatenate([i, oi], axis=1)
        # selection already happened in-kernel — the merge select runs with
        # pad_rules=False so TOPK_PAD cannot double-pad it (ISSUE 10)
        v, i = select_k(cand_v, k, select_min=True, indices=cand_i,
                        pad_rules=False)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


_search_fused_jit = jax.jit(
    _search_fused_core,
    static_argnames=("metric", "k", "n_probes", "pad_tile", "has_overflow",
                     "interpret"),
)

#: public traceable-core name for the fused path (R004; audited by
#: graftcheck --jaxpr-audit at the VMEM-budget canonical shape)
search_fused_core = _search_fused_core


def scan_bytes_per_query(n_probes: int, list_pad: int, dim: int) -> int:
    """TRUE peak live-set bytes of the flat scan per query: the gathered
    probe tile [P, pad, dim] fp32, ×2 for the distance/score temporaries
    live with it. The itemized accounting ``plan_scan_tiles`` solves
    against — public so the obs.costs calibration audit can compare the
    planner's prediction to the compiled ``memory_analysis`` truth."""
    return n_probes * list_pad * dim * 4 * 2


def plan_scan_tiles(n_probes: int, list_pad: int, dim: int,
                    workspace_limit_bytes: int) -> int:
    """q_tile from the workspace budget: the gathered probe tile is
    [q_tile, n_probes, list_pad, dim] fp32, ×2 for the distance/score
    temporaries that are live with it (shared by ``search`` and the
    graftcheck jaxpr audit, which certifies the solve statically)."""
    per_q = scan_bytes_per_query(n_probes, list_pad, dim)
    q_tile = int(np.clip(workspace_limit_bytes // max(per_q, 1), 1, 1024))
    if q_tile >= 8:
        q_tile -= q_tile % 8
    return q_tile


@tracing.range("ivf_flat.search")
def search(
    index: Index,
    queries,
    k: int,
    params: Optional[SearchParams] = None,
    filter: Optional[Bitset] = None,
    res: Optional[Resources] = None,
    explain: bool = False,
):
    """Search (reference: ivf_flat::search, ivf_flat-inl.cuh:430).

    Returns (distances [nq, k], indices [nq, k]); indices are source row ids,
    -1 where fewer than k valid candidates were probed. With
    ``explain=True`` a third element carries the
    :class:`raft_tpu.obs.explain.ExplainRecord` of the dispatch decision.
    """
    params = params or SearchParams()
    res = ensure_resources(res)
    if index.list_data is None:
        raise ValueError("index has no data; call extend() first")
    queries = as_query_array(queries)  # host inputs stay host-side: the
    if queries.shape[1] != index.dim:  # jit call transfers the padded
        raise ValueError(              # batch in ONE dispatch
            f"query dim {queries.shape[1]} != index dim {index.dim}")
    nq = queries.shape[0]
    queries = pad_rows(queries, query_bucket(nq))  # serving batch bucket
    n_probes = int(min(params.n_probes, index.n_lists))
    list_pad = index.list_data.shape[1]
    q_tile = plan_scan_tiles(n_probes, list_pad, index.dim,
                             res.workspace_limit_bytes)
    from raft_tpu.ops import pallas_kernels as pk

    fast_scan = params.scan_dtype is not None
    scan_mode = getattr(params, "scan_mode", "auto")
    if scan_mode not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"scan_mode={scan_mode!r}: expected 'auto', 'xla' or 'pallas'")
    if fast_scan:
        if jnp.dtype(params.scan_dtype) != jnp.bfloat16:
            raise ValueError(
                f"scan_dtype={params.scan_dtype!r}: only bfloat16 is supported")
        if index.list_data.dtype != jnp.float32:
            raise ValueError("scan_dtype requires fp32 list data")
    has_overflow = index.overflow_data.shape[0] > 0
    # ---- fused Pallas scan+select (the VMEM top-k carry). Fallback
    # matrix (docs/tuning.md): L2 metrics, no filter (no in-carry filter
    # epilogue), no bf16 fast scan, small k.
    use_fused, fused_interp, dreason = pk.fused_dispatch_explained(
        "ivf_flat", scan_mode)
    ineligible = fused_ineligible_reason(
        index.metric, index.list_data.dtype, int(k), filter is not None,
        fast_scan, require_float=False)
    ex_params = {"k": int(k), "nq": nq, "bucket": queries.shape[0],
                 "n_probes": n_probes, "n_lists": index.n_lists,
                 "list_pad": list_pad, "dim": index.dim,
                 "metric": index.metric.name}
    with contextlib.ExitStack() as stack:
        cap = stack.enter_context(obs_explain.capture()) if explain else None
        if use_fused and ineligible is None:
            pad_tile = pk.plan_fused_ivf_tile(
                list_pad, index.dim, int(k),
                jnp.dtype(index.list_data.dtype).itemsize)
            obs_explain.record_dispatch(
                "ivf_flat", scan_mode, "pallas", dreason, params=ex_params,
                plan={"pad_tile": pad_tile, "interpret": fused_interp})
            v, i = _search_fused_jit(
                queries, index.centers, index.list_data, index.list_indices,
                index.list_sizes, index.ensure_row_norms(),
                index.overflow_data, index.overflow_indices,
                index.metric, int(k), n_probes, pad_tile, has_overflow,
                fused_interp,
            )
        else:
            # The unfused ivf_scan kernel only routes where a committed probe
            # artifact shows it beating XLA — PALLAS_PROBE_tpu.json currently
            # says it does not (22.3 ms vs 10.9 ms), so this stays off
            # without a measured verdict; the RAFT_TPU_PALLAS=1 env override
            # is retired. An explicit bf16 request still wins over any fp32
            # Pallas scan — never silently benchmark fp32 under a bf16 label.
            use_pallas = pk.fused_crossover("ivf_scan") and not fast_scan
            reason = ineligible if (use_fused and ineligible) else dreason
            obs_explain.record_dispatch(
                "ivf_flat", scan_mode, "xla", reason, params=ex_params,
                plan={"q_tile": q_tile, "unfused_ivf_scan": use_pallas,
                      "predicted_workspace_bytes": q_tile *
                      scan_bytes_per_query(n_probes, list_pad, index.dim)})
            # Cached exact norms are required by the Pallas path and the bf16
            # fast scan; the plain XLA path keeps computing norms per probed
            # tile instead (materializing [L, pad] fp32 norms for a large
            # narrow-dtype index is a needless device-memory spike there).
            need_norms = use_pallas or (
                fast_scan and index.metric != DistanceType.InnerProduct)
            v, i = _search_jit(
                queries, index.centers, index.list_data, index.list_indices,
                index.list_sizes,
                filter.words if filter is not None
                else jnp.zeros((0,), jnp.uint32),
                index.metric, int(k), n_probes, q_tile, filter is not None,
                index.ensure_row_norms() if need_norms else None, use_pallas,
                False, fast_scan, index.overflow_data, index.overflow_indices,
                has_overflow, float(params.select_recall),
                refine_multiplier(params.refine_ratio, fast_scan),
            )
    if explain:
        return v[:nq], i[:nq], cap.last
    return v[:nq], i[:nq]


_SERIAL_VERSION = 2  # v2: + list_pad_expansion, overflow block


def serialize(index: Index, file) -> None:
    """reference: detail/ivf_flat_serialize.cuh. Paths are written
    atomically (tmp + os.replace) with per-record crc framing."""
    if index.list_data is None:
        raise ValueError("index has no data; call extend() before serialize()")
    with ser.writer_for(file) as stream:
        w = ser.IndexWriter(stream, "ivf_flat", _SERIAL_VERSION)
        w.scalar(int(index.metric), "<i4")
        w.scalar(index.params.n_lists, "<i8")
        w.scalar(index.params.kmeans_n_iters, "<i4")
        w.scalar(index.params.kmeans_trainset_fraction, "<f8")
        w.scalar(1 if index.params.adaptive_centers else 0, "<i4")
        w.scalar(index.params.list_pad_expansion, "<f8")
        w.scalar(index.n_rows, "<i8")
        w.array(index.centers)
        w.array(index.list_data)
        w.array(index.list_indices)
        w.array(index.list_sizes)
        w.array(index.overflow_data)
        w.array(index.overflow_indices)
        w.finish()


def deserialize(file, res: Optional[Resources] = None) -> Index:
    ensure_resources(res)
    with ser.reader_for(file) as stream:
        r = ser.IndexReader(stream, "ivf_flat", _SERIAL_VERSION)
        metric = DistanceType(r.scalar())
        params = IndexParams(
            n_lists=r.scalar(), metric=metric, kmeans_n_iters=r.scalar(),
            kmeans_trainset_fraction=r.scalar(),
            adaptive_centers=bool(r.scalar()),
            # v1 files predate the capped pad: max-driven layout, no spill
            list_pad_expansion=r.scalar() if r.version >= 2 else 1e30,
        )
        n_rows = r.scalar()
        centers = jnp.asarray(r.array())
        data = jnp.asarray(r.array())
        idxs = jnp.asarray(r.array())
        sizes = jnp.asarray(r.array())
        over_rows = jnp.asarray(r.array()) if r.version >= 2 else None
        over_ids = jnp.asarray(r.array()) if r.version >= 2 else None
        r.finish()
        return Index(params, centers, data, idxs, sizes, n_rows,
                     over_rows, over_ids)


# ------------------------------------------------------------------ helpers


class helpers:
    """List-data access utilities (reference: ivf_flat_helpers.cuh /
    ivf_flat_codepacker.hpp — ``helpers::codepacker::{pack,unpack}``).
    Our list storage is already a padded dense block, so pack/unpack are
    plain placements rather than interleaved-group bit shuffles."""

    @staticmethod
    def unpack_list_data(index: "Index", label: int) -> np.ndarray:
        """Valid vectors of list ``label`` → [size, dim] host array."""
        size = int(np.asarray(index.list_sizes)[label])
        return np.asarray(index.list_data)[label, :size]

    @staticmethod
    def unpack_list_ids(index: "Index", label: int) -> np.ndarray:
        size = int(np.asarray(index.list_sizes)[label])
        return np.asarray(index.list_indices)[label, :size]

    @staticmethod
    def pack_list_data(index: "Index", label: int, vectors,
                       ids=None) -> "Index":
        """Overwrite list ``label`` with ``vectors`` (and optional ids);
        returns a new Index (functional analog of in-place pack)."""
        vectors = np.asarray(vectors, np.asarray(index.list_data).dtype)
        n_new = len(vectors)
        pad = index.list_data.shape[1]
        if n_new > pad:
            raise ValueError(f"{n_new} vectors exceed list capacity {pad}")
        data = np.asarray(index.list_data).copy()
        idxs = np.asarray(index.list_indices).copy()
        sizes = np.asarray(index.list_sizes).copy()
        data[label, :n_new] = vectors
        data[label, n_new:] = 0
        if ids is not None:
            idxs[label, :n_new] = np.asarray(ids, np.int32)
        idxs[label, n_new:] = -1
        old = int(sizes[label])
        sizes[label] = n_new
        n_rows = index.n_rows - old + n_new
        return Index(index.params, index.centers, jnp.asarray(data),
                     jnp.asarray(idxs), jnp.asarray(sizes), n_rows)
