"""Brute-force (exact) k-nearest neighbors.

Reference: ``raft::neighbors::brute_force`` (neighbors/brute_force-inl.cuh,
detail/knn_brute_force.cuh) — ``tiled_brute_force_knn`` picks tile sizes from
free memory (:84), precomputes row norms (:97-136), runs a cuBLAS gemm +
epilogue per tile, ``select_k`` per tile, then ``knn_merge_parts``
(detail/knn_merge_parts.cuh). A persistent ``brute_force::index`` caches the
dataset and its norms (brute_force_types.hpp).

TPU-native design: the distance tile is a bf16/fp32 ``dot_general`` on the MXU
with the metric epilogue fused by XLA; per-tile top-k via ``select_k``; tiles
merged pairwise by concatenating the k-candidate lists and re-selecting —
identical math to knn_merge_parts but expressed as one more top-k. Query
batches stream through a ``lax.map`` so HBM holds only [q_tile, db_tile]
distances. Doubles as the exact ground-truth oracle for ANN tests (replacing
the reference's internal naive_knn.cuh:82).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import serialize as ser
from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import (
    DistanceType,
    cosine_expanded,
    gathered_distances,
    inner_product,
    is_min_close,
    l2_expanded,
    resolve_metric,
    row_norms_sq,
    pairwise_core,
)
from raft_tpu.obs import explain as obs_explain
from raft_tpu.ops import pallas_kernels as pk
from raft_tpu.ops.select_k import (refine_multiplier, select_k,
                                   select_k_maybe_approx)
from raft_tpu.utils.shape import (as_query_array, balanced_tile, cdiv, pad_rows,
                                  query_bucket)


class Index:
    """Persistent brute-force index: dataset + cached norms
    (reference: brute_force_types.hpp)."""

    def __init__(self, dataset: jax.Array, metric: DistanceType, metric_arg: float,
                 norms: Optional[jax.Array] = None):
        self.dataset = dataset
        self.metric = metric
        self.metric_arg = metric_arg
        self.norms = norms

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]


@tracing.range("brute_force.build")
def build(dataset, metric="euclidean", metric_arg: float = 2.0,
          res: Optional[Resources] = None) -> Index:
    """Build = store dataset + precompute norms for expanded metrics
    (reference: brute_force::build, brute_force-inl.cuh)."""
    ensure_resources(res)
    dataset = jnp.asarray(dataset)
    m = resolve_metric(metric)
    norms = None
    if m in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
             DistanceType.CosineExpanded):
        norms = row_norms_sq(dataset)
    return Index(dataset, m, float(metric_arg), norms)


def _choose_tiles(n_queries: int, n_db: int, dim: int, k: int, budget: int
                  ) -> Tuple[int, int]:
    """Pick (query_tile, db_tile) so the distance tile fits the workspace
    budget (analog of chooseTileSize, detail/knn_brute_force.cuh:84).

    The budget pays for (a) one whole-dataset pad copy that stays live
    across the scan (the tile reshape needs n_db rounded up to the tile)
    and (b) ~5 concurrent fp32 tiles in the expanded-L2 chain
    (dot, norm-add, clamp, mask-select, top-k negation) — the graftcheck
    jaxpr audit certifies the resulting peak statically; the old solve
    charged only 4 tiles and no pad copy and overshot by ~25%."""
    q_tile = balanced_tile(n_queries, min(n_queries, 1024), 8)
    pad_copy = n_db * dim * 4
    avail = max(budget - pad_copy, budget // 4)
    db_budget = max(avail // (5 * max(q_tile, 1) * 4), 1)
    db_tile = min(n_db, max(db_budget, 4 * k, 1024))
    return q_tile, balanced_tile(n_db, db_tile, 128)


#: public planner name — consumed by the graftcheck jaxpr audit, which
#: certifies the solve statically against the workspace budget (R004)
choose_tiles = _choose_tiles


def planned_peak_bytes(n_queries: int, n_db: int, dim: int, k: int,
                       budget: int) -> int:
    """The peak live set ``choose_tiles`` believes its solve yields: the
    whole-dataset pad copy plus the 5 concurrent fp32 distance tiles of
    the expanded-L2 chain at the planned (q_tile, db_tile). Public so the
    obs.costs calibration audit can compare this prediction against the
    compiled ``memory_analysis`` ground truth at the same shape."""
    q_tile, db_tile = _choose_tiles(n_queries, n_db, dim, k, budget)
    return n_db * dim * 4 + 5 * q_tile * db_tile * 4


#: metrics eligible for the bf16 fast-scan (their scan is one MXU matmul and
#: their exact distance is recoverable from gathered candidates at refine)
_FAST_SCAN_METRICS = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.CosineExpanded,
    DistanceType.InnerProduct,
)


@functools.partial(
    jax.jit,
    static_argnames=("metric", "metric_arg", "k", "q_tile", "db_tile",
                     "budget", "has_filter", "fast_scan", "refine_mult",
                     "select_recall"),
)
def _knn_jit(queries, dataset, db_norms, filter_words, metric, metric_arg, k,
             q_tile, db_tile, budget, has_filter: bool = False,
             fast_scan: bool = False, refine_mult: int = 4,
             select_recall: float = 1.0):
    nq, dim = queries.shape
    ndb = dataset.shape[0]
    minimize = is_min_close(metric)

    def _sel(vals, kk, sel_min):
        return select_k_maybe_approx(vals, kk, sel_min, select_recall)
    use_cached_norms = db_norms is not None and metric in (
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.CosineExpanded,
    )

    n_db_tiles = cdiv(ndb, db_tile)
    db_pad = n_db_tiles * db_tile - ndb
    n_q_tiles = cdiv(nq, q_tile)
    q_pad = n_q_tiles * q_tile - nq

    qp = jnp.pad(queries, ((0, q_pad), (0, 0)))
    # Pad DB once; padded rows get +inf (or -inf for max-close) distances.
    dbp = jnp.pad(dataset, ((0, db_pad), (0, 0)))
    need_norms = use_cached_norms or (
        fast_scan and metric != DistanceType.InnerProduct)
    if use_cached_norms:
        dbn = jnp.pad(db_norms, (0, db_pad))
    elif need_norms:
        dbn = row_norms_sq(dbp)
    else:
        dbn = None
    pad_bad = jnp.arange(n_db_tiles * db_tile) >= ndb
    bad_fill = jnp.inf if minimize else -jnp.inf
    # Fast scan over-selects candidates; exact fp32 re-rank recovers them.
    k_scan = min(refine_mult * k, db_tile) if fast_scan else min(k, db_tile)
    # Refine pool must still hold >= k candidates when db_tile < k; the
    # merged pool has n_db_tiles*k_scan >= k entries, so this never exceeds it.
    k_refine = max(k_scan, k)

    def _filter_pass(ids):
        """Packed-bitset test for row ids (shared by scan + refine)."""
        words = filter_words[jnp.minimum(ids // 32, filter_words.shape[0] - 1)]
        return ((words >> (ids % 32).astype(jnp.uint32)) & 1).astype(bool)

    def q_body(qt):
        # Query-tile norms hoisted out of the db-tile loop (analog of the
        # reference's rowNorm precompute, detail/knn_brute_force.cuh:97-136).
        qt_norms = row_norms_sq(qt) if need_norms else None
        qt_bf = qt.astype(jnp.bfloat16) if fast_scan else None

        def db_body(t):
            db_t = jax.lax.dynamic_slice_in_dim(dbp, t * db_tile, db_tile, 0)
            if fast_scan:
                # Single-pass bf16 MXU matmul (the TPU analog of the
                # reference's TF32/CUTLASS fast path, dispatch_sm80.cuh):
                # bf16 inputs take _dot's fast-precision path while the
                # precomputed norms stay fp32, so only the cross term is
                # approximate. Ranking-only score: sqrt skipped for
                # L2SqrtExpanded (monotone); exact distances come from the
                # refine stage.
                db_bf = db_t.astype(jnp.bfloat16)
                if metric == DistanceType.InnerProduct:
                    d = inner_product(qt_bf, db_bf)
                elif metric == DistanceType.CosineExpanded:
                    dbn_t = jax.lax.dynamic_slice_in_dim(
                        dbn, t * db_tile, db_tile, 0)
                    d = cosine_expanded(qt_bf, db_bf, x_norms=qt_norms,
                                        y_norms=dbn_t)
                else:
                    dbn_t = jax.lax.dynamic_slice_in_dim(
                        dbn, t * db_tile, db_tile, 0)
                    d = l2_expanded(qt_bf, db_bf, sqrt=False,
                                    x_norms=qt_norms, y_norms=dbn_t)
            elif use_cached_norms:
                dbn_t = jax.lax.dynamic_slice_in_dim(dbn, t * db_tile, db_tile, 0)
                if metric == DistanceType.CosineExpanded:
                    d = cosine_expanded(qt, db_t, x_norms=qt_norms, y_norms=dbn_t)
                else:
                    d = l2_expanded(
                        qt, db_t, sqrt=(metric == DistanceType.L2SqrtExpanded),
                        x_norms=qt_norms, y_norms=dbn_t,
                    )
            else:
                d = pairwise_core(qt, db_t, metric, metric_arg, budget)
            bad = jax.lax.dynamic_slice_in_dim(pad_bad, t * db_tile, db_tile, 0)
            if has_filter:
                # bitset prefilter in the tile epilogue (reference:
                # bitset_filter, sample_filter_types.hpp:55-82)
                bad = bad | ~_filter_pass(t * db_tile + jnp.arange(db_tile))
            d = jnp.where(bad[None, :], bad_fill, d)
            v, i = _sel(d, k_scan, minimize)
            return v, i + t * db_tile

        tile_v, tile_i = jax.lax.map(db_body, jnp.arange(n_db_tiles))
        # Merge parts: concat candidates over tiles, re-select (the analog of
        # knn_merge_parts' pairwise heap merge).
        kk = tile_v.shape[-1]
        all_v = jnp.moveaxis(tile_v, 0, 1).reshape(q_tile, n_db_tiles * kk)
        all_i = jnp.moveaxis(tile_i, 0, 1).reshape(q_tile, n_db_tiles * kk)
        if fast_scan:
            # Exact fp32 re-rank of the scanned candidates (reference analog:
            # neighbors::refine over a coarse candidate list).
            _, sel = _sel(all_v, min(k_refine, all_v.shape[-1]), minimize)
            cand_i = jnp.take_along_axis(all_i, sel, axis=1)
            cand_vecs = jnp.take(dbp, cand_i, axis=0)  # [q_tile, k_ref, dim]
            exact = gathered_distances(qt, cand_vecs, metric)
            # Re-mask padded/filtered rows (their gathered distance is real).
            bad_rows = jnp.take(pad_bad, cand_i)
            if has_filter:
                bad_rows = bad_rows | ~_filter_pass(cand_i)
            exact = jnp.where(bad_rows, bad_fill, exact)
            v, sel2 = select_k(exact, k, select_min=minimize)
            return v, jnp.take_along_axis(cand_i, sel2, axis=1)
        v, sel = select_k(all_v, k, select_min=minimize)
        return v, jnp.take_along_axis(all_i, sel, axis=1)

    if n_q_tiles == 1:
        vals, idxs = q_body(qp)
    else:
        vq = jax.lax.map(q_body, qp.reshape(n_q_tiles, q_tile, dim))
        vals = vq[0].reshape(-1, k)
        idxs = vq[1].reshape(-1, k)
    return vals[:nq], idxs[:nq]


#: public traceable-core name — consumed by the graftcheck jaxpr audit
#: (R004: the underscore spelling stays package-private)
knn_core = _knn_jit


@functools.partial(
    jax.jit, static_argnames=("k", "tm", "tn", "sqrt", "interpret"))
def _knn_fused_jit(queries, dataset, db_norms, k: int, tm: int, tn: int,
                   sqrt: bool, interpret: bool):
    """Fused-Pallas brute-force core: the [nq, ndb] distance slab never
    touches HBM — each [tm, tn] tile feeds the VMEM-resident top-k carry
    (``ops.pallas_kernels.fused_l2_topk``). Selection happens in-kernel,
    so no ``select_k`` call and no TOPK_PAD padding applies here."""
    qn = row_norms_sq(queries)
    dbn = row_norms_sq(dataset) if db_norms is None else db_norms
    v, i = pk.fused_l2_topk(queries, dataset, k, x_norms=qn, y_norms=dbn,
                            tm=tm, tn=tn, interpret=interpret)
    if sqrt:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


#: public traceable-core name for the fused path (R004; audited by
#: graftcheck --jaxpr-audit at the VMEM-budget canonical shape)
knn_fused_core = _knn_fused_jit


#: metrics the fused scan+select kernel serves exactly (the minimize-only
#: VMEM carry is not rank-safe for IP/cosine without negation plumbing)
_FUSED_SCAN_METRICS = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
)


def _fused_eligible(index: Index, k: int, has_filter: bool,
                    fast_scan: bool) -> bool:
    """The fallback matrix for ``scan_mode="pallas"`` (docs/tuning.md):
    L2 metrics, float data, small k, no bitset filter (the kernel has no
    in-carry filter epilogue), not combined with the bf16 fast scan."""
    return fused_ineligible_reason(index.metric, index.dataset.dtype, k,
                                   has_filter, fast_scan) is None


def fused_ineligible_reason(metric, dtype, k: int, has_filter: bool,
                            fast_scan: bool,
                            require_float: bool = True) -> Optional[str]:
    """First failing clause of the fused fallback matrix as an
    ``obs.explain`` reason code, or None when fully eligible — shared by
    brute_force and ivf_flat (same conjunction, except ivf_flat's fused
    scan accepts narrow list dtypes → ``require_float=False``) so the
    explain record names the same cause docs/tuning.md documents."""
    if metric not in _FUSED_SCAN_METRICS:
        return "non_l2"
    if has_filter:
        return "filtered"
    if fast_scan:
        return "fast_scan"
    if k > 1024:
        return "k_gt_1024"
    if require_float and not jnp.issubdtype(dtype, jnp.floating):
        return "non_float_dtype"
    return None


@tracing.range("brute_force.search")
def search(index: Index, queries, k: int, filter=None,
           res: Optional[Resources] = None, scan_dtype=None,
           refine_ratio: float = 4.0,
           select_recall: float = 1.0,
           scan_mode: str = "auto",
           explain: bool = False):
    """Exact kNN search → (distances [nq, k], indices [nq, k]).

    ``filter`` is an optional :class:`raft_tpu.core.bitset.Bitset` over
    database row ids; cleared bits are excluded (reference: the
    bitset_filter overloads of brute_force::search).

    ``scan_dtype="bfloat16"`` (fp32 data, expanded-L2/cosine/inner-product
    metrics only) runs the distance matmul as a single bf16 MXU pass and
    exactly re-ranks the top ``refine_ratio·k`` candidates in fp32 — the TPU
    analog of the reference's TF32/CUTLASS Ampere path (detail/
    pairwise_matrix/dispatch_sm80.cuh). Returned distances are exact fp32;
    ranking is exact except for candidates the bf16 screen misses
    (recall ≥ 0.999 at refine_ratio=4 in practice).

    ``scan_mode`` selects the scan/select engine: ``"xla"`` forces the
    tiled XLA two-step, ``"pallas"`` requests the fused Pallas
    scan+select kernel (VMEM-resident top-k carry, docs/tuning.md), and
    ``"auto"`` picks pallas on TPU only where the committed probe artifact
    shows it winning. Unsupported combinations (non-L2 metric, filter,
    fast scan, k > 1024, CPU without the interpret hook) fall back to XLA
    silently — the mode is a performance hint, never a correctness
    switch. Every resolution is attributed: a reason-coded dispatch
    counter increments per call, and ``explain=True`` additionally
    returns ``(distances, indices, ExplainRecord)``."""
    res = ensure_resources(res)
    if scan_mode not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"scan_mode={scan_mode!r}: expected 'auto', 'xla' or 'pallas'")
    # host inputs stay host-side: the jit call transfers the padded
    # batch in ONE dispatch
    queries = as_query_array(queries, dtype=index.dataset.dtype)
    if queries.shape[1] != index.dim:
        raise ValueError(f"query dim {queries.shape[1]} != index dim {index.dim}")
    k = int(min(k, index.size))
    fast_scan = scan_dtype is not None
    if fast_scan:
        if jnp.dtype(scan_dtype) != jnp.bfloat16:
            raise ValueError(
                f"scan_dtype={scan_dtype!r}: only bfloat16 is supported")
        if index.dataset.dtype != jnp.float32:
            raise ValueError(
                "scan_dtype requires an fp32 dataset (narrow dtypes already "
                "take the fast MXU path)")
        if index.metric not in _FAST_SCAN_METRICS:
            raise ValueError(
                f"scan_dtype unsupported for metric {index.metric.name}; "
                "eligible: L2Expanded/L2SqrtExpanded/CosineExpanded/"
                "InnerProduct")
    refine_mult = refine_multiplier(refine_ratio, fast_scan)
    nq = queries.shape[0]
    queries = pad_rows(queries, query_bucket(nq))  # serving batch bucket
    use_fused, fused_interp, dreason = pk.fused_dispatch_explained(
        "brute_force", scan_mode)
    ineligible = fused_ineligible_reason(
        index.metric, index.dataset.dtype, k, filter is not None, fast_scan)
    ex_params = {"k": k, "nq": nq, "bucket": queries.shape[0],
                 "n_db": index.size, "dim": index.dim,
                 "metric": index.metric.name}
    with contextlib.ExitStack() as stack:
        cap = stack.enter_context(obs_explain.capture()) if explain else None
        if use_fused and ineligible is None:
            tm, tn = pk.plan_fused_topk_tiles(
                queries.shape[0], index.size, index.dim, k)
            obs_explain.record_dispatch(
                "brute_force", scan_mode, "pallas", dreason,
                params=ex_params, plan={"tm": tm, "tn": tn,
                                        "interpret": fused_interp})
            v, i = _knn_fused_jit(
                queries, index.dataset, index.norms, k, tm, tn,
                index.metric == DistanceType.L2SqrtExpanded, fused_interp)
        else:
            q_tile, db_tile = _choose_tiles(
                queries.shape[0], index.size, index.dim, k,
                res.workspace_limit_bytes)
            if fast_scan:
                # Budget the refine gather too: [q_tile, k_refine, dim] fp32
                # candidates must fit the workspace like the scan tile does.
                k_refine = max(min(refine_mult * k, db_tile), k)
                per_row = k_refine * index.dim * 4
                q_cap = max(
                    8, res.workspace_limit_bytes // (4 * max(per_row, 1)))
                q_tile = min(q_tile, q_cap - q_cap % 8 or 8)
            # fused was dispatchable but this request's shape wasn't
            # eligible -> the matrix clause outranks the dispatch verdict
            reason = ineligible if (use_fused and ineligible) else dreason
            obs_explain.record_dispatch(
                "brute_force", scan_mode, "xla", reason, params=ex_params,
                plan={"q_tile": q_tile, "db_tile": db_tile,
                      "predicted_peak_bytes": planned_peak_bytes(
                          queries.shape[0], index.size, index.dim, k,
                          res.workspace_limit_bytes)})
            v, i = _knn_jit(
                queries, index.dataset, index.norms,
                filter.words if filter is not None
                else jnp.zeros((0,), jnp.uint32),
                index.metric, index.metric_arg,
                k, q_tile, db_tile, res.workspace_limit_bytes,
                filter is not None, fast_scan, refine_mult,
                select_recall=float(select_recall),
            )
    if explain:
        return v[:nq], i[:nq], cap.last
    return v[:nq], i[:nq]


@tracing.range("brute_force.knn")
def knn(queries, dataset, k: int, metric="euclidean", metric_arg: float = 2.0,
        res: Optional[Resources] = None, scan_dtype=None,
        refine_ratio: float = 4.0,
        select_recall: float = 1.0,
        scan_mode: str = "auto", explain: bool = False):
    """One-shot exact kNN (reference: brute_force::knn)."""
    return search(build(dataset, metric, metric_arg, res), queries, k,
                  res=res, scan_dtype=scan_dtype, refine_ratio=refine_ratio,
                  select_recall=select_recall, scan_mode=scan_mode,
                  explain=explain)


_SERIAL_VERSION = 1


def serialize(index: Index, file) -> None:
    """Write index (reference: brute_force_serialize.cuh). Paths are
    written atomically (tmp + os.replace) with per-record crc framing."""
    with ser.writer_for(file) as stream:
        w = ser.IndexWriter(stream, "brute_force", _SERIAL_VERSION)
        w.scalar(int(index.metric), "<i4").scalar(index.metric_arg, "<f8")
        w.array(index.dataset)
        w.scalar(1 if index.norms is not None else 0, "<i4")
        if index.norms is not None:
            w.array(index.norms)
        w.finish()


def deserialize(file, res: Optional[Resources] = None) -> Index:
    ensure_resources(res)
    with ser.reader_for(file) as stream:
        r = ser.IndexReader(stream, "brute_force", _SERIAL_VERSION)
        metric = DistanceType(r.scalar())
        metric_arg = r.scalar()
        dataset = jnp.asarray(r.array())
        norms = jnp.asarray(r.array()) if r.scalar() else None
        r.finish()
        return Index(dataset, metric, metric_arg, norms)


def make_batch_k_query(index: Index, queries, batch_size: int,
                       res: Optional[Resources] = None):
    """Iterate over each query's neighbor list in batches of ``batch_size``:
    the first yield holds the nearest ``batch_size`` neighbors, the next the
    following ``batch_size``, … (reference: brute_force::make_batch_k_query,
    detail/knn_brute_force_batch_k_query.cuh).

    The searched k grows geometrically and several batches are sliced from
    each result, so draining n neighbors costs O(log(n/batch_size)) searches
    (and compilations) rather than one per batch."""
    res = ensure_resources(res)
    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")

    def _iter():
        offset = 0
        k = 0
        d = i = None
        while offset < index.size:
            if offset + batch_size > k:  # widen: double, at least 4 batches
                k = min(max(4 * batch_size, 2 * k), index.size)
                d, i = search(index, queries, k, res=res)
            end = min(offset + batch_size, index.size)
            yield d[:, offset:end], i[:, offset:end]
            offset = end

    return _iter()
