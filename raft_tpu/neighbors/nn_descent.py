"""NN-descent — all-neighbors kNN-graph construction.

Reference: ``raft::neighbors::experimental::nn_descent`` (neighbors/
nn_descent.cuh, nn_descent_types.hpp; detail/nn_descent.cuh — GNND: bloom-
filter sampling of new/old neighbors :319-330, ``local_join`` :358, reverse-
edge insertion :499-510, ``BuildConfig`` :212).

TPU-native design: the GPU GNND's scatter-heavy local join (every candidate
pair scatters into two per-node heaps guarded by locks) is a poor fit for
XLA's functional model. We reformulate each NN-descent round as a **gather +
matmul + merge** pipeline that keeps GNND's two load-bearing mechanisms:

- **new/old edge flags** (detail/nn_descent.cuh:319-330): every edge starts
  "new"; each round a node expands its closest still-new neighbors (their
  whole adjacency becomes candidates) and marks them joined, so converged
  neighborhoods stop generating work — the functional analog of GNND's
  flag-clearing sampled lists. Flags ride the merged top-k buffer
  (duplicate collapse ORs the flag, ops/select_k.merge_topk_dedup_flagged).
- **symmetric local join** (:358, :499-510): besides forward 2-hop
  candidates (v ∈ G(u), u ∈ G(i)), each round expands sampled *reverse*
  neighbors u (i ∈ G(u)) — their lists supply exactly the (i, v) pairs
  with i, v ∈ G(u) that GNND's pair join produces; without this, edges
  only propagate along the forward direction and clustered data stalls.

Each round: flag-preferring candidate generation → exact distances in one
tiled einsum (MXU) → flagged top-k merge with duplicate + self
suppression. A ``while_loop`` with the update-rate termination threshold
(BuildConfig, :212) bounds iterations inside one XLA program.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import (
    DistanceType,
    gathered_distances,
    resolve_metric,
)
from raft_tpu.ops.select_k import merge_topk_dedup, merge_topk_dedup_flagged
from raft_tpu.utils.shape import cdiv


@dataclasses.dataclass
class IndexParams:
    """reference: nn_descent_types.hpp index_params — graph_degree,
    intermediate_graph_degree, max_iterations, termination_threshold."""

    graph_degree: int = 64
    intermediate_graph_degree: int = 128
    max_iterations: int = 20
    termination_threshold: float = 0.0001
    metric: DistanceType = DistanceType.L2Expanded

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.metric not in (DistanceType.L2Expanded,
                               DistanceType.L2SqrtExpanded,
                               DistanceType.InnerProduct,
                               DistanceType.CosineExpanded):
            raise ValueError(
                f"nn_descent supports L2/IP/Cosine, got {self.metric.name}")


def _candidate_distances(x, cand, metric: DistanceType, node_tile: int):
    """d(i, cand[i, j]) for all i — tiled batched einsum."""
    n, dim = x.shape
    n_cand = cand.shape[1]
    n_tiles = cdiv(n, node_tile)
    pad = n_tiles * node_tile - n
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    cp = jnp.pad(cand, ((0, pad), (0, 0)))

    def body(args):
        xt, ct = args
        vecs = x[jnp.maximum(ct, 0)]  # [t, C, dim]
        d = gathered_distances(xt, vecs, metric)
        if metric == DistanceType.InnerProduct:
            d = -d  # minimize
        return d

    if n_tiles == 1:
        d = body((xp, cp))
    else:
        d = jax.lax.map(
            body,
            (xp.reshape(n_tiles, node_tile, dim),
             cp.reshape(n_tiles, node_tile, n_cand)),
        ).reshape(-1, n_cand)
    return d[:n]


def _merge_topk(graph, dists, cand, cand_d, k: int):
    """Merge candidate lists into the current graph: top-k of the union with
    duplicate + self suppression (the functional analog of the GNND heap
    insert)."""
    n = graph.shape[0]
    ids = jnp.concatenate([graph, cand], axis=1)
    ds = jnp.concatenate([dists, cand_d], axis=1)
    return merge_topk_dedup(ids, ds, k,
                            exclude_ids=jnp.arange(n, dtype=ids.dtype))


def _reverse_sample(key, graph, n_rev: int):
    """Sample reverse edges: scatter each edge (i→j) into j's reverse slots
    pseudo-randomly (functional analog of GNND's reverse-edge insertion,
    detail/nn_descent.cuh:499-510)."""
    n, k = graph.shape
    rev = jnp.full((n, n_rev), -1, jnp.int32)
    # random slot per edge; later writes win — a random subset survives.
    # Invalid (-1) edges are routed out of bounds and dropped so they don't
    # pollute node 0's slots.
    slots = jax.random.randint(key, (n, k), 0, n_rev)
    src = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, k))
    tgt = jnp.where(graph >= 0, graph, n)
    rev = rev.at[tgt.reshape(-1), slots.reshape(-1)].set(
        src.reshape(-1), mode="drop")
    return rev


@functools.partial(
    jax.jit,
    static_argnames=("k_inter", "n_iters", "metric", "node_tile",
                     "fwd_expand", "rev_expand", "rev_sample"),
)
def _build_jit(key, x, term_threshold, k_inter: int, n_iters: int,
               metric: DistanceType, node_tile: int, fwd_expand: int,
               rev_expand: int, rev_sample: int):
    n, dim = x.shape
    n_tiles = cdiv(n, node_tile)
    n_pad = n_tiles * node_tile

    # init: random neighbors, every surviving edge flagged "new"
    k0, key = jax.random.split(key)
    graph = jax.random.randint(k0, (n, k_inter), 0, n, jnp.int32)
    d0 = _candidate_distances(x, graph, metric, node_tile)
    graph, dists = _merge_topk(
        jnp.full((n, k_inter), -1, jnp.int32),
        jnp.full((n, k_inter), jnp.inf), graph, d0, k_inter)
    flags = jnp.zeros((n, k_inter), bool)  # False = new (not yet joined)

    xf_pad = jnp.pad(x, ((0, n_pad - n), (0, 0)))
    node_ids = jnp.arange(n_pad, dtype=jnp.int32).reshape(n_tiles, node_tile)

    def round_cond(state):
        # early termination when the update rate drops below the threshold
        # (reference: BuildConfig.termination_threshold, GNND's convergence
        # check on the per-round update counter)
        i, _, _, _, _, rate = state
        return (i < n_iters) & (rate > term_threshold)

    def round_body(state):
        i, graph, dists, flags, key = state[:5]
        old_graph = graph
        key, k_rev, k_rand = jax.random.split(key, 3)

        # reverse edges (the GNND reverse-list analog) + random exploration
        rev = _reverse_sample(k_rev, graph, rev_sample)  # [n, R]
        rand = jax.random.randint(k_rand, (n, 8), 0, n, jnp.int32)
        nb = jnp.maximum(graph, 0)

        def tile_body(args):
            ids_t, xt, g_t, d_t, f_t, rev_t, rand_t = args
            t = ids_t.shape[0]
            # GNND new-list sampling: expand the closest still-new
            # neighbors and mark them joined (flag-clear on sample,
            # nn_descent.cuh:319-330); entries stay distance-sorted, so a
            # stable argsort on (joined, invalid) picks new-first in rank
            # order
            order = jnp.argsort(f_t | (g_t < 0), axis=1, stable=True)
            pick = order[:, :fwd_expand]  # [t, E]
            fwd = jnp.take_along_axis(g_t, pick, axis=1)
            fwd_ok = jnp.take_along_axis(
                (g_t >= 0) & ~f_t, pick, axis=1)
            rows_t = jnp.arange(t)[:, None]
            f_t = f_t.at[rows_t, pick].set(True)
            fwd_nofn = nb[jnp.maximum(fwd, 0).reshape(-1)].reshape(
                t, fwd_expand * k_inter)  # new × (new ∪ old) join
            fwd_nofn = jnp.where(
                jnp.repeat(fwd_ok, k_inter, axis=1), fwd_nofn, -1)
            # symmetric join: reverse neighbors' lists supply the (i, v)
            # pairs with i, v ∈ G(u) of GNND's pair join (:358, :499-510)
            rexp = rev_t[:, :rev_expand]
            rev_nofn = nb[jnp.maximum(rexp, 0).reshape(-1)].reshape(
                t, rev_expand * k_inter)
            rev_nofn = jnp.where(
                jnp.repeat(rexp >= 0, k_inter, axis=1), rev_nofn, -1)

            cand = jnp.concatenate([fwd_nofn, rev_nofn, rev_t, rand_t],
                                   axis=1)
            cand = jnp.where(cand == ids_t[:, None], -1, cand)  # self
            vecs = x[jnp.maximum(cand, 0)]  # [t, C, dim]
            cd = gathered_distances(xt, vecs, metric)
            if metric == DistanceType.InnerProduct:
                cd = -cd
            cd = jnp.where(cand < 0, jnp.inf, cd)
            ids = jnp.concatenate([g_t, cand], axis=1)
            ds = jnp.concatenate([d_t, cd], axis=1)
            fl = jnp.concatenate(
                [f_t, jnp.zeros_like(cand, dtype=bool)], axis=1)
            return merge_topk_dedup_flagged(ids, ds, fl, k_inter)

        g_pad = jnp.pad(graph, ((0, n_pad - n), (0, 0)), constant_values=-1)
        d_pad = jnp.pad(dists, ((0, n_pad - n), (0, 0)),
                        constant_values=jnp.inf)
        f_pad = jnp.pad(flags, ((0, n_pad - n), (0, 0)),
                        constant_values=True)
        rev_pad = jnp.pad(rev, ((0, n_pad - n), (0, 0)), constant_values=-1)
        rand_pad = jnp.pad(rand, ((0, n_pad - n), (0, 0)), constant_values=-1)
        new_g, new_d, new_f = jax.lax.map(
            tile_body,
            (node_ids,
             xf_pad.reshape(n_tiles, node_tile, dim),
             g_pad.reshape(n_tiles, node_tile, k_inter),
             d_pad.reshape(n_tiles, node_tile, k_inter),
             f_pad.reshape(n_tiles, node_tile, k_inter),
             rev_pad.reshape(n_tiles, node_tile, rev_sample),
             rand_pad.reshape(n_tiles, node_tile, 8)),
        )
        new_graph = new_g.reshape(n_pad, k_inter)[:n]
        dists = new_d.reshape(n_pad, k_inter)[:n]
        flags = new_f.reshape(n_pad, k_inter)[:n]
        rate = jnp.mean((new_graph != old_graph).astype(jnp.float32))
        return i + 1, new_graph, dists, flags, key, rate

    _, graph, dists, _, _, _ = jax.lax.while_loop(
        round_cond, round_body, (jnp.int32(0), graph, dists, flags, key,
                                 jnp.float32(1.0)))
    return graph, dists


class Index:
    """All-neighbors graph (reference: nn_descent_types.hpp index — the
    [n, graph_degree] neighbor matrix; distances optionally retained)."""

    def __init__(self, graph, distances, metric: DistanceType):
        self.graph = graph  # [n, graph_degree] int32
        self.distances = distances  # [n, graph_degree] fp32 (internal order)
        self.metric = metric


@tracing.range("nn_descent.build")
def build(
    dataset,
    params: Optional[IndexParams] = None,
    res: Optional[Resources] = None,
) -> Index:
    """Build the kNN graph (reference: nn_descent::build, nn_descent.cuh)."""
    params = params or IndexParams()
    res = ensure_resources(res)
    x = jnp.asarray(dataset).astype(jnp.float32)
    n, dim = x.shape
    k_inter = int(min(params.intermediate_graph_degree, n - 1))
    k_out = int(min(params.graph_degree, k_inter))

    # candidate-set sizing: the join expands fwd_expand still-new forward
    # neighbors + rev_expand reverse neighbors fully ((E+R)·K candidates
    # per node per round — the coverage knobs of GNND's sample sizes)
    fwd_expand = int(np.clip(768 // max(k_inter, 1), 3, 12))
    fwd_expand = min(fwd_expand, k_inter)
    rev_expand = int(np.clip(384 // max(k_inter, 1), 2, 6))
    rev_expand = min(rev_expand, k_inter)
    rev_sample = min(max(k_inter // 2, 16), 64)
    n_cand = (fwd_expand + rev_expand) * k_inter + rev_sample + 8
    per_node = n_cand * (dim + 8) * 4 * 2
    node_tile = int(np.clip(res.workspace_limit_bytes // max(per_node, 1),
                            64, 4096))
    node_tile -= node_tile % 8

    graph, dists = _build_jit(
        res.next_key(), x, jnp.float32(params.termination_threshold),
        k_inter, int(params.max_iterations), params.metric,
        max(node_tile, 8), fwd_expand, rev_expand, rev_sample)
    return Index(graph[:, :k_out], dists[:, :k_out], params.metric)
