"""NN-descent — all-neighbors kNN-graph construction.

Reference: ``raft::neighbors::experimental::nn_descent`` (neighbors/
nn_descent.cuh, nn_descent_types.hpp; detail/nn_descent.cuh — GNND: bloom-
filter sampling of new/old neighbors :319-330, ``local_join`` :358, reverse-
edge insertion :499-510, ``BuildConfig`` :212).

TPU-native design: the GPU GNND's scatter-heavy local join (every candidate
pair scatters into two per-node heaps guarded by locks) is a poor fit for
XLA's functional model. We reformulate each NN-descent round as a **gather +
matmul + merge** pipeline with identical fixed-point semantics (a node's
neighborhood is improved using neighbors-of-neighbors and reverse edges):

1. candidate generation: for node i take its neighbors, a sample of
   neighbors-of-neighbors (the forward local join), a sample of reverse
   neighbors, and random rows (the reference's num_random_samplings analog);
2. exact distances d(i, c) for all candidates in one tiled einsum (MXU);
3. merge: top-k over [old ∪ candidates] with duplicate suppression.

Convergence matches the classic NN-descent fixed point; iterations are a
static ``n_iters`` so the whole build jits into one XLA program.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import (
    DistanceType,
    gathered_distances,
    resolve_metric,
)
from raft_tpu.ops.select_k import merge_topk_dedup
from raft_tpu.utils.shape import cdiv


@dataclasses.dataclass
class IndexParams:
    """reference: nn_descent_types.hpp index_params — graph_degree,
    intermediate_graph_degree, max_iterations, termination_threshold."""

    graph_degree: int = 64
    intermediate_graph_degree: int = 128
    max_iterations: int = 20
    termination_threshold: float = 0.0001
    metric: DistanceType = DistanceType.L2Expanded

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.metric not in (DistanceType.L2Expanded,
                               DistanceType.L2SqrtExpanded,
                               DistanceType.InnerProduct,
                               DistanceType.CosineExpanded):
            raise ValueError(
                f"nn_descent supports L2/IP/Cosine, got {self.metric.name}")


def _candidate_distances(x, cand, metric: DistanceType, node_tile: int):
    """d(i, cand[i, j]) for all i — tiled batched einsum."""
    n, dim = x.shape
    n_cand = cand.shape[1]
    n_tiles = cdiv(n, node_tile)
    pad = n_tiles * node_tile - n
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    cp = jnp.pad(cand, ((0, pad), (0, 0)))

    def body(args):
        xt, ct = args
        vecs = x[jnp.maximum(ct, 0)]  # [t, C, dim]
        d = gathered_distances(xt, vecs, metric)
        if metric == DistanceType.InnerProduct:
            d = -d  # minimize
        return d

    if n_tiles == 1:
        d = body((xp, cp))
    else:
        d = jax.lax.map(
            body,
            (xp.reshape(n_tiles, node_tile, dim),
             cp.reshape(n_tiles, node_tile, n_cand)),
        ).reshape(-1, n_cand)
    return d[:n]


def _merge_topk(graph, dists, cand, cand_d, k: int):
    """Merge candidate lists into the current graph: top-k of the union with
    duplicate + self suppression (the functional analog of the GNND heap
    insert)."""
    n = graph.shape[0]
    ids = jnp.concatenate([graph, cand], axis=1)
    ds = jnp.concatenate([dists, cand_d], axis=1)
    return merge_topk_dedup(ids, ds, k,
                            exclude_ids=jnp.arange(n, dtype=ids.dtype))


def _reverse_sample(key, graph, n_rev: int):
    """Sample reverse edges: scatter each edge (i→j) into j's reverse slots
    pseudo-randomly (functional analog of GNND's reverse-edge insertion,
    detail/nn_descent.cuh:499-510)."""
    n, k = graph.shape
    rev = jnp.full((n, n_rev), -1, jnp.int32)
    # random slot per edge; later writes win — a random subset survives.
    # Invalid (-1) edges are routed out of bounds and dropped so they don't
    # pollute node 0's slots.
    slots = jax.random.randint(key, (n, k), 0, n_rev)
    src = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, k))
    tgt = jnp.where(graph >= 0, graph, n)
    rev = rev.at[tgt.reshape(-1), slots.reshape(-1)].set(
        src.reshape(-1), mode="drop")
    return rev


@functools.partial(
    jax.jit,
    static_argnames=("k_inter", "n_iters", "metric", "node_tile",
                     "expand_width", "rev_sample"),
)
def _build_jit(key, x, term_threshold, k_inter: int, n_iters: int,
               metric: DistanceType, node_tile: int, expand_width: int,
               rev_sample: int):
    n, dim = x.shape
    n_tiles = cdiv(n, node_tile)
    n_pad = n_tiles * node_tile

    # init: random neighbors
    k0, key = jax.random.split(key)
    graph = jax.random.randint(k0, (n, k_inter), 0, n, jnp.int32)
    d0 = _candidate_distances(x, graph, metric, node_tile)
    graph, dists = _merge_topk(
        jnp.full((n, k_inter), -1, jnp.int32),
        jnp.full((n, k_inter), jnp.inf), graph, d0, k_inter)

    xf_pad = jnp.pad(x, ((0, n_pad - n), (0, 0)))
    node_ids = jnp.arange(n_pad, dtype=jnp.int32).reshape(n_tiles, node_tile)

    def round_cond(state):
        # early termination when the update rate drops below the threshold
        # (reference: BuildConfig.termination_threshold, GNND's convergence
        # check on the per-round update counter)
        i, _, _, _, rate = state
        return (i < n_iters) & (rate > term_threshold)

    def round_body(state):
        i, graph, dists, key = state[:4]
        old_graph = graph
        key, k_rev, k_rand = jax.random.split(key, 3)

        # reverse edges (the GNND reverse-list analog) + random exploration
        rev = _reverse_sample(k_rev, graph, rev_sample)  # [n, R]
        rand = jax.random.randint(k_rand, (n, 8), 0, n, jnp.int32)
        nb = jnp.maximum(graph, 0)

        def tile_body(args):
            ids_t, xt, g_t, d_t, rev_t, rand_t = args
            # full local join over the expand_width closest neighbors: every
            # neighbor-of-near-neighbor is a candidate (the dense, MXU-sized
            # replacement for GNND's sampled pair join)
            mid = jnp.maximum(g_t[:, :expand_width], 0)  # [t, E]
            nofn = nb[mid.reshape(-1)].reshape(
                -1, expand_width * k_inter)  # [t, E*K]
            cand = jnp.concatenate([nofn, rev_t, rand_t], axis=1)
            vecs = x[jnp.maximum(cand, 0)]  # [t, C, dim]
            cd = gathered_distances(xt, vecs, metric)
            if metric == DistanceType.InnerProduct:
                cd = -cd
            cd = jnp.where(cand < 0, jnp.inf, cd)
            return _merge_topk_rows(g_t, d_t, cand, cd, ids_t, k_inter)

        g_pad = jnp.pad(graph, ((0, n_pad - n), (0, 0)), constant_values=-1)
        d_pad = jnp.pad(dists, ((0, n_pad - n), (0, 0)),
                        constant_values=jnp.inf)
        rev_pad = jnp.pad(rev, ((0, n_pad - n), (0, 0)), constant_values=-1)
        rand_pad = jnp.pad(rand, ((0, n_pad - n), (0, 0)), constant_values=-1)
        new_g, new_d = jax.lax.map(
            tile_body,
            (node_ids,
             xf_pad.reshape(n_tiles, node_tile, dim),
             g_pad.reshape(n_tiles, node_tile, k_inter),
             d_pad.reshape(n_tiles, node_tile, k_inter),
             rev_pad.reshape(n_tiles, node_tile, rev_sample),
             rand_pad.reshape(n_tiles, node_tile, 8)),
        )
        new_graph = new_g.reshape(n_pad, k_inter)[:n]
        dists = new_d.reshape(n_pad, k_inter)[:n]
        rate = jnp.mean((new_graph != old_graph).astype(jnp.float32))
        return i + 1, new_graph, dists, key, rate

    _, graph, dists, _, _ = jax.lax.while_loop(
        round_cond, round_body, (jnp.int32(0), graph, dists, key,
                                 jnp.float32(1.0)))
    return graph, dists


def _merge_topk_rows(graph, dists, cand, cand_d, row_ids, k: int):
    """Like _merge_topk but for a node tile whose global ids are ``row_ids``
    (self-suppression uses the global id)."""
    ids = jnp.concatenate([graph, cand], axis=1)
    ds = jnp.concatenate([dists, cand_d], axis=1)
    return merge_topk_dedup(ids, ds, k, exclude_ids=row_ids)


class Index:
    """All-neighbors graph (reference: nn_descent_types.hpp index — the
    [n, graph_degree] neighbor matrix; distances optionally retained)."""

    def __init__(self, graph, distances, metric: DistanceType):
        self.graph = graph  # [n, graph_degree] int32
        self.distances = distances  # [n, graph_degree] fp32 (internal order)
        self.metric = metric


def build(
    dataset,
    params: Optional[IndexParams] = None,
    res: Optional[Resources] = None,
) -> Index:
    """Build the kNN graph (reference: nn_descent::build, nn_descent.cuh)."""
    params = params or IndexParams()
    res = ensure_resources(res)
    x = jnp.asarray(dataset).astype(jnp.float32)
    n, dim = x.shape
    k_inter = int(min(params.intermediate_graph_degree, n - 1))
    k_out = int(min(params.graph_degree, k_inter))

    # candidate-set sizing: the dense local join expands the expand_width
    # closest neighbors fully (E·K candidates/node/round — the coverage knob)
    expand_width = int(np.clip(1024 // max(k_inter, 1), 4, 16))
    expand_width = min(expand_width, k_inter)
    rev_sample = min(max(k_inter // 2, 16), 64)
    n_cand = expand_width * k_inter + rev_sample + 8
    per_node = n_cand * (dim + 8) * 4 * 2
    node_tile = int(np.clip(res.workspace_limit_bytes // max(per_node, 1),
                            64, 4096))
    node_tile -= node_tile % 8

    graph, dists = _build_jit(
        res.next_key(), x, jnp.float32(params.termination_threshold),
        k_inter, int(params.max_iterations), params.metric,
        max(node_tile, 8), expand_width, rev_sample)
    return Index(graph[:, :k_out], dists[:, :k_out], params.metric)
