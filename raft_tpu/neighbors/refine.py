"""Refine — exact re-ranking of ANN candidate lists.

Reference: ``raft::neighbors::refine`` (neighbors/refine-inl.cuh:70-100;
device path detail/refine_device.cuh:40 — a specialized interleaved scan over
only the candidate vectors; host path detail/refine_host-inl.hpp). Given a
candidate index list per query (typically from ivf_pq/cagra with
``k·refine_ratio`` entries), recompute exact distances and keep the top k.

TPU-native design: gather candidate rows to a dense
``[q_tile, n_cand, dim]`` block, one einsum against the queries (MXU), mask
invalid (-1) candidates, select_k. Query tiles stream through ``lax.map``
bounded by the Resources workspace budget.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import (
    DistanceType,
    gathered_distances,
    resolve_metric,
)
from raft_tpu.ops.select_k import select_k
from raft_tpu.utils.shape import cdiv


@functools.partial(
    jax.jit, static_argnames=("metric", "k", "q_tile"))
def _refine_jit(dataset, queries, candidates, metric: DistanceType, k: int,
                q_tile: int):
    nq, n_cand = candidates.shape
    dim = dataset.shape[1]
    minimize = metric != DistanceType.InnerProduct

    n_tiles = cdiv(nq, q_tile)
    pad_q = n_tiles * q_tile - nq
    qp = jnp.pad(queries.astype(jnp.float32), ((0, pad_q), (0, 0)))
    cp = jnp.pad(candidates, ((0, pad_q), (0, 0)), constant_values=-1)

    def body(args):
        qt, ct = args  # [t, dim], [t, C]
        valid = ct >= 0
        safe = jnp.maximum(ct, 0)
        vecs = dataset[safe]  # [t, C, dim]
        d = gathered_distances(qt, vecs, metric)
        bad = jnp.inf if minimize else -jnp.inf
        d = jnp.where(valid, d, bad)
        kk = min(k, n_cand)
        v, sel = select_k(d, kk, select_min=minimize)
        i_out = jnp.take_along_axis(ct, sel, axis=1)
        i_out = jnp.where(jnp.isfinite(v) if minimize else v > -jnp.inf,
                          i_out, -1)
        if kk < k:
            v = jnp.pad(v, ((0, 0), (0, k - kk)), constant_values=bad)
            i_out = jnp.pad(i_out, ((0, 0), (0, k - kk)), constant_values=-1)
        return v, i_out

    if n_tiles == 1:
        vals, idxs = body((qp, cp))
    else:
        vals, idxs = jax.lax.map(
            body,
            (qp.reshape(n_tiles, q_tile, dim),
             cp.reshape(n_tiles, q_tile, n_cand)),
        )
        vals = vals.reshape(-1, k)
        idxs = idxs.reshape(-1, k)
    return vals[:nq], idxs[:nq]


@tracing.range("refine.refine")
def refine(
    dataset,
    queries,
    candidates,
    k: int,
    metric="sqeuclidean",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Re-rank ``candidates`` [nq, n_cand] (row ids into ``dataset``, -1 =
    missing) by exact distance; return the top ``k`` (reference:
    neighbors::refine, refine-inl.cuh:70-100).
    """
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    queries = jnp.asarray(queries)
    candidates = jnp.asarray(candidates, jnp.int32)
    if queries.shape[1] != dataset.shape[1]:
        raise ValueError(
            f"query dim {queries.shape[1]} != dataset dim {dataset.shape[1]}")
    if candidates.shape[0] != queries.shape[0]:
        raise ValueError("candidates rows must match queries rows")
    if k > candidates.shape[1]:
        raise ValueError(f"k={k} > n_candidates={candidates.shape[1]}")
    m = resolve_metric(metric)
    per_q = candidates.shape[1] * dataset.shape[1] * 4 * 2
    q_tile = int(np.clip(res.workspace_limit_bytes // max(per_q, 1), 1, 1024))
    if q_tile >= 8:
        q_tile -= q_tile % 8
    return _refine_jit(dataset, queries, candidates, m, int(k), q_tile)
