"""Out-of-core (streamed) index builds from on-disk fbin datasets.

Reference analog: the reference's larger-than-device-memory story —
host-memory datasets with batched device staging (bench
``dataset_memory_type``, ann_types.hpp:68-118), subsampled training
(ivf_pq_types.hpp:59 ``kmeans_trainset_fraction``), and the wiki-all 88M×768
dataset "intentionally larger than GPU memory"
(docs/source/wiki_all_dataset.md:3). RAFT streams build batches through
``extend``; here the whole pipeline is two passes over the file:

1. **Train** on a strided row sample (never materializes the full dataset).
2. **Pass A** streams batches through the coarse quantizer to get labels and
   exact list sizes; **Pass B** allocates the final padded list storage once
   and scatters each batch into place (encode-on-the-fly for PQ) — avoiding
   the O(N²) repack that repeated ``extend`` calls would cost.

The file format is the raft-ann-bench fbin/ibin layout (bench
common/dataset.hpp) read through the native IO layer.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu import native
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.neighbors import list_packing


def sample_rows_from_file(path: str, n_sample: int, seed: int = 0,
                          dtype=None, batch_rows: int = 1 << 18,
                          row_range=None) -> np.ndarray:
    """Uniform-ish strided row sample without loading the file: reads
    contiguous chunks and keeps an evenly spaced subset of each (the
    trainset subsample of detail/ivf_pq_build.cuh:1759, host-streamed).
    ``row_range=(lo, hi)`` samples only that span (per-shard builds)."""
    total, dim = native.read_bin_header(path)
    lo, hi = (0, total) if row_range is None else row_range
    lo, hi = int(lo), int(min(hi, total))
    n = hi - lo
    n_sample = min(int(n_sample), n)
    out = []
    taken = 0
    rng = np.random.default_rng(seed)
    for start in range(lo, hi, batch_rows):
        rows = min(batch_rows, hi - start)
        want = int(round(n_sample * (start + rows - lo) / n)) - taken
        if want <= 0:
            continue
        batch = native.read_bin(path, start, rows, dtype=dtype)
        if want >= rows:
            sel = batch
        else:
            pick = rng.choice(rows, size=want, replace=False)
            pick.sort()
            sel = batch[pick]
        out.append(np.ascontiguousarray(sel))
        taken += len(sel)
    return np.concatenate(out, axis=0)


def _labels_pass(path: str, centers, metric, batch_rows: int, dtype,
                 res: Resources, row_range=None) -> np.ndarray:
    """Pass A: stream batches through the coarse quantizer → labels
    [hi - lo] (offset-local when a row_range is given)."""
    total, _ = native.read_bin_header(path)
    lo, hi = (0, total) if row_range is None else row_range
    km = KMeansBalancedParams(metric=metric)
    labels = np.empty(int(hi) - int(lo), np.int32)
    for start, batch in native.iter_bin_batches_prefetch(
            path, batch_rows, dtype, row_range=row_range):
        s = start - int(lo)
        lb = kmeans_balanced.predict(centers, jnp.asarray(batch), km, res=res)
        labels[s:s + len(batch)] = np.asarray(lb, np.int32)
    return labels


def _scatter_positions(lb: np.ndarray, offsets: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Slot position for every batch row given running per-list offsets;
    returns (positions, new bincount). Vectorized grouped cumcount."""
    order = np.argsort(lb, kind="stable")
    sorted_lb = lb[order]
    cc = np.arange(len(lb), dtype=np.int64)
    if len(lb):
        starts = np.r_[0, np.flatnonzero(np.diff(sorted_lb)) + 1]
        group_len = np.diff(np.r_[starts, len(lb)])
        cc -= np.repeat(cc[starts], group_len)
    pos = np.empty(len(lb), np.int64)
    pos[order] = offsets[sorted_lb] + cc
    return pos, np.bincount(lb, minlength=len(offsets))


def build_ivf_flat_from_file(path: str, params=None,
                             res: Optional[Resources] = None,
                             batch_rows: int = 1 << 18, dtype=None,
                             max_train_rows: Optional[int] = None,
                             row_range=None):
    """Streamed IVF-Flat build from an fbin file → ivf_flat.Index.

    The dataset is read twice (labels pass + fill pass) in ``batch_rows``
    chunks; peak host memory is the final padded list storage + one batch.
    ``row_range=(lo, hi)`` builds over that span only, with file-absolute
    row ids (per-shard MNMG builds).
    """
    from raft_tpu.neighbors import ivf_flat

    params = params or ivf_flat.IndexParams()
    res = ensure_resources(res)
    total, dim = native.read_bin_header(path)
    lo, hi = (0, total) if row_range is None else row_range
    lo, hi = int(lo), int(min(hi, total))
    n = hi - lo
    if params.n_lists > n:
        raise ValueError(f"n_lists={params.n_lists} > n_rows={n}")

    n_train = max(int(n * params.kmeans_trainset_fraction), params.n_lists)
    if max_train_rows is not None:
        n_train = min(n_train, int(max_train_rows))
    trainset = sample_rows_from_file(path, n_train, seed=0, dtype=dtype,
                                     batch_rows=batch_rows,
                                     row_range=(lo, hi))
    km = KMeansBalancedParams(n_iters=params.kmeans_n_iters,
                              metric=params.metric)
    centers = kmeans_balanced.fit(res.next_key(),
                                  jnp.asarray(trainset, jnp.float32),
                                  params.n_lists, km, res=res)
    del trainset

    labels = _labels_pass(path, centers, params.metric, batch_rows, dtype,
                          res, row_range=(lo, hi))
    sizes = np.bincount(labels, minlength=params.n_lists).astype(np.int32)
    pad = list_packing.choose_list_pad(sizes, params.list_pad_expansion)

    first = native.read_bin(path, 0, 1, dtype=dtype)
    data = np.zeros((params.n_lists, pad, dim), first.dtype)
    idxs = np.full((params.n_lists, pad), -1, np.int32)
    offsets = np.zeros(params.n_lists, np.int64)
    over_rows, over_ids = [], []
    for start, batch in native.iter_bin_batches_prefetch(
            path, batch_rows, dtype, row_range=(lo, hi)):
        rows = len(batch)
        lb = labels[start - lo:start - lo + rows]
        row_ids = np.arange(start, start + rows, dtype=np.int32)
        pos, cnt = _scatter_positions(lb, offsets)
        fits = pos < pad  # rows past a hot list's cap spill to overflow
        data[lb[fits], pos[fits]] = batch[fits]
        idxs[lb[fits], pos[fits]] = row_ids[fits]
        if not fits.all():
            over_rows.append(np.ascontiguousarray(batch[~fits]))
            over_ids.append(row_ids[~fits])
        offsets += cnt

    o_rows, o_ids = _gather_overflow(over_rows, over_ids, (0, dim),
                                     first.dtype)
    return ivf_flat.Index(params, centers, jnp.asarray(data),
                          jnp.asarray(idxs),
                          jnp.asarray(np.minimum(sizes, pad)), n,
                          jnp.asarray(o_rows), jnp.asarray(o_ids))


def _gather_overflow(chunks, id_chunks, empty_shape, dtype):
    """Concatenate spilled-row chunks into an 8-aligned overflow block."""
    if not chunks:
        return np.zeros(empty_shape, dtype), np.zeros((0,), np.int32)
    return list_packing.pad_overflow_block(
        np.concatenate(chunks, axis=0),
        np.concatenate(id_chunks))


def build_ivf_pq_from_file(path: str, params=None,
                           res: Optional[Resources] = None,
                           batch_rows: int = 1 << 18, dtype=None,
                           max_train_rows: Optional[int] = None,
                           row_range=None, trained_index=None):
    """Streamed IVF-PQ build from an fbin file → ivf_pq.Index.

    Training (coarse centers, rotation, codebooks) runs on a row sample via
    the in-memory ``ivf_pq.build``; the full dataset is then encoded batch
    by batch into the final packed-code storage (the streaming analog of
    process_and_fill_codes, detail/ivf_pq_build.cuh:1185-1351).

    ``trained_index`` (a dataless ``ivf_pq.Index`` holding centers,
    rotation, codebooks) skips training entirely and only runs the encode
    passes — the sharded-PQ-encode leg of the pod-scale build, where one
    mesh-wide quantizer is shared by every shard (so ``n_lists`` may
    exceed this span's rows; unused lists stay empty).
    """
    from raft_tpu.neighbors import ivf_pq

    params = params or ivf_pq.IndexParams()
    res = ensure_resources(res)
    total, dim = native.read_bin_header(path)
    lo, hi = (0, total) if row_range is None else row_range
    lo, hi = int(lo), int(min(hi, total))
    n = hi - lo
    if trained_index is not None:
        if trained_index.n_lists != params.n_lists:
            raise ValueError(
                f"trained_index has n_lists={trained_index.n_lists}, "
                f"params ask for {params.n_lists}")
        index = trained_index
    else:
        if params.n_lists > n:
            raise ValueError(f"n_lists={params.n_lists} > n_rows={n}")
        n_train = max(int(n * params.kmeans_trainset_fraction),
                      params.n_lists)
        if max_train_rows is not None:
            n_train = min(n_train, int(max_train_rows))
        trainset = sample_rows_from_file(path, n_train, seed=0, dtype=dtype,
                                         batch_rows=batch_rows,
                                         row_range=(lo, hi))
        train_params = dataclasses.replace(params,
                                           kmeans_trainset_fraction=1.0,
                                           add_data_on_build=False)
        index = ivf_pq.build(np.asarray(trainset, np.float32), train_params,
                             res=res)
        del trainset

    labels = _labels_pass(path, index.centers, params.metric, batch_rows,
                          dtype, res, row_range=(lo, hi))
    sizes = np.bincount(labels, minlength=params.n_lists).astype(np.int32)
    pad = list_packing.choose_list_pad(sizes, params.list_pad_expansion)
    packed_width = index.pq_dim * index.pq_bits // 8

    codes = np.zeros((params.n_lists, pad, packed_width), np.uint8)
    idxs = np.full((params.n_lists, pad), -1, np.int32)
    offsets = np.zeros(params.n_lists, np.int64)
    over_codes, over_labels, over_ids = [], [], []
    for start, batch in native.iter_bin_batches_prefetch(
            path, batch_rows, dtype, row_range=(lo, hi)):
        rows = len(batch)
        lb = labels[start - lo:start - lo + rows]
        packed = np.asarray(ivf_pq.encode_batch(index, batch, lb, res))
        row_ids = np.arange(start, start + rows, dtype=np.int32)
        pos, cnt = _scatter_positions(lb, offsets)
        fits = pos < pad
        codes[lb[fits], pos[fits]] = packed[fits]
        idxs[lb[fits], pos[fits]] = row_ids[fits]
        if not fits.all():
            over_codes.append(np.ascontiguousarray(packed[~fits]))
            over_labels.append(lb[~fits])
            over_ids.append(row_ids[~fits])
        offsets += cnt

    o_codes, o_ids = _gather_overflow(over_codes, over_ids,
                                      (0, packed_width), np.uint8)
    o_labels = np.zeros((len(o_ids),), np.int32)
    if over_labels:
        lab = np.concatenate(over_labels)
        o_labels[:len(lab)] = lab
    return ivf_pq.Index(params, index.pq_dim, index.centers, index.rotation,
                        index.codebooks, jnp.asarray(codes),
                        jnp.asarray(idxs),
                        jnp.asarray(np.minimum(sizes, pad)), n,
                        jnp.asarray(o_codes), jnp.asarray(o_labels),
                        jnp.asarray(o_ids))
