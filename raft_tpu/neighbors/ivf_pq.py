"""IVF-PQ — inverted-file index with product-quantized residuals.

Reference: ``raft::neighbors::ivf_pq`` (neighbors/ivf_pq-inl.cuh:115-480;
types ivf_pq_types.hpp:48-146; build detail/ivf_pq_build.cuh:1732; search
detail/ivf_pq_search.cuh). Build: subsample trainset → balanced k-means
coarse clustering → random-orthonormal rotation (normal + QR,
detail/ivf_pq_build.cuh:121-137) → PQ codebooks per-subspace or per-cluster
(each trained by balanced k-means on residual sub-vectors,
detail/ivf_pq_build.cuh:394,471) → encode + bit-pack all vectors into
per-cluster lists (process_and_fill_codes, detail/ivf_pq_build.cuh:1185).
Search: coarse top-``n_probes`` via gemm + select_k (select_clusters,
detail/ivf_pq_search.cuh:69-155) → per query×probe look-up-table (LUT) scan
of packed codes with fp32/fp16/fp8 LUTs (detail/ivf_pq_compute_similarity)
→ final select_k → postprocess.

TPU-native design:
- **Storage**: padded dense ``[n_lists, list_pad, n_code_bytes]`` uint8 of
  bit-packed codes (pq_bits ∈ [4,8], invariant pq_dim·pq_bits ≡ 0 mod 8 —
  ivf_pq_types.hpp:538-545) + int32 row ids. Lane-aligned padding instead of
  the GPU's interleaved group-of-32 layout.
- **LUT build is a batched matmul** (MXU): for each query×probe the LUT is
  ``||q_sub − codebook||²`` expanded into norms + one einsum over
  [pq_dim, book_size, pq_len] — the analog of the shared-memory LUT fill.
- **Code scan**: static two-byte gathers unpack pq_bits codes from the byte
  stream (each code spans ≤ 2 bytes); scores come from a flat LUT gather and
  a sum over subspaces. ``lut_dtype``/``internal_distance_dtype`` map to
  fp32/bf16 (fp8 LUTs are emulated with bf16 — TPUs have no fp8 gather win).
- **Codebook training**: one jitted Lloyd-EM body ``lax.map``-ed across
  subspaces (PER_SUBSPACE) or across clusters (PER_CLUSTER), trained on
  rotated residuals, weights masking ragged membership — one compile serves
  all groups.
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import serialize as ser
from raft_tpu.core import tracing
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.bitset import filter_mask as bitset_filter_mask
from raft_tpu.core.resources import (Resources, ensure_resources,
                                     solve_joint_tiles)
from raft_tpu.cluster import kmeans_balanced
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.ops.distance import DistanceType, resolve_metric
from raft_tpu.ops.select_k import select_k, select_k_maybe_approx
from raft_tpu.neighbors import list_packing
from raft_tpu.neighbors.brute_force import fused_ineligible_reason
from raft_tpu.obs import explain as obs_explain
from raft_tpu.ops import rng as rrng
from raft_tpu.utils.shape import (as_query_array, balanced_tile, cdiv, pad_rows,
                                  query_bucket)


class CodebookGen(enum.IntEnum):
    """reference: ivf_pq_types.hpp codebook_gen."""

    PER_SUBSPACE = 0
    PER_CLUSTER = 1


@dataclasses.dataclass
class IndexParams:
    """reference: ivf_pq_types.hpp:48-108 index_params."""

    n_lists: int = 1024
    metric: DistanceType = DistanceType.L2Expanded
    kmeans_n_iters: int = 20
    kmeans_trainset_fraction: float = 0.5
    pq_bits: int = 8
    pq_dim: int = 0  # 0 → heuristic (see _calc_pq_dim)
    codebook_kind: CodebookGen = CodebookGen.PER_SUBSPACE
    force_random_rotation: bool = False
    add_data_on_build: bool = True
    # Padded-storage budget (see ivf_flat.IndexParams.list_pad_expansion):
    # caps the dense list_pad; spilled rows live in a small overflow block
    # scanned brute-force per query (candidate superset, no recall loss).
    list_pad_expansion: float = 1.5

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if not 4 <= self.pq_bits <= 8:
            raise ValueError(f"pq_bits must be in [4, 8], got {self.pq_bits}")
        if self.list_pad_expansion < 1.0:
            raise ValueError(
                f"list_pad_expansion must be >= 1.0, got "
                f"{self.list_pad_expansion}")
        if self.metric not in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.InnerProduct,
        ):
            raise ValueError(
                f"ivf_pq supports L2Expanded/L2SqrtExpanded/InnerProduct, got "
                f"{self.metric.name}"
            )


@dataclasses.dataclass
class SearchParams:
    """reference: ivf_pq_types.hpp:110-146 search_params. ``lut_dtype``
    accepts jnp.float32, jnp.bfloat16, or jnp.float8_e4m3fn/e5m2 (fp8 LUTs
    are stored max-abs-scaled per subspace, the fp_8bit analog —
    detail/ivf_pq_fp_8bit.cuh); ``internal_distance_dtype`` accepts
    jnp.float32 or jnp.bfloat16."""

    n_probes: int = 20
    lut_dtype: object = jnp.float32
    internal_distance_dtype: object = jnp.float32
    # TPU-specific: how the ADC scan is evaluated.
    #   "auto"/"cache": scan decoded residuals with an MXU matmul (exactly
    #     the ADC distance, evaluated as ||q_res||² − 2·q_res·dec + ||dec||²
    #     instead of per-code LUT gathers, which XLA lowers to scalar loads).
    #     The decoded cache (bf16, rot_dim per row) is built lazily on the
    #     index and invalidated by extend().
    #   "lut": force the reference-shaped LUT gather path (lower memory —
    #     only the packed codes are resident).
    #   "pallas": fused Pallas scan+select — probed slabs (or packed codes
    #     + in-kernel LUT) are DMA'd to VMEM and the top-k is carried
    #     in-kernel, so no candidate slab touches HBM (docs/tuning.md).
    #     L2 metrics, no filter, k <= 1024; the LUT regime additionally
    #     needs pq_bits=8, PER_SUBSPACE, fp32 LUT dtypes. Unsupported
    #     combinations (and CPU without the interpret hook) fall back to
    #     the XLA engines silently; "auto" picks pallas on TPU only where
    #     the committed probe artifact shows it winning.
    scan_mode: str = "auto"
    # dtype of the decoded scan cache: bf16 (default; halves scan HBM
    # traffic, ~1e-3 recall cost — the reference's fp16/fp8-LUT trade) or
    # float32 (bit-exact vs the LUT path).
    scan_cache_dtype: object = jnp.bfloat16
    # <1.0 routes internal top-k through the TPU PartialReduce engine
    # (ops.select_k APPROX) at this per-element recall target; exact by
    # default — the same recall/speed dial family as lut_dtype
    select_recall: float = 1.0


def _calc_pq_dim(dim: int) -> int:
    """Heuristic default pq_dim (analog of the reference's calculate_pq_dim:
    a power of two close to dim/2, at least 8)."""
    p = 1
    while p * 2 <= dim // 2 or p < 8:
        p *= 2
        if p >= 512:
            break
    return max(min(p, dim + (-dim) % 8), 8)


class Index:
    """IVF-PQ index (reference: ivf_pq_types.hpp:149-560 — coarse centers,
    rotation matrix, codebooks, packed per-list codes + ids)."""

    def __init__(self, params: IndexParams, pq_dim: int, centers, rotation,
                 codebooks, list_codes, list_indices, list_sizes, n_rows: int,
                 overflow_codes=None, overflow_labels=None,
                 overflow_indices=None):
        self.params = params
        self.pq_dim = int(pq_dim)
        self.centers = centers  # [n_lists, dim] fp32
        self.rotation = rotation  # [rot_dim, dim] fp32 (orthonormal columns)
        # codebooks: PER_SUBSPACE [pq_dim, book, pq_len]
        #            PER_CLUSTER  [n_lists, book, pq_len]
        self.codebooks = codebooks
        self.list_codes = list_codes  # [n_lists, list_pad, n_code_bytes] u8
        self.list_indices = list_indices  # [n_lists, list_pad] int32, -1 pad
        self.list_sizes = list_sizes  # [n_lists] int32
        self.n_rows = int(n_rows)
        # rows spilled past the capped list_pad (list_packing
        # .choose_list_pad): packed codes + their coarse list + ids. Their
        # decoded rotated vectors (lazy, below) are scanned brute-force by
        # every query and merged into the final select_k. Empty in the
        # balanced common case.
        n_bytes = (pq_dim * params.pq_bits) // 8
        self.overflow_codes = (overflow_codes if overflow_codes is not None
                               else jnp.zeros((0, n_bytes), jnp.uint8))
        self.overflow_labels = (
            overflow_labels if overflow_labels is not None
            else jnp.zeros((0,), jnp.int32))
        self.overflow_indices = (
            overflow_indices if overflow_indices is not None
            else jnp.zeros((0,), jnp.int32))
        # lazy decoded-residual scan cache (see SearchParams.scan_mode):
        # [n_lists, list_pad, rot_dim] bf16 + per-row ||dec||² f32
        self.list_decoded = None
        self.decoded_norms = None
        # lazy decoded overflow: FULL rotated vectors (center_rot + decoded
        # residual) [n_over, rot_dim] + ||v||² f32 — both engines share it
        self.overflow_decoded = None
        self.overflow_norms = None

    @property
    def metric(self) -> DistanceType:
        return self.params.metric

    @property
    def n_lists(self) -> int:
        return self.centers.shape[0]

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def pq_bits(self) -> int:
        return self.params.pq_bits

    @property
    def pq_len(self) -> int:
        return self.rot_dim // self.pq_dim

    @property
    def pq_book_size(self) -> int:
        return 1 << self.pq_bits

    @property
    def size(self) -> int:
        return self.n_rows

    @property
    def centers_rot(self) -> jax.Array:
        return jnp.matmul(self.centers, self.rotation.T,
                          precision=jax.lax.Precision.HIGHEST)


# ------------------------------------------------------------- rotation matrix


def make_rotation_matrix(key, rot_dim: int, dim: int,
                         force_random: bool) -> jax.Array:
    """[rot_dim, dim] with orthonormal columns (reference:
    detail/ivf_pq_build.cuh:121-137 — random normal + in-place QR when
    force_random or rot_dim != dim, else identity)."""
    if not force_random and rot_dim == dim:
        return jnp.eye(dim, dtype=jnp.float32)
    if not force_random:
        # dim-padding only: identity embedding keeps exactness
        return jnp.eye(rot_dim, dim, dtype=jnp.float32)
    a = jax.random.normal(key, (rot_dim, rot_dim), jnp.float32)
    q, _ = jnp.linalg.qr(a)
    return q[:, :dim]


# --------------------------------------------------------- codebook training


def _codebook_em(subvecs, weights, book_size: int, n_iters: int, key):
    """Lloyd EM for one codebook: subvecs [n, l], weights [n] (0 = padding).
    Empty codes re-seed from a pseudo-random weighted row (the balancing
    analog of kmeans_balanced's adjust_centers for tiny codebook fits)."""
    n, l = subvecs.shape

    def m_step(labels):
        w = weights
        sums = jnp.zeros((book_size, l), jnp.float32).at[labels].add(
            subvecs * w[:, None])
        counts = jnp.zeros((book_size,), jnp.float32).at[labels].add(w)
        return sums, counts

    def body(i, state):
        centers, _ = state
        cn = jnp.sum(centers * centers, -1)
        d = cn[None, :] - 2.0 * jnp.matmul(
            subvecs, centers.T, precision=jax.lax.Precision.HIGHEST)
        # (+ ||x||², rank-invariant)
        labels = jnp.argmin(d, axis=1).astype(jnp.int32)
        sums, counts = m_step(labels)
        new = sums / jnp.maximum(counts, 1.0)[:, None]
        # re-seed empty codes from the weighted seed pool (never padding)
        donor = seed_rows[jax.random.randint(
            jax.random.fold_in(key, i), (book_size,), 0, pool_size)]
        empty = counts < 0.5
        new = jnp.where(empty[:, None], subvecs[donor], new)
        return new, labels

    # init: ``book_size`` distinct (weight>0) data rows via Gumbel top-k —
    # the data-point seeding that keeps Lloyd from collapsing to the mean.
    # Trainsets smaller than the book reuse rows cyclically.
    g = jax.random.gumbel(jax.random.fold_in(key, n_iters + 1), (n,))
    g = jnp.where(weights > 0, g, -jnp.inf)
    _, seed_rows = jax.lax.top_k(g, min(book_size, n))
    if n < book_size:
        seed_rows = jnp.tile(seed_rows, cdiv(book_size, n))[:book_size]
    pool_size = seed_rows.shape[0]
    centers0 = subvecs[seed_rows]
    labels0 = jnp.zeros((n,), jnp.int32)
    centers, _ = jax.lax.fori_loop(
        0, n_iters, body, (centers0, labels0))
    return centers


@functools.partial(jax.jit, static_argnames=("book_size", "n_iters"))
def _train_codebooks_jit(keys, subvecs, weights, book_size: int, n_iters: int):
    """subvecs [G, n, l], weights [G, n] → codebooks [G, book, l]; sequential
    over groups (one compile), each EM internally vectorized."""

    def one(args):
        key, sv, w = args
        return _codebook_em(sv, w, book_size, n_iters, key)

    return jax.lax.map(one, (keys, subvecs, weights))


# ----------------------------------------------------------- code (un)packing


def _pack_codes_np(codes: np.ndarray, pq_bits: int) -> np.ndarray:
    """Bit-pack [n, pq_dim] uint8 codes → [n, pq_dim*pq_bits/8] bytes
    (little-endian bit order; analog of process_and_fill_codes' packing,
    detail/ivf_pq_build.cuh:1185-1351)."""
    n, pq_dim = codes.shape
    bits = (codes[:, :, None] >> np.arange(pq_bits, dtype=np.uint8)) & 1
    flat = bits.reshape(n, pq_dim * pq_bits)
    return np.packbits(flat, axis=1, bitorder="little")


@functools.lru_cache(maxsize=None)
def _pack_terms(pq_dim: int, pq_bits: int):
    """Static (code index, shift) terms per output byte for device-side
    bit-packing: byte j collects the codes whose [k·bits, (k+1)·bits) span
    intersects [8j, 8j+8) — at most 3 codes for pq_bits ∈ [4, 8].
    shift ≥ 0 means ``code << shift``, else ``code >> -shift``."""
    n_bytes = pq_dim * pq_bits // 8
    terms = []
    for j in range(n_bytes):
        lo_k = (8 * j) // pq_bits
        hi_k = min((8 * j + 7) // pq_bits, pq_dim - 1)
        terms.append([(k, k * pq_bits - 8 * j)
                      for k in range(lo_k, hi_k + 1)])
    width = max(len(t) for t in terms)
    ks = np.zeros((n_bytes, width), np.int32)
    shifts = np.zeros((n_bytes, width), np.int32)
    valid = np.zeros((n_bytes, width), bool)
    for j, t in enumerate(terms):
        for w, (k, s) in enumerate(t):
            ks[j, w], shifts[j, w], valid[j, w] = k, s, True
    # plain numpy (trace-safe constants): this cache may be populated
    # inside a jit trace, where a jnp array would memoize a leaked tracer
    return ks, shifts, valid


@functools.partial(jax.jit, static_argnames=("pq_dim", "pq_bits"))
def _pack_codes_jit(codes, pq_dim: int, pq_bits: int):
    """[..., pq_dim] int codes → [..., pq_dim·pq_bits/8] uint8, on device
    (bit-identical to ``_pack_codes_np``; the packing half of
    process_and_fill_codes, detail/ivf_pq_build.cuh:1185-1351)."""
    ks, shifts, valid = _pack_terms(pq_dim, pq_bits)
    c = jnp.take(codes.astype(jnp.int32), ks, axis=-1)  # [..., nb, w]
    up = jnp.where(shifts >= 0, c << jnp.maximum(shifts, 0),
                   c >> jnp.maximum(-shifts, 0))
    up = jnp.where(valid, up, 0)
    # in-byte bits of the terms are disjoint, so the mod-256 sum equals
    # the OR of the in-byte contributions (out-of-byte bits fall off in
    # the uint8 cast — they belong to neighboring bytes' own terms)
    return up.sum(-1).astype(jnp.uint8)


def _unpack_positions(pq_dim: int, pq_bits: int):
    """Static per-subspace (lo_byte, hi_byte, shift) for two-byte unpack."""
    pos = np.arange(pq_dim) * pq_bits
    lo = pos // 8
    sh = pos % 8
    n_bytes = pq_dim * pq_bits // 8
    hi = np.minimum(lo + 1, n_bytes - 1)
    return jnp.asarray(lo), jnp.asarray(hi), jnp.asarray(sh)


def _unpack_codes(code_bytes: jax.Array, pq_dim: int, pq_bits: int) -> jax.Array:
    """[..., n_bytes] uint8 → [..., pq_dim] int32 codes. Each pq_bits field
    spans ≤ 2 bytes; static gathers keep this a pure vector op."""
    lo, hi, sh = _unpack_positions(pq_dim, pq_bits)
    b = code_bytes.astype(jnp.int32)
    lo_b = jnp.take(b, lo, axis=-1)
    hi_b = jnp.take(b, hi, axis=-1)
    word = lo_b | (hi_b << 8)
    return (word >> sh) & ((1 << pq_bits) - 1)


@functools.partial(jax.jit, static_argnames=("pq_dim", "pq_bits",
                                              "per_cluster", "list_tile",
                                              "cache_dtype"))
def _decode_lists_jit(codebooks, list_codes, pq_dim: int, pq_bits: int,
                      per_cluster: bool, list_tile: int,
                      cache_dtype=jnp.bfloat16):
    """Decode packed list codes → residual vectors [L, pad, rot_dim] bf16
    plus their squared norms [L, pad] f32 (the scan cache). The codebook
    gather runs once per build over list tiles (bounded HBM), not per query."""
    n_lists, list_pad, _ = list_codes.shape
    book = codebooks.shape[1]
    pq_len = codebooks.shape[2]

    n_tiles = cdiv(n_lists, list_tile)
    pad_l = n_tiles * list_tile - n_lists
    codes_p = jnp.pad(list_codes, ((0, pad_l), (0, 0), (0, 0)))
    cb_p = (jnp.pad(codebooks, ((0, pad_l), (0, 0), (0, 0)))
            if per_cluster else codebooks)

    def tile_body(args):
        ct, cbt = args
        codes = _unpack_codes(ct, pq_dim, pq_bits)  # [lt, pad, s]
        if per_cluster:
            # decoded[l,p,s,:] = cbt[l, codes[l,p,s], :]
            dec = jnp.take_along_axis(
                cbt[:, None, None, :, :],
                codes[:, :, :, None, None].astype(jnp.int32), axis=3,
            )[:, :, :, 0, :]
        else:
            # decoded[l,p,s,:] = codebooks[s, codes[l,p,s], :]
            flat = codebooks.reshape(pq_dim * book, pq_len)
            dec = jnp.take(flat, codes + jnp.arange(pq_dim) * book, axis=0)
        dec = dec.reshape(ct.shape[0], list_pad, pq_dim * pq_len)
        norms = jnp.sum(dec.astype(jnp.float32) ** 2, -1)
        return dec.astype(cache_dtype), norms

    if per_cluster:
        dec, norms = jax.lax.map(
            tile_body,
            (codes_p.reshape(n_tiles, list_tile, list_pad, -1),
             cb_p.reshape(n_tiles, list_tile, book, pq_len)))
    else:
        dec, norms = jax.lax.map(
            lambda ct: tile_body((ct, None)),
            codes_p.reshape(n_tiles, list_tile, list_pad, -1))
    dec = dec.reshape(n_tiles * list_tile, list_pad, -1)[:n_lists]
    norms = norms.reshape(n_tiles * list_tile, list_pad)[:n_lists]
    return dec, norms


def ensure_scan_cache(index: Index, dtype=jnp.bfloat16) -> None:
    """Build the decoded-residual scan cache if absent (idempotent).

    bf16 (default) halves scan HBM traffic for ~1e-3 recall — the same
    precision/bandwidth trade the reference's fp16/fp8 LUTs make; pass
    ``dtype=jnp.float32`` for bit-exact parity with the LUT path."""
    if index.list_codes is None:
        return
    if (index.list_decoded is not None
            and index.list_decoded.dtype == jnp.dtype(dtype)):
        return
    per_cluster = index.params.codebook_kind == CodebookGen.PER_CLUSTER
    # balanced grid: n_lists=130 with a flat 128 cap would pay a second,
    # 98%-padding tile (cf. shape.balanced_tile)
    list_tile = balanced_tile(index.n_lists, min(index.n_lists, 128), 8)
    # pad list count so tiles divide evenly inside the jit
    index.list_decoded, index.decoded_norms = _decode_lists_jit(
        index.codebooks, index.list_codes, index.pq_dim, index.pq_bits,
        per_cluster, list_tile, jnp.dtype(dtype).name)


@functools.partial(jax.jit, static_argnames=("pq_dim", "pq_bits",
                                             "per_cluster", "cache_dtype"))
def _decode_overflow_jit(codebooks, centers_rot, codes_bytes, labels,
                         pq_dim: int, pq_bits: int, per_cluster: bool,
                         cache_dtype=jnp.bfloat16):
    """Decode spilled code rows → FULL rotated vectors [O, rot_dim]
    (coarse center + decoded residual; unlike the list cache, overflow
    rows mix lists, so the center term must be baked in) + ||v||² f32."""
    book = codebooks.shape[1]
    pq_len = codebooks.shape[2]
    codes = _unpack_codes(codes_bytes, pq_dim, pq_bits)  # [O, s]
    if per_cluster:
        # dec[o, s, :] = codebooks[labels[o], codes[o, s], :]
        dec = codebooks[labels[:, None], codes]  # [O, s, l]
    else:
        flat = codebooks.reshape(pq_dim * book, pq_len)
        dec = jnp.take(flat, codes + jnp.arange(pq_dim) * book, axis=0)
    full = centers_rot[labels] + dec.reshape(codes.shape[0],
                                             pq_dim * pq_len)
    norms = jnp.sum(full.astype(jnp.float32) ** 2, -1)
    return full.astype(cache_dtype), norms


def ensure_overflow_decoded(index: Index, dtype=jnp.bfloat16) -> None:
    """Materialize the decoded overflow block (tiny: only spilled rows)."""
    if index.overflow_codes.shape[0] == 0:
        return
    if (index.overflow_decoded is not None
            and index.overflow_decoded.dtype == jnp.dtype(dtype)):
        return
    per_cluster = index.params.codebook_kind == CodebookGen.PER_CLUSTER
    index.overflow_decoded, index.overflow_norms = _decode_overflow_jit(
        index.codebooks, index.centers_rot, index.overflow_codes,
        index.overflow_labels, index.pq_dim, index.pq_bits, per_cluster,
        jnp.dtype(dtype).name)


# ----------------------------------------------------------------- encoding


@functools.partial(jax.jit, static_argnames=("per_cluster", "row_tile"))
def _encode_jit(x, labels, centers, rotation, codebooks, per_cluster: bool,
                row_tile: int):
    """Residual-encode rows → int32 codes [n, pq_dim]."""
    n, dim = x.shape
    pq_len = codebooks.shape[2]
    pq_dim = rotation.shape[0] // pq_len

    n_tiles = cdiv(n, row_tile)
    pad = n_tiles * row_tile - n
    xp = jnp.pad(x.astype(jnp.float32), ((0, pad), (0, 0)))
    lp = jnp.pad(labels, (0, pad))

    def tile_body(args):
        xt, lt = args
        res = xt - centers[lt]
        rr = jax.lax.dot_general(
            res, rotation, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # [t, rot_dim]
        sub = rr.reshape(-1, pq_dim, pq_len)  # [t, s, l]
        if per_cluster:
            cb = codebooks[lt]  # [t, book, l]
            dots = jnp.einsum("tsl,tcl->tsc", sub, cb,
                              preferred_element_type=jnp.float32)
            cn = jnp.sum(cb * cb, -1)  # [t, book]
            d = cn[:, None, :] - 2.0 * dots
        else:
            dots = jnp.einsum("tsl,scl->tsc", sub, codebooks,
                              preferred_element_type=jnp.float32)
            cn = jnp.sum(codebooks * codebooks, -1)  # [s, book]
            d = cn[None, :, :] - 2.0 * dots
        return jnp.argmin(d, axis=-1).astype(jnp.int32)  # [t, s]

    codes = jax.lax.map(
        tile_body,
        (xp.reshape(n_tiles, row_tile, dim), lp.reshape(n_tiles, row_tile)),
    )
    return codes.reshape(-1, pq_dim)[:n]


def _pack_lists_np(code_bytes: np.ndarray, labels: np.ndarray, n_lists: int,
                   ids: np.ndarray, max_expansion: float = 1.5):
    """Group packed code rows by cluster into padded list storage (native
    C++ packer; analog of process_and_fill_codes' list placement). ``pad``
    is budget-capped (list_packing.choose_list_pad); rows past a hot
    list's cap spill to the returned overflow block.

    Returns (codes, idxs, sizes, over_codes, over_labels, over_ids)."""
    from raft_tpu import native

    sizes = np.bincount(labels, minlength=n_lists).astype(np.int32)
    pad = list_packing.choose_list_pad(sizes, max_expansion)
    if int(sizes.max(initial=0)) <= pad:
        codes, idxs, sizes = native.pack_lists(code_bytes, labels, n_lists,
                                               pad, ids)
        return (codes, idxs, sizes, code_bytes[:0],
                np.zeros((0,), np.int32), np.zeros((0,), np.int32))
    keep = list_packing.fit_mask(labels, n_lists, pad)
    codes, idxs, sizes = native.pack_lists(
        np.ascontiguousarray(code_bytes[keep]), labels[keep], n_lists, pad,
        np.ascontiguousarray(np.asarray(ids, np.int32)[keep]))
    over_codes, over_ids = list_packing.pad_overflow_block(
        np.ascontiguousarray(code_bytes[~keep]),
        np.ascontiguousarray(np.asarray(ids, np.int32)[~keep]))
    over_labels = np.zeros((len(over_ids),), np.int32)
    spill_lab = labels[~keep]
    over_labels[:len(spill_lab)] = spill_lab
    return codes, idxs, sizes, over_codes, over_labels, over_ids


@functools.partial(jax.jit, static_argnames=("n_lists", "cap"))
def _group_rows_jit(rows, labels, n_lists: int, cap: int):
    """Group rows by label into padded [n_lists, cap, d] storage + 0/1
    weights, keeping each label's first ``cap`` rows in input order (device
    analog of the PER_CLUSTER trainset grouping loop)."""
    order, sl, slot = list_packing.label_slots(
        labels, jnp.zeros((n_lists,), jnp.int32), n_lists)
    grouped = jnp.zeros((n_lists, cap, rows.shape[1]), jnp.float32)
    grouped = grouped.at[sl, slot].set(
        rows[order].astype(jnp.float32), mode="drop")
    weights = jnp.zeros((n_lists, cap), jnp.float32).at[sl, slot].set(
        1.0, mode="drop")
    return grouped, weights


# --------------------------------------------------------------------- build


@tracing.range("ivf_pq.build")
def build(
    dataset,
    params: Optional[IndexParams] = None,
    res: Optional[Resources] = None,
    coarse_centers=None,
) -> Index:
    """Build the index (reference: ivf_pq::build, ivf_pq-inl.cuh:273 →
    detail/ivf_pq_build.cuh:1732).

    ``coarse_centers`` skips the coarse k-means and trains rotation +
    codebooks against the given ``[n_lists, dim]`` centers — the pod-scale
    build path (parallel/sharded.build_ivf_pq_from_file_pod) trains ONE
    mesh-wide quantizer and injects it into every shard's build."""
    params = params or IndexParams()
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    n_rows, dim = dataset.shape
    if params.n_lists > n_rows:
        raise ValueError(f"n_lists={params.n_lists} > n_rows={n_rows}")

    pq_dim = params.pq_dim or _calc_pq_dim(dim)
    if (pq_dim * params.pq_bits) % 8 != 0:
        raise ValueError(
            f"pq_dim*pq_bits must be a multiple of 8 "
            f"(got {pq_dim}*{params.pq_bits}); see ivf_pq_types.hpp:538-545"
        )
    pq_len = cdiv(dim, pq_dim)
    rot_dim = pq_len * pq_dim

    # trainset subsample (detail/ivf_pq_build.cuh:1759)
    n_train = max(int(n_rows * params.kmeans_trainset_fraction), params.n_lists)
    n_train = min(n_train, n_rows)
    trainset = rrng.subsample_rows(res.next_key(), dataset, n_train)
    trainset = trainset.astype(jnp.float32)

    # coarse quantizer
    km = KMeansBalancedParams(n_iters=params.kmeans_n_iters,
                              metric=params.metric)
    if coarse_centers is not None:
        centers = jnp.asarray(coarse_centers, jnp.float32)
        if centers.shape != (params.n_lists, dim):
            raise ValueError(
                f"coarse_centers shape {tuple(centers.shape)} != "
                f"(n_lists={params.n_lists}, dim={dim})")
    else:
        centers = kmeans_balanced.fit(res.next_key(), trainset,
                                      params.n_lists, km, res=res)

    rotation = make_rotation_matrix(res.next_key(), rot_dim, dim,
                                    params.force_random_rotation)

    # residuals of the trainset, rotated
    labels = kmeans_balanced.predict(centers, trainset, km, res=res)
    residuals = jnp.matmul(trainset - centers[labels], rotation.T,
                           precision=jax.lax.Precision.HIGHEST)

    book = 1 << params.pq_bits
    if params.codebook_kind == CodebookGen.PER_SUBSPACE:
        # [pq_dim groups] × (subvectors of every training row)
        sub = jnp.transpose(
            residuals.reshape(n_train, pq_dim, pq_len), (1, 0, 2)
        )  # [G=pq_dim, n_train, pq_len]
        w = jnp.ones((pq_dim, n_train), jnp.float32)
        keys = jax.random.split(res.next_key(), pq_dim)
        codebooks = _train_codebooks_jit(keys, sub, w, book,
                                         params.kmeans_n_iters)
    else:
        # group training residuals per coarse cluster (ragged → padded) —
        # a device segment-scatter, no host loop over lists
        sizes = np.bincount(np.asarray(labels), minlength=params.n_lists)
        cap = max(int(min(sizes.max(), max(2 * n_train // params.n_lists, book))), book)
        grouped, weights = _group_rows_jit(residuals, labels,
                                           params.n_lists, int(cap))
        # pool subspace positions: codebook shared across subspaces
        sub = grouped.reshape(params.n_lists, cap * pq_dim, pq_len)
        w = jnp.repeat(weights, pq_dim, axis=1)
        keys = jax.random.split(res.next_key(), params.n_lists)
        codebooks = _train_codebooks_jit(keys, sub, w, book,
                                         params.kmeans_n_iters)

    index = Index(params, pq_dim, centers, rotation, codebooks,
                  None, None, None, 0)
    if params.add_data_on_build:
        index = extend(index, dataset, res=res)
    return index


def encode_batch(index: Index, vectors, labels,
                 res: Optional[Resources] = None) -> jax.Array:
    """Residual-encode + bit-pack one batch of vectors against their coarse
    labels → packed code bytes [n, pq_dim*pq_bits/8], entirely on device
    (the per-batch body of process_and_fill_codes,
    detail/ivf_pq_build.cuh:1185-1351). Shared by ``extend`` and the
    streamed ``neighbors.ooc`` builder."""
    res = ensure_resources(res)
    per_cluster = index.params.codebook_kind == CodebookGen.PER_CLUSTER
    row_tile = int(np.clip(
        res.workspace_limit_bytes //
        max(index.pq_dim * index.pq_book_size * 4 * 4, 1), 8, 4096))
    row_tile = balanced_tile(len(vectors), row_tile, 8)
    codes = _encode_jit(jnp.asarray(vectors, jnp.float32),
                        jnp.asarray(labels), index.centers, index.rotation,
                        index.codebooks, per_cluster, max(row_tile, 8))
    return _pack_codes_jit(codes, index.pq_dim, index.pq_bits)


@tracing.range("ivf_pq.extend")
def extend(index: Index, new_vectors, new_indices=None,
           res: Optional[Resources] = None) -> Index:
    """Encode + add vectors (reference: ivf_pq::extend, ivf_pq-inl.cuh:355 →
    detail/ivf_pq_build.cuh:1653)."""
    res = ensure_resources(res)
    new_vectors = jnp.asarray(new_vectors).astype(jnp.float32)
    km = KMeansBalancedParams(metric=index.metric)
    labels = kmeans_balanced.predict(index.centers, new_vectors, km, res=res)

    code_bytes = encode_batch(index, new_vectors, labels, res)

    labels_np = np.asarray(labels)
    if new_indices is None:
        # past the row count and any user-supplied id, spilled ids included
        base = index.n_rows
        if index.list_indices is not None:
            base = max(base, int(np.asarray(index.list_indices).max()) + 1)
        if index.overflow_indices.shape[0]:
            base = max(base,
                       int(np.asarray(index.overflow_indices).max()) + 1)
        new_ids = np.arange(base, base + len(code_bytes), dtype=np.int32)
    else:
        new_ids = np.asarray(new_indices, np.int32)

    code_bytes_np = np.asarray(code_bytes)
    if index.list_codes is None:
        # first fill goes through the native host packer (shared with the
        # out-of-core streamed builds, which pack from host RAM without a
        # device round-trip); test_extend_matches_single_shot_lists pins it
        # bit-for-bit to the device scatter below
        data, idxs, sizes, o_codes, o_labels, o_ids = _pack_lists_np(
            code_bytes_np, labels_np, index.n_lists, new_ids,
            index.params.list_pad_expansion)
        data, idxs, sizes = (jnp.asarray(data), jnp.asarray(idxs),
                             jnp.asarray(sizes))
        o_codes, o_labels, o_ids = (jnp.asarray(o_codes),
                                    jnp.asarray(o_labels),
                                    jnp.asarray(o_ids))
        n_rows = len(code_bytes_np)
    else:
        # device-side append: grow the pad (budget-capped) if needed, then
        # segment-scatter the new batch after each list's tail — existing
        # lists stay packed on device (VERDICT r1 #3; reference:
        # process_and_fill_codes). Rows past a hot list's cap spill to the
        # overflow block (the pad never shrinks — no repack on extend).
        old_sizes = np.asarray(index.list_sizes)
        counts = np.bincount(labels_np, minlength=index.n_lists)
        cap = max(list_packing.choose_list_pad(
            old_sizes + counts, index.params.list_pad_expansion),
            index.list_codes.shape[1])
        keep = list_packing.fit_mask(labels_np, index.n_lists, cap,
                                     sizes=old_sizes)
        data, idxs = list_packing.grow_pad(
            index.list_codes, index.list_indices,
            int((old_sizes + np.bincount(
                labels_np[keep], minlength=index.n_lists)).max()))
        data, idxs, sizes = list_packing.append_lists(
            data, idxs, index.list_sizes, jnp.asarray(code_bytes_np[keep]),
            jnp.asarray(new_ids[keep]), jnp.asarray(labels_np[keep]),
            index.n_lists)
        o_codes, o_labels, o_ids = _merge_pq_overflow(
            index, code_bytes_np[~keep], labels_np[~keep], new_ids[~keep])
        n_rows = index.n_rows + len(code_bytes_np)
    return Index(index.params, index.pq_dim, index.centers, index.rotation,
                 index.codebooks, data, idxs, sizes, n_rows,
                 o_codes, o_labels, o_ids)


def _merge_pq_overflow(index: Index, new_codes_np, new_labels_np,
                       new_ids_np):
    """Append spilled code rows to the overflow block (8-aligned; valid
    rows stay a prefix — padding ids are -1 at the tail only)."""
    if len(new_codes_np) == 0:
        return (index.overflow_codes, index.overflow_labels,
                index.overflow_indices)
    old_ids = np.asarray(index.overflow_indices)
    n_old = int((old_ids >= 0).sum())
    codes = np.concatenate(
        [np.asarray(index.overflow_codes)[:n_old], new_codes_np], axis=0)
    labels = np.concatenate(
        [np.asarray(index.overflow_labels)[:n_old],
         np.asarray(new_labels_np, np.int32)])
    ids = np.concatenate([old_ids[:n_old],
                          np.asarray(new_ids_np, np.int32)])
    codes_p, ids_p = list_packing.pad_overflow_block(codes, ids)
    labels_p = np.zeros((len(ids_p),), np.int32)
    labels_p[:len(labels)] = labels
    return jnp.asarray(codes_p), jnp.asarray(labels_p), jnp.asarray(ids_p)


# --------------------------------------------------------------------- search


def _pq_overflow_scan(q_rot, overflow_decoded, overflow_norms,
                      overflow_indices, filter_words,
                      metric: DistanceType, has_filter: bool, bad_fill):
    """Distances of one query tile against the decoded overflow block
    (FULL rotated vectors: center + residual — see ensure_overflow_decoded)
    in the same squared-L2 / IP space as the probed-list scan: [t, O]
    distances + broadcast ids, ready for the final select_k."""
    dots = jax.lax.dot_general(
        q_rot, overflow_decoded.astype(jnp.float32),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [t, O]
    if metric == DistanceType.InnerProduct:
        od = dots  # q_rot·v = q·center + q_rot·dec (rotation orthonormal)
    else:
        qn = jnp.sum(q_rot * q_rot, -1)
        od = qn[:, None] - 2.0 * dots + overflow_norms[None, :]
    ok = overflow_indices >= 0
    if has_filter:
        ok = ok & bitset_filter_mask(overflow_indices, filter_words)
    od = jnp.where(ok[None, :], od, bad_fill)
    oi = jnp.broadcast_to(overflow_indices[None, :],
                          (q_rot.shape[0], overflow_indices.shape[0]))
    return od, oi


def _search_cache_core(queries, centers, rotation, list_decoded,
                       decoded_norms, list_indices, list_sizes, filter_words,
                       metric: DistanceType, k: int, n_probes: int,
                       q_tile: int, has_filter: bool,
                       use_pallas: bool = False,
                       pallas_interpret: bool = False,
                       overflow_decoded=None, overflow_norms=None,
                       overflow_indices=None, has_overflow: bool = False,
                 select_recall: float = 1.0):
    """ADC scan over the decoded-residual cache: identical distances to the
    LUT formulation (||q_res − dec||² expands to ||q_res||² − 2 q_res·dec +
    ||dec||²), evaluated as one batched matvec per probe on the MXU."""
    nq, dim = queries.shape
    n_lists, list_pad, rot_dim = list_decoded.shape
    minimize = metric != DistanceType.InnerProduct

    def _sel(vals, kk, sel_min):
        return select_k_maybe_approx(vals, kk, sel_min, select_recall)

    n_q_tiles = cdiv(nq, q_tile)
    pad_q = n_q_tiles * q_tile - nq
    qp = jnp.pad(queries.astype(jnp.float32), ((0, pad_q), (0, 0)))

    centers_rot = jax.lax.dot_general(
        centers, rotation, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    valid_slot = jnp.arange(list_pad)[None, :] < list_sizes[:, None]

    def q_body(qt):
        q_rot = jax.lax.dot_general(
            qt, rotation, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        dots_c = jax.lax.dot_general(
            q_rot, centers_rot, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if metric == DistanceType.InnerProduct:
            _, probes = _sel(dots_c, n_probes, False)
        else:
            cn = jnp.sum(centers_rot * centers_rot, -1)
            _, probes = _sel(cn[None, :] - 2.0 * dots_c, n_probes, True)

        g_idx = list_indices[probes]
        g_valid = valid_slot[probes]
        if use_pallas:
            # fused probe-gather + scan kernel: each probed list slab is
            # DMA'd straight into VMEM (scalar-prefetch block index); the
            # [t, P, pad, rot] gather intermediate never exists in HBM
            from raft_tpu.ops import pallas_kernels as pk

            if metric == DistanceType.InnerProduct:
                qv = jnp.broadcast_to(
                    q_rot[:, None, :],
                    (qt.shape[0], n_probes, q_rot.shape[1]))
                part = pk.ivf_scan(probes, qv, list_decoded, decoded_norms,
                                   interpret=pallas_interpret)
                g_n = decoded_norms[probes]
                base = jnp.take_along_axis(dots_c, probes, axis=1)
                d = base[:, :, None] + 0.5 * (g_n - part)
            else:
                qr_res = q_rot[:, None, :] - centers_rot[probes]
                part = pk.ivf_scan(probes, qr_res, list_decoded,
                                   decoded_norms,
                                   interpret=pallas_interpret)
                qn = jnp.sum(qr_res * qr_res, -1)
                d = qn[:, :, None] + part
        elif metric == DistanceType.InnerProduct:
            g_dec = list_decoded[probes]  # [t, P, pad, rot] bf16
            # score = q·center + q_rot·dec
            dots = jnp.einsum("td,tpld->tpl", q_rot,
                              g_dec.astype(jnp.float32),
                              preferred_element_type=jnp.float32)
            base = jnp.take_along_axis(dots_c, probes, axis=1)
            d = base[:, :, None] + dots
        else:
            g_dec = list_decoded[probes]  # [t, P, pad, rot] bf16
            g_n = decoded_norms[probes]  # [t, P, pad]
            qr_res = q_rot[:, None, :] - centers_rot[probes]  # [t, P, rot]
            dots = jnp.einsum("tpd,tpld->tpl", qr_res,
                              g_dec.astype(jnp.float32),
                              preferred_element_type=jnp.float32)
            qn = jnp.sum(qr_res * qr_res, -1)  # [t, P]
            d = qn[:, :, None] - 2.0 * dots + g_n

        bad_fill = jnp.inf if minimize else -jnp.inf
        ok = g_valid
        if has_filter:
            ok = ok & bitset_filter_mask(g_idx, filter_words)
        d = jnp.where(ok, d, bad_fill)

        n_cand = n_probes * list_pad
        flat_d = d.reshape(qt.shape[0], n_cand)
        flat_i = g_idx.reshape(qt.shape[0], n_cand)
        if has_overflow:
            od, oi = _pq_overflow_scan(q_rot, overflow_decoded,
                                       overflow_norms, overflow_indices,
                                       filter_words, metric, has_filter,
                                       bad_fill)
            flat_d = jnp.concatenate([flat_d, od], axis=1)
            flat_i = jnp.concatenate([flat_i, oi], axis=1)
            n_cand += od.shape[1]
        kk = min(k, n_cand)
        v, sel = _sel(flat_d, kk, minimize)
        i_out = jnp.take_along_axis(flat_i, sel, axis=1)
        if kk < k:
            v = jnp.pad(v, ((0, 0), (0, k - kk)), constant_values=bad_fill)
            i_out = jnp.pad(i_out, ((0, 0), (0, k - kk)),
                            constant_values=-1)
        if metric == DistanceType.L2SqrtExpanded:
            v = jnp.sqrt(jnp.maximum(v, 0.0))
        return v, i_out

    if n_q_tiles == 1:
        vals, idxs = q_body(qp)
    else:
        vals, idxs = jax.lax.map(q_body, qp.reshape(n_q_tiles, q_tile, dim))
        vals = vals.reshape(-1, k)
        idxs = idxs.reshape(-1, k)
    return vals[:nq], idxs[:nq]


_search_cache_jit = jax.jit(
    _search_cache_core,
    static_argnames=("metric", "k", "n_probes", "q_tile", "has_filter",
                     "use_pallas", "pallas_interpret", "has_overflow",
                     "select_recall"),
)

#: public traceable-core names — the cross-package contract for the
#: sharded engines (parallel/sharded.py shard_maps these bodies) and the
#: graftcheck jaxpr audit; the underscore spellings stay package-private
#: (R004 layering, docs/analysis.md)
search_cache_core = _search_cache_core
encode_core = _encode_jit


def _search_lut_core(queries, centers, rotation, codebooks, list_codes,
                     list_indices, list_sizes, filter_words,
                     metric: DistanceType, k: int, n_probes: int, q_tile: int,
                     per_cluster: bool, pq_dim: int, pq_bits: int,
                     has_filter: bool, lut_dtype, dist_dtype,
                     overflow_decoded=None, overflow_norms=None,
                     overflow_indices=None, has_overflow: bool = False,
                 select_recall: float = 1.0, probe_tile: int = 0):
    """LUT-engine scan over packed codes (traceable core — also runs inside
    ``shard_map`` for the memory-lean sharded search, parallel/sharded.py).

    ``probe_tile`` bounds the peak scan intermediate: 0 or >= ``n_probes``
    scans all probed lists of a query tile in one pass (the original
    shape, peak [q_tile, n_probes, list_pad, …]); otherwise probes are
    processed in ``probe_tile``-wide chunks under ``lax.scan`` with a
    running top-k carry merged through the existing ``select_k`` machinery
    (the TPU analog of the GPU kernel's per-CTA probe loop), so the peak
    is [q_tile, probe_tile, list_pad, …] regardless of n_probes. Distance
    VALUES are bit-identical to the single-pass shape (each candidate's
    contraction is elementwise the same); only tie ORDER among equal
    distances can differ, because the running merge re-ranks ties by
    carry position rather than global flat index."""
    nq, dim = queries.shape
    n_lists, list_pad, _ = list_codes.shape
    pq_len = codebooks.shape[2]
    book = codebooks.shape[1]
    minimize = metric != DistanceType.InnerProduct
    p_tile = probe_tile if 0 < probe_tile < n_probes else n_probes

    def _sel(vals, kk, sel_min):
        return select_k_maybe_approx(vals, kk, sel_min, select_recall)

    n_q_tiles = cdiv(nq, q_tile)
    pad_q = n_q_tiles * q_tile - nq
    qp = jnp.pad(queries.astype(jnp.float32), ((0, pad_q), (0, 0)))

    centers_rot = jax.lax.dot_general(
        centers, rotation, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )  # [n_lists, rot_dim]
    cb_norms = jnp.sum(codebooks.astype(jnp.float32) ** 2, -1)  # [G, book]
    valid_slot = jnp.arange(list_pad)[None, :] < list_sizes[:, None]

    def q_body(qt):
        # ---- coarse cluster selection (select_clusters,
        # detail/ivf_pq_search.cuh:69-155)
        q_rot = jax.lax.dot_general(
            qt, rotation, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )  # [t, rot_dim]
        dots_c = jax.lax.dot_general(
            q_rot, centers_rot, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if metric == DistanceType.InnerProduct:
            coarse = dots_c
            _, probes = _sel(coarse, n_probes, False)
        else:
            cn = jnp.sum(centers_rot * centers_rot, -1)
            coarse = cn[None, :] - 2.0 * dots_c  # + ||q||² (rank-invariant)
            _, probes = _sel(coarse, n_probes, True)
        # [t, P]
        bad_fill = jnp.inf if minimize else -jnp.inf

        def probe_block(probes_blk, probe_ok):
            """LUT build + code scan of one probe chunk ``probes_blk``
            [t, pt] → (distances [t, pt, pad], ids [t, pt, pad]).
            ``probe_ok`` masks the scan-padding probes of the last chunk
            (None when every probe is real)."""
            pt = probes_blk.shape[1]
            # ---- LUT per (query, probe): [t, pt, pq_dim, book]
            qr_res = q_rot[:, None, :] - centers_rot[probes_blk]
            if metric == DistanceType.InnerProduct:
                qr_res = jnp.broadcast_to(q_rot[:, None, :], qr_res.shape)
            sub = qr_res.reshape(qt.shape[0], pt, pq_dim, pq_len)
            if per_cluster:
                cb_p = codebooks[probes_blk]  # [t, pt, book, l]
                dots = jnp.einsum("tpsl,tpcl->tpsc", sub, cb_p,
                                  preferred_element_type=jnp.float32)
                cbn = cb_norms[probes_blk][:, :, None, :]
            else:
                dots = jnp.einsum("tpsl,scl->tpsc", sub, codebooks,
                                  preferred_element_type=jnp.float32)
                cbn = cb_norms[None, None, :, :]  # [1, 1, s, book]
            if metric == DistanceType.InnerProduct:
                # score = q·center + Σ_s q_sub·cb[code_s]
                lut = dots
                base = jnp.take_along_axis(
                    dots_c, probes_blk, axis=1)  # [t, pt] — q·center term
            else:
                # ||q−center−decode||² = ||q_res||² − 2 q_res·cb + ||cb||²
                qn = jnp.sum(qr_res * qr_res, -1)  # [t, pt]
                lut = cbn - 2.0 * dots
                base = qn
            if str(lut_dtype) in ("float8_e4m3fn", "float8_e5m2"):
                # fp8 LUT with per-subspace max-abs scaling (the
                # reference's fp_8bit offset/scale normalization,
                # detail/ivf_pq_fp_8bit.cuh)
                lut_scale = jnp.maximum(
                    jnp.max(jnp.abs(lut), axis=-1), 1e-30)  # [t, pt, s]
                lut = (lut / lut_scale[..., None]).astype(lut_dtype)
            else:
                lut_scale = None
                lut = lut.astype(lut_dtype)

            # ---- gather probed lists and scan codes
            g_codes = list_codes[probes_blk]  # [t, pt, pad, n_bytes] u8
            g_idx = list_indices[probes_blk]  # [t, pt, pad]
            g_valid = valid_slot[probes_blk]
            codes = _unpack_codes(g_codes, pq_dim, pq_bits)  # [t,pt,pad,s]
            # flat-LUT gather: score contribution LUT[t,pt,s,code]
            flat_lut = lut.reshape(qt.shape[0], pt, pq_dim * book)
            gidx = codes + (jnp.arange(pq_dim) * book)[None, None, None, :]
            gather_dtype = dist_dtype if lut_scale is None else flat_lut.dtype
            contrib = jnp.take_along_axis(
                flat_lut[:, :, None, :].astype(gather_dtype),
                gidx.reshape(qt.shape[0], pt, list_pad * pq_dim)[:, :, None, :],
                axis=-1,
            ).reshape(qt.shape[0], pt, list_pad, pq_dim)
            if lut_scale is not None:
                # de-scale fp8 contributions per subspace before
                # accumulating
                contrib = contrib.astype(dist_dtype) * lut_scale[
                    :, :, None, :].astype(dist_dtype)
            d = jnp.sum(contrib.astype(dist_dtype),
                        axis=-1).astype(jnp.float32)
            d = d + base[:, :, None]

            ok = g_valid
            if has_filter:
                ok = ok & bitset_filter_mask(g_idx, filter_words)
            if probe_ok is not None:
                ok = ok & probe_ok[None, :, None]
                g_idx = jnp.where(probe_ok[None, :, None], g_idx, -1)
            d = jnp.where(ok, d, bad_fill)
            return d, g_idx

        if p_tile == n_probes:
            d, g_idx = probe_block(probes, None)
            n_cand = n_probes * list_pad
            flat_d = d.reshape(qt.shape[0], n_cand)
            flat_i = g_idx.reshape(qt.shape[0], n_cand)
        else:
            # probe-tile loop: running top-kk merge keeps the peak live
            # set at [t, p_tile, pad, …] however many lists are probed
            n_pt = cdiv(n_probes, p_tile)
            pp = n_pt * p_tile
            probes_p = jnp.pad(probes, ((0, 0), (0, pp - n_probes)))
            ok_p = (jnp.arange(pp) < n_probes).reshape(n_pt, p_tile)
            blocks = jnp.moveaxis(
                probes_p.reshape(qt.shape[0], n_pt, p_tile), 1, 0)
            kk = min(k, n_probes * list_pad)

            def step(carry, xs):
                cv, ci = carry
                pr, okb = xs
                d, gi = probe_block(pr, okb)
                cand_v = jnp.concatenate(
                    [cv, d.reshape(d.shape[0], -1)], axis=1)
                cand_i = jnp.concatenate(
                    [ci, gi.reshape(gi.shape[0], -1)], axis=1)
                v, sel = _sel(cand_v, kk, minimize)
                return (v, jnp.take_along_axis(cand_i, sel, axis=1)), None

            init = (jnp.full((qt.shape[0], kk), bad_fill, jnp.float32),
                    jnp.full((qt.shape[0], kk), -1, jnp.int32))
            (flat_d, flat_i), _ = jax.lax.scan(step, init, (blocks, ok_p))
            n_cand = kk

        if has_overflow:
            od, oi = _pq_overflow_scan(q_rot, overflow_decoded,
                                       overflow_norms, overflow_indices,
                                       filter_words, metric, has_filter,
                                       bad_fill)
            flat_d = jnp.concatenate([flat_d, od], axis=1)
            flat_i = jnp.concatenate([flat_i, oi], axis=1)
            n_cand += od.shape[1]
        kk = min(k, n_cand)
        v, sel = _sel(flat_d, kk, minimize)
        i_out = jnp.take_along_axis(flat_i, sel, axis=1)
        if kk < k:
            v = jnp.pad(v, ((0, 0), (0, k - kk)), constant_values=bad_fill)
            i_out = jnp.pad(i_out, ((0, 0), (0, k - kk)), constant_values=-1)
        if metric == DistanceType.L2SqrtExpanded:
            v = jnp.sqrt(jnp.maximum(v, 0.0))
        return v, i_out

    if n_q_tiles == 1:
        vals, idxs = q_body(qp)
    else:
        vals, idxs = jax.lax.map(q_body, qp.reshape(n_q_tiles, q_tile, dim))
        vals = vals.reshape(-1, k)
        idxs = idxs.reshape(-1, k)
    return vals[:nq], idxs[:nq]


_search_jit = jax.jit(
    _search_lut_core,
    static_argnames=("metric", "k", "n_probes", "q_tile", "per_cluster",
                     "pq_dim", "pq_bits", "has_filter", "lut_dtype",
                     "dist_dtype", "has_overflow", "select_recall",
                     "probe_tile"),
)


#: public traceable-core name (see search_cache_core above)
search_lut_core = _search_lut_core


def _coarse_probes_rot(queries, centers, rotation, n_probes: int):
    """Shared coarse step of the fused cores: rotate the queries and pick
    the top-n_probes clusters in rotated space — the same math (and the
    same tie behavior) as the XLA engines' q_body preamble."""
    q_rot = jax.lax.dot_general(
        queries.astype(jnp.float32), rotation, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    centers_rot = jax.lax.dot_general(
        centers, rotation, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    dots_c = jax.lax.dot_general(
        q_rot, centers_rot, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    cn = jnp.sum(centers_rot * centers_rot, -1)
    _, probes = select_k(cn[None, :] - 2.0 * dots_c, n_probes,
                         select_min=True)
    return q_rot, centers_rot, probes


def _fused_merge_overflow(v, i, q_rot, overflow_decoded, overflow_norms,
                          overflow_indices, k: int):
    """Merge the kernel's VMEM-carry survivors with the XLA overflow scan
    (squared space on both sides). Selection already happened in-kernel,
    so the merge select runs with ``pad_rules=False`` — TOPK_PAD models an
    HBM slab select and must not re-pad the short candidate list
    (ISSUE 10)."""
    od, oi = _pq_overflow_scan(q_rot, overflow_decoded, overflow_norms,
                               overflow_indices,
                               jnp.zeros((0,), jnp.uint32),
                               DistanceType.L2Expanded, False, jnp.inf)
    return select_k(jnp.concatenate([v, od], axis=1), k, select_min=True,
                    indices=jnp.concatenate([i, oi], axis=1),
                    pad_rules=False)


def _search_fused_cache_core(queries, centers, rotation, list_decoded,
                             decoded_norms, list_indices, list_sizes,
                             overflow_decoded, overflow_norms,
                             overflow_indices, metric: DistanceType, k: int,
                             n_probes: int, pad_tile: int,
                             has_overflow: bool, interpret: bool = False):
    """Fused-Pallas ADC scan over the decoded-residual cache
    (``scan_mode="pallas"``, L2 metrics): coarse selection stays XLA, then
    ``ops.pallas_kernels.fused_ivf_topk`` DMAs each probed cache slab to
    VMEM and merges ``||q_res||² − 2·q_res·dec + ||dec||²`` partials into
    an in-kernel top-k carry — the [nq, P, pad] candidate slab never
    exists in HBM and no TOPK_PAD padding applies to the fine scan.
    Unclamped, exactly like the XLA cache engine (ADC space)."""
    from raft_tpu.ops import pallas_kernels as pk

    list_pad = list_decoded.shape[1]
    q_rot, centers_rot, probes = _coarse_probes_rot(
        queries, centers, rotation, n_probes)
    valid_slot = jnp.arange(list_pad)[None, :] < list_sizes[:, None]
    safe_ids = jnp.where(valid_slot, list_indices, -1)
    qr_res = q_rot[:, None, :] - centers_rot[probes]  # [nq, P, rot]
    qn = jnp.sum(qr_res * qr_res, -1)  # [nq, P]
    v, i = pk.fused_ivf_topk(probes, qr_res, qn, list_decoded,
                             decoded_norms, safe_ids, k, pad_tile=pad_tile,
                             clamp=False, interpret=interpret)
    if has_overflow:
        v, i = _fused_merge_overflow(v, i, q_rot, overflow_decoded,
                                     overflow_norms, overflow_indices, k)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


_search_fused_cache_jit = jax.jit(
    _search_fused_cache_core,
    static_argnames=("metric", "k", "n_probes", "pad_tile", "has_overflow",
                     "interpret"),
)


def _search_fused_lut_core(queries, centers, rotation, codebooks,
                           list_codes, list_indices, list_sizes,
                           overflow_decoded, overflow_norms,
                           overflow_indices, metric: DistanceType, k: int,
                           n_probes: int, pad_tile: int, has_overflow: bool,
                           interpret: bool = False):
    """Fused-Pallas LUT engine (``scan_mode="pallas"`` at the LUT memory
    regime; pq_bits=8, PER_SUBSPACE, fp32 LUT only): the per-probe LUT is
    built from the resident codebooks INSIDE the kernel and consumed by
    the one-hot code accumulation feeding the same VMEM top-k carry —
    neither the [nq, P, s, book] LUT nor the [nq, P, pad] candidate slab
    ever materializes in HBM (``ops.pallas_kernels.fused_pq_topk``)."""
    from raft_tpu.ops import pallas_kernels as pk

    list_pad = list_codes.shape[1]
    q_rot, centers_rot, probes = _coarse_probes_rot(
        queries, centers, rotation, n_probes)
    valid_slot = jnp.arange(list_pad)[None, :] < list_sizes[:, None]
    safe_ids = jnp.where(valid_slot, list_indices, -1)
    cb_norms = jnp.sum(codebooks.astype(jnp.float32) ** 2, -1)
    v, i = pk.fused_pq_topk(probes, q_rot, centers_rot, codebooks,
                            cb_norms, list_codes, safe_ids, k,
                            pad_tile=pad_tile, interpret=interpret)
    if has_overflow:
        v, i = _fused_merge_overflow(v, i, q_rot, overflow_decoded,
                                     overflow_norms, overflow_indices, k)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


_search_fused_lut_jit = jax.jit(
    _search_fused_lut_core,
    static_argnames=("metric", "k", "n_probes", "pad_tile", "has_overflow",
                     "interpret"),
)

#: public traceable-core names for the fused paths (R004; audited by
#: graftcheck --jaxpr-audit at the VMEM-budget canonical shapes)
search_fused_cache_core = _search_fused_cache_core
search_fused_lut_core = _search_fused_lut_core


def lut_bytes_per_query_probe(list_pad: int, pq_dim: int, pq_bits: int,
                              lut_itemsize: int = 4,
                              dist_itemsize: int = 4) -> int:
    """TRUE peak live-set bytes of the LUT scan body per (query, probe).

    The pre-fix estimate counted only the LUT ``[t, P, s, book]`` and the
    packed-code gather — NOT the unpack intermediates (lo_b/hi_b/word
    int32, three ``[t, P, list_pad, pq_dim]`` arrays from the two-byte
    gather) or the score-gather temporaries (flat-LUT gather index +
    per-subspace contributions), which dominate as ``list_pad`` grows
    with n and are exactly what blew HBM at 1M rows (LUT_CRASH_tpu.json:
    q_tile solved from ~1/5 of the real footprint → a ~19 GB live set on
    a 16 GB chip). Itemized per (query, probe):

      LUT build   pq_dim·book·(4 + 4 + lut_itemsize)   dots + lut f32 + cast
      code gather list_pad·n_code_bytes                packed u8 rows
      unpack      list_pad·pq_dim·3·4                  lo_b, hi_b, word i32
      score       list_pad·pq_dim·(4 + dist_itemsize)  gather idx + contrib
      reduce      list_pad·(4 + 4 + 1)                 d f32, ids i32, valid
    """
    book = 1 << pq_bits
    n_code_bytes = pq_dim * pq_bits // 8
    return (pq_dim * book * (8 + lut_itemsize)
            + list_pad * n_code_bytes
            + list_pad * pq_dim * 12
            + list_pad * pq_dim * (4 + dist_itemsize)
            + list_pad * 9)


def plan_lut_tiles(n_probes: int, list_pad: int, pq_dim: int, pq_bits: int,
                   workspace_limit_bytes: int, lut_itemsize: int = 4,
                   dist_itemsize: int = 4) -> Tuple[int, int]:
    """Jointly solve (q_tile, probe_tile) for the LUT engine from the
    workspace budget so the scan is memory-bounded BY CONSTRUCTION: the
    peak intermediate is [q_tile, probe_tile, list_pad, …] and
    ``q_tile · probe_tile · lut_bytes_per_query_probe(...)`` fits the
    budget (full n_probes preferred; the probe-tile loop engages only
    when even an 8-query tile cannot hold all probes at once)."""
    per_qp = lut_bytes_per_query_probe(list_pad, pq_dim, pq_bits,
                                       lut_itemsize, dist_itemsize)
    q_tile, probe_tile = solve_joint_tiles(
        workspace_limit_bytes, per_qp, n_probes, outer_cap=256)
    if 1 < probe_tile < n_probes:
        # balance the probe grid (a 7-wide tile over 20 probes would pay
        # a 6/7-padding last chunk; cf. shape.balanced_tile)
        probe_tile = balanced_tile(n_probes, probe_tile, 1)
    return q_tile, probe_tile


def cache_bytes_per_query(n_probes: int, list_pad: int,
                          rot_dim: int) -> int:
    """TRUE peak live-set bytes of the decoded-cache scan per query: the
    gathered cache tile [P, pad, rot] bf16, its fp32 upcast feeding the
    MXU einsum, and the fp32 distance/id/mask temporaries. The itemized
    accounting ``plan_cache_tiles`` solves against — public so the
    obs.costs calibration audit can compare the planner's prediction to
    the compiled ``memory_analysis`` ground truth."""
    return n_probes * list_pad * (rot_dim * 6 + 24)


def plan_cache_tiles(n_probes: int, list_pad: int, rot_dim: int,
                     workspace_limit_bytes: int) -> int:
    """q_tile for the decoded-cache engine from the workspace budget: the
    peak per query is the gathered cache tile [P, pad, rot] bf16, its fp32
    upcast feeding the MXU einsum (the dominant term the old inline solve
    missed — a 3x undercount caught by the graftcheck jaxpr audit), and the
    fp32 distance/id/mask temporaries (shared by ``search`` and the audit,
    which certifies the solve statically)."""
    per_q = cache_bytes_per_query(n_probes, list_pad, rot_dim)
    q_tile = int(np.clip(workspace_limit_bytes // max(per_q, 1), 1, 1024))
    if q_tile >= 8:
        q_tile -= q_tile % 8
    return q_tile


def resolve_scan_mode(n_lists: int, list_pad: int, rot_dim: int,
                      n_code_bytes: int, cache_itemsize: int,
                      device_memory_bytes: Optional[int],
                      workspace_limit_bytes: int) -> str:
    """Memory-aware engine choice for ``scan_mode="auto"`` (VERDICT r2 #3;
    the reference's preferred_shmem_carveout / lut_dtype role,
    ivf_pq_types.hpp:110-146).

    HBM model (per chip):
      packed  = L·pad·(n_code_bytes + 4)          — always resident
      cache   = L·pad·(rot_dim·itemsize + 4)      — ON TOP of packed
      budget  = 50% of device HBM when the backend reports it (queries,
                per-tile gathers, XLA scratch and the rest of the program
                need the other half), else 4× workspace_limit (the CPU /
                unknown-backend fallback).
    Choose the decoded-cache engine only when packed + cache fit the
    budget; otherwise the LUT engine, which keeps only packed codes
    resident. The LUT engine is safe as the fallback at ANY index size:
    its scan workspace is bounded by construction — ``plan_lut_tiles``
    solves (q_tile, probe_tile) from the true peak live set
    (``lut_bytes_per_query_probe``), so the per-dispatch intermediate is
    [q_tile, probe_tile, list_pad, …] no matter how large n·n_probes
    grow (the 1M-row TPU-worker crash, LUT_CRASH_tpu.json, was the old
    one-axis q_tile solve under-counting that live set ~5×).

    DEEP-100M flagship shapes (deep-100M.json:252 — n=1e8, nlist=50000,
    pq_dim=96→rot_dim=96, pq_bits=8, bf16 cache): packed ≈ 1e8·(96+4)·1.5
    (1.5× pad budget) ≈ 15 GB total across 8 chips ≈ 1.9 GB/chip, while
    the decoded cache would ADD ≈ 1e8·(96·2+4)·1.5/8 ≈ 3.7 GB/chip and at
    nlist=50000 on ONE v5e chip (16 GB) the whole-index cache ≈ 29 GB —
    auto must (and does) pick LUT there; the test pins both regimes."""
    slots = n_lists * list_pad
    packed_bytes = slots * (n_code_bytes + 4)
    cache_bytes = slots * (rot_dim * cache_itemsize + 4)
    if device_memory_bytes is not None:
        budget = device_memory_bytes // 2
    else:
        budget = 4 * workspace_limit_bytes
    return "cache" if packed_bytes + cache_bytes <= budget else "lut"


@tracing.range("ivf_pq.search")
def search(
    index: Index,
    queries,
    k: int,
    params: Optional[SearchParams] = None,
    filter: Optional[Bitset] = None,
    res: Optional[Resources] = None,
    explain: bool = False,
):
    """Search (reference: ivf_pq::search, ivf_pq-inl.cuh:480). Distances for
    L2 metrics exclude nothing — they are the full ADC approximation; indices
    are source row ids, -1 where fewer than k candidates were probed. With
    ``explain=True`` a third element carries the
    :class:`raft_tpu.obs.explain.ExplainRecord` of the dispatch decision."""
    params = params or SearchParams()
    res = ensure_resources(res)
    if index.list_codes is None:
        raise ValueError("index has no data; call extend() first")
    queries = as_query_array(queries)  # host inputs stay host-side: the
    if queries.shape[1] != index.dim:  # jit call transfers the padded
        raise ValueError(              # batch in ONE dispatch
            f"query dim {queries.shape[1]} != index dim {index.dim}")
    nq = queries.shape[0]
    queries = pad_rows(queries, query_bucket(nq))  # serving batch bucket
    n_probes = int(min(params.n_probes, index.n_lists))
    list_pad = index.list_codes.shape[1]
    if params.scan_mode not in ("auto", "cache", "lut", "pallas"):
        raise ValueError(f"unknown scan_mode: {params.scan_mode}")
    scan_mode = params.scan_mode
    has_overflow = index.overflow_codes.shape[0] > 0
    if has_overflow:
        ensure_overflow_decoded(index, params.scan_cache_dtype)
    per_cluster = index.params.codebook_kind == CodebookGen.PER_CLUSTER
    from raft_tpu.ops import pallas_kernels as pk

    # ---- fused Pallas scan+select (the VMEM top-k carry). Fallback
    # matrix (docs/tuning.md): L2 metrics, no filter, small k; the fused
    # LUT regime additionally needs byte codes (pq_bits=8), PER_SUBSPACE
    # codebooks and fp32 LUT/distance dtypes. Anything else falls through
    # to the XLA engines below — the mode is a performance hint, never a
    # correctness switch; each resolution records its reason code.
    requested = scan_mode
    use_fused = fused_interp = False
    dreason = "forced"  # explicit "cache"/"lut": honored as asked
    if scan_mode in ("auto", "pallas"):
        use_fused, fused_interp, dreason = pk.fused_dispatch_explained(
            "ivf_pq", scan_mode)
    ineligible = fused_ineligible_reason(
        index.metric, index.list_codes.dtype, int(k), filter is not None,
        False, require_float=False)
    ex_params = {"k": int(k), "nq": nq, "bucket": queries.shape[0],
                 "n_probes": n_probes, "n_lists": index.n_lists,
                 "list_pad": list_pad, "pq_dim": index.pq_dim,
                 "pq_bits": index.pq_bits, "metric": index.metric.name}
    lut_unsupported = False
    with contextlib.ExitStack() as stack:
        cap = stack.enter_context(obs_explain.capture()) if explain else None
        v = i = None
        if use_fused and ineligible is None:
            # the same HBM model that splits cache/lut splits the fused
            # engines: the decoded cache is the faster scan when it fits
            engine = resolve_scan_mode(
                index.n_lists, list_pad, index.rot_dim,
                index.list_codes.shape[2],
                jnp.dtype(params.scan_cache_dtype).itemsize,
                device_memory_bytes=res.device_memory_bytes,
                workspace_limit_bytes=res.workspace_limit_bytes)
            if engine == "cache":
                ensure_scan_cache(index, params.scan_cache_dtype)
                pad_tile = pk.plan_fused_ivf_tile(
                    list_pad, index.rot_dim, int(k),
                    jnp.dtype(index.list_decoded.dtype).itemsize)
                obs_explain.record_dispatch(
                    "ivf_pq", requested, "pallas_cache", dreason,
                    params=ex_params,
                    plan={"memory_model": "cache", "pad_tile": pad_tile,
                          "interpret": fused_interp})
                v, i = _search_fused_cache_jit(
                    queries, index.centers, index.rotation,
                    index.list_decoded, index.decoded_norms,
                    index.list_indices, index.list_sizes,
                    index.overflow_decoded, index.overflow_norms,
                    index.overflow_indices, index.metric, int(k), n_probes,
                    pad_tile, has_overflow, fused_interp,
                )
            elif (not per_cluster and index.pq_bits == 8
                    and jnp.dtype(params.lut_dtype) == jnp.float32
                    and jnp.dtype(params.internal_distance_dtype)
                    == jnp.float32):
                pad_tile = pk.plan_fused_pq_tile(
                    list_pad, index.pq_dim, 1 << index.pq_bits,
                    index.codebooks.shape[2], int(k))
                obs_explain.record_dispatch(
                    "ivf_pq", requested, "pallas_lut", dreason,
                    params=ex_params,
                    plan={"memory_model": "lut", "pad_tile": pad_tile,
                          "interpret": fused_interp})
                v, i = _search_fused_lut_jit(
                    queries, index.centers, index.rotation, index.codebooks,
                    index.list_codes, index.list_indices, index.list_sizes,
                    index.overflow_decoded, index.overflow_norms,
                    index.overflow_indices, index.metric, int(k), n_probes,
                    pad_tile, has_overflow, fused_interp,
                )
            else:
                # fused LUT regime unsupported at these params -> XLA engines
                lut_unsupported = True
        if v is None:
            memory_resolved = scan_mode in ("auto", "pallas")
            if memory_resolved:
                scan_mode = resolve_scan_mode(
                    index.n_lists, list_pad, index.rot_dim,
                    index.list_codes.shape[2],
                    jnp.dtype(params.scan_cache_dtype).itemsize,
                    device_memory_bytes=res.device_memory_bytes,
                    workspace_limit_bytes=res.workspace_limit_bytes)
            if requested not in ("auto", "pallas"):
                reason = "forced"
            elif lut_unsupported:
                reason = "lut_params_unsupported"
            elif use_fused and ineligible:
                reason = ineligible
            else:
                reason = dreason
            if scan_mode == "cache":  # resolve_scan_mode never says "auto"
                ensure_scan_cache(index, params.scan_cache_dtype)
                # workspace: gathered decoded cache [t,P,pad,rot] bf16 +
                # dists
                q_tile = plan_cache_tiles(n_probes, list_pad, index.rot_dim,
                                          res.workspace_limit_bytes)
                obs_explain.record_dispatch(
                    "ivf_pq", requested, "cache", reason, params=ex_params,
                    plan={"memory_model": "cache",
                          "memory_auto": memory_resolved,
                          "q_tile": q_tile,
                          "predicted_workspace_bytes": q_tile *
                          cache_bytes_per_query(n_probes, list_pad,
                                                index.rot_dim)})
                v, i = _search_cache_jit(
                    queries, index.centers, index.rotation,
                    index.list_decoded, index.decoded_norms,
                    index.list_indices, index.list_sizes,
                    filter.words if filter is not None
                    else jnp.zeros((0,), jnp.uint32),
                    index.metric, int(k), n_probes, q_tile,
                    filter is not None,
                    # unfused ivf_scan routes only on a measured probe
                    # verdict (PALLAS_PROBE "fused" table); the env flag is
                    # retired
                    pk.fused_crossover("ivf_scan"), False,
                    index.overflow_decoded, index.overflow_norms,
                    index.overflow_indices, has_overflow,
                    select_recall=float(params.select_recall),
                )
            else:
                # workspace: the TRUE peak live set of the scan body (LUT
                # build + code gather + unpack/score temporaries —
                # lut_bytes_per_query_probe), solved jointly into
                # (q_tile, probe_tile) so the engine never materializes more
                # than the budget however large n·n_probes grow
                q_tile, probe_tile = plan_lut_tiles(
                    n_probes, list_pad, index.pq_dim, index.pq_bits,
                    res.workspace_limit_bytes,
                    jnp.dtype(params.lut_dtype).itemsize,
                    jnp.dtype(params.internal_distance_dtype).itemsize)
                obs_explain.record_dispatch(
                    "ivf_pq", requested, "lut", reason, params=ex_params,
                    plan={"memory_model": "lut",
                          "memory_auto": memory_resolved,
                          "q_tile": q_tile, "probe_tile": probe_tile,
                          "predicted_workspace_bytes": q_tile * probe_tile *
                          lut_bytes_per_query_probe(
                              list_pad, index.pq_dim, index.pq_bits,
                              jnp.dtype(params.lut_dtype).itemsize,
                              jnp.dtype(params.internal_distance_dtype)
                              .itemsize)})
                v, i = _search_jit(
                    queries, index.centers, index.rotation, index.codebooks,
                    index.list_codes, index.list_indices, index.list_sizes,
                    filter.words if filter is not None
                    else jnp.zeros((0,), jnp.uint32),
                    index.metric, int(k), n_probes, q_tile, per_cluster,
                    index.pq_dim, index.pq_bits, filter is not None,
                    jnp.dtype(params.lut_dtype).name, jnp.dtype(
                        params.internal_distance_dtype).name,
                    index.overflow_decoded, index.overflow_norms,
                    index.overflow_indices, has_overflow,
                    select_recall=float(params.select_recall),
                    probe_tile=probe_tile,
                )
    if explain:
        return v[:nq], i[:nq], cap.last
    return v[:nq], i[:nq]


_SERIAL_VERSION = 2  # v2: + list_pad_expansion, overflow block


def serialize(index: Index, file) -> None:
    """reference: detail/ivf_pq_serialize.cuh. Paths are written
    atomically (tmp + os.replace) with per-record crc framing."""
    if index.list_codes is None:
        raise ValueError("index has no data; call extend() before serialize()")
    with ser.writer_for(file) as stream:
        w = ser.IndexWriter(stream, "ivf_pq", _SERIAL_VERSION)
        w.scalar(int(index.metric), "<i4")
        w.scalar(index.params.n_lists, "<i8")
        w.scalar(index.params.kmeans_n_iters, "<i4")
        w.scalar(index.params.kmeans_trainset_fraction, "<f8")
        w.scalar(index.params.pq_bits, "<i4")
        w.scalar(index.pq_dim, "<i4")
        w.scalar(int(index.params.codebook_kind), "<i4")
        w.scalar(1 if index.params.force_random_rotation else 0, "<i4")
        w.scalar(index.params.list_pad_expansion, "<f8")
        w.scalar(index.n_rows, "<i8")
        w.array(index.centers)
        w.array(index.rotation)
        w.array(index.codebooks)
        w.array(index.list_codes)
        w.array(index.list_indices)
        w.array(index.list_sizes)
        w.array(index.overflow_codes)
        w.array(index.overflow_labels)
        w.array(index.overflow_indices)
        w.finish()


def deserialize(file, res: Optional[Resources] = None) -> Index:
    ensure_resources(res)
    with ser.reader_for(file) as stream:
        r = ser.IndexReader(stream, "ivf_pq", _SERIAL_VERSION)
        metric = DistanceType(r.scalar())
        n_lists = r.scalar()
        kmeans_n_iters = r.scalar()
        frac = r.scalar()
        pq_bits = r.scalar()
        pq_dim = r.scalar()
        kind = CodebookGen(r.scalar())
        force_rot = bool(r.scalar())
        # v1 files predate the capped pad: max-driven layout, no spill
        expansion = r.scalar() if r.version >= 2 else 1e30
        params = IndexParams(
            n_lists=n_lists, metric=metric, kmeans_n_iters=kmeans_n_iters,
            kmeans_trainset_fraction=frac, pq_bits=pq_bits, pq_dim=pq_dim,
            codebook_kind=kind, force_random_rotation=force_rot,
            list_pad_expansion=expansion,
        )
        n_rows = r.scalar()
        centers = jnp.asarray(r.array())
        rotation = jnp.asarray(r.array())
        codebooks = jnp.asarray(r.array())
        codes = jnp.asarray(r.array())
        idxs = jnp.asarray(r.array())
        sizes = jnp.asarray(r.array())
        o_codes = jnp.asarray(r.array()) if r.version >= 2 else None
        o_labels = jnp.asarray(r.array()) if r.version >= 2 else None
        o_ids = jnp.asarray(r.array()) if r.version >= 2 else None
        r.finish()
        return Index(params, pq_dim, centers, rotation, codebooks, codes,
                     idxs, sizes, n_rows, o_codes, o_labels, o_ids)


# ------------------------------------------------------------------ helpers


class helpers:
    """Code access utilities (reference: ivf_pq_helpers.cuh —
    ``helpers::codepacker::{pack,unpack}``, ``reconstruct_list_data``)."""

    @staticmethod
    def unpack_list_codes(index: "Index", label: int) -> np.ndarray:
        """Unpacked per-vector PQ codes of list ``label`` → [size, pq_dim]
        uint8 host array."""
        size = int(np.asarray(index.list_sizes)[label])
        packed = jnp.asarray(np.asarray(index.list_codes)[label, :size])
        return np.asarray(_unpack_codes(packed, index.pq_dim,
                                        index.pq_bits)).astype(np.uint8)

    @staticmethod
    def pack_list_codes(index: "Index", label: int, codes,
                        ids=None) -> "Index":
        """Overwrite list ``label`` with unpacked ``codes`` [n, pq_dim];
        returns a new Index."""
        codes = np.asarray(codes, np.uint8)
        packed = _pack_codes_np(codes, index.pq_bits)
        pad = index.list_codes.shape[1]
        if len(packed) > pad:
            raise ValueError(f"{len(packed)} codes exceed list capacity {pad}")
        data = np.asarray(index.list_codes).copy()
        idxs = np.asarray(index.list_indices).copy()
        sizes = np.asarray(index.list_sizes).copy()
        data[label, :len(packed)] = packed
        data[label, len(packed):] = 0
        if ids is not None:
            idxs[label, :len(packed)] = np.asarray(ids, np.int32)
        idxs[label, len(packed):] = -1
        old = int(sizes[label])
        sizes[label] = len(packed)
        out = Index(index.params, index.pq_dim, index.centers, index.rotation,
                    index.codebooks, jnp.asarray(data), jnp.asarray(idxs),
                    jnp.asarray(sizes), index.n_rows - old + len(packed))
        return out

    @staticmethod
    def reconstruct_list_data(index: "Index", label: int) -> np.ndarray:
        """Approximate original vectors of list ``label``
        (reference: helpers::reconstruct_list_data): center + rotationᵀ ·
        decoded residual."""
        codes = helpers.unpack_list_codes(index, label)  # [size, pq_dim]
        book = index.pq_book_size
        cbs = np.asarray(index.codebooks)
        if index.params.codebook_kind == CodebookGen.PER_CLUSTER:
            dec = cbs[label][codes.reshape(-1)]  # [size*s, l]
        else:
            flat = cbs.reshape(index.pq_dim * book, index.pq_len)
            offs = codes + np.arange(index.pq_dim)[None, :] * book
            dec = flat[offs.reshape(-1)]
        dec = dec.reshape(len(codes), index.rot_dim)
        center = np.asarray(index.centers)[label]
        rot = np.asarray(index.rotation)  # [rot_dim, dim]
        return center[None, :] + dec @ rot
