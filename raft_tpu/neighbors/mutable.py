"""Crash-consistent mutable IVF: WAL-backed upsert/delete + tombstones +
background compaction published through hot swap (ROADMAP item 3).

RAFT builds immutable indexes; every production store takes writes. This
module closes the gap without giving up the immutable families' search
quality: a :class:`MutableIvf` wraps an immutable base index (ivf_flat or
ivf_pq) and layers three mutable structures on top —

- a **delta segment**: recent rows kept in a host mirror and scanned
  brute-force on device alongside the base lists, merged bit-stably into
  the final ``select_k`` (candidates concatenate base-first, so ties
  break identically across calls and across a crash/replay cycle);
- a **tombstone bitset**: the standing filter of
  :func:`raft_tpu.ops.select_k.select_k_filtered` — a base row whose id
  was deleted (or superseded by a delta upsert) has its bit cleared, so
  a dead id can never surface no matter what the approximate base
  search returns;
- a **write-ahead log** on the v2 ``[len][payload][crc32]`` framing of
  :mod:`raft_tpu.core.serialize`: ``add``/``upsert``/``delete`` append
  a framed record and are acknowledged only after the frame is
  fsync-durable (fsyncs batch under a group-commit window), so crash
  recovery — replaying the WAL tail onto the last checkpoint — is
  lossless for every acknowledged write. A torn tail (crash mid-append)
  is truncated and reported as a typed
  ``IntegrityError(reason="torn_tail")``, never a crash; damage in the
  *middle* of the log (bytes after the bad frame) is real corruption
  and raises ``reason="corrupt"``.

The **compaction protocol** (:class:`Compactor`) re-clusters delta +
tombstones into a fresh immutable base off the hot path:

1. snapshot the live rows under the writer lock (searches keep serving);
2. build the new base index (family ``build``/``extend`` with the
   original ids — the expensive step, no locks held);
3. install the new base and drop compacted delta slots under the lock;
4. write a checkpoint (atomic ``writer_for`` tmp+rename) and trim the
   WAL to the records the checkpoint does not cover;
5. publish through the existing hot-swap machinery:
   ``Engine.swap_index`` on one engine, ``Fleet.rolling_swap``
   fleet-wide — so serving picks up the compacted artifact with a
   searcher-generation bump and zero dropped requests.

A crash at ANY point of 1–5 recovers: before 4 the old checkpoint plus
the untrimmed WAL replays to the same logical state; ``writer_for``
makes 4 atomic; after 4 the trimmed WAL replays onto the new
checkpoint. Each run emits one ``kind="compaction"`` span on the closed
:data:`COMPACTION_REASONS` vocabulary, reconciled 1:1 with the
``raft_tpu_mutable_compactions_total`` counter; a run exceeding
``stall_timeout_s`` fires a ``kind="compaction_stall"`` event and trips
the publish target's flight recorder (``dump_diagnostics``).

Concurrency discipline (graftcheck ``--threads``/``--flow`` target):
the writer stack uses ONE leaf lock — ``MutableIvf._lock``, shared with
its :class:`WriteAheadLog` so append + state apply commit in lsn order
without ever holding two locks (the repo lock graph stays edge-free).
The compactor's wakeup condition is its own leaf lock, never held
while calling into the writer. Durability waits are budgeted
(``WriteStalled`` after ``ack_timeout_s``) and every background thread
and stall timer is reclaimed from ``close()``/``stop()``.
"""

from __future__ import annotations

import io
import os
import struct
import threading
import time
import zlib
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import serialize as ser
from raft_tpu.core.errors import IntegrityError, RaftError
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import spans as obs_spans
from raft_tpu.ops.distance import DistanceType, is_min_close, resolve_metric
from raft_tpu.ops.select_k import select_k, select_k_filtered

__all__ = [
    "COMPACTION_OUTCOMES", "COMPACTION_REASONS", "Compactor",
    "CompactorCrashed", "MutableIvf", "WalRecord", "WriteAheadLog",
    "WriteStalled", "read_wal", "verify_dir", "verify_wal",
]

WAL_KIND = "mutable_wal"
WAL_VERSION = 1
CKPT_KIND = "mutable_ivf"
CKPT_VERSION = 2
#: on-disk file names inside a MutableIvf directory.
WAL_FILE = "wal.log"
CKPT_FILE = "checkpoint.idx"

OP_ADD, OP_UPSERT, OP_DELETE = 1, 2, 3
_OP_NAMES = {OP_ADD: "add", OP_UPSERT: "upsert", OP_DELETE: "delete"}

#: closed compaction-trigger vocabulary — anything else is a ValueError
#: at the request site, so dashboards never meet a novel reason label.
COMPACTION_REASONS = frozenset(
    {"delta_threshold", "tombstone_ratio", "interval", "manual"})
#: closed per-run outcome vocabulary (the span/counter label).
COMPACTION_OUTCOMES = frozenset({"ok", "failed", "skipped"})

_FAMILIES = ("ivf_flat", "ivf_pq")


class WriteStalled(RaftError):
    """An acknowledged-durability wait exceeded its budget: the WAL
    flusher could not fsync within ``ack_timeout_s``. The write IS in
    the in-memory index and MAY be durable — the caller must treat it
    as unacknowledged (retry-safe: add/upsert/delete replay
    idempotently)."""


class CompactorCrashed(RaftError):
    """Injected compactor death (``testing.faults.crash_compactor``):
    the run aborts between artifact write and publish, exactly the
    window the crash-recovery suite proves safe."""


# ===================================================================== WAL


class WalRecord(NamedTuple):
    """One decoded WAL record."""

    lsn: int
    op: int
    ids: np.ndarray  # [n] int32
    vectors: np.ndarray  # [n, dim] float32 ([0, 0] for deletes)


def _encode_record(lsn: int, op: int, ids: np.ndarray,
                   vectors: np.ndarray) -> bytes:
    buf = io.BytesIO()
    ser.serialize_scalar(buf, int(lsn), "<i8")
    ser.serialize_scalar(buf, int(op), "<i4")
    ser.serialize_array(buf, np.asarray(ids, np.int32))
    ser.serialize_array(buf, np.asarray(vectors, np.float32))
    return buf.getvalue()


def _decode_record(payload: bytes) -> WalRecord:
    buf = io.BytesIO(payload)
    lsn = int(ser.deserialize_scalar(buf))
    op = int(ser.deserialize_scalar(buf))
    ids = ser.deserialize_array(buf)
    vectors = ser.deserialize_array(buf)
    if op not in _OP_NAMES:
        raise IntegrityError(f"WAL record lsn={lsn}: unknown op {op}",
                             reason="corrupt")
    return WalRecord(lsn, op, ids, vectors)


def _wal_header() -> bytes:
    return ser.header_bytes(WAL_KIND, WAL_VERSION)


class WalScan(NamedTuple):
    """Result of reading a WAL file front to back."""

    #: "ok" | "torn_tail" | "corrupt" | "missing"
    status: str
    records: List[WalRecord]
    #: byte offset of the end of the last intact frame (truncation point)
    good_end: int
    #: the typed fault for non-ok statuses (IntegrityError), else None
    error: Optional[IntegrityError]


def read_wal(path) -> WalScan:
    """Scan a WAL front to back, classifying damage by WHERE it sits:

    - every frame intact → ``"ok"``;
    - the LAST frame is short or fails its crc and nothing follows it →
      ``"torn_tail"`` (a crash mid-append; recovery truncates at
      ``good_end`` and loses only never-acknowledged bytes);
    - a bad frame with more bytes after it → ``"corrupt"`` (bit rot in
      the durable prefix — unrecoverable by truncation, typed
      ``reason="corrupt"``).
    """
    if not os.path.exists(path):
        return WalScan("missing", [], 0, IntegrityError(
            f"{path}: WAL missing", path=str(path), reason="missing"))
    records: List[WalRecord] = []
    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        header = _wal_header()
        got = f.read(len(header))
        if got != header:
            return WalScan("corrupt", [], 0, IntegrityError(
                f"{path}: bad WAL header", path=str(path),
                reason="corrupt"))
        good_end = f.tell()
        n_rec = 0
        while True:
            hdr = f.read(ser.FRAME_LEN.size)
            if not hdr:
                return WalScan("ok", records, good_end, None)

            def torn(detail: str) -> WalScan:
                return WalScan("torn_tail", records, good_end, IntegrityError(
                    f"{path}: record {n_rec}: torn tail ({detail}) — "
                    f"truncating at byte {good_end} recovers every "
                    f"acknowledged write",
                    path=str(path), record=n_rec, reason="torn_tail"))

            if len(hdr) < ser.FRAME_LEN.size:
                return torn("partial length prefix")
            (n,) = ser.FRAME_LEN.unpack(hdr)
            payload = f.read(n)
            if len(payload) < n:
                return torn(f"{len(payload)} of {n} payload bytes")
            crc_raw = f.read(ser.FRAME_CRC.size)
            if len(crc_raw) < ser.FRAME_CRC.size:
                return torn("partial crc")
            (crc,) = ser.FRAME_CRC.unpack(crc_raw)
            if zlib.crc32(payload) != crc:
                if f.tell() >= size:
                    return torn(f"crc mismatch on the final frame "
                                f"({n} bytes)")
                return WalScan("corrupt", records, good_end, IntegrityError(
                    f"{path}: record {n_rec}: crc32 mismatch with "
                    f"{size - f.tell()} bytes after it — damage in the "
                    f"durable prefix, not a torn tail",
                    path=str(path), record=n_rec, reason="corrupt"))
            try:
                records.append(_decode_record(payload))
            except IntegrityError as e:
                return WalScan("corrupt", records, good_end, IntegrityError(
                    f"{path}: record {n_rec}: {e}", path=str(path),
                    record=n_rec, reason="corrupt"))
            good_end = f.tell()
            n_rec += 1


def verify_wal(path) -> dict:
    """Pre-flight classification of one WAL file (the
    ``tools/verify_checkpoint.py`` surface): status, record count, and
    the lsn replay range a recovery would apply."""
    scan = read_wal(path)
    lsns = [r.lsn for r in scan.records]
    return {
        "path": str(path),
        "status": scan.status,
        "records": len(scan.records),
        "first_lsn": min(lsns) if lsns else None,
        "last_lsn": max(lsns) if lsns else None,
        "good_end": scan.good_end,
        "error": str(scan.error) if scan.error is not None else None,
    }


class WriteAheadLog:
    """Append-only framed log with group-commit fsync batching.

    The header is IndexWriter-compatible (magic + format v2 + kind
    ``mutable_wal``) so :func:`raft_tpu.core.serialize.record_spans`
    and the byte-level fault injectors work on WAL files unchanged;
    records are raw v2 frames with NO footer (the file grows forever,
    a footer would be stale after the first append).

    ``lock`` may be supplied by the owner (:class:`MutableIvf` shares
    its state lock) so that "assign lsn + append + apply" commits as one
    critical section without ever nesting two locks. Durability waits
    ride a condition on the same lock: a writer blocks (budgeted) until
    the flusher's fsync covers its lsn. The flusher batches: it sleeps
    ``group_window_s`` after the first pending append, and both the
    sleep AND the fsync itself run with no lock held, so concurrent
    writers share one fsync and never stall behind it.
    """

    def __init__(self, path, *, lock: Optional[threading.Lock] = None,
                 group_window_s: float = 0.002):
        self.path = str(path)
        self.group_window_s = float(group_window_s)
        self._lock = lock if lock is not None else threading.Lock()
        self._cond = threading.Condition(self._lock)
        fresh = not os.path.exists(self.path) \
            or os.path.getsize(self.path) == 0
        self._file = open(self.path, "ab")  # guarded_by: _lock
        if fresh:
            self._file.write(_wal_header())
            self._file.flush()
            os.fsync(self._file.fileno())
        self._next_lsn = 1  # guarded_by: _lock
        self._appended_lsn = 0  # guarded_by: _lock
        self._durable_lsn = 0  # guarded_by: _lock
        self._appended_bytes = 0  # guarded_by: _lock
        self._closed = False  # guarded_by: _lock
        #: last benign fsync race (handle rotated/closed mid-sync)
        self.last_sync_error: Optional[BaseException] = None  # guarded_by: atomic
        self._flusher = threading.Thread(  # guarded_by: atomic
            target=self._flush_loop, name=f"wal-flush:{self.path}",
            daemon=True)
        self._flusher.start()

    # ------------------------------------------------------------- append
    def set_next_lsn(self, lsn: int) -> None:
        """Advance the lsn counter past replayed history (recovery)."""
        with self._lock:
            self._next_lsn = max(self._next_lsn, int(lsn))

    def append_locked(self, op: int, ids: np.ndarray,
                      vectors: np.ndarray) -> Tuple[int, int]:
        """Assign the next lsn and buffer one framed record. The CALLER
        holds ``_lock`` — this is the shared-lock commit point that
        keeps WAL order and in-memory apply order identical. Returns
        ``(lsn, frame_bytes)``; durability comes later via
        :meth:`wait_durable`."""
        if self._closed:
            raise ValueError(f"{self.path}: append on a closed WAL")
        lsn = self._next_lsn
        self._next_lsn += 1
        frame = ser.frame(_encode_record(lsn, op, ids, vectors))
        self._file.write(frame)
        self._appended_lsn = lsn
        self._appended_bytes += len(frame)
        self._cond.notify_all()  # wake the flusher
        return lsn, len(frame)

    def append(self, op: int, ids, vectors) -> int:
        """Standalone append (takes the lock itself)."""
        with self._lock:
            lsn, _ = self.append_locked(op, np.asarray(ids, np.int32),
                                        np.asarray(vectors, np.float32))
        return lsn

    def wait_durable(self, lsn: int, timeout_s: float) -> None:
        """Block until the fsync frontier covers ``lsn`` (budgeted)."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while self._durable_lsn < lsn and not self._closed:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise WriteStalled(
                        f"{self.path}: lsn {lsn} not durable within "
                        f"{timeout_s:.3f}s (durable frontier "
                        f"{self._durable_lsn})")
                self._cond.wait(timeout=remaining)
            if self._durable_lsn < lsn:
                raise WriteStalled(
                    f"{self.path}: WAL closed before lsn {lsn} became "
                    f"durable")

    def commit(self, op: int, ids, vectors,
               timeout_s: float = 30.0) -> int:
        """Append + wait for durability: the bare-writer write path."""
        lsn = self.append(op, ids, vectors)
        self.wait_durable(lsn, timeout_s)
        return lsn

    # -------------------------------------------------------------- flush
    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                while (not self._closed
                       and self._appended_lsn <= self._durable_lsn):
                    self._cond.wait(timeout=0.05)
                if self._closed and self._appended_lsn <= self._durable_lsn:
                    return
            # batch window: let concurrent writers pile onto this fsync
            # (no lock held — appends proceed while we sleep)
            if self.group_window_s > 0:
                time.sleep(self.group_window_s)
            self._sync()

    def _sync(self) -> None:
        # Snapshot the frontier and flush under the lock, fsync OUTSIDE
        # it (appends are strictly ordered, so the fsync still covers
        # every lsn <= target), then re-acquire to advance the durable
        # frontier — a group-commit fsync never blocks writers,
        # snapshot builds, or stats reads sharing this lock.
        with self._lock:
            target = self._appended_lsn
            if target <= self._durable_lsn:
                return
            f = self._file
            f.flush()
        try:
            os.fsync(f.fileno())
        except (OSError, ValueError) as e:
            # the handle was rotated (trim_locked) or closed under us;
            # both paths fsync everything appended before swapping the
            # file, so every lsn <= target is already durable
            self.last_sync_error = e
        with self._lock:
            self._durable_lsn = max(self._durable_lsn, target)
            self._cond.notify_all()

    def sync(self) -> int:
        """Force an immediate flush+fsync; returns the durable lsn."""
        self._sync()
        with self._lock:
            return self._durable_lsn

    # --------------------------------------------------------------- trim
    def trim_locked(self, keep_gt_lsn: int) -> int:
        """Atomically rewrite the WAL keeping only records with
        ``lsn > keep_gt_lsn`` (they post-date the checkpoint just
        written). The CALLER holds ``_lock``. Returns records kept."""
        self._file.flush()
        os.fsync(self._file.fileno())
        scan = read_wal(self.path)
        keep = [r for r in scan.records if r.lsn > keep_gt_lsn]
        with ser.writer_for(self.path) as stream:
            stream.write(_wal_header())
            for r in keep:
                stream.write(ser.frame(_encode_record(r.lsn, r.op, r.ids,
                                                      r.vectors)))
        self._file.close()
        self._file = open(self.path, "ab")
        self._durable_lsn = max(self._durable_lsn, self._appended_lsn)
        self._cond.notify_all()
        return len(keep)

    # ---------------------------------------------------------- lifecycle
    @property
    def appended_bytes(self) -> int:
        with self._lock:
            return self._appended_bytes

    @property
    def durable_lsn(self) -> int:
        with self._lock:
            return self._durable_lsn

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._flusher.join(timeout=5.0)
        with self._lock:
            self._file.flush()
            os.fsync(self._file.fileno())
            self._durable_lsn = max(self._durable_lsn, self._appended_lsn)
            self._file.close()
            self._cond.notify_all()


# ================================================================ MutableIvf


class _Mirror:
    """Host-side source of truth for the mutable overlay. Lives OUTSIDE
    the :class:`MutableIvf` ``__dict__`` array sweep on purpose: a
    serving ``Searcher.place()`` device-pins every direct ndarray
    attribute of the index, and these numpy mirrors must stay host
    numpy (they are mutated in place under the writer lock)."""

    def __init__(self, dim: int):
        self.dim = dim
        self.rows = np.zeros((0, dim), np.float32)  # [cap, dim]
        self.ids = np.zeros((0,), np.int32)  # [cap], -1 = free/invalid
        self.lsns = np.zeros((0,), np.int64)  # [cap] insertion lsn
        self.count = 0  # slots used (dense prefix)
        self.slot_of: dict = {}  # live delta id -> slot
        self.tombs: set = set()  # deleted ids whose base copy must hide
        self.base_ids: frozenset = frozenset()  # ids resident in base
        self.words = np.zeros((1,), np.uint32)  # base-ok standing filter
        self.applied_lsn = 0
        self.next_id = 0
        self.version = 0

    # ------------------------------------------------------------ filters
    def _ensure_words(self, max_id: int) -> None:
        need = max_id // 32 + 1
        if need > len(self.words):
            cap = 1 << (need - 1).bit_length()
            grown = np.zeros((cap,), np.uint32)
            grown[: len(self.words)] = self.words
            self.words = grown

    def _set_base_ok(self, id_: int, ok: bool) -> None:
        self._ensure_words(id_)
        w, b = id_ // 32, id_ % 32
        if ok:
            self.words[w] |= np.uint32(1 << b)
        else:
            self.words[w] &= ~np.uint32(1 << b)

    def rebuild_words(self) -> None:
        """Recompute the base-ok bitset from scratch: a base row's bit
        is set iff its id is neither deleted nor superseded by a delta
        copy (compaction install path)."""
        ids = np.fromiter(self.base_ids, np.int64, len(self.base_ids))
        self.words = np.zeros((max(len(self.words), 1),), np.uint32)
        if len(ids):
            self._ensure_words(int(ids.max()))
            dead = self.tombs | set(self.slot_of)
            for id_ in ids:
                if int(id_) not in dead:
                    self.words[id_ // 32] |= np.uint32(1 << (id_ % 32))

    # -------------------------------------------------------------- delta
    def _grow(self, need: int) -> None:
        cap = max(64, 1 << (need - 1).bit_length())
        if cap <= len(self.ids):
            return
        rows = np.zeros((cap, self.dim), np.float32)
        rows[: self.count] = self.rows[: self.count]
        ids = np.full((cap,), -1, np.int32)
        ids[: self.count] = self.ids[: self.count]
        lsns = np.zeros((cap,), np.int64)
        lsns[: self.count] = self.lsns[: self.count]
        self.rows, self.ids, self.lsns = rows, ids, lsns

    def put(self, id_: int, row: np.ndarray, lsn: int) -> None:
        """Insert-or-replace one row in the delta; hides any base copy."""
        old = self.slot_of.get(id_)
        if old is not None:
            self.rows[old] = row
            self.lsns[old] = lsn
        else:
            self._grow(self.count + 1)
            slot = self.count
            self.rows[slot] = row
            self.ids[slot] = id_
            self.lsns[slot] = lsn
            self.slot_of[id_] = slot
            self.count += 1
        self.tombs.discard(id_)
        if id_ in self.base_ids:
            self._set_base_ok(id_, False)
        self.next_id = max(self.next_id, id_ + 1)

    def drop(self, id_: int) -> bool:
        """Delete one id (delta slot invalidated, base copy tombstoned).
        Returns whether the id was live."""
        live = False
        slot = self.slot_of.pop(id_, None)
        if slot is not None:
            self.ids[slot] = -1
            live = True
        if id_ in self.base_ids and id_ not in self.tombs:
            self._set_base_ok(id_, False)
            live = True
        if live:
            # Tombstone EVERY live drop, not just base residents: a
            # delta row deleted while a compaction build is in flight
            # is already in the compactor's snapshot, and only this
            # tombstone (filtered against the NEW base at install)
            # stops it from resurrecting in the next epoch.
            self.tombs.add(id_)
        return live

    # ------------------------------------------------------------ queries
    def delta_live(self) -> int:
        return len(self.slot_of)

    def masked_base(self) -> int:
        return len(self.base_ids & (self.tombs | set(self.slot_of)))

    def live_ids(self) -> set:
        return (self.base_ids - self.tombs - set(self.slot_of)) \
            | set(self.slot_of)

    def tombstone_live_ratio(self) -> float:
        return self.masked_base() / max(len(self.base_ids), 1)


class _Cache(NamedTuple):
    """Device-resident snapshot of one mirror version (search path)."""

    version: int
    base: object
    rows: jax.Array  # [cap, dim]
    ids: jax.Array  # [cap] int32, -1 invalid
    words: jax.Array  # uint32 base-ok filter
    cap: int
    masked_base: int
    base_rows: int


class _CompactionSnapshot(NamedTuple):
    """The compactor's build input. For ivf_flat, ``vectors``/``ids``
    are EVERY live row (base rows are recoverable from flat storage) —
    the build is a full re-cluster that also sheds tombstoned rows.
    For ivf_pq the base stores codes, not rows, so ``vectors`` carry
    only the delta rows NOT already resident in the base and the build
    path re-encodes them into the existing base via ``extend``
    (tombstones persist as filter bits). Delta ids that superseded a
    base row (``keep_delta_ids``) are never extended — ``extend`` does
    not dedupe ids and the standing filter is id-keyed, so it could not
    mask just the stale physical copy; those rows stay in the delta
    segment, keeping the base copy masked."""

    vectors: np.ndarray
    ids: np.ndarray
    lsn: int
    base: object
    full_rebuild: bool
    n_base: int
    n_delta: int
    #: delta ids excluded from the build that must survive the install
    keep_delta_ids: frozenset


class MutableIvf:
    """Mutable overlay over one immutable IVF base index.

    Construct on a directory: an existing checkpoint restores (WAL tail
    replayed, torn tails truncated as typed ``torn_tail``); an empty
    directory initializes fresh — ``dim`` required, ``base`` optional
    (an already-built family index whose ids become the base id set).

    Writes (:meth:`add` / :meth:`upsert` / :meth:`delete`) apply to the
    in-memory overlay and return only after the WAL frame is
    fsync-durable, so every acknowledged write survives kill -9.
    :meth:`search` merges base + delta bit-stably with deleted ids
    filtered by the standing bitset. :meth:`checkpoint` persists the
    full state atomically and trims the WAL; :class:`Compactor` drives
    re-clustering + hot-swap publication in the background.
    """

    def __init__(self, directory, *, dim: Optional[int] = None,
                 family: str = "ivf_flat", base=None,
                 index_params=None, search_params=None, res=None,
                 name: Optional[str] = None,
                 registry: Optional[obs_metrics.Registry] = None,
                 span_sink=None, group_window_s: float = 0.002,
                 ack_timeout_s: float = 30.0):
        if family not in _FAMILIES:
            raise ValueError(f"family must be one of {_FAMILIES}, got "
                             f"{family!r}")
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.family = family
        self.index_params = index_params
        self.search_params = search_params
        self.res = res
        self.name = name if name is not None else os.path.basename(
            os.path.normpath(self.directory))
        self.span_sink = span_sink
        self.ack_timeout_s = float(ack_timeout_s)
        self._lock = threading.Lock()
        self._closed = False  # guarded_by: _lock
        self.compactor: Optional["Compactor"] = None  # guarded_by: atomic
        self._init_metrics(registry)

        ckpt = os.path.join(self.directory, CKPT_FILE)
        self.recovery: Optional[dict] = None  # guarded_by: atomic (init)
        self._ckpt_metric: Optional[str] = None  # guarded_by: atomic (init)
        if os.path.exists(ckpt):
            self.base, self._mirror = self._restore_checkpoint(ckpt)
        else:
            if base is not None:
                dim = int(base.dim)
            if dim is None:
                raise ValueError(
                    f"{self.directory}: no checkpoint to restore and no "
                    f"dim given for a fresh index")
            self.base = base  # guarded_by: _lock (compaction install)
            self._mirror = self._fresh_mirror(int(dim), base)
        self.dim = int(self._mirror.dim)
        # metric precedence: the live base (fresh OR restored — a reopen
        # passes base=None, the checkpoint's base is authoritative), then
        # the metric persisted in a base-less checkpoint, then params.
        if self.base is not None:
            self.metric = resolve_metric(self.base.metric)
        elif self._ckpt_metric is not None:
            self.metric = resolve_metric(self._ckpt_metric)
        else:
            self.metric = resolve_metric(
                getattr(index_params, "metric", DistanceType.L2Expanded))
        self._cache: Optional[_Cache] = None  # guarded_by: _lock

        wal_path = os.path.join(self.directory, WAL_FILE)
        self._recover_wal(wal_path)
        # the WAL object shares _lock (its condition rides on it) and is
        # opened AFTER replay so the recovery scan sees raw on-disk bytes
        self._wal = WriteAheadLog(wal_path, lock=self._lock,
                                  group_window_s=group_window_s)
        self._wal.set_next_lsn(self._mirror.applied_lsn + 1)
        self._set_gauges()

    # ------------------------------------------------------------- metrics
    def _init_metrics(self, registry) -> None:
        r = registry if registry is not None else obs_metrics.REGISTRY
        self.registry = r
        n = self.name
        writes = r.counter(
            "raft_tpu_mutable_writes_total",
            "Write operations applied to the mutable overlay, by op.",
            ("index", "op"))
        self._m_writes = {op: writes.labels(n, op)
                          for op in _OP_NAMES.values()}
        self._m_acks = r.counter(
            "raft_tpu_mutable_acks_total",
            "Writes acknowledged fsync-durable (ack ⊆ write; the gap is "
            "in-flight or stalled).", ("index",)).labels(n)
        self._m_wal_bytes = r.counter(
            "raft_tpu_mutable_wal_bytes_total",
            "Framed bytes appended to the WAL.", ("index",)).labels(n)
        replays = r.counter(
            "raft_tpu_mutable_replays_total",
            "WAL recovery scans by classification.", ("index", "status"))
        self._m_replays = {s: replays.labels(n, s)
                           for s in ("ok", "torn_tail")}
        self._m_compactions = r.counter(
            "raft_tpu_mutable_compactions_total",
            "Compaction runs by (reason, outcome) — reconciles 1:1 with "
            "kind=\"compaction\" spans.", ("index", "reason", "outcome"))
        self._m_stalls = r.counter(
            "raft_tpu_mutable_compaction_stalls_total",
            "Compaction runs that exceeded stall_timeout_s (each also "
            "emits kind=\"compaction_stall\" and trips the publish "
            "target's flight recorder).", ("index",)).labels(n)
        self._m_filtered = r.counter(
            "raft_tpu_mutable_filtered_rows_total",
            "Candidates removed by the tombstone standing filter in "
            "select_k_filtered.", ("index",)).labels(n)
        self._g_ratio = r.gauge(
            "raft_tpu_mutable_tombstone_live_ratio",
            "Masked base rows (deleted or superseded) / base rows — the "
            "compaction-pressure signal.", ("index",)).labels(n)
        self._g_delta = r.gauge(
            "raft_tpu_mutable_delta_rows",
            "Live rows in the delta segment.", ("index",)).labels(n)

    def _set_gauges(self) -> None:
        with self._lock:
            m = self._mirror
            ratio = m.tombstone_live_ratio()
            delta = float(m.delta_live())
        self._g_ratio.set(ratio)
        self._g_delta.set(delta)

    # ------------------------------------------------------------- restore
    def _fresh_mirror(self, dim: int, base) -> _Mirror:
        m = _Mirror(dim)
        if base is not None:
            ids = _index_ids(base)
            m.base_ids = frozenset(int(i) for i in ids)
            m.next_id = (int(ids.max()) + 1) if len(ids) else 0
            m.rebuild_words()
        return m

    def _restore_checkpoint(self, path):
        with ser.reader_for(path) as stream:
            r = ser.IndexReader(stream, CKPT_KIND, CKPT_VERSION,
                                name=str(path))
            # the directory knows best: adopt the persisted family
            self.family = r.string()
            self._ckpt_metric = r.string()
            dim = int(r.scalar())
            applied = int(r.scalar())
            next_id = int(r.scalar())
            has_base = int(r.scalar())
            d_ids = r.array()
            d_lsns = r.array()
            d_rows = r.array()
            tombs = r.array()
            base = None
            if has_base:
                base = _family_mod(self.family).deserialize(
                    io.BytesIO(r.blob()), res=self.res)
            r.finish()
        m = self._fresh_mirror(dim, base)
        m.applied_lsn = applied
        for i in range(len(d_ids)):
            m.put(int(d_ids[i]), d_rows[i], int(d_lsns[i]))
        for t in tombs:
            m.drop(int(t))
        m.next_id = max(m.next_id, next_id)
        m.version += 1
        return base, m

    def _recover_wal(self, wal_path: str) -> int:
        """Classify + repair the WAL and replay its tail onto the
        restored state. Torn tails truncate (typed, recorded — never a
        crash); mid-file corruption raises typed."""
        if not os.path.exists(wal_path):
            return 0
        scan = read_wal(wal_path)
        if scan.status == "corrupt":
            raise scan.error
        if scan.status == "torn_tail":
            with open(wal_path, "r+b") as f:
                f.truncate(scan.good_end)
                f.flush()
                os.fsync(f.fileno())
        replayed = 0
        with self._lock:
            for rec in scan.records:
                if rec.lsn <= self._mirror.applied_lsn:
                    continue
                self._apply_locked(rec.op, rec.ids, rec.vectors, rec.lsn)
                replayed += 1
        status = scan.status if scan.status in ("ok", "torn_tail") else "ok"
        self._m_replays[status].inc()
        self.recovery = {
            "status": scan.status, "replayed": replayed,
            "error": scan.error,
            "applied_lsn": self._mirror.applied_lsn,
        }
        obs_spans.safe_emit(self.span_sink, {
            "kind": "wal_replay", "index": self.name,
            "status": scan.status, "replayed": replayed,
            "applied_lsn": self._mirror.applied_lsn,
        })
        return replayed

    # -------------------------------------------------------------- writes
    def _check_vectors(self, vectors) -> np.ndarray:
        v = np.asarray(vectors, np.float32)
        if v.ndim == 1:
            v = v[None, :]
        if v.ndim != 2 or v.shape[1] != self.dim:
            raise ValueError(f"vectors must be [n, {self.dim}], got "
                             f"{v.shape}")
        return v

    def _apply_locked(self, op: int, ids: np.ndarray, vectors: np.ndarray,
                      lsn: int) -> None:
        m = self._mirror
        if op == OP_DELETE:
            for id_ in ids:
                m.drop(int(id_))
        else:
            for i, id_ in enumerate(ids):
                m.put(int(id_), vectors[i], lsn)
        m.applied_lsn = max(m.applied_lsn, lsn)
        m.version += 1

    def _write(self, op: int, ids, vectors: np.ndarray,
               timeout_s: Optional[float]) -> Tuple[int, np.ndarray]:
        """Commit one write: id resolution, WAL append, and in-memory
        apply run in ONE critical section. ``ids`` may be a callable
        receiving the mirror (under the lock) and returning the id
        array — how :meth:`add` assigns fresh ids and validates explicit
        ones without a release/reacquire window in which a concurrent
        add could observe the same ``next_id``."""
        budget = self.ack_timeout_s if timeout_s is None else timeout_s
        with self._lock:
            resolved = ids(self._mirror) if callable(ids) else ids
            lsn, nbytes = self._wal.append_locked(op, resolved, vectors)
            self._apply_locked(op, resolved, vectors, lsn)
        self._m_writes[_OP_NAMES[op]].inc()
        self._m_wal_bytes.inc(nbytes)
        self._set_gauges()
        self._wal.wait_durable(lsn, budget)
        self._m_acks.inc()
        return lsn, resolved

    def add(self, vectors, ids=None, timeout_s: Optional[float] = None
            ) -> np.ndarray:
        """Append new rows; auto-assigns ids when not given. Explicit
        ids must not collide with live rows (use :meth:`upsert` to
        replace). Returns the int32 id array once fsync-durable."""
        v = self._check_vectors(vectors)
        explicit = None
        if ids is not None:
            explicit = np.asarray(ids, np.int32).reshape(-1)
            if len(explicit) != len(v):
                raise ValueError(f"{len(explicit)} ids for {len(v)} vectors")

        def assign(m: _Mirror) -> np.ndarray:
            if explicit is None:
                return np.arange(m.next_id, m.next_id + len(v),
                                 dtype=np.int32)
            live = m.live_ids()
            clash = [int(i) for i in explicit if int(i) in live]
            if clash:
                raise ValueError(
                    f"add() of live ids {clash[:8]} — use upsert() "
                    f"to replace")
            return explicit

        _, out = self._write(OP_ADD, assign, v, timeout_s)
        return out

    def upsert(self, vectors, ids, timeout_s: Optional[float] = None) -> int:
        """Insert-or-replace rows by id; the old copy (base or delta)
        can never surface again. Returns the commit lsn."""
        v = self._check_vectors(vectors)
        out = np.asarray(ids, np.int32).reshape(-1)
        if len(out) != len(v):
            raise ValueError(f"{len(out)} ids for {len(v)} vectors")
        lsn, _ = self._write(OP_UPSERT, out, v, timeout_s)
        return lsn

    def delete(self, ids, timeout_s: Optional[float] = None) -> int:
        """Tombstone rows by id (unknown ids are a durable no-op so
        replay stays idempotent). Returns the commit lsn."""
        out = np.asarray(ids, np.int32).reshape(-1)
        lsn, _ = self._write(OP_DELETE, out,
                             np.zeros((0, self.dim), np.float32), timeout_s)
        return lsn

    # -------------------------------------------------------------- search
    def _snapshot(self) -> _Cache:
        with self._lock:
            m = self._mirror
            cache = self._cache
            if cache is not None and cache.version == m.version:
                return cache
            version = m.version
            base = self.base
            rows = m.rows.copy()
            ids = m.ids.copy()
            words = m.words.copy()
            masked = m.masked_base()
            n_base = len(m.base_ids)
        built = _Cache(version, base, jnp.asarray(rows), jnp.asarray(ids),
                       jnp.asarray(words), len(ids), masked, n_base)
        with self._lock:
            if self._mirror.version == version:
                self._cache = built  # guarded_by: _lock
        return built

    def search(self, queries, k: int, params=None, res=None,
               ) -> Tuple[jax.Array, jax.Array]:
        """Top-k over base + delta with the tombstone standing filter.

        Base candidates are over-fetched by a power-of-two slack sized
        to the masked-row count (bounded recompiles), folded through
        :func:`select_k_filtered` (deleted/superseded ids can never
        surface — the counted ``filtered_rows`` metric), then merged
        with the brute-force delta scan in ONE ``select_k`` with
        base-first candidate order, so ties break identically on every
        call and across a crash/replay cycle (bit-stable)."""
        c = self._snapshot()
        q = jnp.asarray(np.asarray(queries, np.float32))
        if q.ndim == 1:
            q = q[None, :]
        minimize = is_min_close(self.metric)
        sentinel = jnp.inf if minimize else -jnp.inf
        parts_v: List[jax.Array] = []
        parts_i: List[jax.Array] = []
        if c.base is not None and c.base_rows > 0:
            k_base = min(int(k), c.base_rows)
            slack = 0
            if c.masked_base:
                slack = min(1 << (c.masked_base - 1).bit_length(), 1024)
            k_fetch = min(k_base + slack, c.base_rows)
            p = params if params is not None else self.search_params
            bv, bi = _family_mod(self.family).search(
                c.base, q, k_fetch, p, res=res if res is not None
                else self.res)
            bv, bi, n_filt = select_k_filtered(
                bv, k_base, bi, c.words, select_min=minimize,
                pad_rules=False)
            self._m_filtered.inc(int(n_filt))
            parts_v.append(bv)
            parts_i.append(bi)
        if c.cap:
            dv = _delta_distances(q, c.rows, self.metric)
            dv = jnp.where((c.ids >= 0)[None, :], dv, sentinel)
            parts_v.append(dv)
            parts_i.append(jnp.broadcast_to(c.ids[None, :],
                                            (q.shape[0], c.cap)))
        if not parts_v:
            return (jnp.full((q.shape[0], k), sentinel, jnp.float32),
                    jnp.full((q.shape[0], k), -1, jnp.int32))
        all_v = jnp.concatenate(parts_v, axis=1)
        all_i = jnp.concatenate(parts_i, axis=1)
        k_sel = min(int(k), all_v.shape[1])
        v, i = select_k(all_v, k_sel, minimize, indices=all_i,
                        pad_rules=False)
        if k_sel < k:
            pad = int(k) - k_sel
            v = jnp.concatenate(
                [v, jnp.full((q.shape[0], pad), sentinel, v.dtype)], axis=1)
            i = jnp.concatenate(
                [i, jnp.full((q.shape[0], pad), -1, i.dtype)], axis=1)
        return v, i

    # ----------------------------------------------------------- lifecycle
    @property
    def size(self) -> int:
        with self._lock:
            return len(self._mirror.live_ids())

    @property
    def applied_lsn(self) -> int:
        with self._lock:
            return self._mirror.applied_lsn

    @property
    def wal_path(self) -> str:
        return os.path.join(self.directory, WAL_FILE)

    @property
    def checkpoint_path(self) -> str:
        return os.path.join(self.directory, CKPT_FILE)

    def default_search_params(self):
        """The handle's effective SearchParams: the constructor-supplied
        ones, or the wrapped family's defaults — what serving handles
        (``mutable_ivf_searcher``) apply per-call overrides onto."""
        if self.search_params is not None:
            return self.search_params
        return _family_mod(self.family).SearchParams()

    def stats(self) -> dict:
        with self._lock:
            m = self._mirror
            return {
                "base_rows": len(m.base_ids),
                "delta_rows": m.delta_live(),
                "masked_base": m.masked_base(),
                "tombstone_live_ratio": m.tombstone_live_ratio(),
                "applied_lsn": m.applied_lsn,
                "live_rows": len(m.live_ids()),
            }

    def checkpoint(self) -> str:
        """Persist the full state atomically (``writer_for`` tmp+rename)
        and trim the WAL to the records the checkpoint does not cover.
        Crash-safe at every instant: the replace is atomic and replay
        is lsn-filtered, so an old checkpoint + untrimmed WAL and a new
        checkpoint + trimmed WAL both recover to this state."""
        with self._lock:
            m = self._mirror
            base = self.base
            valid = m.ids[: m.count] >= 0
            d_ids = m.ids[: m.count][valid].copy()
            d_lsns = m.lsns[: m.count][valid].copy()
            d_rows = m.rows[: m.count][valid].copy()
            tombs = np.fromiter(sorted(m.tombs), np.int32, len(m.tombs))
            applied = m.applied_lsn
            next_id = m.next_id
        base_blob = b""
        if base is not None:
            buf = io.BytesIO()
            _family_mod(self.family).serialize(base, buf)
            base_blob = buf.getvalue()
        path = self.checkpoint_path
        with ser.writer_for(path) as stream:
            w = ser.IndexWriter(stream, CKPT_KIND, CKPT_VERSION)
            w.string(self.family)
            w.string(self.metric.name)
            w.scalar(self.dim, "<i4")
            w.scalar(applied, "<i8")
            w.scalar(next_id, "<i8")
            w.scalar(1 if base is not None else 0, "<i4")
            w.array(d_ids)
            w.array(d_lsns)
            w.array(d_rows)
            w.array(tombs)
            if base is not None:
                w.blob(base_blob)
            w.finish()
        with self._lock:
            self._wal.trim_locked(applied)
        return path

    def sync(self) -> int:
        """Force the WAL durable NOW (bypassing the group-commit window)
        and return the durable lsn — what fault injectors call before
        damaging bytes, so the frame under attack is really on disk."""
        return self._wal.sync()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._wal.close()

    # ---------------------------------------------------------- compaction
    def _compaction_snapshot(self) -> _CompactionSnapshot:
        """Gather the compactor's build input under the lock (row
        extraction from flat storage happens after release)."""
        with self._lock:
            m = self._mirror
            snap_lsn = m.applied_lsn
            keep_base = m.base_ids - m.tombs - set(m.slot_of)
            base_resident = m.base_ids  # frozenset: immutable snapshot
            valid = m.ids[: m.count] >= 0
            d_ids = m.ids[: m.count][valid].copy()
            d_rows = m.rows[: m.count][valid].copy()
            base = self.base
        full_rebuild = self.family == "ivf_flat" or base is None
        keep_delta: frozenset = frozenset()
        if not full_rebuild and len(d_ids):
            # extend path: a delta id already resident in the base (an
            # upsert of a base row) would become a second physical row
            # for the same id — keep it in the delta instead.
            keep_delta = frozenset(int(i) for i in d_ids
                                   if int(i) in base_resident)
            if keep_delta:
                sel = np.fromiter((int(i) not in keep_delta for i in d_ids),
                                  bool, len(d_ids))
                d_ids, d_rows = d_ids[sel], d_rows[sel]
        base_rows = np.zeros((0, self.dim), np.float32)
        base_ids = np.zeros((0,), np.int32)
        if full_rebuild and keep_base and base is not None:
            rows, ids = _index_rows(base)
            sel = np.fromiter((int(i) in keep_base for i in ids), bool,
                              len(ids))
            base_rows, base_ids = rows[sel], ids[sel]
        vectors = np.concatenate([base_rows, d_rows], axis=0)
        ids = np.concatenate([base_ids, d_ids], axis=0).astype(np.int32)
        return _CompactionSnapshot(vectors, ids, snap_lsn, base,
                                   full_rebuild, len(base_ids), len(d_ids),
                                   keep_delta)

    def _install_base(self, new_base, snap: _CompactionSnapshot) -> None:
        """Swap in the compacted base and drop the delta slots it
        absorbed (lsn <= snapshot lsn, minus ``keep_delta_ids`` — rows
        the extend path excluded, which must stay in the delta so the
        stale base copy they supersede stays masked). Post-snapshot
        writes — delta slots, tombstones, next_id — carry over
        untouched; the base-ok bitset is rebuilt from the new id set."""
        with self._lock:
            m = self._mirror
            m.base_ids = frozenset(int(i) for i in _index_ids(new_base)) \
                if new_base is not None else frozenset()
            survivors = [(int(m.ids[s]), m.rows[s].copy(), int(m.lsns[s]))
                         for s in range(m.count)
                         if m.ids[s] >= 0
                         and (m.lsns[s] > snap.lsn
                              or int(m.ids[s]) in snap.keep_delta_ids)]
            m.rows = np.zeros((0, self.dim), np.float32)
            m.ids = np.zeros((0,), np.int32)
            m.lsns = np.zeros((0,), np.int64)
            m.count = 0
            m.slot_of = {}
            m.tombs = {t for t in m.tombs if t in m.base_ids}
            for id_, row, lsn in survivors:
                m.put(id_, row, lsn)
            m.rebuild_words()
            m.version += 1
            self.base = new_base  # guarded_by: _lock
            self._cache = None  # guarded_by: _lock
        self._set_gauges()


def _family_mod(family: str):
    if family == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat
        return ivf_flat
    from raft_tpu.neighbors import ivf_pq
    return ivf_pq


def _index_ids(index) -> np.ndarray:
    """Every live row id of a family index (list + overflow storage)."""
    ids = np.asarray(index.list_indices).reshape(-1)
    out = [ids[ids >= 0]]
    over = np.asarray(index.overflow_indices).reshape(-1)
    if len(over):
        out.append(over[over >= 0])
    return np.concatenate(out).astype(np.int32)


def _index_rows(index) -> Tuple[np.ndarray, np.ndarray]:
    """(rows [n, dim], ids [n]) of every live row of an ivf_flat index
    — the compaction gather. (ivf_pq stores codes, not rows; the
    compactor keeps the original vectors in its snapshot instead.)"""
    data = np.asarray(index.list_data, np.float32)
    ids = np.asarray(index.list_indices).reshape(-1)
    rows = data.reshape(-1, data.shape[-1])
    keep = ids >= 0
    rows, ids = rows[keep], ids[keep]
    over_ids = np.asarray(index.overflow_indices).reshape(-1)
    if len(over_ids):
        over_rows = np.asarray(index.overflow_data,
                               np.float32).reshape(-1, data.shape[-1])
        ok = over_ids >= 0
        rows = np.concatenate([rows, over_rows[ok]], axis=0)
        ids = np.concatenate([ids, over_ids[ok]], axis=0)
    return rows, ids.astype(np.int32)


def _delta_distances(q: jax.Array, rows: jax.Array,
                     metric: DistanceType) -> jax.Array:
    """Brute-force [n_q, cap] distances in the family's canonical space
    (mirrors ops.distance.gathered_distances: raw dots for
    InnerProduct, 1−cos for Cosine, clamped squared L2 otherwise)."""
    qf = q.astype(jnp.float32)
    rf = rows.astype(jnp.float32)
    dots = jnp.matmul(qf, rf.T, precision=jax.lax.Precision.HIGHEST)
    if metric == DistanceType.InnerProduct:
        return dots
    if metric == DistanceType.CosineExpanded:
        rn = jnp.sqrt(jnp.maximum(jnp.sum(rf * rf, -1), 1e-20))
        qn = jnp.sqrt(jnp.maximum(jnp.sum(qf * qf, -1), 1e-20))
        return 1.0 - dots / (rn[None, :] * qn[:, None])
    rn2 = jnp.sum(rf * rf, -1)
    qn2 = jnp.sum(qf * qf, -1)
    d = jnp.maximum(qn2[:, None] + rn2[None, :] - 2.0 * dots, 0.0)
    if metric == DistanceType.L2SqrtExpanded:
        d = jnp.sqrt(d)
    return d


# ================================================================ Compactor


class Compactor:
    """Background re-cluster + hot-swap publisher for one writer.

    Wakes on a poll cadence and runs when a closed-vocabulary trigger
    fires: ``delta_threshold`` live delta rows, ``tombstone_ratio``
    masked base fraction, ``interval`` seconds since the last run, or
    an explicit :meth:`request` (``manual``). Each run emits exactly
    one ``kind="compaction"`` span and one
    ``raft_tpu_mutable_compactions_total{reason,outcome}`` increment —
    the 1:1 reconciliation the observability tests pin.

    ``publish`` is the hot-swap target: an ``Engine`` (swap_index), a
    ``Fleet`` (rolling_swap), or None (install only — bare writers).
    A run exceeding ``stall_timeout_s`` fires the stall timer: stall
    counter + ``kind="compaction_stall"`` span + the publish target's
    ``dump_diagnostics(reason="compaction_stall")`` flight-recorder
    bundle. The run itself keeps going — a stall is a detection event,
    not an abort."""

    def __init__(self, writer: MutableIvf, *, publish=None,
                 delta_threshold: int = 4096,
                 tombstone_ratio: float = 0.25,
                 interval_s: Optional[float] = None,
                 stall_timeout_s: float = 30.0,
                 poll_s: float = 0.05,
                 min_rows: int = 2,
                 clock=time.monotonic):
        self.writer = writer
        self.publish = publish
        self.delta_threshold = int(delta_threshold)
        self.tombstone_ratio = float(tombstone_ratio)
        self.interval_s = interval_s
        self.stall_timeout_s = float(stall_timeout_s)
        self.poll_s = float(poll_s)
        self.min_rows = int(min_rows)
        self.clock = clock
        self._wake = threading.Condition()
        self._pending: Optional[str] = None  # guarded_by: _wake
        self._running = False  # guarded_by: _wake
        self._runs = 0  # guarded_by: _wake
        self._thread: Optional[threading.Thread] = None  # guarded_by: atomic
        self._stall_timer: Optional[
            threading.Timer] = None  # guarded_by: _wake
        self._last_run_t = clock()  # guarded_by: atomic (loop-only rebind)
        self.last_error: Optional[BaseException] = None  # guarded_by: atomic
        #: fault hook (testing.faults.crash_compactor): abort the run
        #: between artifact write and publish
        self._crash_after_checkpoint = False  # guarded_by: atomic
        writer.compactor = self

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "Compactor":
        with self._wake:
            if self._running:
                return self
            self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name=f"compactor:{self.writer.name}",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 10.0) -> None:
        with self._wake:
            self._running = False
            if self._stall_timer is not None:
                self._stall_timer.cancel()
                self._stall_timer = None
            self._wake.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)

    def request(self, reason: str = "manual") -> None:
        """Queue a run for ``reason`` (closed vocabulary)."""
        if reason not in COMPACTION_REASONS:
            raise ValueError(f"unknown compaction reason {reason!r}; "
                             f"expected one of {sorted(COMPACTION_REASONS)}")
        with self._wake:
            self._pending = reason
            self._wake.notify_all()

    @property
    def runs(self) -> int:
        with self._wake:
            return self._runs

    # ---------------------------------------------------------------- loop
    def _loop(self) -> None:
        while True:
            with self._wake:
                while self._running and self._pending is None:
                    if not self._wake.wait(timeout=self.poll_s):
                        break  # poll tick: evaluate auto triggers below
                if not self._running:
                    return
                reason = self._pending
                self._pending = None
            if reason is None:
                reason = self._auto_reason()
            if reason is not None:
                self.run_once(reason)

    def _auto_reason(self) -> Optional[str]:
        stats = self.writer.stats()
        if stats["delta_rows"] >= self.delta_threshold:
            return "delta_threshold"
        if stats["base_rows"] and \
                stats["tombstone_live_ratio"] >= self.tombstone_ratio:
            return "tombstone_ratio"
        if self.interval_s is not None and \
                self.clock() - self._last_run_t >= self.interval_s:
            return "interval"
        return None

    # ----------------------------------------------------------------- run
    def run_once(self, reason: str = "manual") -> str:
        """One full compaction: snapshot → build → install → checkpoint
        → publish. Returns the outcome (closed vocabulary). Never
        raises: failures are typed, counted, and recorded on
        ``last_error``."""
        if reason not in COMPACTION_REASONS:
            raise ValueError(f"unknown compaction reason {reason!r}; "
                             f"expected one of {sorted(COMPACTION_REASONS)}")
        writer = self.writer
        t0 = self.clock()
        timer = threading.Timer(self.stall_timeout_s, self._on_stall,
                                args=(reason,))
        timer.daemon = True
        with self._wake:
            self._stall_timer = timer
        timer.start()
        outcome = "failed"
        detail = ""
        gen = None
        try:
            snap = writer._compaction_snapshot()
            if len(snap.ids) < self.min_rows:
                outcome = "skipped"
                detail = f"{len(snap.ids)} live rows < min_rows"
                return outcome
            new_base = self._build(snap)
            writer._install_base(new_base, snap)
            writer.checkpoint()
            if self._crash_after_checkpoint:
                raise CompactorCrashed(
                    f"{writer.name}: injected crash between artifact "
                    f"write and publish")
            gen = self._publish()
            outcome = "ok"
            detail = (f"{snap.n_base} base + {snap.n_delta} delta rows "
                      f"-> {len(snap.ids)} live")
            return outcome
        except CompactorCrashed as e:
            self.last_error = e
            detail = str(e)
            return outcome
        except (RaftError, ValueError, OSError) as e:
            self.last_error = e
            detail = f"{type(e).__name__}: {e}"
            return outcome
        finally:
            with self._wake:
                if self._stall_timer is timer:
                    self._stall_timer = None
                self._runs += 1
            timer.cancel()
            self._last_run_t = self.clock()
            dur = self.clock() - t0
            writer._m_compactions.labels(writer.name, reason, outcome).inc()
            span = {
                "kind": "compaction", "index": writer.name,
                "trace": obs_spans.new_trace_id(), "reason": reason,
                "outcome": outcome, "duration_s": round(dur, 6),
                "detail": detail,
            }
            if gen is not None:
                # searcher-generation breadcrumb: which serving
                # generation(s) now run on the compacted artifact
                span["searcher_gen"] = gen
            obs_spans.safe_emit(writer.span_sink, span)

    def _build(self, snap: _CompactionSnapshot):
        """Produce the compacted base. Full rebuild (ivf_flat, or no
        prior base): re-cluster every live row into a fresh index with
        the original ids (build with add_data_on_build=False, then
        extend — the id-preserving path). ivf_pq with a base: the base
        stores codes, not rows, so the base-fresh delta rows are
        re-encoded into the existing base via extend (ids already
        resident in the base were excluded at snapshot time and stay in
        the delta — extend does not dedupe ids); tombstoned base rows
        stay physically present but permanently filtered by the
        standing bitset."""
        import dataclasses as _dc

        mod = _family_mod(self.writer.family)
        if not snap.full_rebuild:
            if len(snap.ids) == 0:
                return snap.base  # every delta row superseded a base id
            return mod.extend(snap.base, snap.vectors,
                              new_indices=snap.ids, res=self.writer.res)
        params = self.writer.index_params
        if params is None:
            params = mod.IndexParams()
        n_lists = max(1, min(int(params.n_lists), len(snap.ids)))
        # pin the writer's metric: a reopened writer has no index_params,
        # and a default-metric rebuild would silently change the space
        params = _dc.replace(params, n_lists=n_lists,
                             metric=self.writer.metric,
                             add_data_on_build=False)
        base = mod.build(snap.vectors, params, res=self.writer.res)
        return mod.extend(base, snap.vectors, new_indices=snap.ids,
                          res=self.writer.res)

    def _publish(self):
        """Push a fresh searcher through the existing hot-swap surface
        (Engine.swap_index / Fleet.rolling_swap) so serving bumps its
        searcher generation onto the compacted artifact. Returns the
        post-swap generation breadcrumb (int for an engine, list per
        replica for a fleet, None for bare writers)."""
        target = self.publish
        if target is None:
            return None
        from raft_tpu.serving import searchers as serving_searchers

        def handle():
            return serving_searchers.make_searcher(
                "mutable_ivf", self.writer,
                params=self.writer.search_params, res=self.writer.res)

        if hasattr(target, "rolling_swap"):
            target.rolling_swap([handle() for _ in target.replicas])
            return [int(r.engine.searcher_generation)
                    for r in target.replicas
                    if hasattr(getattr(r, "engine", None),
                               "searcher_generation")]
        target.swap_index(handle())
        return int(target.searcher_generation)

    def _on_stall(self, reason: str) -> None:
        """Stall-timer callback: count, span, and trip the publish
        target's flight recorder. Runs on the timer thread with no
        locks held."""
        writer = self.writer
        writer._m_stalls.inc()
        obs_spans.safe_emit(writer.span_sink, {
            "kind": "compaction_stall", "index": writer.name,
            "reason": reason, "stall_timeout_s": self.stall_timeout_s,
        })
        target = self.publish
        engines = []
        if target is not None and hasattr(target, "dump_diagnostics"):
            engines = [target]
        elif target is not None and hasattr(target, "replicas"):
            engines = [r.engine for r in target.replicas
                       if hasattr(getattr(r, "engine", None),
                                  "dump_diagnostics")]
        for eng in engines:
            try:
                eng.dump_diagnostics(reason="compaction_stall")
            except (RaftError, OSError, ValueError) as e:
                self.last_error = e


# ============================================================== verification


def verify_dir(directory) -> dict:
    """Classify a MutableIvf directory for pre-flight verification
    (``tools/verify_checkpoint.py``): checkpoint status, WAL status
    (ok / torn_tail / corrupt / missing), and the lsn replay range a
    recovery would apply onto the checkpoint."""
    directory = str(directory)
    ckpt_path = os.path.join(directory, CKPT_FILE)
    wal_path = os.path.join(directory, WAL_FILE)
    ckpt: dict = {"path": ckpt_path, "status": "ok", "applied_lsn": None}
    if not os.path.exists(ckpt_path):
        ckpt["status"] = "missing"
    else:
        try:
            with ser.reader_for(ckpt_path) as stream:
                r = ser.IndexReader(stream, CKPT_KIND, CKPT_VERSION,
                                    name=ckpt_path)
                r.string()  # family
                r.string()  # metric
                r.scalar()  # dim
                ckpt["applied_lsn"] = int(r.scalar())
                r.scalar()  # next_id
                has_base = int(r.scalar())
                for _ in range(4):  # delta ids/lsns/rows + tombstones
                    r.array()
                if has_base:
                    r.blob()
                r.finish()
        except IntegrityError as e:
            ckpt["status"] = e.reason or "corrupt"
            ckpt["error"] = str(e)
        except ValueError as e:
            ckpt["status"] = "corrupt"
            ckpt["error"] = str(e)
    scan = read_wal(wal_path)
    wal = verify_wal(wal_path)
    applied = ckpt.get("applied_lsn")
    replay = [r for r in scan.records
              if applied is None or r.lsn > applied] \
        if wal["status"] in ("ok", "torn_tail") else []
    replay_range = None
    if replay:
        replay_range = {"first_lsn": replay[0].lsn,
                        "last_lsn": replay[-1].lsn,
                        "records": len(replay)}
    # A missing checkpoint is healthy when the WAL stands alone (a writer
    # that never compacted replays from empty); BOTH missing means the
    # directory is not a mutable-index home at all.
    ckpt_ok = ckpt["status"] == "ok" or (
        ckpt["status"] == "missing" and wal["status"] != "missing")
    if ckpt_ok and wal["status"] in ("ok", "missing"):
        status = "ok"
    elif ckpt_ok and wal["status"] == "torn_tail":
        status = "torn_tail"
    elif ckpt["status"] == "missing" and wal["status"] == "missing":
        status = "missing"
    else:
        status = "corrupt"
    return {
        "directory": directory,
        "status": status,
        "checkpoint": ckpt,
        "wal": wal,
        "replay": replay_range,
    }
