"""HNSW interop — export CAGRA graphs as hnswlib-loadable indexes.

Reference: ``raft::neighbors::hnsw`` (neighbors/hnsw.hpp, detail/
hnsw_types.hpp:60-86 — serializes a CAGRA graph as a base-layer-only
hnswlib index for CPU search; search delegates to hnswlib).

TPU-native design: the file writer is the native C++ component
(raft_tpu.native.hnswlib_write — byte-compatible with hnswlib saveIndex so
hnswlib users can load it directly). When hnswlib isn't installed (this
image), ``load``+``search`` parse the file back and run the same greedy
graph search the CAGRA searcher uses — the graph and data round-trip is
verified either way."""

from __future__ import annotations

import struct
from typing import Tuple

import numpy as np

from raft_tpu import native
from raft_tpu.core import tracing
from raft_tpu.ops.distance import DistanceType


def from_cagra(cagra_index, path: str, compat: str = "hnswlib") -> None:
    """Serialize a CAGRA index as a base-layer-only hnswlib file
    (reference: hnsw::from_cagra / serialize_to_hnswlib).

    ``compat="hnswlib"`` (default) is loadable AND searchable by stock
    hnswlib; ``compat="raft"`` reproduces the reference serializer
    byte-for-byte (its output needs the base_layer_only fork loader,
    hnsw_types.hpp:60-86 — stock hnswlib crashes searching it)."""
    space = ("ip" if cagra_index.metric == DistanceType.InnerProduct
             else "l2")
    native.hnswlib_write(path, np.asarray(cagra_index.dataset),
                         np.asarray(cagra_index.graph), space=space,
                         compat=compat)


class Index:
    """A loaded base-layer hnsw graph (dataset + links)."""

    def __init__(self, dataset: np.ndarray, graph: np.ndarray):
        self.dataset = dataset
        self.graph = graph  # [n, maxM0] int32, -1 padded


def load(path: str) -> Index:
    """Parse an hnswlib index file written by :func:`from_cagra` (layout:
    hnswlib saveIndex — header, level-0 element blocks, link-list sizes)."""
    with open(path, "rb") as f:
        hdr = f.read(8 * 6 + 4 + 4 + 8 * 3 + 8 + 8)
        (offset_level0, max_elements, cur_count, size_per_elem,
         label_offset, offset_data, max_level, enterpoint, maxM, maxM0,
         m_, mult, ef_c) = struct.unpack("<QQQQQQiIQQQdQ", hdr)
        dim = (label_offset - offset_data) // 4
        n = cur_count
        data = np.empty((n, dim), np.float32)
        graph = np.full((n, maxM0), -1, np.int32)
        for i in range(n):
            blk = f.read(size_per_elem)
            (cnt,) = struct.unpack_from("<I", blk, 0)
            links = np.frombuffer(blk, np.uint32, cnt, 4)
            graph[i, :cnt] = links.astype(np.int32)
            data[i] = np.frombuffer(blk, np.float32, dim, offset_data)
    return Index(data, graph)


@tracing.range("hnsw.search")
def search(
    index: Index,
    queries,
    k: int,
    ef: int = 64,
    space: str = "l2",
    engine: str = "xla",
) -> Tuple[np.ndarray, np.ndarray]:
    """Search the loaded base-layer graph.

    ``engine="xla"`` (default) reuses the CAGRA greedy searcher over the
    same graph — identical algorithm family (hnswlib's base-layer search
    IS greedy beam search with ef as itopk), batched on the accelerator.
    ``engine="cpu"`` runs the native C++ layer-0 ef-search
    (``native.graph_greedy_search`` — hnswlib's searchBaseLayerST
    algorithm exactly, entry point 0 like the exported files; l2 only) —
    what delegating to hnswlib itself would execute, latency-oriented.

    ``space`` must match the space the index was exported with ('l2'|'ip') —
    the hnswlib file format does not record it (hnswlib keeps the space at
    wrapper level), same contract as hnswlib's own load."""
    if space not in ("l2", "ip"):
        raise ValueError(f"unknown space {space!r}; use 'l2' or 'ip'")
    if engine == "cpu":
        if space != "l2":
            raise ValueError("engine='cpu' supports space='l2' only")
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None]
        if q.shape[1] != index.dataset.shape[1]:
            raise ValueError(f"query dim {q.shape[1]} != index dim "
                             f"{index.dataset.shape[1]}")
        d, i = native.graph_greedy_search(
            np.asarray(index.dataset), np.asarray(index.graph), q, k,
            ef=ef)
        return d, i
    if engine != "xla":
        raise ValueError(f"unknown engine {engine!r}; use 'xla' or 'cpu'")
    from raft_tpu.neighbors import cagra

    metric = {"l2": DistanceType.L2Expanded,
              "ip": DistanceType.InnerProduct}[space]
    params = cagra.IndexParams(
        graph_degree=index.graph.shape[1],
        metric=metric)
    cg = cagra.Index(params, np.asarray(index.dataset),
                     np.asarray(index.graph))
    d, i = cagra.search(cg, queries, k,
                        cagra.SearchParams(itopk_size=max(ef, k)))
    return np.asarray(d), np.asarray(i)
