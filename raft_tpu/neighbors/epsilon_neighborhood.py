"""Epsilon neighborhood — all pairs within a radius.

Reference: ``raft::neighbors::epsilon_neighborhood`` (neighbors/
epsilon_neighborhood.cuh epsUnexpL2SqNeighborhood — dense boolean adjacency
+ per-row vertex degrees for L2).

TPU-native design: one tiled pairwise-distance pass (ops.distance) with a
fused threshold — XLA fuses the compare into the distance epilogue; the
adjacency never materializes distances in HBM beyond the tile."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import pairwise_distance


@tracing.range("epsilon_neighborhood.eps_neighbors")
def eps_neighbors(
    x,
    y,
    eps: float,
    metric="sqeuclidean",
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Boolean adjacency [m, n] (x rows × y rows within ``eps``) and vertex
    degrees [m] (reference: epsUnexpL2SqNeighborhood's adj + vd outputs;
    eps is compared against the *squared* L2 distance for the default
    metric, matching the reference's UnexpL2Sq semantics)."""
    res = ensure_resources(res)
    d = pairwise_distance(x, y, metric=metric, res=res)
    adj = d <= eps
    return adj, jnp.sum(adj, axis=1).astype(jnp.int32)
