"""Neighbors layer — the ANN index suite (SURVEY.md §2.7): brute_force,
ivf_flat, ivf_pq, cagra, nn_descent, refine, filtering."""

from raft_tpu.neighbors import (
    brute_force,
    cagra,
    ivf_flat,
    ivf_pq,
    nn_descent,
    refine,
)

__all__ = ["brute_force", "cagra", "ivf_flat", "ivf_pq", "nn_descent",
           "refine"]
