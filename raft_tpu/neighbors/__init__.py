"""Neighbors layer — the ANN index suite (SURVEY.md §2.7): brute_force,
ivf_flat, ivf_pq, cagra, nn_descent, refine, filtering, plus the
crash-consistent mutable write path (mutable)."""

from raft_tpu.neighbors import (
    ball_cover,
    brute_force,
    cagra,
    epsilon_neighborhood,
    hnsw,
    ivf_flat,
    ivf_pq,
    mutable,
    nn_descent,
    ooc,
    quantize,
    rbc,
    refine,
    tiered,
)

__all__ = ["ball_cover", "brute_force", "cagra", "epsilon_neighborhood",
           "hnsw", "ivf_flat", "ivf_pq", "mutable", "nn_descent", "ooc",
           "quantize", "rbc", "refine", "tiered"]
