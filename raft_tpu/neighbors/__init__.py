"""Neighbors layer — the ANN index suite (SURVEY.md §2.7): brute_force,
ivf_flat, ivf_pq, cagra, nn_descent, refine, filtering."""

from raft_tpu.neighbors import (
    ball_cover,
    brute_force,
    cagra,
    epsilon_neighborhood,
    hnsw,
    ivf_flat,
    ivf_pq,
    nn_descent,
    ooc,
    quantize,
    rbc,
    refine,
    tiered,
)

__all__ = ["ball_cover", "brute_force", "cagra", "epsilon_neighborhood",
           "hnsw", "ivf_flat", "ivf_pq", "nn_descent", "ooc", "quantize",
           "rbc", "refine", "tiered"]
