"""Device-side IVF list placement shared by ivf_flat and ivf_pq.

Reference: the list-fill kernels (`build_index_kernel`,
detail/ivf_flat_build.cuh:123-160; `process_and_fill_codes`,
detail/ivf_pq_build.cuh:1185-1351) place each encoded row at its cluster
list's tail via atomic offsets. The TPU-native analog is a segment
scatter: a stable sort by label + searchsorted rank gives every row its
(list, slot) without atomics, and one `.at[].set` writes the batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.utils.shape import round_up_to


def choose_list_pad(sizes, max_expansion: float = 1.5,
                    align: int = 8) -> int:
    """Per-list capacity bounding padded storage (VERDICT r2 #2).

    The reference pays only group-of-32 padding on ragged lists
    (ivf_list.hpp); a dense [L, pad, ...] layout padded to the LARGEST
    list lets one hot cluster inflate every list's storage — at DEEP-100M
    nlist=50000 shapes, several-fold. This picks the largest ``align``-ed
    capacity whose total storage — ``L·pad`` slots plus the (align-ed)
    overflow block of rows spilled from longer lists — stays within
    ``max_expansion ×`` the raw row count. When the max-driven pad already
    fits the budget (the balanced common case) it is returned unchanged
    and nothing spills.

    Returns the chosen pad; overflow rows = ``sum(max(size - pad, 0))``.
    """
    sizes = np.asarray(sizes, np.int64)
    n = int(sizes.sum())
    n_lists = len(sizes)
    max_pad = max(round_up_to(int(sizes.max() if n_lists else 1), align),
                  align)
    budget = max_expansion * max(n, 1)
    if n_lists * max_pad <= budget:
        return max_pad
    # prefix sums over descending sizes → vectorized overflow(cap)
    s_desc = np.sort(sizes)[::-1]
    csum = np.concatenate([[0], np.cumsum(s_desc)])
    caps = np.arange(max_pad - align, 0, -align, dtype=np.int64)
    m = np.searchsorted(-s_desc, -caps, side="left")  # lists with size > cap
    overflow = csum[m] - caps * m
    over_pad = np.where(overflow > 0,
                        (-(-overflow // align)) * align, 0)
    storage = n_lists * caps + over_pad
    # largest cap within budget spills the fewest rows (overflow rows cost
    # every query a scan, capacity slots only cost idle storage)
    ok = np.flatnonzero(storage <= budget)
    return int(caps[ok[0]]) if len(ok) else align


def fit_mask(labels: np.ndarray, n_lists: int, cap,
             sizes=None) -> np.ndarray:
    """True for rows that fit their list's remaining capacity in batch
    order, False for rows that spill to the overflow block. ``sizes``
    gives each list's pre-batch occupancy (extend); default 0 (fresh
    pack). ``cap`` may be scalar or per-list."""
    labels = np.asarray(labels)
    order = np.argsort(labels, kind="stable")
    sl = labels[order]
    starts = np.searchsorted(sl, np.arange(n_lists))
    rank = np.arange(len(sl), dtype=np.int64) - starts[sl]
    room = np.broadcast_to(np.asarray(cap, np.int64), (n_lists,)).copy()
    if sizes is not None:
        room = np.maximum(room - np.asarray(sizes, np.int64), 0)
    keep = np.empty(len(labels), bool)
    keep[order] = rank < room[sl]
    return keep


def pad_overflow_block(rows: np.ndarray, ids: np.ndarray,
                       align: int = 8):
    """Pad spilled rows/ids up to ``align`` (ids -1-filled) so the block
    is lane-friendly; a zero-row block stays shape-[0]."""
    n = len(rows)
    if n == 0:
        return rows, np.zeros((0,), np.int32)
    pad = max(round_up_to(n, align), align)
    out = np.zeros((pad,) + rows.shape[1:], rows.dtype)
    out[:n] = rows
    out_ids = np.full((pad,), -1, np.int32)
    out_ids[:n] = ids
    return out, out_ids


def grow_pad(data, idxs, new_max: int):
    """Grow list storage to fit ``new_max`` rows per list (8-aligned, like
    the initial packers'): pads ``data`` [L, pad, ...] with zeros and
    ``idxs`` [L, pad] with the -1 null id. No-op if it already fits."""
    new_pad = max(-(-max(int(new_max), 1) // 8) * 8, 8)
    old_pad = data.shape[1]
    if new_pad <= old_pad:
        return data, idxs
    grow = new_pad - old_pad
    data = jnp.pad(data, ((0, 0), (0, grow)) + ((0, 0),) * (data.ndim - 2))
    idxs = jnp.pad(idxs, ((0, 0), (0, grow)), constant_values=-1)
    return data, idxs


def label_slots(labels, sizes, n_lists: int):
    """For each new row, (order, list, slot): slot appends after the list's
    current tail, preserving batch order within a list (stable sort →
    searchsorted rank)."""
    order = jnp.argsort(labels, stable=True)
    sl = labels[order]
    starts = jnp.searchsorted(sl, jnp.arange(n_lists, dtype=labels.dtype))
    rank = (jnp.arange(sl.shape[0], dtype=jnp.int32)
            - starts[sl].astype(jnp.int32))
    slot = sizes[sl] + rank
    return order, sl, slot


@functools.partial(jax.jit, static_argnames=("n_lists",))
def append_lists(data, idxs, sizes, new_rows, new_ids, labels,
                 n_lists: int):
    """Scatter a new batch into (already re-padded) list storage on device —
    no per-list host loop, existing lists are never unpacked (VERDICT r1
    #3). ``data`` [L, pad, ...] any dtype; ``idxs`` [L, pad] int32;
    ``sizes`` [L]. Returns the updated triple."""
    order, sl, slot = label_slots(labels, sizes, n_lists)
    data = data.at[sl, slot].set(new_rows[order], mode="drop")
    idxs = idxs.at[sl, slot].set(new_ids[order], mode="drop")
    counts = jnp.zeros((n_lists,), sizes.dtype).at[labels].add(1)
    return data, idxs, sizes + counts
