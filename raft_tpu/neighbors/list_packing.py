"""Device-side IVF list placement shared by ivf_flat and ivf_pq.

Reference: the list-fill kernels (`build_index_kernel`,
detail/ivf_flat_build.cuh:123-160; `process_and_fill_codes`,
detail/ivf_pq_build.cuh:1185-1351) place each encoded row at its cluster
list's tail via atomic offsets. The TPU-native analog is a segment
scatter: a stable sort by label + searchsorted rank gives every row its
(list, slot) without atomics, and one `.at[].set` writes the batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def grow_pad(data, idxs, new_max: int):
    """Grow list storage to fit ``new_max`` rows per list (8-aligned, like
    the initial packers'): pads ``data`` [L, pad, ...] with zeros and
    ``idxs`` [L, pad] with the -1 null id. No-op if it already fits."""
    new_pad = max(-(-max(int(new_max), 1) // 8) * 8, 8)
    old_pad = data.shape[1]
    if new_pad <= old_pad:
        return data, idxs
    grow = new_pad - old_pad
    data = jnp.pad(data, ((0, 0), (0, grow)) + ((0, 0),) * (data.ndim - 2))
    idxs = jnp.pad(idxs, ((0, 0), (0, grow)), constant_values=-1)
    return data, idxs


def label_slots(labels, sizes, n_lists: int):
    """For each new row, (order, list, slot): slot appends after the list's
    current tail, preserving batch order within a list (stable sort →
    searchsorted rank)."""
    order = jnp.argsort(labels, stable=True)
    sl = labels[order]
    starts = jnp.searchsorted(sl, jnp.arange(n_lists, dtype=labels.dtype))
    rank = (jnp.arange(sl.shape[0], dtype=jnp.int32)
            - starts[sl].astype(jnp.int32))
    slot = sizes[sl] + rank
    return order, sl, slot


@functools.partial(jax.jit, static_argnames=("n_lists",))
def append_lists(data, idxs, sizes, new_rows, new_ids, labels,
                 n_lists: int):
    """Scatter a new batch into (already re-padded) list storage on device —
    no per-list host loop, existing lists are never unpacked (VERDICT r1
    #3). ``data`` [L, pad, ...] any dtype; ``idxs`` [L, pad] int32;
    ``sizes`` [L]. Returns the updated triple."""
    order, sl, slot = label_slots(labels, sizes, n_lists)
    data = data.at[sl, slot].set(new_rows[order], mode="drop")
    idxs = idxs.at[sl, slot].set(new_ids[order], mode="drop")
    counts = jnp.zeros((n_lists,), sizes.dtype).at[labels].add(1)
    return data, idxs, sizes + counts
