"""Tiered IVF-PQ serving: host-resident lists behind a device LRU arena.

Everything else in the repo assumes the index fits HBM. This module is
the serve-time tier that breaks that assumption (ROADMAP item 6): the
PQ code/id lists live in host RAM (:class:`HostTier`, plain numpy —
loadable straight from the streamed-build files via
``native.iter_bin_batches_prefetch``), while the coarse quantizer,
rotation, codebooks and the tiny overflow block stay HBM-resident.
Probed lists resolve through a fixed-size device slab arena
(:class:`SlabArena`) managed as an LRU keyed by ``(namespace, coarse
cluster id)`` — the SPANN memory/disk split (hot coarse structures,
paged posting lists) recast onto the host/HBM boundary.

Bit-identity with the all-HBM cache engine is a hard invariant, pinned
by test: :func:`tiered_scan_core` mirrors
``ivf_pq._search_cache_core``'s per-tile body op for op (same q_tile
padding, same ``[t, P, pad, rot]`` gather shapes, same einsum/select
calls), with only the gather *source* swapped from ``list_decoded`` to
the arena slabs — a pure copy, so every f32 reduction sees identical
shapes and operand values. The arena's decoded slabs come from the
same ``_decode_lists_jit`` decode the resident cache uses, and the
host-precomputed slab norms are produced by chunking that decode at
exactly the ``list_tile`` ``ensure_scan_cache`` would pick, so chunk
boundaries coincide with the reference's internal tiles.

Concurrency model: arena device state is updated *functionally*
(``.at[slots].set`` returns new arrays), so an in-flight scan holds an
immutable snapshot and an eviction can never tear it. The only mutable
state is the LRU map + counters, all under one lock; nothing blocks
under that lock (host reads are numpy slices; fetch dispatch is async;
``block_until_ready`` stall accounting happens after release).

A :class:`TierPrefetcher` thread peeks the serving batcher's
already-formed next batch (``Batcher.peek()``, non-consuming) and
resolves its probes through the prefetch path, so the host→device copy
overlaps the previous batch's device time. Because the arena is keyed
by namespace, one arena multiplexes N indexes per chip: cold tenants
cost only host RAM, and a fleet ``rolling_swap`` onto a tiered searcher
is a cache-promotion event — the new generation's lists warm on first
probe while the old generation's slabs age out of the same LRU.
"""

from __future__ import annotations

import functools
import itertools
import json
import logging
import os
import threading
import time
import zlib
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import native
from raft_tpu.core.resources import (Resources, ensure_resources,
                                     solve_host_tier)
from raft_tpu.neighbors import ivf_pq
from raft_tpu.neighbors.ivf_pq import CodebookGen, SearchParams
from raft_tpu.obs import explain as obs_explain
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import spans as obs_spans
from raft_tpu.ops.distance import DistanceType
from raft_tpu.ops.select_k import select_k_maybe_approx
from raft_tpu.utils.shape import (as_query_array, balanced_tile, cdiv,
                                  pad_rows, query_bucket)

__all__ = [
    "HostTier",
    "SlabArena",
    "TierPrefetcher",
    "TierReadError",
    "TierStats",
    "TieredArenaError",
    "TieredIvfPq",
    "attach_prefetcher",
    "coarse_probes_core",
    "host_tier_from_index",
    "load_manifest",
    "load_tiered",
    "save_tiered",
    "tiered_scan_core",
    "validate_manifest",
    "MANIFEST_PREFIX",
    "MANIFEST_SCHEMA",
]

logger = logging.getLogger("raft_tpu.neighbors.tiered")

MANIFEST_PREFIX = "TIERED_MANIFEST_"
MANIFEST_SCHEMA = "raft_tpu.tiered_manifest/v1"

_arena_seq = itertools.count()


class TierReadError(RuntimeError):
    """A host-tier list read failed. Always raised *before* the arena map
    mutates, and always chained (``__cause__``) to the underlying error —
    the serving engine's containment turns it into a typed
    ``BatchFailed``, never a hang."""


class TieredArenaError(RuntimeError):
    """One batch probes more distinct lists than the arena has slots —
    a sizing error (``solve_host_tier`` reports the per-batch worst
    case), not a runtime condition to retry."""


# ------------------------------------------------------------- host tier


class HostTier:
    """Host-RAM residence for one index's packed lists.

    ``norms`` are the decoded-residual squared norms the resident cache
    engine would hold in ``decoded_norms`` — precomputed once here (see
    :func:`host_tier_from_index`) so a fetch uploads them instead of
    re-reducing on device, keeping the scan's ``g_n`` operand bit-equal
    to the reference's.
    """

    def __init__(self, codes: np.ndarray, ids: np.ndarray,
                 sizes: np.ndarray, norms: np.ndarray) -> None:
        if codes.ndim != 3 or ids.shape != codes.shape[:2]:
            raise ValueError(f"codes {codes.shape} / ids {ids.shape} "
                             f"disagree")
        if norms.shape != ids.shape or sizes.shape != (codes.shape[0],):
            raise ValueError(f"norms {norms.shape} / sizes {sizes.shape} "
                             f"disagree with lists {ids.shape}")
        self.codes = np.ascontiguousarray(codes, np.uint8)
        self.ids = np.ascontiguousarray(ids, np.int32)
        self.sizes = np.ascontiguousarray(sizes, np.int32)
        self.norms = np.ascontiguousarray(norms, np.float32)

    @property
    def n_lists(self) -> int:
        return self.codes.shape[0]

    @property
    def list_pad(self) -> int:
        return self.codes.shape[1]

    @property
    def n_code_bytes(self) -> int:
        return self.codes.shape[2]

    @property
    def nbytes(self) -> int:
        return (self.codes.nbytes + self.ids.nbytes + self.sizes.nbytes
                + self.norms.nbytes)

    def read_lists(self, clusters: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                              np.ndarray]:
        """Gather the named lists' host rows. Any failure surfaces as a
        chained :class:`TierReadError` (the typed degraded path)."""
        try:
            cl = np.asarray(clusters, np.int64)
            if cl.size and (cl.min() < 0 or cl.max() >= self.n_lists):
                raise IndexError(f"cluster ids {cl.min()}..{cl.max()} "
                                 f"outside [0, {self.n_lists})")
            return (self.codes[cl], self.ids[cl], self.sizes[cl],
                    self.norms[cl])
        except Exception as e:
            raise TierReadError(
                f"host tier read failed for {np.size(clusters)} "
                f"list(s)") from e


def _host_norms(index: "ivf_pq.Index", cache_dtype=jnp.bfloat16
                ) -> np.ndarray:
    """Decoded-residual norms for every list, chunked at exactly the
    ``list_tile`` ``ensure_scan_cache`` uses so each chunk reproduces one
    of the reference decode's internal tiles (last chunk zero-pads the
    same way) — the norms are bit-equal to ``index.decoded_norms``."""
    per_cluster = index.params.codebook_kind == CodebookGen.PER_CLUSTER
    n_lists = index.n_lists
    list_pad = index.list_codes.shape[1]
    list_tile = balanced_tile(n_lists, min(n_lists, 128), 8)
    out = np.empty((n_lists, list_pad), np.float32)
    for a in range(0, n_lists, list_tile):
        b = min(a + list_tile, n_lists)
        cb = index.codebooks[a:b] if per_cluster else index.codebooks
        _, nrm = ivf_pq._decode_lists_jit(
            cb, index.list_codes[a:b], index.pq_dim, index.pq_bits,
            per_cluster, list_tile, jnp.dtype(cache_dtype).name)
        out[a:b] = np.asarray(nrm)[:b - a]
    return out


def host_tier_from_index(index: "ivf_pq.Index",
                         cache_dtype=jnp.bfloat16) -> HostTier:
    """Demote an in-memory index's lists to a :class:`HostTier`."""
    if index.list_codes is None:
        raise ValueError("index has no packed lists to demote")
    return HostTier(np.asarray(index.list_codes),
                    np.asarray(index.list_indices),
                    np.asarray(index.list_sizes),
                    _host_norms(index, cache_dtype))


# ------------------------------------------------------------ telemetry

#: prefetch accounting vocabulary (``raft_tpu_tier_prefetch_total``'s
#: ``event`` label) — fetch: lists pulled by the prefetch path;
#: already_resident: peeked lists that were already in the arena;
#: useful: a demand hit landed on a slab the prefetcher staged;
#: error: a prefetch pass failed (never takes serving down)
_PREFETCH_EVENTS = ("fetch", "already_resident", "useful", "error")

_STALL_PATHS = ("demand", "prefetch")


class TierStats:
    """Registry-backed tier telemetry for one arena (the
    ``ServingStats`` idiom: labeled children pre-touched so a scrape
    shows the full vocabulary at 0)."""

    def __init__(self, registry: Optional[obs_metrics.Registry] = None,
                 arena_label: str = "arena") -> None:
        r = registry if registry is not None else obs_metrics.REGISTRY
        self.registry = r
        self.arena_label = arena_label
        a = arena_label
        self._hits = r.counter(
            "raft_tpu_tier_cache_hits_total",
            "Demand-path probed lists found resident in the arena.",
            ("arena",)).labels(a)
        self._misses = r.counter(
            "raft_tpu_tier_cache_misses_total",
            "Demand-path probed lists fetched from the host tier.",
            ("arena",)).labels(a)
        self._evictions = r.counter(
            "raft_tpu_tier_cache_evictions_total",
            "LRU slab evictions (any path).", ("arena",)).labels(a)
        pf = r.counter(
            "raft_tpu_tier_prefetch_total",
            "Prefetcher accounting by event.", ("arena", "event"))
        self._pf = {ev: pf.labels(a, ev) for ev in _PREFETCH_EVENTS}
        stall = r.histogram(
            "raft_tpu_tier_fetch_stall_seconds",
            "Wall time a resolve blocked on host->device slab fetches.",
            ("arena", "path"),
            buckets=obs_metrics.exponential_buckets(1e-5, 2.0, 20))
        self._stall = {p: stall.labels(a, p) for p in _STALL_PATHS}
        self._occ = r.gauge(
            "raft_tpu_tier_arena_occupancy",
            "Occupied arena slot fraction.", ("arena",)).labels(a)
        self._occ.set(0.0)

    def record_resolve(self, path: str, hits: int, misses: int,
                       evictions: int, useful: int,
                       occupancy_frac: float) -> None:
        if path == "demand":
            if hits:
                self._hits.inc(hits)
            if misses:
                self._misses.inc(misses)
            if useful:
                self._pf["useful"].inc(useful)
        else:
            if hits:
                self._pf["already_resident"].inc(hits)
            if misses:
                self._pf["fetch"].inc(misses)
        if evictions:
            self._evictions.inc(evictions)
        self._occ.set(occupancy_frac)

    def record_stall(self, path: str, seconds: float) -> None:
        self._stall[path].observe(seconds)

    def prefetch_event(self, event: str, n: int = 1) -> None:
        self._pf[event].inc(n)


# ------------------------------------------------------------ slab arena


class _ArenaSnapshot(NamedTuple):
    """Immutable view of the arena's device state at resolve time —
    in-flight scans keep scanning it unperturbed by later fetches."""

    dec: jax.Array    # [slots, list_pad, rot_dim] cache dtype
    norms: jax.Array  # [slots, list_pad] f32
    ids: jax.Array    # [slots, list_pad] i32 (-1 padding)
    sizes: jax.Array  # [slots] i32


@functools.partial(jax.jit, static_argnames=("pq_dim", "pq_bits",
                                             "per_cluster", "cache_dtype"))
def _fetch_insert_jit(arena_dec, arena_norms, arena_ids, arena_sizes,
                      codebooks, clusters, codes, norms, ids, sizes, slots,
                      pq_dim: int, pq_bits: int, per_cluster: bool,
                      cache_dtype: str):
    """Decode one fixed-shape group of host lists and scatter them into
    the arena (functional: returns the replacement arrays). The decode
    is ``ivf_pq._decode_lists_jit`` itself (inlined by the nested jit)
    at ``list_tile == group size``, so slab values are the exact bytes
    ``ensure_scan_cache`` would have produced; the norms ride along
    host-precomputed (see :func:`_host_norms`) and the decode's own
    norm output is dead code."""
    cb = codebooks[clusters] if per_cluster else codebooks
    dec, _ = ivf_pq._decode_lists_jit(cb, codes, pq_dim, pq_bits,
                                      per_cluster, codes.shape[0],
                                      cache_dtype)
    return (arena_dec.at[slots].set(dec),
            arena_norms.at[slots].set(norms),
            arena_ids.at[slots].set(ids),
            arena_sizes.at[slots].set(sizes))


class SlabArena:
    """Fixed-size device-resident LRU of decoded list slabs.

    Keyed by ``(namespace, cluster)`` so one arena multiplexes every
    tiered index on the chip: a tenant with no traffic holds zero slots
    (host RAM only); a hot tenant's probed lists stay resident. All
    mutable bookkeeping lives under one lock; device arrays are only
    *replaced* (functional scatter), never mutated, so readers hold
    consistent snapshots without taking the lock during the scan.
    """

    def __init__(self, slots: int, list_pad: int, rot_dim: int,
                 cache_dtype=jnp.bfloat16, fetch_tile: int = 8,
                 registry: Optional[obs_metrics.Registry] = None,
                 label: Optional[str] = None, span_sink=None,
                 clock=time.perf_counter) -> None:
        if slots < 1:
            raise ValueError(f"arena needs >= 1 slot, got {slots}")
        self.slots = int(slots)
        self.list_pad = int(list_pad)
        self.rot_dim = int(rot_dim)
        self.cache_dtype = jnp.dtype(cache_dtype)
        self.fetch_tile = max(1, min(int(fetch_tile), self.slots))
        self.label = label or f"arena{next(_arena_seq)}"
        self.span_sink = span_sink
        self.clock = clock
        self.stats = TierStats(registry, self.label)
        d3, d2 = (slots, list_pad, rot_dim), (slots, list_pad)
        self._dec = jnp.zeros(d3, self.cache_dtype)    # guarded_by: _lock
        self._norms = jnp.zeros(d2, jnp.float32)       # guarded_by: _lock
        self._ids = jnp.full(d2, -1, jnp.int32)        # guarded_by: _lock
        self._sizes = jnp.zeros((slots,), jnp.int32)   # guarded_by: _lock
        self._lock = threading.Lock()
        # (namespace, cluster) -> slot, in LRU order (front = coldest)
        self._map = OrderedDict()                      # guarded_by: _lock
        self._prefetched = [False] * slots             # guarded_by: _lock
        self._free = list(range(slots - 1, -1, -1))    # guarded_by: _lock
        self.counts = {                                # guarded_by: _lock
            "hits": 0, "misses": 0, "evictions": 0, "inserts": 0,
            "resolved": 0, "prefetch_fetches": 0, "prefetch_hits": 0,
            "useful_prefetch": 0,
        }

    @property
    def nbytes(self) -> int:
        """Measured device footprint (the number ``solve_host_tier``'s
        ``arena_bytes`` predicts; the C001 smoke pins the ratio)."""
        return int(self._dec.nbytes + self._norms.nbytes + self._ids.nbytes
                   + self._sizes.nbytes)

    def occupancy(self) -> int:
        with self._lock:
            return len(self._map)

    def snapshot_counts(self) -> Dict[str, int]:
        """Consistent counter snapshot plus occupancy — the interleave
        tests reconcile these exactly per seed (hits + misses +
        prefetch_hits + prefetch_fetches == resolved; inserts == misses
        + prefetch_fetches; evictions == inserts - occupancy)."""
        with self._lock:
            out = dict(self.counts)
            out["occupancy"] = len(self._map)
            return out

    def resolve_probes(self, owner: "TieredIvfPq",
                       cluster_probes: np.ndarray,
                       trace_id: Optional[str] = None
                       ) -> Tuple[_ArenaSnapshot, np.ndarray]:
        """Demand path: make every probed cluster resident and return
        ``(snapshot, slot_probes)`` with ``slot_probes`` shaped like
        ``cluster_probes`` — ready to gather the snapshot's slabs."""
        cp = np.asarray(cluster_probes)
        uniq = np.unique(cp)
        snap, resolved = self._resolve(owner, uniq, "demand", trace_id)
        lut = np.zeros(int(uniq.max()) + 1 if uniq.size else 1, np.int32)
        for c, s in resolved.items():
            lut[c] = s
        return snap, lut[cp].astype(np.int32)

    def prefetch(self, owner: "TieredIvfPq", clusters: np.ndarray,
                 trace_id: Optional[str] = None) -> int:
        """Prefetch path: stage ``clusters`` without demand accounting.
        Returns the number of lists actually fetched."""
        uniq = np.unique(np.asarray(clusters))
        _, resolved = self._resolve(owner, uniq, "prefetch", trace_id)
        return len(resolved)

    # the single mutation point — everything else is a view
    def _resolve(self, owner: "TieredIvfPq", uniq: np.ndarray, path: str,
                 trace_id: Optional[str]
                 ) -> Tuple[_ArenaSnapshot, Dict[int, int]]:
        ns = owner.namespace
        t0 = self.clock()
        groups: List[Tuple[List[int], List[int]]] = []
        with self._lock:
            if len(uniq) > self.slots:
                raise TieredArenaError(
                    f"batch probes {len(uniq)} distinct lists but the "
                    f"arena has {self.slots} slots — size the arena with "
                    f"solve_host_tier (worst case max_batch * n_probes)")
            resolved: Dict[int, int] = {}
            missing: List[int] = []
            n_hits = n_useful = 0
            for c in uniq:
                key = (ns, int(c))
                slot = self._map.get(key)
                if slot is None:
                    missing.append(int(c))
                    continue
                self._map.move_to_end(key)
                resolved[int(c)] = slot
                n_hits += 1
                if path == "demand" and self._prefetched[slot]:
                    self._prefetched[slot] = False
                    n_useful += 1
            if missing:
                # host reads before any map mutation: a TierReadError
                # leaves the arena exactly as it was
                codes, ids, sizes, norms = owner.tier.read_lists(
                    np.asarray(missing, np.int64))
                n_evict = 0
                for c in missing:
                    if self._free:
                        slot = self._free.pop()
                    else:
                        _, slot = self._map.popitem(last=False)
                        n_evict += 1
                    self._map[(ns, c)] = slot
                    self._prefetched[slot] = path == "prefetch"
                    resolved[c] = slot
                ft = self.fetch_tile
                for a in range(0, len(missing), ft):
                    pos = list(range(a, min(a + ft, len(missing))))
                    pos += [pos[0]] * (ft - len(pos))  # repeat-pad: the
                    # duplicate scatter carries an identical payload
                    grp = [missing[p] for p in pos]
                    slots_g = [resolved[c] for c in grp]
                    self._dec, self._norms, self._ids, self._sizes = \
                        _fetch_insert_jit(
                            self._dec, self._norms, self._ids, self._sizes,
                            owner.codebooks,
                            jnp.asarray(grp, jnp.int32),
                            jnp.asarray(codes[pos]),
                            jnp.asarray(norms[pos]),
                            jnp.asarray(ids[pos]),
                            jnp.asarray(sizes[pos]),
                            jnp.asarray(slots_g, jnp.int32),
                            owner.pq_dim, owner.pq_bits,
                            owner.per_cluster, self.cache_dtype.name)
                    groups.append((grp, slots_g))
                cnt = self.counts
                cnt["inserts"] += len(missing)
                cnt["evictions"] += n_evict
            else:
                n_evict = 0
            cnt = self.counts
            cnt["resolved"] += len(uniq)
            if path == "demand":
                cnt["hits"] += n_hits
                cnt["misses"] += len(missing)
                cnt["useful_prefetch"] += n_useful
            else:
                cnt["prefetch_hits"] += n_hits
                cnt["prefetch_fetches"] += len(missing)
            snap = _ArenaSnapshot(self._dec, self._norms, self._ids,
                                  self._sizes)
            occ = len(self._map)
        # emission + the stall wait happen OUTSIDE the lock: telemetry
        # never extends the critical section, and the lock graph stays
        # zero-edge (this lock is never held across another acquire)
        self.stats.record_resolve(path, n_hits, len(missing), n_evict,
                                  n_useful, occ / self.slots)
        if groups:
            jax.block_until_ready(snap.dec)
            stall = self.clock() - t0
            self.stats.record_stall(path, stall)
            if self.span_sink is not None:
                obs_spans.safe_emit(self.span_sink, {
                    "kind": "tier_fetch",
                    "trace": trace_id or obs_spans.new_trace_id(),
                    "arena": self.label,
                    "namespace": ns,
                    "path": path,
                    "n_lists": len(missing),
                    "clusters": [c for g, _ in groups for c in g],
                    "slots": [s for _, g in groups for s in g],
                    "stall_s": stall,
                })
        return snap, resolved


# ----------------------------------------------------------- scan cores


def coarse_probes_core(queries, centers, rotation, metric: DistanceType,
                       n_probes: int, q_tile: int,
                       select_recall: float = 1.0):
    """Coarse top-``n_probes`` clusters per query — the exact probe ids
    ``_search_cache_core`` computes internally, lifted out so the host
    can resolve them against the arena. Same q_tile padding, same
    HIGHEST-precision matmuls, same ``select_k_maybe_approx`` call: the
    returned probes are bit-equal to the resident engine's."""
    nq, dim = queries.shape
    n_q_tiles = cdiv(nq, q_tile)
    pad_q = n_q_tiles * q_tile - nq
    qp = jnp.pad(queries.astype(jnp.float32), ((0, pad_q), (0, 0)))
    centers_rot = jax.lax.dot_general(
        centers, rotation, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )

    def q_body(qt):
        q_rot = jax.lax.dot_general(
            qt, rotation, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        dots_c = jax.lax.dot_general(
            q_rot, centers_rot, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if metric == DistanceType.InnerProduct:
            _, probes = select_k_maybe_approx(dots_c, n_probes, False,
                                              select_recall)
        else:
            cn = jnp.sum(centers_rot * centers_rot, -1)
            _, probes = select_k_maybe_approx(cn[None, :] - 2.0 * dots_c,
                                              n_probes, True, select_recall)
        return probes

    if n_q_tiles == 1:
        probes = q_body(qp)
    else:
        probes = jax.lax.map(q_body, qp.reshape(n_q_tiles, q_tile, dim))
        probes = probes.reshape(-1, n_probes)
    return probes[:nq]


_coarse_probes_jit = jax.jit(
    coarse_probes_core,
    static_argnames=("metric", "n_probes", "q_tile", "select_recall"),
)


def tiered_scan_core(queries, centers, rotation, arena_dec, arena_norms,
                     arena_ids, arena_sizes, cluster_probes, slot_probes,
                     metric: DistanceType, k: int, n_probes: int,
                     q_tile: int, overflow_decoded=None,
                     overflow_norms=None, overflow_indices=None,
                     has_overflow: bool = False,
                     select_recall: float = 1.0):
    """ADC scan over arena-resident slabs — ``_search_cache_core``'s
    non-pallas tile body with the probes injected (``cluster_probes``
    for the ``centers_rot`` terms, ``slot_probes`` for the slab
    gathers). Every arithmetic op, operand shape and reduction matches
    the reference, so restricted to the same probed lists the outputs
    are bit-identical (pinned by tests/test_tiered.py)."""
    nq, dim = queries.shape
    slots, list_pad, rot_dim = arena_dec.shape
    minimize = metric != DistanceType.InnerProduct

    def _sel(vals, kk, sel_min):
        return select_k_maybe_approx(vals, kk, sel_min, select_recall)

    n_q_tiles = cdiv(nq, q_tile)
    pad_q = n_q_tiles * q_tile - nq
    qp = jnp.pad(queries.astype(jnp.float32), ((0, pad_q), (0, 0)))
    cp = jnp.pad(cluster_probes, ((0, pad_q), (0, 0)))
    sp = jnp.pad(slot_probes, ((0, pad_q), (0, 0)))

    centers_rot = jax.lax.dot_general(
        centers, rotation, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    valid_slot = jnp.arange(list_pad)[None, :] < arena_sizes[:, None]

    def q_body(args):
        qt, probes, slotp = args
        q_rot = jax.lax.dot_general(
            qt, rotation, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        g_idx = arena_ids[slotp]
        g_valid = valid_slot[slotp]
        if metric == DistanceType.InnerProduct:
            dots_c = jax.lax.dot_general(
                q_rot, centers_rot, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            g_dec = arena_dec[slotp]  # [t, P, pad, rot] bf16
            dots = jnp.einsum("td,tpld->tpl", q_rot,
                              g_dec.astype(jnp.float32),
                              preferred_element_type=jnp.float32)
            base = jnp.take_along_axis(dots_c, probes, axis=1)
            d = base[:, :, None] + dots
        else:
            g_dec = arena_dec[slotp]  # [t, P, pad, rot] bf16
            g_n = arena_norms[slotp]  # [t, P, pad]
            qr_res = q_rot[:, None, :] - centers_rot[probes]  # [t, P, rot]
            dots = jnp.einsum("tpd,tpld->tpl", qr_res,
                              g_dec.astype(jnp.float32),
                              preferred_element_type=jnp.float32)
            qn = jnp.sum(qr_res * qr_res, -1)  # [t, P]
            d = qn[:, :, None] - 2.0 * dots + g_n

        bad_fill = jnp.inf if minimize else -jnp.inf
        d = jnp.where(g_valid, d, bad_fill)

        n_cand = n_probes * list_pad
        flat_d = d.reshape(qt.shape[0], n_cand)
        flat_i = g_idx.reshape(qt.shape[0], n_cand)
        if has_overflow:
            od, oi = ivf_pq._pq_overflow_scan(
                q_rot, overflow_decoded, overflow_norms, overflow_indices,
                jnp.zeros((0,), jnp.uint32), metric, False, bad_fill)
            flat_d = jnp.concatenate([flat_d, od], axis=1)
            flat_i = jnp.concatenate([flat_i, oi], axis=1)
            n_cand += od.shape[1]
        kk = min(k, n_cand)
        v, sel = _sel(flat_d, kk, minimize)
        i_out = jnp.take_along_axis(flat_i, sel, axis=1)
        if kk < k:
            v = jnp.pad(v, ((0, 0), (0, k - kk)), constant_values=bad_fill)
            i_out = jnp.pad(i_out, ((0, 0), (0, k - kk)),
                            constant_values=-1)
        if metric == DistanceType.L2SqrtExpanded:
            v = jnp.sqrt(jnp.maximum(v, 0.0))
        return v, i_out

    if n_q_tiles == 1:
        vals, idxs = q_body((qp, cp, sp))
    else:
        vals, idxs = jax.lax.map(
            q_body, (qp.reshape(n_q_tiles, q_tile, dim),
                     cp.reshape(n_q_tiles, q_tile, n_probes),
                     sp.reshape(n_q_tiles, q_tile, n_probes)))
        vals = vals.reshape(-1, k)
        idxs = idxs.reshape(-1, k)
    return vals[:nq], idxs[:nq]


_tiered_scan_jit = jax.jit(
    tiered_scan_core,
    static_argnames=("metric", "k", "n_probes", "q_tile", "has_overflow",
                     "select_recall"),
)


# -------------------------------------------------------- tiered index


class TieredIvfPq:
    """IVF-PQ searcher with HBM-resident coarse structures and
    host-resident lists resolved through a :class:`SlabArena`.

    ``namespace`` keys this index's slabs in the (possibly shared)
    arena; distinct tiered indexes sharing one arena multiplex the same
    device budget, which is the multi-tenant story: promotion is just
    LRU traffic, demotion is just silence.
    """

    def __init__(self, params: "ivf_pq.IndexParams", pq_dim: int,
                 centers, rotation, codebooks, tier: HostTier,
                 arena: SlabArena, n_rows: int,
                 overflow_decoded=None, overflow_norms=None,
                 overflow_indices=None, namespace: Optional[str] = None,
                 res: Optional[Resources] = None) -> None:
        if arena.list_pad != tier.list_pad:
            raise ValueError(f"arena list_pad {arena.list_pad} != tier "
                             f"list_pad {tier.list_pad}")
        if arena.rot_dim != rotation.shape[0]:
            raise ValueError(f"arena rot_dim {arena.rot_dim} != index "
                             f"rot_dim {rotation.shape[0]}")
        self.params = params
        self.pq_dim = int(pq_dim)
        self.centers = centers
        self.rotation = rotation
        self.codebooks = codebooks
        self.tier = tier
        self.arena = arena
        self.n_rows = int(n_rows)
        self.overflow_decoded = overflow_decoded
        self.overflow_norms = overflow_norms
        self.overflow_indices = overflow_indices
        self.namespace = namespace or f"tiered{id(self):x}"
        self.res = res

    # -- geometry -----------------------------------------------------
    @property
    def metric(self) -> DistanceType:
        return self.params.metric

    @property
    def dim(self) -> int:
        return self.centers.shape[1]

    @property
    def rot_dim(self) -> int:
        return self.rotation.shape[0]

    @property
    def n_lists(self) -> int:
        return self.tier.n_lists

    @property
    def list_pad(self) -> int:
        return self.tier.list_pad

    @property
    def pq_bits(self) -> int:
        return self.params.pq_bits

    @property
    def per_cluster(self) -> bool:
        return self.params.codebook_kind == CodebookGen.PER_CLUSTER

    @property
    def has_overflow(self) -> bool:
        return (self.overflow_indices is not None
                and self.overflow_indices.shape[0] > 0)

    # -- construction -------------------------------------------------
    @classmethod
    def from_index(cls, index: "ivf_pq.Index",
                   res: Optional[Resources] = None,
                   arena: Optional[SlabArena] = None,
                   arena_slots: Optional[int] = None,
                   namespace: Optional[str] = None,
                   cache_dtype=jnp.bfloat16,
                   registry: Optional[obs_metrics.Registry] = None,
                   span_sink=None) -> "TieredIvfPq":
        """Demote an in-memory index: lists → host tier, coarse
        structures stay device-resident, arena sized by
        :func:`solve_host_tier` unless given."""
        res = ensure_resources(res)
        tier = host_tier_from_index(index, cache_dtype)
        od = on = oi = None
        if index.overflow_codes.shape[0] > 0:
            ivf_pq.ensure_overflow_decoded(index, cache_dtype)
            od, on = index.overflow_decoded, index.overflow_norms
            oi = index.overflow_indices
        if arena is None:
            plan = solve_host_tier(
                tier.n_lists, tier.list_pad, index.rot_dim,
                tier.n_code_bytes, res.workspace_limit_bytes,
                cache_itemsize=jnp.dtype(cache_dtype).itemsize)
            slots = arena_slots if arena_slots is not None \
                else plan["arena_slots"]
            arena = SlabArena(slots, tier.list_pad, index.rot_dim,
                              cache_dtype=cache_dtype, registry=registry,
                              span_sink=span_sink)
        return cls(index.params, index.pq_dim, index.centers,
                   index.rotation, index.codebooks, tier, arena,
                   index.n_rows, od, on, oi, namespace=namespace, res=res)

    @classmethod
    def from_file(cls, path: str, params=None,
                  res: Optional[Resources] = None,
                  batch_rows: int = 1 << 18, dtype=None,
                  max_train_rows: Optional[int] = None,
                  **kwargs) -> "TieredIvfPq":
        """Streamed build straight into the tier: ``ooc``'s
        ``iter_bin_batches_prefetch``-backed file build produces the
        index, whose lists are immediately demoted to host RAM."""
        from raft_tpu.neighbors import ooc
        res = ensure_resources(res)
        index = ooc.build_ivf_pq_from_file(
            path, params=params, res=res, batch_rows=batch_rows,
            dtype=dtype, max_train_rows=max_train_rows)
        return cls.from_index(index, res=res, **kwargs)

    # -- search -------------------------------------------------------
    def search(self, queries, k: int,
               params: Optional[SearchParams] = None,
               res: Optional[Resources] = None):
        """Top-``k`` search, bit-identical to ``ivf_pq.search`` with
        ``scan_mode="cache"`` over the same probed lists. Steady-state
        hits re-dispatch three cached executables (coarse, fetchless
        resolve, scan) — zero compiles after warmup."""
        params = params or SearchParams()
        if params.scan_mode not in ("auto", "cache"):
            raise ValueError(
                f"tiered serving has only the cache engine; scan_mode="
                f"{params.scan_mode!r} is not tierable")
        res = ensure_resources(res if res is not None else self.res)
        queries = as_query_array(queries)
        nq = queries.shape[0]
        if queries.shape[1] != self.dim:
            raise ValueError(f"queries dim {queries.shape[1]} != index "
                             f"dim {self.dim}")
        queries = pad_rows(queries, query_bucket(nq))
        n_probes = min(params.n_probes, self.n_lists)
        q_tile = ivf_pq.plan_cache_tiles(n_probes, self.list_pad,
                                         self.rot_dim,
                                         res.workspace_limit_bytes)
        probes_dev = _coarse_probes_jit(
            queries, self.centers, self.rotation, self.metric, n_probes,
            q_tile, float(params.select_recall))
        cluster_probes = np.asarray(probes_dev)
        snap, slot_probes = self.arena.resolve_probes(
            self, cluster_probes, trace_id=obs_spans.current_trace())
        obs_explain.record_dispatch(
            "tiered_ivf_pq", params.scan_mode, "cache", "only_engine",
            params={"n_probes": n_probes, "k": int(k)},
            plan={"q_tile": q_tile, "arena_slots": self.arena.slots,
                  "namespace": self.namespace})
        v, i = _tiered_scan_jit(
            queries, self.centers, self.rotation,
            snap.dec, snap.norms, snap.ids, snap.sizes,
            probes_dev, jnp.asarray(slot_probes),
            self.metric, int(k), n_probes, q_tile,
            self.overflow_decoded, self.overflow_norms,
            self.overflow_indices, self.has_overflow,
            float(params.select_recall))
        return v[:nq], i[:nq]

    def prefetch_queries(self, queries, params: Optional[SearchParams] = None,
                         depth: Optional[int] = None,
                         trace_id: Optional[str] = None) -> int:
        """Stage the lists a future ``search(queries)`` would probe.
        Shares the demand path's compiled coarse program (same bucket
        shapes → no extra compiles). ``depth`` caps the number of lists
        staged; a cap is LOGGED, never silent."""
        params = params or SearchParams()
        res = ensure_resources(self.res)
        queries = as_query_array(queries)
        queries = pad_rows(queries, query_bucket(queries.shape[0]))
        n_probes = min(params.n_probes, self.n_lists)
        q_tile = ivf_pq.plan_cache_tiles(n_probes, self.list_pad,
                                         self.rot_dim,
                                         res.workspace_limit_bytes)
        probes = np.asarray(_coarse_probes_jit(
            queries, self.centers, self.rotation, self.metric, n_probes,
            q_tile, float(params.select_recall)))
        uniq = np.unique(probes)
        if depth is not None and len(uniq) > depth:
            logger.warning(
                "tier prefetch capped at depth=%d (batch probes %d "
                "distinct lists) — coverage is partial, raise depth to "
                "stage the full peeked batch", depth, len(uniq))
            uniq = uniq[:depth]
        return self.arena.prefetch(self, uniq, trace_id=trace_id)


# ------------------------------------------------------------ prefetcher


class TierPrefetcher:
    """Batcher-driven prefetch thread: peeks the engine batcher's
    already-formed next batch (non-consuming ``Batcher.peek()``) and
    stages its probed lists, so the host→device slab copies overlap the
    previous batch's device time instead of stalling dispatch.

    Thread discipline (graftcheck T-series): the loop's only wait is the
    budgeted ``Event.wait(poll_s)``; all cross-thread state it touches
    is owned elsewhere under those owners' locks (batcher, arena), and
    its own fields are single-writer (this thread) — progress counters
    are read racily by tests/benches, which is fine for monotonic ints.
    """

    def __init__(self, engine, tiered: TieredIvfPq,
                 params: Optional[SearchParams] = None,
                 depth: Optional[int] = None,
                 poll_s: float = 0.0005) -> None:
        self.engine = engine
        self.tiered = tiered
        self.params = params or SearchParams()
        self.depth = depth
        self.poll_s = float(poll_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seen = None      # single-writer: the prefetch thread
        self.n_passes = 0      # single-writer: the prefetch thread
        self.n_capped = 0      # single-writer: the prefetch thread
        self.n_errors = 0      # single-writer: the prefetch thread

    def start(self) -> "TierPrefetcher":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(  # guarded_by: atomic
            target=self._loop, name=f"tier-prefetch-{self.tiered.namespace}",
            daemon=True)
        self._thread.start()
        return self

    def close(self, timeout: float = 2.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None  # guarded_by: atomic

    def __enter__(self) -> "TierPrefetcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):  # budgeted wait
            batch = self.engine.batcher.peek()
            if not batch:
                continue
            head = batch[0]
            key = (id(head), head.trace_id, len(batch))
            if key == self._seen:
                continue
            self._seen = key
            try:
                t = self.tiered
                bucket = query_bucket(len(batch))
                qs = np.zeros((bucket, t.dim), np.float32)
                for j, r in enumerate(batch):
                    qs[j] = np.asarray(r.query, np.float32).reshape(-1)
                t.prefetch_queries(qs, params=self.params,
                                   depth=self.depth,
                                   trace_id=head.trace_id)
                self.n_passes += 1
            except Exception as e:  # prefetch never takes serving down
                self.n_errors += 1
                self.tiered.arena.stats.prefetch_event("error")
                logger.warning("tier prefetch pass failed: %s: %s",
                               type(e).__name__, e)


def attach_prefetcher(engine, tiered: TieredIvfPq,
                      params: Optional[SearchParams] = None,
                      depth: Optional[int] = None,
                      poll_s: float = 0.0005) -> TierPrefetcher:
    """Start a :class:`TierPrefetcher` against a running engine. The
    caller owns shutdown (``close()`` or use as a context manager)."""
    return TierPrefetcher(engine, tiered, params=params, depth=depth,
                          poll_s=poll_s).start()


# -------------------------------------------------------------- manifest


def _file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as fh:
        while True:
            b = fh.read(chunk)
            if not b:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(b, crc)


def save_tiered(tiered: TieredIvfPq, dir_path: str,
                name: str = "default") -> str:
    """Persist a tiered index: host lists as ``.bin`` files (the
    streamed-IO format ``iter_bin_batches_prefetch`` reads), coarse
    structures as one ``.npz``, and a ``TIERED_MANIFEST_*.json`` tying
    them together with per-list spans and crc32s (the artifact
    graftcheck ``--artifacts`` validates under :func:`load_manifest`)."""
    os.makedirs(dir_path, exist_ok=True)
    t = tiered.tier
    L, P, B = t.n_lists, t.list_pad, t.n_code_bytes
    rels = {
        "codes": f"tier_{name}_codes.bin",
        "ids": f"tier_{name}_ids.bin",
        "norms": f"tier_{name}_norms.bin",
        "sizes": f"tier_{name}_sizes.bin",
        "coarse": f"tier_{name}_coarse.npz",
    }
    native.write_bin(os.path.join(dir_path, rels["codes"]),
                     t.codes.reshape(L * P, B))
    native.write_bin(os.path.join(dir_path, rels["ids"]), t.ids)
    native.write_bin(os.path.join(dir_path, rels["norms"]), t.norms)
    native.write_bin(os.path.join(dir_path, rels["sizes"]),
                     t.sizes.reshape(L, 1))
    coarse = {
        "centers": np.asarray(tiered.centers, np.float32),
        "rotation": np.asarray(tiered.rotation, np.float32),
        "codebooks": np.asarray(tiered.codebooks, np.float32),
    }
    if tiered.has_overflow:
        coarse["overflow_decoded"] = np.asarray(tiered.overflow_decoded,
                                                np.float32)
        coarse["overflow_norms"] = np.asarray(tiered.overflow_norms,
                                              np.float32)
        coarse["overflow_indices"] = np.asarray(tiered.overflow_indices,
                                                np.int32)
    np.savez(os.path.join(dir_path, rels["coarse"]), **coarse)
    dtypes = {"codes": "uint8", "ids": "int32", "norms": "float32",
              "sizes": "int32"}
    dims = {"codes": B, "ids": P, "norms": P, "sizes": 1}
    n_rows_of = {"codes": L * P, "ids": L, "norms": L, "sizes": L}
    files = {}
    for key, rel in rels.items():
        full = os.path.join(dir_path, rel)
        entry = {"path": rel, "crc32": _file_crc32(full)}
        if key != "coarse":
            entry.update(rows=n_rows_of[key], dim=dims[key],
                         dtype=dtypes[key])
        files[key] = entry
    sizes = t.sizes
    manifest = {
        "schema": MANIFEST_SCHEMA,
        "namespace": tiered.namespace,
        "n_lists": L, "list_pad": P, "n_code_bytes": B,
        "pq_dim": tiered.pq_dim, "pq_bits": tiered.pq_bits,
        "metric": int(tiered.metric),
        "codebook_kind": int(tiered.params.codebook_kind),
        "n_rows": tiered.n_rows, "dim": tiered.dim,
        "rot_dim": tiered.rot_dim,
        "files": files,
        "lists": [{"list": i, "row_start": i * P, "rows": P,
                   "size": int(sizes[i])} for i in range(L)],
    }
    mpath = os.path.join(dir_path, f"{MANIFEST_PREFIX}{name}.json")
    with open(mpath, "w") as fh:
        json.dump(manifest, fh, indent=1)
    return mpath


def validate_manifest(art: dict, base_dir: Optional[str] = None,
                      check_files: bool = False) -> None:
    """Schema + span validation; with ``check_files`` also header/crc32
    verification of every referenced host file. This is the exact
    front half of :func:`load_tiered` — graftcheck's A001 checker calls
    it so the gate can never drift from the consuming loader."""
    if art.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(f"schema {art.get('schema')!r} != "
                         f"{MANIFEST_SCHEMA!r}")
    for key in ("n_lists", "list_pad", "n_code_bytes", "pq_dim",
                "pq_bits", "n_rows", "dim", "rot_dim"):
        if not isinstance(art.get(key), int) or art[key] < 0:
            raise ValueError(f"manifest key {key!r} must be a "
                             f"non-negative int, got {art.get(key)!r}")
    L, P = art["n_lists"], art["list_pad"]
    files = art.get("files")
    if not isinstance(files, dict):
        raise ValueError("manifest has no 'files' dict")
    for key in ("codes", "ids", "norms", "sizes", "coarse"):
        entry = files.get(key)
        if not isinstance(entry, dict) or "path" not in entry \
                or "crc32" not in entry:
            raise ValueError(f"files[{key!r}] needs 'path' and 'crc32'")
    lists = art.get("lists")
    if not isinstance(lists, list) or len(lists) != L:
        raise ValueError(f"'lists' must enumerate all {L} lists")
    for row in lists:
        if not all(k in row for k in ("list", "row_start", "rows", "size")):
            raise ValueError(f"list span {row} lacks a "
                             f"list/row_start/rows/size key")
        if row["row_start"] + row["rows"] > L * P:
            raise ValueError(f"list span {row} overruns the codes file "
                             f"({L * P} rows)")
        if row["size"] > P:
            raise ValueError(f"list {row['list']} size {row['size']} "
                             f"exceeds list_pad {P}")
    if not check_files:
        return
    base = base_dir or "."
    for key, entry in files.items():
        full = os.path.join(base, entry["path"])
        if not os.path.exists(full):
            raise FileNotFoundError(f"manifest references missing host "
                                    f"file {entry['path']!r}")
        crc = _file_crc32(full)
        if crc != entry["crc32"]:
            raise ValueError(f"{entry['path']}: crc32 {crc:#010x} != "
                             f"manifest {entry['crc32']:#010x}")
        if key != "coarse":
            rows, dim = native.read_bin_header(full)
            if (rows, dim) != (entry["rows"], entry["dim"]):
                raise ValueError(
                    f"{entry['path']}: header [{rows}, {dim}] != "
                    f"manifest [{entry['rows']}, {entry['dim']}]")


def load_manifest(path: str) -> dict:
    with open(path) as fh:
        art = json.load(fh)
    validate_manifest(art, base_dir=os.path.dirname(path) or ".",
                      check_files=True)
    return art


def load_tiered(manifest_path: str, res: Optional[Resources] = None,
                arena: Optional[SlabArena] = None,
                arena_slots: Optional[int] = None,
                batch_rows: int = 1 << 16,
                registry: Optional[obs_metrics.Registry] = None,
                span_sink=None) -> TieredIvfPq:
    """Rebuild a :class:`TieredIvfPq` from its manifest: the packed
    codes stream in through ``native.iter_bin_batches_prefetch`` (IO
    overlapped with the copy into the pinned host block), everything
    else loads whole (small)."""
    art = load_manifest(manifest_path)
    base = os.path.dirname(manifest_path) or "."
    L, P, B = art["n_lists"], art["list_pad"], art["n_code_bytes"]
    files = art["files"]
    codes = np.empty((L * P, B), np.uint8)
    for off, batch in native.iter_bin_batches_prefetch(
            os.path.join(base, files["codes"]["path"]), batch_rows,
            dtype=np.uint8):
        codes[off:off + len(batch)] = batch
    ids = native.read_bin(os.path.join(base, files["ids"]["path"]),
                          dtype=np.int32)
    norms = native.read_bin(os.path.join(base, files["norms"]["path"]),
                            dtype=np.float32)
    sizes = native.read_bin(os.path.join(base, files["sizes"]["path"]),
                            dtype=np.int32).reshape(-1)
    tier = HostTier(codes.reshape(L, P, B), ids, sizes, norms)
    with np.load(os.path.join(base, files["coarse"]["path"])) as z:
        centers = jnp.asarray(z["centers"])
        rotation = jnp.asarray(z["rotation"])
        codebooks = jnp.asarray(z["codebooks"])
        od = on = oi = None
        if "overflow_indices" in z:
            od = jnp.asarray(z["overflow_decoded"])
            on = jnp.asarray(z["overflow_norms"])
            oi = jnp.asarray(z["overflow_indices"])
    res = ensure_resources(res)
    params = ivf_pq.IndexParams(
        n_lists=L, metric=DistanceType(art["metric"]),
        pq_bits=art["pq_bits"],
        codebook_kind=CodebookGen(art["codebook_kind"]))
    if arena is None:
        plan = solve_host_tier(L, P, art["rot_dim"], B,
                               res.workspace_limit_bytes)
        slots = arena_slots if arena_slots is not None \
            else plan["arena_slots"]
        arena = SlabArena(slots, P, art["rot_dim"], registry=registry,
                          span_sink=span_sink)
    return TieredIvfPq(params, art["pq_dim"], centers, rotation,
                       codebooks, tier, arena, art["n_rows"], od, on, oi,
                       namespace=art.get("namespace"), res=res)
