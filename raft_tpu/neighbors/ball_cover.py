"""Random ball cover — landmark-pruned exact kNN.

Reference: ``raft::neighbors::ball_cover`` (neighbors/ball_cover-inl.cuh;
types ball_cover_types.hpp:35-92 — √n sampled landmarks, per-landmark sorted
member lists with radii; spatial/knn/detail/ball_cover/registers-inl.cuh —
triangle-inequality-pruned scan passes). Supported metrics: L2 family and
haversine, as in the reference.

TPU-native design: the index is an IVF-like padded layout ([L, pad, dim]
member lists + radii). Search is the two-pass RBC scheme split across a
host decision point: pass 1 (jit) scans the ``n_init_probes`` closest
landmarks' lists for a kth-distance estimate; the host then applies the
triangle-inequality lower bound d(q, lm) − radius_lm ≥ kth → such lists
cannot improve any query and are dropped from pass 2's shape entirely
(bucketed to powers of two to bound recompiles); pass 2 (jit) scans only
the surviving union with per-query bound masks for exactness. Pruning on
TPU must change the *shape*, not mask lanes — the one host sync is what
buys real compute savings. Worst case degrades to brute force — exactly
the RBC guarantee."""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import (
    DistanceType,
    gathered_distances,
    haversine,
    l2_expanded,
    resolve_metric,
)
from raft_tpu.ops.select_k import select_k
from raft_tpu.utils.shape import cdiv, round_up_to

_SUPPORTED = (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
              DistanceType.Haversine)


class BallCoverIndex:
    """Landmarks + padded member lists + radii (ball_cover_types.hpp)."""

    def __init__(self, landmarks, list_data, list_indices, list_sizes, radii,
                 metric: DistanceType, n_rows: int):
        self.landmarks = landmarks  # [L, dim]
        self.list_data = list_data  # [L, pad, dim]
        self.list_indices = list_indices  # [L, pad]
        self.list_sizes = list_sizes  # [L]
        self.radii = radii  # [L] max member distance (rooted metric)
        self.metric = metric
        self.n_rows = n_rows

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]


def _rooted_dist(q, pts, metric: DistanceType):
    """Rooted (triangle-inequality-valid) distance matrix."""
    if metric == DistanceType.Haversine:
        return haversine(q, pts)
    return l2_expanded(q, pts, sqrt=True)


@tracing.range("ball_cover.build")
def build(
    dataset,
    metric="euclidean",
    n_landmarks: Optional[int] = None,
    res: Optional[Resources] = None,
) -> BallCoverIndex:
    """Build (reference: ball_cover::build_index): sample √n landmarks,
    assign every point to its closest landmark, record ball radii."""
    res = ensure_resources(res)
    m = resolve_metric(metric)
    if m not in _SUPPORTED:
        raise ValueError(f"ball_cover supports L2/haversine, got {m.name}")
    dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    L = int(n_landmarks or max(int(math.sqrt(n)), 1))

    from raft_tpu.ops import rng as rrng

    landmarks = rrng.subsample_rows(res.next_key(), dataset, L)
    d = _rooted_dist(dataset, landmarks, m)  # [n, L]
    labels = np.asarray(jnp.argmin(d, axis=1))
    dmin = np.asarray(jnp.min(d, axis=1))

    from raft_tpu import native

    sizes = np.bincount(labels, minlength=L).astype(np.int32)
    pad = max(int(round_up_to(max(int(sizes.max()), 1), 8)), 8)
    data, idxs, sizes = native.pack_lists(np.asarray(dataset), labels, L, pad)
    radii = np.zeros((L,), np.float32)
    np.maximum.at(radii, labels, dmin)
    return BallCoverIndex(landmarks, jnp.asarray(data), jnp.asarray(idxs),
                          jnp.asarray(sizes), jnp.asarray(radii), m, n)


def _scan_gathered(q, g_data, g_valid, metric: DistanceType):
    nq, dim = q.shape
    flat = g_data.reshape(nq, -1, dim) if g_data.ndim == 4 else g_data
    if metric == DistanceType.Haversine:
        qd = jax.vmap(lambda qq, pts: haversine(qq[None], pts)[0])(q, flat)
    else:
        # rooted L2 keeps the triangle inequality valid for pruning
        qd = gathered_distances(q, flat, DistanceType.L2SqrtExpanded)
    return jnp.where(g_valid.reshape(nq, -1), qd.reshape(nq, -1), jnp.inf)


@functools.partial(jax.jit, static_argnames=("metric", "k", "init_probes"))
def _pass1_jit(queries, landmarks, list_data, list_indices, list_sizes,
               metric: DistanceType, k: int, init_probes: int):
    nq, dim = queries.shape
    L, pad, _ = list_data.shape
    q = queries.astype(jnp.float32)
    lm_d = _rooted_dist(q, landmarks, metric)  # [nq, L] rooted
    valid_slot = jnp.arange(pad)[None, :] < list_sizes[:, None]

    _, probes = select_k(lm_d, init_probes, select_min=True)
    d1 = _scan_gathered(q, list_data[probes], valid_slot[probes], metric)
    i1 = list_indices[probes].reshape(nq, -1)
    kk = min(k, d1.shape[1])
    best_d, best_sel = select_k(d1, kk, select_min=True)
    best_i = jnp.take_along_axis(i1, best_sel, axis=1)
    return best_d, best_i, lm_d, probes


@functools.partial(jax.jit, static_argnames=("metric", "k"))
def _pass2_jit(queries, sub_data, sub_indices, sub_valid, needed_sub,
               best_d, best_i, metric: DistanceType, k: int):
    """Scan only the union-of-needed lists ([M, pad, …], M « L) with the
    per-query bound mask for exactness."""
    nq = queries.shape[0]
    M, pad, dim = sub_data.shape
    q = queries.astype(jnp.float32)
    # query-invariant candidates → ONE [nq, dim]×[dim, M·pad] MXU GEMM (no
    # per-query data copy; the batched einsum path is for per-query gathers)
    flat_pts = sub_data.reshape(M * pad, dim)
    if metric == DistanceType.Haversine:
        d_all = haversine(q, flat_pts)
    else:
        d_all = _rooted_dist(q, flat_pts, metric)
    mask = (jnp.repeat(needed_sub, pad, axis=1)
            & sub_valid.reshape(1, M * pad))
    d_all = jnp.where(mask, d_all, jnp.inf)
    i_all = jnp.broadcast_to(sub_indices.reshape(1, M * pad), (nq, M * pad))
    cat_d = jnp.concatenate([best_d, d_all], axis=1)
    cat_i = jnp.concatenate([best_i, i_all], axis=1)
    out_d, sel = select_k(cat_d, min(k, cat_d.shape[1]), select_min=True)
    return out_d, jnp.take_along_axis(cat_i, sel, axis=1)


def _finalize(out_d, out_i, k: int, metric: DistanceType):
    kk = out_d.shape[1]
    if kk < k:
        out_d = jnp.pad(out_d, ((0, 0), (0, k - kk)), constant_values=jnp.inf)
        out_i = jnp.pad(out_i, ((0, 0), (0, k - kk)), constant_values=-1)
    if metric == DistanceType.L2Expanded:
        out_d = out_d * out_d  # unrooted output for sqeuclidean parity
    return out_d, out_i


@tracing.range("ball_cover.knn")
def knn(
    index: BallCoverIndex,
    queries,
    k: int,
    n_init_probes: Optional[int] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN via the two-pass RBC search (reference:
    ball_cover::knn_query / all_knn_query)."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    L = index.n_landmarks
    pad = index.list_data.shape[1]
    p = int(n_init_probes or max(min(L, int(math.sqrt(L)) + 1), 1))
    p = min(max(p, 1), L)

    best_d, best_i, lm_d, probes = _pass1_jit(
        queries, index.landmarks, index.list_data, index.list_indices,
        index.list_sizes, index.metric, int(k), p)

    # host-side pruning decision: union of lists any query still needs
    # (the triangle-inequality bound |d(q,lm)| − radius > kth ⇒ skip). The
    # host sync buys real compute savings — pass 2's shape is M« L lists,
    # bucketed to powers of two to bound recompilation.
    kth = np.asarray(best_d[:, -1])
    lb = np.asarray(lm_d) - np.asarray(index.radii)[None, :]
    needed = lb < kth[:, None]
    scanned = np.zeros((queries.shape[0], L), bool)
    np.put_along_axis(scanned, np.asarray(probes), True, axis=1)
    needed &= ~scanned
    needed_lists = np.nonzero(needed.any(axis=0))[0]
    if len(needed_lists) == 0:
        return _finalize(best_d, best_i, int(k), index.metric)
    m_bucket = 1 << int(np.ceil(np.log2(len(needed_lists))))
    m_bucket = min(m_bucket, L)
    sub = np.full((m_bucket,), int(needed_lists[0]), np.int64)
    sub[: len(needed_lists)] = needed_lists
    needed_sub = needed[:, sub]
    needed_sub[:, len(needed_lists):] = False  # padding lists contribute 0
    sub_sizes = np.asarray(index.list_sizes)[sub]
    sub_valid = np.arange(pad)[None, :] < sub_sizes[:, None]
    out_d, out_i = _pass2_jit(
        queries, index.list_data[jnp.asarray(sub)],
        index.list_indices[jnp.asarray(sub)], jnp.asarray(sub_valid),
        jnp.asarray(needed_sub), best_d, best_i, index.metric, int(k))
    return _finalize(out_d, out_i, int(k), index.metric)


@functools.partial(jax.jit, static_argnames=("metric", "n_rows", "q_tile"))
def _eps_nn_jit(queries, list_data, list_valid, list_indices, eps,
                metric: DistanceType, n_rows: int, q_tile: int):
    nq = queries.shape[0]
    M, pad, dim = list_data.shape
    n_q_tiles = cdiv(nq, q_tile)
    qp = jnp.pad(queries, ((0, n_q_tiles * q_tile - nq), (0, 0)))
    flat_ids = jnp.maximum(list_indices.reshape(-1), 0)  # [M*pad]

    def q_body(qt):
        gf = list_data.reshape(M * pad, dim)
        d = _rooted_dist(qt, gf, metric).reshape(qt.shape[0], M, pad)
        hit = (d <= eps) & list_valid[None]
        flat_hit = hit.reshape(qt.shape[0], M * pad)
        adj = jnp.zeros((qt.shape[0], n_rows), bool)
        return adj.at[:, flat_ids].max(flat_hit)

    if n_q_tiles == 1:
        adj = q_body(qp)
    else:
        adj = jax.lax.map(
            q_body, qp.reshape(n_q_tiles, q_tile, -1)
        ).reshape(-1, n_rows)
    adj = adj[:nq]
    return adj, jnp.sum(adj, axis=1).astype(jnp.int32)


@tracing.range("ball_cover.eps_nn")
def eps_nn(index: BallCoverIndex, queries, eps: float,
           res: Optional[Resources] = None) -> Tuple[jax.Array, jax.Array]:
    """All neighbors within ``eps`` (reference: ball_cover::eps_nn,
    ball_cover-inl.cuh:313-365). ``eps`` is in the rooted metric (true L2 /
    haversine). Returns (adjacency [nq, n_rows] bool, vertex degrees [nq]
    int32) — the epsilon_neighborhood output shape.

    The RBC triangle-inequality bound prunes whole lists HOST-side (the
    union over queries, like knn()'s pass 2), so the device scan shrinks —
    with a small slack absorbing the expanded-L2 rounding error so a
    boundary neighbor is never dropped; in-range membership itself is an
    exact distance compare."""
    res = ensure_resources(res)
    queries = jnp.asarray(queries)
    L, pad, dim = index.list_data.shape
    # bound with error slack: lm_d − radius ≤ eps ⇒ list may contain hits
    lm_d = np.asarray(_rooted_dist(queries, index.landmarks, index.metric))
    slack = 1e-3 * np.abs(lm_d) + 1e-3 * np.asarray(index.radii)[None, :]         + 1e-5
    needed = (lm_d - np.asarray(index.radii)[None, :] - slack) <= eps
    needed_lists = np.nonzero(needed.any(axis=0))[0]
    nq = queries.shape[0]
    if len(needed_lists) == 0:
        adj = jnp.zeros((nq, index.n_rows), bool)
        return adj, jnp.zeros((nq,), jnp.int32)
    # bucket the subset size to a power of two (bounds recompilation)
    m_bucket = min(1 << int(np.ceil(np.log2(len(needed_lists)))), L)
    sub = np.full((m_bucket,), int(needed_lists[0]), np.int64)
    sub[: len(needed_lists)] = needed_lists
    sub_sizes = np.asarray(index.list_sizes)[sub]
    sub_valid = np.arange(pad)[None, :] < sub_sizes[:, None]
    sub_valid[len(needed_lists):] = False  # padding lists contribute 0
    per_q = m_bucket * pad * (dim + 8) * 4
    q_tile = int(np.clip(res.workspace_limit_bytes // max(per_q, 1), 1, 512))
    q_tile = min(q_tile, int(round_up_to(nq, 8)))
    if q_tile >= 8:
        q_tile -= q_tile % 8
    return _eps_nn_jit(queries, index.list_data[jnp.asarray(sub)],
                       jnp.asarray(sub_valid),
                       index.list_indices[jnp.asarray(sub)],
                       jnp.float32(eps), index.metric, index.n_rows,
                       max(q_tile, 1))
