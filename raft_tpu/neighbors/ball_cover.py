"""Random ball cover — landmark-pruned exact kNN.

Reference: ``raft::neighbors::ball_cover`` (neighbors/ball_cover-inl.cuh;
types ball_cover_types.hpp:35-92 — √n sampled landmarks, per-landmark sorted
member lists with radii; spatial/knn/detail/ball_cover/registers-inl.cuh —
triangle-inequality-pruned scan passes). Supported metrics: L2 family and
haversine, as in the reference.

TPU-native design: the index is an IVF-like padded layout ([L, pad, dim]
member lists + radii). Search is the two-pass RBC scheme recast for tiles:
pass 1 scans the ``n_init_probes`` closest landmarks' lists (dense batched
einsum) for a kth-distance estimate; pass 2 applies the triangle-inequality
lower bound |d(q, lm)| − radius_lm > kth → the landmark's list cannot
improve the result. Pruning on TPU pays at *tile* granularity: a list is
scanned only if any query in the tile still needs it, and per-query masks
keep exactness. Worst case degrades to brute force — exactly the RBC
guarantee."""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import (
    DistanceType,
    gathered_distances,
    haversine,
    l2_expanded,
    resolve_metric,
)
from raft_tpu.ops.select_k import select_k
from raft_tpu.utils.shape import cdiv, round_up_to

_SUPPORTED = (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
              DistanceType.Haversine)


class BallCoverIndex:
    """Landmarks + padded member lists + radii (ball_cover_types.hpp)."""

    def __init__(self, landmarks, list_data, list_indices, list_sizes, radii,
                 metric: DistanceType, n_rows: int):
        self.landmarks = landmarks  # [L, dim]
        self.list_data = list_data  # [L, pad, dim]
        self.list_indices = list_indices  # [L, pad]
        self.list_sizes = list_sizes  # [L]
        self.radii = radii  # [L] max member distance (rooted metric)
        self.metric = metric
        self.n_rows = n_rows

    @property
    def n_landmarks(self) -> int:
        return self.landmarks.shape[0]


def _rooted_dist(q, pts, metric: DistanceType):
    """Rooted (triangle-inequality-valid) distance matrix."""
    if metric == DistanceType.Haversine:
        return haversine(q, pts)
    return l2_expanded(q, pts, sqrt=True)


def build(
    dataset,
    metric="euclidean",
    n_landmarks: Optional[int] = None,
    res: Optional[Resources] = None,
) -> BallCoverIndex:
    """Build (reference: ball_cover::build_index): sample √n landmarks,
    assign every point to its closest landmark, record ball radii."""
    res = ensure_resources(res)
    m = resolve_metric(metric)
    if m not in _SUPPORTED:
        raise ValueError(f"ball_cover supports L2/haversine, got {m.name}")
    dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    L = int(n_landmarks or max(int(math.sqrt(n)), 1))

    from raft_tpu.ops import rng as rrng

    landmarks = rrng.subsample_rows(res.next_key(), dataset, L)
    d = _rooted_dist(dataset, landmarks, m)  # [n, L]
    labels = np.asarray(jnp.argmin(d, axis=1))
    dmin = np.asarray(jnp.min(d, axis=1))

    from raft_tpu import native

    sizes = np.bincount(labels, minlength=L).astype(np.int32)
    pad = max(int(round_up_to(max(int(sizes.max()), 1), 8)), 8)
    data, idxs, sizes = native.pack_lists(np.asarray(dataset), labels, L, pad)
    radii = np.zeros((L,), np.float32)
    np.maximum.at(radii, labels, dmin)
    return BallCoverIndex(landmarks, jnp.asarray(data), jnp.asarray(idxs),
                          jnp.asarray(sizes), jnp.asarray(radii), m, n)


@functools.partial(jax.jit, static_argnames=("metric", "k", "init_probes"))
def _search_jit(queries, landmarks, list_data, list_indices, list_sizes,
                radii, metric: DistanceType, k: int, init_probes: int):
    nq, dim = queries.shape
    L, pad, _ = list_data.shape
    q = queries.astype(jnp.float32)
    lm_d = _rooted_dist(q, landmarks, metric)  # [nq, L] rooted

    valid_slot = jnp.arange(pad)[None, :] < list_sizes[:, None]

    def scan_lists(probe_ids):
        """Scan given landmark lists: probe_ids [nq, P] → (d, ids)."""
        g_data = list_data[probe_ids]  # [nq, P, pad, dim]
        g_idx = list_indices[probe_ids]
        g_valid = valid_slot[probe_ids]
        flat = g_data.reshape(nq, -1, dim)
        if metric == DistanceType.Haversine:
            qd = jax.vmap(lambda qq, pts: haversine(qq[None], pts)[0])(
                q, flat)
        else:
            # rooted L2 keeps the triangle inequality valid for pruning
            qd = gathered_distances(q, flat, DistanceType.L2SqrtExpanded)
        d = qd.reshape(nq, -1)
        d = jnp.where(g_valid.reshape(nq, -1), d, jnp.inf)
        return d, g_idx.reshape(nq, -1)

    # ---- pass 1: closest landmarks give the kth-distance estimate
    _, probes = select_k(lm_d, init_probes, select_min=True)
    d1, i1 = scan_lists(probes)
    kk = min(k, d1.shape[1])
    best_d, best_sel = select_k(d1, kk, select_min=True)
    best_i = jnp.take_along_axis(i1, best_sel, axis=1)
    kth = best_d[:, -1]  # [nq]

    # ---- pass 2: triangle-inequality prune — a list can contain a closer
    # point only if d(q, lm) − radius_lm < kth
    lower_bound = lm_d - radii[None, :]
    needed = lower_bound < kth[:, None]  # [nq, L]
    # mask out already-scanned probes
    scanned = jnp.zeros((nq, L), bool).at[
        jnp.arange(nq)[:, None], probes].set(True)
    needed = needed & ~scanned
    # scan all lists directly from the query-invariant packed layout — one
    # [nq, L·pad] distance matrix, NO per-query data copy; the bound mask
    # delivers exactness and zeroes pruned columns (RBC's win on TPU is the
    # pass-1/kth-bound structure, not per-element skipping)
    flat_pts = list_data.reshape(L * pad, dim)
    if metric == DistanceType.Haversine:
        d_all = haversine(q, flat_pts)
    else:
        d_all = _rooted_dist(q, flat_pts, metric)
    flat_valid = valid_slot.reshape(1, L * pad)
    i_all = jnp.broadcast_to(
        list_indices.reshape(1, L * pad), (nq, L * pad))
    mask = jnp.repeat(needed, pad, axis=1) & flat_valid
    d_all = jnp.where(mask, d_all, jnp.inf)

    cat_d = jnp.concatenate([best_d, d_all], axis=1)
    cat_i = jnp.concatenate([best_i, i_all], axis=1)
    out_d, sel = select_k(cat_d, kk, select_min=True)
    out_i = jnp.take_along_axis(cat_i, sel, axis=1)
    if kk < k:
        out_d = jnp.pad(out_d, ((0, 0), (0, k - kk)),
                        constant_values=jnp.inf)
        out_i = jnp.pad(out_i, ((0, 0), (0, k - kk)), constant_values=-1)
    if metric == DistanceType.L2Expanded:
        out_d = out_d * out_d  # unrooted output for sqeuclidean parity
    return out_d, out_i


def knn(
    index: BallCoverIndex,
    queries,
    k: int,
    n_init_probes: Optional[int] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN via the two-pass RBC search (reference:
    ball_cover::knn_query / all_knn_query)."""
    ensure_resources(res)
    queries = jnp.asarray(queries)
    L = index.n_landmarks
    p = int(n_init_probes or max(min(L, int(math.sqrt(L)) + 1), 1))
    p = min(max(p, 1), L)
    return _search_jit(queries, index.landmarks, index.list_data,
                       index.list_indices, index.list_sizes, index.radii,
                       index.metric, int(k), p)
