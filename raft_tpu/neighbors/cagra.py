"""CAGRA — graph-based ANN index (build + greedy graph search).

Reference: ``raft::neighbors::cagra`` (neighbors/cagra.cuh:299-376; types
cagra_types.hpp:48-189; build detail/cagra/cagra_build.cuh:43-296; graph
pruning detail/cagra/graph_core.cuh; search plan detail/cagra/search_plan.cuh
+ single-CTA kernel detail/cagra/search_single_cta_kernel-inl.cuh).

Build = (1) all-neighbors kNN graph at ``intermediate_graph_degree`` via
IVF-PQ build+search+refine batches (cagra_build.cuh:43-160) or NN-descent
(:241-258); (2) ``optimize``: detour-count based pruning to ``graph_degree``
with reverse-edge augmentation (graph_core.cuh).

TPU-native design:
- **optimize** is pure gather/compare tensor algebra: the 2-hop detour count
  of edge (i→a) is #{b<a : G[i,a] ∈ G[G[i,b]]}, computed per node tile as a
  [tile, K, K, K] membership reduction (XLA fuses the compare+reduce; no
  atomics), then a stable top-``graph_degree`` by (count, rank). Reverse
  edges fill the tail slots, as in graph_core.cuh's rev-edge pass.
- **search** replaces the CTA-resident loop + hashmap visited-set with a
  functional beam state per query: an itopk buffer (dist, id) + a fixed-size
  expanded-parents list (the visited set — parents are the only nodes that
  matter for termination, mirroring search_single_cta's parent bitmask trick
  cagra_types: itopk entries carry a "visited" flag). Each iteration:
  pick ``search_width`` best unexpanded entries → gather their graph rows →
  mask already-expanded/duplicate targets → batched einsum distances (MXU) →
  merge into the buffer by a sort. Fixed ``max_iterations`` under
  ``lax.fori_loop`` with per-query done-masking keeps it one XLA program;
  queries batch along the leading axis (the batch analog of one CTA/query).
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core import serialize as ser
from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.neighbors.brute_force import fused_ineligible_reason
from raft_tpu.obs import explain as obs_explain
from raft_tpu.ops.distance import (
    DistanceType,
    gathered_distances,
    resolve_metric,
)
from raft_tpu.ops.select_k import merge_topk_dedup_flagged
from raft_tpu.utils.shape import (as_query_array, cdiv, pad_rows,
                                  query_bucket)


class BuildAlgo(enum.IntEnum):
    """reference: cagra_types.hpp graph_build_algo."""

    IVF_PQ = 0
    NN_DESCENT = 1


@dataclasses.dataclass
class IndexParams:
    """reference: cagra_types.hpp:48-63 index_params."""

    intermediate_graph_degree: int = 128
    graph_degree: int = 64
    build_algo: BuildAlgo = BuildAlgo.NN_DESCENT
    nn_descent_niter: int = 20
    metric: DistanceType = DistanceType.L2Expanded

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.metric not in (DistanceType.L2Expanded,
                               DistanceType.L2SqrtExpanded,
                               DistanceType.InnerProduct):
            raise ValueError(
                f"cagra supports L2Expanded/L2SqrtExpanded/InnerProduct, got "
                f"{self.metric.name}")


@dataclasses.dataclass
class SearchParams:
    """reference: cagra_types.hpp:66-116 search_params (the single-CTA-
    relevant subset; algo/team_size dispatch is an XLA concern here)."""

    itopk_size: int = 64
    search_width: int = 1
    max_iterations: int = 0  # 0 → auto heuristic (search_plan.cuh:31-123)
    num_random_samplings: int = 1
    rand_xor_mask: int = 0x128394
    #: None = fp32-accurate scan. "bfloat16" gathers beam candidates from a
    #: cached bf16 dataset copy (half the HBM gather bytes, single MXU pass)
    #: and exactly re-ranks the final buffer in fp32 — the TPU analog of the
    #: reference's half-precision compute_distance teams
    #: (detail/cagra/compute_distance.hpp).
    scan_dtype: Optional[object] = None
    #: "auto" routes the fused Pallas beam-search engine only where the
    #: committed PALLAS_PROBE artifact records a ``fused.cagra.fused_wins``
    #: verdict for this platform (conservative XLA default otherwise);
    #: "pallas"/"xla" force an engine. Same contract as the other fused
    #: families (docs/tuning.md fallback matrix).
    scan_mode: str = "auto"


class Index:
    """dataset + fixed-degree neighbor graph (cagra_types.hpp:127-189)."""

    def __init__(self, params: IndexParams, dataset, graph):
        self.params = params
        self.dataset = dataset  # [n, dim]
        self.graph = graph  # [n, graph_degree] int32
        self._dataset_bf16 = None  # lazy bf16 copy for scan_dtype searches

    def ensure_scan_dataset(self):
        if self._dataset_bf16 is None:
            self._dataset_bf16 = self.dataset.astype(jnp.bfloat16)
        return self._dataset_bf16

    @property
    def metric(self) -> DistanceType:
        return self.params.metric

    @property
    def size(self) -> int:
        return self.dataset.shape[0]

    @property
    def dim(self) -> int:
        return self.dataset.shape[1]

    @property
    def graph_degree(self) -> int:
        return self.graph.shape[1]


# ------------------------------------------------------------------ optimize


@functools.partial(jax.jit, static_argnames=("node_tile",))
def _detour_counts_jit(graph, node_tile: int):
    """count[i, a] = #{b < a : G[i,a] ∈ G[G[i,b]]} — 2-hop detour count
    (functional analog of graph_core.cuh's detourable-edge counting).

    Blocked formulation: the [tile, K, K] membership matrix is accumulated
    over chunks of the 2-hop axis c (compare + any fused per chunk), so
    scratch is O(K²) per node — the [tile, K, K, K] tensor of the naive
    broadcast never exists and ``member`` traffic drops by the chunk
    factor. Semantics are exactly any-over-c, so results match the naive
    formulation bit-for-bit (duplicate ids included)."""
    n, k = graph.shape
    n_tiles = cdiv(n, node_tile)
    pad = n_tiles * node_tile - n
    gp = jnp.pad(graph, ((0, pad), (0, 0)), constant_values=-1)
    chunk = min(16, k)
    kc = cdiv(k, chunk) * chunk  # pad c axis to a whole number of chunks

    def body(gt):  # [t, K] neighbor ids of one node tile
        t = gt.shape[0]
        nb = jnp.maximum(gt, 0)
        g2 = graph[nb.reshape(-1)].reshape(t, k, k)  # [t, b, c] 2-hop ids
        # invalid b rows (padded edges) contribute nothing
        g2 = jnp.where((gt >= 0)[:, :, None], g2, -1)
        g2 = jnp.pad(g2, ((0, 0), (0, 0), (0, kc - k)),
                     constant_values=-1)
        g2r = g2.reshape(t, k, kc // chunk, chunk)

        def step(j, member):
            col = jax.lax.dynamic_slice_in_dim(g2r, j, 1, axis=2)[:, :, 0]
            hit = jnp.any(col[:, :, :, None] == gt[:, None, None, :], axis=2)
            return member | hit  # member[t, b, a]

        member = jax.lax.fori_loop(
            0, kc // chunk, step, jnp.zeros((t, k, k), bool))
        member = member & (gt[:, None, :] >= 0) & (gt[:, :, None] >= 0)
        ltm = jnp.tril(jnp.ones((k, k), bool), -1).T  # [b, a]: b < a
        return (member & ltm[None]).sum(1).astype(jnp.int32)

    if n_tiles == 1:
        counts = body(gp)
    else:
        counts = jax.lax.map(
            body, gp.reshape(n_tiles, node_tile, k)).reshape(-1, k)
    return counts[:n]


@functools.partial(jax.jit, static_argnames=("out_degree",))
def _prune_jit(graph, counts, out_degree: int):
    """Keep the ``out_degree`` edges with the smallest (detour count, rank)
    per node (graph_core.cuh prune pass)."""
    n, k = graph.shape
    # composite key: count major, original rank minor; invalid edges last
    key = counts.astype(jnp.float32) * (k + 1) + jnp.arange(k)[None, :]
    key = jnp.where(graph >= 0, key, jnp.inf)
    _, sel = jax.lax.top_k(-key, out_degree)
    sel = jnp.sort(sel, axis=1)  # preserve rank order among survivors
    return jnp.take_along_axis(graph, sel, axis=1)


@functools.partial(jax.jit, static_argnames=("max_rev",))
def _reverse_graph_jit(graph, max_rev: int):
    """Reverse adjacency with per-node cap (graph_core.cuh rev-edge pass).
    Collision policy: random slot, later writers win."""
    n, d = graph.shape
    rev = jnp.full((n, max_rev), -1, jnp.int32)
    src = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, d))
    # invalid edges route out of bounds (dropped) instead of hitting node 0
    tgt = jnp.where(graph >= 0, graph, n)
    # deterministic pseudo-random slots: Knuth multiplicative hash in uint32
    slots = ((src.astype(jnp.uint32) * jnp.uint32(2654435761)
              + jnp.arange(d, dtype=jnp.uint32)[None, :] * jnp.uint32(40503))
             % jnp.uint32(max_rev)).astype(jnp.int32)
    rev = rev.at[tgt.reshape(-1), slots.reshape(-1)].set(
        src.reshape(-1), mode="drop")
    return rev


@functools.partial(jax.jit, static_argnames=())
def _augment_reverse_jit(pruned, rev):
    """Replace tail slots of the pruned graph with reverse edges not already
    present (graph_core.cuh: forward edges keep priority, reverse edges fill
    up to half the degree)."""
    n, d = pruned.shape
    n_rev = rev.shape[1]
    # dedupe reverse edges against forward ones
    dup = jnp.any(rev[:, :, None] == pruned[:, None, :], axis=2)
    rev = jnp.where(dup | (rev == jnp.arange(n)[:, None]), -1, rev)
    # compact valid reverse edges to the front
    order = jnp.argsort(rev < 0, axis=1, stable=True)
    rev_c = jnp.take_along_axis(rev, order, axis=1)
    n_valid = jnp.sum(rev_c >= 0, axis=1)
    n_replace = jnp.minimum(n_valid, d // 2)  # at most half the degree
    slot = jnp.arange(d)[None, :]
    take_rev = slot >= (d - n_replace)[:, None]
    rev_idx = jnp.clip(slot - (d - n_replace)[:, None], 0, n_rev - 1)
    out = jnp.where(take_rev,
                    jnp.take_along_axis(rev_c, rev_idx, axis=1), pruned)
    return out


@tracing.range("cagra.optimize")
def optimize(knn_graph, graph_degree: int,
             res: Optional[Resources] = None) -> jax.Array:
    """Prune an intermediate kNN graph to ``graph_degree`` (reference:
    cagra::optimize, cagra_build.cuh:266-285 → graph_core.cuh)."""
    res = ensure_resources(res)
    g = jnp.asarray(knn_graph, jnp.int32)
    n, k = g.shape
    if graph_degree >= k:
        return g
    # scratch per node: g2 + its padded copy (2×4·K² i32), member (K² bool),
    # and the per-chunk hit tensor ([K, 16, K] bool = 16·K²) ≈ 25·K² bytes;
    # modest tiles keep member cache/VMEM-resident (measured fastest 64-256)
    per_node = 25 * k * k
    node_tile = int(np.clip(res.workspace_limit_bytes // max(per_node, 1),
                            8, 256))
    node_tile -= node_tile % 8 or 0
    counts = _detour_counts_jit(g, max(node_tile, 8))
    pruned = _prune_jit(g, counts, int(graph_degree))
    rev = _reverse_graph_jit(pruned, int(graph_degree))
    return _augment_reverse_jit(pruned, rev)


# --------------------------------------------------------------------- build


@tracing.range("cagra.build")
def build(
    dataset,
    params: Optional[IndexParams] = None,
    res: Optional[Resources] = None,
) -> Index:
    """Build (reference: cagra::build, cagra.cuh → cagra_build.cuh:296):
    kNN graph at intermediate degree, then optimize to graph_degree."""
    params = params or IndexParams()
    res = ensure_resources(res)
    dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    k_inter = int(min(params.intermediate_graph_degree, n - 1))

    if params.build_algo == BuildAlgo.NN_DESCENT:
        from raft_tpu.neighbors import nn_descent

        nd_params = nn_descent.IndexParams(
            graph_degree=k_inter,
            intermediate_graph_degree=min(int(k_inter * 1.5), n - 1),
            max_iterations=params.nn_descent_niter,
            metric=params.metric,
        )
        knn = nn_descent.build(dataset, nd_params, res=res).graph
    else:
        knn = _build_knn_graph_ivf_pq(dataset, k_inter, params, res)

    graph = optimize(knn, int(min(params.graph_degree, k_inter)), res=res)
    return Index(params, dataset, graph)


def _build_knn_graph_ivf_pq(dataset, k_inter: int, params: IndexParams,
                            res: Resources) -> jax.Array:
    """IVF-PQ path (cagra_build.cuh:43-160): build ivf_pq on the dataset,
    batched self-search for top (k_inter+1), refine with exact distances,
    drop self."""
    from raft_tpu.neighbors import ivf_pq as ivf_pq_mod
    from raft_tpu.neighbors import refine as refine_mod

    n, dim = dataset.shape
    n_lists = int(np.clip(int(np.sqrt(n) * 2), 16, 8192))
    n_lists = min(n_lists, max(n // 64, 16))
    ipq = ivf_pq_mod.IndexParams(
        n_lists=n_lists,
        metric=(DistanceType.L2Expanded
                if params.metric != DistanceType.InnerProduct
                else DistanceType.InnerProduct),
        pq_dim=max(8, (dim // 2 + 7) // 8 * 8),
    )
    index = ivf_pq_mod.build(dataset, ipq, res=res)
    top = k_inter + 1
    sp = ivf_pq_mod.SearchParams(n_probes=max(min(n_lists, 32), n_lists // 16))
    # Device-resident pipeline (VERDICT r2 #5 — the old loop staged every
    # batch through np.asarray + a numpy argsort, a device→host→device
    # round-trip per 8192 rows; the reference keeps the whole build on
    # device, cagra_build.cuh:43-160): search → refine → jitted drop-self
    # all stay on device; the host loop only slices the next batch. The
    # tail batch is padded to the batch shape so every step reuses one
    # compiled program.
    batch = min(8192, n)
    dataset_j = jnp.asarray(dataset)
    parts = []
    for s in range(0, n, batch):
        hi = min(s + batch, n)
        q = jax.lax.dynamic_slice_in_dim(
            dataset_j, min(s, n - batch), batch)  # tail overlaps, static shape
        row0 = min(s, n - batch)
        _, cand = ivf_pq_mod.search(index, q, min(top * 2, n), sp, res=res)
        _, refined = refine_mod.refine(dataset_j, q, cand, top,
                                       metric=params.metric, res=res)
        keep = _drop_self_jit(refined, row0, k_inter)
        parts.append(keep if row0 == s else keep[s - row0:])
    return jnp.concatenate(parts, axis=0)


@functools.partial(jax.jit, static_argnames=("k_inter",))
def _drop_self_jit(refined, row0: int, k_inter: int):
    """Drop each row's own id where present, else the last slot — a stable
    argsort pushes the dropped slot past everything (device analog of the
    reference's self-exclusion in the graph fill)."""
    r = refined
    rows = jnp.arange(r.shape[0]) + row0
    is_self = r == rows[:, None]
    drop = jnp.where(is_self.any(1)[:, None], is_self,
                     jnp.arange(r.shape[1])[None, :] == r.shape[1] - 1)
    order = jnp.argsort(drop, axis=1, stable=True)
    keep = jnp.take_along_axis(r, order, axis=1)[:, :k_inter]
    return keep.astype(jnp.int32)


# -------------------------------------------------------------------- search


@functools.partial(
    jax.jit,
    static_argnames=("metric", "k", "itopk", "width", "max_iter",
                     "has_filter", "fast_scan"),
)
def _search_jit(queries, dataset, scan_data, graph, seed_ids, filter_words,
                metric: DistanceType, k: int, itopk: int, width: int,
                max_iter: int, has_filter: bool = False,
                fast_scan: bool = False):
    nq, dim = queries.shape
    n, degree = graph.shape
    minimize = metric != DistanceType.InnerProduct
    bad = jnp.inf

    qf = queries.astype(jnp.float32)
    # fast scan: bf16 query + bf16 gathered rows → gathered_distances picks
    # the single-pass MXU einsum (its HIGHEST request is fp32-data-only)
    q_scan = qf.astype(jnp.bfloat16) if fast_scan else qf
    # distances are minimized internally; IP negates, L2Sqrt defers the sqrt
    inner_metric = (DistanceType.L2Expanded
                    if metric == DistanceType.L2SqrtExpanded else metric)

    def dists_to(ids):  # ids [nq, C] → [nq, C] (minimized quantity)
        vecs = scan_data[jnp.maximum(ids, 0)]
        d = gathered_distances(q_scan, vecs, inner_metric)
        if metric == DistanceType.InnerProduct:
            d = -d
        if has_filter:
            # filtered nodes never enter the candidate buffer — the
            # reference's filtered search skips them at topk insertion
            safe = jnp.maximum(ids, 0)
            words = filter_words[jnp.minimum(
                safe // 32, filter_words.shape[0] - 1)]
            bits = ((words >> (safe % 32).astype(jnp.uint32)) & 1
                    ).astype(bool)
            d = jnp.where(bits, d, bad)
        return jnp.where(ids < 0, bad, d)

    # ---- init: random seed nodes (random_samplings, search_plan.cuh)
    init_ids = seed_ids  # [nq, S]
    init_d = dists_to(init_ids)
    init_fl = jnp.zeros_like(init_ids, dtype=bool)
    buf_ids, buf_d, buf_fl = merge_topk_dedup_flagged(
        init_ids, init_d, init_fl, itopk)

    # The "expanded" flag rides the itopk buffer instead of a growing visited
    # array (the reference's hashmap): the buffer is monotone under the
    # merge, so a node that falls out of the top-itopk can never re-enter —
    # buffer-resident flags are a complete visited set.
    rows = jnp.arange(nq)[:, None]

    # The per-iteration merge is THE cost of the TPU beam walk (r3 on-chip:
    # sort-class primitives run at a few GB/s effective). The old body paid
    # three of them per hop — top_k(parent pick), argsort-by-id (dedup),
    # top_k(merge). This body keeps the buffer SORTED BY DISTANCE as a loop
    # invariant (merge_topk_dedup_flagged establishes it at init), so:
    # - parent pick is an argmin (width=1) or a tiny top_k over itopk;
    # - dedup happens BEFORE the merge with two small membership compares
    #   (targets vs buffer, targets vs earlier targets) — valid because
    #   the buffer is dup-free by induction, so post-concat adjacency
    #   tricks aren't needed;
    # - the merge is ONE lax.sort of the [itopk + W·D] concat, sliced back
    #   to itopk. Same semantics as merge_topk_dedup_flagged (a target
    #   equal to a buffer entry is dropped, keeping the buffer copy's
    #   expanded flag — the OR of the copies' flags, since target copies
    #   are never flagged).
    wd = width * degree

    def body(state):
        it, buf_ids, buf_d, buf_fl, done = state
        # pickup_next_parents: best `width` unexpanded buffer entries
        cand_d = jnp.where(buf_fl | (buf_ids < 0), bad, buf_d)
        if width == 1:
            p_sel = jnp.argmin(cand_d, axis=1)[:, None]
            valid_p = jnp.isfinite(
                jnp.take_along_axis(cand_d, p_sel, axis=1))
        else:
            p_d, p_sel = jax.lax.top_k(-cand_d, width)
            valid_p = jnp.isfinite(-p_d)
        parents = jnp.take_along_axis(buf_ids, p_sel, axis=1)  # [nq, W]
        valid_p = valid_p & (parents >= 0) & ~done[:, None]
        has_parent = valid_p[:, 0]
        newly_done = ~has_parent
        parents = jnp.where(valid_p, parents, -1)

        # mark picked parents expanded in the buffer
        mark = jnp.zeros_like(buf_fl).at[rows, p_sel].max(valid_p)
        buf_fl = buf_fl | mark

        # expand: gather graph rows of parents
        targets = graph[jnp.maximum(parents, 0)].reshape(-1, wd)
        targets = jnp.where(
            jnp.repeat(parents < 0, degree, axis=1), -1, targets)
        # drop targets already in the buffer (the visited-set test) and
        # copies among the targets themselves (parents sharing neighbors)
        in_buf = jnp.any(targets[:, :, None] == buf_ids[:, None, :], axis=2)
        if wd > 1:
            earlier = jnp.tril(jnp.ones((wd, wd), bool), -1)
            dup_t = jnp.any((targets[:, :, None] == targets[:, None, :])
                            & earlier[None], axis=2)
            in_buf = in_buf | dup_t
        targets = jnp.where(in_buf, -1, targets)
        t_d = dists_to(targets)

        new_d = jnp.concatenate([buf_d, t_d], axis=1)
        new_ids = jnp.concatenate([buf_ids, targets], axis=1)
        new_fl = jnp.concatenate(
            [buf_fl, jnp.zeros_like(targets, dtype=bool)], axis=1)
        sd, si, sf = jax.lax.sort((new_d, new_ids, new_fl), dimension=1,
                                  num_keys=1)
        # frozen queries keep their state
        keep = done[:, None]
        buf_ids = jnp.where(keep, buf_ids, si[:, :itopk])
        buf_d = jnp.where(keep, buf_d, sd[:, :itopk])
        buf_fl = jnp.where(keep, buf_fl, sf[:, :itopk])
        done = done | newly_done
        return it + 1, buf_ids, buf_d, buf_fl, done

    # while_loop with an all-done exit instead of a fixed fori_loop: once
    # every query's buffer has no unexpanded parent, further iterations
    # are pure wasted HBM gathers (the batch converges well before the
    # max_iter bound in practice; the reference's terminate_flag plays the
    # same role, search_single_cta_kernel-inl.cuh)
    done0 = jnp.zeros((nq,), bool)
    _, buf_ids, buf_d, buf_fl, _ = jax.lax.while_loop(
        lambda s: (s[0] < max_iter) & ~jnp.all(s[4]),
        body, (jnp.int32(0), buf_ids, buf_d, buf_fl, done0))

    if fast_scan:
        # exact fp32 re-rank of the whole itopk buffer (nq×itopk×dim — tiny
        # next to the beam walk) so returned order/distances are exact
        vecs = dataset[jnp.maximum(buf_ids, 0)]
        ex = gathered_distances(qf, vecs, inner_metric)
        if metric == DistanceType.InnerProduct:
            ex = -ex
        ex = jnp.where(buf_ids < 0, bad, ex)
        ex, sel = jax.lax.top_k(-ex, k)
        out_d, out_i = -ex, jnp.take_along_axis(buf_ids, sel, axis=1)
    else:
        out_d, out_i = buf_d[:, :k], buf_ids[:, :k]
    if metric == DistanceType.InnerProduct:
        out_d = -out_d
    elif metric == DistanceType.L2SqrtExpanded:
        out_d = jnp.sqrt(jnp.maximum(out_d, 0.0))
    return out_d, out_i


#: public traceable-core name — the cross-package contract for the
#: sharded engine (parallel/sharded.py); the underscore spelling stays
#: package-private (R004 layering, docs/analysis.md)
search_core = _search_jit


def _search_fused_core(queries, dataset, graph, seed_ids,
                       metric: DistanceType, k: int, itopk: int, width: int,
                       max_iter: int, ct: int, interpret: bool = False):
    """Fused-engine traceable core: the whole beam walk inside one Pallas
    kernel (``ops.pallas_kernels.fused_cagra_topk`` — VMEM-resident beam
    state, in-kernel gather DMAs), plus the metric epilogue the kernel
    defers (it minimizes squared L2; L2SqrtExpanded takes the sqrt here,
    exactly as ``_search_jit`` does on its sliced buffer). Eligibility —
    L2 metrics, unfiltered, fp32, itopk ≤ 1024 — is the caller's job
    (``fused_ineligible_reason``); semantics inside that envelope are
    bit-checked against ``search_core`` (tests/test_pallas_fused.py)."""
    from raft_tpu.ops import pallas_kernels as pk

    v, i = pk.fused_cagra_topk(queries, dataset, graph, seed_ids, k,
                               itopk, width, max_iter, ct=ct,
                               interpret=interpret)
    if metric == DistanceType.L2SqrtExpanded:
        v = jnp.sqrt(jnp.maximum(v, 0.0))
    return v, i


_search_fused_jit = jax.jit(
    _search_fused_core,
    static_argnames=("metric", "k", "itopk", "width", "max_iter", "ct",
                     "interpret"),
)

#: public traceable-core name for the fused path (R004; audited by
#: graftcheck --jaxpr-audit at the canonical 1M shape, interpret=True)
search_fused_core = _search_fused_core


def resolve_search_plan(params: SearchParams, k: int, size: int):
    """The resolved beam plan — (itopk, width, max_iter, n_seeds) — shared
    by both engines' dispatch records so EXPLAIN artifacts are replayable
    (the ``max_iterations=0`` auto-clip and the seed-pool sizing used to
    be recomputed inline and never surfaced uniformly)."""
    itopk = max(int(params.itopk_size), int(k))
    width = max(int(params.search_width), 1)
    max_iter = int(params.max_iterations)
    if max_iter <= 0:
        # auto heuristic (search_plan.cuh:31-123): enough hops to drain the
        # itopk buffer, bounded
        max_iter = int(np.clip(itopk // width + 10, 16, 200))
    n_rand = max(int(params.num_random_samplings), 1)
    n_seeds = min(max(itopk, 32) * n_rand, int(size))
    return itopk, width, max_iter, n_seeds


@tracing.range("cagra.search")
def search(
    index: Index,
    queries,
    k: int,
    params: Optional[SearchParams] = None,
    filter=None,
    res: Optional[Resources] = None,
    explain: bool = False,
):
    """Greedy graph search (reference: cagra::search, cagra.cuh:299 →
    search_single_cta_kernel-inl.cuh). Returns (distances, indices); with
    ``explain=True`` a third element carries the dispatch
    :class:`raft_tpu.obs.explain.ExplainRecord` — which engine ran the
    beam walk (fused Pallas vs XLA) and why, plus the resolved beam plan
    (itopk/width/max_iter/n_seeds) in both branches so the artifact is
    replayable.

    ``filter`` is an optional :class:`raft_tpu.core.bitset.Bitset` over
    dataset row ids; cleared bits are excluded from results (and from the
    candidate buffer, as in the reference's filtered search)."""
    params = params or SearchParams()
    res = ensure_resources(res)
    queries = as_query_array(queries)  # host inputs stay host-side: the
    if queries.ndim == 1:              # jit call transfers the padded
        queries = queries[None]        # batch in ONE dispatch
    if queries.shape[1] != index.dim:
        raise ValueError(
            f"query dim {queries.shape[1]} != index dim {index.dim}")
    nq = queries.shape[0]
    queries = pad_rows(queries, query_bucket(nq))  # serving batch bucket
    # num_random_samplings multiplies the random seed pool (the reference's
    # random init batches, search_plan.cuh) — the recall lever when the
    # dataset has many well-separated clusters: a kNN graph cannot walk
    # across disconnected components, so a query's component must be
    # seeded. Seeds beyond itopk are fine: they enter through the merge.
    itopk, width, max_iter, n_seeds = resolve_search_plan(
        params, k, index.size)
    # deterministic pseudo-random seeds per query (rand_xor_mask analog):
    # a stratified lattice rotated by a per-row draw. Row q's seed set
    # depends only on q and the mask — never on the (padded) batch size —
    # so batch 1 and batch 64 see the same seeds for the same query, and
    # the lattice guarantees every size/n_seeds stretch of the dataset
    # (hence every graph component that large) holds a seed, which a
    # bare uniform draw cannot promise on clustered data.
    base = jnp.asarray(
        (np.arange(n_seeds, dtype=np.int64) * index.size) // n_seeds,
        jnp.int32)
    key = jax.random.key(params.rand_xor_mask & 0x7FFFFFFF)
    offsets = jax.vmap(
        lambda row: jax.random.randint(
            jax.random.fold_in(key, row), (), 0, index.size, jnp.int32)
    )(jnp.arange(queries.shape[0], dtype=jnp.uint32))
    seed_ids = (base[None, :] + offsets[:, None]) % index.size
    fast_scan = params.scan_dtype is not None
    if fast_scan:
        if jnp.dtype(params.scan_dtype) != jnp.bfloat16:
            raise ValueError(
                f"scan_dtype={params.scan_dtype!r}: only bfloat16 is "
                "supported")
        if index.dataset.dtype != jnp.float32:
            raise ValueError("scan_dtype requires an fp32 dataset")
    scan_data = index.ensure_scan_dataset() if fast_scan else index.dataset
    from raft_tpu.ops import pallas_kernels as pk

    scan_mode = getattr(params, "scan_mode", "auto")
    if scan_mode not in ("auto", "xla", "pallas"):
        raise ValueError(
            f"scan_mode={scan_mode!r}: expected 'auto', 'xla' or 'pallas'")
    # ---- fused Pallas beam-search engine (the VMEM-resident beam carry).
    # Fallback matrix (docs/tuning.md): L2 metrics, no filter (no in-carry
    # filter epilogue), no bf16 fast scan, itopk ≤ 1024.
    use_fused, fused_interp, dreason = pk.fused_dispatch_explained(
        "cagra", scan_mode)
    ineligible = fused_ineligible_reason(
        index.metric, index.dataset.dtype, itopk, filter is not None,
        fast_scan)
    ex_params = {"k": int(k), "nq": nq, "bucket": queries.shape[0],
                 "metric": index.metric.name, "graph_degree":
                 index.graph_degree, "fast_scan": fast_scan}
    # resolved beam plan recorded identically by BOTH engines — an EXPLAIN
    # artifact replays without re-deriving the auto-clips
    ex_plan = {"itopk": itopk, "search_width": width, "max_iter": max_iter,
               "n_seeds": n_seeds}
    with contextlib.ExitStack() as stack:
        cap = stack.enter_context(obs_explain.capture()) if explain else None
        if use_fused and ineligible is None:
            ct = pk.plan_fused_cagra_tile(
                itopk, width, index.graph_degree, index.dim, n_seeds)
            obs_explain.record_dispatch(
                "cagra", scan_mode, "pallas", dreason, params=ex_params,
                plan={**ex_plan, "ct": ct, "interpret": fused_interp,
                      "predicted_workspace_bytes":
                      pk.fused_cagra_workspace_bytes(
                          queries.shape[0], index.size, index.dim,
                          index.graph_degree, itopk, width, n_seeds,
                          int(k), ct)})
            v, i = _search_fused_jit(
                queries, index.dataset, index.graph, seed_ids,
                index.metric, int(k), itopk, width, max_iter, ct,
                fused_interp)
        else:
            reason = ineligible if (use_fused and ineligible) else dreason
            obs_explain.record_dispatch(
                "cagra", scan_mode, "xla", reason, params=ex_params,
                plan=ex_plan)
            v, i = _search_jit(
                queries, index.dataset, scan_data, index.graph, seed_ids,
                filter.words if filter is not None
                else jnp.zeros((0,), jnp.uint32),
                index.metric, int(k), itopk, width, max_iter,
                filter is not None, fast_scan)
    if explain:
        return v[:nq], i[:nq], cap.last
    return v[:nq], i[:nq]


_SERIAL_VERSION = 1


def serialize(index: Index, file, include_dataset: bool = True) -> None:
    """reference: detail/cagra/cagra_serialize.cuh. Paths are written
    atomically (tmp + os.replace) with per-record crc framing."""
    with ser.writer_for(file) as stream:
        w = ser.IndexWriter(stream, "cagra", _SERIAL_VERSION)
        w.scalar(int(index.metric), "<i4")
        w.scalar(index.graph_degree, "<i4")
        w.scalar(1 if include_dataset else 0, "<i4")
        w.array(index.graph)
        if include_dataset:
            w.array(index.dataset)
        w.finish()


def deserialize(file, dataset=None, res: Optional[Resources] = None) -> Index:
    ensure_resources(res)
    with ser.reader_for(file) as stream:
        r = ser.IndexReader(stream, "cagra", _SERIAL_VERSION)
        metric = DistanceType(r.scalar())
        graph_degree = r.scalar()
        has_ds = bool(r.scalar())
        graph = jnp.asarray(r.array())
        if has_ds:
            ds = jnp.asarray(r.array())
        elif dataset is not None:
            ds = jnp.asarray(dataset)
        else:
            raise ValueError(
                "index file has no dataset; pass dataset= to deserialize")
        r.finish()
        params = IndexParams(graph_degree=graph_degree, metric=metric)
        return Index(params, ds, graph)
