"""pylibraft-parity alias: pylibraft.neighbors.rbc (random ball cover)."""

from raft_tpu.neighbors.ball_cover import *  # noqa: F401,F403
from raft_tpu.neighbors.ball_cover import BallCoverIndex, build, knn  # noqa: F401

__all__ = ["BallCoverIndex", "build", "knn"]
