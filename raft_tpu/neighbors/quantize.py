"""Scalar quantization — fp32 → int8 datasets for bandwidth-bound search.

Reference analog: the legacy quantized-kNN path (spatial/knn/detail/
ann_quantized.cuh) — 8-bit scalar quantization in front of the ANN indexes.
TPU-native framing: int8 datasets already take the single-pass MXU path in
brute_force / ivf_flat (int8 values are bf16-exact), so quantization is a
pure host-side transform: per-dimension affine codes with quantile-trimmed
ranges (outliers saturate instead of stretching the grid).

Typical use::

    sq = quantize.ScalarQuantizer.fit(train, quantile=0.99)
    db_i8 = sq.transform(dataset)
    index = brute_force.build(db_i8, metric="sqeuclidean")
    d, i = brute_force.search(index, sq.transform(queries), k)

Distances come back in the quantized domain; rank order is what matters
(recall vs the fp32 ground truth is the acceptance metric, as for PQ).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class ScalarQuantizer:
    """Per-dimension affine int8 quantizer: code = round((x - lo)/scale) - 128."""

    lo: np.ndarray  # [dim] f32
    scale: np.ndarray  # [dim] f32 (width / 255)

    @classmethod
    def fit(cls, train, quantile: float = 1.0) -> "ScalarQuantizer":
        """Learn per-dim ranges from a training sample. ``quantile`` < 1
        trims tails symmetrically (e.g. 0.99 ignores the extreme 1%), so a
        few outliers don't waste code space."""
        x = np.asarray(train, np.float32)
        if not (0.5 < quantile <= 1.0):
            # quantile is the UPPER tail point; ≤ 0.5 would invert lo/hi
            raise ValueError(
                f"quantile must be in (0.5, 1], got {quantile}")
        if quantile < 1.0:
            lo = np.quantile(x, 1.0 - quantile, axis=0)
            hi = np.quantile(x, quantile, axis=0)
        else:
            lo = x.min(axis=0)
            hi = x.max(axis=0)
        scale = np.maximum((hi - lo).astype(np.float32), 1e-12) / 255.0
        return cls(lo=lo.astype(np.float32), scale=scale)

    @property
    def dim(self) -> int:
        return self.lo.shape[0]

    def transform(self, x) -> np.ndarray:
        """fp32 [n, dim] → int8 codes (out-of-range values saturate)."""
        x = np.asarray(x, np.float32)
        q = np.rint((x - self.lo) / self.scale) - 128.0
        return np.clip(q, -128, 127).astype(np.int8)

    def inverse_transform(self, codes) -> np.ndarray:
        """int8 codes → fp32 reconstruction (grid centers)."""
        c = np.asarray(codes, np.float32)
        return (c + 128.0) * self.scale + self.lo
