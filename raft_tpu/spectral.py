"""Top-level ``raft_tpu.spectral`` — alias of :mod:`raft_tpu.sparse.spectral`
(reference: ``raft::spectral`` lives beside, not inside, sparse; both import
paths work here)."""

from raft_tpu.sparse.spectral import (  # noqa: F401
    analyze_partition,
    fit_embedding,
    modularity_maximization,
    partition,
)

__all__ = ["analyze_partition", "fit_embedding", "modularity_maximization",
           "partition"]
