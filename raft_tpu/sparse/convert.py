"""Sparse format conversions — coo↔csr↔dense.

Reference: ``raft::sparse::convert`` (sparse/convert/csr.cuh, coo.cuh,
dense.cuh).

TPU-native design: conversions are sorts + segment counts (XLA-native);
densification is a scatter. COO→CSR requires row-sorted input (documented,
like the reference's expectation of canonical ordering); ``coo_sort``
provides it."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.sparse.types import COO, CSR


def coo_sort(coo: COO) -> COO:
    """Sort entries by (row, col) — sparse/op/sort.cuh analog. Two stable
    int32 argsorts (col minor, row major) — no int64 key, so no silent
    x64-disabled overflow for large shapes."""
    o1 = jnp.argsort(coo.cols, stable=True)
    o2 = jnp.argsort(coo.rows[o1], stable=True)
    order = o1[o2]
    return COO(coo.rows[order], coo.cols[order], coo.data[order], coo.shape)


def coo_to_csr(coo: COO, assume_sorted: bool = False) -> CSR:
    """sparse/convert/csr.cuh: row counts → prefix sum."""
    c = coo if assume_sorted else coo_sort(coo)
    counts = jnp.zeros((coo.shape[0],), jnp.int32).at[c.rows].add(1)
    indptr = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    return CSR(indptr, c.cols, c.data, c.shape)


def csr_to_coo(csr: CSR) -> COO:
    """sparse/convert/coo.cuh: expand indptr to row ids."""
    return COO(csr.row_ids(), csr.indices, csr.data, csr.shape)


def csr_to_dense(csr: CSR) -> jax.Array:
    """sparse/convert/dense.cuh. Duplicate coordinates sum (standard COO
    semantics) — this also makes zero-data padding entries harmless."""
    out = jnp.zeros(csr.shape, csr.dtype)
    return out.at[csr.row_ids(), csr.indices].add(csr.data)


def coo_to_dense(coo: COO) -> jax.Array:
    out = jnp.zeros(coo.shape, coo.dtype)
    return out.at[coo.rows, coo.cols].add(coo.data)


def dense_to_csr(dense, nnz: Optional[int] = None) -> CSR:
    """Dense → CSR with a static nnz (TPU shapes must be static: callers pass
    the known/max nnz; surplus slots become explicit zeros at (0, 0) —
    harmless under duplicate-sum densification)."""
    dense = jnp.asarray(dense)
    n, m = dense.shape
    mask = dense != 0
    total = int(jnp.sum(mask)) if nnz is None else int(nnz)
    flat = mask.reshape(-1)
    idx = jnp.nonzero(flat, size=total, fill_value=-1)[0]
    is_real = idx >= 0
    safe = jnp.maximum(idx, 0)
    rows = jnp.where(is_real, safe // m, 0).astype(jnp.int32)
    cols = jnp.where(is_real, safe % m, 0).astype(jnp.int32)
    data = jnp.where(is_real, dense.reshape(-1)[safe], 0)
    # padding slots don't count toward any row's structure
    counts = jnp.zeros((n,), jnp.int32).at[rows].add(
        is_real.astype(jnp.int32))
    indptr = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)])
    return CSR(indptr, cols, data, (n, m))
