"""Sparse selection — per-row top-k over CSR score matrices.

Reference: ``raft::sparse::selection`` (sparse/selection/select_k.cuh) —
select_k over the CSR output of sparse pairwise distances.

TPU-native design: densify rows tile-by-tile (absent entries fill with the
metric's worst value) and run the dense ``select_k``; TPU top-k wants dense
lanes anyway, and sparse score rows are short."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.ops.select_k import select_k as dense_select_k
from raft_tpu.sparse.types import CSR


def select_k(csr: CSR, k: int, select_min: bool = True
             ) -> Tuple[jax.Array, jax.Array]:
    """Top-k values + column ids per CSR row (missing entries rank last).

    Returns (values [n_rows, k], indices [n_rows, k]); rows with fewer than
    k stored entries pad with (+inf/-inf, -1).
    """
    n_rows, n_cols = csr.shape
    fill = jnp.inf if select_min else -jnp.inf
    dense = jnp.full((n_rows, n_cols), fill, csr.data.dtype)
    rows = csr.row_ids()
    dense = dense.at[rows, csr.indices].set(csr.data)
    kk = min(k, n_cols)
    v, i = dense_select_k(dense, kk, select_min=select_min)
    ok = jnp.isfinite(v)
    i = jnp.where(ok, i, -1)
    if kk < k:
        v = jnp.pad(v, ((0, 0), (0, k - kk)), constant_values=fill)
        i = jnp.pad(i, ((0, 0), (0, k - kk)), constant_values=-1)
    return v, i
