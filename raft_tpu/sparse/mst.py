"""Minimum spanning tree — Borůvka over an edge list.

Reference: ``raft::sparse::solver::mst`` (sparse/mst/mst_solver.cuh +
detail/mst_solver_inl.cuh — a GPU Borůvka with per-supervertex min-edge
selection, used by single-linkage clustering).

TPU-native design: the GPU's atomic min-edge race is replaced by functional
segment scatter-mins; supervertex contraction is pointer jumping. Each round:
(1) per-component minimum outgoing edge via two scatter-min passes (weight,
then canonical-edge-id tie-break — the strict total order that prevents
tie cycles), (2) union via parent[max_comp] = min_comp (always points to a
smaller label → acyclic), (3) log-step pointer jumping to flatten labels.
ceil(log2 n)+1 rounds suffice (components at least halve). All loops are
``lax.fori_loop`` with static trip counts — one XLA program."""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.sparse.types import COO


@functools.partial(jax.jit, static_argnames=("n", "n_rounds", "n_jumps"))
def _boruvka_jit(u, v, w, n: int, n_rounds: int, n_jumps: int):
    ne = u.shape[0]
    big_w = jnp.inf
    # direction-invariant lexicographic tie-break key, int32-safe: the
    # canonical endpoint pair (lo, hi) broken in two scatter passes
    lo_e = jnp.minimum(u, v)
    hi_e = jnp.maximum(u, v)

    def round_body(_, state):
        comp, selected = state
        cu = comp[u]
        cv = comp[v]
        alive = cu != cv
        w_eff = jnp.where(alive, w, big_w)
        # pass 1: per-component min outgoing weight
        min_w = jnp.full((n,), big_w, w.dtype).at[cu].min(w_eff)
        is_min = alive & (w_eff == min_w[cu])
        # passes 2+3: lexicographic (lo, hi) tie break — identical for both
        # directions of an edge, strict total order within a component
        lo_eff = jnp.where(is_min, lo_e, n)
        min_lo = jnp.full((n,), n, jnp.int32).at[cu].min(lo_eff)
        is_min2 = is_min & (lo_e == min_lo[cu])
        hi_eff = jnp.where(is_min2, hi_e, n)
        min_hi = jnp.full((n,), n, jnp.int32).at[cu].min(hi_eff)
        chosen = is_min2 & (hi_e == min_hi[cu])
        selected = selected | chosen
        # union: larger component label points at the smaller
        lo = jnp.minimum(cu, cv)
        hi = jnp.maximum(cu, cv)
        parent = jnp.arange(n, dtype=jnp.int32)
        parent = parent.at[jnp.where(chosen, hi, n)].min(
            jnp.where(chosen, lo, n), mode="drop")
        # pointer jumping flattens the union forest
        parent = jax.lax.fori_loop(
            0, n_jumps, lambda i, p: p[p], parent)
        comp = parent[comp]
        return comp, selected

    comp0 = jnp.arange(n, dtype=jnp.int32)
    sel0 = jnp.zeros((ne,), bool)
    comp, selected = jax.lax.fori_loop(
        0, n_rounds, round_body, (comp0, sel0))
    return comp, selected


def mst(
    graph: COO,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Compute the MST (forest, if disconnected) of a weighted undirected
    graph given as a symmetric COO edge list.

    Returns (src, dst, weight) arrays of the selected edges in canonical
    (src < dst) direction — padded with (-1, -1, inf) to a static n-1 length
    (reference: Graph_COO output of mst_solver.cuh).
    """
    n = graph.shape[0]
    u = jnp.asarray(graph.rows, jnp.int32)
    v = jnp.asarray(graph.cols, jnp.int32)
    w = jnp.asarray(graph.data, jnp.float32)
    n_rounds = max(int(math.ceil(math.log2(max(n, 2)))) + 1, 1)
    n_jumps = n_rounds
    comp, selected = _boruvka_jit(u, v, w, n, n_rounds, n_jumps)

    # extract canonical selected edges (dedup the two directions) on host —
    # int64 keys need numpy (jax x64 is disabled by default)
    un = np.asarray(u)
    vn = np.asarray(v)
    wn = np.asarray(w)
    sel = np.asarray(selected)
    key = (np.minimum(un, vn).astype(np.int64) * n
           + np.maximum(un, vn).astype(np.int64))
    e = np.nonzero(sel)[0]
    _, first = np.unique(key[e], return_index=True)
    e = e[np.sort(first)]
    m = n - 1
    src = np.full((m,), -1, np.int32)
    dst = np.full((m,), -1, np.int32)
    wt = np.full((m,), np.inf, np.float32)
    cnt = min(len(e), m)
    src[:cnt] = np.minimum(un[e[:cnt]], vn[e[:cnt]])
    dst[:cnt] = np.maximum(un[e[:cnt]], vn[e[:cnt]])
    wt[:cnt] = wn[e[:cnt]]
    return jnp.asarray(src), jnp.asarray(dst), jnp.asarray(wt)
