"""Sparse eigensolvers over CSR operators.

Reference: ``raft::sparse::solver`` (sparse/solver/lanczos.cuh —
``lanczos_compute_smallest_eigenvectors``, the solver behind spectral
partitioning/embedding) and the MST solver (sparse/solver/mst.cuh, which
lives in :mod:`raft_tpu.sparse.mst` here).

TPU-native design: the Krylov iteration itself is dense (ops.linalg.lanczos,
a lax.fori_loop of matvecs); sparsity enters only through the CSR matvec
(segment-sum spmv), which XLA executes as scatter-adds. For the small
spectral problems these solvers serve, that is the right split."""

from __future__ import annotations

from typing import Optional, Tuple

import jax

from raft_tpu.ops import linalg as rlinalg
from raft_tpu.sparse.linalg import spmv
from raft_tpu.sparse.types import CSR


def lanczos_eigsh(
    a: CSR,
    k: int,
    key=None,
    ncv: Optional[int] = None,
    which: str = "smallest",
) -> Tuple[jax.Array, jax.Array]:
    """k extremal eigenpairs of a symmetric CSR matrix via Lanczos
    (sparse/solver/lanczos.cuh analog). Returns (eigenvalues [k],
    eigenvectors [n, k])."""
    n = a.shape[0]
    if a.shape[0] != a.shape[1]:
        raise ValueError(f"matrix must be square, got {a.shape}")
    return rlinalg.lanczos(lambda v: spmv(a, v), n, k, key=key, ncv=ncv,
                           which=which)


def lanczos_smallest(a: CSR, k: int, key=None,
                     ncv: Optional[int] = None):
    """``lanczos_compute_smallest_eigenvectors`` parity wrapper."""
    return lanczos_eigsh(a, k, key=key, ncv=ncv, which="smallest")


def lanczos_largest(a: CSR, k: int, key=None, ncv: Optional[int] = None):
    """``computeLargestEigenvectors`` (linalg/lanczos.cuh) parity wrapper."""
    return lanczos_eigsh(a, k, key=key, ncv=ncv, which="largest")
