"""Sparse linear algebra — spmm/sddmm/degree/norm/transpose/symmetrize/
laplacian.

Reference: ``raft::sparse::linalg`` (sparse/linalg/spmm.hpp — cuSPARSE SpMM;
sddmm.hpp; degree.cuh; norm.cuh; symmetrize.cuh; transpose.cuh;
laplacian spectral helpers under spectral/matrix_wrappers.hpp).

TPU-native design: SpMM with a dense RHS is a segment-sum of gathered rows —
`dense[cols] * data` scatter-added by row id; that is the pattern XLA/TPU
executes well (no cuSPARSE analog needed). SDDMM samples a dense product at
nnz positions with two row gathers and an einsum. All ops take/return the
functional CSR/COO containers."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.sparse.types import COO, CSR
from raft_tpu.sparse.convert import coo_to_csr, csr_to_coo


def spmm(csr: CSR, dense, alpha: float = 1.0) -> jax.Array:
    """CSR [n, m] @ dense [m, d] → [n, d] (sparse/linalg/spmm.hpp).

    Gather-scatter formulation: each nnz contributes data·dense[col] to its
    row — one gather + one segment scatter-add, fully fused by XLA."""
    dense = jnp.asarray(dense)
    rows = csr.row_ids()
    contrib = csr.data[:, None] * dense[csr.indices]  # [nnz, d]
    out = jnp.zeros((csr.n_rows, dense.shape[1]), contrib.dtype)
    return alpha * out.at[rows].add(contrib)


def spmv(csr: CSR, vec) -> jax.Array:
    """CSR @ vector."""
    vec = jnp.asarray(vec)
    rows = csr.row_ids()
    contrib = csr.data * vec[csr.indices]
    return jnp.zeros((csr.n_rows,), contrib.dtype).at[rows].add(contrib)


def sddmm(a, b, structure: CSR, alpha: float = 1.0) -> CSR:
    """Sampled dense-dense matmul (sparse/linalg/sddmm.hpp): values of
    A·Bᵀ at the nnz positions of ``structure``."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    rows = structure.row_ids()
    vals = jnp.einsum("nd,nd->n", a[rows], b[structure.indices],
                      preferred_element_type=jnp.float32)
    return CSR(structure.indptr, structure.indices,
               alpha * vals.astype(a.dtype), structure.shape)


def degree(csr: CSR) -> jax.Array:
    """Per-row nnz count (sparse/linalg/degree.cuh)."""
    return jnp.diff(csr.indptr)


def row_norm(csr: CSR, ord: str = "l2") -> jax.Array:
    """Per-row norms over stored values (sparse/linalg/norm.cuh)."""
    rows = csr.row_ids()
    if ord == "l1":
        v = jnp.abs(csr.data)
        return jnp.zeros((csr.n_rows,), v.dtype).at[rows].add(v)
    if ord == "l2":
        v = csr.data * csr.data
        return jnp.zeros((csr.n_rows,), v.dtype).at[rows].add(v)
    if ord == "linf":
        v = jnp.abs(csr.data)
        return jnp.zeros((csr.n_rows,), v.dtype).at[rows].max(v)
    raise ValueError(f"unknown norm {ord!r}")


def row_normalize(csr: CSR, ord: str = "l1") -> CSR:
    """Scale rows to unit norm (sparse/linalg/norm.cuh rowNormalize)."""
    n = row_norm(csr, ord)
    if ord == "l2":
        n = jnp.sqrt(n)
    scale = 1.0 / jnp.maximum(n, 1e-20)
    return CSR(csr.indptr, csr.indices, csr.data * scale[csr.row_ids()],
               csr.shape)


def transpose(csr: CSR) -> CSR:
    """sparse/linalg/transpose.cuh — swap roles and re-sort."""
    coo = csr_to_coo(csr)
    t = COO(coo.cols, coo.rows, coo.data, (csr.shape[1], csr.shape[0]))
    return coo_to_csr(t)


def symmetrize(coo: COO, op: str = "max") -> COO:
    """Make A symmetric: combine with Aᵀ (sparse/linalg/symmetrize.cuh).
    Duplicate (i,j) entries are combined by ``op`` ('max'|'sum'|'mean') via a
    dense-keyed segment reduce on the doubled edge list; output keeps the
    doubled (static) nnz with zero-data entries where a pair collapsed."""
    both_r = jnp.concatenate([coo.rows, coo.cols])
    both_c = jnp.concatenate([coo.cols, coo.rows])
    both_d = jnp.concatenate([coo.data, coo.data])
    # lexicographic (row, col) order via two stable int32 argsorts — no
    # int64 key (would silently overflow with x64 disabled)
    o1 = jnp.argsort(both_c, stable=True)
    o2 = jnp.argsort(both_r[o1], stable=True)
    order = o1[o2]
    r_s = both_r[order]
    c_s = both_c[order]
    d_s = both_d[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool),
         (r_s[1:] != r_s[:-1]) | (c_s[1:] != c_s[:-1])])
    seg = jnp.cumsum(first) - 1  # segment id per entry
    nseg = both_d.shape[0]
    if op == "sum":
        vals = jnp.zeros((nseg,), d_s.dtype).at[seg].add(d_s)
    elif op == "max":
        vals = jnp.full((nseg,), -jnp.inf, d_s.dtype).at[seg].max(d_s)
    elif op == "mean":
        s = jnp.zeros((nseg,), d_s.dtype).at[seg].add(d_s)
        c = jnp.zeros((nseg,), jnp.float32).at[seg].add(1.0)
        vals = s / jnp.maximum(c, 1.0)
    else:
        raise ValueError(f"unknown symmetrize op {op!r}")
    # one representative entry per segment; collapsed duplicates become
    # zero-data self-loops at (0, 0) — harmless for duplicate-sum
    # densification AND for MST (self-loops are never selected)
    d_out = jnp.where(first, vals[seg], 0.0).astype(coo.data.dtype)
    r_out = jnp.where(first, r_s, 0)
    c_out = jnp.where(first, c_s, 0)
    return COO(r_out, c_out, d_out, coo.shape)


def laplacian(adj: CSR, normalized: bool = False) -> jax.Array:
    """Dense graph Laplacian from a sparse adjacency (the spectral input —
    reference: spectral/matrix_wrappers.hpp laplacian_matrix_t). Returns
    dense [n, n]: spectral solvers here use dense matvecs (n is the number
    of graph nodes, modest by construction)."""
    from raft_tpu.sparse.convert import csr_to_dense

    a = csr_to_dense(adj)
    a = jnp.maximum(a, a.T)  # enforce symmetry
    d = jnp.sum(a, axis=1)
    if not normalized:
        return jnp.diag(d) - a
    inv_sqrt = 1.0 / jnp.sqrt(jnp.maximum(d, 1e-20))
    return jnp.eye(a.shape[0]) - inv_sqrt[:, None] * a * inv_sqrt[None, :]
