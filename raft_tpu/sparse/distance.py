"""Pairwise distances between sparse (CSR) row sets.

Reference: ``raft::sparse::distance`` (sparse/distance/distance.cuh:38-48 —
the supported metric set: L2/L2Sqrt (expanded+unexpanded), IP, L1, Cosine,
Jaccard, Canberra, Linf, Lp, Hamming, JensenShannon, KL, Dice) with
load-balanced coo-spmv kernels.

TPU-native design: the GPU's per-nnz load-balancing machinery has no TPU
analog — the MXU wants dense tiles. Rows are densified in x-tiles (a scatter
per tile) and fed to the dense pairwise engine (ops.distance), which covers
every overlap-algebra metric; Jaccard/Dice — the two sparse-only metrics —
are computed from binarized dot products on the same tiles. For realistic
sparse-ANN dims (d ≤ ~100k) a [tile, d] dense slab is modest; the tile size
comes from the Resources workspace budget like every other tiled op."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import (
    DistanceType,
    pairwise_core,
    resolve_metric,
)
from raft_tpu.sparse.types import CSR
from raft_tpu.sparse.convert import csr_to_dense

SUPPORTED = (
    DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
    DistanceType.L2Unexpanded, DistanceType.L2SqrtUnexpanded,
    DistanceType.InnerProduct, DistanceType.L1, DistanceType.CosineExpanded,
    DistanceType.JaccardExpanded, DistanceType.Canberra, DistanceType.Linf,
    DistanceType.LpUnexpanded, DistanceType.HammingUnexpanded,
    DistanceType.JensenShannon, DistanceType.KLDivergence,
    DistanceType.DiceExpanded,
)


def _binary_overlap(xd, yd):
    """Row-pair overlap counts of binarized matrices via one matmul."""
    xb = (xd != 0).astype(jnp.float32)
    yb = (yd != 0).astype(jnp.float32)
    inter = jnp.matmul(xb, yb.T, precision=jax.lax.Precision.HIGHEST)
    nx = jnp.sum(xb, 1)
    ny = jnp.sum(yb, 1)
    return inter, nx, ny


def pairwise_distance(
    x: CSR,
    y: CSR,
    metric="euclidean",
    metric_arg: float = 2.0,
    res: Optional[Resources] = None,
) -> jax.Array:
    """All-pairs distances between CSR row sets [m, d] × [n, d] → [m, n]
    (reference: sparse::distance::pairwise_distance, distance.cuh)."""
    res = ensure_resources(res)
    m = resolve_metric(metric)
    if m not in SUPPORTED:
        raise NotImplementedError(
            f"metric {m.name} not in the sparse metric set "
            "(sparse/distance/distance.cuh:38-48)")
    if x.shape[1] != y.shape[1]:
        raise ValueError(f"dim mismatch {x.shape} vs {y.shape}")

    # y (the dataset side) is densified once; x streams through in row
    # tiles sized by the workspace budget, each tile densified by a scatter
    # over its nnz slice (indptr is concrete here, so slicing is host-side)
    yd = csr_to_dense(y)
    n_x, d = x.shape
    tile = int(np.clip(
        res.workspace_limit_bytes // max(d * 4 * 4, 1), 8, max(n_x, 8)))
    indptr = np.asarray(x.indptr)

    def block(lo: int, hi: int) -> jax.Array:
        s, e = int(indptr[lo]), int(indptr[hi])
        xt = jnp.zeros((hi - lo, d), x.dtype)
        rows = (jnp.searchsorted(
            jnp.asarray(indptr[lo : hi + 1] - indptr[lo])[1:-1],
            jnp.arange(e - s), side="right")).astype(jnp.int32)
        xt = xt.at[rows, x.indices[s:e]].add(x.data[s:e])
        if m == DistanceType.JaccardExpanded:
            inter, nx, ny = _binary_overlap(xt, yd)
            union = nx[:, None] + ny[None, :] - inter
            return 1.0 - inter / jnp.maximum(union, 1.0)
        if m == DistanceType.DiceExpanded:
            inter, nx, ny = _binary_overlap(xt, yd)
            return 1.0 - 2.0 * inter / jnp.maximum(
                nx[:, None] + ny[None, :], 1.0)
        return pairwise_core(xt, yd, m, float(metric_arg),
                              res.workspace_limit_bytes)

    if n_x <= tile:
        return block(0, n_x)
    return jnp.concatenate(
        [block(lo, min(lo + tile, n_x)) for lo in range(0, n_x, tile)])


def knn(
    queries: CSR,
    dataset: CSR,
    k: int,
    metric="euclidean",
    res: Optional[Resources] = None,
):
    """Sparse brute-force kNN (reference: sparse/neighbors/knn.cuh
    brute_force_knn over CSR inputs): pairwise distances + select_k."""
    from raft_tpu.ops.select_k import select_k
    from raft_tpu.ops.distance import is_min_close

    res = ensure_resources(res)
    d = pairwise_distance(queries, dataset, metric, res=res)
    return select_k(d, k, select_min=is_min_close(metric))
