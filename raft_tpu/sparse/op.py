"""Sparse element/row operations.

Reference: ``raft::sparse::op`` (sparse/op/filter.cuh — ``coo_remove_scalar``
/ ``coo_remove_zeros``; sparse/op/reduce.cuh — ``max_duplicates``;
sparse/op/row_op.cuh; sparse/op/slice.cuh — ``csr_row_slice``;
sparse/op/sort.cuh).

TPU-native design: XLA needs static shapes, so "removal" keeps the nnz
capacity and compacts valid entries to the front, returning the new logical
nnz alongside; padding entries carry row/col -1 and value 0. This mirrors
how the reference's stream-compaction output is sized by a prior count —
here the count travels with the result instead of resizing the buffer.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.sparse.types import COO, CSR


def _compact(coo: COO, keep) -> Tuple[COO, jax.Array]:
    """Stable-compact kept entries to the front; returns (coo, new_nnz)."""
    order = jnp.argsort(~keep, stable=True)  # kept first, original order
    rows = jnp.where(keep[order], coo.rows[order], -1)
    cols = jnp.where(keep[order], coo.cols[order], -1)
    data = jnp.where(keep[order], coo.data[order], 0)
    return COO(rows, cols, data, coo.shape), jnp.sum(keep).astype(jnp.int32)


def coo_remove_scalar(coo: COO, scalar) -> Tuple[COO, jax.Array]:
    """Drop entries equal to ``scalar`` (op/filter.cuh: coo_remove_scalar)."""
    return _compact(coo, coo.data != scalar)


def coo_remove_zeros(coo: COO) -> Tuple[COO, jax.Array]:
    """Drop explicit zeros (op/filter.cuh: coo_remove_zeros)."""
    return coo_remove_scalar(coo, 0)


def coo_sum_duplicates(coo: COO) -> COO:
    """Sum duplicate (row, col) entries, keeping one representative each
    (op/reduce.cuh's duplicate coalescing, summing instead of max)."""
    n_cols = coo.shape[1]
    valid = coo.rows >= 0
    lin = jnp.where(valid, coo.rows * n_cols + coo.cols, jnp.iinfo(jnp.int32).max)
    order = jnp.argsort(lin)
    lin_s = lin[order]
    data_s = coo.data[order]
    first = jnp.concatenate([jnp.array([True]), lin_s[1:] != lin_s[:-1]])
    seg = jnp.cumsum(first) - 1  # segment id per entry
    sums = jnp.zeros_like(data_s).at[seg].add(data_s)
    rows = jnp.where(first & (lin_s != jnp.iinfo(jnp.int32).max),
                     (lin_s // n_cols).astype(jnp.int32), -1)
    cols = jnp.where(rows >= 0, (lin_s % n_cols).astype(jnp.int32), -1)
    data = jnp.where(rows >= 0, sums[seg], 0)
    # compact representatives to the front
    rep = rows >= 0
    order2 = jnp.argsort(~rep, stable=True)
    return COO(rows[order2], cols[order2], data[order2], coo.shape)


def coo_max_duplicates(coo: COO) -> COO:
    """Max-reduce duplicate (row, col) entries (op/reduce.cuh:
    max_duplicates)."""
    n_cols = coo.shape[1]
    valid = coo.rows >= 0
    lin = jnp.where(valid, coo.rows * n_cols + coo.cols,
                    jnp.iinfo(jnp.int32).max)
    order = jnp.argsort(lin)
    lin_s = lin[order]
    data_s = coo.data[order]
    first = jnp.concatenate([jnp.array([True]), lin_s[1:] != lin_s[:-1]])
    seg = jnp.cumsum(first) - 1
    neg_inf = jnp.array(-jnp.inf, data_s.dtype) if jnp.issubdtype(
        data_s.dtype, jnp.floating) else jnp.iinfo(data_s.dtype).min
    maxs = jnp.full_like(data_s, neg_inf).at[seg].max(data_s)
    rows = jnp.where(first & (lin_s != jnp.iinfo(jnp.int32).max),
                     (lin_s // n_cols).astype(jnp.int32), -1)
    cols = jnp.where(rows >= 0, (lin_s % n_cols).astype(jnp.int32), -1)
    data = jnp.where(rows >= 0, maxs[seg], 0)
    rep = rows >= 0
    order2 = jnp.argsort(~rep, stable=True)
    return COO(rows[order2], cols[order2], data[order2], coo.shape)


def csr_row_op(csr: CSR, fn) -> CSR:
    """Apply ``fn(row_id, values) -> values`` across rows (op/row_op.cuh).
    ``fn`` receives the per-nnz row-id vector and the data vector."""
    rows = csr.row_ids()
    return CSR(csr.indptr, csr.indices, fn(rows, csr.data), csr.shape)


def csr_row_slice(csr: CSR, start: int, stop: int) -> CSR:
    """Rows [start, stop) as a new CSR (op/slice.cuh: csr_row_slice).
    start/stop are Python ints (static shapes)."""
    start = int(start)
    stop = int(stop)
    lo = int(csr.indptr[start])
    hi = int(csr.indptr[stop])
    return CSR(csr.indptr[start:stop + 1] - lo, csr.indices[lo:hi],
               csr.data[lo:hi], (stop - start, csr.shape[1]))


def coo_sort(coo: COO) -> COO:
    """Row-major sort (op/sort.cuh: coo_sort); padding (-1 rows) sinks to
    the end."""
    n_cols = coo.shape[1]
    lin = jnp.where(coo.rows >= 0, coo.rows * n_cols + coo.cols,
                    jnp.iinfo(jnp.int32).max)
    order = jnp.argsort(lin)
    return COO(coo.rows[order], coo.cols[order], coo.data[order], coo.shape)
