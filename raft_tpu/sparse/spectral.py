"""Spectral graph partitioning & embedding.

Reference: ``raft::spectral`` (spectral/partition.cuh — Laplacian smallest
eigenvectors via Lanczos + k-means on the embedding; spectral/
modularity_maximization.cuh — modularity matrix largest eigenvectors +
k-means; analysis helpers computing cut cost / modularity).

TPU-native design: the Laplacian matvec is a dense MXU op (partition sizes
are modest); eigenpairs come from ops.linalg.lanczos (full-reorth Lanczos,
same algorithm family as the reference's restarted Lanczos); the embedding
is clustered with the existing Lloyd k-means. One functional pipeline, no
cuSPARSE/cuSOLVER split."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops import linalg as rlinalg
from raft_tpu.sparse.types import CSR
from raft_tpu.sparse.linalg import laplacian as make_laplacian
from raft_tpu.sparse.convert import csr_to_dense


def fit_embedding(
    adj: CSR,
    n_components: int,
    normalized: bool = True,
    key=None,
    res: Optional[Resources] = None,
) -> jax.Array:
    """Spectral embedding: the ``n_components`` smallest non-trivial
    Laplacian eigenvectors [n, k] (reference: spectral/partition.cuh's
    eigensolver stage; also sparse/linalg/spectral.cuh fit_embedding)."""
    res = ensure_resources(res)
    if key is None:
        key = res.next_key()
    lap = make_laplacian(adj, normalized=normalized)
    n = lap.shape[0]

    def matvec(v):
        return jnp.matmul(lap, v, precision=jax.lax.Precision.HIGHEST)

    # k+1 smallest: drop the trivial constant eigenvector
    _, vecs = rlinalg.lanczos(matvec, n, n_components + 1, key=key,
                              ncv=min(n, max(4 * (n_components + 1), 32)))
    return vecs[:, 1 : n_components + 1]


def partition(
    adj: CSR,
    n_clusters: int,
    n_eig_vects: Optional[int] = None,
    kmeans_iters: int = 25,
    key=None,
    res: Optional[Resources] = None,
) -> Tuple[np.ndarray, jax.Array]:
    """Spectral partition (reference: spectral::partition,
    spectral/partition.cuh): Laplacian eigenvectors → k-means labels.
    Returns (labels [n], embedding [n, k])."""
    from raft_tpu.cluster import kmeans

    res = ensure_resources(res)
    k_eig = n_eig_vects or n_clusters
    emb = fit_embedding(adj, k_eig, normalized=True, key=key, res=res)
    # row-normalize the embedding (standard normalized-spectral practice)
    emb_n = emb / jnp.maximum(
        jnp.linalg.norm(emb, axis=1, keepdims=True), 1e-12)
    params = kmeans.KMeansParams(n_clusters=n_clusters, max_iter=kmeans_iters)
    centers, labels = kmeans.fit_predict(emb_n, params, res=res)
    return np.asarray(labels), emb


def analyze_partition(adj: CSR, labels) -> Tuple[float, float]:
    """Edge-cut cost and ratio-cut style balance (reference:
    spectral/partition.cuh analyzePartition). Returns (edge_cut,
    ratio_cut)."""
    a = csr_to_dense(adj)
    a = jnp.maximum(a, a.T)
    labels = jnp.asarray(labels)
    diff = labels[:, None] != labels[None, :]
    edge_cut = float(jnp.sum(jnp.where(diff, a, 0.0)) / 2.0)
    ratio = 0.0
    for c in np.unique(np.asarray(labels)):
        size = float(jnp.sum(labels == int(c)))
        if size > 0:
            cut_c = float(jnp.sum(jnp.where(
                diff & (labels[:, None] == int(c)), a, 0.0)))
            ratio += cut_c / size
    return edge_cut, float(ratio)


def modularity_maximization(
    adj: CSR,
    n_clusters: int,
    key=None,
    res: Optional[Resources] = None,
) -> Tuple[np.ndarray, jax.Array]:
    """Modularity-matrix spectral clustering (reference:
    spectral/modularity_maximization.cuh): largest eigenvectors of
    B = A − d·dᵀ/2m, then k-means."""
    from raft_tpu.cluster import kmeans

    res = ensure_resources(res)
    if key is None:
        key = res.next_key()
    a = csr_to_dense(adj)
    a = jnp.maximum(a, a.T)
    d = jnp.sum(a, axis=1)
    two_m = jnp.maximum(jnp.sum(d), 1e-20)
    n = a.shape[0]

    def matvec(v):
        return (jnp.matmul(a, v, precision=jax.lax.Precision.HIGHEST)
                - d * (jnp.vdot(d, v) / two_m))

    _, vecs = rlinalg.lanczos(matvec, n, n_clusters, key=key,
                              which="largest",
                              ncv=min(n, max(4 * n_clusters, 32)))
    emb = vecs / jnp.maximum(
        jnp.linalg.norm(vecs, axis=1, keepdims=True), 1e-12)
    params = kmeans.KMeansParams(n_clusters=n_clusters, max_iter=25)
    centers, labels = kmeans.fit_predict(emb, params, res=res)
    return np.asarray(labels), vecs
