"""Sparse layer (SURVEY.md §2.4): COO/CSR containers, conversions, sparse
linalg (spmm/sddmm/degree/norm/symmetrize/transpose/laplacian), element/row
ops (filter/reduce/slice/sort), sparse pairwise distances + kNN,
cross-component NN, Lanczos solver, Borůvka MST, spectral partitioning."""

from raft_tpu.sparse import (convert, distance, linalg, mst, neighbors, op,
                             selection, solver, spectral, types)
from raft_tpu.sparse.types import COO, CSR, coo_from_arrays, csr_from_scipy_like

__all__ = ["convert", "distance", "linalg", "mst", "neighbors", "op",
           "selection", "solver", "spectral", "types",
           "COO", "CSR", "coo_from_arrays", "csr_from_scipy_like"]
