"""Sparse neighbors: CSR brute-force kNN and cross-component 1-NN.

Reference: ``raft::sparse::neighbors`` — brute-force kNN over CSR rows
(sparse/neighbors/knn.cuh, batched semiring distances + select_k) and
``cross_component_nn`` (sparse/neighbors/cross_component_nn.cuh) — for each
point, the nearest point belonging to a *different* connected component;
the primitive that lets single-linkage/HDBSCAN connect component fragments.

TPU-native design: CSR rows are tile-densified and ride the dense distance
engine (TPUs have no sparse MXU — a gathered-dense matmul IS the fast
path); cross-component masking happens in the distance tile's epilogue
exactly like masked_l2_nn, so the full matrix never reaches HBM.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from raft_tpu.ops.distance import DistanceType
from raft_tpu.sparse.types import CSR
from raft_tpu.sparse import distance as sparse_distance
from raft_tpu.utils.shape import cdiv


def brute_force_knn(
    database: CSR,
    queries: CSR,
    k: int,
    metric: DistanceType = DistanceType.L2Expanded,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN between CSR rows (sparse/neighbors/knn.cuh analog).

    Returns (distances [nq, k], indices [nq, k]).
    """
    return sparse_distance.knn(queries, database, k, metric=metric)


@functools.partial(jax.jit, static_argnames=("tile",))
def _cross_component_nn_jit(x, colors, tile: int):
    n, dim = x.shape
    xn = jnp.sum(x * x, -1)

    n_tiles = cdiv(n, tile)
    pad = n_tiles * tile - n
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    xnp_ = jnp.pad(xn, (0, pad))
    cp = jnp.pad(colors, (0, pad), constant_values=-1)

    def tile_body(args):
        xt, xnt, ct = args
        dots = jax.lax.dot_general(
            xt, x, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        d = xnt[:, None] + xn[None, :] - 2.0 * dots
        # mask same-component pairs (and tile padding)
        same = ct[:, None] == colors[None, :]
        bad = same | (ct[:, None] < 0)
        d = jnp.where(bad, jnp.inf, jnp.maximum(d, 0.0))
        return jnp.min(d, axis=1), jnp.argmin(d, axis=1).astype(jnp.int32)

    vals, idxs = jax.lax.map(
        tile_body,
        (xp.reshape(n_tiles, tile, dim),  # graftcheck: R005 — O(input) view
         xnp_.reshape(n_tiles, tile), cp.reshape(n_tiles, tile)),
    )
    return vals.reshape(-1)[:n], idxs.reshape(-1)[:n]


def cross_component_nn(
    x,
    colors,
    tile: int = 1024,
) -> Tuple[jax.Array, jax.Array]:
    """For each row of ``x``, the squared-L2 nearest row with a different
    ``colors`` label (sparse/neighbors/cross_component_nn.cuh analog).

    Returns (min_sq_dist [n], argmin [n]); rows whose component has no
    other component get distance inf.
    """
    x = jnp.asarray(x, jnp.float32)
    colors = jnp.asarray(colors, jnp.int32)
    tile = int(min(tile, x.shape[0]))
    return _cross_component_nn_jit(x, colors, max(tile, 1))


def connect_components_edges(
    x,
    colors,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Candidate cross-component edges (one per source point): (rows, cols,
    sq_dists). Feeding these into MST alongside the kNN graph guarantees
    connectivity — the role connect_components plays for single-linkage in
    the reference (sparse/neighbors/cross_component_nn.cuh:22-60)."""
    d, j = cross_component_nn(x, colors)
    i = jnp.arange(x.shape[0], dtype=jnp.int32)
    return i, j, d
