"""Sparse matrix containers — CSR and COO.

Reference: ``raft::core`` sparse types (core/sparse_types.hpp,
core/device_csr_matrix.hpp, core/device_coo_matrix.hpp) — owning/view
structure-plus-values containers.

TPU-native design: immutable dataclasses of jax.Arrays. TPUs have no sparse
MXU; these containers exist to hold graph/matrix structure compactly in HBM
and to feed either segment ops (degree/reduce) or tile-densification
(distances, spmm with dense rhs). Fixed static shapes (nnz is part of the
shape) keep everything jit-stable."""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class COO:
    """Coordinate format (core/device_coo_matrix.hpp analog)."""

    rows: jax.Array  # [nnz] int32
    cols: jax.Array  # [nnz] int32
    data: jax.Array  # [nnz]
    shape: Tuple[int, int]

    @property
    def nnz(self) -> int:
        return self.data.shape[0]

    @property
    def dtype(self):
        return self.data.dtype


@dataclasses.dataclass(frozen=True)
class CSR:
    """Compressed sparse row (core/device_csr_matrix.hpp analog)."""

    indptr: jax.Array  # [n_rows + 1] int32
    indices: jax.Array  # [nnz] int32 column ids
    data: jax.Array  # [nnz]
    shape: Tuple[int, int]

    @property
    def nnz(self) -> int:
        return self.data.shape[0]

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    @property
    def dtype(self):
        return self.data.dtype

    def row_ids(self) -> jax.Array:
        """Expand indptr to per-nnz row ids (sparse/convert/csr.cuh's
        csr_to_coo row expansion) — searchsorted keeps it one XLA op."""
        return (jnp.searchsorted(self.indptr[1:-1],
                                 jnp.arange(self.nnz, dtype=jnp.int32),
                                 side="right")).astype(jnp.int32)


def csr_from_scipy_like(indptr, indices, data, shape) -> CSR:
    return CSR(jnp.asarray(indptr, jnp.int32),
               jnp.asarray(indices, jnp.int32),
               jnp.asarray(data), tuple(shape))


def coo_from_arrays(rows, cols, data, shape) -> COO:
    return COO(jnp.asarray(rows, jnp.int32), jnp.asarray(cols, jnp.int32),
               jnp.asarray(data), tuple(shape))
