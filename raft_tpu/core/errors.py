"""Error types and validation helpers.

Reference: ``raft::core`` error machinery (core/error.hpp — ``raft::exception``,
``logic_error``, ``RAFT_EXPECTS``, ``RAFT_FAIL``). The CUDA/cuBLAS/etc.
status-check macros have no analog — XLA raises its own exceptions.
"""

from __future__ import annotations


class RaftError(RuntimeError):
    """Base exception (raft::exception analog)."""


class LogicError(RaftError):
    """Precondition violation (raft::logic_error / RAFT_EXPECTS)."""


class IntegrityError(RaftError):
    """A checkpoint file failed validation: missing, truncated, or corrupt.

    ``path`` names the file, ``record`` the 0-based framed record inside it
    (None when the fault is file-level), and ``reason`` is one of
    ``"missing"``, ``"truncated"``, ``"corrupt"``, ``"torn_tail"`` so
    callers (degraded-mode restore, pre-flight verification, WAL recovery)
    can branch without parsing messages. ``"torn_tail"`` is specific to
    append-only logs (neighbors/mutable.py): the LAST frame is damaged and
    nothing follows it — a crash mid-append, recoverable by truncation
    with only never-acknowledged bytes lost — where the same damage
    mid-file would be ``"corrupt"``.
    """

    def __init__(self, message: str, *, path=None, record=None, reason=None):
        super().__init__(message)
        self.path = path
        self.record = record
        self.reason = reason


def expects(condition: bool, message: str = "precondition violated") -> None:
    """``RAFT_EXPECTS(cond, msg)`` — raise LogicError unless condition.

    Host-side validation only: call on static shapes/params before tracing,
    never on traced values (use checkify inside jit for those).
    """
    if not condition:
        raise LogicError(message)


def fail(message: str) -> None:
    """``RAFT_FAIL(msg)`` — unconditional LogicError."""
    raise LogicError(message)
