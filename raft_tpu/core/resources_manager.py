"""Pooled per-device Resources for multi-threaded servers.

Reference: ``raft::device_resources_manager``
(core/device_resources_manager.hpp:36-95) — a process-wide singleton handing
out pooled ``device_resources`` round-robin so server threads don't each
construct handles/streams.

TPU-native design: XLA owns streams, so the pooled state reduces to
Resources objects (PRNG key streams + workspace budgets + resource slots)
per device. Round-robin across a configurable pool bounds PRNG-key
contention between threads; hand-out is lock-protected and cheap.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax

from raft_tpu.core.resources import Resources


class _Manager:
    def __init__(self):
        self._lock = threading.Lock()
        self._pools: Dict[int, List[Resources]] = {}  # guarded_by: _lock
        self._next: Dict[int, int] = {}  # guarded_by: _lock
        self._pool_size = 1  # guarded_by: _lock
        self._workspace_limit: Optional[int] = None  # guarded_by: _lock
        self._frozen = False  # guarded_by: _lock

    def set_resources_per_device(self, n: int) -> None:
        """Analog of ``set_streams_per_device`` — pool width per device.
        Must be called before the first hand-out (like the reference, which
        ignores post-first-use option changes)."""
        with self._lock:
            if self._frozen:
                return  # reference semantics: options frozen after first use
            self._pool_size = max(int(n), 1)

    def set_workspace_limit(self, n_bytes: int) -> None:
        with self._lock:
            if self._frozen:
                return
            self._workspace_limit = int(n_bytes)

    def get_resources(self, device: Optional[jax.Device] = None) -> Resources:
        """Round-robin a pooled Resources for ``device`` (default: jax
        default device) — ``get_device_resources`` analog."""
        device = device or jax.devices()[0]
        did = device.id
        with self._lock:
            self._frozen = True
            pool = self._pools.get(did)
            if pool is None:
                kwargs = {}
                if self._workspace_limit is not None:
                    kwargs["workspace_limit_bytes"] = self._workspace_limit
                pool = [Resources(seed=1000 + did * 101 + i, device=device,
                                  **kwargs)
                        for i in range(self._pool_size)]
                self._pools[did] = pool
                self._next[did] = 0
            i = self._next[did]
            self._next[did] = (i + 1) % len(pool)
            return pool[i]

    def reset(self) -> None:
        """Testing hook: drop all pools and unfreeze options."""
        with self._lock:
            self._pools.clear()
            self._next.clear()
            self._pool_size = 1
            self._workspace_limit = None
            self._frozen = False


_manager = _Manager()

set_resources_per_device = _manager.set_resources_per_device
set_workspace_limit = _manager.set_workspace_limit
get_resources = _manager.get_resources
reset = _manager.reset
