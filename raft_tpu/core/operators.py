"""Composable math operators.

Reference: ``raft::core`` operator functors (core/operators.hpp — identity,
sq_op, abs_op, add_op, sub_op, mul_op, div_op, min/max, pow, sqrt, and the
``compose_op`` / ``map_args_op`` / ``const_op`` / ``plug_const_op``
combinators) used to parameterize map/reduce prims.

TPU-native design: plain Python callables over jnp — XLA traces and fuses
them wherever they are applied, so there is no functor machinery to port;
these exist so code written against the reference's vocabulary (e.g.
``linalg.map(ops.sq_op, x)``) reads the same.
"""

from __future__ import annotations

import jax.numpy as jnp


def identity_op(x):
    return x


def sq_op(x):
    return x * x


def abs_op(x):
    return jnp.abs(x)


def sqrt_op(x):
    return jnp.sqrt(x)


def nz_op(x):
    """1 where nonzero else 0 (core/operators.hpp nz_op)."""
    return jnp.where(x != 0, 1, 0).astype(x.dtype)


def add_op(a, b):
    return a + b


def sub_op(a, b):
    return a - b


def mul_op(a, b):
    return a * b


def div_op(a, b):
    return a / b


def div_checkzero_op(a, b):
    """a/b with 0 where b == 0 (core/operators.hpp div_checkzero_op)."""
    return jnp.where(b == 0, 0, a / jnp.where(b == 0, 1, b))


def min_op(a, b):
    return jnp.minimum(a, b)


def max_op(a, b):
    return jnp.maximum(a, b)


def pow_op(a, b):
    return a ** b


def mod_op(a, b):
    return a % b


def equal_op(a, b):
    return a == b


def notequal_op(a, b):
    return a != b


def greater_op(a, b):
    return a > b


def less_op(a, b):
    return a < b


def const_op(c):
    """Returns an op ignoring its inputs (core/operators.hpp const_op)."""
    return lambda *args: c


def compose_op(*ops):
    """compose_op(f, g, h)(x) == f(g(h(x))) (core/operators.hpp
    compose_op — applied innermost-last like the reference)."""

    def composed(*args):
        out = ops[-1](*args)
        for f in reversed(ops[:-1]):
            out = f(out)
        return out

    return composed


def plug_const_op(c, op):
    """Binds a constant as the second argument (plug_const_op)."""
    return lambda x: op(x, c)


add_const_op = lambda c: plug_const_op(c, add_op)  # noqa: E731
sub_const_op = lambda c: plug_const_op(c, sub_op)  # noqa: E731
mul_const_op = lambda c: plug_const_op(c, mul_op)  # noqa: E731
div_const_op = lambda c: plug_const_op(c, div_op)  # noqa: E731
pow_const_op = lambda c: plug_const_op(c, pow_op)  # noqa: E731


def map_args_op(op, *maps):
    """Applies per-argument transforms before ``op`` (map_args_op)."""

    def mapped(*args):
        return op(*(m(a) for m, a in zip(maps, args)))

    return mapped
