"""Device bitset — the basis of ANN search filtering.

Reference: ``raft::core::bitset`` / ``bitset_view`` (core/bitset.cuh:91-147):
a packed device bitset with set/test/flip/count used by
``bitset_filter`` sample filters (neighbors/sample_filter_types.hpp:27-82) to
exclude dataset rows from search results.

TPU-native design: bits packed into a ``uint32`` jax.Array; all ops are pure
functions returning new arrays (XLA fuses the word-twiddling); ``test`` on a
batch of indices is a gather + mask — exactly what the search pipelines need
to build additive distance masks.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

_WORD_BITS = 32


def _n_words(n_bits: int) -> int:
    return (n_bits + _WORD_BITS - 1) // _WORD_BITS


class Bitset:
    """Immutable-functional packed bitset over ``n_bits`` positions."""

    def __init__(self, words: jax.Array, n_bits: int):
        self.words = words
        self.n_bits = int(n_bits)

    # ------------------------------------------------------------ constructors
    @staticmethod
    def create(n_bits: int, default: bool = True) -> "Bitset":
        """New bitset; RAFT's bitset default-constructs to all-set (all samples
        pass the filter)."""
        fill = jnp.uint32(0xFFFFFFFF) if default else jnp.uint32(0)
        words = jnp.full((_n_words(n_bits),), fill, dtype=jnp.uint32)
        return Bitset(words, n_bits)._mask_tail()

    @staticmethod
    def from_mask(mask) -> "Bitset":
        """Build from a boolean vector of length n_bits."""
        mask = jnp.asarray(mask, dtype=bool)
        n_bits = mask.shape[0]
        pad = _n_words(n_bits) * _WORD_BITS - n_bits
        mask = jnp.pad(mask, (0, pad))
        bits = mask.reshape(-1, _WORD_BITS).astype(jnp.uint32)
        shifts = jnp.arange(_WORD_BITS, dtype=jnp.uint32)
        words = jnp.sum(bits << shifts, axis=1, dtype=jnp.uint32)
        return Bitset(words, n_bits)

    def _mask_tail(self) -> "Bitset":
        tail = self.n_bits % _WORD_BITS
        if tail == 0:
            return self
        last_mask = jnp.uint32((1 << tail) - 1)
        words = self.words.at[-1].set(self.words[-1] & last_mask)
        return Bitset(words, self.n_bits)

    # ------------------------------------------------------------------- ops
    def set(self, indices, value: bool = True) -> "Bitset":
        """Set (or clear) the bits at ``indices``; duplicate indices are fine.

        Scatter-OR has no native lowering, so route through a boolean scatter
        (one bit-position per element) and re-pack — XLA fuses the repack.
        """
        indices = jnp.asarray(indices)
        touched = jnp.zeros((self.n_bits,), dtype=bool).at[indices].set(True)
        mask_words = Bitset.from_mask(touched).words
        if value:
            return Bitset(self.words | mask_words, self.n_bits)._mask_tail()
        return Bitset(self.words & ~mask_words, self.n_bits)._mask_tail()

    def test(self, indices) -> jax.Array:
        """Gather bit values for a batch of indices → bool array."""
        indices = jnp.asarray(indices)
        words = self.words[indices // _WORD_BITS]
        return ((words >> (indices % _WORD_BITS).astype(jnp.uint32)) & 1).astype(bool)

    def flip(self) -> "Bitset":
        return Bitset(~self.words, self.n_bits)._mask_tail()

    def count(self) -> jax.Array:
        """Population count (reference: bitset::count)."""
        return jnp.sum(_popcount32(self.words))

    def to_mask(self) -> jax.Array:
        """Expand to a boolean vector of length n_bits."""
        shifts = jnp.arange(_WORD_BITS, dtype=jnp.uint32)
        bits = (self.words[:, None] >> shifts[None, :]) & 1
        return bits.reshape(-1)[: self.n_bits].astype(bool)

    def __len__(self) -> int:
        return self.n_bits


def _popcount32(x: jax.Array) -> jax.Array:
    x = x - ((x >> 1) & jnp.uint32(0x55555555))
    x = (x & jnp.uint32(0x33333333)) + ((x >> 2) & jnp.uint32(0x33333333))
    x = (x + (x >> 4)) & jnp.uint32(0x0F0F0F0F)
    return (x * jnp.uint32(0x01010101)) >> 24


def filter_mask(ids: jax.Array, filter_words: jax.Array) -> jax.Array:
    """Traceable membership test for candidate-id arrays against a bitset's
    word array (the sample-filter bit test, sample_filter_types.hpp:27-82).
    Negative ids (padding) index word 0 safely and should be masked by the
    caller's validity mask. Shared by every IVF/CAGRA scan so the bit
    arithmetic lives in exactly one place."""
    safe_ids = jnp.maximum(ids, 0)
    words = filter_words[safe_ids // 32]
    return ((words >> (safe_ids % 32).astype(jnp.uint32)) & 1).astype(bool)
