"""Cooperative cross-thread cancellation of device waits.

Reference: ``raft::interruptible`` (core/interruptible.hpp:71-100) — a
per-thread token lets any other thread cancel a spinning stream-sync;
``interruptible::synchronize`` polls the flag while waiting and throws
``interrupted_exception`` when cancelled. Also hooked into comms
sync_stream.

TPU-native design: JAX dispatch is async; the wait point is
``block_until_ready``. ``synchronize`` polls array readiness in small
sleeps, checking the calling thread's token — same cooperative contract,
no busy device spin."""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import jax


class InterruptedException(RuntimeError):
    """Raised by synchronize() in a cancelled thread (reference:
    raft::interruptible::interrupted_exception)."""


_tokens: Dict[int, threading.Event] = {}
_lock = threading.Lock()


def get_token(thread_id: Optional[int] = None) -> threading.Event:
    """The cancellation token of a thread (reference: get_token())."""
    tid = thread_id if thread_id is not None else threading.get_ident()
    with _lock:
        if tid not in _tokens:
            _tokens[tid] = threading.Event()
        return _tokens[tid]


def cancel(thread_id: int) -> None:
    """Cancel another thread's waits (reference: interruptible::cancel)."""
    get_token(thread_id).set()


def yield_now() -> None:
    """Throw if this thread is cancelled (reference: yield_no_throw's
    throwing sibling). The consumed token is removed so a reused thread
    ident never inherits a stale cancellation (the reference clears its
    per-thread store on thread exit)."""
    tid = threading.get_ident()
    with _lock:
        tok = _tokens.get(tid)
        if tok is not None and tok.is_set():
            del _tokens[tid]
            raise InterruptedException(
                "interruptible::synchronize cancelled")


def release_token(thread_id: Optional[int] = None) -> None:
    """Drop a thread's token (call at thread exit in long-lived pools to
    bound the registry)."""
    tid = thread_id if thread_id is not None else threading.get_ident()
    with _lock:
        _tokens.pop(tid, None)


def synchronize(arrays, poll_s: float = 0.01) -> None:
    """Block until arrays are ready, polling the cancellation token
    (reference: interruptible::synchronize, core/interruptible.hpp:83-100).
    """
    leaves = [a for a in jax.tree_util.tree_leaves(arrays)
              if isinstance(a, jax.Array)]
    for a in leaves:
        while True:
            yield_now()
            if a.is_ready():
                break
            time.sleep(poll_s)
    # final fence for anything is_ready() raced with
    for a in leaves:
        a.block_until_ready()
