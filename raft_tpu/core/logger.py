"""Logging + named-scope tracing.

Reference: spdlog-backed singleton logger with a callback sink so Python can
capture C++ logs (core/logger-inl.hpp:74-131, detail/callback_sink.hpp) and
``RAFT_LOG_{TRACE..CRITICAL}`` macros (core/logger-macros.hpp); NVTX RAII
ranges at every nontrivial entry point (core/nvtx.hpp:25-91).

TPU-native design: stdlib ``logging`` with an optional user callback sink
(mirroring the reference's Python-capture path), and tracing via
``jax.named_scope`` / ``jax.profiler.TraceAnnotation`` so ranges show up in
XLA profiles (xprof) exactly where NVTX ranges show up in Nsight.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Callable, Optional

import jax

_logger = logging.getLogger("raft_tpu")
if not _logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter("[%(levelname)s] [%(asctime)s] %(message)s"))
    _logger.addHandler(_h)
    _logger.setLevel(logging.WARNING)

_callback: Optional[Callable[[int, str], None]] = None


def get_logger() -> logging.Logger:
    return _logger


def set_level(level: int) -> None:
    """Set log level (reference: logger::set_level, core/logger-inl.hpp:103)."""
    _logger.setLevel(level)


def set_callback(cb: Optional[Callable[[int, str], None]]) -> None:
    """Install a capture callback receiving (level, message) — the analog of
    the reference's callback_sink used by pylibraft to surface C++ logs."""
    global _callback
    _callback = cb


def _emit(level: int, msg: str, *args) -> None:
    if args:
        msg = msg % args
    if _callback is not None:
        _callback(level, msg)
    _logger.log(level, msg)


def trace(msg, *args):
    _emit(logging.DEBUG - 5, msg, *args)


def debug(msg, *args):
    _emit(logging.DEBUG, msg, *args)


def info(msg, *args):
    _emit(logging.INFO, msg, *args)


def warn(msg, *args):
    _emit(logging.WARNING, msg, *args)


def error(msg, *args):
    _emit(logging.ERROR, msg, *args)


@contextlib.contextmanager
def annotate(name: str):
    """RAII trace range (reference: common::nvtx::range, core/nvtx.hpp:25-91).

    Inside jit traces this adds a named_scope (shows in HLO + xprof op names);
    outside it adds a profiler TraceAnnotation (shows on the host timeline).
    """
    with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
        yield
