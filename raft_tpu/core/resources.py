"""Resources — the per-call context object (TPU analog of ``raft::resources``).

The reference threads a ``raft::resources const&`` through every public API as
the first argument (reference: cpp/include/raft/core/resources.hpp:47-137); it
carries the CUDA stream, cuBLAS/cuSOLVER handles, the communicator, and the
workspace memory resource. On TPU, XLA owns streams and library handles, so the
equivalent context is much lighter: a device (or mesh of devices), a PRNG key
stream, an HBM workspace budget used to pick tile/batch sizes, and the comms
handle for multi-host runs.

Like the reference's type-erased resource container (``resources::get_resource``
keyed by ``resource_type`` slots — core/resource/resource_types.hpp:29-47), the
``Resources`` object supports lazily-built custom slots via ``get_resource`` so
downstream layers can stash caches (e.g. compiled kernels, sub-communicators)
without new fields.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import jax
import numpy as np


class Resources:
    """Lightweight resource/context container threaded through public APIs.

    Parameters
    ----------
    device:
        A single ``jax.Device`` to place work on. ``None`` = JAX default.
    mesh:
        A ``jax.sharding.Mesh`` for SPMD execution; when set, algorithms that
        support sharded execution pjit/shard_map over this mesh. Mutually
        compatible with ``device`` (single-device work ignores the mesh).
    seed:
        Base seed for this context's PRNG key stream (analog of
        ``random::RngState`` living in the handle).
    workspace_limit_bytes:
        Soft HBM budget used to size tiles/batches (analog of the reference's
        ``limiting_memory_resource`` workspace —
        core/resource/device_memory_resource.hpp:38-88). Defaults to a
        conservative estimate from the device's memory stats.
    """

    def __init__(
        self,
        device: Optional[jax.Device] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        seed: int = 0,
        workspace_limit_bytes: Optional[int] = None,
    ):
        self._device = device
        self.mesh = mesh
        self._key = jax.random.key(seed)  # guarded_by: _key_lock
        self._key_lock = threading.Lock()
        self._workspace_limit = workspace_limit_bytes
        self._slots: dict[str, Any] = {}  # guarded_by: _slot_lock
        self._slot_lock = threading.Lock()
        self._comms = None  # set by raft_tpu.parallel.comms.inject_comms

    # ------------------------------------------------------------------ device
    @property
    def device(self) -> jax.Device:
        if self._device is not None:
            return self._device
        # local_devices: in a multi-controller deployment jax.devices()[0]
        # can be another process's (non-addressable) device
        return jax.local_devices()[0]

    @property
    def device_memory_bytes(self) -> Optional[int]:
        """Total device memory (HBM) when the backend reports it, else
        None (e.g. XLA:CPU). Engine/layout choices that must not OOM the
        chip key off this (ivf_pq scan_mode="auto")."""
        try:
            stats = getattr(self.device, "memory_stats", lambda: None)()
        except Exception:  # non-addressable device / backend w/o stats
            stats = None
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
        return None

    @property
    def workspace_limit_bytes(self) -> int:
        if self._workspace_limit is not None:
            return self._workspace_limit
        try:
            stats = getattr(self.device, "memory_stats", lambda: None)()
        except Exception:  # non-addressable device / backend w/o stats
            stats = None
        if stats and "bytes_limit" in stats:
            # Leave headroom: workspace is for scratch, not the whole HBM.
            return int(stats["bytes_limit"] * 0.25)
        return 2 << 30  # 2 GiB fallback (CPU backend / unknown device)

    # -------------------------------------------------------------------- rng
    def next_key(self) -> jax.Array:
        """Split and return a fresh PRNG key (thread-safe)."""
        with self._key_lock:
            self._key, sub = jax.random.split(self._key)
        return sub

    def next_keys(self, n: int) -> jax.Array:
        with self._key_lock:
            keys = jax.random.split(self._key, n + 1)
            self._key = keys[0]
        return keys[1:]

    # ------------------------------------------------------------------ slots
    def get_resource(self, name: str, factory: Callable[[], Any]) -> Any:
        """Lazily-created custom resource slot (analog of resource_type CUSTOM)."""
        with self._slot_lock:
            if name not in self._slots:
                self._slots[name] = factory()
            return self._slots[name]

    def has_resource(self, name: str) -> bool:
        with self._slot_lock:
            return name in self._slots

    # ------------------------------------------------------------------ comms
    @property
    def comms(self):
        """The injected communicator (raft_tpu.parallel.comms.Comms) or None.

        Analog of ``resource::get_comms(handle)`` (reference:
        core/resource/comms.hpp); raises if none injected, matching the
        reference's behavior of failing when the COMMUNICATOR slot is unset.
        """
        if self._comms is None:
            raise RuntimeError(
                "No communicator injected into this Resources; call "
                "raft_tpu.parallel.comms.inject_comms(res, ...) first."
            )
        return self._comms

    @property
    def has_comms(self) -> bool:
        return self._comms is not None

    # ------------------------------------------------------------------- sync
    def sync(self, *arrays) -> None:
        """Block until given arrays (or all dispatched work) are ready.

        Analog of ``resource::sync_stream``; under JAX, async dispatch means
        results materialize lazily — tests and benchmarks call this to fence.
        """
        if arrays:
            for a in jax.tree_util.tree_leaves(arrays):
                if isinstance(a, jax.Array):
                    a.block_until_ready()
        else:
            # Fence the whole device queue.
            jax.effects_barrier()


def solve_joint_tiles(
    budget_bytes: int,
    bytes_per_cell: int,
    inner_max: int,
    outer_cap: int = 256,
    outer_multiple: int = 8,
) -> tuple:
    """Jointly size an (outer_tile, inner_tile) loop nest so the peak live
    set ``outer_tile * inner_tile * bytes_per_cell`` stays within
    ``budget_bytes`` (the workspace analog of the reference's
    limiting_memory_resource sizing batch loops).

    ``bytes_per_cell`` is the caller's accounting of the TRUE peak live
    set per (outer, inner) cell — every simultaneously-live intermediate,
    not just the largest named array. The solve prefers the full inner
    extent (no inner loop) with the largest outer tile; when even a
    minimal outer tile cannot hold the full inner extent it shrinks the
    inner tile instead, and degrades to (1, 1) only when a single cell
    exceeds the budget (the loop still runs; past that point the budget
    is a target, not a guarantee).

    Returns ``(outer_tile, inner_tile)`` with ``outer_tile`` a multiple of
    ``outer_multiple`` (when >= it) capped at ``outer_cap``, and
    ``1 <= inner_tile <= inner_max``.
    """
    budget = max(int(budget_bytes), 1)
    cell = max(int(bytes_per_cell), 1)
    inner_max = max(int(inner_max), 1)
    outer = budget // (cell * inner_max)
    if outer >= outer_multiple:
        outer = min(outer, outer_cap)
        outer -= outer % outer_multiple
        return outer, inner_max
    # the full inner extent does not fit even a lane-aligned outer tile:
    # tile the inner loop so the peak is [outer, inner_tile, ...]
    outer = outer_multiple if budget // (outer_multiple * cell) >= 1 else 1
    inner = int(np.clip(budget // (outer * cell), 1, inner_max))
    return outer, inner


def solve_vmem_tiles(
    budget_bytes: int,
    cell_bytes: int,
    outer_bytes: int,
    inner_bytes: int,
    inner_max: int,
    fixed_bytes: int = 0,
    outer_cap: int = 256,
    outer_multiple: int = 8,
    inner_multiple: int = 128,
) -> tuple:
    """``solve_joint_tiles`` generalized from the HBM workspace to a fused
    kernel's ~16 MiB VMEM arena: size an (outer_tile, inner_tile) grid so

        fixed + outer·outer_bytes + inner·inner_bytes + outer·inner·cell_bytes

    stays within ``budget_bytes``. The affine terms are what VMEM adds over
    the HBM model: per-row blocks (query vectors, the running top-k carry)
    scale with ONE axis while the distance tile scales with both, and the
    whole set must be simultaneously resident on-chip for the kernel's
    revisited output block to stay live across inner iterations.

    Mirrors ``solve_joint_tiles``' preference order: the full inner extent
    at the largest aligned outer tile first; shrink the inner tile only
    when a minimal outer tile cannot hold the full extent; degrade to
    ``(outer_multiple, inner_multiple)`` when even one aligned cell
    exceeds the budget (the kernel still runs; past that point the budget
    is a target, not a guarantee).

    Returns ``(outer_tile, inner_tile)`` with ``outer_tile`` a multiple of
    ``outer_multiple`` capped at ``outer_cap`` and ``inner_tile`` a
    multiple of ``inner_multiple`` capped at ``inner_max`` (rounded up to
    the multiple — lane alignment on TPU)."""
    budget = max(int(budget_bytes) - int(fixed_bytes), 1)
    cell = max(int(cell_bytes), 0)
    outer_b = max(int(outer_bytes), 0)
    inner_b = max(int(inner_bytes), 0)
    inner_max = max(int(inner_max), 1)
    inner_max += (-inner_max) % inner_multiple
    # full inner extent: budget pays inner_max·inner_bytes once, then each
    # outer row costs outer_bytes + inner_max·cell
    per_outer = outer_b + inner_max * cell
    outer = (budget - inner_max * inner_b) // max(per_outer, 1)
    if outer >= outer_multiple:
        outer = min(outer, outer_cap)
        outer -= outer % outer_multiple
        return outer, inner_max
    # tile the inner axis at the minimal aligned outer tile
    outer = outer_multiple
    per_inner = inner_b + outer * cell
    inner = (budget - outer * outer_b) // max(per_inner, 1)
    inner = min(inner, inner_max)
    inner -= inner % inner_multiple
    if inner >= inner_multiple:
        return outer, inner
    return outer_multiple, inner_multiple


def solve_merge_bytes(size: int, nq: int, kk: int, k_out: int,
                      val_bytes: int = 4, idx_bytes: int = 4,
                      pos_bytes: int = 4) -> dict:
    """Predicted per-device cross-chip RECEIVE bytes for each sharded
    top-k merge engine (parallel/sharded.py merge_mode) — the planner side
    of the roofline calibration obs/costs.py checks against the compiled
    HLO's collective shapes.

    - ``allgather``: every device materializes the full [nq, size·kk]
      value+id slab; (size-1)/size of it arrives over ICI.
    - ``tree``: log₂(size) hypercube rounds; round r receives a
      min(k_out, kk·2^r)-wide (value, pos, id) carry from the partner.
    - ``ring``: size-1 neighbor hops of the fixed [nq, kk] (value, pos,
      id) block — more total bytes than the tree, but a constant-shape
      transfer the RDMA kernel overlaps with local compute.
    """
    size, nq, kk, k_out = int(size), int(nq), int(kk), int(k_out)
    pair = val_bytes + idx_bytes
    triple = pair + pos_bytes
    out = {
        "allgather": (size - 1) * nq * kk * pair,
        "ring": (size - 1) * nq * kk * triple,
    }
    tree = 0
    width, step = kk, 1
    while step < size:
        tree += nq * width * triple
        width = min(k_out, 2 * width)
        step *= 2
    # non-power-of-two meshes never take the tree path (dispatch falls
    # back to allgather); report the allgather cost so the prediction
    # matches what would compile
    out["tree"] = tree if size >= 2 and (size & (size - 1)) == 0 \
        else out["allgather"]
    return out


def solve_host_tier(n_lists: int, list_pad: int, rot_dim: int,
                    n_code_bytes: int, workspace_limit_bytes: int,
                    n_probes: int = 20, max_batch: int = 64,
                    cache_itemsize: int = 2, arena_fraction: float = 0.5,
                    host_bw_bytes_per_s: float = 8e9) -> dict:
    """Byte/bandwidth model for the HBM-as-cache tier
    (neighbors/tiered.py): size the device slab arena from the
    workspace budget and predict the host-tier footprint and per-slab
    fetch cost. The C001 calibration audit (obs/costs.py) and the
    tiered smoke test pin these predictions against measured bytes.

    Per-slot device cost (one decoded list slab):

        slab_bytes = list_pad · (rot_dim·cache_itemsize + 4 + 4) + 4

    (decoded residuals + f32 norms + i32 ids, plus the i32 size) — the
    exact ``nbytes`` sum of the arena's four arrays. ``arena_fraction``
    of the workspace budget goes to slots, floored at ``n_probes`` (one
    query's probes must be co-resident) and capped at ``n_lists``
    (beyond that the tier degenerates to the resident cache engine).

    Host-side truth: packed codes + ids + norms per list, plus the
    sizes vector. The fetch model is per-slab payload over an assumed
    pinned-host→HBM bandwidth (DMA-dominated; the measured stall
    histogram ``raft_tpu_tier_fetch_stall_seconds`` is its check).

    ``worst_batch_distinct`` is the sizing constraint a caller must
    respect: one batch can probe up to ``max_batch · n_probes``
    distinct lists, and the arena must hold them simultaneously or the
    resolve raises ``TieredArenaError``.
    """
    n_lists = max(int(n_lists), 1)
    list_pad = max(int(list_pad), 1)
    slab_bytes = list_pad * (rot_dim * cache_itemsize + 4 + 4) + 4
    arena_budget = int(max(workspace_limit_bytes, 0) * arena_fraction)
    floor_slots = min(n_lists, max(int(n_probes), 1))
    arena_slots = int(np.clip(arena_budget // max(slab_bytes, 1),
                              floor_slots, n_lists))
    host_bytes_per_list = list_pad * (n_code_bytes + 4 + 4)
    fetch_bytes = list_pad * (n_code_bytes + 4 + 4) + 4
    worst = min(n_lists, int(max_batch) * max(int(n_probes), 1))
    return {
        "arena_slots": arena_slots,
        "slab_bytes": slab_bytes,
        "arena_bytes": arena_slots * slab_bytes,
        "host_bytes": n_lists * host_bytes_per_list + 4 * n_lists,
        "fetch_bytes_per_slab": fetch_bytes,
        "predicted_fetch_s": fetch_bytes / max(host_bw_bytes_per_s, 1.0),
        "worst_batch_distinct": worst,
    }


_default_resources: Optional[Resources] = None
_default_lock = threading.Lock()


def default_resources() -> Resources:
    """Process-wide default Resources (analog of device_resources_manager —
    reference: core/device_resources_manager.hpp:36-95)."""
    global _default_resources
    with _default_lock:
        if _default_resources is None:
            _default_resources = Resources()
        return _default_resources


def ensure_resources(res: Optional[Resources]) -> Resources:
    """Internal helper: APIs accept ``res=None`` and fall back to the default."""
    return res if res is not None else default_resources()
