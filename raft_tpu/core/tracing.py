"""Tracing & profiling annotations.

Reference: NVTX RAII ranges at every nontrivial entry point
(core/nvtx.hpp:25-91 — ``common::nvtx::range``; enabled by the RAFT_NVTX
CMake flag, cpp/CMakeLists.txt:262-263) consumed by Nsight.

TPU-native design: ``jax.named_scope`` tags the HLO so ranges appear in
XLA/xprof traces; ``jax.profiler`` start/stop covers the Nsight role.
``range`` works as both a context manager and a decorator, like the
reference's RAII type + RAFT_NVTX_FUNC_RANGE macro."""

from __future__ import annotations

import contextlib
import functools
from typing import Optional

import jax


class range:  # noqa: A001 — mirrors nvtx::range naming
    """Named trace scope (context manager or decorator).

    Analog of ``common::nvtx::range`` (core/nvtx.hpp:25-91): inside jit the
    scope names the emitted HLO ops (visible in xprof); outside jit it
    annotates the host timeline via TraceAnnotation."""

    def __init__(self, name: str):
        self.name = name
        self._scope = None

    def __enter__(self):
        self._scope = jax.named_scope(self.name)
        self._scope.__enter__()
        return self

    def __exit__(self, *exc):
        scope, self._scope = self._scope, None
        return scope.__exit__(*exc)

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with jax.named_scope(self.name):
                return fn(*args, **kwargs)

        return wrapper


@contextlib.contextmanager
def profile(log_dir: str = "/tmp/raft_tpu_trace",
            host_tracer_level: int = 2):
    """Capture an xprof/Perfetto trace around a region (the Nsight-capture
    analog): ``with tracing.profile('/tmp/trace'): search(...)``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def annotate(name: Optional[str] = None):
    """Decorator form: @annotate("ivf_pq::search")."""

    def deco(fn):
        return range(name or fn.__qualname__)(fn)

    return deco
