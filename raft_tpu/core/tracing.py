"""Tracing & profiling annotations.

Reference: NVTX RAII ranges at every nontrivial entry point
(core/nvtx.hpp:25-91 — ``common::nvtx::range``; enabled by the RAFT_NVTX
CMake flag, cpp/CMakeLists.txt:262-263) consumed by Nsight.

TPU-native design: ``jax.named_scope`` tags the HLO so ranges appear in
XLA/xprof traces, and ``jax.profiler.TraceAnnotation`` marks the host
timeline so the Python-side interval (queue wait, pad/copy) lines up
with the device stream in the same capture. ``jax.profiler`` start/stop
covers the Nsight role (see also :func:`raft_tpu.obs.profile_session`,
which adds session accounting on the metrics registry).

``range`` works as both a context manager and a decorator, like the
reference's RAII type + RAFT_NVTX_FUNC_RANGE macro. graftcheck rule
R006 requires it on every public neighbors ``search``/``build``/``knn``
entry point (docs/analysis.md)."""

from __future__ import annotations

import contextlib
import functools
from typing import Optional

import jax


class range:  # noqa: A001 — mirrors nvtx::range naming
    """Named trace scope (context manager or decorator).

    Analog of ``common::nvtx::range`` (core/nvtx.hpp:25-91): inside jit
    the scope names the emitted HLO ops (visible in xprof); the
    TraceAnnotation marks the wall-clock interval on the host timeline.
    Exceptions propagate unchanged; one instance nests and re-enters
    safely (each ``__enter__`` pushes its own scope pair)."""

    def __init__(self, name: str):
        self.name = name
        self._stack = []

    def _scopes(self) -> contextlib.ExitStack:
        stack = contextlib.ExitStack()
        stack.enter_context(jax.named_scope(self.name))
        stack.enter_context(jax.profiler.TraceAnnotation(self.name))
        return stack

    def __enter__(self):
        self._stack.append(self._scopes())
        return self

    def __exit__(self, *exc):
        return self._stack.pop().__exit__(*exc)

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with self._scopes():
                return fn(*args, **kwargs)

        return wrapper


@contextlib.contextmanager
def profile(log_dir: str = "/tmp/raft_tpu_trace",
            host_tracer_level: int = 2):
    """Capture an xprof/Perfetto trace around a region (the Nsight-capture
    analog): ``with tracing.profile('/tmp/trace'): search(...)``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def annotate(name: Optional[str] = None):
    """Decorator form: @annotate("ivf_pq::search")."""

    def deco(fn):
        return range(name or fn.__qualname__)(fn)

    return deco
