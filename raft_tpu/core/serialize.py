"""Array + index (de)serialization, numpy-``.npy`` compatible.

The reference serializes every mdspan in numpy .npy format so index files are
language-interchangeable (reference: core/serialize.hpp:36-145,
core/detail/mdspan_numpy_serializer.hpp:42-161), and each ANN index writes a
version constant followed by scalars + arrays (e.g.
neighbors/detail/ivf_pq_serialize.cuh). We keep the same container model:

- ``serialize_array`` / ``deserialize_array``: one jax/numpy array in .npy
  format on a binary stream (delegates to numpy, which *is* the format).
- ``serialize_scalar`` / ``deserialize_scalar``: fixed-dtype little-endian
  scalars (reference serializes scalars via a 0-d mdspan; we write raw dtype
  bytes with an explicit dtype tag for robustness).
- ``IndexWriter`` / ``IndexReader``: magic + named-version header, then an
  ordered sequence of scalars and arrays — the pattern every index's
  serialize/deserialize uses.

Integrity (container format v2): every record is framed
``[u64 payload_len][payload][u32 crc32]`` and the writer's ``finish()``
appends a length-prefixed footer carrying the record count and total payload
bytes. The reader verifies each record's crc as it is consumed and
``finish()`` verifies the footer, so a restore can tell apart

- **missing** — the file is not there at all (``FileNotFoundError`` /
  manifest check),
- **truncated** — the stream ends mid-record or before the footer, and
- **corrupt** — a record's bytes do not match its crc,

each raised as a typed :class:`~raft_tpu.core.errors.IntegrityError` naming
the file and the record. v1 files (unframed, no footer) are still readable:
the header's format version selects the decode path.

``writer_for(path)`` makes file writes atomic (tmp + ``os.replace``): a
crash mid-serialize leaves the previous checkpoint intact instead of a
half-written file that only fails at the next restore.
"""

from __future__ import annotations

import contextlib
import io
import os
import struct
import zlib
from typing import BinaryIO, List, Optional, Tuple, Union

import jax
import numpy as np

from raft_tpu.core.errors import IntegrityError

_MAGIC = b"RAFT_TPU_IDX"
# v2: per-record [u64 len][payload][u32 crc32] framing + footer
_SERIALIZATION_VERSION = 2
_FOOTER_MAGIC = b"RTFT"
_FRAME_LEN = struct.Struct("<Q")
_FRAME_CRC = struct.Struct("<I")
#: public aliases for append-only consumers (the mutable-index WAL)
#: that parse frames themselves to classify damage by file position.
FRAME_LEN = _FRAME_LEN
FRAME_CRC = _FRAME_CRC

ArrayLike = Union[np.ndarray, "jax.Array"]


def _to_numpy(a: ArrayLike) -> np.ndarray:
    if isinstance(a, np.ndarray):
        return a
    return np.asarray(jax.device_get(a))


def serialize_array(stream: BinaryIO, a: ArrayLike) -> None:
    """Write one array in .npy format (same wire format as the reference's
    serialize_mdspan — core/serialize.hpp:36)."""
    np.save(stream, _to_numpy(a), allow_pickle=False)


def deserialize_array(stream: BinaryIO) -> np.ndarray:
    return np.load(stream, allow_pickle=False)


def serialize_scalar(stream: BinaryIO, value, dtype: str) -> None:
    """Write a tagged little-endian scalar (dtype in numpy str form)."""
    dt = np.dtype(dtype).newbyteorder("<")
    tag = dt.str.encode()
    stream.write(struct.pack("<B", len(tag)))
    stream.write(tag)
    stream.write(np.asarray(value, dtype=dt).tobytes())


def deserialize_scalar(stream: BinaryIO):
    head = stream.read(1)
    if len(head) < 1:
        raise IntegrityError("scalar truncated: no dtype-tag length byte",
                             reason="truncated")
    (tag_len,) = struct.unpack("<B", head)
    tag = stream.read(tag_len)
    if len(tag) < tag_len:
        raise IntegrityError("scalar truncated mid dtype tag",
                             reason="truncated")
    try:
        dt = np.dtype(tag.decode())
    except (TypeError, ValueError, UnicodeDecodeError) as e:
        raise IntegrityError(
            f"bad scalar dtype tag {tag!r}: not a numpy dtype "
            f"(corrupt stream?)", reason="corrupt") from e
    raw = stream.read(dt.itemsize)
    if len(raw) < dt.itemsize:
        raise IntegrityError(
            f"scalar truncated: {len(raw)} of {dt.itemsize} value bytes",
            reason="truncated")
    return np.frombuffer(raw, dtype=dt)[0].item()


# ------------------------------------------------------------ file helpers


def _is_pathlike(file_or_stream) -> bool:
    return (isinstance(file_or_stream, (str, bytes))
            or hasattr(file_or_stream, "__fspath__"))


def open_for(file_or_stream, mode: str):
    """Return (stream, should_close) for a path or an already-open stream."""
    if _is_pathlike(file_or_stream):
        return open(file_or_stream, mode), True
    return file_or_stream, False


@contextlib.contextmanager
def writer_for(file_or_stream):
    """Binary-write context for a path or stream. Paths are written
    ATOMICALLY: bytes go to ``<path>.tmp.<pid>`` and ``os.replace`` installs
    them only after the body (including any ``IndexWriter.finish()``)
    succeeded — a crash mid-serialize can truncate only the tmp file, never
    an existing checkpoint. Streams pass through unchanged (caller owns
    their lifetime)."""
    if not _is_pathlike(file_or_stream):
        yield file_or_stream
        return
    path = os.fsdecode(file_or_stream if not hasattr(
        file_or_stream, "__fspath__") else os.fspath(file_or_stream))
    tmp = f"{path}.tmp.{os.getpid()}"
    stream = open(tmp, "wb")
    try:
        yield stream
        stream.flush()
        os.fsync(stream.fileno())
        stream.close()
        os.replace(tmp, path)
    except BaseException:
        stream.close()
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


@contextlib.contextmanager
def reader_for(file_or_stream):
    """Binary-read context symmetric with :func:`writer_for`."""
    stream, close = open_for(file_or_stream, "rb")
    try:
        yield stream
    finally:
        if close:
            stream.close()


def frame(payload: bytes) -> bytes:
    """One v2 record frame (``[u64 len][payload][u32 crc32]``) as raw
    bytes — for append-only files (the mutable-index WAL) that write
    frames past a :func:`header_bytes` header with no footer."""
    return _FRAME_LEN.pack(len(payload)) + payload \
        + _FRAME_CRC.pack(zlib.crc32(payload))


def header_bytes(kind: str, version: int) -> bytes:
    """The v2 container header (magic + format version + kind +
    version) as raw bytes. Files headed this way are recognized by
    :func:`record_spans` and the byte-level fault injectors even when
    they frame their own records (the mutable-index WAL)."""
    buf = io.BytesIO()
    IndexWriter(buf, kind, version)
    return buf.getvalue()


def _stream_name(stream, name: Optional[str]) -> str:
    if name is not None:
        return name
    got = getattr(stream, "name", None)
    return got if isinstance(got, str) else "<stream>"


class IndexWriter:
    """Header + ordered payload writer used by every index's serialize().

    Format v2 frames each record with a length prefix and crc32; call
    :meth:`finish` after the last record to append the footer (readers use
    it to tell a complete file from one truncated at a record boundary).
    """

    def __init__(self, stream: BinaryIO, kind: str, version: int):
        self.stream = stream
        stream.write(_MAGIC)
        stream.write(struct.pack("<I", _SERIALIZATION_VERSION))
        kind_b = kind.encode()
        stream.write(struct.pack("<I", len(kind_b)))
        stream.write(kind_b)
        stream.write(struct.pack("<I", version))
        self._n_records = 0
        self._payload_bytes = 0

    def _record(self, payload: bytes) -> None:
        self.stream.write(_FRAME_LEN.pack(len(payload)))
        self.stream.write(payload)
        self.stream.write(_FRAME_CRC.pack(zlib.crc32(payload)))
        self._n_records += 1
        self._payload_bytes += len(payload)

    def scalar(self, value, dtype: str) -> "IndexWriter":
        buf = io.BytesIO()
        serialize_scalar(buf, value, dtype)
        self._record(buf.getvalue())
        return self

    def string(self, s: str) -> "IndexWriter":
        self._record(s.encode())
        return self

    def array(self, a: ArrayLike) -> "IndexWriter":
        buf = io.BytesIO()
        serialize_array(buf, a)
        self._record(buf.getvalue())
        return self

    def blob(self, b: bytes) -> "IndexWriter":
        """One opaque byte record — e.g. a whole nested index file
        (the mutable-index checkpoint embeds its base's serialization
        as a single crc-framed record)."""
        self._record(bytes(b))
        return self

    def finish(self) -> "IndexWriter":
        """Append the length-prefixed footer (record count + payload bytes).
        A file without it reads as truncated under ``IndexReader.finish``."""
        payload = _FOOTER_MAGIC + struct.pack(
            "<IQ", self._n_records, self._payload_bytes)
        self.stream.write(_FRAME_LEN.pack(len(payload)))
        self.stream.write(payload)
        self.stream.write(_FRAME_CRC.pack(zlib.crc32(payload)))
        return self


class IndexReader:
    def __init__(self, stream: BinaryIO, kind: str, max_version: int,
                 name: Optional[str] = None):
        self.stream = stream
        self.name = _stream_name(stream, name)
        magic = stream.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(
                f"{self.name}: bad magic {magic!r}: not a raft_tpu index "
                f"file")
        (fmt_ver,) = struct.unpack("<I", stream.read(4))
        if fmt_ver > _SERIALIZATION_VERSION:
            raise ValueError(
                f"{self.name}: serialization format v{fmt_ver} is newer "
                f"than supported")
        self.fmt_version = fmt_ver
        (kind_len,) = struct.unpack("<I", stream.read(4))
        found = stream.read(kind_len).decode()
        if found != kind:
            raise ValueError(
                f"{self.name}: index kind mismatch: file has {found!r}, "
                f"expected {kind!r}")
        (self.version,) = struct.unpack("<I", stream.read(4))
        if self.version > max_version:
            raise ValueError(
                f"{self.name}: {kind} index version {self.version} is newer "
                f"than supported {max_version}"
            )
        self._n_records = 0
        self._payload_bytes = 0

    # ----------------------------------------------------------- v2 frames
    def _truncated(self, detail: str) -> IntegrityError:
        return IntegrityError(
            f"{self.name}: record {self._n_records}: truncated ({detail})",
            path=self.name, record=self._n_records, reason="truncated")

    def _next_record(self) -> bytes:
        hdr = self.stream.read(_FRAME_LEN.size)
        if len(hdr) < _FRAME_LEN.size:
            raise self._truncated(
                "stream ends before the record's length prefix — file cut "
                "at a record boundary, or footer missing")
        (n,) = _FRAME_LEN.unpack(hdr)
        payload = self.stream.read(n)
        if len(payload) < n:
            raise self._truncated(
                f"{len(payload)} of {n} payload bytes present")
        crc_raw = self.stream.read(_FRAME_CRC.size)
        if len(crc_raw) < _FRAME_CRC.size:
            raise self._truncated("stream ends inside the record's crc")
        (crc,) = _FRAME_CRC.unpack(crc_raw)
        if zlib.crc32(payload) != crc:
            raise IntegrityError(
                f"{self.name}: record {self._n_records}: crc32 mismatch "
                f"(corrupt payload, {n} bytes)",
                path=self.name, record=self._n_records, reason="corrupt")
        self._n_records += 1
        self._payload_bytes += n
        return payload

    # -------------------------------------------------------------- records
    def scalar(self):
        if self.fmt_version < 2:
            return deserialize_scalar(self.stream)
        try:
            return deserialize_scalar(io.BytesIO(self._next_record()))
        except IntegrityError as e:
            if e.path is None:  # scalar-level fault inside a valid frame
                raise IntegrityError(
                    f"{self.name}: record {self._n_records - 1}: {e}",
                    path=self.name, record=self._n_records - 1,
                    reason=e.reason) from e
            raise

    def string(self) -> str:
        if self.fmt_version < 2:
            (n,) = struct.unpack("<I", self.stream.read(4))
            return self.stream.read(n).decode()
        return self._next_record().decode()

    def array(self) -> np.ndarray:
        if self.fmt_version < 2:
            return deserialize_array(self.stream)
        payload = self._next_record()
        try:
            return np.load(io.BytesIO(payload), allow_pickle=False)
        except ValueError as e:
            raise IntegrityError(
                f"{self.name}: record {self._n_records - 1}: npy payload "
                f"failed to parse despite matching crc: {e}",
                path=self.name, record=self._n_records - 1,
                reason="corrupt") from e

    def blob(self) -> bytes:
        """One opaque byte record (see :meth:`IndexWriter.blob`). v2
        only — v1 files carry no self-describing record boundaries."""
        if self.fmt_version < 2:
            raise ValueError(
                f"{self.name}: blob records need v2 framing; this file "
                f"is format v{self.fmt_version}")
        return self._next_record()

    def finish(self) -> None:
        """Verify the footer (v2 files): record count and payload bytes must
        match what was read. No-op for v1 files (they carry no footer)."""
        if self.fmt_version < 2:
            return
        n_read, bytes_read = self._n_records, self._payload_bytes
        payload = self._next_record()
        self._n_records, self._payload_bytes = n_read, bytes_read
        if (len(payload) != len(_FOOTER_MAGIC) + 12
                or payload[:len(_FOOTER_MAGIC)] != _FOOTER_MAGIC):
            raise IntegrityError(
                f"{self.name}: footer record is malformed (extra records "
                f"after the expected field set?)",
                path=self.name, record=n_read, reason="corrupt")
        n_records, payload_bytes = struct.unpack(
            "<IQ", payload[len(_FOOTER_MAGIC):])
        if n_records != n_read or payload_bytes != bytes_read:
            raise IntegrityError(
                f"{self.name}: footer declares {n_records} records / "
                f"{payload_bytes} payload bytes but {n_read} / {bytes_read} "
                f"were read — reader/writer field-set mismatch",
                path=self.name, record=n_read, reason="corrupt")


def record_spans(path) -> List[Tuple[int, int]]:
    """[(payload_offset, payload_len)] of every framed record in a v2 index
    file, footer included as the last entry. The fault-injection harness
    uses this to flip or truncate a specific record; raises ValueError for
    v1 (unframed) files whose record boundaries are not self-describing."""
    spans: List[Tuple[int, int]] = []
    with open(path, "rb") as stream:
        magic = stream.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"{path}: not a raft_tpu index file")
        (fmt_ver,) = struct.unpack("<I", stream.read(4))
        if fmt_ver < 2:
            raise ValueError(
                f"{path}: format v{fmt_ver} records are unframed; spans are "
                f"only recoverable for v2+ files")
        (kind_len,) = struct.unpack("<I", stream.read(4))
        stream.read(kind_len)
        stream.read(4)  # kind version
        while True:
            hdr = stream.read(_FRAME_LEN.size)
            if not hdr:
                return spans
            if len(hdr) < _FRAME_LEN.size:
                return spans  # trailing garbage / truncation: stop cleanly
            (n,) = _FRAME_LEN.unpack(hdr)
            off = stream.tell()
            spans.append((off, n))
            stream.seek(n + _FRAME_CRC.size, os.SEEK_CUR)
            if stream.tell() > os.fstat(stream.fileno()).st_size:
                return spans


def file_crc32(path, chunk: int = 1 << 20) -> int:
    """Whole-file crc32 (the manifest digest)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)
