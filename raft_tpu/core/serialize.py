"""Array + index (de)serialization, numpy-``.npy`` compatible.

The reference serializes every mdspan in numpy .npy format so index files are
language-interchangeable (reference: core/serialize.hpp:36-145,
core/detail/mdspan_numpy_serializer.hpp:42-161), and each ANN index writes a
version constant followed by scalars + arrays (e.g.
neighbors/detail/ivf_pq_serialize.cuh). We keep the same container model:

- ``serialize_array`` / ``deserialize_array``: one jax/numpy array in .npy
  format on a binary stream (delegates to numpy, which *is* the format).
- ``serialize_scalar`` / ``deserialize_scalar``: fixed-dtype little-endian
  scalars (reference serializes scalars via a 0-d mdspan; we write raw dtype
  bytes with an explicit dtype tag for robustness).
- ``IndexWriter`` / ``IndexReader``: magic + named-version header, then an
  ordered sequence of scalars and arrays — the pattern every index's
  serialize/deserialize uses.
"""

from __future__ import annotations

import struct
from typing import BinaryIO, Union

import jax
import numpy as np

_MAGIC = b"RAFT_TPU_IDX"
_SERIALIZATION_VERSION = 1

ArrayLike = Union[np.ndarray, "jax.Array"]


def _to_numpy(a: ArrayLike) -> np.ndarray:
    if isinstance(a, np.ndarray):
        return a
    return np.asarray(jax.device_get(a))


def serialize_array(stream: BinaryIO, a: ArrayLike) -> None:
    """Write one array in .npy format (same wire format as the reference's
    serialize_mdspan — core/serialize.hpp:36)."""
    np.save(stream, _to_numpy(a), allow_pickle=False)


def deserialize_array(stream: BinaryIO) -> np.ndarray:
    return np.load(stream, allow_pickle=False)


def serialize_scalar(stream: BinaryIO, value, dtype: str) -> None:
    """Write a tagged little-endian scalar (dtype in numpy str form)."""
    dt = np.dtype(dtype).newbyteorder("<")
    tag = dt.str.encode()
    stream.write(struct.pack("<B", len(tag)))
    stream.write(tag)
    stream.write(np.asarray(value, dtype=dt).tobytes())


def deserialize_scalar(stream: BinaryIO):
    (tag_len,) = struct.unpack("<B", stream.read(1))
    dt = np.dtype(stream.read(tag_len).decode())
    val = np.frombuffer(stream.read(dt.itemsize), dtype=dt)[0]
    return val.item()


class IndexWriter:
    """Header + ordered payload writer used by every index's serialize()."""

    def __init__(self, stream: BinaryIO, kind: str, version: int):
        self.stream = stream
        stream.write(_MAGIC)
        stream.write(struct.pack("<I", _SERIALIZATION_VERSION))
        kind_b = kind.encode()
        stream.write(struct.pack("<I", len(kind_b)))
        stream.write(kind_b)
        stream.write(struct.pack("<I", version))

    def scalar(self, value, dtype: str) -> "IndexWriter":
        serialize_scalar(self.stream, value, dtype)
        return self

    def string(self, s: str) -> "IndexWriter":
        b = s.encode()
        self.stream.write(struct.pack("<I", len(b)))
        self.stream.write(b)
        return self

    def array(self, a: ArrayLike) -> "IndexWriter":
        serialize_array(self.stream, a)
        return self


class IndexReader:
    def __init__(self, stream: BinaryIO, kind: str, max_version: int):
        self.stream = stream
        magic = stream.read(len(_MAGIC))
        if magic != _MAGIC:
            raise ValueError(f"bad magic {magic!r}: not a raft_tpu index file")
        (fmt_ver,) = struct.unpack("<I", stream.read(4))
        if fmt_ver > _SERIALIZATION_VERSION:
            raise ValueError(f"serialization format v{fmt_ver} is newer than supported")
        (kind_len,) = struct.unpack("<I", stream.read(4))
        found = stream.read(kind_len).decode()
        if found != kind:
            raise ValueError(
                f"index kind mismatch: file has {found!r}, expected {kind!r}")
        (self.version,) = struct.unpack("<I", stream.read(4))
        if self.version > max_version:
            raise ValueError(
                f"{kind} index version {self.version} is newer than "
                f"supported {max_version}"
            )

    def scalar(self):
        return deserialize_scalar(self.stream)

    def string(self) -> str:
        (n,) = struct.unpack("<I", self.stream.read(4))
        return self.stream.read(n).decode()

    def array(self) -> np.ndarray:
        return deserialize_array(self.stream)


def open_for(file_or_stream, mode: str):
    """Return (stream, should_close) for a path or an already-open stream."""
    if (isinstance(file_or_stream, (str, bytes))
            or hasattr(file_or_stream, "__fspath__")):
        return open(file_or_stream, mode), True
    return file_or_stream, False
