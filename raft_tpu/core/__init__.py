"""Core layer: resources/context, serialization, bitset, logging/tracing.

TPU-native analog of ``cpp/include/raft/core`` (SURVEY.md §2.1). The mdspan/
mdarray machinery of the reference collapses into plain ``jax.Array`` here —
shape/dtype conventions are documented per-API instead of encoded in types.
"""

from raft_tpu.core.resources import Resources, default_resources, ensure_resources
from raft_tpu.core.bitset import Bitset
from raft_tpu.core.errors import RaftError, LogicError, expects, fail
from raft_tpu.core import (interruptible, logger, operators, resources_manager,
                           serialize, tracing)

__all__ = [
    "Resources",
    "default_resources",
    "ensure_resources",
    "Bitset",
    "RaftError",
    "LogicError",
    "expects",
    "fail",
    "interruptible",
    "logger",
    "operators",
    "resources_manager",
    "serialize",
    "tracing",
]
