"""Synthetic benchmark data — single source for bench.py, the BASELINE
target runner, and tests.

Real ANN benchmark datasets (glove/deep/sift embeddings) share two
properties the generator must reproduce or the numbers measure the
generator, not the index: **low intrinsic dimension** (full-dim iid
gaussians concentrate distances, so top-k gaps vanish as dim grows) and
**one connected neighborhood manifold** (widely-separated clusters
disconnect kNN graphs, which no graph walk can cross — only seeding can).
"""

from __future__ import annotations

import numpy as np


def low_rank_clusters(rng: np.random.Generator, n: int, dim: int,
                      n_centers: int = 96, intrinsic: int = 16,
                      spread: float = 1.5) -> np.ndarray:
    """[n, dim] float32: gaussian clusters in an ``intrinsic``-dim latent
    space (unit cluster std, centers ~ N(0, spread²)), embedded in ``dim``
    ambient dims by one shared random projection. ``spread ≈ 1.5`` keeps
    clusters overlapping (connected kNN graph); larger spreads separate
    them (the disconnected regime — a seeding stress test, not a realistic
    benchmark distribution)."""
    proj = rng.standard_normal((intrinsic, dim)).astype(np.float32)
    centers = rng.standard_normal((n_centers, intrinsic)) * spread
    z = (centers[rng.integers(0, n_centers, n)]
         + rng.standard_normal((n, intrinsic)))
    return z.astype(np.float32) @ proj
