"""Benchmark harness (SURVEY.md §2.11/§2.10): raft-ann-bench-compatible
run configs, QPS/recall measurement, CSV + pareto export, groundtruth
generation. CLI: ``python -m raft_tpu.bench --conf <config.json>``."""

from raft_tpu.bench import export, runner
from raft_tpu.bench.runner import (
    ALGOS,
    AnnAlgo,
    DatasetSpec,
    generate_groundtruth,
    run_benchmark,
)

__all__ = ["export", "runner", "ALGOS", "AnnAlgo", "DatasetSpec",
           "generate_groundtruth", "run_benchmark"]
