"""Tunnel-safe timing primitives shared by every benchmark entry point.

Measured on the axon TPU tunnel (2026-07-31, TPU_PROBE.json era):

- ``jax.Array.block_until_ready()`` returns when the remote enqueue is
  acknowledged, NOT when execution completes — an 8192³ bf16 matmul
  "finished" in 0.03 ms (34 PFLOP/s, physically impossible; the chained
  in-jit measurement gives 139 TFLOP/s ≈ 70% of v5e peak). The only
  honest completion fence is a host readback of data that depends on the
  result.
- A host readback costs ~75-80 ms round-trip, and bulk transfers run at
  ~16 MB/s up / ~7 MB/s down. Timed regions must therefore (a) amortize
  ONE fence over many asynchronously dispatched repeats, and (b) never
  contain a host→device upload of benchmark inputs.

These helpers also behave correctly (just redundantly) on CPU/GPU where
``block_until_ready`` does wait. This is the TPU analog of the CUDA-event
timing fixture the reference benches use
(``/root/reference/cpp/bench/prims/common/benchmark.hpp:84-105``): events
fence device work without stalling the pipeline per iteration.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "fence",
    "fence_index",
    "fence_overhead",
    "prepare",
    "time_dispatches",
    "time_latency_chained",
    "chain_perturb",
    "last_info",
]

# Populated by time_dispatches / time_latency_chained after every
# measurement: {"rtt_bound": bool, "fence_overhead_frac": float,
# "samples_s": [per-round per-iter seconds]}. A loop that is still
# RTT-dominated when iteration scaling gives up (the _MAX_ITERS / HBM
# caps) returns a noise-bound number; callers that persist results
# should record this flag so artifacts distinguish clean from
# noise-bound measurements (ADVICE r3). "samples_s" holds one sample per
# fenced round (len == the rounds argument), so callers can report
# percentiles instead of a mean that hides host-contention skew (the r5
# 37-45 ms b1 outliers sat invisible under a 6 ms mean for a whole
# round). Contract: read IMMEDIATELY after the timing call returns — the
# next timing call (including any nested inside a dispatch fn)
# overwrites it.
last_info: dict = {"rtt_bound": False, "fence_overhead_frac": 0.0,
                   "samples_s": []}


def fence(out: Any) -> int:
    """Block until every execution producing ``out``'s array leaves has
    completed, via a single scalar-per-leaf host readback.

    An XLA execution is atomic, so reading one element of one output
    forces the whole execution (and its dependencies) to finish; probing
    every leaf covers outputs produced by distinct dispatches. All probes
    are fetched in ONE transfer so the tunnel round-trip is paid once.
    Returns the number of leaves fenced (0 = pure-host data, no readback
    paid — timed loops must then skip the RTT subtraction).
    """
    leaves = [l for l in jax.tree_util.tree_leaves(out)
              if isinstance(l, jax.Array)]
    if not leaves:
        return 0
    try:
        probes = [jnp.ravel(l)[:1].astype(jnp.float32) for l in leaves]
        np.asarray(jnp.concatenate(probes))
    except ValueError:
        # leaves committed to different devices can't be concatenated into
        # one probe (multichip tooling); pay one readback per leaf instead
        for l in leaves:
            np.asarray(jax.device_get(jnp.ravel(l)[:1]))
    return len(leaves)


def fence_index(index: Any) -> None:
    """Fence a built ANN index: readback-probe every jax.Array it holds
    (indexes are plain classes; a slotted/NamedTuple type without
    ``__dict__`` degrades to fencing nothing rather than raising)."""
    attrs = getattr(index, "__dict__", {})
    fence(list(attrs.values()))


_FENCE_OVERHEAD_S: float | None = None


def fence_overhead() -> float:
    """Median cost of fencing already-ready data — the tunnel's readback
    round-trip (~75-80 ms on axon, ~µs locally). Measured once per
    process and cached; subtracted from timed loops so short-timescale
    measurements (sub-ms select_k, single-query latency) aren't swamped
    by the harness. The subtraction slightly over-corrects when the
    readback overlaps trailing device work, so timed loops floor at a
    tenth of the raw measurement."""
    global _FENCE_OVERHEAD_S
    if _FENCE_OVERHEAD_S is None:
        x = jnp.zeros((8,), jnp.float32)
        fence(x)
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            fence(x)
            samples.append(time.perf_counter() - t0)
        _FENCE_OVERHEAD_S = sorted(samples)[1]
    return _FENCE_OVERHEAD_S


def _amortize(elapsed: float, iters: int, fenced: bool = True) -> float:
    """Per-iteration seconds with the single fence round-trip removed
    (floored: the correction must never produce zero/negative time).
    Also records whether this measurement is noise-bound (``last_info``).
    A loop that fenced nothing (pure-host algos: numpy in/out, no device
    arrays) paid no readback, so nothing is subtracted — otherwise the
    correction would inflate exactly the CPU-baseline QPS it exists to
    keep honest."""
    last_info["samples_s"] = []  # a multi-round caller refills after
    if not fenced:
        last_info["rtt_bound"] = False
        last_info["fence_overhead_frac"] = 0.0
        return elapsed / iters
    oh = fence_overhead()
    last_info["rtt_bound"] = bool(elapsed < 5 * oh)
    last_info["fence_overhead_frac"] = round(oh / max(elapsed, 1e-12), 4)
    return max(elapsed - oh, elapsed * 0.1) / iters


_MAX_ITERS = 4096


def _scaled_iters(elapsed: float, iters: int) -> Optional[int]:
    """When the fence round-trip dominates a measured loop (sub-ms work on
    the ~75 ms tunnel), the subtraction is noise-bound — return a larger
    iteration count that makes real work ~10x the RTT, or None if the
    measurement already dominates (or the cap is hit)."""
    oh = fence_overhead()
    if elapsed >= 5 * oh or iters >= _MAX_ITERS:
        return None
    per_iter = max((elapsed - oh) / iters, elapsed * 0.02 / iters, 1e-7)
    return int(min(_MAX_ITERS, max(iters * 2, (10 * oh) / per_iter)))


def prepare(x: Any) -> Any:
    """Move inputs to device OUTSIDE the timed region (uploads ride the
    slow tunnel link) and fence so the transfer cannot leak into timing."""
    def _put(a):
        if isinstance(a, jax.Array):
            return a  # already device-resident: never round-trip the link
        if isinstance(a, np.ndarray):
            return jax.device_put(a)
        return a

    out = jax.tree_util.tree_map(_put, x)
    fence(out)
    return out


def time_dispatches(dispatch: Callable[[], Any], iters: int = 5,
                    warmup: int = 1) -> float:
    """Wall seconds per ``dispatch()``: ``iters`` asynchronous dispatches,
    one fence at the end (throughput mode — the chip stays saturated by
    in-flight work, matching the reference's thread-pool throughput mode,
    raft_ann_benchmarks.md:154)."""
    # RTT calibration happens lazily in _amortize/_scaled_iters (fenced
    # loops only) — an eager fence_overhead() here would force device
    # backend init even for pure-host loops, and on a dead tunnel that
    # hangs a baselines-only run in make_c_api_client.
    for _ in range(warmup):
        fence(dispatch())
    while True:
        t0 = time.perf_counter()
        outs = [dispatch() for _ in range(iters)]
        fenced = fence(outs) > 0
        elapsed = time.perf_counter() - t0
        nxt = _scaled_iters(elapsed, iters) if fenced else None
        if nxt is not None:
            # every retained result stays alive on device until the fence:
            # cap in-flight growth so scaled loops can't exhaust HBM
            out_bytes = sum(
                l.size * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(outs[0])
                if isinstance(l, jax.Array)) or 1
            nxt = min(nxt, max(iters, (1 << 30) // out_bytes))
        if nxt is None or nxt <= iters:
            return _amortize(elapsed, iters, fenced)
        iters = nxt  # RTT-dominated: amortize over more dispatches


def time_latency_chained(step: Callable[[Any], Any], x0: Any,
                         iters: int = 8, rounds: int = 1) -> float:
    """Per-call device latency WITHOUT a per-call readback: each call's
    input depends on the previous call's output (caller encodes the
    dependency, e.g. via :func:`chain_perturb`), so executions serialize
    on-device; the fence round-trip is paid once and amortized.

    ``rounds > 1`` repeats the converged measurement, each round fenced
    separately, leaving one per-iter sample per round in
    ``last_info["samples_s"]`` (read immediately — the next timing call
    overwrites it) and returning their mean. Round-level samples are the
    honest tail-latency granularity here: a finer per-call probe would
    need a per-call readback, which would measure the tunnel instead of
    the chip (module docstring)."""

    def _one_round(n):
        t0 = time.perf_counter()
        out = x0
        for _ in range(n):
            out = step(out)
        fenced = fence(out) > 0
        return time.perf_counter() - t0, fenced

    fence(step(x0))  # warm / compile (calibration is lazy — see above)
    while True:
        elapsed, fenced = _one_round(iters)
        nxt = _scaled_iters(elapsed, iters) if fenced else None
        if nxt is None:
            break
        iters = nxt  # RTT-dominated: chain more calls
    samples = [_amortize(elapsed, iters, fenced)]
    for _ in range(max(int(rounds), 1) - 1):
        elapsed, fenced = _one_round(iters)
        samples.append(_amortize(elapsed, iters, fenced))
    last_info["samples_s"] = list(samples)
    return sum(samples) / len(samples)


def chain_perturb(x: jax.Array, prev_out: Any) -> jax.Array:
    """Return ``x`` plus a zero-valued contribution of ``prev_out``'s
    first leaf — value-identical to ``x`` but data-dependent on the
    previous call, forcing serial on-device execution in chained-latency
    loops."""
    leaves = [l for l in jax.tree_util.tree_leaves(prev_out)
              if isinstance(l, jax.Array)]
    if not leaves:
        return x
    p = jnp.ravel(leaves[0])[0]
    # inf/NaN probes (top-k pad values, bf16 overflow) must not poison the
    # chain: inf * 0 = NaN would turn every later input into NaN
    z = (jnp.where(jnp.isfinite(p), p, 0) * 0).astype(x.dtype)
    return x + z
