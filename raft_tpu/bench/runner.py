"""End-to-end ANN benchmark runner.

Reference: ``raft-ann-bench`` (python/raft-ann-bench/src — the `run`
orchestrator feeding JSON configs to the C++ gbench harness,
cpp/bench/ann/src/common/benchmark.hpp:379-509) and the ``ANN<T>`` plugin
interface (bench/ann/src/common/ann_types.hpp:85-118: build / search /
set_search_param / save / load).

TPU-native design: one Python process drives JAX directly (the "harness" is
jit + block_until_ready timing). Config files use the same shape and
parameter names as raft-ann-bench's run/conf JSONs (nlist/nprobe/pq_dim/
itopk/…) so existing configs translate 1:1; datasets are fbin/ibin files
read through the native IO layer. Results are JSON-lines with QPS, recall
and build time — the columns data_export/plot consume."""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from raft_tpu import native
from raft_tpu.bench import timing
from raft_tpu.core.resources import Resources
from raft_tpu.stats import neighborhood_recall


# ------------------------------------------------------------ algo registry


class AnnAlgo:
    """The ANN<T>-style plugin seam (ann_types.hpp:85-118): build / search /
    save / load with dict params."""

    name = "base"
    # Host-library algos (sklearn/scipy/hnswlib) consume numpy queries; on
    # accelerator runs handing them the device copy would make every timed
    # dispatch pay a device→host readback over the tunnel (~7 MB/s), skewing
    # the comparative pareto against the CPU baselines (ADVICE r3).
    wants_host_queries = False

    def build(self, dataset: np.ndarray, build_param: Dict[str, Any],
              metric: str, res: Resources):
        raise NotImplementedError

    def search(self, index, queries: np.ndarray, k: int,
               search_param: Dict[str, Any], res: Resources):
        raise NotImplementedError

    def save(self, index, path: str):
        raise NotImplementedError

    def load(self, path: str, res: Resources):
        raise NotImplementedError


def _scan_dtype(search_param):
    """Map a config's scan_dtype string; raises on typos instead of silently
    benchmarking the fp32 path under a bf16 label."""
    v = search_param.get("scan_dtype")
    if v is None:
        return None
    if v in ("bf16", "bfloat16", "half"):
        return "bfloat16"
    raise ValueError(f"unknown scan_dtype {v!r}; use bf16/bfloat16/half")


def _lookup_dtype(search_param, key, table, default):
    """Validated dtype lookup for bench search params: raises a named
    ValueError listing the allowed spellings instead of a bare KeyError
    (mirrors the reference's explicit lut/internal dtype validation,
    ivf_pq_types.hpp:110-146)."""
    v = search_param.get(key, default)
    if v not in table:
        raise ValueError(
            f"unknown {key} {v!r}; allowed: {sorted(table)}")
    return table[v]


def _internal_distance_dtype(search_param):
    import jax.numpy as jnp

    return _lookup_dtype(
        search_param, "internalDistanceDtype",
        {"float": jnp.float32, "fp32": jnp.float32,
         "half": jnp.bfloat16, "fp16": jnp.bfloat16,
         "bf16": jnp.bfloat16}, "float")


def _lut_dtype(search_param):
    import jax.numpy as jnp

    return _lookup_dtype(
        search_param, "smemLutDtype",
        {"float": jnp.float32, "fp32": jnp.float32,
         "half": jnp.bfloat16, "fp16": jnp.bfloat16,
         "bf16": jnp.bfloat16, "fp8": jnp.float8_e4m3fn}, "float")


class BruteForce(AnnAlgo):
    name = "raft_brute_force"

    def build(self, dataset, build_param, metric, res):
        from raft_tpu.neighbors import brute_force

        return brute_force.build(dataset, metric=metric, res=res)

    def search(self, index, queries, k, search_param, res):
        from raft_tpu.neighbors import brute_force

        return brute_force.search(
            index, queries, k, res=res,
            scan_dtype=_scan_dtype(search_param),
            refine_ratio=float(search_param.get("refine_ratio", 4.0)),
            select_recall=float(search_param.get("select_recall", 1.0)))

    def save(self, index, path):
        from raft_tpu.neighbors import brute_force

        brute_force.serialize(index, path)

    def load(self, path, res):
        from raft_tpu.neighbors import brute_force

        return brute_force.deserialize(path, res=res)


class IvfFlat(AnnAlgo):
    name = "raft_ivf_flat"

    def build(self, dataset, build_param, metric, res):
        from raft_tpu.neighbors import ivf_flat

        params = ivf_flat.IndexParams(
            n_lists=int(build_param.get("nlist", 1024)),
            kmeans_n_iters=int(build_param.get("niter", 20)),
            kmeans_trainset_fraction=_ratio(build_param.get("ratio", 2)),
            metric=metric,
        )
        return ivf_flat.build(dataset, params, res=res)

    def search(self, index, queries, k, search_param, res):
        from raft_tpu.neighbors import ivf_flat

        sp = ivf_flat.SearchParams(
            n_probes=int(search_param.get("nprobe", 20)),
            scan_dtype=_scan_dtype(search_param),
            refine_ratio=float(search_param.get("refine_ratio", 4.0)),
            select_recall=float(search_param.get("select_recall", 1.0)))
        return ivf_flat.search(index, queries, k, sp, res=res)

    def save(self, index, path):
        from raft_tpu.neighbors import ivf_flat

        ivf_flat.serialize(index, path)

    def load(self, path, res):
        from raft_tpu.neighbors import ivf_flat

        return ivf_flat.deserialize(path, res=res)


class IvfPq(AnnAlgo):
    name = "raft_ivf_pq"

    _dataset = None  # retained by build() for refine_ratio re-ranking

    def build(self, dataset, build_param, metric, res):
        from raft_tpu.neighbors import ivf_pq

        self._dataset = dataset
        params = ivf_pq.IndexParams(
            n_lists=int(build_param.get("nlist", 1024)),
            pq_dim=int(build_param.get("pq_dim", 0)),
            pq_bits=int(build_param.get("pq_bits", 8)),
            kmeans_n_iters=int(build_param.get("niter", 20)),
            kmeans_trainset_fraction=_ratio(build_param.get("ratio", 2)),
            metric=metric,
        )
        return ivf_pq.build(dataset, params, res=res)

    def search(self, index, queries, k, search_param, res):
        import jax.numpy as jnp

        from raft_tpu.neighbors import ivf_pq, refine

        lut = _lut_dtype(search_param)
        scan_mode = search_param.get("scan_mode", "auto")
        if lut == jnp.float8_e4m3fn and scan_mode != "lut":
            # fp8 LUTs only exist on the LUT engine; the cache engine would
            # silently benchmark fp32-cache numbers under an fp8 label
            scan_mode = "lut"
        sp = ivf_pq.SearchParams(
            n_probes=int(search_param.get("nprobe", 20)),
            lut_dtype=lut,
            internal_distance_dtype=_internal_distance_dtype(search_param),
            scan_mode=scan_mode,
            select_recall=float(search_param.get("select_recall", 1.0)),
        )
        rr = float(search_param.get("refine_ratio", 1.0))
        if rr > 1.0:
            if self._dataset is None:
                raise ValueError(
                    "refine_ratio needs the raw dataset; a loaded index "
                    "doesn't carry it — set algo.set_dataset(data) first")
            d, i = ivf_pq.search(index, queries,
                                 int(np.ceil(k * rr)), sp, res=res)
            return refine.refine(self._dataset, queries, i, k,
                                 metric=index.metric, res=res)
        return ivf_pq.search(index, queries, k, sp, res=res)

    def set_dataset(self, dataset):
        self._dataset = dataset

    def save(self, index, path):
        from raft_tpu.neighbors import ivf_pq

        ivf_pq.serialize(index, path)

    def load(self, path, res):
        from raft_tpu.neighbors import ivf_pq

        return ivf_pq.deserialize(path, res=res)


class Cagra(AnnAlgo):
    name = "raft_cagra"

    def build(self, dataset, build_param, metric, res):
        from raft_tpu.neighbors import cagra

        algo = {"ivf_pq": cagra.BuildAlgo.IVF_PQ,
                "nn_descent": cagra.BuildAlgo.NN_DESCENT}[
            build_param.get("graph_build_algo", "nn_descent").lower()]
        params = cagra.IndexParams(
            graph_degree=int(build_param.get("graph_degree", 64)),
            intermediate_graph_degree=int(
                build_param.get("intermediate_graph_degree", 128)),
            build_algo=algo,
            nn_descent_niter=int(build_param.get("nn_descent_niter", 20)),
            metric=metric,
        )
        return cagra.build(dataset, params, res=res)

    def search(self, index, queries, k, search_param, res):
        from raft_tpu.neighbors import cagra

        sp = cagra.SearchParams(
            itopk_size=int(search_param.get("itopk", 64)),
            search_width=int(search_param.get("search_width", 1)),
            max_iterations=int(search_param.get("max_iterations", 0)),
            scan_dtype=_scan_dtype(search_param),
        )
        return cagra.search(index, queries, k, sp, res=res)

    def save(self, index, path):
        from raft_tpu.neighbors import cagra

        cagra.serialize(index, path)

    def load(self, path, res):
        from raft_tpu.neighbors import cagra

        return cagra.deserialize(path, res=res)


# ---------------------------------------------------- competitor wrappers
# The reference bench ships faiss/hnswlib/ggnn wrappers behind the same
# ANN<T> seam (bench/ann/src/faiss/faiss_wrapper.h, hnswlib/
# hnswlib_wrapper.h) so cross-library pareto plots come from one run.
# This image is offline (no faiss/hnswlib wheels); the CPU baselines
# available here are sklearn's brute-force and a KD-tree — enough to make
# the QPS-vs-recall plots comparative rather than self-referential.


class SklearnBruteForce(AnnAlgo):
    """Exact CPU baseline (the faiss_cpu/bruteforce comparison role)."""

    name = "sklearn_brute_force"
    wants_host_queries = True

    def build(self, dataset, build_param, metric, res):
        from sklearn.neighbors import NearestNeighbors

        m = {"sqeuclidean": "sqeuclidean", "euclidean": "sqeuclidean",
             "cosine": "cosine", "inner_product": None}.get(metric, metric)
        if m is None:
            raise ValueError(f"sklearn wrapper: unsupported metric {metric}")
        nn = NearestNeighbors(algorithm="brute", metric=m)
        nn.fit(np.asarray(dataset))
        return nn

    def search(self, index, queries, k, search_param, res):
        d, i = index.kneighbors(np.asarray(queries), n_neighbors=k)
        return d.astype(np.float32), i.astype(np.int32)


class ScipyKDTree(AnnAlgo):
    """cKDTree baseline (the hnswlib-CPU comparison role for low dims)."""

    name = "scipy_kdtree"
    wants_host_queries = True

    def build(self, dataset, build_param, metric, res):
        from scipy.spatial import cKDTree

        if metric not in ("sqeuclidean", "euclidean"):
            raise ValueError(f"kdtree wrapper: unsupported metric {metric}")
        return cKDTree(np.asarray(dataset),
                       leafsize=int(build_param.get("leafsize", 32)))

    def search(self, index, queries, k, search_param, res):
        # eps > 0 = approximate pruning (the ef/nprobe-style recall knob)
        d, i = index.query(np.asarray(queries), k=k,
                           eps=float(search_param.get("eps", 0.0)))
        if k == 1:
            d, i = d[:, None], i[:, None]
        return (d.astype(np.float32) ** 2), i.astype(np.int32)


class HnswCpu(AnnAlgo):
    """The hnswlib competitor row (the role of bench/ann/src/hnswlib/
    hnswlib_wrapper.h — no hnswlib wheel exists on this image): a CAGRA
    graph searched by the native C++ ef-search, which is hnswlib's
    layer-0 searchBaseLayerST algorithm over the same on-disk format
    neighbors/hnsw.py exports. Rival pareto points come from a genuinely
    different (CPU, latency-oriented, sequential-walk) execution model.

    build_param: M (hnswlib meaning; graph_degree = 2*M like maxM0).
    search_param: ef.
    """

    name = "hnsw_cpu"
    wants_host_queries = True

    def build(self, dataset, build_param, metric, res):
        from raft_tpu.neighbors import cagra

        if metric not in ("sqeuclidean", "euclidean"):
            raise ValueError(f"hnsw_cpu: unsupported metric {metric}")
        m = int(build_param.get("M", 16))
        idx = cagra.build(
            np.asarray(dataset),
            cagra.IndexParams(
                graph_degree=2 * m,
                intermediate_graph_degree=max(3 * m, 2 * m + 16)),
            res=res)
        return (np.asarray(idx.dataset), np.asarray(idx.graph))

    def search(self, index, queries, k, search_param, res):
        from raft_tpu import native

        data, graph = index
        d, i = native.graph_greedy_search(
            data, graph, np.asarray(queries), k,
            ef=int(search_param.get("ef", max(2 * k, 64))))
        return d, i

    def save(self, index, path):
        from raft_tpu import native

        native.hnswlib_write(path, index[0], index[1])


ALGOS: Dict[str, Callable[[], AnnAlgo]] = {
    a.name: a for a in (BruteForce, IvfFlat, IvfPq, Cagra,
                        SklearnBruteForce, ScipyKDTree, HnswCpu)
}


def _ratio(r) -> float:
    """raft-ann-bench 'ratio' = subsample divisor (2 → half the data)."""
    r = float(r)
    return 1.0 / r if r >= 1.0 else r


_METRIC_MAP = {"euclidean": "sqeuclidean", "angular": "cosine",
               "inner_product": "inner_product", "ip": "inner_product",
               "sqeuclidean": "sqeuclidean", "cosine": "cosine"}


# ------------------------------------------------------------------- runner


@dataclasses.dataclass
class DatasetSpec:
    """Dataset block of a run config (run/conf/*.json 'dataset')."""

    name: str
    base_file: str
    query_file: str
    groundtruth_neighbors_file: Optional[str] = None
    distance: str = "euclidean"
    subset_size: Optional[int] = None

    def load(self):
        base = native.read_bin(self.base_file, 0, self.subset_size)
        queries = native.read_bin(self.query_file)
        gt = None
        if self.groundtruth_neighbors_file and os.path.exists(
                self.groundtruth_neighbors_file):
            gt = native.read_bin(self.groundtruth_neighbors_file,
                                 dtype=np.int32)
        return base, queries, gt


def generate_groundtruth(dataset: np.ndarray, queries: np.ndarray, k: int,
                         metric: str = "euclidean",
                         res: Optional[Resources] = None) -> np.ndarray:
    """Exact ground truth via brute force (the generate_groundtruth CLI,
    python/raft-ann-bench generate_groundtruth)."""
    from raft_tpu.neighbors import brute_force

    _, idx = brute_force.knn(queries, dataset,
                             k=k, metric=_METRIC_MAP.get(metric, metric),
                             res=res)
    return np.asarray(idx)


def split_groundtruth(gt_path: str, out_neighbors: str,
                      out_distances: str) -> None:
    """Split a big-ann combined groundtruth file into the .ibin/.fbin pair
    the runner reads (the split_groundtruth CLI, python/raft-ann-bench
    split_groundtruth/split_groundtruth.pl). Layout: int32 header (n, k),
    then one block of n·k uint32 neighbor ids, then one block of n·k
    float32 distances."""
    n, k = native.read_bin_header(gt_path)
    with open(gt_path, "rb") as f:
        f.seek(8)
        neigh = np.fromfile(f, np.uint32, n * k)
        dist = np.fromfile(f, np.float32, n * k)
    if neigh.size != n * k or dist.size != n * k:
        raise IOError(
            f"{gt_path}: expected {n}*{k} ids + distances "
            "(big-ann block layout)")
    native.write_bin(out_neighbors, neigh.reshape(n, k).astype(np.int32))
    native.write_bin(out_distances, dist.reshape(n, k))


def scale_config(config: Dict[str, Any], target_rows: int,
                 data_dir: str = "/tmp/raft_tpu_scaled") -> Dict[str, Any]:
    """Shrink a full-scale run config (e.g. deep-100M) to ``target_rows``
    so it is runnable on one chip / this box: cluster counts scale with
    the row factor (bounded below at 256), and when the config's dataset
    files don't exist locally (offline image), a synthetic clustered
    stand-in of the right shape is generated and cached under
    ``data_dir``. Search/index param STRUCTURE is untouched — the point
    is to smoke the exact sweep the reference runs, at chip scale."""
    import copy

    from raft_tpu import native
    from raft_tpu.bench.datagen import low_rank_clusters

    conf = copy.deepcopy(config)
    ds = conf["dataset"]
    full_rows = int(ds.get("subset_size") or 0)
    if not full_rows:
        n, _ = native.read_bin_header(ds["base_file"])
        full_rows = n
    factor = target_rows / max(full_rows, 1)
    for entry in conf["index"]:
        bp = entry.get("build_param", {})
        if "nlist" in bp:
            bp["nlist"] = max(256, int(round(bp["nlist"] * factor)))
    if not os.path.exists(ds["base_file"]):
        # dataset dim: the real query file when present, else the
        # ann-benchmarks name convention ("sift-128-euclidean"), else 96
        if os.path.exists(ds.get("query_file", "")):
            _, dim = native.read_bin_header(ds["query_file"])
            dim = int(dim)
        else:
            digits = [int(t) for t in ds["name"].split("-") if t.isdigit()]
            dim = digits[0] if digits else 96
        os.makedirs(data_dir, exist_ok=True)
        base_path = os.path.join(data_dir,
                                 f"{ds['name']}-{target_rows}.fbin")
        q_path = os.path.join(data_dir, f"{ds['name']}-q.fbin")
        if not os.path.exists(base_path):
            rng = np.random.default_rng(0)
            native.write_bin(base_path,
                             low_rank_clusters(rng, target_rows, dim,
                                               n_centers=1024))
            qi = rng.integers(0, target_rows, 10_000)
            b = native.read_bin(base_path)
            native.write_bin(q_path,
                             b[qi] + rng.standard_normal(
                                 (10_000, dim)).astype(np.float32) * 0.01)
        ds["base_file"], ds["query_file"] = base_path, q_path
    # a full-scale groundtruth is wrong for ANY subset (its neighbor ids
    # point at rows outside the shrunk base) — always regenerate
    ds.pop("groundtruth_neighbors_file", None)
    ds["subset_size"] = target_rows
    ds["name"] = f"{ds['name']}-scaled-{target_rows}"
    return conf


def run_benchmark(
    config: Dict[str, Any],
    k: int = 10,
    batch_size: Optional[int] = None,
    search_iters: int = 3,
    out_path: Optional[str] = None,
    res: Optional[Resources] = None,
) -> List[Dict[str, Any]]:
    """Run every index/search-param combo in a raft-ann-bench-shaped config.

    ``config``: {"dataset": {...}, "index": [{"name", "algo",
    "build_param", "search_params": [...]}]}. Returns result rows
    (one per search param set): name, algo, build_time, qps, recall, k…
    """
    res = res or Resources()
    ds = DatasetSpec(**config["dataset"])
    base, queries, gt = ds.load()
    metric = _METRIC_MAP.get(ds.distance, ds.distance)
    if gt is None:
        gt = generate_groundtruth(base, queries, k, metric, res=res)
    gt = gt[:, :k]
    # one upload for the whole run — per-search re-uploads ride the slow
    # tunnel link (~16 MB/s) and would dominate small-index measurements;
    # host-library algos instead get the numpy copy so their timed loops
    # don't pay a device→host readback per dispatch (ADVICE r3). Skip the
    # upload entirely for a baselines-only config.
    queries_host = np.asarray(queries)
    queries = (timing.prepare(queries_host)
               if any(not ALGOS[c["algo"]].wants_host_queries
                      for c in config["index"]) else queries_host)

    results = []
    for index_conf in config["index"]:
        algo = ALGOS[index_conf["algo"]]()
        t0 = time.perf_counter()
        index = algo.build(base, index_conf.get("build_param", {}), metric,
                           res)
        _block_on_index(index)
        build_time = time.perf_counter() - t0
        q = queries_host if algo.wants_host_queries else queries
        for sp in index_conf.get("search_params", [{}]):
            row = _run_search(algo, index, q, k, sp, gt, batch_size,
                              search_iters, res)
            row.update({"name": index_conf.get("name", index_conf["algo"]),
                        "algo": index_conf["algo"],
                        "dataset": ds.name,
                        "build_time": round(build_time, 3),
                        "search_param": sp})
            results.append(row)
            if out_path:
                with open(out_path, "a") as f:
                    f.write(json.dumps(row) + "\n")
    return results


def _block_on_index(index) -> None:
    """Fence the async build via a host readback of every jax.Array the
    index holds (block_until_ready under-waits on the axon tunnel — see
    bench/timing.py)."""
    timing.fence_index(index)


def _run_search(algo, index, queries, k, search_param, gt, batch_size,
                iters, res):
    """Times both benchmark modes of the reference harness
    (docs raft_ann_benchmarks.md:154):

    - **throughput**: every batch is dispatched before any is awaited, so
      in-flight batches keep the chip saturated (the TPU analog of the
      thread-pool pipelining in bench/ann/src/common/thread_pool.hpp —
      XLA's async dispatch is the queue) → ``qps``.
    - **latency**: batches are serialized by a data dependency (each
      batch's input depends on the previous output), measuring device
      serial latency with the host readback round-trip amortized →
      ``latency_ms`` (mean per-batch time) and ``qps_latency_mode``.
    """
    nq = len(queries)
    bs = batch_size or nq
    n_batches = max(-(-nq // bs), 1)

    def dispatch(s, q_batch=None):
        qb = queries[s : s + bs] if q_batch is None else q_batch
        return algo.search(index, qb, k, search_param, res)

    # warmup + correctness (also compiles both shapes: full + tail batch)
    outs = [dispatch(s) for s in range(0, nq, bs)]
    timing.fence(outs)
    idx = np.concatenate([np.asarray(i) for _, i in outs])
    recall = float(neighborhood_recall(idx[:, :k], gt))

    # throughput mode: dispatch-ahead, one fence per pass
    thr_dt = timing.time_dispatches(
        lambda: [dispatch(s) for s in range(0, nq, bs)],
        iters=iters, warmup=0)
    thr_rtt_bound = timing.last_info["rtt_bound"]

    # latency mode: batches serialized by a data dependency (per-batch
    # host syncs would measure the tunnel round-trip, not the chip);
    # the tail batch is timed separately when nq % bs != 0
    lat_rtt_bound = False

    def chained_latency(q0):
        nonlocal lat_rtt_bound
        dt = timing.time_latency_chained(
            lambda qq: timing.chain_perturb(q0, dispatch(0, q_batch=qq)),
            q0, iters=max(iters * n_batches, 4))
        lat_rtt_bound = lat_rtt_bound or timing.last_info["rtt_bound"]
        return dt

    n_full = nq // bs
    lat_dt = chained_latency(queries[:bs]) * n_full if n_full else 0.0
    tail = nq % bs
    if tail:
        lat_dt += chained_latency(queries[nq - tail:])

    row = {"k": k, "batch_size": bs, "qps": round(nq / thr_dt, 1),
           "qps_latency_mode": round(nq / lat_dt, 1),
           "latency_ms": round(1000.0 * lat_dt / n_batches, 3),
           "recall": round(recall, 4)}
    # noise-bound (elapsed < 5× fence RTT at the iteration cap), flagged
    # per mode so a clean qps isn't caveated by an RTT-bound tail chain
    if thr_rtt_bound:
        row["rtt_bound_qps"] = True
    if lat_rtt_bound:
        row["rtt_bound_latency"] = True
    return row
