"""Result export + QPS-vs-recall pareto plots.

Reference: ``raft-ann-bench.data_export`` (CSV + throughput/latency pareto
frontiers — docs/source/raft_ann_benchmarks.md:204-205) and
``raft-ann-bench.plot`` (QPS-vs-recall pareto curves)."""

from __future__ import annotations

import csv
import json
from typing import Dict, List


def load_results(path: str) -> List[Dict]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def pareto_frontier(rows: List[Dict], x_key: str = "recall",
                    y_key: str = "qps") -> List[Dict]:
    """Points not dominated by any other (higher recall AND higher qps).
    Ties on x are broken by y so a dominated equal-recall point never
    survives."""
    out = []
    best_y = -float("inf")
    for r in sorted(rows, key=lambda r: (-r[x_key], -r[y_key])):
        if r[y_key] > best_y:
            out.append(r)
            best_y = r[y_key]
    return list(reversed(out))


def export_csv(rows: List[Dict], path: str,
               pareto: bool = False) -> None:
    """Flat CSV of result rows (data_export analog); optionally only the
    per-algo pareto frontier."""
    if pareto:
        by_algo: Dict[str, List[Dict]] = {}
        for r in rows:
            by_algo.setdefault(r.get("name", r.get("algo", "?")), []).append(r)
        rows = [p for rs in by_algo.values() for p in pareto_frontier(rs)]
    if not rows:
        return
    # leading columns use the reference data_export names (index_name /
    # recall / throughput / latency, data_export/__main__.py:159-162) so
    # its downstream plotting tooling reads our CSVs unchanged; the
    # richer native fields follow
    keys = ["index_name", "recall", "throughput", "latency",
            "dataset", "name", "algo", "k", "batch_size", "qps",
            "latency_ms", "build_time", "search_param"]
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=keys, extrasaction="ignore")
        w.writeheader()
        for r in rows:
            r = dict(r)
            r["index_name"] = r.get("name", r.get("algo", "?"))
            r["throughput"] = r.get("qps")
            r["latency"] = (r.get("latency_ms", 0.0) or 0.0) / 1e3
            r["search_param"] = json.dumps(r.get("search_param", {}))
            w.writerow(r)


def plot(rows: List[Dict], path: str, title: str = "QPS vs recall") -> None:
    """QPS-vs-recall pareto plot per algo (plot CLI analog)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    by_algo: Dict[str, List[Dict]] = {}
    for r in rows:
        by_algo.setdefault(r.get("name", r.get("algo", "?")), []).append(r)
    fig, ax = plt.subplots(figsize=(7, 5))
    for name, rs in sorted(by_algo.items()):
        front = pareto_frontier(rs)
        ax.plot([r["recall"] for r in front], [r["qps"] for r in front],
                marker="o", label=name)
    ax.set_xlabel("recall@k")
    ax.set_ylabel("QPS")
    ax.set_yscale("log")
    ax.set_title(title)
    ax.legend()
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
