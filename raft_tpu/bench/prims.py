"""Micro-benchmarks for the primitive layer.

Reference: ``cpp/bench/prims`` — google-benchmark suites with CUDA-event
timing (bench/prims/common/benchmark.hpp:74-147) for distance, select_k,
fused L2 NN, k-means, linalg and random prims.

TPU-native design: wall-clock around ``block_until_ready`` after a compile
warm-up (the XLA analog of CUDA-event timing), one jitted callable per
case. Run as ``python -m raft_tpu.bench.prims [case ...]``; emits one JSON
line per case: {"case", "shape", "ms", "gb_s" | "gflops"}.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np


def _time(fn: Callable, *args, iters: int = 10) -> float:
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_pairwise(m=4096, n=4096, d=128, metric="sqeuclidean"):
    from raft_tpu.ops.distance import pairwise_distance

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((m, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    f = jax.jit(lambda a, b: pairwise_distance(a, b, metric=metric))
    dt = _time(f, x, y)
    flops = 2.0 * m * n * d
    return {"case": f"pairwise_{metric}", "shape": [m, n, d],
            "ms": round(dt * 1e3, 3), "gflops": round(flops / dt / 1e9, 1)}


def bench_fused_l2_nn(m=100_000, n=1024, d=128):
    from raft_tpu.ops.fused_l2_nn import fused_l2_nn_argmin

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((m, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    dt = _time(fused_l2_nn_argmin, x, y)
    flops = 2.0 * m * n * d
    return {"case": "fused_l2_nn", "shape": [m, n, d],
            "ms": round(dt * 1e3, 3), "gflops": round(flops / dt / 1e9, 1)}


def bench_select_k(batch=1024, n=16384, k=64):
    from raft_tpu.ops.select_k import select_k

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, n)), jnp.float32)
    f = jax.jit(lambda a: select_k(a, k, select_min=True))
    dt = _time(f, x)
    gb = batch * n * 4 / 1e9
    return {"case": "select_k", "shape": [batch, n, k],
            "ms": round(dt * 1e3, 3), "gb_s": round(gb / dt, 1)}


def bench_kmeans_iter(m=100_000, d=96, c=1024):
    from raft_tpu.cluster.kmeans import assign

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((m, d)), jnp.float32)
    cen = jnp.asarray(rng.standard_normal((c, d)), jnp.float32)
    xn = jnp.sum(x * x, -1)
    f = jax.jit(lambda a, an, b: assign(a, an, b, 65536))
    dt = _time(f, x, xn, cen)
    flops = 2.0 * m * c * d
    return {"case": "kmeans_assign", "shape": [m, d, c],
            "ms": round(dt * 1e3, 3), "gflops": round(flops / dt / 1e9, 1)}


def bench_rng(n=10_000_000):
    from raft_tpu.ops import rng as rrng

    st = rrng.RngState(0)
    f = jax.jit(lambda k: jax.random.normal(k, (n,), jnp.float32))
    key = jax.random.key(0)
    dt = _time(f, key)
    return {"case": "rng_normal", "shape": [n],
            "ms": round(dt * 1e3, 3), "gb_s": round(n * 4 / dt / 1e9, 1)}


CASES: Dict[str, Callable] = {
    "pairwise": bench_pairwise,
    "fused_l2_nn": bench_fused_l2_nn,
    "select_k": bench_select_k,
    "kmeans_assign": bench_kmeans_iter,
    "rng": bench_rng,
}


def main(argv=None) -> int:
    import sys

    names = (argv or sys.argv[1:]) or list(CASES)
    for name in names:
        print(json.dumps(CASES[name]()))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
