"""CLI: ``python -m raft_tpu.bench --conf config.json [--k 10] ...``

The raft-ann-bench.run orchestration analog (python/raft-ann-bench
run/__main__.py): reads a run config, executes every index/search combo,
writes JSON-lines + CSV (+ optional pareto plot)."""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None):
    p = argparse.ArgumentParser(prog="raft_tpu.bench")
    p.add_argument("--conf", required=True, help="run config JSON path")
    p.add_argument("--k", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--out", default="bench_results.jsonl")
    p.add_argument("--csv", default=None)
    p.add_argument("--plot", default=None)
    p.add_argument("--pareto", action="store_true")
    args = p.parse_args(argv)

    from raft_tpu.bench import export, runner

    with open(args.conf) as f:
        config = json.load(f)
    rows = runner.run_benchmark(config, k=args.k, batch_size=args.batch_size,
                                search_iters=args.iters, out_path=args.out)
    for r in rows:
        print(json.dumps(r))
    if args.csv:
        export.export_csv(rows, args.csv, pareto=args.pareto)
    if args.plot:
        export.plot(rows, args.plot)
    return 0


if __name__ == "__main__":
    sys.exit(main())
