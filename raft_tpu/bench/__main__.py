"""CLI — the raft-ann-bench orchestration analog (python/raft-ann-bench):

    python -m raft_tpu.bench run --conf config.json [--k 10] ...
    python -m raft_tpu.bench get-dataset --hdf5 glove-100-angular.hdf5 --out data/
    python -m raft_tpu.bench generate-groundtruth --base b.fbin \\
        --queries q.fbin --out gt.ibin
    python -m raft_tpu.bench split-groundtruth --gt combined.fbin --out-prefix gt

``run`` reads a run config, executes every index/search combo, writes
JSON-lines + CSV (+ optional pareto plot). ``get-dataset`` converts a local
ann-benchmarks HDF5 file into the fbin/ibin layout (the reference CLI
downloads then converts — this environment is offline, so conversion only).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _honor_cpu_request() -> None:
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the axon sitecustomize pre-sets jax_platforms at interpreter
        # startup, overriding the env var — honor an explicit cpu request
        # via jax.config so CPU runs can't hang on a dead tunnel. Applies
        # to every subcommand that touches jax (run, generate-groundtruth).
        import jax

        jax.config.update("jax_platforms", "cpu")


def _cmd_run(args) -> int:
    _honor_cpu_request()
    from raft_tpu.bench import export, runner

    with open(args.conf) as f:
        config = json.load(f)
    if args.scale:
        target = {"chip": 4_000_000, "smoke": 100_000}.get(args.scale)
        if target is None:
            target = int(args.scale)
        config = runner.scale_config(config, target)
    def entry_name(e):
        # the runner itself tolerates a missing "name" via the same
        # fallback (runner.run_benchmark row labeling)
        return e.get("name", e.get("algo", ""))

    if args.algos:
        config["index"] = [
            e for e in config["index"]
            if any(s in entry_name(e) or s in e.get("algo", "")
                   for s in args.algos)]
        print(f"--algos: running {[entry_name(e) for e in config['index']]}")
    prior = []
    if args.resume and args.out and os.path.exists(args.out):
        # skip work already in the out JSONL — the CPU-baseline rows can
        # be produced off-window and the chip window then only pays for
        # the accelerator algos. Completion is keyed per
        # (name, search_param), not per entry: the runner appends one row
        # per search_param as each completes, so a timeout kill mid-entry
        # leaves a partial entry whose remaining points must still run on
        # the next resume (a name-only key would silently drop them from
        # the pareto front).
        done = set()
        with open(args.out) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                prior.append(r)
                done.add((r.get("name"),
                          json.dumps(r.get("search_param", {}),
                                     sort_keys=True)))
        kept, skipped, partial = [], [], []
        for e in config["index"]:
            name = entry_name(e)
            sps = e.get("search_params", [{}])
            missing = [sp for sp in sps
                       if (name, json.dumps(sp, sort_keys=True)) not in done]
            if not missing:
                skipped.append(name)
            else:
                if len(missing) < len(sps):
                    partial.append(f"{name} ({len(missing)}/{len(sps)} "
                                   "search params left)")
                kept.append(dict(e, search_params=missing))
        config["index"] = kept
        if skipped:
            print(f"--resume: skipping completed {skipped}")
        if partial:
            print(f"--resume: finishing partial {partial}")
    rows = runner.run_benchmark(config, k=args.k, batch_size=args.batch_size,
                                search_iters=args.iters, out_path=args.out)
    for r in rows:
        print(json.dumps(r))
    all_rows = prior + rows  # resumed runs export the full set
    if args.csv:
        export.export_csv(all_rows, args.csv, pareto=args.pareto)
    if args.plot:
        export.plot(all_rows, args.plot)
    return 0


def _cmd_get_dataset(args) -> int:
    """HDF5 (ann-benchmarks layout: train/test/neighbors/distances) → fbin
    files (the get_dataset CLI's hdf5_to_fbin step,
    python/raft-ann-bench get_dataset/__main__.py)."""
    import h5py
    import numpy as np

    from raft_tpu import native

    name = os.path.splitext(os.path.basename(args.hdf5))[0]
    out_dir = os.path.join(args.out, name)
    os.makedirs(out_dir, exist_ok=True)
    with h5py.File(args.hdf5, "r") as f:
        normalize = args.normalize or name.endswith("-angular")
        for key, fname, dt in (("train", "base.fbin", np.float32),
                               ("test", "query.fbin", np.float32),
                               ("neighbors", "groundtruth.neighbors.ibin",
                                np.int32),
                               ("distances", "groundtruth.distances.fbin",
                                np.float32)):
            if key not in f:
                continue
            arr = np.asarray(f[key], dt)
            if normalize and key in ("train", "test"):
                arr = arr / np.maximum(
                    np.linalg.norm(arr, axis=1, keepdims=True), 1e-20)
            native.write_bin(os.path.join(out_dir, fname), arr)
            print(f"wrote {out_dir}/{fname} {arr.shape}")
    return 0


def _cmd_generate_groundtruth(args) -> int:
    _honor_cpu_request()
    import numpy as np

    from raft_tpu import native
    from raft_tpu.bench import runner

    base = native.read_bin(args.base)
    queries = native.read_bin(args.queries)
    gt = runner.generate_groundtruth(base, queries, args.k, args.metric)
    native.write_bin(args.out, np.asarray(gt, np.int32))
    print(f"wrote {args.out} {gt.shape}")
    return 0


def _cmd_split_groundtruth(args) -> int:
    from raft_tpu.bench import runner

    neigh = args.out_prefix + ".neighbors.ibin"
    dist = args.out_prefix + ".distances.fbin"
    runner.split_groundtruth(args.gt, neigh, dist)
    print(f"wrote {neigh}, {dist}")
    return 0


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # back-compat: `--conf ...` without a subcommand means `run`
    # (but let --help/-h reach the top-level parser so subcommands show)
    if argv and argv[0].startswith("--") and argv[0] not in ("--help",):
        argv = ["run", *argv]

    p = argparse.ArgumentParser(prog="raft_tpu.bench")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("run", help="run a benchmark config")
    pr.add_argument("--conf", required=True, help="run config JSON path")
    pr.add_argument("--scale", default=None,
                    help="shrink the config to run at reduced scale: "
                         "'chip' (4M rows, single v5e), 'smoke' (100k), "
                         "or an explicit row count; cluster counts scale "
                         "with the row factor and a synthetic clustered "
                         "dataset stands in for missing files")
    pr.add_argument("--k", type=int, default=10)
    pr.add_argument("--batch-size", type=int, default=None)
    pr.add_argument("--iters", type=int, default=3)
    pr.add_argument("--out", default="bench_results.jsonl")
    pr.add_argument("--csv", default=None)
    pr.add_argument("--plot", default=None)
    pr.add_argument("--pareto", action="store_true")
    pr.add_argument("--algos", nargs="*", default=None,
                    help="only run index entries whose name/algo contains "
                         "one of these substrings")
    pr.add_argument("--resume", action="store_true",
                    help="skip index entries already present in --out")
    pr.set_defaults(fn=_cmd_run)

    pg = sub.add_parser("get-dataset",
                        help="convert a local ann-benchmarks HDF5 to fbin")
    pg.add_argument("--hdf5", required=True)
    pg.add_argument("--out", default="datasets")
    pg.add_argument("--normalize", action="store_true",
                    help="L2-normalize rows (angular datasets)")
    pg.set_defaults(fn=_cmd_get_dataset)

    pq = sub.add_parser("generate-groundtruth",
                        help="exact brute-force ground truth → ibin")
    pq.add_argument("--base", required=True)
    pq.add_argument("--queries", required=True)
    pq.add_argument("--out", required=True)
    pq.add_argument("--k", type=int, default=100)
    pq.add_argument("--metric", default="euclidean")
    pq.set_defaults(fn=_cmd_generate_groundtruth)

    ps = sub.add_parser("split-groundtruth",
                        help="split combined gt fbin into neighbors+distances")
    ps.add_argument("--gt", required=True)
    ps.add_argument("--out-prefix", required=True)
    ps.set_defaults(fn=_cmd_split_groundtruth)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
