"""pylibraft-parity namespace: ``raft_tpu.random``.

Mirrors ``pylibraft.random`` (python/pylibraft/pylibraft/random — rmat) plus
the full raft::random generator surface from ops.rng."""

from raft_tpu.ops.rng import (  # noqa: F401
    RngState,
    bernoulli,
    exponential,
    gumbel,
    laplace,
    lognormal,
    make_blobs,
    make_regression,
    multi_variable_gaussian,
    normal,
    permute,
    rayleigh,
    rmat,
    sample_without_replacement,
    subsample_rows,
    uniform,
)

__all__ = ["RngState", "rmat", "make_blobs", "make_regression",
           "multi_variable_gaussian", "normal", "uniform", "laplace",
           "gumbel", "lognormal", "exponential", "rayleigh", "bernoulli",
           "permute", "sample_without_replacement", "subsample_rows"]
