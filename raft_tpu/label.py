"""Label utilities.

Reference: ``raft::label`` (label/classlabels.cuh — ``getUniquelabels``,
``getOvhaInstance``... i.e. unique-label extraction and monotonic relabeling
``make_monotonic``; label/merge_labels.cuh — ``merge_labels``, the
union-find-style label merge used by connected-components).

TPU-native design: unique/relabel ride ``jnp.unique``-style sort machinery
with static output capacity (XLA needs static shapes — callers pass the
max number of classes); merge_labels is the same min-propagation fixpoint
the reference runs, expressed as a bounded ``lax.while_loop``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def get_unique_labels(labels, max_labels: int) -> Tuple[jax.Array, jax.Array]:
    """Sorted unique labels padded to ``max_labels`` with -1, plus the count
    (label/classlabels.cuh getUniquelabels analog; capacity is static)."""
    labels = jnp.asarray(labels, jnp.int32).ravel()
    uniq = jnp.unique(labels, size=max_labels, fill_value=-1)
    # jnp.unique sorts ascending; -1 fill can collide with real -1 labels,
    # which the reference treats as "unlabeled" anyway
    n = jnp.sum(uniq >= 0) + jnp.any(labels == -1).astype(jnp.int32) * 0
    return uniq, n


def make_monotonic(labels, max_labels: int) -> jax.Array:
    """Relabel to a dense 0..n-1 range by rank among unique values
    (label/classlabels.cuh make_monotonic analog). Negative labels pass
    through unchanged (unlabeled convention)."""
    labels = jnp.asarray(labels, jnp.int32)
    uniq = jnp.unique(jnp.where(labels < 0, jnp.iinfo(jnp.int32).max, labels),
                      size=max_labels, fill_value=jnp.iinfo(jnp.int32).max)
    rank = jnp.searchsorted(uniq, labels)
    return jnp.where(labels < 0, labels, rank.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("max_iters",))
def merge_labels(labels_a, labels_b, max_iters: int = 32) -> jax.Array:
    """Merge two labelings into their finest common coarsening: rows sharing
    a label in EITHER input end up with the same (minimum) output label —
    the connected-components merge of label/merge_labels.cuh.

    Runs min-propagation through both label tables until fixpoint (bounded
    by ``max_iters``; log₂(n) rounds suffice in practice).
    """
    a = jnp.asarray(labels_a, jnp.int32)
    b = jnp.asarray(labels_b, jnp.int32)
    n = a.shape[0]
    out0 = jnp.arange(n, dtype=jnp.int32)

    def propagate(out, lab):
        # every group in `lab` adopts the min current out-label of the group
        big = jnp.iinfo(jnp.int32).max
        gmin = jnp.full((n,), big, jnp.int32).at[lab].min(out)
        return jnp.minimum(out, gmin[lab])

    def cond(state):
        i, out, prev_changed = state
        return (i < max_iters) & prev_changed

    def body(state):
        i, out, _ = state
        new = propagate(propagate(out, a), b)
        return i + 1, new, jnp.any(new != out)

    _, out, _ = jax.lax.while_loop(cond, body, (0, out0, jnp.bool_(True)))
    return out
