"""Seeded schedule amplification for concurrency tests.

Plain pytest runs rarely catch real interleaving bugs: CPython's
default 5 ms switch interval means a racy read-modify-write window of a
few bytecodes almost never gets preempted. :class:`InterleaveAmplifier`
widens those windows two ways, both scoped to a ``with`` block:

* ``sys.setswitchinterval`` is dropped to microseconds, so the GIL
  rotates between runnable threads orders of magnitude more often;
* a ``threading.settrace``/``sys.settrace`` tracer injects seeded
  yield points — tiny sleeps — on line events inside matching files
  (optionally only on lines touching named fields, e.g. the attributes
  carrying ``# guarded_by:`` annotations), so races hide behind the
  GIL's atomicity far less often.

Reproducibility is best-effort, not bit-exact: the seed fixes the yield
pattern per (thread-creation-order, line) but the OS scheduler still
has a vote. In practice a failing seed refails within a few runs, which
is what replayability needs. The seed comes from the
``RAFT_TPU_INTERLEAVE_SEED`` environment variable when not given, so CI
can export one value and chaos failures are replayable locally.

Only threads *started inside* the context are traced
(``threading.settrace`` affects new threads); start workers inside the
``with`` block.
"""

from __future__ import annotations

import itertools
import linecache
import os
import random
import re
import sys
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["ENV_SEED", "env_seed", "seeds", "InterleaveAmplifier",
           "guarded_fields"]

ENV_SEED = "RAFT_TPU_INTERLEAVE_SEED"

_GUARD_RE = re.compile(r"self\.(\w+).*#\s*guarded_by:")


def env_seed(default: int = 0) -> int:
    """The CI-exported replay seed, or ``default`` when unset."""
    try:
        return int(os.environ.get(ENV_SEED, default))
    except ValueError:
        return default


def seeds(n: int, base: Optional[int] = None) -> List[int]:
    """``n`` distinct seeds anchored at ``base`` (default: the env
    seed) — the sweep helper for "assert across N seeds" tests."""
    b = env_seed() if base is None else base
    return [b + i for i in range(n)]


def guarded_fields(path: str) -> Tuple[str, ...]:
    """Attribute names carrying ``# guarded_by:`` annotations in a
    source file — natural yield points for that file's classes."""
    names = []
    try:
        with open(path) as f:
            for line in f:
                m = _GUARD_RE.search(line)
                if m:
                    names.append(m.group(1))
    except OSError:
        pass
    return tuple(dict.fromkeys(names))


class InterleaveAmplifier:
    """Context manager that amplifies thread preemption (see module
    docstring). Typical use::

        with InterleaveAmplifier(seed=7, path_filters=("raft_tpu",)):
            ... start threads, hammer the object under test ...

    Parameters
    ----------
    seed:
        Yield-pattern seed; ``None`` reads ``RAFT_TPU_INTERLEAVE_SEED``.
    switch_interval:
        Temporary ``sys.setswitchinterval`` value (seconds).
    yield_probability:
        Chance of injecting a sleep at each eligible line event.
    sleep_s:
        Injected sleep length; half the yields use ``sleep(0)`` (a pure
        GIL drop) instead, mixing long and short perturbations.
    path_filters:
        Substrings; only frames whose filename contains one are traced
        (keep this tight — tracing is expensive).
    fields:
        Optional name substrings; when given, yields fire only on lines
        whose source mentions one (e.g. ``guarded_fields(engine_py)``).
    """

    def __init__(self, seed: Optional[int] = None,
                 switch_interval: float = 1e-5,
                 yield_probability: float = 0.1,
                 sleep_s: float = 2e-5,
                 path_filters: Sequence[str] = ("raft_tpu",),
                 fields: Optional[Iterable[str]] = None):
        self.seed = env_seed() if seed is None else int(seed)
        self.switch_interval = switch_interval
        self.yield_probability = yield_probability
        self.sleep_s = sleep_s
        self.path_filters = tuple(path_filters)
        self.fields = tuple(fields) if fields is not None else None
        self._thread_ids = itertools.count()
        self._local = threading.local()
        self._path_cache: Dict[str, bool] = {}
        self._line_cache: Dict[Tuple[str, int], bool] = {}
        self._old_interval: Optional[float] = None
        self._old_thread_trace = None

    # ------------------------------------------------------------ seeded rng
    def _rng(self) -> random.Random:
        rng = getattr(self._local, "rng", None)
        if rng is None:
            # thread index, not OS ident: creation order is stable for a
            # fixed workload, so the yield pattern replays with the seed
            idx = next(self._thread_ids)
            rng = self._local.rng = random.Random((self.seed << 16) ^ idx)
        return rng

    def _path_matches(self, filename: str) -> bool:
        hit = self._path_cache.get(filename)
        if hit is None:
            hit = any(s in filename for s in self.path_filters)
            self._path_cache[filename] = hit
        return hit

    def _line_matches(self, filename: str, lineno: int) -> bool:
        if self.fields is None:
            return True
        key = (filename, lineno)
        hit = self._line_cache.get(key)
        if hit is None:
            src = linecache.getline(filename, lineno)
            hit = any(f in src for f in self.fields)
            self._line_cache[key] = hit
        return hit

    # --------------------------------------------------------------- tracer
    def _call_tracer(self, frame, event, arg):
        if event != "call" or not self._path_matches(
                frame.f_code.co_filename):
            return None
        rng = self._rng()

        def line_tracer(frame, event, arg):
            if (event == "line" and rng.random() < self.yield_probability
                    and self._line_matches(frame.f_code.co_filename,
                                           frame.f_lineno)):
                time.sleep(self.sleep_s if rng.random() < 0.5 else 0.0)
            return line_tracer

        return line_tracer

    # ------------------------------------------------------------- lifecycle
    def __enter__(self) -> "InterleaveAmplifier":
        self._old_interval = sys.getswitchinterval()
        sys.setswitchinterval(self.switch_interval)
        gettrace = getattr(threading, "gettrace", lambda: None)
        self._old_thread_trace = gettrace()
        threading.settrace(self._call_tracer)
        sys.settrace(self._call_tracer)
        return self

    def __exit__(self, *exc) -> None:
        sys.settrace(None)
        threading.settrace(self._old_thread_trace)
        if self._old_interval is not None:
            sys.setswitchinterval(self._old_interval)
        return None
