"""Composable fault injectors for chaos tests (tests/test_faults.py).

Each injector perturbs exactly one failure domain the serving stack claims
to survive (docs/robustness.md):

- checkpoint bytes — :func:`flip_record_byte`, :func:`truncate_record`,
  :func:`truncate_file` corrupt/cut a specific framed record of a
  core.serialize v2 file, exercising the crc + footer detection paths;
- checkpoint files — :func:`delete_rank_file` removes one shard's rank
  file, exercising degraded-mode (``allow_partial``) restore;
- the host p2p fabric — :func:`sever_connection` hard-cuts a live
  outbound connection mid-stream, exercising send retry and peer-death
  grace logic;
- memory budget — :func:`shrink_workspace` pins a Resources' workspace
  ceiling low, exercising the tiled fallbacks that keep results
  bit-identical under pressure;
- the serving device path — :func:`fail_next_dispatch`,
  :func:`hang_next_dispatch`, :func:`slow_searcher` perturb a serving
  :class:`~raft_tpu.serving.searchers.Searcher` handle's device call,
  exercising the engine's per-batch failure containment, the hang
  watchdog + circuit breaker, and deadline/overload shedding
  (tests/test_serving_chaos.py);
- the mutable write path — :func:`tear_wal_tail` damages the LAST frame
  of a ``MutableIvf`` write-ahead log (truncate mid-payload or flip a
  byte), the crash-mid-append shape recovery must classify as a typed
  ``IntegrityError(reason="torn_tail")`` and truncate away, and
  :func:`crash_compactor` kills the background compactor between
  artifact write and publish (``CompactorCrashed``), the window where
  checkpoint and serving generation disagree until replay reconciles
  them — tests/test_mutable.py;
- fleet replicas — :func:`kill_replica` hard-stops one engine of a
  :class:`~raft_tpu.serving.fleet.Fleet` mid-traffic (queued riders
  fail typed and must be retried on a sibling), :func:`hang_replica`
  stalls one replica's next device call (watchdog → breaker → the
  fleet routes around it), and :func:`trip_breaker` opens a replica's
  circuit breaker directly (the route-around + probe re-admission path
  without waiting out a real hang) — tests/test_fleet_chaos.py.

All injectors operate on real bytes/sockets — no monkeypatched readers —
so the detection paths under test are the ones production restores run.
The serving injectors wrap the handle's real search callable (the same
object the dispatch thread calls), so the engine's containment sees the
exception/hang exactly where a sick device would raise it.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Iterator, Optional, Tuple

from raft_tpu.core.serialize import record_spans


def _span(path: str, record: int) -> Tuple[int, int]:
    spans = record_spans(path)
    if not -len(spans) <= record < len(spans):
        raise IndexError(
            f"{path}: record {record} out of range ({len(spans)} records, "
            f"footer included)")
    return spans[record]


def flip_record_byte(path: str, record: int, offset: int = 0) -> int:
    """XOR one payload byte of record ``record`` (negative indexes from the
    end; -1 is the footer) so the frame's crc32 no longer matches. Returns
    the absolute file offset flipped."""
    off, n = _span(path, record)
    if n == 0:
        raise ValueError(f"{path}: record {record} has an empty payload")
    if not 0 <= offset < n:
        raise IndexError(
            f"{path}: offset {offset} outside record {record}'s {n} "
            f"payload bytes")
    pos = off + offset
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))
    return pos


def truncate_record(path: str, record: int) -> int:
    """Cut the file mid-way through record ``record``'s payload (half of
    it survives), as a crash mid-write would. Returns the new size."""
    off, n = _span(path, record)
    new_size = off + n // 2
    with open(path, "r+b") as f:
        f.truncate(new_size)
    return new_size


def truncate_file(path: str, drop_bytes: int = 1) -> int:
    """Drop the last ``drop_bytes`` bytes (footer-tail truncation — the
    torn-write case atomic replace prevents, kept for files that bypassed
    it). Returns the new size."""
    size = os.path.getsize(path)
    new_size = max(size - int(drop_bytes), 0)
    with open(path, "r+b") as f:
        f.truncate(new_size)
    return new_size


def delete_rank_file(prefix: str, rank: int) -> str:
    """Remove shard ``rank``'s checkpoint file (``prefix.rank<rank>``),
    simulating a lost disk/object. Returns the removed path."""
    path = f"{prefix}.rank{rank}"
    os.remove(path)
    return path


def sever_connection(endpoint, dest: int) -> bool:
    """Hard-cut ``endpoint``'s live outbound connection to rank ``dest``
    (both directions, like a mid-stream network partition). Returns False
    when no connection is currently open — callers racing a send should
    retry until it lands. The endpoint's send retry/backoff is expected to
    re-deliver."""
    return endpoint._sever_send(dest)


def partition_hosts(a, b):
    """Two-way network partition between endpoint ``a`` and peer ``b``
    (a :class:`~raft_tpu.parallel.host_p2p.HostP2P` endpoint, or a bare
    rank int for one-sided partitions — the split-brain shape, where
    ``a`` cannot reach ``b`` but ``b`` is alive and self-reporting ok).

    Every live connection is cut AND every reconnect attempt fails
    typed (EHOSTUNREACH) until the returned zero-arg ``heal()`` runs;
    heal also clears stream poison on both sides so healed links carry
    traffic again (the breaker-probe re-admission path exercises this,
    tests/test_remote_fleet.py)."""
    b_rank = b if isinstance(b, int) else b.rank
    a._partition(b_rank)
    two_way = not isinstance(b, int)
    if two_way:
        b._partition(a.rank)

    def heal():
        a._heal(b_rank)
        if two_way:
            b._heal(a.rank)
    return heal


def delay_link(endpoint, dest: int, delay_s: float):
    """Inject ``delay_s`` of extra one-way latency on every frame
    ``endpoint`` sends to rank ``dest`` (a slow WAN hop / congested
    link, the gray-failure sibling of :func:`partition_hosts`). Returns
    a zero-arg restore function."""
    endpoint._set_link_delay(dest, float(delay_s))

    def restore():
        endpoint._set_link_delay(dest, None)
    return restore


def kill_host(target) -> None:
    """Abrupt host death — no drain frame, no goodbye. For a
    ``subprocess.Popen`` (a replica_main child): SIGKILL. For an
    in-process :class:`~raft_tpu.parallel.host_p2p.HostP2P` endpoint:
    close without :meth:`announce_drain`, so peers get the peer-death
    grace-timer verdict, not the typed clean ``PeerDrained`` — exactly
    the distinction the fleet's typed accounting must preserve."""
    if hasattr(target, "kill") and hasattr(target, "pid"):
        target.kill()
        return
    target.close()


# ------------------------------------------------- mutable-WAL injectors


def _resolve_writer(target):
    """Accept an Engine (its searcher must serve a mutable index), a bare
    ``MutableIvf`` writer, or a WAL path string; return ``(writer, path)``
    where ``writer`` is None for a bare path. Mirrors
    :func:`_resolve_replica`'s target flexibility so chaos tests read the
    same against either surface."""
    if isinstance(target, (str, os.PathLike)):
        return None, os.fspath(target)
    if hasattr(target, "swap_index") and hasattr(target, "writer"):
        target = target.writer()  # Engine -> the index behind the searcher
    wal_path = getattr(target, "wal_path", None)
    if wal_path is None:
        raise TypeError(
            f"tear_wal_tail wants an Engine serving a mutable index, a "
            f"MutableIvf writer, or a WAL path; got "
            f"{type(target).__name__}")
    return target, wal_path


def tear_wal_tail(target, mode: str = "truncate") -> str:
    """Damage the LAST frame of the write-ahead log — the crash-mid-append
    shape. ``mode="truncate"`` cuts the file mid-way through the final
    record's payload (the length header survives, the bytes don't);
    ``mode="flip"`` XORs one payload byte so the frame's crc32 fails.
    Either way nothing follows the damaged frame, so recovery must
    classify it ``torn_tail`` (typed, recoverable by truncation) — the
    same damage mid-file would be ``corrupt``.

    ``target`` resolves like the fleet injectors: an Engine serving a
    mutable index, a bare ``MutableIvf`` writer (synced first so the
    frame under attack is really on disk), or a WAL path. Returns the
    damaged path. Real bytes, no monkeypatched readers."""
    writer, path = _resolve_writer(target)
    if writer is not None:
        writer.sync()
    spans = record_spans(path)
    if not spans:
        raise ValueError(f"{path}: no WAL records to tear")
    if mode == "truncate":
        truncate_record(path, -1)
    elif mode == "flip":
        flip_record_byte(path, -1)
    else:
        raise ValueError(f"unknown tear mode {mode!r}; "
                         f"expected 'truncate' or 'flip'")
    return path


def _resolve_compactor(target):
    """Engine / MutableIvf / Compactor -> the Compactor."""
    if hasattr(target, "swap_index") and hasattr(target, "writer"):
        target = target.writer()
    comp = getattr(target, "compactor", target)
    if not hasattr(comp, "_crash_after_checkpoint"):
        raise TypeError(
            f"crash_compactor wants an Engine serving a mutable index, a "
            f"MutableIvf with an attached Compactor, or a Compactor; got "
            f"{type(target).__name__}")
    return comp


@contextlib.contextmanager
def crash_compactor(target) -> Iterator:
    """Context manager: while active, any compaction run on ``target``'s
    compactor dies between artifact write (checkpoint durable) and
    publish (hot swap) — the widest crash window, where the on-disk
    state is ahead of the serving generation. The run records a typed
    ``CompactorCrashed`` (outcome ``"failed"``, counted + spanned like
    any other run, never an untyped escape), and a recovery/replay must
    reconcile to the exact acknowledged prefix. Yields the compactor."""
    comp = _resolve_compactor(target)
    comp._crash_after_checkpoint = True
    try:
        yield comp
    finally:
        comp._crash_after_checkpoint = False


# ----------------------------------------------------- serving injectors


class InjectedFault(RuntimeError):
    """The exception :func:`fail_next_dispatch` raises by default — a
    distinctive type so chaos tests can assert the engine relayed THIS
    cause (via ``BatchFailed.cause``) and not some coincidental error."""


def _wrap_search(searcher, wrapper):
    """Replace ``searcher.search`` with ``wrapper(original, queries, k)``
    and return a zero-arg restore function. The wrapper is installed on
    the real handle attribute, so the engine's dispatch thread (and any
    solo oracle call) goes through it — no engine internals are
    monkeypatched."""
    original = searcher.search

    def wrapped(queries, k):
        return wrapper(original, queries, k)

    searcher.search = wrapped

    def restore():
        searcher.search = original

    return restore


def fail_next_dispatch(searcher, exc: Optional[BaseException] = None,
                       times: int = 1):
    """Arm ``searcher`` so its next ``times`` search calls raise (default
    :class:`InjectedFault`), then pass through untouched — the injected
    analog of a transient device/runtime error mid-serve. Returns a
    zero-arg disarm function (idempotent; auto-disarms after ``times``).
    Thread-safe: the dispatch thread may race the arming."""
    state = {"left": int(times)}
    lock = threading.Lock()

    def wrapper(original, queries, k):
        with lock:
            armed = state["left"] > 0
            if armed:
                state["left"] -= 1
        if armed:
            raise exc if exc is not None else InjectedFault(
                "injected dispatch failure")
        return original(queries, k)

    return _wrap_search(searcher, wrapper)


def hang_next_dispatch(searcher, hang_s: float, times: int = 1):
    """Arm ``searcher`` so its next ``times`` search calls block for
    ``hang_s`` seconds before delegating — a device call that stops
    answering (the watchdog should fail the batch and trip the breaker
    long before the sleep ends). Returns a zero-arg disarm function."""
    state = {"left": int(times)}
    lock = threading.Lock()

    def wrapper(original, queries, k):
        with lock:
            armed = state["left"] > 0
            if armed:
                state["left"] -= 1
        if armed:
            time.sleep(float(hang_s))
        return original(queries, k)

    return _wrap_search(searcher, wrapper)


@contextlib.contextmanager
def slow_searcher(searcher, delay_s: float) -> Iterator:
    """Context manager: every search on ``searcher`` pays an extra
    ``delay_s`` while active — sustained device slowness, the overload
    injector (drives queue depth past the admission watermarks without
    needing a flood of real work)."""
    restore = _wrap_search(
        searcher,
        lambda original, queries, k: (time.sleep(float(delay_s)),
                                      original(queries, k))[1])
    try:
        yield searcher
    finally:
        restore()


# ------------------------------------------------------- fleet injectors


def _resolve_replica(fleet_or_engine, replica):
    """Accept either an Engine (``replica`` ignored) or a Fleet plus a
    replica name/index, returning the target engine. Keeps chaos tests
    readable: ``kill_replica(fleet, "replica1")``."""
    engine = fleet_or_engine
    replicas = getattr(fleet_or_engine, "replicas", None)
    if replicas is not None:
        if isinstance(replica, int):
            engine = replicas[replica].engine
        else:
            by_name = {r.name: r.engine for r in replicas}
            if replica not in by_name:
                raise KeyError(
                    f"no replica {replica!r} (have {sorted(by_name)})")
            engine = by_name[replica]
    return engine


def kill_replica(fleet_or_engine, replica=None) -> None:
    """Hard-kill one replica mid-traffic: ``Engine.stop(drain=False)``
    — queued riders fail typed (``EngineStopped`` / cancelled futures,
    never silent), batches already on the device still complete, and
    the replica's ``health()`` goes ``"unhealthy"`` so the fleet routes
    around it and retries the casualties on siblings. The injected
    analog of a replica process dying; a killed engine does not come
    back."""
    _resolve_replica(fleet_or_engine, replica).stop(drain=False)


def hang_replica(fleet_or_engine, replica=None, hang_s: float = 60.0,
                 times: int = 1):
    """Stall one replica's next ``times`` device calls for ``hang_s``
    (default long enough that the hang watchdog, not the sleep, ends
    the episode): the watchdog fails the batch (``BatchFailed`` with
    ``.hang``), trips the breaker, and the fleet must route around the
    replica until a probe closes the breaker again. Returns the
    zero-arg disarm function from :func:`hang_next_dispatch`."""
    engine = _resolve_replica(fleet_or_engine, replica)
    return hang_next_dispatch(engine.searcher, hang_s, times=times)


def trip_breaker(fleet_or_engine, replica=None) -> None:
    """Open one replica's circuit breaker NOW, exactly as the watchdog
    would on a hang (same ``trip()`` + trip counter), without paying a
    real ``hang_timeout_s`` wait — the fast path to exercising the
    fleet's route-around and half-open probe re-admission."""
    engine = _resolve_replica(fleet_or_engine, replica)
    engine.breaker.trip()
    engine.stats.record_breaker_trip()


@contextlib.contextmanager
def shrink_workspace(res, limit_bytes: int = 1 << 20,
                     restore: Optional[int] = None) -> Iterator:
    """Temporarily pin ``res.workspace_limit_bytes`` to ``limit_bytes``
    (default 1 MiB — small enough to force the tiled paths at test sizes).
    Restores the previous explicit limit (or ``restore``) on exit."""
    prev = res._workspace_limit
    res._workspace_limit = int(limit_bytes)
    try:
        yield res
    finally:
        res._workspace_limit = prev if restore is None else int(restore)
