"""Test-support utilities shipped with the library (not the test suite):
fault injectors for chaos-testing checkpoint restore, host p2p, and
memory-budget behavior. See :mod:`raft_tpu.testing.faults`."""

from raft_tpu.testing import faults  # noqa: F401
