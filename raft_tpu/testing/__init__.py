"""Test-support utilities shipped with the library (not the test suite):
fault injectors for chaos-testing checkpoint restore, host p2p, and
memory-budget behavior (:mod:`raft_tpu.testing.faults`), plus the
seeded schedule amplifier for concurrency tests
(:mod:`raft_tpu.testing.interleave`)."""

from raft_tpu.testing import faults, interleave  # noqa: F401
