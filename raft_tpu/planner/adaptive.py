"""Committed Pareto frontiers + the deadline-aware operating-point policy.

The repo measures everything a query planner needs — per-phase latency
percentiles, compiled-cost rooflines with min-attainable times, a
per-request ``deadline_ms``, an online recall estimate — yet every
speed/recall knob (``n_probes``, ``itopk_size``, ``scan_mode``, query
bucket) is still frozen at SearchParams construction. This module closes
the loop (ROADMAP open item 5; the ann-benchmarks QPS@recall
methodology, PAPERS.md):

- ``tools/autotune.py`` sweeps the knob grid offline against an exact
  oracle and commits the non-dominated QPS-vs-recall frontier as
  ``PARETO_<platform>.json`` (:data:`PARETO_SCHEMA`, same artifact
  discipline as PALLAS_PROBE / SELECT_K_TABLE: schema-versioned, flat
  ``"metrics"`` mirror, diffed by ``tools/bench_gate.py``'s curve-aware
  ``frontier`` kind);
- :func:`choose_operating_point` is the policy: given a frontier and the
  batch's remaining latency budget, return the highest-recall point
  whose predicted device time fits — pure and deterministic given
  (points, budget, floor, scale), which is what the property tests pin;
- :class:`Calibration` rescales the committed predictions against the
  live device-time histogram (EWMA of observed/predicted, bounded) so a
  mispredicted frontier self-corrects instead of thrashing;
- :class:`AdaptivePlanner` bundles the three for the serving engine and
  attributes every choice: the
  ``raft_tpu_adaptive_choice_total{family,reason}`` counter plus an
  :class:`~raft_tpu.obs.explain.ExplainRecord` into the open capture, so
  each degradation decision rides the request span.

Layering: registry-only, like :mod:`raft_tpu.obs.explain` — no jax, no
neighbors import. The sweep machinery that *produces* frontiers lives in
:mod:`raft_tpu.planner.sweep`.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from raft_tpu.obs import explain as obs_explain
from raft_tpu.obs import metrics as _metrics

__all__ = [
    "ADAPTIVE_REASONS",
    "PARETO_SCHEMA",
    "RECALL_BANDS",
    "OperatingPoint",
    "Choice",
    "Frontier",
    "Calibration",
    "AdaptivePlanner",
    "pareto_prune",
    "choose_operating_point",
    "hypervolume",
    "qps_at_recall",
    "frontier_metrics",
    "load_frontier",
    "record_choice",
    "adaptive_choice_counts",
]

#: Artifact schema tag; bench_gate keys its curve-aware ``frontier``
#: comparison off this string (bump on breaking layout changes).
PARETO_SCHEMA = "raft_tpu.pareto/v1"

#: The closed choice-reason vocabulary — a subset of
#: :data:`raft_tpu.obs.explain.REASONS` so choices ride the same explain
#: stream as engine dispatch decisions.
ADAPTIVE_REASONS = frozenset({
    "pareto_default",     # highest-recall point fits the budget (or no
                          # deadline: nothing to trade away)
    "deadline_degraded",  # budget forced a lower-recall point
    "floor_clamped",      # recall floor stopped the degradation: the
                          # chosen point may overrun the budget, but it
                          # never dips below the floor
    "no_frontier",        # no committed points for (family, k): static
                          # SearchParams serve, nothing is degraded
})

#: Recall bands the flat metrics mirror (and bench_gate's frontier kind)
#: report best-QPS at.
RECALL_BANDS = (0.80, 0.90, 0.95, 0.99)

_CHOICE = _metrics.REGISTRY.counter(
    "raft_tpu_adaptive_choice_total",
    "Adaptive-planner operating-point choices by family and reason "
    "(docs/tuning.md 'Adaptive planning').",
    ("family", "reason"))


@dataclasses.dataclass(frozen=True)
class OperatingPoint:
    """One measured (params, bucket) point on a QPS-vs-recall frontier.

    ``params`` is the SearchParams override dict the serving handles
    apply per batch (``Searcher.search_with``); ``bucket`` is the query
    bucket the point was measured at; ``predicted_ms`` is the committed
    per-batch device-time prediction the policy budgets against (before
    live calibration); ``roofline_min_ms`` is the obs/costs roofline
    floor for the family entrypoint where peaks are known (None on CPU)
    — a sanity anchor, never below which a prediction is trusted."""

    params: Dict[str, object]
    bucket: int
    qps: float
    recall: float
    predicted_ms: float
    roofline_min_ms: Optional[float] = None

    def to_dict(self) -> dict:
        d = {"params": dict(self.params), "bucket": int(self.bucket),
             "qps": round(float(self.qps), 3),
             "recall": round(float(self.recall), 6),
             "predicted_ms": round(float(self.predicted_ms), 6)}
        if self.roofline_min_ms is not None:
            d["roofline_min_ms"] = round(float(self.roofline_min_ms), 6)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "OperatingPoint":
        return cls(params=dict(d.get("params", {})),
                   bucket=int(d["bucket"]), qps=float(d["qps"]),
                   recall=float(d["recall"]),
                   predicted_ms=float(d["predicted_ms"]),
                   roofline_min_ms=(float(d["roofline_min_ms"])
                                    if d.get("roofline_min_ms") is not None
                                    else None))

    def _sort_key(self):
        # total deterministic order: recall desc, qps desc, time asc,
        # then the params repr as the final tie-break (sweep logs arrive
        # in arbitrary order; the frontier must not depend on it)
        return (-self.recall, -self.qps, self.predicted_ms,
                json.dumps(self.params, sort_keys=True))


def pareto_prune(points: Sequence[OperatingPoint]) -> List[OperatingPoint]:
    """Non-dominated subset of ``points``, highest recall first.

    A point is kept iff no other point has >= recall AND > qps (ties on
    both collapse to one representative via the deterministic sort key).
    The result is monotone: recall strictly decreases down the list and
    qps strictly increases — the invariant the property tests pin."""
    out: List[OperatingPoint] = []
    best_qps = float("-inf")
    for p in sorted(points, key=OperatingPoint._sort_key):
        # sorted recall desc (qps desc within a tie): a point survives
        # iff it beats every higher-recall point's qps strictly, which
        # also collapses recall ties to their best-qps representative
        if p.qps > best_qps:
            out.append(p)
            best_qps = p.qps
    return out


def choose_operating_point(
        points: Sequence[OperatingPoint],
        remaining_budget_ms: Optional[float],
        recall_floor: Optional[float] = None,
        scale: float = 1.0,
) -> Tuple[Optional[OperatingPoint], str]:
    """THE policy: spend the latency budget on recall.

    Pure and deterministic given its arguments (the acceptance
    criterion): no clocks, no globals, no randomness. ``points`` is a
    frontier (any order; re-sorted highest-recall-first internally);
    ``scale`` is the live calibration multiplier applied to every
    ``predicted_ms`` before comparing against the budget.

    Returns ``(point, reason)`` with ``reason`` in
    :data:`ADAPTIVE_REASONS`:

    - no points → ``(None, "no_frontier")`` — serve the static params;
    - no budget (request has no deadline) → highest-recall point,
      ``pareto_default``;
    - the highest-recall point above the floor fits → it,
      ``pareto_default``;
    - a lower point fits → the highest-recall fitting one,
      ``deadline_degraded``;
    - nothing above the floor fits → the fastest point still above the
      floor — ``floor_clamped`` when the floor actually excluded faster
      points, else ``deadline_degraded`` (the frontier simply bottoms
      out above the budget). Degradation stops at the floor by design:
      the point may overrun the budget, but recall never goes below it.
    """
    if not points:
        return None, "no_frontier"
    pts = sorted(points, key=OperatingPoint._sort_key)
    eligible = [p for p in pts
                if recall_floor is None or p.recall >= recall_floor]
    if not eligible:
        # floor above the entire frontier: clamp to the best we have
        return pts[0], "floor_clamped"
    floor_bound = len(eligible) < len(pts)
    if remaining_budget_ms is None:
        return eligible[0], "pareto_default"
    for p in eligible:
        if p.predicted_ms * scale <= remaining_budget_ms:
            return p, ("pareto_default" if p is eligible[0]
                       else "deadline_degraded")
    fastest = eligible[-1]
    return fastest, ("floor_clamped" if floor_bound
                     else "deadline_degraded")


# ------------------------------------------------------- curve summaries
def hypervolume(points: Sequence[OperatingPoint]) -> float:
    """2-D hypervolume of the frontier vs the (recall=0, qps=0)
    reference point — the area under the staircase, the scalar a curve
    refresh is gated on (points may move along the curve freely; the
    dominated area must not shrink)."""
    pruned = pareto_prune(points)  # recall desc, qps asc
    hv = 0.0
    prev_recall = 0.0
    for p in reversed(pruned):  # recall asc, qps desc
        hv += (p.recall - prev_recall) * p.qps
        prev_recall = p.recall
    return hv


def qps_at_recall(points: Sequence[OperatingPoint],
                  band: float) -> Optional[float]:
    """Best QPS among points with recall >= ``band`` (None when the
    frontier never reaches the band)."""
    vals = [p.qps for p in points if p.recall >= band]
    return max(vals) if vals else None


def frontier_metrics(doc: dict) -> Dict[str, float]:
    """Flat ``{metric: value}`` summary of a :data:`PARETO_SCHEMA` doc:
    per (family, k, bucket) curve, the hypervolume and best-QPS per
    recall band — the artifact's ``"metrics"`` mirror, and what
    bench_gate's ``frontier`` kind compares instead of raw points."""
    out: Dict[str, float] = {}
    for fam, fam_doc in sorted((doc.get("families") or {}).items()):
        for k_key, buckets in sorted((fam_doc.get("frontier") or {}).items()):
            for b_key, raw in sorted(buckets.items()):
                pts = [OperatingPoint.from_dict(p) for p in raw]
                stem = f"pareto.{fam}.k{k_key}.b{b_key}"
                out[f"{stem}.hypervolume"] = round(hypervolume(pts), 4)
                out[f"{stem}.n_points"] = float(len(pts))
                for band in RECALL_BANDS:
                    q = qps_at_recall(pts, band)
                    if q is not None:
                        out[f"{stem}.qps_at_r{int(band * 100)}"] = round(
                            q, 3)
    return out


# ------------------------------------------------------------ the artifact
class Frontier:
    """Loaded ``PARETO_<platform>.json``: per-(family, k, bucket) point
    lists, with nearest-bucket lookup for serving."""

    def __init__(self, doc: dict):
        schema = doc.get("schema")
        if schema != PARETO_SCHEMA:
            raise ValueError(
                f"frontier schema {schema!r} != {PARETO_SCHEMA!r} "
                f"(regenerate with tools/autotune.py)")
        self.doc = doc
        self.platform = str(doc.get("platform", "unknown"))
        # (family, k) -> {bucket: [OperatingPoint, ...] recall desc}
        self._points: Dict[Tuple[str, int], Dict[int, List[OperatingPoint]]]
        self._points = {}
        for fam, fam_doc in (doc.get("families") or {}).items():
            for k_key, buckets in (fam_doc.get("frontier") or {}).items():
                by_bucket = self._points.setdefault((fam, int(k_key)), {})
                for b_key, raw in buckets.items():
                    by_bucket[int(b_key)] = pareto_prune(
                        OperatingPoint.from_dict(p) for p in raw)

    @property
    def families(self) -> List[str]:
        return sorted({fam for fam, _ in self._points})

    def ks(self, family: str) -> List[int]:
        return sorted(k for fam, k in self._points if fam == family)

    def points(self, family: str, k: int,
               bucket: Optional[int] = None) -> List[OperatingPoint]:
        """Frontier for (family, k) at the measured bucket nearest
        ``bucket``. When the serving bucket differs from the measured
        one, ``predicted_ms`` is scaled linearly by the row ratio — an
        approximation the live :class:`Calibration` corrects — while
        ``bucket`` keeps the measured value for provenance. Empty list
        when the artifact has nothing for (family, k)."""
        by_bucket = self._points.get((str(family), int(k)))
        if not by_bucket:
            return []
        if bucket is None:
            src = max(by_bucket)
        else:
            src = min(by_bucket, key=lambda b: (abs(b - int(bucket)), b))
        pts = by_bucket[src]
        if bucket is None or src == int(bucket):
            return list(pts)
        ratio = int(bucket) / src
        return [dataclasses.replace(p, predicted_ms=p.predicted_ms * ratio)
                for p in pts]


def load_frontier(path: str) -> Frontier:
    """Read + validate a committed ``PARETO_<platform>.json``. Raises
    ``OSError`` on a missing file and ``ValueError`` on a schema
    mismatch — callers that want missing→static-params semantics (the
    engine) catch and serve with no planner frontier."""
    with open(path) as fh:
        return Frontier(json.load(fh))


# ----------------------------------------------------------- calibration
class Calibration:
    """EWMA of observed/predicted device time, bounded.

    The committed ``predicted_ms`` was measured on some machine at some
    point; the serving host's truth is the live device-time histogram.
    Each completed adaptive batch feeds :meth:`observe`; :attr:`scale`
    is the clamped EWMA ratio the policy multiplies predictions by.
    Bounded (``lo``/``hi``) so one pathological sample cannot swing the
    policy to shedding everything or promising the impossible."""

    def __init__(self, alpha: float = 0.2, lo: float = 0.25,
                 hi: float = 4.0):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = float(alpha)
        self.lo, self.hi = float(lo), float(hi)
        self._lock = threading.Lock()
        self._ratio = 1.0  # guarded_by: _lock
        self._n = 0  # guarded_by: _lock

    def observe(self, predicted_ms: float, actual_ms: float) -> None:
        if predicted_ms <= 0.0 or actual_ms <= 0.0:
            return
        # clamp the single observation too: a warmup compile or a hung
        # readback must nudge the EWMA, not own it
        r = min(max(actual_ms / predicted_ms, self.lo), self.hi)
        with self._lock:
            self._ratio += self.alpha * (r - self._ratio)
            self._n += 1

    @property
    def scale(self) -> float:
        with self._lock:
            return min(max(self._ratio, self.lo), self.hi)

    @property
    def n_observed(self) -> int:
        with self._lock:
            return self._n


# ------------------------------------------------------------ attribution
def record_choice(family: str, reason: str,
                  point: Optional[OperatingPoint] = None,
                  budget_ms: Optional[float] = None,
                  predicted_ms: Optional[float] = None) -> None:
    """Attribute one operating-point choice, twice from one call site:
    bump ``raft_tpu_adaptive_choice_total{family,reason}`` and emit an
    explain record (``requested="adaptive"``, ``engine="planner"``) into
    every open capture so the choice rides the batch/request spans
    exactly like the engine-dispatch decisions do. ``reason`` outside
    :data:`ADAPTIVE_REASONS` raises — closed vocabulary, same contract
    as :func:`raft_tpu.obs.explain.record_dispatch`."""
    if reason not in ADAPTIVE_REASONS:
        raise ValueError(f"reason {reason!r} outside the adaptive choice "
                         f"vocabulary {sorted(ADAPTIVE_REASONS)}")
    _CHOICE.labels(family, reason).inc()
    params = dict(point.params) if point is not None else {}
    plan: Dict[str, object] = {}
    if budget_ms is not None:
        plan["budget_ms"] = round(float(budget_ms), 3)
    if predicted_ms is not None:
        plan["predicted_ms"] = round(float(predicted_ms), 3)
    if point is not None:
        plan["recall"] = round(float(point.recall), 6)
    obs_explain.record_dispatch(family, "adaptive", "planner", reason,
                                params=params, plan=plan)


def adaptive_choice_counts(
        registry: Optional[_metrics.Registry] = None) -> Dict[tuple, int]:
    """``{(family, reason): count}`` view of the adaptive choice counter
    (serving_bench's proof that every degradation decision is
    visible)."""
    reg = registry if registry is not None else _metrics.REGISTRY
    fam = reg.get("raft_tpu_adaptive_choice_total")
    if fam is None:
        return {}
    return {tuple(key): int(child.value) for key, child in fam.collect()
            if int(child.value)}


# -------------------------------------------------------------- the planner
@dataclasses.dataclass
class Choice:
    """One resolved operating point, as handed to the engine: the point
    (None on ``no_frontier``), the closed reason, and the calibrated
    prediction the completion loop reconciles against ``device_ms``."""

    point: Optional[OperatingPoint]
    reason: str
    budget_ms: Optional[float]
    predicted_ms: Optional[float]
    scale: float

    def brief(self) -> dict:
        d: Dict[str, object] = {"reason": self.reason,
                                "scale": round(self.scale, 4)}
        if self.budget_ms is not None:
            d["budget_ms"] = round(self.budget_ms, 3)
        if self.point is not None:
            d["params"] = dict(self.point.params)
            d["recall"] = round(self.point.recall, 6)
            d["predicted_ms"] = round(self.predicted_ms, 3)
        return d


class AdaptivePlanner:
    """Frontier + floor + calibration, bundled for the serving engine.

    ``frontier`` may be None (or a path that fails to load may be
    handled by the caller) — every choice is then ``no_frontier`` and
    the engine serves its static SearchParams, attributed. The planner
    is cheap and thread-safe: :meth:`choose` runs on the dispatch
    thread per batch, :meth:`observe` on the completion thread."""

    def __init__(self, frontier: Optional[Frontier] = None,
                 recall_floor: Optional[float] = None,
                 calibration: Optional[Calibration] = None):
        self.frontier = frontier
        self.recall_floor = (float(recall_floor)
                             if recall_floor is not None else None)
        self.calibration = calibration or Calibration()

    @classmethod
    def from_artifact(cls, path: str,
                      recall_floor: Optional[float] = None,
                      calibration: Optional[Calibration] = None
                      ) -> "AdaptivePlanner":
        """Planner from a committed artifact path; a missing or
        schema-mismatched file degrades to a frontier-less planner
        (every choice ``no_frontier``) rather than failing serving."""
        try:
            frontier = load_frontier(path)
        except (OSError, ValueError):
            frontier = None
        return cls(frontier, recall_floor=recall_floor,
                   calibration=calibration)

    def choose(self, family: str, k: int, bucket: Optional[int],
               remaining_budget_ms: Optional[float]) -> Choice:
        """Resolve + attribute the batch's operating point. A negative
        remaining budget (riders already past their deadline still get
        served if the batcher launched them) degrades like a tiny one —
        the fastest floor-eligible point."""
        points = (self.frontier.points(family, k, bucket)
                  if self.frontier is not None else [])
        scale = self.calibration.scale
        point, reason = choose_operating_point(
            points, remaining_budget_ms, self.recall_floor, scale)
        predicted = (point.predicted_ms * scale
                     if point is not None else None)
        record_choice(family, reason, point=point,
                      budget_ms=remaining_budget_ms,
                      predicted_ms=predicted)
        return Choice(point, reason, remaining_budget_ms, predicted,
                      scale)

    def observe(self, predicted_ms: float, actual_ms: float) -> None:
        """Feed one completed adaptive batch's (calibrated prediction,
        measured device_ms) back into the EWMA. The prediction passed in
        is the *calibrated* one the policy used; dividing out the scale
        keeps the loop stable (the EWMA tracks the raw-prediction error,
        not its own output)."""
        scale = self.calibration.scale
        if scale > 0:
            self.calibration.observe(predicted_ms / scale, actual_ms)

    def warm_points(self, family: str, k: int,
                    bucket: Optional[int] = None) -> List[OperatingPoint]:
        """Points the engine pre-compiles at warmup (per warm bucket/k)
        so a deadline-driven param change never pays a cold compile on
        the hot path."""
        if self.frontier is None:
            return []
        return self.frontier.points(family, k, bucket)
