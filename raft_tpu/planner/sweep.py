"""Offline operating-point sweep — the machinery behind tools/autotune.py.

Per family/shape/k, measure every (params, query_bucket) grid point
**through the public search APIs** (the serving handles' ``search_with``
— the exact code path the engine's adaptive policy replays online)
against an exact numpy oracle, then prune to the Pareto-optimal
QPS-vs-recall frontier (:func:`raft_tpu.planner.adaptive.pareto_prune`).

Each surviving point carries:

- ``qps``: queries/second at its bucket (bucket / best-of-N per-batch
  wall time, fenced per bench/timing.py);
- ``recall``: mean neighborhood recall vs the exact oracle over the
  whole eval query set;
- ``predicted_ms``: the committed per-batch device-time prediction the
  serving policy budgets against (the measured best-of-N batch time);
- ``roofline_min_ms``: the obs/costs roofline floor for the family's
  compiled entrypoint where chip peaks are known (None on CPU) — the
  anchor that flags a prediction promising less than physics allows.

The default grids are deliberately modest (the artifact is refreshed by
a tpu_queue2.sh step with a bounded window); ``mini=True`` shrinks them
to CI scale (seconds on CPU).
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from raft_tpu.planner import adaptive

__all__ = ["FAMILIES", "default_grid", "exact_oracle", "sweep_family",
           "build_artifact"]

FAMILIES = ("brute_force", "ivf_flat", "ivf_pq", "cagra",
            "tiered_ivf_pq")


def default_grid(family: str, mini: bool = False) -> List[Dict[str, object]]:
    """The params grid per family: every knob combination the sweep
    measures (the frontier prune discards the dominated ones)."""
    if family == "brute_force":
        # exact search: the only speed/recall knob is the select stage's
        # exactness relaxation
        grid = [{"select_recall": 1.0}]
        if not mini:
            grid.append({"select_recall": 0.9})
        return grid
    if family in ("ivf_flat", "ivf_pq", "tiered_ivf_pq"):
        # tiered shares ivf_pq's knob: n_probes trades recall for scan
        # work AND arena churn (more probes -> more distinct lists per
        # batch -> lower hit rate at fixed slots), so the measured
        # frontier already prices the tier's fetch stalls
        probes = (4, 32) if mini else (4, 8, 16, 32, 64)
        return [{"n_probes": int(p)} for p in probes]
    if family == "cagra":
        if mini:
            combos = ((32, 1), (64, 4))
        else:
            combos = ((32, 1), (64, 1), (64, 4), (128, 4))
        # scan_mode is a sweepable knob since the fused Pallas beam
        # engine landed: "auto" follows the committed probe verdict,
        # "pallas" forces the fused walk — sweeping both grows committed
        # Pareto frontiers fused operating points wherever the kernel
        # wins, and keeps an XLA-routed point for replay parity. On
        # hosts with no TPU the forced point measures the silent XLA
        # fallback (identical results, ~identical ms) and the frontier
        # prune discards the duplicate.
        modes = ("auto",) if mini else ("auto", "pallas")
        return [{"itopk_size": int(it), "search_width": int(w),
                 "scan_mode": mode}
                for it, w in combos for mode in modes]
    raise ValueError(f"unknown family {family!r}; expected one of "
                     f"{FAMILIES}")


def _params_key(params: Dict[str, object]) -> str:
    return json.dumps(params, sort_keys=True)


def exact_oracle(db: np.ndarray, queries: np.ndarray,
                 k: int) -> np.ndarray:
    """Ground-truth top-k indices by squared L2, pure numpy (no device,
    no jit — the oracle must not share code with the thing it grades)."""
    d2 = ((queries ** 2).sum(1)[:, None] + (db ** 2).sum(1)[None, :]
          - 2.0 * queries @ db.T)
    part = np.argpartition(d2, kth=k - 1, axis=1)[:, :k]
    order = np.take_along_axis(d2, part, axis=1).argsort(axis=1)
    return np.take_along_axis(part, order, axis=1)


def _build_searcher(family: str, db: np.ndarray, res,
                    mini: bool = False):
    """One index + serving handle per family at sweep-shaped build
    params (mirrors tools/serving_bench.py's bench shapes)."""
    from raft_tpu import serving
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

    n_lists = 32 if mini else 128
    if family == "brute_force":
        index = brute_force.build(db, metric="sqeuclidean", res=res)
        searcher = serving.brute_force_searcher(index, res=res)
        shape = {}
    elif family == "ivf_flat":
        index = ivf_flat.build(db, ivf_flat.IndexParams(n_lists=n_lists),
                               res=res)
        searcher = serving.ivf_flat_searcher(index, res=res)
        shape = {"n_lists": n_lists}
    elif family == "ivf_pq":
        index = ivf_pq.build(
            db, ivf_pq.IndexParams(n_lists=n_lists, pq_dim=32), res=res)
        searcher = serving.ivf_pq_searcher(index, res=res)
        shape = {"n_lists": n_lists, "pq_dim": 32}
    elif family == "cagra":
        index = cagra.build(db, cagra.IndexParams(
            graph_degree=32, intermediate_graph_degree=64), res=res)
        searcher = serving.cagra_searcher(index, res=res)
        shape = {"graph_degree": 32}
    elif family == "tiered_ivf_pq":
        # same index as ivf_pq, lists demoted to host RAM. The arena
        # holds every list (a smaller one could refuse a single batch
        # probing more distinct lists than it has slots): the sweep
        # prices the steady-state HIT path — the slot-indirected scan
        # the planner's operating point actually serves — while arena
        # churn under pressure is serving_bench's tiered arm.
        from raft_tpu.neighbors import tiered
        index = ivf_pq.build(
            db, ivf_pq.IndexParams(n_lists=n_lists, pq_dim=32), res=res)
        t = tiered.TieredIvfPq.from_index(
            index, res=res, arena_slots=n_lists, namespace="sweep")
        searcher = serving.tiered_ivf_pq_searcher(t, res=res)
        shape = {"n_lists": n_lists, "pq_dim": 32,
                 "arena_slots": t.arena.slots}
    else:
        raise ValueError(f"unknown family {family!r}")
    shape.update({"rows": int(db.shape[0]), "dim": int(db.shape[1])})
    return searcher, shape


def _device_peaks():
    """ChipPeaks for the active backend (None on CPU/unknown)."""
    try:
        import jax

        from raft_tpu.obs import costs as obs_costs

        return obs_costs.peaks_for_device_kind(
            jax.devices()[0].device_kind)
    except Exception:
        return None


def _roofline_min_ms(family: str, params: Dict[str, object], shape: dict,
                     bucket: int, peaks) -> Optional[float]:
    """obs/costs roofline floor for one (family, params, bucket) point:
    max(scan bytes / HBM peak, scan FLOPs / MXU peak) per batch — the
    min-attainable device time of the dominant scan phase at this
    operating point (same :func:`raft_tpu.obs.costs.apply_roofline`
    regime rule, applied to the sweep's own workload instead of the
    fixed audit shapes). None on CPU (no peaks table) and for cagra
    (the greedy graph walk is latency-bound, not roofline-bound)."""
    if peaks is None:
        return None
    rows, dim = int(shape["rows"]), int(shape["dim"])
    if family == "brute_force":
        scanned_rows, row_bytes = rows, dim * 4
        flops = 2.0 * bucket * rows * dim
    elif family == "ivf_flat":
        frac = int(params.get("n_probes", 20)) / max(
            int(shape.get("n_lists", 1)), 1)
        scanned_rows, row_bytes = min(frac, 1.0) * rows, dim * 4
        flops = 2.0 * bucket * scanned_rows * dim
    elif family in ("ivf_pq", "tiered_ivf_pq"):
        # the tiered hit path scans decoded slabs through the same
        # cache-core math, so the ivf_pq roofline is its floor too
        frac = int(params.get("n_probes", 20)) / max(
            int(shape.get("n_lists", 1)), 1)
        scanned_rows = min(frac, 1.0) * rows
        row_bytes = int(shape.get("pq_dim", 32))  # one code byte per dim
        flops = 2.0 * bucket * scanned_rows * row_bytes
    else:
        return None
    t_mem = scanned_rows * row_bytes / peaks.hbm_bytes_per_s
    t_flop = flops / peaks.flops_per_s
    return max(t_mem, t_flop) * 1e3


def _time_batch_s(searcher, batch: np.ndarray, k: int,
                  params: Dict[str, object], reps: int) -> float:
    """Best-of-``reps`` fenced wall time for one padded batch (best-of
    kills scheduler hiccups the same way bench_gate's noise rule
    does)."""
    from raft_tpu.bench import timing

    timing.fence(searcher.search_with(batch, k, params))  # warm/compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        timing.fence(searcher.search_with(batch, k, params))
        best = min(best, time.perf_counter() - t0)
    return best


def sweep_family(family: str, db: np.ndarray, queries: np.ndarray,
                 ks: Sequence[int], buckets: Sequence[int],
                 grid: Optional[List[Dict[str, object]]] = None,
                 res=None, reps: int = 3, mini: bool = False,
                 log=None) -> dict:
    """Sweep one family: returns the artifact's per-family payload
    (``shape``, ``build_s``, ``frontier`` keyed ``str(k) -> str(bucket)
    -> [point dicts]``, and sweep accounting)."""
    from raft_tpu.core.resources import ensure_resources

    res = ensure_resources(res)
    grid = grid if grid is not None else default_grid(family, mini=mini)
    t0 = time.perf_counter()
    searcher, shape = _build_searcher(family, db, res, mini=mini)
    build_s = time.perf_counter() - t0
    peaks = _device_peaks()
    n_swept = 0
    frontier: Dict[str, Dict[str, list]] = {}
    eval_bucket = max(buckets)
    for k in ks:
        gt = exact_oracle(db, queries, int(k))
        # recall is per-params, NOT per-bucket: the search cores are
        # row-wise and padding rows are zeros, so a row's result is
        # bucket-invariant (the serving bit-identity guarantee) — grade
        # once at the largest bucket and reuse across the bucket sweep
        recalls: Dict[str, float] = {}
        for params in grid:
            hits, total = 0, 0
            for j in range(0, len(queries), eval_bucket):
                chunk = queries[j:j + eval_bucket]
                batch = np.zeros((eval_bucket, db.shape[1]), np.float32)
                batch[:len(chunk)] = chunk
                _, idx = searcher.search_with(batch, int(k), params)
                idx = np.asarray(idx)[:len(chunk)]
                for row, ref in zip(idx, gt[j:j + eval_bucket]):
                    hits += np.isin(row, ref).sum()
                    total += len(ref)
            recalls[_params_key(params)] = hits / max(total, 1)
        per_bucket: Dict[str, list] = {}
        for bucket in buckets:
            points = []
            for params in grid:
                recall = recalls[_params_key(params)]
                batch = np.zeros((bucket, db.shape[1]), np.float32)
                batch[:] = queries[:bucket] if len(queries) >= bucket \
                    else np.resize(queries, (bucket, db.shape[1]))
                batch_s = _time_batch_s(searcher, batch, int(k), params,
                                        reps)
                points.append(adaptive.OperatingPoint(
                    params=dict(params), bucket=int(bucket),
                    qps=bucket / batch_s, recall=float(recall),
                    predicted_ms=batch_s * 1e3,
                    roofline_min_ms=_roofline_min_ms(
                        family, params, shape, bucket, peaks)))
                n_swept += 1
                if log is not None:
                    log(f"  {family} k={k} b={bucket} {params}: "
                        f"recall={recall:.4f} "
                        f"batch={batch_s * 1e3:.2f} ms")
            pruned = adaptive.pareto_prune(points)
            per_bucket[str(int(bucket))] = [p.to_dict() for p in pruned]
        frontier[str(int(k))] = per_bucket
    return {"shape": shape, "build_s": round(build_s, 2),
            "frontier": frontier, "n_swept": n_swept,
            "grid": [dict(g) for g in grid]}


def build_artifact(platform: str, families: Dict[str, dict],
                   config: Optional[dict] = None) -> dict:
    """Assemble the committed ``PARETO_<platform>.json`` document:
    schema tag, per-family frontiers, and the flat ``"metrics"`` mirror
    bench_gate's generic path reads (the ``frontier`` kind recomputes
    curve summaries from the points themselves)."""
    doc = {
        "schema": adaptive.PARETO_SCHEMA,
        "platform": platform,
        "when": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": dict(config or {}),
        "families": families,
    }
    doc["metrics"] = adaptive.frontier_metrics(doc)
    # round-trip through the loader so a malformed artifact can never be
    # written in the first place
    adaptive.Frontier(doc)
    return doc
