"""raft_tpu.planner — deadline-aware adaptive query planning.

Turns the telemetry stack (phase latencies, compiled-cost rooflines,
per-request deadlines, online recall) from observability into control
(docs/tuning.md "Adaptive planning"):

- :mod:`~raft_tpu.planner.adaptive` — the committed QPS-vs-recall
  Pareto-frontier artifact (``PARETO_<platform>.json``), the pure
  :func:`~raft_tpu.planner.adaptive.choose_operating_point` policy, and
  the EWMA prediction calibration the serving engine feeds from the
  live device-time histogram;
- :mod:`~raft_tpu.planner.sweep` — the offline parameter sweep behind
  ``tools/autotune.py``: per family/shape/k, measure every grid point
  through the PUBLIC search APIs against an exact oracle and prune to
  the non-dominated frontier.

Layering: ``adaptive`` is registry-only (no jax import) so the serving
hot path and the bench_gate tool can load frontiers cheaply; ``sweep``
imports the neighbor families and is tool/offline territory.
"""

from raft_tpu.planner.adaptive import (ADAPTIVE_REASONS, PARETO_SCHEMA,
                                       AdaptivePlanner, Calibration, Choice,
                                       Frontier, OperatingPoint,
                                       adaptive_choice_counts,
                                       choose_operating_point,
                                       frontier_metrics, hypervolume,
                                       load_frontier, pareto_prune,
                                       record_choice)

__all__ = [
    "ADAPTIVE_REASONS",
    "PARETO_SCHEMA",
    "AdaptivePlanner",
    "Calibration",
    "Choice",
    "Frontier",
    "OperatingPoint",
    "adaptive_choice_counts",
    "choose_operating_point",
    "frontier_metrics",
    "hypervolume",
    "load_frontier",
    "pareto_prune",
    "record_choice",
]
