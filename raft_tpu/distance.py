"""pylibraft-parity namespace: ``raft_tpu.distance``.

Mirrors ``pylibraft.distance`` (python/pylibraft/pylibraft/distance —
pairwise_distance, fused_l2_nn_argmin) so reference users find the same
import paths; implementations live in ops.distance / ops.fused_l2_nn."""

from raft_tpu.ops.distance import (  # noqa: F401
    DistanceType,
    is_min_close,
    pairwise_distance,
    resolve_metric,
)
from raft_tpu.ops.fused_l2_nn import (  # noqa: F401
    fused_l2_nn_argmin,
    masked_l2_nn_argmin,
)
from raft_tpu.ops import kernels  # noqa: F401  (raft::distance::kernels)

DISTANCE_TYPES = [t.name for t in DistanceType]

__all__ = ["DistanceType", "DISTANCE_TYPES", "pairwise_distance",
           "fused_l2_nn_argmin", "masked_l2_nn_argmin", "is_min_close",
           "resolve_metric", "kernels"]
