"""Stats layer (SURVEY.md §2.3 'stats'): summary statistics, clustering
quality metrics, model metrics, and neighborhood_recall — the ANN-recall
metric that gates every index test/benchmark."""

from raft_tpu.stats.recall import neighborhood_recall
from raft_tpu.stats.basic import (
    mean,
    stddev,
    var,
    cov,
    histogram,
    minmax,
    accuracy_score,
    r2_score,
    mean_squared_error,
    dispersion,
    trustworthiness_score,
)
from raft_tpu.stats.cluster_metrics import (
    silhouette_score,
    adjusted_rand_index,
    rand_index,
    mutual_info_score,
    entropy,
    homogeneity_score,
    completeness_score,
    v_measure,
)

__all__ = [
    "neighborhood_recall",
    "mean",
    "stddev",
    "var",
    "cov",
    "histogram",
    "minmax",
    "accuracy_score",
    "r2_score",
    "mean_squared_error",
    "dispersion",
    "trustworthiness_score",
    "silhouette_score",
    "adjusted_rand_index",
    "rand_index",
    "mutual_info_score",
    "entropy",
    "homogeneity_score",
    "completeness_score",
    "v_measure",
]
