"""ANN recall metric.

Reference: ``raft::stats::neighborhood_recall`` (stats/neighborhood_recall.cuh
:86-120) — fraction of predicted neighbor indices present in the ground-truth
lists, optionally accepting distance ties within an epsilon.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def neighborhood_recall(
    indices,
    ref_indices,
    distances: Optional[jax.Array] = None,
    ref_distances: Optional[jax.Array] = None,
    eps: float = 0.001,
) -> jax.Array:
    """Mean recall of ``indices`` [n, k] vs ``ref_indices`` [n, k].

    A prediction counts if its index appears in the reference row, or (when
    both distance arrays are given) if its distance matches some reference
    distance within ``eps`` — the tie-acceptance rule of the reference metric.
    """
    indices = jnp.asarray(indices)
    ref_indices = jnp.asarray(ref_indices)
    match = jnp.any(indices[:, :, None] == ref_indices[:, None, :], axis=-1)
    if distances is not None and ref_distances is not None:
        distances = jnp.asarray(distances)
        ref_distances = jnp.asarray(ref_distances)
        tie = jnp.any(
            jnp.abs(distances[:, :, None] - ref_distances[:, None, :]) <= eps,
            axis=-1,
        )
        match = match | tie
    return jnp.mean(match.astype(jnp.float32))
