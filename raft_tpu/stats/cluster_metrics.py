"""Clustering quality metrics (reference: raft::stats — silhouette_score.cuh,
adjusted_rand_index.cuh, rand_index.cuh, mutual_info_score.cuh, entropy.cuh,
homogeneity_score.cuh, completeness_score.cuh, v_measure.cuh).

All are contingency-table computations — pure XLA scatter/reduce territory.
Label arrays are int32 in [0, n_classes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.ops.distance import pairwise_distance


def _contingency(a, b, n_a: int, n_b: int) -> jax.Array:
    a = jnp.asarray(a, jnp.int32)
    b = jnp.asarray(b, jnp.int32)
    flat = a * n_b + b
    counts = jnp.zeros(
        (n_a * n_b,),
        jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32)
    return counts.at[flat].add(1.0).reshape(n_a, n_b)


def rand_index(a, b, n_classes_a: int, n_classes_b: int):
    c = _contingency(a, b, n_classes_a, n_classes_b)
    n = jnp.sum(c)
    sum_all = jnp.sum(c * (c - 1)) / 2
    sum_rows = jnp.sum(jnp.sum(c, 1) * (jnp.sum(c, 1) - 1)) / 2
    sum_cols = jnp.sum(jnp.sum(c, 0) * (jnp.sum(c, 0) - 1)) / 2
    total = n * (n - 1) / 2
    return (total + 2 * sum_all - sum_rows - sum_cols) / jnp.maximum(total, 1.0)


def adjusted_rand_index(a, b, n_classes_a: int, n_classes_b: int):
    c = _contingency(a, b, n_classes_a, n_classes_b)
    n = jnp.sum(c)
    sum_comb = jnp.sum(c * (c - 1)) / 2
    comb_a = jnp.sum(jnp.sum(c, 1) * (jnp.sum(c, 1) - 1)) / 2
    comb_b = jnp.sum(jnp.sum(c, 0) * (jnp.sum(c, 0) - 1)) / 2
    total = n * (n - 1) / 2
    expected = comb_a * comb_b / jnp.maximum(total, 1.0)
    max_idx = 0.5 * (comb_a + comb_b)
    return (sum_comb - expected) / jnp.maximum(max_idx - expected, 1e-38)


def entropy(labels, n_classes: int):
    l = jnp.asarray(labels, jnp.int32)
    counts = jnp.zeros((n_classes,), jnp.float32).at[l].add(1.0)
    p = counts / jnp.maximum(jnp.sum(counts), 1.0)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-38)), 0.0))


def mutual_info_score(a, b, n_classes_a: int, n_classes_b: int):
    c = _contingency(a, b, n_classes_a, n_classes_b)
    n = jnp.maximum(jnp.sum(c), 1.0)
    pij = c / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    ratio = pij / jnp.maximum(pi * pj, 1e-38)
    return jnp.sum(jnp.where(pij > 0, pij * jnp.log(jnp.maximum(ratio, 1e-38)), 0.0))


def homogeneity_score(truth, pred, n_classes_t: int, n_classes_p: int):
    mi = mutual_info_score(truth, pred, n_classes_t, n_classes_p)
    h = entropy(truth, n_classes_t)
    return jnp.where(h > 0, mi / jnp.maximum(h, 1e-38), 1.0)


def completeness_score(truth, pred, n_classes_t: int, n_classes_p: int):
    return homogeneity_score(pred, truth, n_classes_p, n_classes_t)


def v_measure(truth, pred, n_classes_t: int, n_classes_p: int, beta: float = 1.0):
    h = homogeneity_score(truth, pred, n_classes_t, n_classes_p)
    c = completeness_score(truth, pred, n_classes_t, n_classes_p)
    return (1 + beta) * h * c / jnp.maximum(beta * h + c, 1e-38)


def silhouette_score(x, labels, n_classes: int, metric="l2_expanded"):
    """Mean silhouette coefficient (reference: stats/silhouette_score.cuh).

    O(n²) pairwise distances — intended for test-sized inputs, like the
    reference's batched variant is for larger ones.
    """
    x = jnp.asarray(x)
    labels = jnp.asarray(labels, jnp.int32)
    n = x.shape[0]
    d = pairwise_distance(x, x, metric=metric)
    onehot = jax.nn.one_hot(labels, n_classes, dtype=jnp.float32)  # [n, c]
    cluster_sizes = jnp.sum(onehot, axis=0)  # [c]
    # Sum of distances from each point to each cluster: [n, c]
    sums = d @ onehot
    own = labels
    own_size = cluster_sizes[own]
    # a: mean intra-cluster distance excluding self (distance to self is 0).
    a = jnp.where(own_size > 1,
                  jnp.take_along_axis(sums, own[:, None], 1)[:, 0]
                  / jnp.maximum(own_size - 1, 1),
                  0.0)
    # b: min over other clusters of mean distance.
    means = sums / jnp.maximum(cluster_sizes[None, :], 1.0)
    means = jnp.where(jnp.arange(n_classes)[None, :] == own[:, None], jnp.inf, means)
    means = jnp.where(cluster_sizes[None, :] == 0, jnp.inf, means)
    b = jnp.min(means, axis=1)
    s = jnp.where(own_size > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-38), 0.0)
    return jnp.mean(s)


def contingency_matrix(a, b, n_classes_a: int, n_classes_b: int):
    """Public contingency table (stats/contingency_matrix.cuh
    contingencyMatrix): counts[i, j] = |{k : a[k]=i ∧ b[k]=j}|."""
    return _contingency(jnp.asarray(a, jnp.int32), jnp.asarray(b, jnp.int32),
                        n_classes_a, n_classes_b)
