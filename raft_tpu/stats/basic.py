"""Summary statistics + model metrics (reference: raft::stats — mean.cuh,
stddev.cuh, cov.cuh, histogram.cuh, minmax.cuh, accuracy.cuh, r2_score.cuh,
regression_metrics.cuh)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def mean(x, axis=0):
    return jnp.mean(jnp.asarray(x, jnp.float32), axis=axis)


def var(x, axis=0, sample: bool = False):
    return jnp.var(jnp.asarray(x, jnp.float32), axis=axis, ddof=1 if sample else 0)


def stddev(x, axis=0, sample: bool = False):
    return jnp.std(jnp.asarray(x, jnp.float32), axis=axis, ddof=1 if sample else 0)


def cov(x, sample: bool = True):
    """Column covariance matrix of x [n, d] (reference: stats/cov.cuh)."""
    xf = jnp.asarray(x, jnp.float32)
    xc = xf - jnp.mean(xf, axis=0, keepdims=True)
    denom = xf.shape[0] - 1 if sample else xf.shape[0]
    return (xc.T @ xc) / denom


def histogram(x, n_bins: int, lo=None, hi=None) -> Tuple[jax.Array, jax.Array]:
    """Fixed-width histogram (reference: stats/histogram.cuh). Returns
    (counts, edges)."""
    xf = jnp.asarray(x, jnp.float32).reshape(-1)
    lo = jnp.min(xf) if lo is None else lo
    hi = jnp.max(xf) if hi is None else hi
    edges = jnp.linspace(lo, hi, n_bins + 1)
    width = jnp.maximum((hi - lo) / n_bins, 1e-38)
    idx = jnp.clip(((xf - lo) / width).astype(jnp.int32), 0, n_bins - 1)
    counts = jnp.zeros((n_bins,), jnp.int32).at[idx].add(1)
    return counts, edges


def minmax(x, axis=0):
    xf = jnp.asarray(x)
    return jnp.min(xf, axis=axis), jnp.max(xf, axis=axis)


def accuracy_score(predictions, labels):
    p = jnp.asarray(predictions)
    l = jnp.asarray(labels)
    return jnp.mean((p == l).astype(jnp.float32))


def r2_score(y_true, y_pred):
    yt = jnp.asarray(y_true, jnp.float32)
    yp = jnp.asarray(y_pred, jnp.float32)
    ss_res = jnp.sum((yt - yp) ** 2)
    ss_tot = jnp.sum((yt - jnp.mean(yt)) ** 2)
    return 1.0 - ss_res / jnp.maximum(ss_tot, 1e-38)


def mean_squared_error(y_true, y_pred):
    yt = jnp.asarray(y_true, jnp.float32)
    yp = jnp.asarray(y_pred, jnp.float32)
    return jnp.mean((yt - yp) ** 2)


def dispersion(centroids, cluster_sizes, global_centroid=None):
    """Between-cluster dispersion: Σ_c size_c·||centroid_c − μ||²
    (reference: stats/dispersion.cuh — the k-means auto-find-k criterion)."""
    c = jnp.asarray(centroids, jnp.float32)
    s = jnp.asarray(cluster_sizes, jnp.float32)
    if global_centroid is None:
        global_centroid = jnp.sum(c * s[:, None], 0) / jnp.maximum(
            jnp.sum(s), 1e-38)
    d2 = jnp.sum((c - global_centroid[None, :]) ** 2, -1)
    return jnp.sum(d2 * s)


def trustworthiness_score(x, x_embedded, n_neighbors: int = 5,
                          metric="sqeuclidean", res=None):
    """Trustworthiness of a low-dim embedding (reference:
    stats/trustworthiness_score.cuh): 1 − penalty for points that enter a
    point's embedded k-neighborhood while being far in the original space."""
    from raft_tpu.neighbors import brute_force

    x = jnp.asarray(x)
    n = x.shape[0]
    k = int(n_neighbors)
    if k >= n / 2:
        raise ValueError(
            f"n_neighbors={k} must be < n_samples/2 = {n / 2} (the "
            "normalizer changes sign beyond that; sklearn's contract)")
    # ranks in the original space (full argsort — trustworthiness is an
    # offline quality metric; n here is an evaluation subsample)
    from raft_tpu.ops.distance import pairwise_distance as pd

    d_orig = pd(x, x, metric=metric, res=res)
    rank_order = jnp.argsort(d_orig, axis=1)  # [n, n] ids by closeness
    ranks = jnp.zeros((n, n), jnp.int32).at[
        jnp.arange(n)[:, None], rank_order].set(
        jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (n, n)))
    _, emb_nn = brute_force.knn(x_embedded, x_embedded, k=k + 1,
                                metric=metric, res=res)
    emb_nn = jnp.asarray(emb_nn)[:, 1:]  # drop self
    r = jnp.take_along_axis(ranks, emb_nn, axis=1)  # original-space ranks
    penalty = jnp.sum(jnp.maximum(r - k, 0).astype(jnp.float32))
    norm = 2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0))
    return 1.0 - norm * penalty


def sum(x, axis=0):
    """Column/row sums (stats/sum.cuh)."""
    return jnp.sum(jnp.asarray(x), axis=axis)


def mean_center(x, axis=0):
    """Subtract the mean along ``axis`` (stats/mean_center.cuh mean_center);
    returns (centered, means)."""
    x = jnp.asarray(x)
    mu = jnp.mean(x, axis=axis, keepdims=True)
    return x - mu, jnp.squeeze(mu, axis=axis)


def meanvar(x, axis=0, sample: bool = False):
    """Fused mean+variance (stats/meanvar.cuh)."""
    x = jnp.asarray(x)
    mu = jnp.mean(x, axis=axis)
    v = jnp.var(x, axis=axis, ddof=1 if sample else 0)
    return mu, v


def kl_divergence(p, q):
    """Σ p·log(p/q) over all elements (stats/kl_divergence.cuh; terms with
    p == 0 contribute 0, as in the reference's modKL op)."""
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    t = jnp.where(p > 0, p * (jnp.log(jnp.maximum(p, 1e-38))
                              - jnp.log(jnp.maximum(q, 1e-38))), 0.0)
    return jnp.sum(t)


def regression_metrics(y_true, y_pred):
    """(mean_abs_error, mean_squared_error, median_abs_error) —
    stats/regression_metrics.cuh regression_metrics."""
    y_true = jnp.asarray(y_true, jnp.float32)
    y_pred = jnp.asarray(y_pred, jnp.float32)
    err = y_pred - y_true
    return (jnp.mean(jnp.abs(err)), jnp.mean(err * err),
            jnp.median(jnp.abs(err)))


def information_criterion_batched(log_likelihood, n_params: int,
                                  n_samples: int, criterion: str = "aic"):
    """AIC/AICc/BIC from per-series log-likelihoods
    (stats/information_criterion.cuh compute_batched_ics; criterion ∈
    {aic, aicc, bic})."""
    ll = jnp.asarray(log_likelihood, jnp.float32)
    k = float(n_params)
    n = float(n_samples)
    base = -2.0 * ll
    if criterion == "aic":
        return base + 2.0 * k
    if criterion == "aicc":
        return base + 2.0 * k + 2.0 * k * (k + 1.0) / jnp.maximum(
            n - k - 1.0, 1e-6)
    if criterion == "bic":
        return base + k * jnp.log(jnp.maximum(n, 1.0))
    raise ValueError(f"unknown criterion: {criterion}")
