"""Summary statistics + model metrics (reference: raft::stats — mean.cuh,
stddev.cuh, cov.cuh, histogram.cuh, minmax.cuh, accuracy.cuh, r2_score.cuh,
regression_metrics.cuh)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def mean(x, axis=0):
    return jnp.mean(jnp.asarray(x, jnp.float32), axis=axis)


def var(x, axis=0, sample: bool = False):
    return jnp.var(jnp.asarray(x, jnp.float32), axis=axis, ddof=1 if sample else 0)


def stddev(x, axis=0, sample: bool = False):
    return jnp.std(jnp.asarray(x, jnp.float32), axis=axis, ddof=1 if sample else 0)


def cov(x, sample: bool = True):
    """Column covariance matrix of x [n, d] (reference: stats/cov.cuh)."""
    xf = jnp.asarray(x, jnp.float32)
    xc = xf - jnp.mean(xf, axis=0, keepdims=True)
    denom = xf.shape[0] - 1 if sample else xf.shape[0]
    return (xc.T @ xc) / denom


def histogram(x, n_bins: int, lo=None, hi=None) -> Tuple[jax.Array, jax.Array]:
    """Fixed-width histogram (reference: stats/histogram.cuh). Returns
    (counts, edges)."""
    xf = jnp.asarray(x, jnp.float32).reshape(-1)
    lo = jnp.min(xf) if lo is None else lo
    hi = jnp.max(xf) if hi is None else hi
    edges = jnp.linspace(lo, hi, n_bins + 1)
    width = jnp.maximum((hi - lo) / n_bins, 1e-38)
    idx = jnp.clip(((xf - lo) / width).astype(jnp.int32), 0, n_bins - 1)
    counts = jnp.zeros((n_bins,), jnp.int32).at[idx].add(1)
    return counts, edges


def minmax(x, axis=0):
    xf = jnp.asarray(x)
    return jnp.min(xf, axis=axis), jnp.max(xf, axis=axis)


def accuracy_score(predictions, labels):
    p = jnp.asarray(predictions)
    l = jnp.asarray(labels)
    return jnp.mean((p == l).astype(jnp.float32))


def r2_score(y_true, y_pred):
    yt = jnp.asarray(y_true, jnp.float32)
    yp = jnp.asarray(y_pred, jnp.float32)
    ss_res = jnp.sum((yt - yp) ** 2)
    ss_tot = jnp.sum((yt - jnp.mean(yt)) ** 2)
    return 1.0 - ss_res / jnp.maximum(ss_tot, 1e-38)


def mean_squared_error(y_true, y_pred):
    yt = jnp.asarray(y_true, jnp.float32)
    yp = jnp.asarray(y_pred, jnp.float32)
    return jnp.mean((yt - yp) ** 2)
