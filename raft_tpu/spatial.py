"""Legacy ``spatial.knn`` namespace — thin forwarding layer.

Reference: ``raft::spatial::knn`` (spatial/knn/*.cuh) is the deprecated
pre-``neighbors`` API that still forwards to the same implementations
(knn.cuh, ball_cover.cuh, epsilon_neighborhood.cuh, ivf_flat.cuh,
ivf_pq.cuh) and hosts the haversine utilities. Kept here so code written
against the old paths ports unchanged; new code should import
``raft_tpu.neighbors`` / ``raft_tpu.distance`` directly.
"""

from __future__ import annotations

from types import SimpleNamespace

from raft_tpu.neighbors import (ball_cover, brute_force, epsilon_neighborhood,
                                ivf_flat, ivf_pq)
from raft_tpu.neighbors.brute_force import knn as brute_force_knn
from raft_tpu.ops.distance import pairwise_distance


def knn_search(dataset, queries, k: int, metric="euclidean", **kwargs):
    """Legacy entry (spatial/knn/knn.cuh brute_force_knn shape)."""
    return brute_force_knn(queries, dataset, k, metric=metric, **kwargs)


def haversine_distance(x, y):
    """Pairwise haversine over [n, 2] (lat, lon) radians
    (spatial/knn/detail/haversine_distance.cuh)."""
    return pairwise_distance(x, y, metric="haversine")


knn = SimpleNamespace(
    knn=knn_search,
    brute_force=brute_force,
    ball_cover=ball_cover,
    epsilon_neighborhood=epsilon_neighborhood,
    ivf_flat=ivf_flat,
    ivf_pq=ivf_pq,
    haversine_distance=haversine_distance,
)

__all__ = ["knn", "knn_search", "haversine_distance"]
