"""Runtime-checked execution — the sanitizer role under XLA's model.

Reference: the CUDA stack relies on compute-sanitizer / stream-ordered
discipline plus `RAFT_EXPECTS` host checks (core/error.hpp); SURVEY.md §5
maps that, under JAX's functional model, to ``checkify`` (traced-value
assertions inside jit) and NaN/index guards. This module packages those as
an opt-in debug harness: zero cost when unused, no global flags flipped.

    from raft_tpu.utils import debug

    checked_search = debug.checked(ivf_pq.search)   # or checks=...
    (dists, ids) = checked_search(index, q, 10)     # raises on NaN/OOB

    with debug.debug_mode():                        # jax_debug_nans etc.
        cagra.build(db)
"""

from __future__ import annotations

import contextlib
import functools

import jax
from jax.experimental import checkify


#: default check set: float NaN/Inf production + out-of-bounds gathers —
#: the two failure classes the CUDA sanitizers catch for the reference
DEFAULT_CHECKS = checkify.float_checks | checkify.index_checks


def checked(fn, checks=None):
    """Wrap ``fn`` so traced-value errors (NaN/Inf, out-of-bounds indexing,
    explicit ``checkify.check`` calls) raise ``JaxRuntimeError`` eagerly
    instead of producing silent garbage. Works on jitted functions — the
    checks compile into the program."""
    checks = DEFAULT_CHECKS if checks is None else checks
    cfn = checkify.checkify(fn, errors=checks)

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        err, out = cfn(*args, **kwargs)
        checkify.check_error(err)
        return out

    return wrapper


@contextlib.contextmanager
def debug_mode(nans: bool = True, infs: bool = False):
    """Scoped `jax_debug_nans`/`jax_debug_infs`: every primitive result is
    re-checked on host and the offending op re-run un-jitted for a precise
    traceback. Heavyweight — wrap only the region under investigation."""
    prev_nans = jax.config.jax_debug_nans
    prev_infs = jax.config.jax_debug_infs
    try:
        jax.config.update("jax_debug_nans", nans)
        jax.config.update("jax_debug_infs", infs)
        yield
    finally:
        jax.config.update("jax_debug_nans", prev_nans)
        jax.config.update("jax_debug_infs", prev_infs)
