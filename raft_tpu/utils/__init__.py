"""Utility layer (SURVEY.md §2.2): padding/tiling arithmetic and small
helpers. Most of the reference's device utilities (warp primitives, vectorized
loads, atomics) disappear into XLA; what remains is shape/layout math."""

from raft_tpu.utils.shape import round_up_to, pad_rows, cdiv
from raft_tpu.utils.compile_cache import enable_persistent_cache

__all__ = ["round_up_to", "pad_rows", "cdiv", "enable_persistent_cache"]
