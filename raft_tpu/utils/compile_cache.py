"""Persistent compilation cache.

Reference analog: the ``-ext``/``-inl`` explicit-instantiation split +
``libraft`` precompiled library (SURVEY.md §1, util/raft_explicit.hpp) —
RAFT pre-builds its expensive templates once so users don't pay nvcc time
per TU. The XLA analog is the persistent compilation cache: traced programs
compile once per (shape, dtype, flags) and later processes load the cached
executable instead of re-running XLA.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def enable_persistent_cache(cache_dir: Optional[str] = None,
                            min_compile_time_secs: float = 1.0) -> str:
    """Turn on XLA's on-disk compilation cache (idempotent). Returns the
    cache directory. Call once at program start; all subsequent jit
    compilations (ivf/cagra search kernels, pairwise engines, …) persist
    across processes — the runtime analog of shipping ``libraft``."""
    cache_dir = cache_dir or os.environ.get(
        "RAFT_TPU_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "raft_tpu_xla"))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_secs))
    return cache_dir
