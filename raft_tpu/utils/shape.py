"""Shape/tile arithmetic (TPU analog of util/pow2_utils.cuh): lane-aligned
padding helpers used by the IVF list layouts and Pallas kernels."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

LANES = 128  # TPU lane count: last-dim tiling unit
SUBLANES_F32 = 8


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def round_up_to(n: int, multiple: int) -> int:
    return cdiv(n, multiple) * multiple


def balanced_tile(total: int, tile: int, multiple: int) -> int:
    """Balance a 1-d tile grid: split ``total`` evenly over the tile count
    a budget-derived ``tile`` implies, aligned up to ``multiple`` when that
    stays within the budget.

    Rounding a budget tile DOWN to the alignment multiple (the old
    pattern) turned total=10000 / tile=10000 into 9984 -> TWO tiles, the
    second 99.8% padding — double the scan work on the headline shape.
    Invariants: result <= max(tile, 1) (a [tile, ...] workspace budget is
    never exceeded — alignment yields to budget when tile < multiple),
    result * cdiv(total, result) - total < multiple * n_tiles (bounded
    padding), and total == 0 degrades to 1 (callers produce empty
    outputs, not a ZeroDivisionError)."""
    tile = max(tile, 1)
    if total <= tile:
        return max(total, 1)
    n_tiles = cdiv(total, tile)
    balanced = cdiv(total, n_tiles)
    aligned = round_up_to(balanced, multiple)
    return aligned if aligned <= tile else balanced


def pad_rows(x, target_rows: int, fill=0):
    """Pad a [n, ...] array to [target_rows, ...]. Host arrays pad on the
    host (numpy) so serving wrappers don't pay an eager device dispatch
    per call — the padded batch then rides the jit call's single
    transfer; device arrays pad on device as before."""
    n = x.shape[0]
    if n == target_rows:
        return x
    pad_widths = [(0, target_rows - n), *[(0, 0)] * (x.ndim - 1)]
    if isinstance(x, np.ndarray):
        return np.pad(x, pad_widths, constant_values=fill)
    return jnp.pad(x, pad_widths, constant_values=fill)


def as_query_array(queries, dtype=None):
    """Wrapper-side query normalization that KEEPS host inputs on the
    host: lists/numpy become a numpy array (validated/shaped for free),
    device arrays pass through; ``dtype`` casts on whichever side the
    data lives. The device transfer then happens once, inside the
    search's jit call, instead of as an eager ``jnp.asarray`` dispatch
    (+ a second eager pad) per serving call — on a tunnel-attached TPU
    each eager op is a separate runtime enqueue."""
    if isinstance(queries, jax.Array):
        return queries if dtype is None else queries.astype(dtype)
    queries = np.asarray(queries)
    if dtype is not None:
        queries = queries.astype(dtype, copy=False)
    return queries


def query_bucket(nq: int, max_bucket: int = 256) -> int:
    """Serving-latency batch bucket: round small query batches up to the
    next power of two (min 8) so repeated small-batch searches of varying
    size reuse ONE compiled program instead of recompiling per shape (the
    role of the reference's MULTI_CTA/MULTI_KERNEL small-batch modes,
    cagra_types.hpp:66-116 — on TPU the recompile, not the kernel shape,
    is what kills small-batch latency). Batches above ``max_bucket`` keep
    their exact size: throughput runs have stable shapes, and rounding
    10k → 16k would waste real compute."""
    if nq > max_bucket:
        return nq
    b = 8
    while b < nq:
        b *= 2
    return b
