"""Shape/tile arithmetic (TPU analog of util/pow2_utils.cuh): lane-aligned
padding helpers used by the IVF list layouts and Pallas kernels."""

from __future__ import annotations

import jax.numpy as jnp

LANES = 128  # TPU lane count: last-dim tiling unit
SUBLANES_F32 = 8


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def round_up_to(n: int, multiple: int) -> int:
    return cdiv(n, multiple) * multiple


def pad_rows(x, target_rows: int, fill=0):
    """Pad a [n, ...] array to [target_rows, ...]."""
    n = x.shape[0]
    if n == target_rows:
        return x
    pad_widths = [(0, target_rows - n)] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad_widths, constant_values=fill)


def query_bucket(nq: int, max_bucket: int = 256) -> int:
    """Serving-latency batch bucket: round small query batches up to the
    next power of two (min 8) so repeated small-batch searches of varying
    size reuse ONE compiled program instead of recompiling per shape (the
    role of the reference's MULTI_CTA/MULTI_KERNEL small-batch modes,
    cagra_types.hpp:66-116 — on TPU the recompile, not the kernel shape,
    is what kills small-batch latency). Batches above ``max_bucket`` keep
    their exact size: throughput runs have stable shapes, and rounding
    10k → 16k would waste real compute."""
    if nq > max_bucket:
        return nq
    b = 8
    while b < nq:
        b *= 2
    return b
