"""Native C++ runtime components (host side, off the XLA compute path).

Reference analogs: the mmap'd fbin dataset reader
(cpp/bench/ann/src/common/dataset.hpp), the CAGRA→hnswlib serializer
(neighbors/detail/hnsw_types.hpp:60-86), the agglomerative labeling kernel
(cluster/detail/agglomerative.cuh), and the IVF list fill
(detail/ivf_flat_build.cuh:123-160). The TPU compute path stays JAX/XLA;
these are the IO/packing/sequential-host pieces the reference also keeps
native.

Built with g++ into ``libraft_tpu_native.so`` on first use (``ensure_built``)
and bound via ctypes — no pybind11 dependency. Every entry point has a
pure-numpy fallback so the package works without a toolchain."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "raft_tpu_native.cpp")
_SO = os.path.join(_HERE, "libraft_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False
_has_prefetch = False
_has_graph_search = False


def ensure_built(force: bool = False) -> bool:
    """Compile the shared library if missing or older than its source;
    returns availability."""
    global _build_failed
    # rebuild only when the source exists and is newer; a shipped .so
    # without src/ is still valid
    if (os.path.exists(_SO) and not force
            and (not os.path.exists(_SRC)
                 or os.path.getmtime(_SO) >= os.path.getmtime(_SRC))):
        return True
    if _build_failed and not force:
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
             _SRC, "-o", _SO],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        _build_failed = True
        return os.path.exists(_SO)


def _get_lib():
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not ensure_built():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # stale/foreign-arch artifact: the numpy fallbacks take over
            _build_failed = True
            return None
        lib.bin_read_header.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64)]
        lib.bin_read_header.restype = ctypes.c_int
        lib.bin_read_rows.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p]
        lib.bin_read_rows.restype = ctypes.c_int
        lib.bin_write.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64]
        lib.bin_write.restype = ctypes.c_int
        lib.hnswlib_write.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64]
        lib.hnswlib_write.restype = ctypes.c_int
        lib.agglomerative_label.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p]
        lib.agglomerative_label.restype = ctypes.c_int
        lib.pack_lists.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.pack_lists.restype = ctypes.c_int
        global _has_prefetch
        try:
            # newer symbols: a stale .so built before they existed must not
            # take down the whole native layer — degrade to the sync reader
            lib.prefetch_open_v2.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64]
            lib.prefetch_open_v2.restype = ctypes.c_void_p
            lib.prefetch_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.prefetch_next.restype = ctypes.c_int64
            lib.prefetch_close.argtypes = [ctypes.c_void_p]
            lib.prefetch_close.restype = None
            _has_prefetch = True
        except AttributeError:
            _has_prefetch = False
        global _has_graph_search
        try:
            lib.graph_greedy_search.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
            lib.graph_greedy_search.restype = ctypes.c_int
            _has_graph_search = True
        except AttributeError:
            _has_graph_search = False
        _lib = lib
        return _lib


def available() -> bool:
    return _get_lib() is not None


# ------------------------------------------------------------------- bin IO


_DTYPES = {"fbin": np.float32, "ibin": np.int32, "u8bin": np.uint8}


def _dtype_for(path: str, dtype=None):
    if dtype is not None:
        return np.dtype(dtype)
    ext = path.rsplit(".", 1)[-1]
    if ext in _DTYPES:
        return np.dtype(_DTYPES[ext])
    return np.dtype(np.float32)


def read_bin_header(path: str) -> Tuple[int, int]:
    """(n_rows, dim) of an fbin/ibin/u8bin file."""
    lib = _get_lib()
    if lib is not None:
        n = ctypes.c_int64()
        d = ctypes.c_int64()
        rc = lib.bin_read_header(path.encode(), ctypes.byref(n),
                                 ctypes.byref(d))
        if rc != 0:
            raise IOError(f"bin_read_header({path}) failed rc={rc}")
        return n.value, d.value
    with open(path, "rb") as f:
        hdr = np.fromfile(f, np.int32, 2)
    return int(hdr[0]), int(hdr[1])


def read_bin(path: str, row_start: int = 0, n_rows: Optional[int] = None,
             dtype=None) -> np.ndarray:
    """Read a row range of an ANN-benchmark bin file (header int32 n, dim).
    The C path uses pread (thread-safe, no Python buffering); out-of-core
    pipelines stream batches through this (SURVEY.md §5 scale axis)."""
    total, dim = read_bin_header(path)
    dt = _dtype_for(path, dtype)
    if n_rows is None:
        n_rows = total - row_start
    n_rows = max(min(n_rows, total - row_start), 0)
    out = np.empty((n_rows, dim), dt)
    lib = _get_lib()
    if lib is not None and n_rows:
        rc = lib.bin_read_rows(path.encode(), row_start, n_rows, dt.itemsize,
                               out.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise IOError(f"bin_read_rows({path}) failed rc={rc}")
        return out
    with open(path, "rb") as f:
        f.seek(8 + row_start * dim * dt.itemsize)
        out = np.fromfile(f, dt, n_rows * dim).reshape(n_rows, dim)
    return out


def write_bin(path: str, data: np.ndarray) -> None:
    data = np.ascontiguousarray(data)
    lib = _get_lib()
    if lib is not None:
        rc = lib.bin_write(path.encode(),
                           data.ctypes.data_as(ctypes.c_void_p),
                           data.shape[0], data.shape[1], data.itemsize)
        if rc != 0:
            raise IOError(f"bin_write({path}) failed rc={rc}")
        return
    with open(path, "wb") as f:
        np.asarray(data.shape, np.int32).tofile(f)
        data.tofile(f)


def iter_bin_batches(path: str, batch_rows: int, dtype=None):
    """Stream a bin file in row batches (host→HBM staging loop)."""
    total, _ = read_bin_header(path)
    for s in range(0, total, batch_rows):
        yield s, read_bin(path, s, min(batch_rows, total - s), dtype)


def iter_bin_batches_prefetch(path: str, batch_rows: int, dtype=None,
                              row_range=None):
    """Like :func:`iter_bin_batches` but IO-overlapped: a native reader
    thread preads batch i+1 while the consumer processes batch i (the
    reference bench harness's mmap+thread-pool staging role). Falls back to
    the synchronous iterator when the native library is unavailable.
    ``row_range=(lo, hi)`` streams only that row span (shard builds);
    yielded offsets are file-absolute."""
    lib = _get_lib()
    dt = _dtype_for(path, dtype)
    total, dim = read_bin_header(path)
    lo, hi = (0, total) if row_range is None else row_range
    lo = int(lo)
    hi = int(max(lo, min(hi, total)))  # empty range behaves like the sync path
    if lib is None or not _has_prefetch:
        for s in range(lo, hi, batch_rows):
            yield s, read_bin(path, s, min(batch_rows, hi - s), dt)
        return
    handle = lib.prefetch_open_v2(path.encode(), batch_rows, dt.itemsize,
                                  lo, hi - lo)
    if not handle:
        for s in range(lo, hi, batch_rows):
            yield s, read_bin(path, s, min(batch_rows, hi - s), dt)
        return
    try:
        start = lo
        while True:
            buf = np.empty((batch_rows, dim), dt)
            rows = lib.prefetch_next(
                handle, buf.ctypes.data_as(ctypes.c_void_p))
            if rows == 0:
                break
            if rows < 0:
                raise IOError(f"prefetch_next({path}) failed rc={rows}")
            yield start, buf[:rows]
            start += rows
    finally:
        lib.prefetch_close(handle)


# -------------------------------------------------------------- hnsw export


def hnswlib_write(path: str, dataset: np.ndarray, graph: np.ndarray,
                  space: str = "l2", compat: str = "hnswlib") -> None:
    """Write a base-layer-only hnswlib index file: header in saveIndex
    order, per-element level-0 block [link_count u32][maxM0 u32 links]
    [dim f32][label u64], zero upper-level link lists.

    ``compat="hnswlib"`` (default) emits max_level=0/enterpoint=0 — safe
    for stock hnswlib's loadIndex **and** search (no upper-layer descent).
    ``compat="raft"`` reproduces the reference serializer byte-for-byte
    (cagra_serialize.cuh:113-154; the base_layer_only loader contract of
    hnsw_types.hpp:60-86) — stock hnswlib would crash *searching* that
    variant, exactly as it does on the reference's own output."""
    dataset = np.ascontiguousarray(dataset, np.float32)
    graph = np.ascontiguousarray(graph, np.int32)
    n, dim = dataset.shape
    if graph.shape[0] != n:
        raise ValueError("graph rows must match dataset rows")
    degree = graph.shape[1]
    sp = {"l2": 0, "ip": 1}[space]
    rc_compat = {"hnswlib": 0, "raft": 1}[compat]
    lib = _get_lib()
    if lib is not None:
        rc = lib.hnswlib_write(path.encode(),
                               dataset.ctypes.data_as(ctypes.c_void_p),
                               graph.ctypes.data_as(ctypes.c_void_p),
                               n, dim, degree, sp, rc_compat)
        if rc != 0:
            raise IOError(f"hnswlib_write({path}) failed rc={rc}")
        return
    _hnswlib_write_py(path, dataset, graph, compat)


def _hnswlib_write_py(path: str, dataset: np.ndarray, graph: np.ndarray,
                      compat: str = "hnswlib") -> None:
    import struct

    n, dim = dataset.shape
    degree = graph.shape[1]
    size_links0 = degree * 4 + 4
    data_size = dim * 4
    size_per_elem = size_links0 + data_size + 8
    m = max(degree // 2, 1)
    # header constants must stay identical to the C++ writer (see
    # hnswlib_write for the compat semantics) —
    # test_hnswlib_python_fallback_writer gates this
    raft = compat == "raft"
    with open(path, "wb") as f:
        f.write(struct.pack(
            "<QQQQQQiiQQQdQ",
            0, n, n, size_per_elem,
            size_links0 + data_size, size_links0,
            1 if raft else 0, n // 2 if raft else 0, m, degree, m,
            0.42424242 if raft else 1.0 / np.log(max(m, 2)),
            500 if raft else 200))
        for i in range(n):
            links = graph[i][graph[i] >= 0].astype(np.uint32)
            buf = bytearray(size_per_elem)
            buf[0:4] = struct.pack("<I", len(links))
            buf[4 : 4 + 4 * len(links)] = links.tobytes()
            buf[size_links0 : size_links0 + data_size] = (
                dataset[i].astype(np.float32).tobytes())
            buf[size_links0 + data_size :] = struct.pack("<Q", i)
            f.write(bytes(buf))
        f.write(b"\x00\x00\x00\x00" * n)


def graph_greedy_search(dataset: np.ndarray, graph: np.ndarray,
                        queries: np.ndarray, k: int, ef: int = 128,
                        entry: int = 0, n_threads: int = 0):
    """CPU ef-search over a fixed-degree graph — hnswlib's layer-0
    searchBaseLayerST algorithm, searching exactly the indexes
    :func:`hnswlib_write` emits (entry point 0). The external-competitor
    row of the bench harness (hnswlib wrapper role, bench/ann/src/
    hnswlib/hnswlib_wrapper.h); no hnswlib wheel exists on this image.

    Returns (distances [nq, k] squared-L2, ids [nq, k]); -1/inf pads when
    a query's reachable component is smaller than k.
    """
    dataset = np.ascontiguousarray(dataset, np.float32)
    graph = np.ascontiguousarray(graph, np.int32)
    queries = np.ascontiguousarray(queries, np.float32)
    n, dim = dataset.shape
    nq = queries.shape[0]
    ef = max(int(ef), int(k))
    lib = _get_lib()
    if lib is None or not _has_graph_search:
        return _graph_greedy_search_py(dataset, graph, queries, k, ef,
                                       entry)
    out_i = np.empty((nq, k), np.int32)
    out_d = np.empty((nq, k), np.float32)
    rc = lib.graph_greedy_search(
        dataset.ctypes.data_as(ctypes.c_void_p), n, dim,
        graph.ctypes.data_as(ctypes.c_void_p), graph.shape[1],
        queries.ctypes.data_as(ctypes.c_void_p), nq,
        int(k), ef, int(entry),
        out_i.ctypes.data_as(ctypes.c_void_p),
        out_d.ctypes.data_as(ctypes.c_void_p), int(n_threads))
    if rc != 0:
        raise ValueError(f"graph_greedy_search failed rc={rc}")
    return out_d, out_i


def _graph_greedy_search_py(dataset, graph, queries, k, ef, entry):
    """Reference-rate numpy fallback (same algorithm, one query at a
    time) — correctness seam for CI boxes without the .so."""
    import heapq

    n, dim = dataset.shape
    nq = queries.shape[0]
    out_i = np.full((nq, k), -1, np.int32)
    out_d = np.full((nq, k), np.inf, np.float32)
    for qi in range(nq):
        q = queries[qi]
        d0 = float(((q - dataset[entry]) ** 2).sum())
        visited = {entry}
        cand = [(d0, entry)]  # min-heap frontier
        res = [(-d0, entry)]  # max-heap of top-ef (negated)
        while cand:
            d, c = heapq.heappop(cand)
            if d > -res[0][0] and len(res) >= ef:
                break
            nbrs = graph[c]
            nbrs = nbrs[nbrs >= 0]
            # dedupe while filtering: a row may repeat an id, and a
            # double-push would put the node in the result heap twice
            new = []
            for b in nbrs:
                b = int(b)
                if b not in visited:
                    visited.add(b)
                    new.append(b)
            if not new:
                continue
            dists = ((queries[qi][None] - dataset[new]) ** 2).sum(1)
            for b, db in zip(new, dists):
                db = float(db)
                if len(res) < ef or db < -res[0][0]:
                    heapq.heappush(cand, (db, int(b)))
                    heapq.heappush(res, (-db, int(b)))
                    if len(res) > ef:
                        heapq.heappop(res)
        top = sorted((-d, i) for d, i in res)[:k]
        for j, (d, i) in enumerate(top):
            out_d[qi, j], out_i[qi, j] = d, i
    return out_d, out_i


# --------------------------------------------------- agglomerative labeling


def agglomerative_label(src: np.ndarray, dst: np.ndarray, n: int,
                        n_clusters: int) -> np.ndarray:
    """Union-find dendrogram labeling over weight-sorted MST edges
    (cluster/detail/agglomerative.cuh analog). Returns labels [n]."""
    src = np.ascontiguousarray(src, np.int32)
    dst = np.ascontiguousarray(dst, np.int32)
    lib = _get_lib()
    if lib is not None:
        labels = np.empty((n,), np.int32)
        lib.agglomerative_label(
            src.ctypes.data_as(ctypes.c_void_p),
            dst.ctypes.data_as(ctypes.c_void_p),
            len(src), n, n_clusters,
            labels.ctypes.data_as(ctypes.c_void_p))
        return labels
    # numpy fallback
    parent = np.arange(n, dtype=np.int64)

    def find(a):
        root = a
        while parent[root] != root:
            root = parent[root]
        while parent[a] != root:
            parent[a], a = root, parent[a]
        return root

    target = n - n_clusters
    merges = 0
    for e in range(len(src)):
        if merges >= target:
            break
        if src[e] < 0 or dst[e] < 0:
            continue
        ra, rb = find(int(src[e])), find(int(dst[e]))
        if ra != rb:
            parent[max(ra, rb)] = min(ra, rb)
            merges += 1
    roots = np.array([find(i) for i in range(n)])
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.int32)


# ------------------------------------------------------------- list packing


def pack_lists(rows: np.ndarray, labels: np.ndarray, n_lists: int,
               list_pad: int, ids: Optional[np.ndarray] = None
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack rows into padded per-list storage (host half of the IVF list
    fill, detail/ivf_flat_build.cuh:123-160). Returns (data [L, pad, ...],
    ids [L, pad] int32, sizes [L] int32)."""
    rows = np.ascontiguousarray(rows)
    labels = np.ascontiguousarray(labels, np.int32)
    n = len(rows)
    row_bytes = rows.dtype.itemsize * int(np.prod(rows.shape[1:]))
    out = np.zeros((n_lists, list_pad) + rows.shape[1:], rows.dtype)
    out_ids = np.empty((n_lists, list_pad), np.int32)
    sizes = np.zeros((n_lists,), np.int32)
    lib = _get_lib()
    if lib is not None:
        ids_c = (np.ascontiguousarray(ids, np.int32)
                 if ids is not None else None)
        rc = lib.pack_lists(
            rows.ctypes.data_as(ctypes.c_void_p),
            labels.ctypes.data_as(ctypes.c_void_p),
            ids_c.ctypes.data_as(ctypes.c_void_p) if ids_c is not None
            else None,
            n, row_bytes, n_lists, list_pad,
            out.ctypes.data_as(ctypes.c_void_p),
            out_ids.ctypes.data_as(ctypes.c_void_p),
            sizes.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise ValueError(f"pack_lists failed rc={rc} (bad label or "
                             f"list_pad too small)")
        return out, out_ids, sizes
    # numpy fallback
    out_ids.fill(-1)
    src_ids = ids if ids is not None else np.arange(n, dtype=np.int32)
    order = np.argsort(labels, kind="stable")
    sizes = np.bincount(labels, minlength=n_lists).astype(np.int32)
    if sizes.max(initial=0) > list_pad:
        raise ValueError("list_pad too small")
    starts = np.zeros(n_lists + 1, np.int64)
    np.cumsum(sizes, out=starts[1:])
    rs = rows[order]
    si = np.asarray(src_ids)[order]
    for l in range(n_lists):
        s, e = starts[l], starts[l + 1]
        out[l, : e - s] = rs[s:e]
        out_ids[l, : e - s] = si[s:e]
    return out, out_ids, sizes
