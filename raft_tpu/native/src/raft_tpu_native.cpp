// raft_tpu native runtime — host-side C++ for the pieces the reference
// implements natively and that sit off the XLA compute path:
//
//  * bin dataset IO (fbin/ibin/u8bin) with mmap'd zero-copy batch reads —
//    the role of the reference's mmap'd fbin reader
//    (cpp/bench/ann/src/common/dataset.hpp) for out-of-core datasets.
//  * hnswlib-format serializer: writes a base-layer-only hnswlib index
//    from a CAGRA graph + dataset, interoperable with hnswlib's
//    loadIndex (the reference's CAGRA→HNSW export,
//    neighbors/detail/hnsw_types.hpp:60-86).
//  * agglomerative union-find labeling over sorted MST edges — the
//    sequential dendrogram step of single-linkage
//    (cluster/detail/agglomerative.cuh analog).
//  * IVF list packing: group rows by cluster label into padded lists —
//    the host half of build_index_kernel (detail/ivf_flat_build.cuh:123).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>
#include <algorithm>
#include <limits>
#include <numeric>
#include <thread>
#include <utility>
#include <mutex>
#include <condition_variable>

namespace {

// Double-buffered background batch reader: a worker thread preads batch
// i+1 while the consumer processes batch i — the role of the reference
// bench harness's mmap'd dataset + thread pool (bench/ann/src/common/
// dataset.hpp, thread_pool.hpp) for streaming larger-than-memory builds.
struct Prefetcher {
  int fd = -1;
  int64_t n_rows = 0, dim = 0, elem = 0, batch_rows = 0, n_batches = 0;
  int64_t row0 = 0;
  std::thread worker;
  std::mutex m;
  std::condition_variable cv;
  std::vector<std::vector<char>> bufs;
  std::vector<int64_t> buf_rows;  // rows in slot, -1 = empty
  int64_t consumed = 0;
  bool stop = false;
  int err = 0;
};

// pread until `want` bytes land (short reads are routine: 2 GiB syscall
// cap, EINTR, network filesystems). Returns false on EOF/error.
bool pread_fully(int fd, char* out, int64_t want, int64_t off) {
  int64_t done = 0;
  while (done < want) {
    ssize_t got = pread(fd, out + done, want - done, off + done);
    if (got <= 0) return false;
    done += got;
  }
  return true;
}

}  // namespace

extern "C" {

// ------------------------------------------------------------------ bin IO

// Header: int32 n_rows, int32 dim. Returns 0 on success.
int bin_read_header(const char* path, int64_t* n_rows, int64_t* dim) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int32_t hdr[2];
  if (std::fread(hdr, sizeof(int32_t), 2, f) != 2) {
    std::fclose(f);
    return -2;
  }
  *n_rows = hdr[0];
  *dim = hdr[1];
  std::fclose(f);
  return 0;
}

// Read rows [row_start, row_start+n_rows) into out (caller-allocated,
// n_rows*dim*elem_size bytes). Uses pread — no seek state, thread-safe.
int bin_read_rows(const char* path, int64_t row_start, int64_t n_rows,
                  int64_t elem_size, void* out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  int32_t hdr[2];
  if (pread(fd, hdr, sizeof(hdr), 0) != (ssize_t)sizeof(hdr)) {
    close(fd);
    return -2;
  }
  const int64_t dim = hdr[1];
  const int64_t row_bytes = dim * elem_size;
  const int64_t off = 8 + row_start * row_bytes;
  const int64_t want = n_rows * row_bytes;
  if (!pread_fully(fd, (char*)out, want, off)) {
    close(fd);
    return -3;
  }
  close(fd);
  return 0;
}

int bin_write(const char* path, const void* data, int64_t n_rows,
              int64_t dim, int64_t elem_size) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  int32_t hdr[2] = {(int32_t)n_rows, (int32_t)dim};
  if (std::fwrite(hdr, sizeof(int32_t), 2, f) != 2) {
    std::fclose(f);
    return -2;
  }
  const size_t want = (size_t)(n_rows * dim * elem_size);
  if (std::fwrite(data, 1, want, f) != want) {
    std::fclose(f);
    return -3;
  }
  std::fclose(f);
  return 0;
}

// --------------------------------------------------------- hnswlib writer

// Writes a base-layer-only hnswlib index: header fields in hnswlib
// saveIndex order, one level-0 block per element
// [uint32 n_links][maxM0 x uint32][dim x float][size_t label], then a zero
// linkListSize per element (no upper layers). space: 0 = l2, 1 = ip.
// raft_compat selects the header constants:
//   0 ("hnswlib"): max_level=0, enterpoint=0 — stock hnswlib's searchKnn
//     never descends through (absent) upper layers, so the file is safe
//     for an unpatched HierarchicalNSW::loadIndex + search.
//   1 ("raft"): byte-identical to the reference serializer
//     (cagra_serialize.cuh:113-154 — max_level=1, enterpoint=n/2,
//     mult=0.42424242, efConstruction=500), the layout its
//     base_layer_only fork loader consumes (hnsw_types.hpp:60-86). Stock
//     hnswlib would crash searching this variant (null upper link list at
//     the enterpoint) — it exists for byte-compat proofs.
int hnswlib_write(const char* path, const float* data, const int32_t* graph,
                  int64_t n, int64_t dim, int64_t degree, int64_t /*space*/,
                  int64_t raft_compat) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;

  const uint64_t offset_level0 = 0;
  const uint64_t max_elements = (uint64_t)n;
  const uint64_t cur_count = (uint64_t)n;
  const uint64_t size_links0 = (uint64_t)degree * 4 + 4;
  const uint64_t data_size = (uint64_t)dim * 4;
  const uint64_t size_per_elem = size_links0 + data_size + 8;
  const uint64_t label_offset = size_links0 + data_size;
  const uint64_t offset_data = size_links0;
  const int32_t max_level = raft_compat ? 1 : 0;
  const int32_t enterpoint = raft_compat ? (int32_t)(n / 2) : 0;
  const uint64_t maxM = (uint64_t)degree / 2 ? (uint64_t)degree / 2 : 1;
  const uint64_t maxM0 = (uint64_t)degree;
  const uint64_t M = maxM;
  const double mult =
      raft_compat ? 0.42424242 : 1.0 / std::log((double)(M > 1 ? M : 2));
  const uint64_t ef_construction = raft_compat ? 500 : 200;

#define W(x) if (std::fwrite(&(x), sizeof(x), 1, f) != 1) { std::fclose(f); return -2; }
  W(offset_level0);
  W(max_elements);
  W(cur_count);
  W(size_per_elem);
  W(label_offset);
  W(offset_data);
  W(max_level);
  W(enterpoint);
  W(maxM);
  W(maxM0);
  W(M);
  W(mult);
  W(ef_construction);
#undef W

  std::vector<char> elem(size_per_elem);
  for (int64_t i = 0; i < n; ++i) {
    // count valid links (graph entries >= 0)
    uint32_t cnt = 0;
    for (int64_t j = 0; j < degree; ++j)
      if (graph[i * degree + j] >= 0) ++cnt;
    std::memset(elem.data(), 0, elem.size());
    std::memcpy(elem.data(), &cnt, 4);
    uint32_t* links = (uint32_t*)(elem.data() + 4);
    uint32_t w = 0;
    for (int64_t j = 0; j < degree; ++j) {
      int32_t t = graph[i * degree + j];
      if (t >= 0) links[w++] = (uint32_t)t;
    }
    std::memcpy(elem.data() + offset_data, data + i * dim, data_size);
    uint64_t label = (uint64_t)i;
    std::memcpy(elem.data() + label_offset, &label, 8);
    if (std::fwrite(elem.data(), 1, elem.size(), f) != elem.size()) {
      std::fclose(f);
      return -3;
    }
  }
  const uint32_t zero = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (std::fwrite(&zero, 4, 1, f) != 1) {
      std::fclose(f);
      return -4;
    }
  }
  std::fclose(f);
  return 0;
}

// ------------------------------------------- union-find dendrogram labels

static int64_t uf_find(int64_t* parent, int64_t a) {
  int64_t root = a;
  while (parent[root] != root) root = parent[root];
  while (parent[a] != root) {
    int64_t next = parent[a];
    parent[a] = root;
    a = next;
  }
  return root;
}

// Merge MST edges (already sorted by weight ascending; -1 src = padding)
// until n_clusters components remain. labels out: [n] compacted 0..k-1.
int agglomerative_label(const int32_t* src, const int32_t* dst,
                        int64_t n_edges, int64_t n, int64_t n_clusters,
                        int32_t* labels) {
  std::vector<int64_t> parent(n);
  std::iota(parent.begin(), parent.end(), 0);
  int64_t target = n - n_clusters;
  int64_t merges = 0;
  for (int64_t e = 0; e < n_edges && merges < target; ++e) {
    if (src[e] < 0 || dst[e] < 0) continue;
    int64_t ra = uf_find(parent.data(), src[e]);
    int64_t rb = uf_find(parent.data(), dst[e]);
    if (ra == rb) continue;
    parent[std::max(ra, rb)] = std::min(ra, rb);
    ++merges;
  }
  // compact root ids to 0..k-1
  std::vector<int32_t> remap(n, -1);
  int32_t next_label = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t r = uf_find(parent.data(), i);
    if (remap[r] < 0) remap[r] = next_label++;
    labels[i] = remap[r];
  }
  return next_label;
}

// ----------------------------------------------------- IVF list packing

// Group rows by label into padded [n_lists, list_pad, row_bytes] storage +
// ids [n_lists, list_pad] (-1 pad) + sizes [n_lists]. Returns 0.
int pack_lists(const char* rows, const int32_t* labels, const int32_t* ids,
               int64_t n_rows, int64_t row_bytes, int64_t n_lists,
               int64_t list_pad, char* out_data, int32_t* out_ids,
               int32_t* out_sizes) {
  std::vector<int64_t> cursor(n_lists, 0);
  std::memset(out_sizes, 0, n_lists * sizeof(int32_t));
  for (int64_t i = 0; i < n_rows; ++i) {
    const int32_t l = labels[i];
    if (l < 0 || l >= n_lists) return -1;
    const int64_t pos = cursor[l]++;
    if (pos >= list_pad) return -2;
    std::memcpy(out_data + (l * list_pad + pos) * row_bytes,
                rows + i * row_bytes, row_bytes);
    out_ids[l * list_pad + pos] = ids ? ids[i] : (int32_t)i;
    out_sizes[l] = (int32_t)cursor[l];
  }
  // -1-fill unused id slots
  for (int64_t l = 0; l < n_lists; ++l)
    for (int64_t p = cursor[l]; p < list_pad; ++p)
      out_ids[l * list_pad + p] = -1;
  return 0;
}

// ------------------------------------------------------- batch prefetcher

// row_start/row_limit bound the streamed range (row_limit<0 = to EOF).
// _v2 suffix: the signature was widened from the first release; a distinct
// symbol keeps a stale old-ABI .so from silently ignoring the range args.
void* prefetch_open_v2(const char* path, int64_t batch_rows,
                       int64_t elem_size, int64_t row_start,
                       int64_t row_limit) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  int32_t hdr[2];
  if (pread(fd, hdr, sizeof(hdr), 0) != (ssize_t)sizeof(hdr)) {
    close(fd);
    return nullptr;
  }
  // validate the header before sizing buffers: a corrupt file must fail
  // with a catchable Python error, not a C++ exception crossing the C ABI
  if (hdr[0] < 0 || hdr[1] <= 0 || batch_rows <= 0 || elem_size <= 0 ||
      (int64_t)hdr[1] * elem_size > (int64_t)1 << 40) {
    close(fd);
    return nullptr;
  }
  int64_t total = hdr[0];
  if (row_start < 0 || row_start > total) {
    close(fd);
    return nullptr;
  }
  int64_t avail = total - row_start;
  int64_t n = (row_limit < 0 || row_limit > avail) ? avail : row_limit;
  auto* p = new Prefetcher();
  p->fd = fd;
  p->n_rows = n;
  p->row0 = row_start;
  p->dim = hdr[1];
  p->elem = elem_size;
  p->batch_rows = batch_rows;
  p->n_batches = (p->n_rows + batch_rows - 1) / batch_rows;
  const int depth = 2;
  try {
    p->bufs.resize(depth);
    p->buf_rows.assign(depth, -1);
    for (auto& b : p->bufs)
      b.resize((size_t)batch_rows * p->dim * elem_size);
  } catch (...) {  // bad_alloc on absurd batch sizes
    close(fd);
    delete p;
    return nullptr;
  }
  p->worker = std::thread([p, depth]() {
    for (int64_t bi = 0; bi < p->n_batches; ++bi) {
      int slot = (int)(bi % depth);
      {
        std::unique_lock<std::mutex> lk(p->m);
        p->cv.wait(lk, [&] { return p->stop || p->buf_rows[slot] < 0; });
        if (p->stop) return;
      }
      int64_t start = bi * p->batch_rows;
      int64_t rows = std::min(p->batch_rows, p->n_rows - start);
      int64_t bytes = rows * p->dim * p->elem;
      int64_t off = 8 + (p->row0 + start) * p->dim * p->elem;
      bool ok = pread_fully(p->fd, p->bufs[slot].data(), bytes, off);
      std::lock_guard<std::mutex> lk(p->m);
      if (!ok) {
        p->err = -3;
        p->buf_rows[slot] = 0;
      } else {
        p->buf_rows[slot] = rows;
      }
      p->cv.notify_all();
      if (p->err) return;
    }
  });
  return p;
}

// Copies the next batch into out (caller-allocated, batch_rows*dim*elem).
// Returns rows copied, 0 at EOF, <0 on read error.
int64_t prefetch_next(void* handle, void* out) {
  auto* p = (Prefetcher*)handle;
  const int depth = (int)p->bufs.size();
  if (p->consumed >= p->n_batches) return 0;
  int slot = (int)(p->consumed % depth);
  int64_t rows;
  {
    std::unique_lock<std::mutex> lk(p->m);
    p->cv.wait(lk, [&] { return p->buf_rows[slot] >= 0 || p->err; });
    rows = p->buf_rows[slot];
    // Drain any valid batch already staged in this slot even if the worker
    // has since failed on a later batch; surface the error only when this
    // slot itself carries it (the worker stores 0 rows on a failed read —
    // real batches always have >= 1 row) or was never filled.
    if (rows <= 0) return p->err ? p->err : 0;
  }
  std::memcpy(out, p->bufs[slot].data(), (size_t)rows * p->dim * p->elem);
  {
    std::lock_guard<std::mutex> lk(p->m);
    p->buf_rows[slot] = -1;
    p->consumed++;
    p->cv.notify_all();
  }
  return rows;
}

void prefetch_close(void* handle) {
  auto* p = (Prefetcher*)handle;
  {
    std::lock_guard<std::mutex> lk(p->m);
    p->stop = true;
    p->cv.notify_all();
  }
  if (p->worker.joinable()) p->worker.join();
  close(p->fd);
  delete p;
}

// --------------------------------------------- graph ef-search (hnsw role)
//
// CPU greedy beam search over a fixed-degree neighbor graph — hnswlib's
// layer-0 searchBaseLayerST algorithm (candidate min-heap + bounded
// result max-heap + visited stamps), run from a fixed entry point, which
// is exactly how the base-layer-only indexes hnswlib_write() emits are
// searched. This is the external-competitor row of the bench harness
// (the hnswlib wrapper role, cpp/bench/ann/src/hnswlib/
// hnswlib_wrapper.h): no hnswlib wheel exists on this image, so the
// algorithm itself provides the CPU rival pareto points.
int graph_greedy_search(const float* data, int64_t n, int64_t dim,
                        const int32_t* graph, int64_t degree,
                        const float* queries, int64_t nq,
                        int64_t k, int64_t ef, int64_t entry,
                        int32_t* out_ids, float* out_dists,
                        int64_t n_threads) {
  if (n <= 0 || k <= 0 || ef < k || entry < 0 || entry >= n) return -1;
  if (nq <= 0) return 0;  // empty batch: nothing to do (and the thread
                          // clamp below would otherwise divide by zero)
  if (n_threads <= 0)
    n_threads = (int64_t)std::thread::hardware_concurrency();
  if (n_threads < 1) n_threads = 1;
  if (n_threads > nq) n_threads = nq;

  auto worker = [&](int64_t q_lo, int64_t q_hi) {
    std::vector<uint32_t> visited(n, 0);
    uint32_t epoch = 0;
    // (dist, id) heaps: cand = min-first frontier, res = max-first top-ef
    using Entry = std::pair<float, int32_t>;
    std::vector<Entry> cand, res;
    for (int64_t qi = q_lo; qi < q_hi; ++qi) {
      const float* q = queries + qi * dim;
      ++epoch;
      cand.clear();
      res.clear();
      auto l2 = [&](int64_t row) {
        const float* v = data + row * dim;
        float s = 0.f;
        for (int64_t d = 0; d < dim; ++d) {
          float t = q[d] - v[d];
          s += t * t;
        }
        return s;
      };
      float d0 = l2(entry);
      cand.push_back({-d0, (int32_t)entry});  // negate: max-heap = nearest
      res.push_back({d0, (int32_t)entry});
      visited[entry] = epoch;
      float worst = d0;
      while (!cand.empty()) {
        std::pop_heap(cand.begin(), cand.end());
        Entry c = cand.back();
        cand.pop_back();
        if (-c.first > worst && (int64_t)res.size() >= ef) break;
        const int32_t* row = graph + (int64_t)c.second * degree;
        for (int64_t j = 0; j < degree; ++j) {
          int32_t nb = row[j];
          if (nb < 0 || nb >= n || visited[nb] == epoch) continue;
          visited[nb] = epoch;
          float d = l2(nb);
          if ((int64_t)res.size() < ef || d < worst) {
            cand.push_back({-d, nb});
            std::push_heap(cand.begin(), cand.end());
            res.push_back({d, nb});
            std::push_heap(res.begin(), res.end());
            if ((int64_t)res.size() > ef) {
              std::pop_heap(res.begin(), res.end());
              res.pop_back();
            }
            worst = res.front().first;
          }
        }
      }
      std::sort(res.begin(), res.end());
      for (int64_t j = 0; j < k; ++j) {
        bool have = j < (int64_t)res.size();
        out_ids[qi * k + j] = have ? res[j].second : -1;
        out_dists[qi * k + j] = have ? res[j].first
                                     : std::numeric_limits<float>::infinity();
      }
    }
  };

  if (n_threads == 1) {
    worker(0, nq);
  } else {
    std::vector<std::thread> pool;
    int64_t chunk = (nq + n_threads - 1) / n_threads;
    for (int64_t t = 0; t < n_threads; ++t) {
      int64_t lo = t * chunk, hi = std::min(nq, lo + chunk);
      if (lo >= hi) break;
      pool.emplace_back(worker, lo, hi);
    }
    for (auto& th : pool) th.join();
  }
  return 0;
}

}  // extern "C"
