// raft_tpu native runtime — host-side C++ for the pieces the reference
// implements natively and that sit off the XLA compute path:
//
//  * bin dataset IO (fbin/ibin/u8bin) with mmap'd zero-copy batch reads —
//    the role of the reference's mmap'd fbin reader
//    (cpp/bench/ann/src/common/dataset.hpp) for out-of-core datasets.
//  * hnswlib-format serializer: writes a base-layer-only hnswlib index
//    from a CAGRA graph + dataset, interoperable with hnswlib's
//    loadIndex (the reference's CAGRA→HNSW export,
//    neighbors/detail/hnsw_types.hpp:60-86).
//  * agglomerative union-find labeling over sorted MST edges — the
//    sequential dendrogram step of single-linkage
//    (cluster/detail/agglomerative.cuh analog).
//  * IVF list packing: group rows by cluster label into padded lists —
//    the host half of build_index_kernel (detail/ivf_flat_build.cuh:123).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cmath>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>
#include <algorithm>
#include <numeric>

extern "C" {

// ------------------------------------------------------------------ bin IO

// Header: int32 n_rows, int32 dim. Returns 0 on success.
int bin_read_header(const char* path, int64_t* n_rows, int64_t* dim) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int32_t hdr[2];
  if (std::fread(hdr, sizeof(int32_t), 2, f) != 2) {
    std::fclose(f);
    return -2;
  }
  *n_rows = hdr[0];
  *dim = hdr[1];
  std::fclose(f);
  return 0;
}

// Read rows [row_start, row_start+n_rows) into out (caller-allocated,
// n_rows*dim*elem_size bytes). Uses pread — no seek state, thread-safe.
int bin_read_rows(const char* path, int64_t row_start, int64_t n_rows,
                  int64_t elem_size, void* out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  int32_t hdr[2];
  if (pread(fd, hdr, sizeof(hdr), 0) != (ssize_t)sizeof(hdr)) {
    close(fd);
    return -2;
  }
  const int64_t dim = hdr[1];
  const int64_t row_bytes = dim * elem_size;
  const int64_t off = 8 + row_start * row_bytes;
  const int64_t want = n_rows * row_bytes;
  int64_t done = 0;
  while (done < want) {
    ssize_t got = pread(fd, (char*)out + done, want - done, off + done);
    if (got <= 0) {
      close(fd);
      return -3;
    }
    done += got;
  }
  close(fd);
  return 0;
}

int bin_write(const char* path, const void* data, int64_t n_rows,
              int64_t dim, int64_t elem_size) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  int32_t hdr[2] = {(int32_t)n_rows, (int32_t)dim};
  if (std::fwrite(hdr, sizeof(int32_t), 2, f) != 2) {
    std::fclose(f);
    return -2;
  }
  const size_t want = (size_t)(n_rows * dim * elem_size);
  if (std::fwrite(data, 1, want, f) != want) {
    std::fclose(f);
    return -3;
  }
  std::fclose(f);
  return 0;
}

// --------------------------------------------------------- hnswlib writer

// Writes a base-layer-only hnswlib index: header fields in hnswlib
// saveIndex order, one level-0 block per element
// [uint32 n_links][maxM0 x uint32][dim x float][size_t label], then a zero
// linkListSize per element (no upper layers; maxlevel 0, enterpoint 0).
// space: 0 = l2, 1 = ip.
int hnswlib_write(const char* path, const float* data, const int32_t* graph,
                  int64_t n, int64_t dim, int64_t degree, int64_t /*space*/) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;

  const uint64_t offset_level0 = 0;
  const uint64_t max_elements = (uint64_t)n;
  const uint64_t cur_count = (uint64_t)n;
  const uint64_t size_links0 = (uint64_t)degree * 4 + 4;
  const uint64_t data_size = (uint64_t)dim * 4;
  const uint64_t size_per_elem = size_links0 + data_size + 8;
  const uint64_t label_offset = size_links0 + data_size;
  const uint64_t offset_data = size_links0;
  const int32_t max_level = 0;
  const uint32_t enterpoint = 0;
  const uint64_t maxM = (uint64_t)degree / 2 ? (uint64_t)degree / 2 : 1;
  const uint64_t maxM0 = (uint64_t)degree;
  const uint64_t M = maxM;
  const double mult = 1.0 / std::log((double)(M > 1 ? M : 2));
  const uint64_t ef_construction = 200;

#define W(x) if (std::fwrite(&(x), sizeof(x), 1, f) != 1) { std::fclose(f); return -2; }
  W(offset_level0);
  W(max_elements);
  W(cur_count);
  W(size_per_elem);
  W(label_offset);
  W(offset_data);
  W(max_level);
  W(enterpoint);
  W(maxM);
  W(maxM0);
  W(M);
  W(mult);
  W(ef_construction);
#undef W

  std::vector<char> elem(size_per_elem);
  for (int64_t i = 0; i < n; ++i) {
    // count valid links (graph entries >= 0)
    uint32_t cnt = 0;
    for (int64_t j = 0; j < degree; ++j)
      if (graph[i * degree + j] >= 0) ++cnt;
    std::memset(elem.data(), 0, elem.size());
    std::memcpy(elem.data(), &cnt, 4);
    uint32_t* links = (uint32_t*)(elem.data() + 4);
    uint32_t w = 0;
    for (int64_t j = 0; j < degree; ++j) {
      int32_t t = graph[i * degree + j];
      if (t >= 0) links[w++] = (uint32_t)t;
    }
    std::memcpy(elem.data() + offset_data, data + i * dim, data_size);
    uint64_t label = (uint64_t)i;
    std::memcpy(elem.data() + label_offset, &label, 8);
    if (std::fwrite(elem.data(), 1, elem.size(), f) != elem.size()) {
      std::fclose(f);
      return -3;
    }
  }
  const uint32_t zero = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (std::fwrite(&zero, 4, 1, f) != 1) {
      std::fclose(f);
      return -4;
    }
  }
  std::fclose(f);
  return 0;
}

// ------------------------------------------- union-find dendrogram labels

static int64_t uf_find(int64_t* parent, int64_t a) {
  int64_t root = a;
  while (parent[root] != root) root = parent[root];
  while (parent[a] != root) {
    int64_t next = parent[a];
    parent[a] = root;
    a = next;
  }
  return root;
}

// Merge MST edges (already sorted by weight ascending; -1 src = padding)
// until n_clusters components remain. labels out: [n] compacted 0..k-1.
int agglomerative_label(const int32_t* src, const int32_t* dst,
                        int64_t n_edges, int64_t n, int64_t n_clusters,
                        int32_t* labels) {
  std::vector<int64_t> parent(n);
  std::iota(parent.begin(), parent.end(), 0);
  int64_t target = n - n_clusters;
  int64_t merges = 0;
  for (int64_t e = 0; e < n_edges && merges < target; ++e) {
    if (src[e] < 0 || dst[e] < 0) continue;
    int64_t ra = uf_find(parent.data(), src[e]);
    int64_t rb = uf_find(parent.data(), dst[e]);
    if (ra == rb) continue;
    parent[std::max(ra, rb)] = std::min(ra, rb);
    ++merges;
  }
  // compact root ids to 0..k-1
  std::vector<int32_t> remap(n, -1);
  int32_t next_label = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t r = uf_find(parent.data(), i);
    if (remap[r] < 0) remap[r] = next_label++;
    labels[i] = remap[r];
  }
  return next_label;
}

// ----------------------------------------------------- IVF list packing

// Group rows by label into padded [n_lists, list_pad, row_bytes] storage +
// ids [n_lists, list_pad] (-1 pad) + sizes [n_lists]. Returns 0.
int pack_lists(const char* rows, const int32_t* labels, const int32_t* ids,
               int64_t n_rows, int64_t row_bytes, int64_t n_lists,
               int64_t list_pad, char* out_data, int32_t* out_ids,
               int32_t* out_sizes) {
  std::vector<int64_t> cursor(n_lists, 0);
  std::memset(out_sizes, 0, n_lists * sizeof(int32_t));
  for (int64_t i = 0; i < n_rows; ++i) {
    const int32_t l = labels[i];
    if (l < 0 || l >= n_lists) return -1;
    const int64_t pos = cursor[l]++;
    if (pos >= list_pad) return -2;
    std::memcpy(out_data + (l * list_pad + pos) * row_bytes,
                rows + i * row_bytes, row_bytes);
    out_ids[l * list_pad + pos] = ids ? ids[i] : (int32_t)i;
    out_sizes[l] = (int32_t)cursor[l];
  }
  // -1-fill unused id slots
  for (int64_t l = 0; l < n_lists; ++l)
    for (int64_t p = cursor[l]; p < list_pad; ++p)
      out_ids[l * list_pad + p] = -1;
  return 0;
}

}  // extern "C"
