"""Balanced hierarchical k-means — the IVF coarse-quantizer trainer.

Reference: ``raft::cluster::kmeans_balanced`` (cluster/kmeans_balanced.cuh:76,
134,199,258 public API; cluster/detail/kmeans_balanced.cuh implementation).
Behavioral contract reproduced here:

- ``build_clusters`` (detail:700-757): init labels = row_index % n_clusters,
  compute centers, then balancing EM (pullback=2, threshold=0.25): per
  iteration (detail:617-697) — (a) ``adjust_centers`` (skipped on iter 0):
  every cluster with size ≤ average·threshold is re-seeded to gravitate toward
  a sample from a large (size ≥ average) cluster: new_center =
  (wc·center[donor_label] + 1·x_donor)/(wc+1) with wc = min(size,
  kAdjustCentersWeight=7) (detail:439-484); the balancing counter starts at
  ``pullback`` so the first rebalance immediately grants one extra EM
  iteration (detail:636); (b) for InnerProduct/Cosine/Correlation metrics the
  centers are L2-row-normalized every iteration (detail:656-670); (c) E-step
  predict; (d) M-step calc_centers_and_sizes.
- ``build_hierarchical`` (detail:956-1090): n_mesoclusters = min(n, round(
  √n_clusters)); coarse build_clusters over the trainset; fine cluster counts
  per mesocluster proportional to mesocluster sizes (arrange_fine_clusters,
  detail:759-818); per-mesocluster build_clusters over exactly that
  mesocluster's fine count; final fine-tuning EM over all clusters with
  max(n_iters/10, 2) iterations, pullback=5, threshold=0.2 (detail:1075-1090).

TPU-native design: E-step = fused-L2 argmin (MXU matmul, tiled); M-step =
scatter-add segment sum; adjust_centers vectorized — starving clusters pick
donors from a pre-sampled pool of big-cluster rows (the reference's
pseudo-random host scan, done functionally). One shared jitted
``lax.while_loop`` EM body serves build_clusters and the fine-tune stage. The
mesocluster stage pads member sets to a static ``mesocluster_size_max`` with
row weights, and pads cluster counts to a static ``fine_max`` with an active-
cluster count, so one compiled kernel serves every mesocluster while each
trains exactly its own number of clusters.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import DistanceType, resolve_metric, row_norms_sq
from raft_tpu.utils.shape import cdiv

_ADJUST_CENTERS_WEIGHT = 7.0  # detail/kmeans_balanced.cuh:62
_BUILD_PULLBACK = 2  # detail:752
_BUILD_THRESHOLD = 0.25  # detail:753
_TUNE_PULLBACK = 5  # detail:1087
_TUNE_THRESHOLD = 0.2  # detail:1088
_DONOR_POOL = 256  # candidate donors sampled per adjust step


@dataclasses.dataclass
class KMeansBalancedParams:
    """Hyper-parameters (reference: kmeans_balanced_types.hpp:34).

    ``target_balance_cv``/``balance_polish_rounds`` go beyond the
    reference: its adjust_centers only rescues STARVING clusters
    (size ≤ threshold·avg, detail:439-484), which leaves a heavy tail of
    hot clusters (measured CV 0.42 on the bench target — VERDICT r2 #2).
    The polish stage splits the largest clusters into the smallest ones
    (center + radius-scaled perturbation, then an EM settle) until the
    size coefficient-of-variation reaches the target. Balanced lists are
    what bound IVF list padding and per-probe scan cost. Set
    ``target_balance_cv=None`` to disable."""

    n_iters: int = 20
    metric: DistanceType = DistanceType.L2Expanded
    target_balance_cv: Optional[float] = 0.24
    balance_polish_rounds: int = 16

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if self.metric not in (
            DistanceType.L2Expanded,
            DistanceType.L2SqrtExpanded,
            DistanceType.InnerProduct,
            DistanceType.CosineExpanded,
        ):
            raise ValueError(
                f"kmeans_balanced supports L2/IP/Cosine metrics, got {self.metric.name}"
            )


def _needs_normalized_centers(metric: DistanceType) -> bool:
    # reference detail:656-670: avoid collapse to zero centers
    return metric in (
        DistanceType.InnerProduct,
        DistanceType.CosineExpanded,
        DistanceType.CorrelationExpanded,
    )


def _predict_labels(x, centers, metric: DistanceType, active_mask=None,
                    tile: int = 65536):
    """E-step: nearest *active* center per row; the matmul rides the MXU,
    tiled over rows so only [tile, n_clusters] scores exist at once (analog
    of detail::predict's minibatched fusedL2NN)."""
    cf = centers.astype(jnp.float32)
    cn = row_norms_sq(cf)
    if metric == DistanceType.CosineExpanded:
        c_inv_norm = 1.0 / jnp.maximum(jnp.sqrt(cn), 1e-20)

    def tile_body(xt):
        xf = xt.astype(jnp.float32)
        dots = jax.lax.dot_general(
            xf, cf, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if metric in (DistanceType.InnerProduct, DistanceType.CosineExpanded):
            score = (dots * c_inv_norm[None, :]
                     if metric == DistanceType.CosineExpanded else dots)
            if active_mask is not None:
                score = jnp.where(active_mask[None, :], score, -jnp.inf)
            return jnp.argmax(score, axis=1).astype(jnp.int32)
        d = row_norms_sq(xf)[:, None] + cn[None, :] - 2.0 * dots
        if active_mask is not None:
            d = jnp.where(active_mask[None, :], d, jnp.inf)
        return jnp.argmin(d, axis=1).astype(jnp.int32)

    m = x.shape[0]
    if m <= tile:
        return tile_body(x)
    n_tiles = cdiv(m, tile)
    pad = n_tiles * tile - m
    xp = jnp.pad(x, ((0, pad), (0, 0)))
    labels = jax.lax.map(
        tile_body,
        xp.reshape(n_tiles, tile, x.shape[1]))  # graftcheck: R005 — O(input)
    return labels.reshape(-1)[:m]


def calc_centers_and_sizes(x, labels, n_clusters: int, weights=None
                           ) -> Tuple[jax.Array, jax.Array]:
    """M-step (reference: kmeans_balanced::helpers::calc_centers_and_sizes,
    kmeans_balanced.cuh:258): per-cluster mean + counts via scatter-add."""
    xf = x.astype(jnp.float32)
    if weights is not None:
        w = weights.astype(jnp.float32)
        xf = xf * w[:, None]
        counts = jnp.zeros((n_clusters,), jnp.float32).at[labels].add(w)
    else:
        counts = jnp.zeros((n_clusters,), jnp.float32).at[labels].add(1.0)
    sums = jnp.zeros((n_clusters, x.shape[1]), jnp.float32).at[labels].add(xf)
    centers = sums / jnp.maximum(counts, 1.0)[:, None]
    return centers, counts


def _adjust_centers(key, centers, sizes, x, labels, weights, active_mask,
                    threshold: float):
    """Re-seed starving clusters from big-cluster samples (detail:439-484).

    Vectorized: sample a _DONOR_POOL of row indices, keep those in big
    clusters, and give starving cluster l the (l mod pool)-th good donor.
    Returns (adjusted_any, new_centers).
    """
    n_rows = x.shape[0]
    n_clusters = centers.shape[0]
    n_eff = jnp.sum(weights) if weights is not None else jnp.float32(n_rows)
    if active_mask is not None:
        n_active = jnp.sum(active_mask.astype(jnp.float32))
    else:
        n_active = jnp.float32(n_clusters)
    average = n_eff / jnp.maximum(n_active, 1.0)

    starving = sizes <= average * threshold  # includes empty clusters
    if active_mask is not None:
        starving = starving & active_mask
    big = sizes >= average

    pool_idx = jax.random.randint(key, (_DONOR_POOL,), 0, n_rows)
    pool_ok = big[labels[pool_idx]]
    if weights is not None:
        pool_ok = pool_ok & (weights[pool_idx] > 0)
    # Compact good donors to the front (stable), cycling to fill the pool.
    order = jnp.argsort(~pool_ok)  # good donors first
    pool_idx = pool_idx[order]
    n_good = jnp.sum(pool_ok.astype(jnp.int32))
    slot = jnp.arange(n_clusters) % jnp.maximum(n_good, 1)
    donor_rows = pool_idx[slot]  # [n_clusters]
    have_donor = (n_good > 0) & starving

    donor_label = labels[donor_rows]
    wc = jnp.minimum(sizes, _ADJUST_CENTERS_WEIGHT)[:, None]
    new = (wc * centers[donor_label] + x[donor_rows].astype(jnp.float32)) / (wc + 1.0)
    centers = jnp.where(have_donor[:, None], new, centers)
    return jnp.any(have_donor), centers


def _balancing_em_loop(key, x, weights, active_mask, centers0, labels0, sizes0,
                       n_iters: int, pullback: int, threshold: float,
                       metric: DistanceType):
    """The shared balancing-EM loop (reference: balancing_em_iters,
    detail:617-697). Counter starts at ``pullback`` so the first rebalance
    grants an extra iteration (detail:636)."""
    n_clusters = centers0.shape[0]
    max_iters = n_iters + cdiv(n_iters, 2) + 1  # bounded extra-iteration budget

    def cond(state):
        i, iters_target = state[0], state[1]
        return i < jnp.minimum(iters_target, max_iters)

    def body(state):
        i, iters_target, balance_ctr, key, centers, labels, sizes = state
        key, k_adj = jax.random.split(key)
        adjusted, centers = jax.lax.cond(
            i > 0,
            lambda: _adjust_centers(
                k_adj, centers, sizes, x, labels, weights, active_mask, threshold
            ),
            lambda: (jnp.bool_(False), centers),
        )
        balance_ctr = balance_ctr + adjusted.astype(jnp.int32)
        extra = balance_ctr >= pullback
        balance_ctr = jnp.where(extra, balance_ctr - pullback, balance_ctr)
        iters_target = iters_target + extra.astype(jnp.int32)
        if _needs_normalized_centers(metric):
            centers = centers / jnp.maximum(
                jnp.linalg.norm(centers, axis=1, keepdims=True), 1e-20
            )
        labels = _predict_labels(x, centers, metric, active_mask)
        centers, sizes = calc_centers_and_sizes(x, labels, n_clusters, weights)
        return (i + 1, iters_target, balance_ctr, key, centers, labels, sizes)

    state = (jnp.int32(0), jnp.int32(n_iters), jnp.int32(pullback), key,
             centers0, labels0, sizes0)
    _, _, _, _, centers, labels, sizes = jax.lax.while_loop(cond, body, state)
    return centers, labels, sizes


@functools.partial(
    jax.jit,
    static_argnames=("n_clusters", "n_iters", "metric", "has_weights",
                     "has_active"),
)
def _build_clusters_jit(key, x, weights, n_active, n_clusters: int,
                        n_iters: int, metric: DistanceType, has_weights: bool,
                        has_active: bool):
    n_rows = x.shape[0]
    w = weights if has_weights else None
    if has_active:
        active_mask = jnp.arange(n_clusters) < n_active
        labels0 = (jnp.arange(n_rows) % jnp.maximum(n_active, 1)).astype(jnp.int32)
    else:
        active_mask = None
        labels0 = (jnp.arange(n_rows) % n_clusters).astype(jnp.int32)
    centers0, sizes0 = calc_centers_and_sizes(x, labels0, n_clusters, w)
    return _balancing_em_loop(
        key, x, w, active_mask, centers0, labels0, sizes0,
        n_iters, _BUILD_PULLBACK, _BUILD_THRESHOLD, metric,
    )


def build_clusters(
    key,
    x,
    n_clusters: int,
    params: Optional[KMeansBalancedParams] = None,
    weights: Optional[jax.Array] = None,
    n_active: Optional[jax.Array] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-level balanced k-means (reference: helpers::build_clusters,
    kmeans_balanced.cuh:258). Returns (centers, labels, sizes).

    ``n_clusters`` is the (static) center-array size; ``n_active`` optionally
    limits training to the first n_active clusters (used by the hierarchical
    fine stage so one compilation serves all mesoclusters).
    """
    params = params or KMeansBalancedParams()
    ensure_resources(res)
    x = jnp.asarray(x)
    return _build_clusters_jit(
        key, x,
        weights if weights is not None else jnp.zeros((0,)),
        n_active if n_active is not None else jnp.int32(0),
        int(n_clusters), int(params.n_iters), params.metric,
        weights is not None, n_active is not None,
    )


@functools.partial(jax.jit, static_argnames=("fine_max", "n_iters", "metric"))
def _fine_stage_jit(keys, x, member_idx, weights, n_actives, fine_max: int,
                    n_iters: int, metric: DistanceType):
    """Batched fine-stage builds: lax.map of the single-level balanced
    build over mesocluster member lists (gathered device-side)."""

    def body(args):
        key, idx, w, n_active = args
        sub = x[idx]  # [meso_max, dim] gather
        centers, _, _ = _build_clusters_jit(
            key, sub, w, n_active, fine_max, n_iters, metric,
            True, True)
        return centers

    return jax.lax.map(body, (keys, member_idx, weights, n_actives))


def _arrange_fine_clusters(n_clusters: int, n_meso: int, n_rows: int,
                           meso_sizes: np.ndarray) -> np.ndarray:
    """Fine-cluster count per mesocluster, proportional to its size
    (reference: arrange_fine_clusters, detail:759-818). Host-side."""
    fine_nums = np.zeros(n_meso, dtype=np.int64)
    n_lists_rem = n_clusters
    n_rows_rem = n_rows
    n_nonempty_rem = int((meso_sizes > 0).sum())
    for i in range(n_meso):
        if i < n_meso - 1:
            if meso_sizes[i] == 0:
                fine_nums[i] = 0
            else:
                n_nonempty_rem -= 1
                # proportional share, rounded; keep ≥1 per nonempty, and leave
                # ≥1 for each remaining nonempty mesocluster
                share = int(n_lists_rem * meso_sizes[i] / max(n_rows_rem, 1) + 0.5)
                fine_nums[i] = min(
                    max(share, 1), max(n_lists_rem - n_nonempty_rem, 1)
                )
        else:
            fine_nums[i] = n_lists_rem if meso_sizes[i] > 0 else 0
        n_lists_rem -= fine_nums[i]
        n_rows_rem -= int(meso_sizes[i])
    return fine_nums


def fit(
    key,
    x,
    n_clusters: int,
    params: Optional[KMeansBalancedParams] = None,
    res: Optional[Resources] = None,
) -> jax.Array:
    """Hierarchical balanced k-means fit (reference: kmeans_balanced::fit,
    kmeans_balanced.cuh:76 → detail::build_hierarchical:956).

    Returns cluster centers [n_clusters, dim] (fp32).
    """
    params = params or KMeansBalancedParams()
    res = ensure_resources(res)
    x = jnp.asarray(x)
    n_rows, dim = x.shape
    if n_clusters > n_rows:
        raise ValueError(f"n_clusters={n_clusters} > n_rows={n_rows}")

    n_meso = min(n_clusters, int(math.sqrt(n_clusters) + 0.5))
    if n_meso <= 1 or n_clusters <= n_meso:
        k_build, k_polish = jax.random.split(key)
        centers, _, _ = build_clusters(k_build, x, n_clusters, params,
                                       res=res)
        return _balance_polish(k_polish, x, centers, params)

    k_coarse, k_fine, k_final, k_polish = jax.random.split(key, 4)

    # --- coarse stage: mesoclusters over the whole trainset
    _, meso_labels, meso_sizes_f = build_clusters(k_coarse, x, n_meso, params, res=res)
    meso_labels_np = np.asarray(meso_labels)
    meso_sizes = np.asarray(meso_sizes_f).astype(np.int64)

    fine_nums = _arrange_fine_clusters(n_clusters, n_meso, n_rows, meso_sizes)
    assert fine_nums.sum() == n_clusters, (fine_nums.sum(), n_clusters)

    # cap per-mesocluster trainset like the reference's balanced max
    # (detail:1032-1046)
    meso_max = int(min(meso_sizes.max(), max(cdiv(2 * n_rows, max(n_meso, 1)), 1)))
    fine_max = int(fine_nums.max())

    # --- fine stage: all mesoclusters in ONE device program (lax.map over
    # padded member-index rows) — the per-meso builds are identical padded
    # shapes, so batching them removes n_meso host↔device round-trips
    member_idx = np.zeros((n_meso, meso_max), np.int32)
    wts = np.zeros((n_meso, meso_max), np.float32)
    for i in range(n_meso):
        members = np.nonzero(meso_labels_np == i)[0][:meso_max]
        member_idx[i, : len(members)] = members
        wts[i, : len(members)] = 1.0
    fine_keys = jax.random.split(k_fine, n_meso)
    c_all = _fine_stage_jit(
        fine_keys, x.astype(jnp.float32), jnp.asarray(member_idx),
        jnp.asarray(wts), jnp.asarray(fine_nums.astype(np.int32)),
        fine_max, params.n_iters, params.metric,
    )  # [n_meso, fine_max, dim]
    c_all = np.asarray(c_all)
    centers_out = np.zeros((n_clusters, dim), np.float32)
    done = 0
    for i in range(n_meso):
        centers_out[done : done + fine_nums[i]] = c_all[i, : fine_nums[i]]
        done += int(fine_nums[i])

    # --- final fine-tuning over all clusters (reference: max(n_iters/10, 2)
    # iterations, pullback=5, threshold=0.2 — detail:1075-1090)
    centers = jnp.asarray(centers_out)
    centers, _, _ = _fine_tune_jit(
        k_final, x.astype(jnp.float32), centers,
        max(params.n_iters // 10, 2), params.metric,
    )
    return _balance_polish(k_polish, x, centers, params)


@functools.partial(jax.jit, static_argnames=("metric", "target_cv"))
def _polish_round_jit(key, x, centers, thr_hi, thr_lo,
                      metric: DistanceType, target_cv: float):
    """One balance-polish round: split a few of the hottest clusters into
    the emptiest centers, then two EM iterations to settle. The split
    re-seeds a small cluster's center AT a hot cluster's center plus a
    perturbation ~0.3× the hot cluster's RMS radius — the settle then
    divides the hot cluster's members between the two centers. Gentle
    moves (few pairs, hot/starving thresholds ``thr_hi``/``thr_lo`` in
    units of the average size) converge where aggressive stealing churns:
    dumping many small clusters' members each round just creates new
    holes elsewhere. Returns (centers, cv_pre, cv_post, n_moved); no
    split happens once cv_pre ≤ target."""
    n_rows, dim = x.shape
    n_clusters = centers.shape[0]
    labels = _predict_labels(x, centers, metric)
    centers_m, sizes = calc_centers_and_sizes(x, labels, n_clusters)
    cv_pre = jnp.std(sizes) / jnp.maximum(jnp.mean(sizes), 1e-9)
    # per-cluster mean squared radius: E||x||² − ||c||² (one scatter-add)
    xsq = jnp.zeros((n_clusters,), jnp.float32).at[labels].add(
        jnp.sum(x * x, -1))
    msd = (xsq / jnp.maximum(sizes, 1.0)
           - jnp.sum(centers_m * centers_m, -1))
    order = jnp.argsort(sizes)
    n_pairs = min(max(n_clusters // 16, 1), 64)
    small = order[:n_pairs]
    large = order[::-1][:n_pairs]
    avg = n_rows / n_clusters
    do = ((sizes[large] > thr_hi * avg) & (sizes[small] < thr_lo * avg)
          & (cv_pre > target_cv))
    scale = 0.3 * jnp.sqrt(jnp.maximum(msd[large], 1e-12) / dim)[:, None]
    noise = jax.random.normal(key, (n_pairs, dim), jnp.float32) * scale
    new_small = centers_m[large] + noise
    cf = centers_m.at[small].set(
        jnp.where(do[:, None], new_small, centers_m[small]))
    sizes2 = sizes
    for _ in range(2):  # settle
        if _needs_normalized_centers(metric):
            cf = cf / jnp.maximum(
                jnp.linalg.norm(cf, axis=1, keepdims=True), 1e-20)
        labels2 = _predict_labels(x, cf, metric)
        cf, sizes2 = calc_centers_and_sizes(x, labels2, n_clusters)
    cv_post = jnp.std(sizes2) / jnp.maximum(jnp.mean(sizes2), 1e-9)
    return cf, cv_pre, cv_post, jnp.sum(do.astype(jnp.int32))


def _balance_polish(key, x, centers, params: KMeansBalancedParams):
    """Host-looped polish rounds (each ≈3 EM iterations), keeping the
    best-CV centers seen (the split moves are stochastic, and the input
    centers are the baseline to beat — a failed polish never returns
    centers LESS balanced than it was given).

    The split thresholds adapt: rounds start strict (split > 1.4×avg into
    < 0.5×avg) and relax one notch each time no pair fires while CV is
    still above target — mid-spread distributions (every cluster between
    0.5 and 1.4 of average, CV ≈ 0.25) need the milder splits. Stops at
    the target, when fully-relaxed thresholds still find nothing to move,
    or after 4 rounds without measurable progress — bounding the cost of
    an unreachable target to a few EM-equivalents."""
    target = params.target_balance_cv
    if target is None or params.balance_polish_rounds <= 0:
        return centers
    xf = x.astype(jnp.float32)
    best, best_cv = centers, np.inf  # re-seeded from cv_pre on round 1
    stalled = 0
    thr_hi, thr_lo = 1.4, 0.5
    for _ in range(params.balance_polish_rounds):
        key, k = jax.random.split(key)
        new_centers, cv_pre, cv_post, n_moved = _polish_round_jit(
            k, xf, centers, jnp.float32(thr_hi), jnp.float32(thr_lo),
            params.metric, float(target))
        if float(cv_pre) <= target:
            return centers  # already balanced — this round didn't split
        if float(cv_pre) < best_cv:
            # cv_pre measures the CURRENT `centers` array: keep the pair
            # together, else `best` and `best_cv` diverge and the array
            # that achieved the tracked best is thrown away
            best, best_cv = centers, float(cv_pre)
        if float(cv_post) < best_cv - 1e-3:
            best, best_cv, stalled = new_centers, float(cv_post), 0
        else:
            stalled += 1
        centers = new_centers
        if best_cv <= target or stalled >= 4:
            break
        if int(n_moved) == 0:
            if thr_hi <= 1.15:
                break  # nothing movable even at the mildest thresholds
            thr_hi = max(thr_hi - 0.1, 1.15)
            thr_lo = min(thr_lo + 0.1, 0.85)
    return best


@functools.partial(jax.jit, static_argnames=("n_iters", "metric"))
def _fine_tune_jit(key, x, centers0, n_iters: int, metric: DistanceType):
    n_clusters = centers0.shape[0]
    labels0 = _predict_labels(x, centers0, metric)
    sizes0 = jnp.zeros((n_clusters,), jnp.float32).at[labels0].add(1.0)
    return _balancing_em_loop(
        key, x, None, None, centers0, labels0, sizes0,
        n_iters, _TUNE_PULLBACK, _TUNE_THRESHOLD, metric,
    )


def predict(
    centers,
    x,
    params: Optional[KMeansBalancedParams] = None,
    res: Optional[Resources] = None,
) -> jax.Array:
    """Assign each row of x to its nearest center (reference:
    kmeans_balanced::predict, kmeans_balanced.cuh:134)."""
    params = params or KMeansBalancedParams()
    ensure_resources(res)
    return _predict_jit(jnp.asarray(x), jnp.asarray(centers), params.metric)


@functools.partial(jax.jit, static_argnames=("metric",))
def _predict_jit(x, centers, metric: DistanceType):
    return _predict_labels(x, centers, metric)


def fit_predict(
    key,
    x,
    n_clusters: int,
    params: Optional[KMeansBalancedParams] = None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """fit + predict (reference: kmeans_balanced::fit_predict,
    kmeans_balanced.cuh:199)."""
    centers = fit(key, x, n_clusters, params, res)
    return centers, predict(centers, x, params, res)
