"""Cluster layer (SURVEY.md §2.6): k-means (Lloyd), balanced hierarchical
k-means (IVF coarse-quantizer trainer), single-linkage."""

from raft_tpu.cluster import kmeans, kmeans_balanced, single_linkage
from raft_tpu.cluster.kmeans import KMeansParams
from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
from raft_tpu.cluster.single_linkage import SingleLinkageParams

__all__ = ["kmeans", "kmeans_balanced", "single_linkage", "KMeansParams",
           "KMeansBalancedParams", "SingleLinkageParams"]
