"""Cluster layer (SURVEY.md §2.6): k-means (Lloyd), balanced hierarchical
k-means (IVF coarse-quantizer trainer), single-linkage."""

__all__ = []
