"""Single-linkage agglomerative clustering.

Reference: ``raft::cluster::single_linkage`` (cluster/single_linkage.cuh →
detail/connectivities.cuh builds a kNN connectivity graph, detail/mst.cuh
solves the MST, detail/agglomerative.cuh labels the dendrogram with a
union-find, with ``n_clusters`` cutting the tree at the (n−k) shortest
merges).

TPU-native design: connectivity = brute-force kNN graph (MXU) symmetrized;
MST = the functional Borůvka (sparse.mst) — both on device. The dendrogram
labeling is an inherently sequential union-find over n−1 sorted edges;
it runs on host over the (tiny) MST edge list, exactly the part the
reference implements with a specialized kernel whose work is O(n α(n)) —
negligible next to the O(n²d) connectivity step."""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import DistanceType, resolve_metric
from raft_tpu.sparse.types import COO
from raft_tpu.sparse import mst as mst_mod


@dataclasses.dataclass
class SingleLinkageParams:
    """reference: single_linkage.cuh template params (KNN_GRAPH vs
    PAIRWISE connectivity) + n_clusters control."""

    n_clusters: int = 2
    metric: DistanceType = DistanceType.L2SqrtExpanded
    connectivity_k: int = 15  # kNN connectivity degree (detail: c param)

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)


def _knn_connectivity(x, k: int, metric: DistanceType,
                      res: Resources) -> COO:
    """Symmetrized kNN graph (detail/connectivities.cuh KNN_GRAPH path)."""
    from raft_tpu.neighbors import brute_force

    n = x.shape[0]
    d, idx = brute_force.knn(x, x, k=min(k + 1, n), metric=metric, res=res)
    d = jnp.asarray(d)[:, 1:]  # drop self
    idx = jnp.asarray(idx)[:, 1:]
    rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32), idx.shape[1])
    cols = idx.reshape(-1)
    data = d.reshape(-1).astype(jnp.float32)
    # both directions so Borůvka sees every incident edge from each side
    return COO(jnp.concatenate([rows, cols]),
               jnp.concatenate([cols, rows]),
               jnp.concatenate([data, data]), (n, n))


def _label_dendrogram(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
                      n: int, n_clusters: int) -> np.ndarray:
    """Union-find over MST edges sorted by weight — merges cheapest-first
    until n_clusters components remain (or the forest runs out; disconnected
    inputs keep their natural component count, like the reference before
    connect_components). Runs in the native C++ labeler
    (detail/agglomerative.cuh analog) with a numpy fallback."""
    from raft_tpu import native

    order = np.argsort(w, kind="stable")
    keep = np.isfinite(w[order]) & (src[order] >= 0)
    order = order[keep]
    return native.agglomerative_label(src[order], dst[order], n, n_clusters)


def single_linkage(
    x,
    params: Optional[SingleLinkageParams] = None,
    res: Optional[Resources] = None,
) -> np.ndarray:
    """Cluster rows of ``x`` into ``n_clusters`` by single linkage
    (reference: cluster::single_linkage, single_linkage.cuh). Returns
    labels [n]."""
    params = params or SingleLinkageParams()
    res = ensure_resources(res)
    x = jnp.asarray(x)
    n = x.shape[0]
    if params.n_clusters < 1 or params.n_clusters > n:
        raise ValueError(f"n_clusters={params.n_clusters} out of range")
    graph = _knn_connectivity(x, params.connectivity_k, params.metric, res)
    src, dst, w = mst_mod.mst(graph)
    return _label_dendrogram(np.asarray(src), np.asarray(dst),
                             np.asarray(w), n, params.n_clusters)
