"""Lloyd k-means with k-means++ init.

Reference: ``raft::cluster::kmeans`` (cluster/detail/kmeans.cuh:361-1054,
cluster/kmeans_types.hpp) — ``KMeansParams{n_clusters, max_iter=300,
tol=1e-4, init: KMeansPlusPlus|Random|Array, n_init=1, rng_state,
oversampling_factor, inertia_check}``; fit = kmeans++ init
(``initKMeansPlusPlus``) then Lloyd iterations of fusedL2NN-style assignment
(``minClusterAndDistanceCompute``, detail/kmeans_common.cuh:354) + centroid
update via reduce_rows_by_key, stopping on center-shift² < tol.

TPU-native design: assignment = fused-L2 argmin (MXU matmul + fused epilogue,
tiled by the Resources workspace budget); update = scatter-add segment sum;
the whole fit is one jitted ``lax.while_loop`` carrying (centers, shift).
k-means++ is a ``fori_loop`` over centers sampling from the min-distance²
distribution — the standard single-trial variant of the reference's
algorithm.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.ops.distance import DistanceType, resolve_metric, row_norms_sq
from raft_tpu.ops.fused_l2_nn import (fused_l2_nn_argmin,
    choose_tile_rows, fused_l2_nn_core)


class InitMethod(enum.Enum):
    KMeansPlusPlus = "k-means++"
    Random = "random"
    Array = "array"  # user-provided centroids


@dataclasses.dataclass
class KMeansParams:
    """reference: cluster/kmeans_types.hpp KMeansParams."""

    n_clusters: int = 8
    max_iter: int = 300
    tol: float = 1e-4
    init: InitMethod = InitMethod.KMeansPlusPlus
    n_init: int = 1
    metric: DistanceType = DistanceType.L2Expanded
    seed: int = 0

    def __post_init__(self):
        self.metric = resolve_metric(self.metric)
        if isinstance(self.init, str):
            self.init = InitMethod(self.init)


def _assign(x, x_norms, centers, tile: int):
    """E-step: (labels, distance²) via the shared tiled fused-L2 kernel
    (raft_tpu.ops.fused_l2_nn) — single implementation for kmeans, predict
    and cluster_cost."""
    d2, labels = fused_l2_nn_core(x, centers, x_norms, row_norms_sq(centers),
                                  False, tile)
    return labels, d2


#: public traceable-core name — the cross-package contract for the bench
#: harness and any caller jitting the E-step directly (R004).
assign = _assign


def _update(x, labels, old_centers, weights=None):
    n_clusters = old_centers.shape[0]
    w = jnp.ones((x.shape[0],), jnp.float32) if weights is None else weights
    counts = jnp.zeros((n_clusters,), jnp.float32).at[labels].add(w)
    sums = jnp.zeros_like(old_centers).at[labels].add(x * w[:, None])
    # empty clusters keep their previous center (reference behavior)
    centers = jnp.where(
        (counts > 0)[:, None], sums / jnp.maximum(counts, 1e-20)[:, None],
        old_centers
    )
    return centers, counts


@functools.partial(jax.jit, static_argnames=("n_clusters",))
def _kmeans_pp_init(key, x, x_norms, n_clusters: int):
    """k-means++ (reference: initKMeansPlusPlus, detail/kmeans.cuh): seed with
    a uniform row, then sample each next center ∝ min distance²."""
    n = x.shape[0]
    k0, key = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, n)
    centers0 = jnp.zeros((n_clusters, x.shape[1]), x.dtype).at[0].set(x[first])
    d0 = row_norms_sq(x - x[first][None, :])

    def body(i, state):
        centers, min_d, key = state
        key, kc = jax.random.split(key)
        # categorical over min_d (gumbel-free: use log weights)
        logits = jnp.where(min_d > 0, jnp.log(jnp.maximum(min_d, 1e-38)), -jnp.inf)
        # all-zero distances (duplicate points) → uniform
        logits = jnp.where(jnp.all(min_d <= 0), jnp.zeros_like(logits), logits)
        nxt = jax.random.categorical(kc, logits)
        c = x[nxt]
        centers = centers.at[i].set(c)
        d_new = row_norms_sq(x - c[None, :])
        return centers, jnp.minimum(min_d, d_new), key

    centers, _, _ = jax.lax.fori_loop(1, n_clusters, body, (centers0, d0, key))
    return centers


@functools.partial(jax.jit, static_argnames=("max_iter", "tile", "weighted"))
def _lloyd_jit(x, x_norms, centers0, weights, tol: float, max_iter: int,
               tile: int, weighted: bool):
    w = weights if weighted else None

    def cond(state):
        i, shift2, *_ = state
        return (i < max_iter) & (shift2 >= tol)

    def body(state):
        i, _, centers = state
        labels, _ = _assign(x, x_norms, centers, tile)
        new_centers, _ = _update(x, labels, centers, w)
        shift2 = jnp.sum((new_centers - centers) ** 2)
        return i + 1, shift2, new_centers

    n_iter, _, centers = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.float32(jnp.inf), centers0)
    )
    labels, d2 = _assign(x, x_norms, centers, tile)
    inertia = jnp.sum(d2 * weights) if weighted else jnp.sum(d2)
    return centers, labels, inertia, n_iter


def fit(
    x,
    params: Optional[KMeansParams] = None,
    init_centers=None,
    sample_weights=None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """K-means fit (reference: kmeans::fit, detail/kmeans.cuh:361).

    Returns (centers, labels, inertia, n_iter). ``n_init`` restarts keep the
    lowest-inertia solution, as in the reference.
    """
    params = params or KMeansParams()
    res = ensure_resources(res)
    if params.metric not in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        raise NotImplementedError("kmeans supports L2 metrics (like the reference)")
    if params.init == InitMethod.Array and init_centers is None:
        raise ValueError("init='array' requires init_centers")
    if init_centers is not None and params.init != InitMethod.Array:
        raise ValueError(
            f"init_centers given but init={params.init.value!r}; use init='array'"
        )
    x = jnp.asarray(x, jnp.float32)
    if params.n_clusters > x.shape[0]:
        raise ValueError(
            f"n_clusters={params.n_clusters} > n_rows={x.shape[0]}"
        )
    xn = row_norms_sq(x)
    weighted = sample_weights is not None
    weights = (jnp.asarray(sample_weights, jnp.float32) if weighted
               else jnp.ones((x.shape[0],), jnp.float32))
    key = jax.random.key(params.seed)
    tile = choose_tile_rows(x.shape[0], params.n_clusters, res.workspace_limit_bytes)

    # Array init is deterministic — extra restarts are identical
    n_init = 1 if params.init == InitMethod.Array else max(params.n_init, 1)
    best = None
    for trial in range(n_init):
        key, kt = jax.random.split(key)
        if params.init == InitMethod.Array:
            c0 = jnp.asarray(init_centers, jnp.float32)
        elif params.init == InitMethod.Random:
            idx = jax.random.choice(kt, x.shape[0], (params.n_clusters,), replace=False)
            c0 = x[idx]
        else:
            c0 = _kmeans_pp_init(kt, x, xn, params.n_clusters)
        centers, labels, inertia, n_iter = _lloyd_jit(
            x, xn, c0, weights, params.tol, params.max_iter, tile, weighted
        )
        if best is None or float(inertia) < float(best[2]):
            best = (centers, labels, inertia, n_iter)
    return best


def predict(centers, x, res: Optional[Resources] = None
            ) -> Tuple[jax.Array, jax.Array]:
    """Nearest-center labels + inertia (reference: kmeans::predict)."""
    res = ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    d2, labels = fused_l2_nn_argmin(x, jnp.asarray(centers, jnp.float32), res=res)
    return labels, jnp.sum(d2)


def fit_predict(x, params: Optional[KMeansParams] = None,
                res: Optional[Resources] = None):
    centers, labels, inertia, n_iter = fit(x, params, res=res)
    return centers, labels


def cluster_cost(x, centers, res: Optional[Resources] = None) -> jax.Array:
    """Sum of squared distances to nearest center (reference:
    kmeans::cluster_cost, detail/kmeans.cuh)."""
    d2, _ = fused_l2_nn_argmin(
        jnp.asarray(x, jnp.float32), jnp.asarray(centers, jnp.float32), res=res
    )
    return jnp.sum(d2)


def update_centroids(
    x,
    centroids,
    sample_weights=None,
    res: Optional[Resources] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One weighted M-step: assign rows to their nearest centroid, then
    return (new_centroids, weight_per_cluster) — parity with
    ``pylibraft.cluster.kmeans.compute_new_centroids`` /
    ``raft::runtime::cluster::kmeans::update_centroids``. Empty clusters
    keep their previous centroid."""
    res = ensure_resources(res)
    x = jnp.asarray(x, jnp.float32)
    centroids = jnp.asarray(centroids, jnp.float32)
    w = (None if sample_weights is None
         else jnp.asarray(sample_weights, jnp.float32))
    tile = choose_tile_rows(x.shape[0], centroids.shape[0],
                            res.workspace_limit_bytes)
    labels, _ = _assign(x, row_norms_sq(x), centroids, tile)
    return _update(x, labels, centroids, w)


def find_k(
    x,
    k_max: int,
    k_min: int = 2,
    params: Optional[KMeansParams] = None,
    res: Optional[Resources] = None,
) -> int:
    """Elbow-style auto-find-k (reference: detail/kmeans_auto_find_k.cuh uses
    a binary search over inertia-vs-k curvature; we scan and pick the knee)."""
    params = params or KMeansParams()
    costs = []
    ks = list(range(k_min, k_max + 1))
    for k in ks:
        p = dataclasses.replace(params, n_clusters=k)
        _, _, inertia, _ = fit(x, p, res=res)
        costs.append(float(inertia))
    # knee = max second difference
    if len(costs) < 3:
        return ks[int(jnp.argmin(jnp.asarray(costs)))]
    import numpy as np

    second = np.diff(costs, 2)
    return ks[int(second.argmax()) + 1]
compute_new_centroids = update_centroids  # pylibraft name
