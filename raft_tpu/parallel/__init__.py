"""Distributed layer (SURVEY.md §2.8): comms facade over XLA mesh
collectives (ICI/DCN), multi-host bootstrap, sharded index build/search."""

__all__ = []
