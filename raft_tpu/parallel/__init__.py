"""Distributed layer (SURVEY.md §2.8): comms facade over XLA mesh
collectives (ICI/DCN), multi-host bootstrap, sharded index build/search."""

from raft_tpu.parallel import comms, host_p2p, sharded
from raft_tpu.parallel.comms import (
    Comms,
    ReduceOp,
    init_comms,
    init_distributed,
    inject_comms,
)
from raft_tpu.parallel.host_p2p import HostP2P

__all__ = ["comms", "host_p2p", "sharded", "Comms", "HostP2P", "ReduceOp",
           "init_comms", "init_distributed", "inject_comms"]
