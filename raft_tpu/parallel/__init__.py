"""Distributed layer (SURVEY.md §2.8): comms facade over XLA mesh
collectives (ICI/DCN), multi-host bootstrap, sharded index build/search."""

from raft_tpu.parallel import comms, sharded
from raft_tpu.parallel.comms import (
    Comms,
    ReduceOp,
    init_comms,
    init_distributed,
    inject_comms,
)

__all__ = ["comms", "sharded", "Comms", "ReduceOp", "init_comms",
           "init_distributed", "inject_comms"]
