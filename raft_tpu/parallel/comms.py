"""Comms — the distributed communication facade over XLA mesh collectives.

Reference: ``raft::comms_t`` (core/comms.hpp:127-661 — virtual comms_iface
with allreduce/bcast/reduce/allgather/gather/reducescatter, device p2p
send/recv, comm_split, sync_stream), its NCCL+UCX implementation
(comms/detail/std_comms.hpp:314-422), the MPI variant (comms/mpi_comms.hpp),
and the Dask bootstrap that injects ``std_comms`` into each worker's handle
(raft_dask/common/comms.py:40).

TPU-native design: the backend is the compiler, not a library. A ``Comms``
object wraps a ``jax.sharding.Mesh`` axis; its collective methods are called
**inside ``shard_map``-decorated functions** and lower to XLA collectives
that ride ICI (intra-pod) / DCN (multi-pod) — psum/all_gather/ppermute do
what ncclAllReduce/ncclAllGather/ncclSend+Recv do, but fused and scheduled
by XLA. The bootstrap role of Dask+NCCL uniqueId rendezvous
(comms.py:138-151) is played by ``jax.distributed.initialize`` +
``jax.devices()`` — ``init_comms`` wraps both the single-process multi-device
case (including the CPU-simulated mesh used in CI — the "mock backend" seam
SURVEY.md §4 calls for) and the true multi-host case.

The reference's ``comms_t`` is injected into ``resources``; ``inject_comms``
mirrors that so algorithms take one ``res`` and find the communicator.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.core.resources import Resources


# ------------------------------------------------------------------ datatypes


class ReduceOp:
    """reference: core/comms.hpp op_t (SUM/PROD/MIN/MAX)."""

    SUM = "sum"
    PROD = "prod"
    MIN = "min"
    MAX = "max"


def _lex_topk(v, pos, i, k: int, select_min: bool):
    """The ``k`` lexicographically-smallest (value, pos) candidates per row,
    sorted — the tie rule ``select_k``'s stable engines implement, made
    explicit so partial merges compose in any order. ``pos`` is each
    candidate's position in the virtual rank-order concatenation (unique,
    so the sort key is a total order and stability is moot)."""
    key = v if select_min else -v
    sv, sp, si = jax.lax.sort((key, pos, i), dimension=1, num_keys=2)
    sv, sp, si = sv[:, :k], sp[:, :k], si[:, :k]
    return (sv if select_min else -sv), sp, si


@dataclasses.dataclass(frozen=True)
class Comms:
    """A communicator = a mesh + the axis it communicates over.

    ``size``/``rank`` mirror comms_t::get_size/get_rank (core/comms.hpp:252).
    The collective methods are *traceable* — call them inside a function run
    via :meth:`run` (shard_map) or your own shard_map/pjit.
    """

    mesh: Mesh
    axis: str = "data"

    # ---- topology ---------------------------------------------------------
    @property
    def size(self) -> int:
        return self.mesh.shape[self.axis]

    def rank(self) -> jax.Array:
        """Per-shard rank — traced value, valid inside shard_map (analog of
        get_rank, core/comms.hpp:257)."""
        return jax.lax.axis_index(self.axis)

    # ---- collectives (traceable; inside shard_map) ------------------------
    def allreduce(self, x, op: str = ReduceOp.SUM):
        """ncclAllReduce analog (std_comms.hpp:314) → psum/pmax/pmin lowered
        onto ICI."""
        if op == ReduceOp.SUM:
            return jax.lax.psum(x, self.axis)
        if op == ReduceOp.MAX:
            return jax.lax.pmax(x, self.axis)
        if op == ReduceOp.MIN:
            return jax.lax.pmin(x, self.axis)
        if op == ReduceOp.PROD:
            # gather + prod: exact for zeros/negatives (a log-psum trick
            # would NaN); PROD traffic is rare so the extra bytes are fine
            g = jax.lax.all_gather(x, self.axis)
            return jax.tree.map(lambda a: jnp.prod(a, axis=0), g)
        raise ValueError(f"unknown reduce op {op!r}")

    def allgather(self, x, axis: int = 0, tiled: bool = True):
        """ncclAllGather analog (std_comms.hpp:~360): concatenate shards
        along ``axis``."""
        return jax.lax.all_gather(x, self.axis, axis=axis, tiled=tiled)

    def reducescatter(self, x, scatter_dimension: int = 0):
        """ncclReduceScatter analog: sum across ranks, scatter along dim."""
        return jax.lax.psum_scatter(
            x, self.axis, scatter_dimension=scatter_dimension, tiled=True)

    def bcast(self, x, root: int = 0):
        """ncclBroadcast analog: every rank gets root's value. On a mesh the
        value is materialized on all ranks already; select root's shard."""
        gathered = jax.lax.all_gather(x, self.axis)
        return jax.tree.map(lambda g: g[root], gathered)

    def reduce(self, x, root: int = 0, op: str = ReduceOp.SUM):
        """ncclReduce analog: full reduction, non-root ranks get zeros (the
        typed comms_t contract only defines the root's value)."""
        full = self.allreduce(x, op)
        is_root = jax.lax.axis_index(self.axis) == root
        return jax.tree.map(lambda f: jnp.where(is_root, f, jnp.zeros_like(f)),
                            full)

    def gather(self, x, root: int = 0):
        """ncclGather analog — allgather then non-root zeroing (XLA has no
        rooted gather; the extra ICI traffic is negligible vs the fusion
        win)."""
        g = jax.lax.all_gather(x, self.axis)
        is_root = jax.lax.axis_index(self.axis) == root
        return jax.tree.map(lambda f: jnp.where(is_root, f, jnp.zeros_like(f)),
                            g)

    def allgatherv(self, x, counts: Sequence[int], axis: int = 0):
        """ncclAllGatherv-equivalent (core/comms.hpp allgatherv): shards
        contribute ``counts[rank]`` valid rows each (the rest of the static
        shard is padding). Returns the concatenation of every rank's valid
        rows, padded to sum(counts) with trailing zeros removed by the
        caller if needed. ``counts`` must be host-known (static shapes)."""
        counts = [int(c) for c in counts]
        cap = x.shape[axis]
        if max(counts) > cap:
            raise ValueError(f"counts {counts} exceed shard capacity {cap}")
        g = jax.lax.all_gather(x, self.axis)  # [size, ...]
        parts = [jax.lax.index_in_dim(g, r, axis=0, keepdims=False)
                 for r in range(self.size)]
        parts = [jax.lax.slice_in_dim(p, 0, counts[r], axis=axis)
                 for r, p in enumerate(parts)]
        return jnp.concatenate(parts, axis=axis)

    def gatherv(self, x, counts: Sequence[int], root: int = 0,
                axis: int = 0):
        """ncclGatherv analog: allgatherv, non-root ranks zeroed (the typed
        comms_t contract defines only the root's value)."""
        full = self.allgatherv(x, counts, axis=axis)
        is_root = jax.lax.axis_index(self.axis) == root
        return jax.tree.map(
            lambda f: jnp.where(is_root, f, jnp.zeros_like(f)), full)

    def device_send_recv(self, x, dest_of_rank: Sequence[int]):
        """device_sendrecv analog (core/comms.hpp device p2p): rank r's value
        is delivered to ``dest_of_rank[r]``; every rank receives from the
        rank that names it. The table must be a permutation (XLA ppermute
        contract — matching pairwise send/recv like the reference's
        group_start/end blocks)."""
        dests = [int(d) for d in dest_of_rank]
        if sorted(dests) != list(range(self.size)):
            raise ValueError(f"dest table {dests} is not a permutation")
        return jax.lax.ppermute(x, self.axis,
                                perm=[(r, d) for r, d in enumerate(dests)])

    def device_multicast_sendrecv(self, x, root: int, dests: Sequence[int]):
        """device_multicast_sendrecv analog: ``root``'s value is delivered to
        every rank in ``dests``; other ranks keep their own value (multicast
        over ICI is an allgather+select the compiler prunes)."""
        g = jax.lax.all_gather(x, self.axis)  # [size, ...]
        me = jax.lax.axis_index(self.axis)
        in_dests = jnp.zeros((self.size,), bool
                             ).at[jnp.asarray(list(dests))].set(True)[me]
        return jax.tree.map(
            lambda gg: jnp.where(in_dests, gg[root], gg[me]), g)

    def ppermute(self, x, perm: Sequence[tuple[int, int]]):
        """device_sendrecv analog (core/comms.hpp device p2p): point-to-point
        pairs (src, dst) as one fused ICI permute."""
        return jax.lax.ppermute(x, self.axis, perm=list(perm))

    def shift(self, x, offset: int = 1):
        """Ring shift by ``offset`` — the p2p pattern ring algorithms use."""
        n = self.size
        perm = [(i, (i + offset) % n) for i in range(n)]
        return jax.lax.ppermute(x, self.axis, perm=perm)

    def alltoall(self, x):
        """ncclAllToAll analog: x [size, ...] per rank → transpose across
        ranks (used by all-to-all sequence/context parallelism)."""
        return jax.lax.all_to_all(x, self.axis, split_axis=0, concat_axis=0,
                                  tiled=False)

    # ---- streaming cross-chip top-k merge (traceable; inside shard_map) ----
    #
    # The MNMG merge (knn_merge_parts across ranks) without the all_gather
    # slab: candidates are tagged with their position in the virtual
    # rank-order concatenation and merged by lexicographic (value, pos)
    # selection. Stable-by-position selection is associative AND
    # commutative over candidate sets, so any merge order — hypercube
    # tree, neighbor ring — produces the identical replicated output, and
    # that output is bit-identical to ``select_k(allgather(v), k)`` + id
    # gather (select_k's engines are all position-stable on ties: DIRECT
    # is lax.top_k, TWO_PHASE merges tile-ordered survivors, SCREEN sorts
    # (value, pos) stably). Peak cross-chip bytes drop from S·nq·kk to
    # nq·k·log₂S (tree) / nq·kk per step (ring).

    def tree_topk_merge(self, v, i, k: int, select_min: bool = True):
        """Hypercube top-k merge in log₂(size) ``ppermute`` rounds.

        ``v``/``i`` are this shard's [nq, kk] candidates (ids global;
        invalid candidates must already carry ±inf values). Each round
        exchanges carries with the rank's XOR partner and re-selects down
        to ``min(k, candidates_so_far)`` — live candidate sets halve each
        round while per-device carry bytes stay O(nq·k). Requires a
        power-of-two ``size`` (the dispatch layer falls back to
        all_gather otherwise). Returns replicated (values, ids) of width
        ``min(k, size·kk)``, bit-identical to the all_gather merge."""
        size = self.size
        if size & (size - 1):
            raise ValueError(f"tree merge needs a power-of-two mesh axis, "
                             f"got size={size}")
        nq, kk = v.shape
        k_out = min(int(k), size * kk)
        pos0 = self.rank() * kk + jnp.arange(kk, dtype=jnp.int32)
        cv, cp, ci = v, jnp.broadcast_to(pos0[None, :], (nq, kk)), i
        width = kk
        step = 1
        while step < size:
            perm = [(r, r ^ step) for r in range(size)]
            pv = self.ppermute(cv, perm)
            pp = self.ppermute(cp, perm)
            pi = self.ppermute(ci, perm)
            width = min(k_out, 2 * width)
            cv, cp, ci = _lex_topk(
                jnp.concatenate([cv, pv], axis=1),
                jnp.concatenate([cp, pp], axis=1),
                jnp.concatenate([ci, pi], axis=1), width, select_min)
            step *= 2
        if size == 1:  # no rounds ran: still honor the sort+truncate contract
            cv, cp, ci = _lex_topk(cv, cp, ci, k_out, select_min)
        return cv, ci

    def ring_topk_merge(self, v, i, k: int, select_min: bool = True,
                        shift=None):
        """Neighbor-ring top-k merge: size-1 steps, each rotating the
        ORIGINAL [nq, kk] candidate block one hop while folding the block
        received last step into the local carry — the streaming schedule
        whose per-step traffic (one fixed-shape block to one neighbor) a
        ``make_async_remote_copy`` kernel can overlap with the local
        probe-tile scan. ``shift`` maps one packed [3, nq, kk] f32 buffer
        to its +1 ring rotation (default: XLA ``ppermute``; the Pallas
        RDMA kernel slots in here). Works for any ``size``. Returns
        replicated (values, ids) of width ``min(k, size·kk)``,
        bit-identical to the all_gather merge (the lex merge is
        commutative, so per-device rotation order doesn't matter)."""
        size = self.size
        nq, kk = v.shape
        k_out = min(int(k), size * kk)
        if shift is None:
            shift = functools.partial(self.shift, offset=1)
        if v.dtype != jnp.float32:
            raise ValueError(f"ring merge packs candidates as float32 "
                             f"words, got values dtype {v.dtype}")
        pos0 = self.rank() * kk + jnp.arange(kk, dtype=jnp.int32)
        pos = jnp.broadcast_to(pos0[None, :], (nq, kk))
        block = jnp.stack([
            v, jax.lax.bitcast_convert_type(pos, jnp.float32),
            jax.lax.bitcast_convert_type(i.astype(jnp.int32), jnp.float32)])
        cv, cp, ci = _lex_topk(v, pos, i, min(k_out, kk), select_min)
        for s in range(size - 1):
            block = shift(block)
            bv = block[0]
            bp = jax.lax.bitcast_convert_type(block[1], jnp.int32)
            bi = jax.lax.bitcast_convert_type(block[2], jnp.int32)
            cv, cp, ci = _lex_topk(
                jnp.concatenate([cv, bv], axis=1),
                jnp.concatenate([cp, bp], axis=1),
                jnp.concatenate([ci, bi], axis=1),
                min(k_out, (s + 2) * kk), select_min)
        return cv, ci

    # ---- split ------------------------------------------------------------
    def comm_split(self, color_axis: str) -> "Comms":
        """comms_t::comm_split analog (std_comms.hpp:156-162): a communicator
        over another mesh axis (the mesh factorization IS the color/key)."""
        if color_axis not in self.mesh.axis_names:
            raise ValueError(f"axis {color_axis!r} not in mesh "
                             f"{self.mesh.axis_names}")
        return Comms(self.mesh, color_axis)

    # ---- host-side helpers -------------------------------------------------
    def run(self, fn: Callable, in_specs, out_specs, check_vma: bool = False):
        """shard_map ``fn`` over this comms' mesh (the "enqueue a collective
        program" entry point; analog of launching NCCL ops on the handle's
        stream)."""
        if hasattr(jax, "shard_map"):
            return jax.shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma)
        # jax < 0.6: shard_map lives in jax.experimental and the replication
        # check is spelled check_rep
        from jax.experimental.shard_map import shard_map as _shard_map

        return _shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)

    def shard(self, x, spec: P):
        """Place ``x`` with a NamedSharding on this mesh. In a
        multi-controller deployment the host value (assumed identical on
        every process, like queries broadcast in raft-dask) is sliced
        per-process via ``make_array_from_callback`` — ``device_put`` of a
        host array onto a global sharding is single-controller-only."""
        sharding = NamedSharding(self.mesh, spec)
        if jax.process_count() > 1:
            arr = np.asarray(x)
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx])
        return jax.device_put(x, sharding)

    def sync(self, *arrays) -> None:
        """sync_stream analog: block on arrays / fence dispatch."""
        if arrays:
            for a in jax.tree_util.tree_leaves(arrays):
                if isinstance(a, jax.Array):
                    a.block_until_ready()
        else:
            jax.effects_barrier()


# ------------------------------------------------------------------ bootstrap


def init_comms(
    devices: Optional[Sequence[jax.Device]] = None,
    axis: str = "data",
    mesh_shape: Optional[Sequence[int]] = None,
    axis_names: Optional[Sequence[str]] = None,
) -> Comms:
    """Build a communicator from local (or all-process) devices.

    The role of raft-dask's ``Comms.init`` (raft_dask/common/comms.py:173):
    on a multi-host deployment call ``jax.distributed.initialize`` first
    (the NCCL-uniqueId rendezvous analog); here the device list already spans
    hosts. With ``mesh_shape``/``axis_names`` a multi-axis mesh is built
    (axis 0 is the comms axis unless ``axis`` says otherwise).
    """
    devs = list(devices) if devices is not None else jax.devices()
    if mesh_shape is None:
        mesh = Mesh(np.array(devs), (axis,))
    else:
        names = tuple(axis_names) if axis_names else tuple(
            f"ax{i}" if i else axis for i in range(len(mesh_shape)))
        if axis not in names:
            raise ValueError(
                f"comms axis {axis!r} not in axis_names {names}")
        mesh = Mesh(np.array(devs).reshape(tuple(mesh_shape)), names)
    return Comms(mesh, axis)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    axis: str = "data",
) -> Comms:
    """Multi-host bootstrap: ``jax.distributed.initialize`` + global-device
    mesh (the jax-native analog of NCCL-uniqueId + Dask RPC rendezvous,
    raft_dask/common/comms.py:138-151)."""
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address, num_processes,
                                   process_id)
    return init_comms(jax.devices(), axis=axis)


def inject_comms(res: Resources, comms: Comms) -> Resources:
    """Attach a communicator to a Resources (analog of
    ``inject_comms_on_handle`` — raft_dask common/comms_utils.pyx:258)."""
    res._comms = comms
    res.mesh = comms.mesh
    return res


# ------------------------------------------------------------------ self-test


def test_collective_allreduce(comms: Comms) -> bool:
    """Smoke tests mirroring raft::comms::test_collective_* helpers
    (comms/comms_test.hpp:34-156) — callable from any deployment to verify
    the comms fabric."""
    x = jnp.ones((comms.size, 8), jnp.float32)
    x = comms.shard(x, P(comms.axis))

    def body(xs):
        return comms.allreduce(jnp.sum(xs))

    out = jax.jit(comms.run(body, P(comms.axis), P()))(x)
    return bool(np.isclose(float(out), comms.size * 8))


def test_collective_allgather(comms: Comms) -> bool:
    x = jnp.arange(comms.size, dtype=jnp.float32)[:, None]
    x = comms.shard(x, P(comms.axis))

    def body(xs):
        return comms.allgather(xs)

    out = jax.jit(comms.run(body, P(comms.axis), P()))(x)
    return bool(np.allclose(np.asarray(out).ravel(), np.arange(comms.size)))


def test_collective_reducescatter(comms: Comms) -> bool:
    x = jnp.ones((comms.size, comms.size), jnp.float32)
    x = comms.shard(x, P(comms.axis))

    def body(xs):
        return comms.reducescatter(xs[0])

    out = jax.jit(comms.run(body, P(comms.axis), P(comms.axis)))(x)
    return bool(np.allclose(np.asarray(out), comms.size))


def test_pointToPoint_simple_send_recv(comms: Comms) -> bool:
    """Ring send/recv analog of comms_test.hpp send_recv tests."""
    x = jnp.arange(comms.size, dtype=jnp.float32)[:, None]
    x = comms.shard(x, P(comms.axis))

    def body(xs):
        return comms.shift(xs, 1)

    out = np.asarray(jax.jit(comms.run(body, P(comms.axis), P(comms.axis)))(x))
    want = np.roll(np.arange(comms.size), 1)
    return bool(np.allclose(out.ravel(), want))
