"""Host async point-to-point — the UCX role of the reference comms stack.

Reference: ``comms_t::isend/irecv/waitall`` (core/comms.hpp:137-141), whose
std_comms implementation runs host-side async messaging over UCX endpoints
(comms/detail/std_comms.hpp:211-253, detail/ucp_helper.hpp) alongside
NCCL's device collectives. Consumers use it to overlap host-side data
exchange (metadata, ragged buffers, dataset spans) with device compute —
the raft-dask pattern.

TPU-native design: device traffic rides XLA collectives over ICI/DCN
(:mod:`raft_tpu.parallel.comms`); this module supplies the *host* channel
as plain TCP — no external dependency, usable across the hosts of a
jax.distributed deployment (each process listens on its ``peers`` entry).
Requests mirror the reference's ``request_t`` handles: ``isend``/``irecv``
return immediately; ``waitall`` blocks on any mix of them.

Ordering contract (matches MPI/UCX non-overtaking semantics): sends to one
destination run on that destination's dedicated sender thread over one
persistent connection, and the receiver matches messages to pending
``irecv`` requests in post order — two isends with the same (dest, tag)
are received in the order they were posted.

Message framing: [i32 magic][i32 src][i32 tag][u64 nbytes][type byte]
[payload]. ndarray payloads carry a dtype/shape header (npy) so they
reconstruct on the receiving side; raw ``bytes`` pass through untouched.

Request/response support (the serving remote-replica proxy rides this):
``correlation_id()`` allocates tags from a reserved range
(``>= _CORR_BASE``) so an RPC reply can be matched to exactly one
outstanding request without colliding with user tags; ``discard()``
drops an abandoned correlation's state so late replies cannot
accumulate in the inbox. ``announce_drain(dest)`` sends a control frame
that tells the peer "nothing more is coming from me — this is a clean
goodbye": the receiver fails that source's pending irecvs with the
typed :class:`PeerDrained` (not a presumed death), suppresses the
peer-death grace timer for the EOF that follows, and fails later
irecvs from that source immediately instead of waiting out the
timeout. A new delivery from the source (a restarted process) clears
the drained verdict.
"""

from __future__ import annotations

import collections
import errno
import io
import itertools
import os
import queue
import random
import selectors
import socket
import struct
import threading
import time
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

from raft_tpu.core import logger
from raft_tpu.obs import metrics as obs_metrics

_MAGIC = 0x52465450  # "RFTP"
_HDR = struct.Struct("<iiiQ")

#: control-frame tag: graceful drain announcement (never delivered to an
#: irecv — intercepted in _deliver)
_DRAIN_TAG = -2

#: correlation tags live at and above this value; user tags should stay
#: below it (the allocator wraps inside [_CORR_BASE, _CORR_LIMIT))
_CORR_BASE = 1 << 20
_CORR_LIMIT = 1 << 30

# fabric counters (docs/observability.md), labeled by the REMOTE rank:
# `peer` is the destination for send-side families, the source for
# receive-side ones — so one scrape shows which link is sick
_SENT_MSGS = obs_metrics.REGISTRY.counter(
    "raft_tpu_p2p_messages_sent_total",
    "Frames delivered to a peer (after any retries).", ("peer",))
_SENT_BYTES = obs_metrics.REGISTRY.counter(
    "raft_tpu_p2p_bytes_sent_total",
    "Wire bytes sent (header + type byte + payload).", ("peer",))
_RECV_MSGS = obs_metrics.REGISTRY.counter(
    "raft_tpu_p2p_messages_received_total",
    "Frames received from a peer.", ("peer",))
_RECV_BYTES = obs_metrics.REGISTRY.counter(
    "raft_tpu_p2p_bytes_received_total",
    "Wire bytes received (header + type byte + payload).", ("peer",))
_SEND_RETRIES = obs_metrics.REGISTRY.counter(
    "raft_tpu_p2p_send_retries_total",
    "Send attempts that failed and were retried with backoff.", ("peer",))
_BACKOFF_SECONDS = obs_metrics.REGISTRY.counter(
    "raft_tpu_p2p_backoff_seconds_total",
    "Cumulative seconds slept in send retry backoff.", ("peer",))
_STREAMS_POISONED = obs_metrics.REGISTRY.counter(
    "raft_tpu_p2p_streams_poisoned_total",
    "Send streams poisoned after exhausting retries.", ("peer",))
_PEER_DEATHS = obs_metrics.REGISTRY.counter(
    "raft_tpu_p2p_peer_deaths_total",
    "Peer-death verdicts (grace timer expiry or mark_peer_dead).",
    ("peer",))


class _EndpointClosed(ConnectionError):
    """Sentinel for "the endpoint closed while this operation was in
    flight". A distinct class because Python maps OSError(ECONNREFUSED/
    ECONNRESET, ...) to ConnectionRefused/ResetError — ConnectionError
    subclasses — so `except ConnectionError` would also swallow ordinary
    refused connects."""


class PeerDrained(ConnectionError):
    """The peer announced a graceful drain (``announce_drain``): nothing
    more will arrive from it, by design. A typed, *clean* verdict — the
    serving proxy maps it to a retry-on-sibling, distinct from the
    presumed-death ConnectionError the grace timer raises."""


class Request:
    """An in-flight isend/irecv (the request_t analog). ``wait`` blocks
    until completion and, for receives, returns the payload. A receive
    whose ``wait`` times out is cancelled: the message it would have
    matched goes to the next ``irecv`` instead of being lost.

    ``wait()`` with no explicit timeout uses the ENDPOINT's timeout as a
    real deadline (raising TimeoutError) rather than blocking forever — a
    dead peer costs a bounded wait, never a hung serving process.

    Deadlines are computed against the endpoint's injectable ``clock``
    (the same seam the fake-clock batcher tests use): with the default
    ``time.monotonic`` the wait is a single blocking ``Event.wait``;
    with an injected clock it polls short real slices against the
    injected time so a test can advance the deadline synthetically."""

    def __init__(self, kind: str, lock: threading.Lock,
                 default_timeout: Optional[float] = None,
                 clock=time.monotonic):
        self.kind = kind
        self._lock = lock  # endpoint matching lock
        self._default_timeout = default_timeout
        self._clock = clock
        self._done = threading.Event()
        self._cancelled = False
        self._value = None
        self._error: Optional[BaseException] = None

    def _finish(self, value=None, error: Optional[BaseException] = None):
        self._value = value
        self._error = error
        self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def _wait_done(self, timeout: Optional[float]) -> bool:
        if timeout is None:
            self._done.wait()
            return True
        if self._clock is time.monotonic:
            return self._done.wait(timeout)
        # injected clock: real-time slices, injected-time deadline
        deadline = self._clock() + timeout
        while True:
            if self._done.wait(0.02):
                return True
            if self._clock() >= deadline:
                return False

    def wait(self, timeout: Optional[float] = None):
        if timeout is None:
            timeout = self._default_timeout
        if not self._wait_done(timeout):
            with self._lock:
                if not self._done.is_set():  # lost the race with delivery?
                    self._cancelled = True
                    raise TimeoutError(
                        f"{self.kind} request timed out after {timeout}s")
        if self._error is not None:
            raise self._error
        return self._value


def _encode(payload) -> Tuple[bytes, bytes]:
    """→ (type tag, wire bytes). Arrays keep dtype/shape; bytes pass raw."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return b"B", bytes(payload)
    arr = np.asarray(payload)
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return b"A", buf.getvalue()


def _decode(tag: bytes, raw: bytes):
    if tag == b"B":
        return raw
    return np.load(io.BytesIO(raw), allow_pickle=False)


def _drain_queue(q: "queue.Queue", error: BaseException) -> None:
    """Fail every request still sitting in a sender queue. Safe to call
    from multiple threads: Queue.get_nowait is atomic, so each request is
    finished exactly once."""
    while True:
        try:
            req = q.get_nowait()[0]
        except queue.Empty:
            return
        req._finish(error=error)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            raise ConnectionError("peer closed mid-message")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


class HostP2P:
    """One endpoint of the host p2p fabric (one per rank/process).

    ``peers``: (host, port) per rank. ``peers=None`` → all-localhost at
    ``base_port + r`` (single-host multiprocess, and the CI shape).

    Fault model (docs/robustness.md): a failed connect/send is RETRIED up
    to ``retries`` times with exponential backoff + jitter before the
    stream poisons (``retries=0`` restores strict fail-fast). Retried
    sends are at-least-once: a frame cut mid-send is resent whole on a
    fresh connection, so a crash window can deliver a message twice —
    receivers that care must dedup by tag/sequence. ``wait``/``waitall``
    default to the endpoint ``timeout`` as a hard deadline (TimeoutError,
    never a hang). A connection that drops MID-FRAME starts a
    ``peer_grace`` timer on the receiver; if the peer has not delivered
    again when it fires, every pending ``irecv`` from that source fails
    with ConnectionError (a reconnect in the window cancels the verdict —
    it was a sender retry, not a death).
    """

    def __init__(self, rank: int, size: int,
                 peers: Optional[Sequence[Tuple[str, int]]] = None,
                 base_port: int = 41300, timeout: float = 120.0,
                 retries: int = 3, retry_backoff: float = 0.05,
                 retry_backoff_max: float = 2.0, peer_grace: float = 2.0,
                 clock=time.monotonic):
        self.rank = int(rank)
        self.size = int(size)
        self.timeout = timeout
        self.retries = int(retries)
        self.retry_backoff = float(retry_backoff)
        self.retry_backoff_max = float(retry_backoff_max)
        self.peer_grace = float(peer_grace)
        # every deadline in the endpoint (wait/waitall, the connect
        # handshake, the peer-grace window) is computed on this clock —
        # the same injectable seam the fake-clock Batcher tests use
        self._clock = clock
        self.peers = (list(peers) if peers is not None
                      else [("127.0.0.1", base_port + r)
                            for r in range(size)])
        if len(self.peers) != size:
            raise ValueError(f"{len(self.peers)} peers for size {size}")
        # receiver matching state, all under one lock: FIFO inbox of
        # unclaimed messages + FIFO queue of waiting irecvs per (src, tag)
        self._match_lock = threading.Lock()
        # (src, tag) -> deque of payloads
        self._inbox: dict = {}  # guarded_by: _match_lock
        # (src, tag) -> deque of Requests
        self._waiting: dict = {}  # guarded_by: _match_lock
        # per-src delivery generation counters: an abnormal connection
        # drop schedules a grace check against the generation at drop
        # time — any later delivery proves the peer (or its retry) is
        # alive and voids the death verdict
        self._peer_gen: dict = {}  # guarded_by: _match_lock
        # sources that announced a graceful drain (module docstring):
        # their EOF is clean and their pending irecvs fail PeerDrained
        self._drained: set = set()  # guarded_by: _match_lock
        # per-destination sender worker: one persistent connection, FIFO
        self._send_queues: dict = {}  # guarded_by: _send_lock
        self._send_lock = threading.Lock()
        # dest -> live outbound socket (test hook _sever_send cuts it)
        self._active_send: dict = {}  # guarded_by: _send_lock
        # dest -> poisoning error; reset_stream() clears it so a healed
        # link can carry traffic again (the caller acknowledges the gap)
        self._poison: dict = {}  # guarded_by: _send_lock
        # injected-fault state (testing.faults.partition_hosts /
        # delay_link): replaced wholesale under _send_lock; hot-path
        # reads are lock-free attribute loads of the immutable values
        self._partitioned: frozenset = frozenset()
        self._link_delay: dict = {}
        # correlation-tag allocator (itertools.count is C-atomic)
        self._corr = itertools.count()
        # live accepted connections (see close())
        self._conns: set = set()  # guarded_by: _conns_lock
        self._conns_lock = threading.Lock()
        self._closed = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        bind_host = self.peers[self.rank][0] if peers is not None \
            else "127.0.0.1"
        self._listener.bind((bind_host, self.peers[self.rank][1]))
        self._listener.listen(size * 4)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"raft-tpu-hostp2p-{rank}")
        self._accept_thread.start()

    # ------------------------------------------------------------- receive
    def _accept_loop(self):
        while not self._closed.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            with self._conns_lock:
                if self._closed.is_set():  # raced with close(): reap now
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        """One thread per inbound connection; messages on a connection are
        delivered in arrival order (TCP preserves the sender's order).

        A connection that ends CLEANLY at a frame boundary is a normal
        disconnect. One that cuts mid-frame (partial header/payload,
        reset) is ABNORMAL: the sender likely died mid-send — schedule a
        peer-death check so its pending irecvs fail after ``peer_grace``
        instead of waiting out the full endpoint timeout."""
        last_src = None
        abnormal = False
        try:
            with conn:
                while True:
                    hdr = conn.recv(_HDR.size, socket.MSG_WAITALL)
                    if not hdr:
                        return  # clean EOF at a frame boundary
                    if len(hdr) < _HDR.size:
                        abnormal = True  # cut mid-header
                        return
                    magic, src, tag, nbytes = _HDR.unpack(hdr)
                    if magic != _MAGIC:
                        raise ConnectionError("bad frame magic")
                    last_src = src
                    ty = _read_exact(conn, 1)
                    raw = _read_exact(conn, nbytes)
                    _RECV_MSGS.labels(src).inc()
                    _RECV_BYTES.labels(src).inc(_HDR.size + 1 + nbytes)
                    self._deliver(src, tag, _decode(ty, raw))
        except (ConnectionError, OSError):
            abnormal = True
            return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            if (abnormal and last_src is not None
                    and not self._closed.is_set()
                    and not self._is_drained(last_src)):
                self._schedule_peer_check(last_src)

    def _is_drained(self, src: int) -> bool:
        with self._match_lock:
            return src in self._drained

    def _deliver(self, src: int, tag: int, payload):
        if src in self._partitioned:
            return  # injected partition: inbound half of the cut
        if tag == _DRAIN_TAG:
            self._handle_drain(src)
            return
        with self._match_lock:
            self._peer_gen[src] = self._peer_gen.get(src, 0) + 1
            self._drained.discard(src)  # delivering again — alive
            waiting = self._waiting.get((src, tag))
            while waiting:
                req = waiting.popleft()
                if not req._cancelled:
                    req._finish(payload)
                    return
            self._inbox.setdefault((src, tag),
                                   collections.deque()).append(payload)

    def _handle_drain(self, src: int) -> None:
        """Graceful-drain control frame: fail this source's pending
        irecvs with the typed :class:`PeerDrained`, void any in-flight
        death verdict (the goodbye proves the peer was alive), and
        remember the drain so the EOF that follows is clean."""
        with self._match_lock:
            self._peer_gen[src] = self._peer_gen.get(src, 0) + 1
            self._drained.add(src)
            self._fail_src_locked(src, PeerDrained(
                f"peer rank {src} announced a graceful drain"))
        logger.info("host_p2p rank %d: peer rank %d drained gracefully",
                    self.rank, src)

    # ----------------------------------------------------------- peer death
    def _schedule_peer_check(self, src: int) -> None:
        with self._match_lock:
            gen = self._peer_gen.get(src, 0)
        t = threading.Thread(
            target=self._grace_wait, args=(src, gen), daemon=True,
            name=f"raft-tpu-p2p-grace-{self.rank}-{src}")
        t.start()

    def _grace_wait(self, src: int, gen: int) -> None:
        """Sleep out the grace window on the endpoint clock, observing
        ``_closed`` (a plain threading.Timer observes neither the clock
        seam nor close(), so a fake-clock test could never expire it and
        close() could leak a pending verdict)."""
        deadline = self._clock() + self.peer_grace
        while not self._closed.is_set():
            remaining = deadline - self._clock()
            if remaining <= 0:
                self._peer_check(src, gen)
                return
            # injected clock: short real slices so synthetic time
            # advances are observed promptly
            slice_s = remaining if self._clock is time.monotonic \
                else min(remaining, 0.02)
            if self._closed.wait(slice_s):
                return

    def _peer_check(self, src: int, gen: int) -> None:
        """Grace timer body: if ``src`` has delivered nothing since the
        abnormal drop, presume it dead; a sender retry that reconnected in
        the window bumped the generation and voids the verdict."""
        if self._closed.is_set():
            return
        with self._match_lock:
            if self._peer_gen.get(src, 0) != gen:
                return  # delivered again — alive (retry/reconnect)
            self._fail_src_locked(src, ConnectionError(
                f"peer rank {src} presumed dead: connection dropped "
                f"mid-frame and nothing arrived within "
                f"peer_grace={self.peer_grace}s"))
        _PEER_DEATHS.labels(src).inc()
        logger.warn(
            "host_p2p rank %d: peer rank %d presumed dead (dropped "
            "mid-frame, nothing delivered within peer_grace=%.1fs)",
            self.rank, src, self.peer_grace)

    def mark_peer_dead(self, src: int,
                       error: Optional[BaseException] = None) -> None:
        """Fail every pending ``irecv`` from ``src`` now (an external
        failure detector — a cluster manager, a died subprocess — can
        short-circuit the grace window)."""
        with self._match_lock:
            self._fail_src_locked(src, error or ConnectionError(
                f"peer rank {src} marked dead"))
        _PEER_DEATHS.labels(src).inc()
        logger.warn("host_p2p rank %d: peer rank %d marked dead (%s)",
                    self.rank, src, error or "external failure detector")

    def _fail_src_locked(self, src: int, error: BaseException) -> None:
        for key in [k for k in self._waiting if k[0] == src]:
            for req in self._waiting.pop(key):
                if not req._cancelled:
                    req._finish(error=error)

    def irecv(self, source: int, tag: int = 0) -> Request:
        """Non-blocking receive (comms_t::irecv, core/comms.hpp:140);
        ``req.wait()`` returns the payload. Requests posted earlier match
        earlier messages (non-overtaking)."""
        if self._closed.is_set():
            raise ConnectionError("irecv on a closed HostP2P endpoint")
        req = Request("irecv", self._match_lock,
                      default_timeout=self.timeout, clock=self._clock)
        with self._match_lock:
            box = self._inbox.get((source, tag))
            if box:
                req._finish(box.popleft())
            elif self._closed.is_set():  # raced with close(): fail bounded
                req._finish(error=ConnectionError(
                    "HostP2P closed with receive outstanding"))
            elif source in self._drained:
                # the peer said goodbye: its message can never arrive —
                # fail now, typed, instead of waiting out the timeout
                req._finish(error=PeerDrained(
                    f"peer rank {source} announced a graceful drain"))
            else:
                self._waiting.setdefault(
                    (source, tag), collections.deque()).append(req)
        return req

    def discard(self, source: int, tag: int) -> int:
        """Drop any unclaimed inbox messages and cancelled waiters for
        ``(source, tag)`` — the cleanup half of the correlation-id
        protocol: an RPC client that abandons a request (deadline spent,
        replica written off) calls this so a late reply cannot sit in
        the inbox forever. Returns the number of messages dropped."""
        with self._match_lock:
            box = self._inbox.pop((source, tag), None)
            waiting = self._waiting.get((source, tag))
            if waiting is not None:
                live = collections.deque(
                    r for r in waiting if not r._cancelled)
                if live:
                    self._waiting[(source, tag)] = live
                else:
                    self._waiting.pop((source, tag), None)
        return len(box) if box else 0

    def correlation_id(self) -> int:
        """Allocate a fresh tag from the reserved correlation range —
        the request/response matching primitive: the requester posts
        ``irecv(source=peer, tag=cid)`` before sending, the responder
        echoes the cid as the reply tag, and the reply can match
        nothing else. Wraps inside [2**20, 2**30); user tags should
        stay below the base."""
        span = _CORR_LIMIT - _CORR_BASE
        return _CORR_BASE + (next(self._corr) % span)

    # ---------------------------------------------------------------- send
    def _sender_for(self, dest: int) -> "queue.Queue":
        with self._send_lock:
            q = self._send_queues.get(dest)
            if q is None:
                q = queue.Queue()
                self._send_queues[dest] = q
                threading.Thread(target=self._send_loop, args=(dest, q),
                                 daemon=True,
                                 name=f"raft-tpu-p2p-send-{dest}").start()
            return q

    def _connect(self, dest: int) -> socket.socket:
        """Open the persistent connection to ``dest``. The handshake runs
        as a non-blocking connect polled in short slices that observe
        ``_closed`` — closing an fd from another thread does NOT wake a
        thread already blocked inside poll on Linux, so a plain blocking
        connect could stall an in-flight isend's wait() for up to
        ``timeout`` after close() returned. Sockets register in ``_conns``
        so close() reaps them. Like socket.create_connection, every
        getaddrinfo result (v4 and v6) is tried before giving up."""
        if dest in self._partitioned:
            raise OSError(errno.EHOSTUNREACH,
                          f"rank {dest} partitioned (injected fault)")
        host, port = self.peers[dest]
        last_err: Optional[BaseException] = None
        for family, stype, proto, _, addr in socket.getaddrinfo(
                host, port, socket.AF_UNSPEC, socket.SOCK_STREAM):
            sock = socket.socket(family, stype, proto)
            with self._conns_lock:
                if self._closed.is_set():
                    sock.close()
                    raise _EndpointClosed("HostP2P closed")
                self._conns.add(sock)
            try:
                self._handshake(sock, addr, dest)
                return sock
            except _EndpointClosed:
                self._drop_conn(sock)
                raise  # closed mid-connect: don't try further addresses
            except (OSError, TimeoutError) as e:
                self._drop_conn(sock)
                last_err = e
        raise last_err if last_err is not None else OSError(
            f"getaddrinfo returned no addresses for {host}:{port}")

    def _wait_writable(self, sel: "selectors.BaseSelector") -> bool:
        """One poll slice of the handshake (the socket is registered once
        per connect — not one epoll fd per slice). close() may reap the
        socket concurrently — polling a dead fd maps to _EndpointClosed."""
        try:
            return bool(sel.select(0.25))
        except (ValueError, OSError):
            if self._closed.is_set():
                raise _EndpointClosed("HostP2P closed during connect")
            raise

    def _handshake(self, sock: socket.socket, addr, dest: int) -> None:
        """Sliced non-blocking connect (see _connect). selectors (epoll on
        Linux) rather than select(): no FD_SETSIZE-1024 limit."""
        sock.setblocking(False)
        rc = sock.connect_ex(addr)
        if rc not in (0, errno.EINPROGRESS, errno.EWOULDBLOCK):
            raise OSError(rc, os.strerror(rc))
        deadline = self._clock() + self.timeout
        sel = selectors.DefaultSelector()
        try:
            if rc != 0:
                try:
                    sel.register(sock, selectors.EVENT_WRITE)
                except (ValueError, OSError):
                    if self._closed.is_set():
                        raise _EndpointClosed(
                            "HostP2P closed during connect")
                    raise
            while rc != 0:
                if self._closed.is_set():
                    raise _EndpointClosed("HostP2P closed during connect")
                if self._clock() > deadline:
                    raise TimeoutError(
                        f"connect to rank {dest} {addr} timed out after "
                        f"{self.timeout}s")
                if self._wait_writable(sel):
                    try:
                        rc = sock.getsockopt(socket.SOL_SOCKET,
                                             socket.SO_ERROR)
                    except OSError:
                        if self._closed.is_set():
                            raise _EndpointClosed(
                                "HostP2P closed during connect")
                        raise
                    if rc != 0:
                        raise OSError(rc, os.strerror(rc))
        finally:
            sel.close()
        sock.setblocking(True)
        sock.settimeout(self.timeout)

    def _drop_conn(self, sock: socket.socket) -> None:
        with self._conns_lock:
            self._conns.discard(sock)
        try:
            sock.close()
        except OSError:
            pass

    def _retry_delay(self, attempt: int) -> float:
        """Exponential backoff with full-range jitter (0.5×–1.5×) so a
        fleet of senders retrying into a restarted peer doesn't
        synchronize into a thundering herd."""
        base = min(self.retry_backoff * (2.0 ** (attempt - 1)),
                   self.retry_backoff_max)
        return base * (0.5 + random.random())

    def _set_active_send(self, dest: int, sock) -> None:
        with self._send_lock:
            if sock is None:
                self._active_send.pop(dest, None)
            else:
                self._active_send[dest] = sock

    def _sever_send(self, dest: int) -> bool:
        """Fault-injection hook (testing.faults.sever_connection): hard-cut
        the live outbound connection to ``dest`` so the next/current send
        fails as a real network partition would. Returns False when no
        connection is live."""
        with self._send_lock:
            sock = self._active_send.get(dest)
        if sock is None:
            return False
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        return True

    def _partition(self, rank: int) -> None:
        """Fault-injection hook (testing.faults.partition_hosts): drop the
        link to/from ``rank`` persistently — outbound connects refuse
        (EHOSTUNREACH), inbound frames are discarded — until
        :meth:`_heal`. Also cuts the live outbound socket so an
        in-flight send fails like a real partition onset."""
        with self._send_lock:
            self._partitioned = self._partitioned | {rank}
        self._sever_send(rank)

    def _heal(self, rank: int) -> None:
        """Undo :meth:`_partition` and clear the send-stream poison so
        traffic can flow again (see :meth:`reset_stream`)."""
        with self._send_lock:
            self._partitioned = self._partitioned - {rank}
        self.reset_stream(rank)

    def _set_link_delay(self, dest: int, delay_s: Optional[float]) -> None:
        """Fault-injection hook (testing.faults.delay_link): sleep
        ``delay_s`` before each frame to ``dest`` (None clears)."""
        with self._send_lock:
            d = dict(self._link_delay)
            if delay_s is None:
                d.pop(dest, None)
            else:
                d[dest] = float(delay_s)
            self._link_delay = d

    def reset_stream(self, dest: int) -> bool:
        """Clear the poison on the send stream to ``dest`` so the next
        send attempts a fresh connection. Poisoning exists to keep the
        non-overtaking stream gap-free — resetting it is the caller
        EXPLICITLY acknowledging that messages may have been lost in the
        gap (safe for the correlation-id RPC layer, which tracks every
        request individually and re-sends whole requests). Returns True
        when a poison was cleared."""
        with self._send_lock:
            return self._poison.pop(dest, None) is not None

    def _send_loop(self, dest: int, q: "queue.Queue"):
        """All sends to ``dest`` go through one connection in post order —
        the non-overtaking half of the contract. A transient failure is
        retried with backoff + jitter (the whole frame is resent on a
        fresh connection — at-least-once, see the class docstring); only
        after ``retries`` are exhausted does the failure POISON the
        stream: every later request to this destination fails with the
        original error, so the receiver can never observe a gap (message i
        lost, i+1 delivered). :meth:`reset_stream` clears the poison for
        callers (the RPC layer, a healed partition) that accept the
        gap explicitly."""
        sock = None
        while not self._closed.is_set():
            try:
                item = q.get(timeout=0.25)
            except queue.Empty:
                continue
            req, tag, ty, raw = item
            with self._send_lock:
                poison = self._poison.get(dest)
            if poison is not None:
                err = ConnectionError(
                    f"send stream to rank {dest} poisoned by earlier "
                    f"failure: {poison!r}")
                err.__cause__ = poison  # keep the class for isinstance
                req._finish(error=err)
                continue
            attempt = 0
            slept_s = 0.0  # cumulative backoff this frame (logged below)
            nbytes = _HDR.size + 1 + len(raw)
            while True:
                try:
                    delay_s = self._link_delay.get(dest)
                    if delay_s and self._closed.wait(delay_s):
                        raise _EndpointClosed("HostP2P closed")
                    if dest in self._partitioned:
                        raise OSError(
                            errno.EHOSTUNREACH,
                            f"rank {dest} partitioned (injected fault)")
                    if sock is None:
                        sock = self._connect(dest)
                        self._set_active_send(dest, sock)
                    sock.sendall(_HDR.pack(_MAGIC, self.rank, tag,
                                           len(raw)))
                    sock.sendall(ty)
                    sock.sendall(raw)
                    req._finish()
                    _SENT_MSGS.labels(dest).inc()
                    _SENT_BYTES.labels(dest).inc(nbytes)
                    break
                except _EndpointClosed as e:  # closed endpoint: terminal
                    req._finish(error=e)
                    with self._send_lock:
                        self._poison[dest] = e
                    break
                except BaseException as e:  # surfaced at wait()
                    if sock is not None:
                        self._set_active_send(dest, None)
                        self._drop_conn(sock)
                        sock = None
                    attempt += 1
                    if attempt > self.retries or self._closed.is_set():
                        req._finish(error=e)
                        with self._send_lock:
                            self._poison[dest] = e
                        _STREAMS_POISONED.labels(dest).inc()
                        logger.error(
                            "host_p2p rank %d: send to rank %d failed "
                            "after %d attempt(s), %.3f s cumulative "
                            "backoff; stream poisoned: %r",
                            self.rank, dest, attempt, slept_s, e)
                        break
                    delay = self._retry_delay(attempt)
                    slept_s += delay
                    _SEND_RETRIES.labels(dest).inc()
                    _BACKOFF_SECONDS.labels(dest).inc(delay)
                    logger.warn(
                        "host_p2p rank %d: send to rank %d failed "
                        "(attempt %d/%d): %r; backing off %.3f s "
                        "(%.3f s cumulative)",
                        self.rank, dest, attempt, self.retries, e,
                        delay, slept_s)
                    # backoff observes _closed so close() stays bounded
                    if self._closed.wait(delay):
                        req._finish(error=e)
                        with self._send_lock:
                            self._poison[dest] = e
                        break
        self._set_active_send(dest, None)
        if sock is not None:
            self._drop_conn(sock)
        _drain_queue(q, ConnectionError(
            f"HostP2P closed before send to rank {dest} completed"))

    def isend(self, payload: Union[bytes, np.ndarray], dest: int,
              tag: int = 0) -> Request:
        """Non-blocking send (comms_t::isend, core/comms.hpp:137)."""
        if not 0 <= dest < self.size:
            raise ValueError(f"dest {dest} out of range")
        if self._closed.is_set():
            raise ConnectionError("isend on a closed HostP2P endpoint")
        req = Request("isend", self._match_lock,
                      default_timeout=self.timeout, clock=self._clock)
        ty, raw = _encode(payload)  # encode eagerly: caller may mutate
        q = self._sender_for(dest)
        q.put((req, tag, ty, raw))
        if self._closed.is_set():
            # lost the race with a concurrent close(): its drain (and the
            # sender loop's exit drain) may already have run, so fail the
            # late put ourselves — double-drain is safe (get is atomic)
            _drain_queue(q, ConnectionError(
                "HostP2P closed before send completed"))
        return req

    def announce_drain(self, dest: int) -> Request:
        """Send the graceful-drain control frame to ``dest`` (module
        docstring): it rides the ordered send stream, so everything
        posted before it is delivered first, then the peer fails its
        pending irecvs from this rank with :class:`PeerDrained` and
        treats the connection EOF that follows as clean. Call before
        :meth:`close` for a polite shutdown (a crash simply doesn't)."""
        return self.isend(b"", dest, tag=_DRAIN_TAG)

    # ---------------------------------------------------------------- wait
    @staticmethod
    def waitall(requests: List[Request],
                timeout: Optional[float] = None) -> list:
        """Block on a mix of send/recv requests (comms_t::waitall,
        core/comms.hpp:141). Returns receive payloads in request order
        (None for sends). ``timeout`` is ONE deadline for the whole batch,
        not per-request: each wait gets only the time remaining.
        ``timeout=None`` falls back to each request's endpoint timeout —
        a real deadline either way, never an unbounded hang. The deadline
        runs on the first request's endpoint clock (one endpoint's
        requests share it), so fake-clock tests drive it too."""
        if timeout is None:
            return [r.wait() for r in requests]
        if not requests:
            return []
        clock = requests[0]._clock
        deadline = clock() + timeout
        return [r.wait(max(deadline - clock(), 0.0)) for r in requests]

    def sendrecv(self, payload, dest: int, source: int, tag: int = 0):
        """Convenience paired exchange (device_sendrecv's host analog)."""
        s = self.isend(payload, dest, tag)
        r = self.irecv(source, tag)
        self.waitall([s], self.timeout)
        return r.wait(self.timeout)

    def close(self):
        self._closed.set()
        # closing an fd does NOT wake a thread blocked in accept() on
        # Linux — poke the listener with a throwaway connection so the
        # accept loop observes _closed and exits (no leaked threads)
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            socket.create_connection(
                (self.peers[self.rank][0], self.peers[self.rank][1]),
                timeout=0.5).close()
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2.0)
        # unblock _serve threads stuck in recv() on one-sided close;
        # the lock + _closed check in _accept_loop means no connection can
        # be admitted after this reap
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        # fail any isends still queued so no Request.wait() blocks forever
        # (sender loops also drain on exit; double-drain is safe)
        with self._send_lock:
            queues = list(self._send_queues.values())
        for q in queues:
            _drain_queue(q, ConnectionError(
                "HostP2P closed before send completed"))
        # ... and symmetrically, every pending irecv: its message can no
        # longer arrive (matching happens under _match_lock, so a request
        # is either finished by a delivery or failed here, never both)
        with self._match_lock:
            waiting, self._waiting = self._waiting, {}
        for reqs in waiting.values():
            for req in reqs:
                req._finish(error=ConnectionError(
                    "HostP2P closed with receive outstanding"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
