"""Sharded (multi-device / multi-host) index build & search.

Reference: the MNMG pattern raft-dask + cuML implement over ``raft::comms``
(SURVEY.md §2.8, §5): each worker holds a data partition with its own local
index; queries are broadcast; each worker searches locally, and the
per-worker top-k lists are merged (the
``knn_merge_parts`` pattern, detail/knn_merge_parts.cuh, applied across
ranks instead of tiles).

TPU-native design: partitions are mesh shards, not worker processes. The
whole search (local scan + cross-device merge) is ONE jitted SPMD program:
``shard_map`` runs the local search per device shard, ``all_gather`` moves
only the [nq, k] candidate lists over ICI (tiny vs the dataset), and the
merge is a final top-k — XLA overlaps the collective with compute. Dataset
shards never move. Build shards rows round-robin; ids stay global.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu.core import tracing
from raft_tpu.core.resources import Resources, ensure_resources
from raft_tpu.obs import explain as obs_explain
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import spans as obs_spans
from raft_tpu.ops.distance import DistanceType, resolve_metric, pairwise_core
from raft_tpu.ops.select_k import refine_multiplier, select_k
from raft_tpu.parallel.comms import Comms
from raft_tpu.utils.shape import cdiv

# MNMG observability (docs/observability.md): entry-point call counters
# plus checkpoint verify/restore outcomes — the numbers the runbook's
# pre-flight reads off /metrics after a restore drill
_SHARDED_SEARCHES = obs_metrics.REGISTRY.counter(
    "raft_tpu_sharded_search_total",
    "Sharded search/knn entry-point calls by family.", ("family",))
_CKPT_VERIFY = obs_metrics.REGISTRY.counter(
    "raft_tpu_checkpoint_verify_total",
    "verify_checkpoint runs by overall result.", ("result",))
_CKPT_FILES = obs_metrics.REGISTRY.counter(
    "raft_tpu_checkpoint_file_status_total",
    "Rank-file statuses observed by verify_checkpoint.", ("status",))
_CKPT_RESTORES = obs_metrics.REGISTRY.counter(
    "raft_tpu_checkpoint_restore_total",
    "Sharded checkpoint restores by kind and coverage mode.",
    ("kind", "mode"))

# ---- per-shard trace spans (docs/observability.md "Sharded search
# spans"): a module-level sink, installed by set_span_sink. With no sink
# (the default) every search entrypoint runs its usual single fused SPMD
# program — zero overhead, zero behavior change. With a sink installed,
# the same local cores run in a two-phase dispatch: phase A is the
# shard_map local scan WITHOUT the in-program merge (per-shard [nq, kk]
# candidates stay sharded), each shard is fenced in rank order to emit a
# per-shard child span (rank, device, readback-order completion ms),
# and phase B merges host-gathered candidates via ``_elastic_merge`` —
# bit-identical math to the in-program allgather merge (rank-order
# concat along the candidate axis feeding the same deterministic
# select_k), pinned by tests/test_parallel.py.
_SPAN_SINK_LOCK = threading.Lock()
_SPAN_SINK: Optional[object] = None


def set_span_sink(sink: Optional[object]) -> Optional[object]:
    """Install (or clear, with None) the sharded-search span sink.
    Anything with ``emit(dict)`` works (:class:`raft_tpu.obs.RingSink`,
    :class:`~raft_tpu.obs.JsonlSink`, ...). Returns the previous sink
    so callers can restore it."""
    global _SPAN_SINK
    with _SPAN_SINK_LOCK:
        prev, _SPAN_SINK = _SPAN_SINK, sink
    return prev


def _span_sink() -> Optional[object]:
    with _SPAN_SINK_LOCK:
        return _SPAN_SINK


def _instrumented_search(comms: Comms, local_scan, in_specs, args,
                         family: str, nq: int, k_eff: int,
                         minimize: bool, sink) -> Tuple[jax.Array,
                                                        jax.Array]:
    """Two-phase sharded search with per-shard child spans.

    ``local_scan`` is the entrypoint's per-device scan (returns the
    [nq, kk] local candidates WITHOUT the merge). Phase A runs it under
    shard_map with the candidates left sharded [S, nq, kk]; each shard
    is then fenced in rank order (``shard_search`` child spans — since
    the dispatch is one SPMD program, all shards compute concurrently
    and ``device_ms`` is each shard's completion lag in readback order,
    the per-rank skew signal). Phase B merges on the default device via
    :func:`_elastic_merge` and emits the parent ``sharded_search`` span
    carrying launch/merge/total wall time under the minted trace id."""
    ax = comms.axis
    trace_id = obs_spans.new_trace_id()
    t0 = time.perf_counter()

    def expanded(*a):
        v, i = local_scan(*a)
        return v[None], i[None]

    fn = comms.run(expanded, in_specs,
                   (P(ax, None, None), P(ax, None, None)))
    v, i = jax.jit(fn)(*args)
    t_launch = time.perf_counter()
    by_rank_i = {s.index[0].start or 0: s for s in i.addressable_shards}
    v_parts, i_parts = [], []
    for sh in sorted(v.addressable_shards,
                     key=lambda s: s.index[0].start or 0):
        rank = int(sh.index[0].start or 0)
        ts = time.perf_counter()
        v_np = np.asarray(sh.data)  # graftcheck: R001 — the fence
        i_np = np.asarray(by_rank_i[rank].data)  # graftcheck: R001
        obs_spans.safe_emit(sink, {
            "kind": "shard_search", "trace_id": trace_id,
            "family": family, "rank": rank, "device": str(sh.device),
            "device_ms": round((time.perf_counter() - ts) * 1e3, 3)})
        v_parts.append(v_np)
        i_parts.append(i_np)
    t_merge = time.perf_counter()
    vm, im = _elastic_merge(
        jnp.asarray(np.concatenate(v_parts, axis=0)),
        jnp.asarray(np.concatenate(i_parts, axis=0)),
        nq, k_eff, minimize)
    jax.block_until_ready((vm, im))
    t_end = time.perf_counter()
    obs_spans.safe_emit(sink, {
        "kind": "sharded_search", "trace_id": trace_id, "family": family,
        "n_shards": len(v_parts),
        "launch_ms": round((t_launch - t0) * 1e3, 3),
        "merge_ms": round((t_end - t_merge) * 1e3, 3),
        "total_ms": round((t_end - t0) * 1e3, 3)})
    return vm, im


# ------------------------------------------------- shard build orchestration


def _shard_device(comms: Comms, r: int) -> jax.Device:
    """First device of shard ``r``'s slice along the comms axis."""
    ax_pos = comms.mesh.axis_names.index(comms.axis)
    return np.asarray(np.take(comms.mesh.devices, r, axis=ax_pos)).flat[0]


def _map_shards(comms: Comms, fn, res: Resources, spans=None) -> dict:
    """Run ``fn(r, shard_res)`` for every shard whose device belongs to this
    process — on accelerator platforms one thread per local shard, each
    pinned to its shard's device via ``jax.default_device`` so per-shard
    builds dispatch to distinct chips instead of queueing on one (VERDICT
    r1 #5: the serial host loop serialized an 8× build); on the cpu
    platform serially (XLA:CPU compile-thread-safety, see below;
    RAFT_TPU_PARALLEL_BUILD=0/1 overrides either default). In a
    multi-controller deployment each process builds only its addressable
    shards (the raft-dask per-worker build role,
    raft_dask/common/comms.py:138-173).

    PRNG keys are pre-derived per shard (deterministic regardless of thread
    completion order). ``spans`` (rows per shard, when the caller knows
    them) lets the warm-up cover every distinct shard shape exactly."""
    size = comms.size
    keys = [res.next_key() for _ in range(size)]
    devs = {r: _shard_device(comms, r) for r in range(size)}
    pid = jax.process_index()
    local = [r for r in range(size) if devs[r].process_index == pid]
    results: dict = {}

    def run(r):
        shard_res = Resources(device=devs[r])
        shard_res._key = keys[r]
        with jax.default_device(devs[r]):
            results[r] = fn(r, shard_res)

    # XLA:CPU's compiler (LLVM JIT) is not safe under concurrent
    # compilation from multiple threads — and op-by-op dispatch compiles
    # per *device*, so even identical per-shard programs compile once per
    # pinned device (observed segfaults in backend_compile_and_load on
    # the 8-device virtual mesh, 128 GB free). Builds therefore run
    # serially on the cpu platform; accelerator platforms keep the
    # one-thread-per-shard dispatch. RAFT_TPU_PARALLEL_BUILD=1/0
    # overrides either way.
    force = os.environ.get("RAFT_TPU_PARALLEL_BUILD")
    if force is not None and force.lower() not in ("0", "1", "true",
                                                   "false", "on", "off"):
        raise ValueError(
            f"RAFT_TPU_PARALLEL_BUILD={force!r}: use 0/1/true/false/on/off")
    parallel = (devs[local[0]].platform != "cpu"
                if force is None
                else force.lower() in ("1", "true", "on")) if local else False
    if not parallel:
        for r in local:
            run(r)
        return results

    # Serial warm-up of one shard per distinct shard shape (from ``spans``
    # when provided; endpoint shards otherwise — linspace puts the odd
    # span sizes at the ends in the single-host case). The warm-up
    # populates the jit cache so the parallel workers mostly *execute*
    # concurrently instead of compiling.
    if spans is not None:
        seen: set = set()
        warm = []
        for r in local:
            s = int(spans[r])
            if s not in seen:
                seen.add(s)
                warm.append(r)
    else:
        warm = [local[0], *([local[-1]] if len(local) > 1 else [])]
    for r in warm:
        run(r)
    rest = [r for r in local if r not in warm]
    if len(rest) == 1:
        run(rest[0])
    elif rest:
        _run_parallel_cancelling(run, rest)
    return results


def _run_parallel_cancelling(run, ranks) -> None:
    """One thread per shard with first-failure cancellation: when any
    shard build raises, unstarted siblings never run and running siblings
    get a ``core.interruptible`` cancellation token — their next
    ``yield_now()``/``synchronize()`` raises instead of burning device
    hours completing builds whose results will be discarded. The FIRST
    failure propagates; sibling-cancellation fallout is suppressed."""
    from raft_tpu.core import interruptible

    failure: list = []
    tids: dict = {}
    lock = threading.Lock()

    def worker(r):
        with lock:
            if failure:
                return
            tids[r] = threading.get_ident()
        try:
            interruptible.yield_now()
            run(r)
        except interruptible.InterruptedException:
            with lock:
                if failure:
                    return  # cancelled because a sibling failed first
            raise
        except BaseException as e:
            with lock:
                failure.append(e)
                for rr, tid in tids.items():
                    if rr != r:
                        interruptible.cancel(tid)
            raise
        finally:
            with lock:
                tids.pop(r, None)
            # never leak an unconsumed token to a reused thread ident
            interruptible.release_token()

    with ThreadPoolExecutor(max_workers=len(ranks)) as ex:
        futs = [ex.submit(worker, r) for r in ranks]
        for f in as_completed(futs):
            if not f.cancelled() and f.exception() is not None:
                for other in futs:
                    other.cancel()
    if failure:
        raise failure[0]


def _global_max_shape(comms: Comms, local_max: np.ndarray) -> np.ndarray:
    """Elementwise max of a small int vector across processes (multi-host
    shard-shape agreement; single-process sees every shard already)."""
    if jax.process_count() == 1:
        return local_max
    x = jax.make_array_from_callback(
        (comms.size, len(local_max)),
        NamedSharding(comms.mesh, P(comms.axis, None)),
        lambda idx: np.asarray(local_max, np.int32)[None])
    fn = comms.run(lambda v: jax.lax.pmax(v[0], comms.axis),
                   P(comms.axis, None), P(None))
    return np.asarray(jax.jit(fn)(x))


def _global_any(comms: Comms, flag: bool) -> bool:
    """OR of a per-process bool (pmax of 0/1). Decisions that gate
    COLLECTIVES (e.g. whether overflow blocks get stacked) must be agreed
    globally — a process-local flag would deadlock the processes that
    disagree and compile divergent SPMD programs."""
    return bool(_global_max_shape(
        comms, np.asarray([1 if flag else 0], np.int64))[0])


def _stack_sharded(comms: Comms, parts: dict, fill=0):
    """Assemble ``{r: np.ndarray}`` per-shard blocks (ragged dims allowed —
    padded with ``fill``) into a global ``[S, ...]`` array sharded
    ``P(axis, None, ...)``. Each block is materialized only for its own
    device via ``make_array_from_callback`` — no host-side ``np.stack`` of
    all shards, and in multi-controller runs each process touches only its
    addressable shards (VERDICT r1 #5: assembly staged all state through
    one host's RAM)."""
    sample = next(iter(parts.values()))
    nd = sample.ndim
    local_max = np.zeros((nd,), np.int64)
    for p in parts.values():
        local_max = np.maximum(local_max, p.shape)
    inner = tuple(int(v) for v in _global_max_shape(comms, local_max))
    global_shape = (comms.size,) + inner
    sharding = NamedSharding(comms.mesh, P(comms.axis, *([None] * nd)))

    def cb(index):
        r = index[0].start or 0
        p = parts[r]
        if p.shape == inner:
            return p[None]
        block = np.full(inner, fill, dtype=sample.dtype)
        block[tuple(slice(0, s) for s in p.shape)] = p
        return block[None]

    return jax.make_array_from_callback(global_shape, sharding, cb)


# ------------------------------------------------------ placement planning
#
# Every sharded entrypoint used to re-derive the same facts inline — row
# bounds, per-shard candidate width, workspace tiles, and (implicitly) the
# one hardcoded all_gather merge. A PlacementPlan solves them once per
# (index, shape) and carries the resolved cross-chip merge engine, so the
# search bodies just execute the plan and ROADMAP item 2's router has one
# object to consume.

MERGE_MODES = ("auto", "allgather", "tree", "ring")


def shard_bounds(size: int, n: int) -> np.ndarray:
    """[S+1] balanced row offsets — THE row partition every sharded build
    uses (np.linspace keeps shard sizes within one row of each other and
    the last shard ragged when S ∤ n)."""
    return np.linspace(0, n, size + 1).astype(np.int64)


def _check_n_lists(bounds: np.ndarray, n_lists: int, n: int,
                   size: int) -> None:
    min_shard = int(np.diff(bounds).min())
    if n_lists > min_shard:
        raise ValueError(
            f"n_lists={n_lists} exceeds the smallest shard's "
            f"{min_shard} rows ({n} rows over {size} devices); every shard "
            f"builds its own index, so n_lists must be ≤ rows-per-shard")


@dataclasses.dataclass(frozen=True)
class PlacementPlan:
    """One sharded search, solved: where the rows live (mesh axis, size,
    bounds), what scans them (family + engine + tiles), and how the
    per-shard candidates merge across chips (mode + reason + predicted
    bytes). Frozen and cached per (index, shape) in ``_PLAN_CACHE`` —
    entrypoints execute plans, they don't re-derive them."""

    axis: str
    size: int
    n_rows: int
    bounds: Tuple[int, ...]   # [S+1] global row offsets ((∅) if unknown)
    family: str               # "brute_force" | "cagra" | "ivf_flat" | "ivf_pq"
    engine: str               # local scan engine ("xla", "cache", "lut", ...)
    nq: int
    k: int
    kk: int                   # per-shard candidate width entering the merge
    k_out: int                # merged output width = min(k, size*kk)
    merge_mode: str           # resolved: "allgather" | "tree" | "ring"
    merge_reason: str         # obs.explain REASONS member
    ring_shift: str           # "pallas" | "pallas_interpret" | "xla" | ""
    mask_invalid: bool        # mask id<0 candidates to ±inf before merging
    tiles: Tuple[Tuple[str, int], ...] = ()   # planner tile choices
    merge_bytes: Tuple[Tuple[str, int], ...] = ()  # predicted bytes by mode

    def explain_plan(self) -> dict:
        """The flat JSON-safe dict an ExplainRecord carries."""
        out = {"size": self.size, "kk": self.kk, "k_out": self.k_out,
               "merge_mode": self.merge_mode, "ring_shift": self.ring_shift}
        out.update({f"tile_{k}": v for k, v in self.tiles})
        out.update({f"merge_bytes_{k}": v for k, v in self.merge_bytes})
        return out


_PLAN_CACHE: dict = {}
_PLAN_CACHE_CAP = 256
_PLAN_LOCK = threading.Lock()
_PLAN_SOLVES = obs_metrics.REGISTRY.counter(
    "raft_tpu_placement_plan_solves_total",
    "PlacementPlan cache misses (fresh solves) by family.", ("family",))


def plan_cache_clear() -> None:
    """Test hook: drop every cached PlacementPlan."""
    with _PLAN_LOCK:
        _PLAN_CACHE.clear()


def merge_dispatch_explained(merge_mode: str, size: int):
    """Resolve the cross-chip merge engine: ``(engine, reason,
    ring_shift)`` with reason from ``obs.explain.REASONS`` — the merge
    analog of ``ops.pallas_kernels.fused_dispatch_explained``, sharing its
    verdict discipline: ``auto`` only routes the RDMA ring kernel on TPU
    when the PALLAS_PROBE artifact records a ``merge_ring`` win; with no
    verdict it stays on the pure-XLA tree merge (safe everywhere) and
    says so. Non-power-of-two meshes fall back to all_gather (the tree
    pairs ranks by XOR)."""
    from raft_tpu.ops import pallas_kernels

    on_tpu = jax.default_backend() in ("tpu", "axon")
    interp = os.environ.get("RAFT_TPU_PALLAS_INTERPRET") == "1"
    pow2 = size >= 2 and (size & (size - 1)) == 0
    if merge_mode == "allgather":
        return "allgather", "forced", ""
    if merge_mode == "tree":
        if not pow2:
            raise ValueError(
                f"merge_mode='tree' needs a power-of-two mesh axis "
                f"(size={size}); use 'allgather' or 'auto'")
        return "tree", "forced", ""
    if merge_mode == "ring":
        if size < 2:
            raise ValueError("merge_mode='ring' needs a mesh axis of at "
                             "least 2 devices")
        # explicit request is the opt-in (cf. scan_mode="pallas"):
        # hardware RDMA on TPU, Mosaic interpreter under the parity hook,
        # the same ring schedule over XLA ppermute elsewhere
        shift = ("pallas" if on_tpu
                 else "pallas_interpret" if interp else "xla")
        return "ring", "forced", shift
    if merge_mode != "auto":
        raise ValueError(f"unknown merge_mode: {merge_mode!r} "
                         f"(one of {MERGE_MODES})")
    if not pow2:
        return "allgather", "merge_allgather", ""
    if on_tpu:
        verdict = pallas_kernels.ring_merge_verdict()
        if verdict:
            return "ring", "merge_ring", "pallas"
        if verdict is None:
            return "tree", "no_ring_verdict", ""
        return "tree", "fused_loses", ""
    return "tree", "merge_tree", ""


def plan_sharded_search(comms: Comms, family: str, n_rows: int, bounds,
                        nq: int, k: int, kk: int, engine: str,
                        merge_mode: str = "auto", mask_invalid: bool = False,
                        tiles: Optional[dict] = None) -> PlacementPlan:
    """Solve (or fetch) the PlacementPlan for one sharded search shape.

    Cached on the full solving key — including backend and merge_mode, so
    a probe artifact landing mid-process or an env flip retraces rather
    than reusing a stale resolution (the select_k AUTO-table rule)."""
    from raft_tpu.core.resources import solve_merge_bytes

    bounds_t = tuple(int(b) for b in bounds) if bounds is not None else ()
    tiles_t = tuple(sorted((tiles or {}).items()))
    key = (family, comms.axis, comms.size, int(n_rows), bounds_t, int(nq),
           int(k), int(kk), engine, merge_mode, bool(mask_invalid), tiles_t,
           jax.default_backend(),
           os.environ.get("RAFT_TPU_PALLAS_INTERPRET"))
    with _PLAN_LOCK:
        plan = _PLAN_CACHE.get(key)
    if plan is not None:
        return plan
    mode, reason, ring_shift = merge_dispatch_explained(merge_mode,
                                                        comms.size)
    k_out = min(int(k), comms.size * int(kk))
    mb = solve_merge_bytes(comms.size, int(nq), int(kk), k_out)
    plan = PlacementPlan(
        axis=comms.axis, size=comms.size, n_rows=int(n_rows),
        bounds=bounds_t, family=family, engine=engine, nq=int(nq),
        k=int(k), kk=int(kk), k_out=k_out, merge_mode=mode,
        merge_reason=reason, ring_shift=ring_shift,
        mask_invalid=bool(mask_invalid), tiles=tiles_t,
        merge_bytes=tuple(sorted(mb.items())))
    _PLAN_SOLVES.labels(family).inc()
    with _PLAN_LOCK:
        if len(_PLAN_CACHE) >= _PLAN_CACHE_CAP:
            _PLAN_CACHE.pop(next(iter(_PLAN_CACHE)))
        _PLAN_CACHE[key] = plan
    return plan


def _plan_merge(comms: Comms, plan: PlacementPlan, v, i, minimize: bool):
    """Execute the plan's cross-chip merge (traceable, inside shard_map).
    All three engines are bit-identical by construction: allgather is the
    reference rank-order concat + stable select_k; tree and ring select
    by explicit (value, concat-pos) lexicographic order, which equals the
    stable selection for any merge schedule (comms.py)."""
    if plan.mask_invalid:
        v = jnp.where(i < 0, jnp.inf if minimize else -jnp.inf, v)
    if plan.merge_mode == "allgather":
        v_all = comms.allgather(v, axis=1)
        i_all = comms.allgather(i, axis=1)
        vm, sel = select_k(v_all, plan.k_out, select_min=minimize)
        return vm, jnp.take_along_axis(i_all, sel, axis=1)
    if plan.merge_mode == "tree":
        return comms.tree_topk_merge(v, i, plan.k_out, select_min=minimize)
    shift = None
    if plan.ring_shift.startswith("pallas"):
        from raft_tpu.ops.pallas_kernels import pallas_ring_shift

        interp = plan.ring_shift == "pallas_interpret"
        shift = functools.partial(pallas_ring_shift, axis=comms.axis,
                                  size=comms.size, interpret=interp)
    return comms.ring_topk_merge(v, i, plan.k_out, select_min=minimize,
                                 shift=shift)


def _record_plan(plan: PlacementPlan, requested: str,
                 params: Optional[dict] = None) -> None:
    """Emit the merge-dispatch ExplainRecord for one sharded search call
    (the parallel/ analog of the single-chip families' attribution —
    graftcheck R007 covers these sites)."""
    p = {"nq": plan.nq, "k": plan.k, "engine": plan.engine}
    p.update(params or {})
    obs_explain.record_dispatch(
        f"sharded_{plan.family}", requested, plan.merge_mode,
        plan.merge_reason, params=p, plan=plan.explain_plan())


# ----------------------------------------------------------- sharded knn


@tracing.range("sharded.knn")
def knn(
    comms: Comms,
    queries,
    dataset,
    k: int,
    metric="sqeuclidean",
    res: Optional[Resources] = None,
    merge_mode: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN over a row-sharded dataset: local brute force per shard +
    ICI merge (the SPMD analog of MNMG brute_force over raft::comms).

    ``dataset`` may already be sharded over ``comms.axis``; otherwise it is
    placed with row sharding here. ``merge_mode`` picks the cross-chip
    top-k merge (docs/sharding.md): "auto" routes the streaming tree/ring
    ladder, "allgather" the legacy full-slab merge — all bit-identical.
    Returns replicated (distances, indices) with global row ids.
    """
    _SHARDED_SEARCHES.labels("brute_force").inc()
    ensure_resources(res)
    m = resolve_metric(metric)
    minimize = m != DistanceType.InnerProduct
    queries = jnp.asarray(queries)
    dataset = jnp.asarray(dataset)
    n, dim = dataset.shape
    size = comms.size
    shard = cdiv(n, size)
    n_pad = shard * size
    if n_pad != n:
        dataset = jnp.pad(dataset, ((0, n_pad - n), (0, 0)))
    x = comms.shard(dataset, P(comms.axis, None))
    q = comms.shard(queries, P(None, None))

    kk = min(k, shard)

    def local_scan(q_rep, x_loc):
        rank = comms.rank()
        base = rank * shard
        d = pairwise_core(q_rep, x_loc, m, 2.0, 1 << 30)
        # mask padding rows of the last shard
        local_ids = jnp.arange(shard) + base
        d = jnp.where(local_ids[None, :] < n, d,
                      jnp.inf if minimize else -jnp.inf)
        v, i = select_k(d, kk, select_min=minimize)
        gids = (i + base).astype(jnp.int32)
        return v, gids

    in_specs = (P(None, None), P(comms.axis, None))
    sink = _span_sink()
    if sink is not None:
        return _instrumented_search(
            comms, local_scan, in_specs, (q, x), "brute_force",
            queries.shape[0], min(k, size * kk), minimize, sink)

    plan = plan_sharded_search(
        comms, "brute_force", n, tuple(range(0, n_pad + 1, shard)),
        queries.shape[0], k, kk, "xla", merge_mode=merge_mode)
    _record_plan(plan, merge_mode, {"metric": m.name})

    def local(q_rep, x_loc):
        v, gids = local_scan(q_rep, x_loc)
        return _plan_merge(comms, plan, v, gids, minimize)

    fn = comms.run(local, in_specs, (P(None, None), P(None, None)))
    return jax.jit(fn)(q, x)


# ---------------------------------------------- sharded pairwise distance


@tracing.range("sharded.pairwise_distance")
def pairwise_distance(
    comms: Comms,
    x,
    y,
    metric="sqeuclidean",
    metric_arg: float = 2.0,
    res: Optional[Resources] = None,
) -> jax.Array:
    """Full [n, m] pairwise distances with BOTH operands row-sharded — the
    MNMG pairwise primitive consumers run over raft::comms (cuML's
    distributed pairwise role).

    Ring schedule (the ring-attention pattern applied to distance tiles):
    x shards stay put; y shards rotate over ICI via ``ppermute``, each
    device computing one [n/S, m/S] MXU tile per step and writing it into
    its output row-block. Peak per-device memory is O(nm/S²) per step +
    the [n/S, m] output block; only y's shards ever move, overlapping with
    compute (XLA schedules the collective ahead of the matmul).

    Returns the distance matrix sharded over rows of ``x``.
    """
    ensure_resources(res)
    m_ = resolve_metric(metric)
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    n, dim = x.shape
    m, _ = y.shape
    size = comms.size
    xs_rows = cdiv(n, size)
    ys_rows = cdiv(m, size)
    xp = jnp.pad(x, ((0, xs_rows * size - n), (0, 0)))
    yp = jnp.pad(y, ((0, ys_rows * size - m), (0, 0)))
    xsh = comms.shard(xp, P(comms.axis, None))
    ysh = comms.shard(yp, P(comms.axis, None))

    def local(x_loc, y_loc):
        rank = comms.rank()

        def tile(i, y_cur, out):
            # after i ring shifts, this device holds shard (rank - i)
            src = (rank - i) % size
            d = pairwise_core(x_loc, y_cur, m_, metric_arg, 1 << 30)
            return jax.lax.dynamic_update_slice(
                out, d.astype(out.dtype), (0, src * ys_rows))

        def step(i, carry):
            y_cur, out = carry
            return comms.shift(y_cur, 1), tile(i, y_cur, out)

        out0 = jnp.zeros((x_loc.shape[0], ys_rows * size), jnp.float32)
        # size-1 compute+shift steps, then a final compute — the last
        # rotation's payload would never be read, so it is never sent
        y_last, out = jax.lax.fori_loop(0, size - 1, step, (y_loc, out0))
        return tile(size - 1, y_last, out)

    fn = comms.run(local, (P(comms.axis, None), P(comms.axis, None)),
                   P(comms.axis, None))
    out = jax.jit(fn)(xsh, ysh)
    return out[:n, :m]


# ------------------------------------------------------- sharded k-means


@tracing.range("sharded.kmeans_fit")
def kmeans_fit(
    comms: Comms,
    x,
    n_clusters: int,
    n_iters: int = 20,
    key=None,
    res: Optional[Resources] = None,
    balance_threshold: Optional[float] = None,
    donor_pool: int = 256,
) -> Tuple[jax.Array, jax.Array]:
    """Data-parallel Lloyd k-means over a row-sharded dataset (the MNMG
    k-means pattern: local assignment, psum of per-cluster sums/counts —
    what cuML does over raft::comms allreduce). Returns (centers, labels).

    ``balance_threshold`` turns on the multi-host analog of
    ``cluster.kmeans_balanced``'s adjust_centers: each iteration, clusters
    whose GLOBAL (psum'd) size falls at or below ``threshold · n/K`` are
    re-seeded toward a donor row from a big (size ≥ average) cluster —
    new_center = (wc·center[donor's cluster] + donor)/(wc+1), wc =
    min(size, 7), exactly the reference rescue but fed by the mesh-wide
    counts. The donor pool is sampled once host-side and replicated, so
    the rescue is pure replicated math and every device stays consistent
    (the rotation of pool slots per iteration stands in for the
    single-chip trainer's per-iteration resampling)."""
    res = ensure_resources(res)
    if key is None:
        key = res.next_key()
    x = jnp.asarray(x).astype(jnp.float32)
    n, dim = x.shape
    size = comms.size
    shard = cdiv(n, size)
    n_pad = shard * size
    if n_pad != n:
        x = jnp.pad(x, ((0, n_pad - n), (0, 0)))
    xs = comms.shard(x, P(comms.axis, None))
    # init must consume `key` exactly as the pre-balanced trainer did so a
    # fixed seed reproduces the same clustering when balancing is off
    init = jax.random.choice(key, n, (n_clusters,), replace=False)
    centers0 = comms.shard(jnp.asarray(x)[jnp.sort(init)], P(None, None))
    balanced = balance_threshold is not None
    if balanced:
        dkey = jax.random.fold_in(key, 1)
        pick = jax.random.randint(dkey, (int(donor_pool),), 0, n)
        donors0 = comms.shard(jnp.asarray(x)[pick], P(None, None))

    def _rescue(it, new_c, counts, donors):
        avg = jnp.float32(n) / n_clusters
        starving = counts <= avg * jnp.float32(balance_threshold)
        big = counts >= avg
        # donor labels vs the freshly updated centers (tiny pool matmul)
        cn = jnp.sum(new_c * new_c, -1)
        dd = cn[None, :] - 2.0 * donors @ new_c.T
        dlab = jnp.argmin(dd, axis=1)
        pool_ok = big[dlab]
        order = jnp.argsort(~pool_ok)  # good donors first (stable)
        drows, dlab = donors[order], dlab[order]
        n_good = jnp.sum(pool_ok.astype(jnp.int32))
        slot = (jnp.arange(n_clusters) + it * 131) % jnp.maximum(n_good, 1)
        have = (n_good > 0) & starving
        wc = jnp.minimum(counts, 7.0)[:, None]
        resc = (wc * new_c[dlab[slot]] + drows[slot]) / (wc + 1.0)
        return jnp.where(have[:, None], resc, new_c)

    def local(x_loc, c0, donors):
        rank = comms.rank()
        base = rank * shard
        valid = (jnp.arange(shard) + base) < n

        def step(c, it):
            cn = jnp.sum(c * c, -1)
            d = cn[None, :] - 2.0 * jax.lax.dot_general(
                x_loc, c, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
            labels = jnp.argmin(d, axis=1)
            w = valid.astype(jnp.float32)
            sums = jnp.zeros((n_clusters, dim), jnp.float32).at[labels].add(
                x_loc * w[:, None])
            counts = jnp.zeros((n_clusters,), jnp.float32).at[labels].add(w)
            sums = comms.allreduce(sums)  # psum over ICI
            counts = comms.allreduce(counts)
            new_c = jnp.where(counts[:, None] > 0,
                              sums / jnp.maximum(counts, 1.0)[:, None], c)
            if balanced:
                new_c = _rescue(it, new_c, counts, donors)
            return new_c, None

        c_final, _ = jax.lax.scan(step, c0, jnp.arange(n_iters))
        cn = jnp.sum(c_final * c_final, -1)
        d = cn[None, :] - 2.0 * x_loc @ c_final.T
        labels = jnp.argmin(d, axis=1).astype(jnp.int32)
        return c_final, labels

    out_specs = (P(None, None), P(comms.axis))
    if balanced:
        fn = comms.run(local, (P(comms.axis, None), P(None, None),
                               P(None, None)), out_specs)
        centers, labels = jax.jit(fn)(xs, centers0, donors0)
    else:
        fn = comms.run(lambda xl, c0: local(xl, c0, None),
                       (P(comms.axis, None), P(None, None)), out_specs)
        centers, labels = jax.jit(fn)(xs, centers0)
    return centers, labels[:n]


# ----------------------------------------------------- sharded cagra


class ShardedCagra:
    """A CAGRA index partitioned over a mesh axis: each device owns the
    graph + dataset of its row shard; queries replicate; per-shard beam
    searches merge over ICI (raft-dask-style MNMG deployment of a
    graph index)."""

    def __init__(self, comms: Comms, datasets, graphs, metric: DistanceType,
                 n_rows: int, bounds):
        self.comms = comms
        self.datasets = datasets  # [S, shard_pad, dim]
        self.graphs = graphs  # [S, shard_pad, degree] local ids
        self.metric = metric
        self.n_rows = n_rows
        self.bounds = bounds  # [S + 1] row offsets per shard
        self._datasets_bf16 = None  # lazy bf16 copies for scan_dtype

    def ensure_scan_datasets(self):
        if self._datasets_bf16 is None:
            self._datasets_bf16 = self.datasets.astype(jnp.bfloat16)
        return self._datasets_bf16


@tracing.range("sharded.build_cagra")
def build_cagra(
    comms: Comms,
    dataset,
    params=None,
    res: Optional[Resources] = None,
) -> ShardedCagra:
    """Per-shard CAGRA builds over row partitions, dispatched concurrently
    one shard per device (see _map_shards).

    Multi-controller contract: every process must pass the IDENTICAL full
    ``dataset`` and an identically-seeded ``res`` (see build_ivf_pq)."""
    from raft_tpu.neighbors import cagra

    res = ensure_resources(res)
    params = params or cagra.IndexParams()
    dataset = np.asarray(dataset)
    n, dim = dataset.shape
    bounds = shard_bounds(comms.size, n)

    def one(r, shard_res):
        lo, hi = bounds[r], bounds[r + 1]
        idx = cagra.build(dataset[lo:hi], params, res=shard_res)
        return np.asarray(idx.dataset), np.asarray(idx.graph)

    subs = _map_shards(comms, one, res, spans=np.diff(bounds))
    # padding rows point at node 0 and are never seeded (their distances
    # are real but they are unreachable unless linked)
    return ShardedCagra(
        comms,
        _stack_sharded(comms, {r: s[0] for r, s in subs.items()}),
        _stack_sharded(comms, {r: s[1] for r, s in subs.items()}),
        params.metric, n, bounds)


@tracing.range("sharded.search_cagra")
def search_cagra(
    index: ShardedCagra,
    queries,
    k: int,
    params=None,
    res: Optional[Resources] = None,
    merge_mode: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """SPMD CAGRA search: per-device beam search over its shard's graph,
    local ids mapped to global row ids, then the planned cross-chip top-k
    merge over ICI (``merge_mode``, docs/sharding.md)."""
    from raft_tpu.neighbors import cagra

    _SHARDED_SEARCHES.labels("cagra").inc()
    ensure_resources(res)
    params = params or cagra.SearchParams()
    comms = index.comms
    queries = jnp.asarray(queries)
    nq = queries.shape[0]
    minimize = index.metric != DistanceType.InnerProduct
    size = comms.size
    shard_rows = jnp.asarray(
        np.diff(index.bounds).astype(np.int32))  # valid rows per shard
    base = jnp.asarray(index.bounds[:-1].astype(np.int32))
    # same resolved beam plan as the single-host engine (seeds scale with
    # num_random_samplings and may exceed the buffer — they enter through
    # the merge), sized to the per-shard row count
    itopk, width, max_iter, n_seeds = cagra.resolve_search_plan(
        params, k, int(index.datasets.shape[1]))
    degree = index.graphs.shape[2]
    key = jax.random.fold_in(
        jax.random.key(params.rand_xor_mask & 0x7FFFFFFF), nq)
    empty = jnp.zeros((0,), jnp.uint32)
    fast_scan = getattr(params, "scan_dtype", None) is not None
    if fast_scan:
        if jnp.dtype(params.scan_dtype) != jnp.bfloat16:
            raise ValueError(
                f"scan_dtype={params.scan_dtype!r}: only bfloat16 is "
                "supported")
        if index.datasets.dtype != jnp.float32:
            raise ValueError("scan_dtype requires an fp32 dataset")

    def local_scan(q_rep, ds, sds, gr, n_valid, b):
        # per-shard seeds within the shard's valid rows
        rank = comms.rank()
        seeds = jax.random.randint(
            jax.random.fold_in(key, rank), (q_rep.shape[0], n_seeds), 0,
            jnp.maximum(n_valid[0], 1), jnp.int32)
        v, i = cagra.search_core(
            q_rep, ds[0], sds[0], gr[0], seeds, empty, index.metric, int(k),
            itopk, width, max_iter, False, fast_scan)
        # local → global ids; mask out padding rows
        pad_hit = (i < 0) | (i >= n_valid[0])
        gid = jnp.where(pad_hit, -1, i + b[0])
        v = jnp.where(pad_hit, jnp.inf if minimize else -jnp.inf, v)
        return v, gid

    ax = comms.axis
    in_specs = (P(None, None), P(ax, None, None), P(ax, None, None),
                P(ax, None, None), P(ax), P(ax))
    q = comms.shard(queries, P(None, None))
    # bf16 scan copies are cached on the index (one cast, reused per search)
    scan_ds = index.ensure_scan_datasets() if fast_scan else index.datasets
    args = (q, index.datasets, scan_ds, index.graphs,
            comms.shard(shard_rows, P(ax)), comms.shard(base, P(ax)))
    sink = _span_sink()
    if sink is not None:
        return _instrumented_search(comms, local_scan, in_specs, args,
                                    "cagra", nq, int(k), minimize, sink)

    plan = plan_sharded_search(
        comms, "cagra", index.n_rows, index.bounds, nq, int(k), int(k),
        "xla", merge_mode=merge_mode)
    _record_plan(plan, merge_mode,
                 {"itopk": itopk, "search_width": width})

    def local(q_rep, ds, sds, gr, n_valid, b):
        v, gid = local_scan(q_rep, ds, sds, gr, n_valid, b)
        return _plan_merge(comms, plan, v, gid, minimize)

    fn = comms.run(local, in_specs, (P(None, None), P(None, None)))
    return jax.jit(fn)(*args)


# --------------------------------------------------- sharded ivf_flat search


class ShardedIvfFlat:
    """An IVF-Flat index partitioned over a mesh axis: each device owns a
    full local index over its row shard (the raft-dask deployment shape);
    search is one SPMD program with an ICI candidate merge."""

    def __init__(self, comms: Comms, centers, list_data, list_indices,
                 list_sizes, metric: DistanceType, n_rows: int,
                 overflow_data=None, overflow_indices=None):
        self.comms = comms
        # all leading-axis [size, ...] stacked per-shard arrays
        self.centers = centers  # [S, L, dim]
        self.list_data = list_data  # [S, L, pad, dim]
        self.list_indices = list_indices  # [S, L, pad] global ids
        self.list_sizes = list_sizes  # [S, L]
        self.metric = metric
        self.n_rows = n_rows
        # per-shard budget-capped spill blocks (global ids; [S, O, dim] /
        # [S, O], O = max over shards, -1-padded) — each device scans its
        # own block alongside its probed lists
        self.overflow_data = overflow_data
        self.overflow_indices = overflow_indices
        # full-mesh restore always serves every row (degraded restores go
        # through the elastic classes, which compute a real fraction)
        self.coverage = 1.0


@tracing.range("sharded.build_ivf_flat")
def build_ivf_flat(
    comms: Comms,
    dataset,
    params=None,
    res: Optional[Resources] = None,
) -> ShardedIvfFlat:
    """Build per-shard IVF-Flat indexes over row partitions with global ids
    (host-orchestrated like raft-dask's per-worker build; the per-shard
    build itself is the single-chip path).

    Multi-controller contract: every process must pass the IDENTICAL full
    ``dataset`` and an identically-seeded ``res`` (see build_ivf_pq)."""
    from raft_tpu.neighbors import ivf_flat

    res = ensure_resources(res)
    params = params or ivf_flat.IndexParams()
    dataset = np.asarray(dataset)
    n = len(dataset)
    size = comms.size
    bounds = shard_bounds(size, n)
    _check_n_lists(bounds, params.n_lists, n, size)

    def one(r, shard_res):
        lo, hi = bounds[r], bounds[r + 1]
        idx = ivf_flat.build(dataset[lo:hi], params, res=shard_res)
        # rewrite ids to global row ids (spilled rows included)
        gl_idx = np.asarray(idx.list_indices)
        gl_idx = np.where(gl_idx >= 0, gl_idx + lo, -1).astype(np.int32)
        return idx, gl_idx, _globalize_overflow_ids(idx, lo)

    subs = _map_shards(comms, one, res, spans=np.diff(bounds))
    out = _assemble_sharded_ivf_flat(comms, subs, params, n)
    out.bounds = bounds
    return out


def _globalize_overflow_ids(idx, lo: int) -> np.ndarray:
    over = np.asarray(idx.overflow_indices)
    return np.where(over >= 0, over + lo, -1).astype(np.int32)


@tracing.range("sharded.build_ivf_flat_from_file")
def build_ivf_flat_from_file(
    comms: Comms,
    path: str,
    params=None,
    res: Optional[Resources] = None,
    batch_rows: int = 1 << 18,
    dtype=None,
    max_train_rows: Optional[int] = None,
) -> ShardedIvfFlat:
    """Streamed MNMG IVF-Flat build: each shard builds out-of-core from its
    row span of the fbin file (ids file-absolute), then shard state is
    placed across the mesh for SPMD search."""
    from raft_tpu.neighbors import ivf_flat, ooc

    params = params or ivf_flat.IndexParams()
    return _build_sharded_from_file(
        comms, path, params, ooc.build_ivf_flat_from_file,
        _assemble_sharded_ivf_flat, res, batch_rows, dtype, max_train_rows)


def _build_sharded_from_file(comms, path, params, ooc_builder, assembler,
                             res, batch_rows, dtype, max_train_rows):
    """Shared streamed-MNMG skeleton: row-span bounds, per-shard ooc build
    (file-absolute ids), mesh placement via ``assembler``."""
    from raft_tpu import native

    res = ensure_resources(res)
    n, _ = native.read_bin_header(path)
    size = comms.size
    bounds = shard_bounds(size, n)
    _check_n_lists(bounds, params.n_lists, n, size)

    def one(r, shard_res):
        lo, hi = int(bounds[r]), int(bounds[r + 1])
        idx = ooc_builder(
            path, params, res=shard_res, batch_rows=batch_rows, dtype=dtype,
            max_train_rows=max_train_rows, row_range=(lo, hi))
        # ids are file-absolute already, overflow ids included
        return idx, np.asarray(idx.list_indices), np.asarray(
            idx.overflow_indices)

    subs = _map_shards(comms, one, res, spans=np.diff(bounds))
    out = assembler(comms, subs, params, n)
    out.bounds = bounds
    return out


def _assemble_sharded_ivf_flat(comms: Comms, subs, params, n: int
                               ) -> ShardedIvfFlat:
    """Place per-shard ``{r: (Index, global_ids, global_overflow_ids)}``
    as mesh-sharded [S, ...] state (ragged list pads equalized per field;
    no one-host staging)."""
    any_overflow = _global_any(
        comms, any(len(go) for _, _, go in subs.values()))
    return ShardedIvfFlat(
        comms,
        _stack_sharded(comms, {r: np.asarray(i.centers)
                               for r, (i, _, _) in subs.items()}),
        _stack_sharded(comms, {r: np.asarray(i.list_data)
                               for r, (i, _, _) in subs.items()}),
        _stack_sharded(comms, {r: g for r, (_, g, _) in subs.items()},
                       fill=-1),
        _stack_sharded(comms, {r: np.asarray(i.list_sizes)
                               for r, (i, _, _) in subs.items()}),
        params.metric, n,
        overflow_data=_stack_sharded(
            comms, {r: np.asarray(i.overflow_data)
                    for r, (i, _, _) in subs.items()})
        if any_overflow else None,
        overflow_indices=_stack_sharded(
            comms, {r: go for r, (_, _, go) in subs.items()}, fill=-1)
        if any_overflow else None)


# ----------------------------------------------------- sharded ivf_pq


class ShardedIvfPq:
    """An IVF-PQ index partitioned over a mesh axis (BASELINE target #4:
    DEEP-100M pq_dim=64 sharded over ICI): each device owns a full local
    IVF-PQ index over its row shard; search is one SPMD program with an ICI
    top-k merge. Two storage engines (the single-chip scan_mode pair):
    ``cache`` keeps the decoded-residual scan cache resident
    ([S, L, pad, rot] bf16 — fastest MXU scan), ``lut`` keeps only the
    packed codes + codebooks ([S, L, pad, B] u8 — ~2× more rows per chip
    at pq_bits=8, the DEEP-100M/8 memory-lean shape)."""

    def __init__(self, comms: Comms, centers, rotation, list_indices,
                 list_sizes, metric: DistanceType, n_rows: int,
                 list_decoded=None, decoded_norms=None, codebooks=None,
                 list_codes=None, per_cluster: bool = False,
                 pq_dim: int = 0, pq_bits: int = 8,
                 overflow_decoded=None, overflow_norms=None,
                 overflow_indices=None):
        self.comms = comms
        # all leading-axis [S, ...] stacked per-shard arrays
        self.centers = centers  # [S, L, dim]
        self.rotation = rotation  # [S, rot, dim]
        self.list_indices = list_indices  # [S, L, pad] global ids
        self.list_sizes = list_sizes  # [S, L]
        self.metric = metric
        self.n_rows = n_rows
        # cache engine state (None when built with scan_mode="lut")
        self.list_decoded = list_decoded  # [S, L, pad, rot] bf16
        self.decoded_norms = decoded_norms  # [S, L, pad] f32
        # lut engine state (None when built with scan_mode="cache")
        self.codebooks = codebooks  # [S, G, book, pq_len]
        self.list_codes = list_codes  # [S, L, pad, n_bytes] u8
        self.per_cluster = per_cluster
        self.pq_dim = pq_dim
        self.pq_bits = pq_bits
        # per-shard budget-capped spill blocks, decoded to full rotated
        # vectors (see ivf_pq.ensure_overflow_decoded); global ids,
        # [S, O, rot] / [S, O] — shared by both engines
        self.overflow_decoded = overflow_decoded
        self.overflow_norms = overflow_norms
        self.overflow_indices = overflow_indices
        # full-mesh restore always serves every row (degraded restores go
        # through the elastic classes, which compute a real fraction)
        self.coverage = 1.0


@tracing.range("sharded.build_ivf_pq")
def build_ivf_pq(
    comms: Comms,
    dataset,
    params=None,
    res: Optional[Resources] = None,
    scan_mode: str = "cache",
    scan_cache_dtype=jnp.bfloat16,
) -> ShardedIvfPq:
    """Build per-shard IVF-PQ indexes over row partitions with global ids,
    dispatched concurrently one shard per device. ``scan_mode="cache"``
    materializes the decoded scan cache per shard (fastest search);
    ``"lut"`` keeps only packed codes + codebooks resident (memory-lean,
    VERDICT r1 #7 — roughly doubles the max shard at pq_bits=8).
    ``scan_cache_dtype`` also sets the overflow-block decode dtype for
    *lut* builds — pin it to fp32 when comparing engines bit-for-bit.

    Multi-controller contract: every process must pass the IDENTICAL full
    ``dataset`` and an identically-seeded ``res`` — each process slices its
    own shards from it, and divergent inputs silently produce inconsistent
    shard state. For datasets too big to replicate, use
    :func:`build_ivf_pq_from_file` (per-process row spans from a shared
    file)."""
    from raft_tpu.neighbors import ivf_pq

    res = ensure_resources(res)
    params = params or ivf_pq.IndexParams()
    dataset = np.asarray(dataset)
    n = len(dataset)
    size = comms.size
    bounds = shard_bounds(size, n)
    _check_n_lists(bounds, params.n_lists, n, size)

    def one(r, shard_res):
        lo, hi = bounds[r], bounds[r + 1]
        idx = ivf_pq.build(dataset[lo:hi], params, res=shard_res)
        gl_idx = np.asarray(idx.list_indices)
        gl_idx = np.where(gl_idx >= 0, gl_idx + lo, -1).astype(np.int32)
        return idx, gl_idx, _globalize_overflow_ids(idx, lo)

    subs = _map_shards(comms, one, res, spans=np.diff(bounds))
    out = _assemble_sharded_ivf_pq(comms, subs, params, n,
                                   scan_mode=scan_mode,
                                   scan_cache_dtype=scan_cache_dtype)
    out.bounds = bounds
    return out


@tracing.range("sharded.build_ivf_pq_from_file")
def build_ivf_pq_from_file(
    comms: Comms,
    path: str,
    params=None,
    res: Optional[Resources] = None,
    batch_rows: int = 1 << 18,
    dtype=None,
    max_train_rows: Optional[int] = None,
    scan_mode: str = "cache",
    scan_cache_dtype=jnp.bfloat16,
) -> ShardedIvfPq:
    """Streamed MNMG IVF-PQ build (BASELINE target #4 at DEEP-100M scale):
    each shard's index is built out-of-core from its row span of the fbin
    file (neighbors.ooc two-pass pipeline, ids file-absolute; the file must
    be reachable from every process in multi-controller runs), then shard
    state is placed across the mesh for SPMD search. ``scan_mode="lut"``
    keeps only packed codes resident — the DEEP-100M/8 shape."""
    from raft_tpu.neighbors import ivf_pq, ooc

    params = params or ivf_pq.IndexParams()
    return _build_sharded_from_file(
        comms, path, params, ooc.build_ivf_pq_from_file,
        functools.partial(_assemble_sharded_ivf_pq, scan_mode=scan_mode,
                          scan_cache_dtype=scan_cache_dtype),
        res, batch_rows, dtype, max_train_rows)


@tracing.range("sharded.build_ivf_pq_from_file_pod")
def build_ivf_pq_from_file_pod(
    comms: Comms,
    path: str,
    params=None,
    res: Optional[Resources] = None,
    batch_rows: int = 1 << 18,
    dtype=None,
    max_train_rows: Optional[int] = None,
    scan_mode: str = "lut",
    scan_cache_dtype=jnp.bfloat16,
    balance_threshold: Optional[float] = 0.25,
) -> ShardedIvfPq:
    """Pod-scale streamed IVF-PQ build (the DEEP-100M path): ONE mesh-wide
    balanced k-means trains the shared coarse centers (``kmeans_fit``'s
    psum pattern scaled past one chip), PQ rotation + codebooks train once
    on the pooled sample, then every shard streams its row span through
    the shared quantizer — the sharded PQ encode.

    Unlike :func:`build_ivf_pq_from_file` (each shard trains its OWN
    quantizer over its span), all shards agree on the coarse partition, so
    ``n_lists`` is bounded by the trainset size, not rows-per-shard, and
    probe routing is consistent across the mesh — the shape the chunked
    ground-truth oracle in tools/deep100m_dryrun.py verifies recall
    against. Training memory is one pooled sample (≤ ``max_train_rows``
    rows); encode memory is one shard's packed codes + a batch."""
    from raft_tpu import native
    from raft_tpu.neighbors import ivf_pq, ooc

    res = ensure_resources(res)
    params = params or ivf_pq.IndexParams()
    n, _ = native.read_bin_header(path)
    size = comms.size
    bounds = shard_bounds(size, n)
    n_train = max(int(n * params.kmeans_trainset_fraction), params.n_lists)
    if max_train_rows is not None:
        n_train = min(n_train, int(max_train_rows))
    if params.n_lists > n_train:
        raise ValueError(f"n_lists={params.n_lists} > trainset rows "
                         f"{n_train}; raise max_train_rows or "
                         f"kmeans_trainset_fraction")
    # per-shard strided samples pooled into one mesh-wide trainset
    per = cdiv(n_train, size)
    trainset = np.concatenate([
        ooc.sample_rows_from_file(
            path, per, seed=r, dtype=dtype, batch_rows=batch_rows,
            row_range=(int(bounds[r]), int(bounds[r + 1])))
        for r in range(size)], axis=0).astype(np.float32)
    centers, _ = kmeans_fit(comms, trainset, params.n_lists,
                            n_iters=params.kmeans_n_iters, res=res,
                            balance_threshold=balance_threshold)
    train_params = dataclasses.replace(params, kmeans_trainset_fraction=1.0,
                                       add_data_on_build=False)
    trained = ivf_pq.build(trainset, train_params, res=res,
                           coarse_centers=np.asarray(centers))
    del trainset

    def one(r, shard_res):
        lo, hi = int(bounds[r]), int(bounds[r + 1])
        idx = ooc.build_ivf_pq_from_file(
            path, params, res=shard_res, batch_rows=batch_rows, dtype=dtype,
            row_range=(lo, hi), trained_index=trained)
        # ids are file-absolute already, overflow ids included
        return idx, np.asarray(idx.list_indices), np.asarray(
            idx.overflow_indices)

    subs = _map_shards(comms, one, res, spans=np.diff(bounds))
    out = _assemble_sharded_ivf_pq(comms, subs, params, n,
                                   scan_mode=scan_mode,
                                   scan_cache_dtype=scan_cache_dtype)
    out.bounds = bounds
    return out


def _assemble_sharded_ivf_pq(comms: Comms, subs, params, n: int,
                             scan_mode: str = "cache",
                             scan_cache_dtype=jnp.bfloat16) -> ShardedIvfPq:
    """Place per-shard ``{r: (Index, global_ids)}`` as mesh-sharded [S, ...]
    state (ragged list pads equalized per field; no one-host staging).
    ``scan_mode`` picks the resident engine: decoded cache or packed
    codes + codebooks."""
    from raft_tpu.neighbors import ivf_pq

    if scan_mode not in ("cache", "lut"):
        raise ValueError(f"unknown scan_mode: {scan_mode!r}")
    first = next(iter(subs.values()))[0]
    common = dict(
        centers=_stack_sharded(comms, {r: np.asarray(i.centers)
                                       for r, (i, _, _) in subs.items()}),
        rotation=_stack_sharded(comms, {r: np.asarray(i.rotation)
                                        for r, (i, _, _) in subs.items()}),
        list_indices=_stack_sharded(comms, {r: g for r, (_, g, _)
                                            in subs.items()}, fill=-1),
        list_sizes=_stack_sharded(comms, {r: np.asarray(i.list_sizes)
                                          for r, (i, _, _) in subs.items()}),
    )
    if _global_any(comms, any(len(go) for _, _, go in subs.values())):
        for idx, _, _ in subs.values():
            ivf_pq.ensure_overflow_decoded(idx, scan_cache_dtype)
        # all-shard equalized decode dtype; a shard with no spill holds a
        # [0, rot] block and pads to the global max with zeros/-1
        common.update(
            overflow_decoded=_stack_sharded(
                comms, {r: np.asarray(
                    i.overflow_decoded if i.overflow_decoded is not None
                    else np.zeros((0, i.rot_dim),
                                  dtype=jnp.dtype(scan_cache_dtype)))
                    for r, (i, _, _) in subs.items()}),
            overflow_norms=_stack_sharded(
                comms, {r: np.asarray(
                    i.overflow_norms if i.overflow_norms is not None
                    else np.zeros((0,), np.float32))
                    for r, (i, _, _) in subs.items()}),
            overflow_indices=_stack_sharded(
                comms, {r: go for r, (_, _, go) in subs.items()},
                fill=-1))
    if scan_mode == "cache":
        for idx, _, _ in subs.values():
            ivf_pq.ensure_scan_cache(idx, scan_cache_dtype)
        return ShardedIvfPq(
            comms, **common, metric=params.metric, n_rows=n,
            list_decoded=_stack_sharded(
                comms, {r: np.asarray(i.list_decoded)
                        for r, (i, _, _) in subs.items()}),
            decoded_norms=_stack_sharded(
                comms, {r: np.asarray(i.decoded_norms)
                        for r, (i, _, _) in subs.items()}))
    return ShardedIvfPq(
        comms, **common, metric=params.metric, n_rows=n,
        codebooks=_stack_sharded(comms, {r: np.asarray(i.codebooks)
                                         for r, (i, _, _) in subs.items()}),
        list_codes=_stack_sharded(comms, {r: np.asarray(i.list_codes)
                                          for r, (i, _, _) in subs.items()}),
        per_cluster=(first.params.codebook_kind
                     == ivf_pq.CodebookGen.PER_CLUSTER),
        pq_dim=first.pq_dim, pq_bits=first.pq_bits)


def _resolve_pq_scan_mode(params, list_decoded, list_codes) -> str:
    """Scan-engine resolution shared by the mesh and elastic searches —
    "auto" follows the engine the index was built with."""
    if params.scan_mode not in ("auto", "cache", "lut"):
        raise ValueError(f"unknown scan_mode: {params.scan_mode!r}")
    mode = params.scan_mode
    if mode == "auto":
        mode = "cache" if list_decoded is not None else "lut"
    if mode == "cache" and list_decoded is None:
        raise ValueError(
            'index holds no decoded cache (built scan_mode="lut"); '
            'search with scan_mode="lut"/"auto" or rebuild')
    if mode == "lut" and list_codes is None:
        raise ValueError(
            'index holds no packed codes (built scan_mode="cache"); '
            'search with scan_mode="cache"/"auto" or rebuild')
    return mode


def _pq_tiles(mode: str, n_probes: int, res: Resources, list_decoded,
              list_codes, pq_dim: int, pq_bits: int,
              lut_itemsize: int = 4, dist_itemsize: int = 4
              ) -> Tuple[int, int]:
    """Workspace-bounded (q_tile, probe_tile), shared by the mesh and
    elastic searches so single-chip serving tiles can't desync from mesh
    tiles. Shapes are [..., pad, last] with any number of leading axes.
    The cache engine scans all probes in one pass (probe_tile =
    n_probes); the LUT engine's tiles come from the true-peak accounting
    (ivf_pq.plan_lut_tiles), engaging its probe loop when the budget
    demands it."""
    from raft_tpu.neighbors import ivf_pq

    if mode == "cache":
        list_pad = list_decoded.shape[-2]
        rot = list_decoded.shape[-1]
        per_q = n_probes * list_pad * (rot * 2 + 12)
        q_tile = int(np.clip(res.workspace_limit_bytes // max(per_q, 1),
                             1, 1024))
        if q_tile >= 8:
            q_tile -= q_tile % 8
        return q_tile, n_probes
    return ivf_pq.plan_lut_tiles(
        n_probes, list_codes.shape[-2], pq_dim, pq_bits,
        res.workspace_limit_bytes, lut_itemsize, dist_itemsize)


@tracing.range("sharded.search_ivf_pq")
def search_ivf_pq(
    index: ShardedIvfPq,
    queries,
    k: int,
    params=None,
    res: Optional[Resources] = None,
    merge_mode: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """SPMD IVF-PQ search: per-device ADC scan of its shard's probed lists
    (cache or LUT engine, per ``params.scan_mode`` — "auto" follows the
    engine the index was built with), then the planned cross-chip top-k
    merge over ICI (``merge_mode``, docs/sharding.md)."""
    from raft_tpu.neighbors import ivf_pq

    _SHARDED_SEARCHES.labels("ivf_pq").inc()
    res = ensure_resources(res)
    params = params or ivf_pq.SearchParams()
    comms = index.comms
    queries = jnp.asarray(queries)
    minimize = index.metric != DistanceType.InnerProduct
    n_lists = index.centers.shape[1]
    n_probes = int(min(params.n_probes, n_lists))
    select_recall = float(getattr(params, "select_recall", 1.0))
    mode = _resolve_pq_scan_mode(params, index.list_decoded,
                                 index.list_codes)
    empty_filter = jnp.zeros((0,), jnp.uint32)
    ax = comms.axis

    has_overflow = index.overflow_decoded is not None
    over_ops = ((index.overflow_decoded, index.overflow_norms,
                 index.overflow_indices) if has_overflow else ())
    over_specs = ((P(ax, None, None), P(ax, None), P(ax, None))
                  if has_overflow else ())

    def unpack_over(args):
        # [1, O, ...] shard_map blocks → per-device overflow kwargs
        if not has_overflow:
            return {}
        od, on, oi = args
        return dict(overflow_decoded=od[0], overflow_norms=on[0],
                    overflow_indices=oi[0], has_overflow=True)

    q = comms.shard(queries, P(None, None))

    if mode == "cache":
        q_tile, _ = _pq_tiles("cache", n_probes, res, index.list_decoded,
                              index.list_codes, index.pq_dim, index.pq_bits)

        def local_scan(q_rep, c, ro, ld, dn, li, ls, *over):
            return ivf_pq.search_cache_core(
                q_rep, c[0], ro[0], ld[0], dn[0], li[0], ls[0], empty_filter,
                index.metric, int(k), n_probes, q_tile, False,
                select_recall=select_recall, **unpack_over(over))

        in_specs = (P(None, None), P(ax, None, None), P(ax, None, None),
                    P(ax, None, None, None), P(ax, None, None),
                    P(ax, None, None), P(ax, None)) + over_specs
        args = (q, index.centers, index.rotation, index.list_decoded,
                index.decoded_norms, index.list_indices, index.list_sizes,
                *over_ops)
    else:
        # LUT engine: packed codes only (the DEEP-100M/8 memory-lean shape)
        q_tile, probe_tile = _pq_tiles(
            "lut", n_probes, res, index.list_decoded, index.list_codes,
            index.pq_dim, index.pq_bits,
            jnp.dtype(params.lut_dtype).itemsize,
            jnp.dtype(params.internal_distance_dtype).itemsize)
        lut_dtype = jnp.dtype(params.lut_dtype).name
        dist_dtype = jnp.dtype(params.internal_distance_dtype).name

        def local_scan(q_rep, c, ro, cb, lc, li, ls, *over):
            return ivf_pq.search_lut_core(
                q_rep, c[0], ro[0], cb[0], lc[0], li[0], ls[0], empty_filter,
                index.metric, int(k), n_probes, q_tile, index.per_cluster,
                index.pq_dim, index.pq_bits, False, lut_dtype, dist_dtype,
                select_recall=select_recall, probe_tile=probe_tile,
                **unpack_over(over))

        in_specs = (P(None, None), P(ax, None, None), P(ax, None, None),
                    P(ax, None, None, None), P(ax, None, None, None),
                    P(ax, None, None), P(ax, None)) + over_specs
        args = (q, index.centers, index.rotation, index.codebooks,
                index.list_codes, index.list_indices, index.list_sizes,
                *over_ops)

    sink = _span_sink()
    if sink is not None:
        return _instrumented_search(comms, local_scan, in_specs, args,
                                    "ivf_pq", queries.shape[0], int(k),
                                    minimize, sink)

    tiles = {"q_tile": int(q_tile)}
    if mode == "lut":
        tiles["probe_tile"] = int(probe_tile)
    plan = plan_sharded_search(
        comms, "ivf_pq", index.n_rows,
        getattr(index, "bounds", None), queries.shape[0], int(k), int(k),
        mode, merge_mode=merge_mode, mask_invalid=True, tiles=tiles)
    _record_plan(plan, merge_mode, {"n_probes": n_probes})

    fn = comms.run(lambda *a: _plan_merge(comms, plan, *local_scan(*a),
                                          minimize),
                   in_specs, (P(None, None), P(None, None)))
    return jax.jit(fn)(*args)


@tracing.range("sharded.search_ivf_flat")
def search_ivf_flat(
    index: ShardedIvfFlat,
    queries,
    k: int,
    params=None,
    res: Optional[Resources] = None,
    merge_mode: str = "auto",
) -> Tuple[jax.Array, jax.Array]:
    """SPMD search: every device scans its local shard's probed lists
    (reusing the single-chip search core inside shard_map), then the
    planned cross-chip top-k merge over ICI (``merge_mode``,
    docs/sharding.md)."""
    from raft_tpu.neighbors import ivf_flat

    _SHARDED_SEARCHES.labels("ivf_flat").inc()
    res = ensure_resources(res)
    params = params or ivf_flat.SearchParams()
    comms = index.comms
    queries = jnp.asarray(queries)
    minimize = index.metric != DistanceType.InnerProduct
    n_lists = index.centers.shape[1]
    n_probes = int(min(params.n_probes, n_lists))
    list_pad = index.list_data.shape[2]
    per_q = n_probes * list_pad * queries.shape[1] * 4 * 2
    q_tile = int(np.clip(res.workspace_limit_bytes // max(per_q, 1), 1, 1024))
    if q_tile >= 8:
        q_tile -= q_tile % 8
    empty_filter = jnp.zeros((0,), jnp.uint32)
    fast_scan = getattr(params, "scan_dtype", None) is not None
    select_recall = float(getattr(params, "select_recall", 1.0))
    refine_mult = refine_multiplier(
        getattr(params, "refine_ratio", 4.0), fast_scan)
    if fast_scan:
        if jnp.dtype(params.scan_dtype) != jnp.bfloat16:
            raise ValueError(
                f"scan_dtype={params.scan_dtype!r}: only bfloat16 is "
                "supported")
        if index.list_data.dtype != jnp.float32:
            raise ValueError("scan_dtype requires fp32 list data")

    has_overflow = index.overflow_data is not None
    ax = comms.axis
    q = comms.shard(queries, P(None, None))
    if has_overflow:
        # each device scans its own spill block alongside its probed lists
        def local_scan(q_rep, c, ld, li, ls, od, oi):
            return ivf_flat.search_core(
                q_rep, c[0], ld[0], li[0], ls[0], empty_filter, index.metric,
                int(k), n_probes, q_tile, False, fast_scan=fast_scan,
                overflow_data=od[0], overflow_indices=oi[0],
                has_overflow=True, select_recall=select_recall,
                refine_mult=refine_mult)

        in_specs = (P(None, None), P(ax, None, None),
                    P(ax, None, None, None), P(ax, None, None), P(ax, None),
                    P(ax, None, None), P(ax, None))
        args = (q, index.centers, index.list_data, index.list_indices,
                index.list_sizes, index.overflow_data,
                index.overflow_indices)
    else:
        def local_scan(q_rep, c, ld, li, ls):
            return ivf_flat.search_core(
                q_rep, c[0], ld[0], li[0], ls[0], empty_filter, index.metric,
                int(k), n_probes, q_tile, False, fast_scan=fast_scan,
                select_recall=select_recall, refine_mult=refine_mult)

        in_specs = (P(None, None), P(ax, None, None),
                    P(ax, None, None, None), P(ax, None, None), P(ax, None))
        args = (q, index.centers, index.list_data, index.list_indices,
                index.list_sizes)

    sink = _span_sink()
    if sink is not None:
        return _instrumented_search(comms, local_scan, in_specs, args,
                                    "ivf_flat", queries.shape[0], int(k),
                                    minimize, sink)

    plan = plan_sharded_search(
        comms, "ivf_flat", index.n_rows,
        getattr(index, "bounds", None), queries.shape[0], int(k), int(k),
        "xla", merge_mode=merge_mode, mask_invalid=True,
        tiles={"q_tile": int(q_tile)})
    _record_plan(plan, merge_mode, {"n_probes": n_probes})

    fn = comms.run(lambda *a: _plan_merge(comms, plan, *local_scan(*a),
                                          minimize),
                   in_specs, (P(None, None), P(None, None)))
    return jax.jit(fn)(*args)


# ------------------------------------------------------------- persistence
#
# Checkpoint/resume for sharded indexes (the raft-dask role of per-worker
# local serialization): ONE file per shard rank (``prefix.rank<r>``), each
# written atomically by the controller process that addresses that shard,
# plus a per-prefix manifest naming every rank file with its whole-file
# digest. Deserialization collects whichever rank files carry the shards
# this process can address — a multi-hour from-file build no longer has to
# be rebuilt to be searched again. Older checkpoints (one multi-rank file
# per process) still load: readers key on the rank ids recorded *inside*
# each file, not on filenames.
#
# Fault model (docs/robustness.md): per-record crc + footer
# (core.serialize v2 framing) classifies a bad file as truncated vs
# corrupt; the manifest names files that are missing outright; and
# ``deserialize_*_elastic(..., allow_partial=True)`` restores around any
# of the three, reporting ``coverage`` instead of refusing the whole
# checkpoint.

_SHARD_SERIAL_VERSION = 1
_MANIFEST_VERSION = 1


class SearchResult(tuple):
    """(distances, indices) that still unpacks as a 2-tuple but carries
    ``coverage`` — the fraction of indexed rows actually searched (1.0 for
    a full index; < 1 after a degraded-mode restore) — so serving callers
    can decide whether degraded recall is acceptable per response."""

    def __new__(cls, distances, indices, coverage: float = 1.0):
        self = super().__new__(cls, (distances, indices))
        self.coverage = float(coverage)
        return self

    @property
    def distances(self):
        return self[0]

    @property
    def indices(self):
        return self[1]


def _local_shard_blocks(arr) -> dict:
    """{global shard rank r: np block} for this process's addressable
    shards of a ``P(axis, None, ...)``-sharded ``[S, ...]`` array."""
    out = {}
    for s in arr.addressable_shards:
        r = s.index[0].start or 0
        out[r] = np.asarray(s.data)[0]
    return out


def _write_field(w, block: np.ndarray) -> None:
    """bf16 has no stable .npy representation — store a uint16 view with
    a dtype flag."""
    is_bf16 = block.dtype == jnp.bfloat16
    w.scalar(1 if is_bf16 else 0, "<i4")
    w.array(block.view(np.uint16) if is_bf16 else block)


def _read_field(r) -> np.ndarray:
    is_bf16 = bool(r.scalar())
    a = r.array()
    return a.view(jnp.bfloat16) if is_bf16 else a


def _serialize_sharded(prefix: str, kind: str, scalars, fields) -> None:
    """``scalars``: [(value, dtype)], ``fields``: [arr or None] — every
    process writes one ATOMIC file per addressable shard rank
    (``prefix.rank<r>``) plus a manifest naming each file and its digest,
    so a single lost/corrupted file costs one shard, not the checkpoint."""
    import json

    from raft_tpu.core import serialize as ser

    present = [a is not None for a in fields]
    blocks = [(_local_shard_blocks(a) if p else None)
              for a, p in zip(fields, present)]
    local_ranks = sorted(next(b for b, p in zip(blocks, present) if p))
    size = int(next(a for a, p in zip(fields, present) if p).shape[0])
    entries = {}
    for r in local_ranks:
        path = f"{prefix}.rank{r}"
        with ser.writer_for(path) as stream:
            w = ser.IndexWriter(stream, kind, _SHARD_SERIAL_VERSION)
            for value, dtype in scalars:
                w.scalar(value, dtype)
            w.scalar(len(present), "<i4")
            for p in present:
                w.scalar(1 if p else 0, "<i4")
            w.scalar(1, "<i4")  # ranks in this file
            w.scalar(r, "<i4")
            for b, p in zip(blocks, present):
                if p:
                    _write_field(w, b[r])
            w.finish()
        entries[os.path.basename(path)] = {
            "ranks": [r],
            "bytes": os.path.getsize(path),
            "crc32": ser.file_crc32(path),
        }
    manifest = {
        "manifest_version": _MANIFEST_VERSION,
        "kind": kind,
        "size": size,
        "files": entries,
    }
    mpath = (f"{prefix}.manifest" if jax.process_count() == 1
             else f"{prefix}.manifest.p{jax.process_index()}")
    with ser.writer_for(mpath) as stream:
        stream.write(json.dumps(manifest, indent=1, sort_keys=True).encode())


def load_manifest(prefix: str) -> Optional[dict]:
    """Merged manifest for a checkpoint prefix (``prefix.manifest`` plus
    any multi-controller ``prefix.manifest.p<i>`` fragments), or None for
    pre-manifest checkpoints."""
    import glob as _glob
    import json

    paths = sorted(_glob.glob(_glob.escape(prefix) + ".manifest*"))
    merged: Optional[dict] = None
    for path in paths:
        if path.endswith((".tmp", )) or ".tmp." in path:
            continue
        with open(path, "rb") as f:
            m = json.load(f)
        if merged is None:
            merged = m
        else:
            if (m.get("kind") != merged.get("kind")
                    or m.get("size") != merged.get("size")):
                raise ValueError(
                    f"{path}: manifest fragment disagrees with others "
                    f"(kind/size) — stale fragments from a previous run?")
            merged["files"].update(m["files"])
    return merged


def verify_checkpoint(prefix: str) -> dict:
    """Pre-flight checkpoint validation against the manifest (TPU runbook:
    run this BEFORE burning a hardware window on a restore). Classifies
    every rank file as ``ok`` / ``missing`` / ``truncated`` / ``corrupt``
    and lists shard ranks with no healthy file. Returns
    ``{"ok": bool, "size": S, "files": {name: status}, "missing_ranks":
    [...], "coverage_ranks": [...]}``; raises FileNotFoundError when there
    is no manifest to verify against."""
    from raft_tpu.core import serialize as ser

    manifest = load_manifest(prefix)
    if manifest is None:
        raise FileNotFoundError(
            f"{prefix}.manifest not found — pre-manifest checkpoint; "
            f"re-serialize to get one, or restore with allow_partial "
            f"validation only")
    dirname = os.path.dirname(prefix) or "."
    statuses = {}
    healthy_ranks: set = set()
    for name, entry in sorted(manifest["files"].items()):
        path = os.path.join(dirname, name)
        if not os.path.exists(path):
            statuses[name] = "missing"
            continue
        nbytes = os.path.getsize(path)
        if nbytes < entry["bytes"]:
            statuses[name] = "truncated"
            continue
        if nbytes != entry["bytes"] or ser.file_crc32(path) != entry["crc32"]:
            statuses[name] = "corrupt"
            continue
        statuses[name] = "ok"
        healthy_ranks.update(entry["ranks"])
    size = int(manifest["size"])
    missing_ranks = sorted(set(range(size)) - healthy_ranks)
    for s in statuses.values():
        _CKPT_FILES.labels(s).inc()
    ok = not missing_ranks and all(s == "ok" for s in statuses.values())
    _CKPT_VERIFY.labels("ok" if ok else "unhealthy").inc()
    return {
        "ok": ok,
        "kind": manifest["kind"],
        "size": size,
        "files": statuses,
        "missing_ranks": missing_ranks,
        "coverage_ranks": sorted(healthy_ranks),
    }


def _addressable_ranks(comms: Comms) -> set:
    """Shard ranks whose devices this process can address."""
    me = jax.process_index()
    return {r for r in range(comms.size)
            if _shard_device(comms, r).process_index == me}


def _read_rank_file(path: str, kind: str, n_scalars: int, want_ranks):
    """Parse one rank file → (scalars, present, {rank: [field blocks]}).
    Blocks for ranks outside ``want_ranks`` are read and dropped (bounding
    host RAM at roughly one rank file). Raises IntegrityError (truncated/
    corrupt) or ValueError; never partially merges into shared state."""
    from raft_tpu.core import serialize as ser

    with open(path, "rb") as stream:
        r = ser.IndexReader(stream, kind, _SHARD_SERIAL_VERSION, name=path)
        s = [r.scalar() for _ in range(n_scalars)]
        n_fields = r.scalar()
        present = [bool(r.scalar()) for _ in range(n_fields)]
        n_local = r.scalar()
        local: dict = {}
        for _ in range(n_local):
            rank = int(r.scalar())
            keep = want_ranks is None or rank in want_ranks
            blocks = []
            for p in present:
                if p:
                    block = _read_field(r)
                    blocks.append(block if keep else None)
            local[rank] = blocks if keep else None
        r.finish()
    return s, present, local


def _deserialize_sharded(prefix: str, kind: str, n_scalars: int,
                         want_ranks=None, on_error: str = "raise"):
    """Read every ``prefix.rank*`` file; returns (scalars, parts, seen,
    errors) where ``parts`` is a list of {r: np block} per field (None =
    absent field) and ``errors`` maps path -> exception for files skipped
    under ``on_error="skip"``.

    Only ranks in ``want_ranks`` are RETAINED (non-addressable shards are
    read file-at-a-time and dropped), but EVERY rank seen is validated: a
    rank appearing twice means stale rank files from a previous run with a
    different process layout are mixed in — that raises even in skip mode,
    because silently picking one copy could resurrect outdated data.

    ``on_error="skip"`` is the degraded-mode path: a file that is
    truncated, corrupt, or unreadable contributes nothing (its ranks stay
    missing) instead of failing the restore — each file's blocks merge
    only after the whole file (footer included) validated."""
    import glob as _glob

    from raft_tpu.core.errors import IntegrityError

    if on_error not in ("raise", "skip"):
        raise ValueError(f"on_error={on_error!r}: use 'raise' or 'skip'")
    paths = sorted(p for p in _glob.glob(_glob.escape(prefix) + ".rank*")
                   if ".tmp." not in p)
    if not paths:
        raise FileNotFoundError(f"no shard files match {prefix}.rank*")
    scalars = None
    parts = None
    seen: dict = {}  # rank -> path
    errors: dict = {}  # path -> exception
    for path in paths:
        try:
            s, present, local = _read_rank_file(
                path, kind, n_scalars, want_ranks)
        except (IntegrityError, ValueError, OSError) as e:
            if on_error == "raise":
                raise
            errors[path] = e
            continue
        if scalars is None:
            scalars = s
            parts = [({} if p else None) for p in present]
        elif s != scalars:
            e = ValueError(f"{path}: header disagrees with other rank files")
            if on_error == "raise":
                raise e
            errors[path] = e
            continue
        for rank, blocks in local.items():
            if rank in seen:
                raise ValueError(
                    f"shard rank {rank} appears in both {seen[rank]} "
                    f"and {path} — stale rank files from a previous "
                    f"run? Remove outdated {prefix}.rank* files")
            seen[rank] = path
            if blocks is None:
                continue
            it = iter(blocks)
            for f, p in zip(parts, present):
                if p:
                    f[rank] = next(it)
    if scalars is None:
        raise IntegrityError(
            f"no readable rank file under {prefix}.rank*: "
            + "; ".join(f"{p}: {e}" for p, e in errors.items()),
            path=prefix, reason="corrupt")
    return scalars, parts, seen, errors


def _expected_rank_paths(prefix: str, ranks, manifest=None) -> list:
    """Best-effort file paths for missing shard ranks: exact names from the
    manifest when one exists, else the writer's ``prefix.rank<r>``
    convention."""
    if manifest:
        dirname = os.path.dirname(prefix) or "."
        named = {}
        for name, entry in manifest.get("files", {}).items():
            for r in entry.get("ranks", ()):
                named[r] = os.path.join(dirname, name)
        return [named.get(r, f"{prefix}.rank{r}") for r in ranks]
    return [f"{prefix}.rank{r}" for r in ranks]


def _check_rank_coverage(seen: dict, size: int, prefix: str,
                         errors=None) -> None:
    missing = sorted(set(range(size)) - set(seen))
    if missing:
        try:
            manifest = load_manifest(prefix)
        except (OSError, ValueError):
            manifest = None
        paths = _expected_rank_paths(prefix, missing, manifest)
        detail = ""
        if errors:
            detail = "; unreadable: " + "; ".join(
                f"{p} ({e})" for p, e in sorted(errors.items()))
        raise ValueError(
            f"{prefix}.rank* files cover only {sorted(seen)} of "
            f"{size} shard ranks; missing {missing} (expected files: "
            f"{', '.join(paths)}){detail} — partial checkpoint? Pass "
            f"allow_partial=True to an elastic restore to serve the "
            f"surviving shards")


def serialize_ivf_pq(index: ShardedIvfPq, prefix: str) -> None:
    """Persist a sharded IVF-PQ index (either engine) as rank files."""
    engine = 1 if index.list_codes is not None else 0
    scalars = [
        (int(index.metric), "<i4"), (index.n_rows, "<i8"),
        (index.comms.size, "<i4"), (index.pq_dim, "<i4"),
        (index.pq_bits, "<i4"), (1 if index.per_cluster else 0, "<i4"),
        (engine, "<i4"),
    ]
    fields = [index.centers, index.rotation, index.list_indices,
              index.list_sizes, index.list_decoded, index.decoded_norms,
              index.codebooks, index.list_codes, index.overflow_decoded,
              index.overflow_norms, index.overflow_indices]
    _serialize_sharded(prefix, "sharded_ivf_pq", scalars, fields)


def deserialize_ivf_pq(prefix: str, comms: Comms) -> ShardedIvfPq:
    scalars, parts, seen, _ = _deserialize_sharded(
        prefix, "sharded_ivf_pq", 7, want_ranks=_addressable_ranks(comms))
    metric, n_rows, size, pq_dim, pq_bits, per_cluster, _engine = scalars
    if size != comms.size:
        raise ValueError(
            f"index was sharded over {size} devices, comms has {comms.size}")
    _check_rank_coverage(seen, int(size), prefix)
    _CKPT_RESTORES.labels("ivf_pq", "strict").inc()
    arrs = [(_stack_sharded(comms, p) if p is not None else None)
            for p in parts]
    (centers, rotation, list_indices, list_sizes, list_decoded,
     decoded_norms, codebooks, list_codes, overflow_decoded,
     overflow_norms, overflow_indices) = arrs
    return ShardedIvfPq(
        comms, centers, rotation, list_indices, list_sizes,
        DistanceType(metric), int(n_rows), list_decoded=list_decoded,
        decoded_norms=decoded_norms, codebooks=codebooks,
        list_codes=list_codes, per_cluster=bool(per_cluster),
        pq_dim=int(pq_dim), pq_bits=int(pq_bits),
        overflow_decoded=overflow_decoded, overflow_norms=overflow_norms,
        overflow_indices=overflow_indices)


# -------------------------------------------------------- elastic restore
#
# A sharded checkpoint normally restores only onto a mesh of the SAME size
# it was built on (deserialize_ivf_pq raises otherwise). Elastic restore
# lifts that: the shard blocks are stacked [S, ...] as plain arrays on the
# default device and searched by running the per-shard core sequentially
# (lax.map) inside one jitted program, then merging with one select_k —
# numerically identical to the mesh search (same cores, same merge). This
# is the single-chip serving story for a multi-shard build: an 8-virtual-
# device CPU-built DEEP-scale index searches on the one real TPU without a
# rebuild. (The reference's raft-dask analog requires re-creating the
# cluster at the original worker count — raft_dask/common/comms.py;
# per-worker local models in cuML's kNN.)


@functools.partial(jax.jit, static_argnames=(
    "metric", "k", "n_probes", "q_tile", "probe_tile", "per_cluster",
    "pq_dim", "pq_bits", "lut_dtype", "dist_dtype", "select_recall",
    "has_overflow"))
def _elastic_lut_search(queries, centers, rotation, codebooks, list_codes,
                        list_indices, list_sizes, overflow_decoded,
                        overflow_norms, overflow_indices, *, metric, k,
                        n_probes, q_tile, probe_tile, per_cluster, pq_dim,
                        pq_bits, lut_dtype, dist_dtype, select_recall,
                        has_overflow):
    from raft_tpu.neighbors import ivf_pq

    empty_filter = jnp.zeros((0,), jnp.uint32)
    minimize = metric != DistanceType.InnerProduct

    def per_shard(blocks):
        c, ro, cb, lc, li, ls, od, on, oi = blocks
        kw = (dict(overflow_decoded=od, overflow_norms=on,
                   overflow_indices=oi, has_overflow=True)
              if has_overflow else {})
        return ivf_pq.search_lut_core(
            queries, c, ro, cb, lc, li, ls, empty_filter, metric, k,
            n_probes, q_tile, per_cluster, pq_dim, pq_bits, False,
            lut_dtype, dist_dtype, select_recall=select_recall,
            probe_tile=probe_tile, **kw)

    v, i = jax.lax.map(per_shard, (centers, rotation, codebooks, list_codes,
                                   list_indices, list_sizes,
                                   overflow_decoded, overflow_norms,
                                   overflow_indices))
    return _elastic_merge(v, i, queries.shape[0], k, minimize)


@functools.partial(jax.jit, static_argnames=(
    "metric", "k", "n_probes", "q_tile", "select_recall", "has_overflow"))
def _elastic_cache_search(queries, centers, rotation, list_decoded,
                          decoded_norms, list_indices, list_sizes,
                          overflow_decoded, overflow_norms, overflow_indices,
                          *, metric, k, n_probes, q_tile, select_recall,
                          has_overflow):
    from raft_tpu.neighbors import ivf_pq

    empty_filter = jnp.zeros((0,), jnp.uint32)
    minimize = metric != DistanceType.InnerProduct

    def per_shard(blocks):
        c, ro, ld, dn, li, ls, od, on, oi = blocks
        kw = (dict(overflow_decoded=od, overflow_norms=on,
                   overflow_indices=oi, has_overflow=True)
              if has_overflow else {})
        return ivf_pq.search_cache_core(
            queries, c, ro, ld, dn, li, ls, empty_filter, metric, k,
            n_probes, q_tile, False, select_recall=select_recall, **kw)

    v, i = jax.lax.map(per_shard, (centers, rotation, list_decoded,
                                   decoded_norms, list_indices, list_sizes,
                                   overflow_decoded, overflow_norms,
                                   overflow_indices))
    return _elastic_merge(v, i, queries.shape[0], k, minimize)


def _elastic_merge(v, i, nq: int, k: int, minimize: bool):
    """[S, nq, k] per-shard candidates → [nq, k] global top-k (the
    knn_merge_parts-across-ranks step, without the all_gather — everything
    already lives on one device)."""
    v = jnp.swapaxes(v, 0, 1).reshape(nq, -1)
    i = jnp.swapaxes(i, 0, 1).reshape(nq, -1)
    v = jnp.where(i < 0, jnp.inf if minimize else -jnp.inf, v)
    vm, sel = select_k(v, k, select_min=minimize)
    return vm, jnp.take_along_axis(i, sel, axis=1)


class ElasticIvfPq:
    """A sharded IVF-PQ checkpoint restored WITHOUT the original mesh —
    shard blocks live stacked [S, ...] on the default device; ``search``
    matches ``sharded.search_ivf_pq`` exactly (same per-shard cores, same
    merge). Under a degraded restore (``allow_partial=True``) S counts
    only the SURVIVING shards and ``coverage`` < 1.0 reports the fraction
    of indexed rows still searchable; results carry it (see
    :class:`SearchResult`)."""

    def __init__(self, n_shards, centers, rotation, list_indices,
                 list_sizes, metric, n_rows, list_decoded=None,
                 decoded_norms=None, codebooks=None, list_codes=None,
                 per_cluster=False, pq_dim=0, pq_bits=8,
                 overflow_decoded=None, overflow_norms=None,
                 overflow_indices=None, coverage: float = 1.0,
                 shard_ranks=None):
        self.n_shards = int(n_shards)
        self.centers = centers  # [S, nlist, dim]
        self.rotation = rotation  # [S, rot, dim]
        self.list_indices = list_indices  # [S, nlist, pad] global ids
        self.list_sizes = list_sizes  # [S, nlist]
        self.metric = metric
        self.n_rows = int(n_rows)
        self.list_decoded = list_decoded
        self.decoded_norms = decoded_norms
        self.codebooks = codebooks
        self.list_codes = list_codes
        self.per_cluster = bool(per_cluster)
        self.pq_dim = int(pq_dim)
        self.pq_bits = int(pq_bits)
        self.overflow_decoded = overflow_decoded
        self.overflow_norms = overflow_norms
        self.overflow_indices = overflow_indices
        self.coverage = float(coverage)
        # original shard-rank ids behind each stacked row (None = all of
        # range(n_shards), i.e. a full restore)
        self.shard_ranks = (None if shard_ranks is None
                            else [int(r) for r in shard_ranks])

    def search(self, queries, k: int, params=None,
               res: Optional[Resources] = None) -> "SearchResult":
        from raft_tpu.neighbors import ivf_pq

        res = ensure_resources(res)
        params = params or ivf_pq.SearchParams()
        queries = jnp.asarray(queries)
        n_lists = self.centers.shape[1]
        n_probes = int(min(params.n_probes, n_lists))
        select_recall = float(getattr(params, "select_recall", 1.0))
        mode = _resolve_pq_scan_mode(params, self.list_decoded,
                                     self.list_codes)
        has_overflow = self.overflow_decoded is not None
        if has_overflow:
            over = (self.overflow_decoded, self.overflow_norms,
                    self.overflow_indices)
        else:
            # stable zero-size placeholders keep the jit signature uniform
            s = self.n_shards
            rot = self.rotation.shape[1]
            over = (jnp.zeros((s, 0, rot), jnp.bfloat16),
                    jnp.zeros((s, 0), jnp.float32),
                    jnp.zeros((s, 0), jnp.int32))

        q_tile, probe_tile = _pq_tiles(
            mode, n_probes, res, self.list_decoded, self.list_codes,
            self.pq_dim, self.pq_bits,
            jnp.dtype(params.lut_dtype).itemsize,
            jnp.dtype(params.internal_distance_dtype).itemsize)
        if mode == "cache":
            v, i = _elastic_cache_search(
                queries, self.centers, self.rotation, self.list_decoded,
                self.decoded_norms, self.list_indices, self.list_sizes,
                *over, metric=self.metric, k=int(k), n_probes=n_probes,
                q_tile=q_tile, select_recall=select_recall,
                has_overflow=has_overflow)
            return SearchResult(v, i, self.coverage)

        v, i = _elastic_lut_search(
            queries, self.centers, self.rotation, self.codebooks,
            self.list_codes, self.list_indices, self.list_sizes, *over,
            metric=self.metric, k=int(k), n_probes=n_probes, q_tile=q_tile,
            probe_tile=probe_tile, per_cluster=self.per_cluster,
            pq_dim=self.pq_dim, pq_bits=self.pq_bits,
            lut_dtype=jnp.dtype(params.lut_dtype).name,
            dist_dtype=jnp.dtype(params.internal_distance_dtype).name,
            select_recall=select_recall, has_overflow=has_overflow)
        return SearchResult(v, i, self.coverage)


def _elastic_restore(prefix: str, kind: str, n_scalars: int,
                     allow_partial: bool):
    """Shared elastic-restore front half: read rank files (strict, or
    best-effort when ``allow_partial``), pick the surviving rank order,
    and return ``(scalars, parts, survivors, size)``."""
    scalars, parts, seen, errors = _deserialize_sharded(
        prefix, kind, n_scalars,
        want_ranks=None, on_error="skip" if allow_partial else "raise")
    size = int(scalars[2])
    if allow_partial:
        survivors = sorted(r for r in seen if r < size)
        if not survivors:
            from raft_tpu.core.errors import IntegrityError
            raise IntegrityError(
                f"{prefix}: no shard rank survived (of {size})",
                path=prefix, reason="missing")
    else:
        _check_rank_coverage(seen, size, prefix, errors)
        survivors = list(range(size))
    return scalars, parts, survivors, size


def _stack_survivors(parts, survivors):
    """Stack each parts dict {rank: np block} over the surviving ranks in
    order (None fields stay None)."""
    return [(None if p is None
             else jnp.asarray(np.stack([p[r] for r in survivors])))
            for p in parts]


def _elastic_coverage(list_indices_parts, overflow_parts, survivors,
                      n_rows) -> float:
    """Fraction of indexed rows actually restorable = valid (>= 0) ids
    across the surviving shards' lists + spill blocks, over ``n_rows``.
    Exact, not estimated — padding slots hold -1."""
    rows = 0
    for r in survivors:
        rows += int((np.asarray(list_indices_parts[r]) >= 0).sum())
        if overflow_parts is not None and r in overflow_parts:
            rows += int((np.asarray(overflow_parts[r]) >= 0).sum())
    return rows / max(int(n_rows), 1)


def deserialize_ivf_pq_elastic(prefix: str,
                               allow_partial: bool = False) -> ElasticIvfPq:
    """Restore a sharded IVF-PQ checkpoint on ANY device count (vs
    ``deserialize_ivf_pq``, which requires the original mesh size). All
    rank files are read and every shard is retained on the default device.

    ``allow_partial=True`` is the degraded serving mode: rank files that
    are missing, truncated, or corrupt are skipped instead of failing the
    restore, and the index serves the surviving shards with
    ``index.coverage = rows_available / n_rows`` (< 1.0); each
    ``search`` result carries that coverage. Strict mode (the default)
    raises — naming the missing file, or the bad file + record."""
    scalars, parts, survivors, size = _elastic_restore(
        prefix, "sharded_ivf_pq", 7, allow_partial)
    metric, n_rows, _size, pq_dim, pq_bits, per_cluster, _engine = scalars
    coverage = (1.0 if len(survivors) == size
                else _elastic_coverage(parts[2], parts[10], survivors,
                                       n_rows))
    _CKPT_RESTORES.labels(
        "ivf_pq", "full" if coverage >= 1.0 else "degraded").inc()
    (centers, rotation, list_indices, list_sizes, list_decoded,
     decoded_norms, codebooks, list_codes, overflow_decoded,
     overflow_norms, overflow_indices) = _stack_survivors(parts, survivors)
    return ElasticIvfPq(
        len(survivors), centers, rotation, list_indices, list_sizes,
        DistanceType(metric), int(n_rows), list_decoded=list_decoded,
        decoded_norms=decoded_norms, codebooks=codebooks,
        list_codes=list_codes, per_cluster=bool(per_cluster),
        pq_dim=int(pq_dim), pq_bits=int(pq_bits),
        overflow_decoded=overflow_decoded, overflow_norms=overflow_norms,
        overflow_indices=overflow_indices, coverage=coverage,
        shard_ranks=survivors)


def serialize_ivf_flat(index: ShardedIvfFlat, prefix: str) -> None:
    """Persist a sharded IVF-Flat index as rank files."""
    scalars = [(int(index.metric), "<i4"), (index.n_rows, "<i8"),
               (index.comms.size, "<i4")]
    fields = [index.centers, index.list_data, index.list_indices,
              index.list_sizes, index.overflow_data, index.overflow_indices]
    _serialize_sharded(prefix, "sharded_ivf_flat", scalars, fields)


def deserialize_ivf_flat(prefix: str, comms: Comms) -> ShardedIvfFlat:
    scalars, parts, seen, _ = _deserialize_sharded(
        prefix, "sharded_ivf_flat", 3, want_ranks=_addressable_ranks(comms))
    metric, n_rows, size = scalars
    if size != comms.size:
        raise ValueError(
            f"index was sharded over {size} devices, comms has {comms.size}")
    _check_rank_coverage(seen, int(size), prefix)
    _CKPT_RESTORES.labels("ivf_flat", "strict").inc()
    arrs = [(_stack_sharded(comms, p) if p is not None else None)
            for p in parts]
    centers, list_data, list_indices, list_sizes, o_data, o_ids = arrs
    return ShardedIvfFlat(comms, centers, list_data, list_indices,
                          list_sizes, DistanceType(metric), int(n_rows),
                          overflow_data=o_data, overflow_indices=o_ids)


@functools.partial(jax.jit, static_argnames=(
    "metric", "k", "n_probes", "q_tile", "select_recall", "fast_scan",
    "refine_mult", "has_overflow"))
def _elastic_flat_search(queries, centers, list_data, list_indices,
                         list_sizes, overflow_data, overflow_indices, *,
                         metric, k, n_probes, q_tile, select_recall,
                         fast_scan, refine_mult, has_overflow):
    from raft_tpu.neighbors import ivf_flat

    empty_filter = jnp.zeros((0,), jnp.uint32)
    minimize = metric != DistanceType.InnerProduct

    def per_shard(blocks):
        c, ld, li, ls, od, oi = blocks
        kw = (dict(overflow_data=od, overflow_indices=oi, has_overflow=True)
              if has_overflow else {})
        return ivf_flat.search_core(
            queries, c, ld, li, ls, empty_filter, metric, k, n_probes,
            q_tile, False, fast_scan=fast_scan, select_recall=select_recall,
            refine_mult=refine_mult, **kw)

    v, i = jax.lax.map(per_shard, (centers, list_data, list_indices,
                                   list_sizes, overflow_data,
                                   overflow_indices))
    return _elastic_merge(v, i, queries.shape[0], k, minimize)


class ElasticIvfFlat:
    """The IVF-Flat twin of :class:`ElasticIvfPq`: a sharded checkpoint
    restored without the original mesh, searched by running the single-
    chip core per stacked shard and merging — degraded restores carry
    ``coverage`` < 1.0."""

    def __init__(self, n_shards, centers, list_data, list_indices,
                 list_sizes, metric, n_rows, overflow_data=None,
                 overflow_indices=None, coverage: float = 1.0,
                 shard_ranks=None):
        self.n_shards = int(n_shards)
        self.centers = centers  # [S, L, dim]
        self.list_data = list_data  # [S, L, pad, dim]
        self.list_indices = list_indices  # [S, L, pad] global ids
        self.list_sizes = list_sizes  # [S, L]
        self.metric = metric
        self.n_rows = int(n_rows)
        self.overflow_data = overflow_data
        self.overflow_indices = overflow_indices
        self.coverage = float(coverage)
        self.shard_ranks = (None if shard_ranks is None
                            else [int(r) for r in shard_ranks])

    def search(self, queries, k: int, params=None,
               res: Optional[Resources] = None) -> "SearchResult":
        from raft_tpu.neighbors import ivf_flat

        res = ensure_resources(res)
        params = params or ivf_flat.SearchParams()
        queries = jnp.asarray(queries)
        n_lists = self.centers.shape[1]
        n_probes = int(min(params.n_probes, n_lists))
        list_pad = self.list_data.shape[2]
        dim = self.list_data.shape[3]
        per_q = n_probes * list_pad * dim * 4 * 2
        q_tile = int(np.clip(res.workspace_limit_bytes // max(per_q, 1),
                             1, 1024))
        if q_tile >= 8:
            q_tile -= q_tile % 8
        fast_scan = getattr(params, "scan_dtype", None) is not None
        select_recall = float(getattr(params, "select_recall", 1.0))
        refine_mult = refine_multiplier(
            getattr(params, "refine_ratio", 4.0), fast_scan)
        has_overflow = self.overflow_data is not None
        if has_overflow:
            over = (self.overflow_data, self.overflow_indices)
        else:
            # stable zero-size placeholders keep the jit signature uniform
            over = (jnp.zeros((self.n_shards, 0, dim), self.list_data.dtype),
                    jnp.zeros((self.n_shards, 0), jnp.int32))
        v, i = _elastic_flat_search(
            queries, self.centers, self.list_data, self.list_indices,
            self.list_sizes, *over, metric=self.metric, k=int(k),
            n_probes=n_probes, q_tile=q_tile, select_recall=select_recall,
            fast_scan=fast_scan, refine_mult=refine_mult,
            has_overflow=has_overflow)
        return SearchResult(v, i, self.coverage)


def deserialize_ivf_flat_elastic(prefix: str, allow_partial: bool = False
                                 ) -> ElasticIvfFlat:
    """IVF-Flat twin of :func:`deserialize_ivf_pq_elastic` — restore on any
    device count; ``allow_partial=True`` serves the surviving shards of a
    damaged checkpoint with ``coverage = rows_available / n_rows``."""
    scalars, parts, survivors, size = _elastic_restore(
        prefix, "sharded_ivf_flat", 3, allow_partial)
    metric, n_rows, _size = scalars
    coverage = (1.0 if len(survivors) == size
                else _elastic_coverage(parts[2], parts[5], survivors,
                                       n_rows))
    _CKPT_RESTORES.labels(
        "ivf_flat", "full" if coverage >= 1.0 else "degraded").inc()
    (centers, list_data, list_indices, list_sizes, o_data,
     o_ids) = _stack_survivors(parts, survivors)
    return ElasticIvfFlat(
        len(survivors), centers, list_data, list_indices, list_sizes,
        DistanceType(metric), int(n_rows), overflow_data=o_data,
        overflow_indices=o_ids, coverage=coverage, shard_ranks=survivors)
