"""raft_tpu — TPU-native vector-search & ML-primitives framework.

A brand-new JAX/XLA/Pallas framework with the capabilities of RAPIDS RAFT
(reference: /root/reference, RAFT 24.02): pairwise distances, batched top-k
selection, fused L2 1-NN, (balanced) k-means, RNG and stats primitives, and
the ANN index suite — brute-force, IVF-Flat, IVF-PQ, CAGRA — plus a comms
facade over ICI/DCN mesh collectives for multi-host sharded index build.

Layout mirrors the reference's layer map (SURVEY.md §1) but the design is
TPU-first: jax.Array instead of mdspan/mdarray, XLA fusion + Pallas kernels
instead of hand-rolled CUDA, jax.sharding.Mesh collectives instead of NCCL.
"""

from raft_tpu.core.resources import Resources
from raft_tpu import core, ops, cluster, neighbors, parallel, sparse, stats, utils
from raft_tpu import bench, common, distance, label, matrix, random
from raft_tpu import planner, serving, solver, spatial, spectral

__version__ = "0.1.0"

__all__ = [
    "Resources",
    "core",
    "ops",
    "cluster",
    "neighbors",
    "parallel",
    "sparse",
    "stats",
    "bench",
    "common",
    "distance",
    "label",
    "matrix",
    "planner",
    "random",
    "serving",
    "solver",
    "spatial",
    "spectral",
    "utils",
    "__version__",
]
