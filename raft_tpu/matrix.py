"""pylibraft-parity namespace: ``raft_tpu.matrix``.

Mirrors ``pylibraft.matrix`` (python/pylibraft/pylibraft/matrix —
select_k); the full matrix-prims surface lives in ops.matrix."""

from raft_tpu.ops.matrix import *  # noqa: F401,F403
from raft_tpu.ops.matrix import select_k, SelectAlgo  # noqa: F401

__all__ = ["select_k", "SelectAlgo"]
