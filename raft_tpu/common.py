"""pylibraft-parity namespace: ``raft_tpu.common``.

Mirrors ``pylibraft.common`` (python/pylibraft/pylibraft/common —
DeviceResources handle.pyx:34-138, device_ndarray, auto-sync decorators).
On TPU the handle is the Resources context; ``device_ndarray`` is a
jax.Array placed on device — ``__cuda_array_interface__`` interop becomes
plain ``__array__``/dlpack, which jax.numpy already speaks."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.resources import Resources, default_resources

# pylibraft.common.DeviceResources → the Resources context
DeviceResources = Resources


def device_ndarray(array_like, dtype=None) -> jax.Array:
    """Place an array on device (pylibraft.common.device_ndarray analog —
    accepts anything numpy/dlpack-convertible)."""
    a = jnp.asarray(array_like, dtype=dtype)
    return jax.device_put(a)


def auto_sync_resources(fn):
    """Decorator: inject a default Resources when ``res=None`` and block on
    returned arrays (the @auto_sync_handle pattern,
    neighbors/ivf_pq/ivf_pq.pyx:310-312)."""

    @functools.wraps(fn)
    def wrapper(*args, res=None, **kwargs):
        res = res or default_resources()
        out = fn(*args, res=res, **kwargs)
        res.sync(*[o for o in jax.tree_util.tree_leaves(out)
                   if isinstance(o, jax.Array)])
        return out

    return wrapper


def to_host(x) -> np.ndarray:
    """Device → host copy (auto_convert_output analog)."""
    return np.asarray(jax.device_get(x))


__all__ = ["DeviceResources", "Resources", "device_ndarray",
           "auto_sync_resources", "to_host"]
