"""Headline benchmark — prints ONE JSON line for the driver.

Flagship config (BASELINE.md target #1): pairwise L2 + brute-force kNN,
sift-128-euclidean shape (10k queries × 10k database, dim=128, k=10).
Metric is QPS in throughput mode (all queries batched), matching
raft-ann-bench's QPS definition (docs/source/raft_ann_benchmarks.md:154).
``vs_baseline`` is 1.0 — BASELINE.json publishes no reference numbers
(``published: {}``), so there is nothing to normalize against.

Secondary index metrics (ivf_flat / ivf_pq / cagra QPS + recall on the same
data) ride along in the ``extra`` key; set RAFT_TPU_BENCH_EXTRAS=0 to skip.

Robustness: the default platform may be a TPU behind a tunnel; an
unreachable tunnel hangs backend init forever. A subprocess probe with a
timeout decides the platform BEFORE jax initializes here, falling back to
CPU (recorded in the JSON) so the driver always gets its line.
"""

import json
import os
import subprocess
import sys
import time


def _probe_platform(timeout_s: int = 540) -> str:
    """Return "default" if the default JAX backend initializes in a
    subprocess within the timeout, else "cpu" (hung/broken accelerator).

    The happy path pays backend init twice (probe + main process) — the
    price of never hanging the driver; the persistent compile cache and
    warm tunnel make the second init much cheaper than the first."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return "cpu"
    timeout_s = int(os.environ.get("RAFT_TPU_PROBE_TIMEOUT", timeout_s))
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, check=True, capture_output=True)
        return "default"
    except subprocess.CalledProcessError as e:
        tail = (e.stderr or b"")[-800:].decode("utf-8", "replace")
        print(f"bench: accelerator backend init failed ({e}); falling back "
              f"to CPU. stderr tail:\n{tail}", file=sys.stderr)
        return "cpu"
    except Exception as e:
        print(f"bench: accelerator backend unreachable ({e!r}); falling "
              "back to CPU", file=sys.stderr)
        return "cpu"


def _last_measured_tpu(here=None):
    """Most recent committed on-chip measurement, as a clearly-labeled
    block for the driver's JSON when this run itself lands on CPU.

    Scans repo-root ``BENCH_TPU_SESSION_r*.json`` session artifacts (banked
    incrementally during tunnel windows) for a driver-shaped row with
    ``platform == "tpu"`` under ``bench_py_rerun``/``bench_py_first_run``
    (the r04+ artifact contract; r03's legacy nested ``bench_py`` shape is
    intentionally out of scope — r04 supersedes it and is committed).
    Returns None when no hardware evidence exists.
    A dead tunnel at round close must not erase the round's hardware
    record (VERDICT r4 weak #1): the driver's capture reads only this
    script's stdout, so the evidence has to ride in this line."""
    import glob
    import re

    if here is None:
        here = os.path.dirname(os.path.abspath(__file__))
    best = None  # (round_number, block)
    for path in glob.glob(os.path.join(here, "BENCH_TPU_SESSION_r*.json")):
        m = re.search(r"_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except Exception:
            continue
        # newest round wins; within an artifact an explicit re-run key is
        # preferred over the first run (iteration order + break below)
        for key in ("bench_py_rerun", "bench_py_first_run"):
            row = doc.get(key)
            if not isinstance(row, dict) or row.get("platform") != "tpu":
                continue
            round_number = int(m.group(1))
            if best is None or round_number > best[0]:
                block = {
                    "note": "most recent committed on-chip measurement "
                            "(this run itself did not land on TPU)",
                    "metric": row.get("metric"),
                    "value": row.get("value"),
                    "unit": row.get("unit"),
                    "recall": row.get("recall"),
                    "scan": row.get("scan"),
                    "when": doc.get("when"),
                    "artifact": os.path.basename(path),
                }
                if isinstance(row.get("extra"), dict):
                    block["extra"] = row["extra"]
                best = (round_number, block)
            break  # only the preferred key per artifact
    return best[1] if best else None


def main():
    degraded = False
    if _probe_platform() == "cpu":
        degraded = os.environ.get("JAX_PLATFORMS") != "cpu"  # fell back
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    import numpy as np

    from raft_tpu.bench.timing import fence, prepare, time_dispatches
    from raft_tpu.neighbors import brute_force
    from raft_tpu.stats import neighborhood_recall

    platform = jax.devices()[0].platform

    n_db, n_q, dim, k = 10_000, 10_000, 128, 10
    rng = np.random.default_rng(0)
    db = rng.standard_normal((n_db, dim)).astype(np.float32)
    # queries live on device BEFORE any timed region — the tunnel's
    # ~16 MB/s host→device link must never be inside a measurement
    q = prepare(rng.standard_normal((n_q, dim)).astype(np.float32))

    index = brute_force.build(db, metric="sqeuclidean")

    # exact fp32 pass = ground truth + the fallback timing target
    d_e, i_e = brute_force.search(index, q, k)
    fence((d_e, i_e))
    gt = np.asarray(i_e)

    # Fast variants (ordered fastest-first), each gated on recall >= 0.999
    # against the exact pass: bf16 MXU screen + exact fp32 re-rank, with
    # and without APPROX candidate selection (the final re-rank select
    # stays exact either way, so the approx screen only risks candidate
    # misses the gate would catch).
    variants = [
        ({"scan_dtype": "bfloat16", "select_recall": 0.95},
         "bf16+approx95+fp32refine"),
        ({"scan_dtype": "bfloat16"}, "bf16+fp32refine"),
        ({}, "fp32"),
    ]
    best = None  # (dt, recall, kwargs, label) — measured, not assumed:
    # variant ordering flips between platforms (approx wins on TPU's
    # PartialReduce, loses to plain top_k on CPU's exact fallback)
    for kw, name in variants:
        d_f, i_f = brute_force.search(index, q, k, **kw)
        rec = float(neighborhood_recall(np.asarray(i_f), gt))
        if rec < 0.999 and kw:
            continue
        dt_v = time_dispatches(
            lambda: brute_force.search(index, q, k, **kw), iters=2,
            warmup=0)
        if best is None or dt_v < best[0]:
            best = (dt_v, rec, kw, name)
    _, recall, chosen, label = best

    dt = time_dispatches(
        lambda: brute_force.search(index, q, k, **chosen), iters=5,
        warmup=0)
    qps = n_q / dt

    # which select algorithm the winning variant's scan actually used:
    # APPROX when the variant opted in via select_recall, else what AUTO
    # resolves at the scan's true select width (db_tile, not n_db) —
    # records whether a measured SELECT_K_TABLE artifact flipped the
    # exact default (SCREEN vs DIRECT) in this run
    if chosen.get("select_recall", 1.0) < 1.0:
        sel_algo = "approx"
        k_pad = 0
    else:
        from raft_tpu.neighbors.brute_force import _choose_tiles
        from raft_tpu.ops.select_k import _pad_k, _resolve_auto
        from raft_tpu.core.resources import ensure_resources

        _, db_tile = _choose_tiles(
            n_q, n_db, dim, k,
            ensure_resources(None).workspace_limit_bytes)
        sel_algo = _resolve_auto(db_tile, k).value
        # whether a measured TOPK_PAD rule rewrote the requested k
        k_pad = _pad_k(db_tile, k) if sel_algo in ("direct", "screen") else 0

    row = {
        "metric": "brute_force_knn_qps_sift10k_k10",
        "value": round(qps, 1),
        "unit": "QPS",
        "vs_baseline": 1.0,
        "recall": round(recall, 5),
        "scan": label,
        "select_algo": sel_algo,
        "platform": platform,
    }
    if k_pad and k_pad != k:
        row["select_k_pad"] = k_pad

    # skip the (minutes-long on CPU) extras in the degraded-fallback case —
    # the driver must still get its line well inside any timeout
    if os.environ.get("RAFT_TPU_BENCH_EXTRAS", "1") != "0" and not degraded:
        row["extra"] = _index_extras(k)

    # evidence survival: a CPU line still carries the last committed
    # hardware number, labeled and dated (VERDICT r4 "make hardware
    # evidence survive a dead tunnel"; ref benchmark JSON emission:
    # cpp/bench/ann/src/common/benchmark.hpp:379-509)
    if platform != "tpu":
        last = _last_measured_tpu()
        if last is not None:
            if degraded:
                last["note"] = ("most recent committed on-chip "
                                "measurement; this run fell back to CPU "
                                "(TPU tunnel down)")
            row["last_measured_tpu"] = last

    print(json.dumps(row))


def _index_extras(k):
    """ANN-index secondary metrics (BASELINE targets #3/#5 shapes, scaled
    to stay a small fraction of bench wall-clock). Uses clustered data of
    low intrinsic dimension — the real benchmark datasets' regime; both
    iid gaussian and full-dim gaussian clusters concentrate distances
    (vanishing top-k gaps), which measures the generator, not the index."""
    import jax
    import numpy as np

    from raft_tpu import Resources
    from raft_tpu.bench.timing import (chain_perturb, fence, fence_index,
                                       last_info, prepare, time_dispatches,
                                       time_latency_chained)
    from raft_tpu.serving.stats import percentiles
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
    from raft_tpu.stats import neighborhood_recall

    from raft_tpu.bench.datagen import low_rank_clusters

    rng = np.random.default_rng(7)
    n_db, n_q, dim = 10_000, 10_000, 128
    both = low_rank_clusters(rng, n_db + n_q, dim, n_centers=64)
    db, q_host = both[:n_db], both[n_db:]
    db = prepare(db)  # builds are jnp.asarray-based: upload once, reuse
    q = prepare(q_host)
    _, gt_j = brute_force.knn(q, db, k=k, metric="sqeuclidean")
    gt = np.asarray(gt_j)
    res = Resources(seed=0)
    out = {}

    def timed(search_fn):
        d, i = search_fn()  # warmup/compile
        fence((d, i))
        rec = float(neighborhood_recall(np.asarray(i), gt))
        dt = time_dispatches(search_fn, iters=3, warmup=0)
        return {"qps": round(n_q / dt, 1), "recall": round(rec, 4)}

    def lat_ms(entry, name, search_small, batch):
        """Serving latency at tiny batches (VERDICT r2 #7): per-call
        device latency with calls chained by a data dependency, so the
        tunnel's ~75 ms readback round-trip is paid once and amortized
        (a per-call host sync would measure the tunnel, not the chip);
        the query bucketing in each search keeps every batch ≤ 256 on
        one compiled program. Eight fenced rounds feed p50/p95/p99
        alongside the mean — a bare mean hid the r5 host-contention
        skew (6 ms medians with 37-45 ms outlier rounds) until it
        was 6x."""
        q0 = q[:batch]
        dt = time_latency_chained(
            lambda qq: chain_perturb(q0, search_small(qq)),
            q0, iters=8, rounds=8)
        entry[name] = round(dt * 1e3, 3)  # the mean, schema-compatible
        for pct, v in percentiles(last_info["samples_s"]).items():
            entry[f"{name}_{pct}"] = round(v * 1e3, 3)

    def timed_build(build_fn):
        """Cold build (includes trace+compile) and warm build (cached
        executables — the steady-state cost); both fenced, since builds
        end in async device work."""
        t0 = time.perf_counter()
        index = build_fn()
        fence_index(index)
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        index = build_fn()
        fence_index(index)
        warm = time.perf_counter() - t0
        return index, round(cold, 2), round(warm, 2)

    fl, fl_cold, fl_warm = timed_build(
        lambda: ivf_flat.build(db, ivf_flat.IndexParams(n_lists=128),
                               res=res))
    sp = ivf_flat.SearchParams(n_probes=32, scan_dtype="bfloat16")
    out["ivf_flat_nprobe32_bf16"] = timed(
        lambda: ivf_flat.search(fl, q, k, sp))
    out["ivf_flat_nprobe32_bf16"]["build_s"] = fl_cold
    out["ivf_flat_nprobe32_bf16"]["build_warm_s"] = fl_warm
    for b in (1, 10):
        lat_ms(out["ivf_flat_nprobe32_bf16"], f"latency_ms_b{b}",
               lambda qq: ivf_flat.search(fl, qq, k, sp), b)

    pq, pq_cold, pq_warm = timed_build(
        lambda: ivf_pq.build(db, ivf_pq.IndexParams(n_lists=128, pq_dim=64),
                             res=res))
    psp = ivf_pq.SearchParams(n_probes=32)
    out["ivf_pq_nprobe32"] = timed(lambda: ivf_pq.search(pq, q, k, psp))
    out["ivf_pq_nprobe32"]["build_s"] = pq_cold
    out["ivf_pq_nprobe32"]["build_warm_s"] = pq_warm
    for b in (1, 10):
        lat_ms(out["ivf_pq_nprobe32"], f"latency_ms_b{b}",
               lambda qq: ivf_pq.search(pq, qq, k, psp), b)

    cg, cg_cold, cg_warm = timed_build(
        lambda: cagra.build(db, cagra.IndexParams(
            graph_degree=32, intermediate_graph_degree=64), res=res))
    csp = cagra.SearchParams(itopk_size=128, search_width=4,
                             scan_dtype="bfloat16")
    out["cagra_itopk128_bf16"] = timed(lambda: cagra.search(cg, q, k, csp))
    out["cagra_itopk128_bf16"]["build_s"] = cg_cold
    out["cagra_itopk128_bf16"]["build_warm_s"] = cg_warm
    for b in (1, 10):
        lat_ms(out["cagra_itopk128_bf16"], f"latency_ms_b{b}",
               lambda qq: cagra.search(cg, qq, k, csp), b)
    return out


if __name__ == "__main__":
    main()
