"""Headline benchmark — prints ONE JSON line for the driver.

Current flagship config (BASELINE.md target #1): pairwise L2 + brute-force
kNN, sift-128-euclidean shape (10k queries × 10k database, dim=128, k=10).
Metric is QPS in throughput mode (all queries batched), matching
raft-ann-bench's QPS definition (docs/source/raft_ann_benchmarks.md:154).
``vs_baseline`` is 1.0 — BASELINE.json publishes no reference numbers
(``published: {}``), so there is nothing to normalize against yet.

As the index suite lands, this graduates to IVF-PQ / CAGRA QPS@recall=0.95.
"""

import json
import time

import jax
import numpy as np


def main():
    from raft_tpu.neighbors import brute_force
    from raft_tpu.stats import neighborhood_recall

    n_db, n_q, dim, k = 10_000, 10_000, 128, 10
    rng = np.random.default_rng(0)
    db = rng.standard_normal((n_db, dim)).astype(np.float32)
    q = rng.standard_normal((n_q, dim)).astype(np.float32)

    index = brute_force.build(db, metric="sqeuclidean")

    # exact fp32 pass = ground truth + the fallback timing target
    d_e, i_e = brute_force.search(index, q, k)
    jax.block_until_ready((d_e, i_e))

    # bf16 MXU fast-scan + exact fp32 re-rank; keep it only if recall holds
    d_f, i_f = brute_force.search(index, q, k, scan_dtype="bfloat16")
    jax.block_until_ready((d_f, i_f))
    recall = float(neighborhood_recall(np.asarray(i_f), np.asarray(i_e)))
    use_fast = recall >= 0.999
    scan_dtype = "bfloat16" if use_fast else None

    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        d, i = brute_force.search(index, q, k, scan_dtype=scan_dtype)
        jax.block_until_ready((d, i))
    dt = (time.perf_counter() - t0) / iters
    qps = n_q / dt

    print(
        json.dumps(
            {
                "metric": "brute_force_knn_qps_sift10k_k10",
                "value": round(qps, 1),
                "unit": "QPS",
                "vs_baseline": 1.0,
                "recall": round(recall, 5) if use_fast else 1.0,
                "scan": "bf16+fp32refine" if use_fast else "fp32",
            }
        )
    )


if __name__ == "__main__":
    main()
