"""Deadline-aware adaptive planning tests (docs/tuning.md "Adaptive
planning").

Pins the acceptance contract of the planner layer:

- ``pareto_prune`` produces a monotone non-dominated frontier,
  deterministic under input shuffling (the committed artifact must not
  depend on sweep-log order);
- ``choose_operating_point`` is pure given (points, budget, floor,
  scale) and spends the latency budget on recall: generous budget →
  highest-recall point, tight budget → degrade, floor stops the
  degradation, no frontier → static params, all with closed reasons;
- the ``Frontier`` artifact round-trips, rejects foreign schemas, and
  the committed ``PARETO_cpu.json`` covers all four ANN families;
- ``Calibration`` is a bounded EWMA that cannot be owned by one sample;
- every choice is attributed (counter + explain record, closed
  vocabulary);
- the Engine policy degrades nprobe/itopk under deadline pressure
  instead of shedding: at 2x overload, goodput with degradation beats
  goodput with shed-only at the same recall floor.
"""

import json
import os
import sys
import types
import time
from concurrent.futures import Future
from pathlib import Path

import numpy as np
import pytest

from raft_tpu import serving
from raft_tpu.obs import explain as obs_explain
from raft_tpu.planner import adaptive
from raft_tpu.planner.adaptive import (ADAPTIVE_REASONS, PARETO_SCHEMA,
                                       AdaptivePlanner, Calibration,
                                       Frontier, OperatingPoint,
                                       adaptive_choice_counts,
                                       choose_operating_point,
                                       frontier_metrics, hypervolume,
                                       load_frontier, pareto_prune,
                                       qps_at_recall, record_choice)
from raft_tpu.serving.batcher import Request
from raft_tpu.serving.searchers import Searcher

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import autotune  # noqa: E402

pytestmark = pytest.mark.fast

REPO_ROOT = Path(__file__).resolve().parents[1]


def _pt(recall, qps, ms, params=None, bucket=8):
    return OperatingPoint(params=dict(params or {"n_probes": int(qps)}),
                          bucket=bucket, qps=float(qps),
                          recall=float(recall), predicted_ms=float(ms))


def _doc(points, family="ivf_flat", k=10, bucket=8, platform="cpu"):
    fams = {family: {"frontier": {str(k): {
        str(bucket): [p.to_dict() for p in points]}}}}
    return {"schema": PARETO_SCHEMA, "platform": platform,
            "families": fams}


# A hand-built frontier: recall down, qps up, predicted time down.
FRONTIER = [
    _pt(0.99, 100.0, 40.0, {"n_probes": 64}),
    _pt(0.95, 400.0, 10.0, {"n_probes": 16}),
    _pt(0.90, 900.0, 4.0, {"n_probes": 4}),
]


# ------------------------------------------------------------ pareto_prune
def test_pareto_prune_monotone_and_nondominated():
    rng = np.random.default_rng(7)
    pts = [_pt(r, q, 1000.0 / q, {"p": i})
           for i, (r, q) in enumerate(zip(rng.uniform(0.5, 1.0, 40),
                                          rng.uniform(10, 1000, 40)))]
    pruned = pareto_prune(pts)
    assert pruned
    for a, b in zip(pruned, pruned[1:]):
        assert a.recall > b.recall   # recall strictly decreasing
        assert a.qps < b.qps         # qps strictly increasing
    # nothing kept is dominated by anything in the input
    for p in pruned:
        assert not any(o.recall >= p.recall and o.qps > p.qps
                       for o in pts)
    # everything dropped is dominated (or a tie-collapsed duplicate)
    for p in pts:
        if p not in pruned:
            assert any(o.recall >= p.recall and o.qps >= p.qps
                       for o in pruned)


def test_pareto_prune_deterministic_under_shuffle():
    rng = np.random.default_rng(11)
    pts = [_pt(r, q, 5.0, {"p": i})
           for i, (r, q) in enumerate(zip(rng.uniform(0.5, 1.0, 25),
                                          rng.uniform(10, 1000, 25)))]
    base = pareto_prune(pts)
    for seed in range(5):
        shuffled = list(pts)
        np.random.default_rng(seed).shuffle(shuffled)
        assert pareto_prune(shuffled) == base
    # idempotent: a frontier is its own frontier
    assert pareto_prune(base) == base


def test_pareto_prune_collapses_ties_to_one_representative():
    a = _pt(0.95, 100.0, 5.0, {"p": 1})
    b = _pt(0.95, 100.0, 5.0, {"p": 2})
    pruned = pareto_prune([a, b])
    assert len(pruned) == 1
    assert pruned[0].params == {"p": 1}  # deterministic tie-break


# --------------------------------------------------- choose_operating_point
def test_choose_no_points_is_no_frontier():
    assert choose_operating_point([], 100.0) == (None, "no_frontier")


def test_choose_no_budget_takes_highest_recall():
    p, reason = choose_operating_point(FRONTIER, None)
    assert (p.recall, reason) == (0.99, "pareto_default")


def test_choose_generous_budget_takes_highest_recall():
    p, reason = choose_operating_point(FRONTIER, 1000.0)
    assert (p.recall, reason) == (0.99, "pareto_default")


def test_choose_tight_budget_degrades():
    p, reason = choose_operating_point(FRONTIER, 12.0)
    assert (p.recall, reason) == (0.95, "deadline_degraded")
    p, reason = choose_operating_point(FRONTIER, 5.0)
    assert (p.recall, reason) == (0.90, "deadline_degraded")


def test_choose_nothing_fits_without_floor_is_fastest_point():
    p, reason = choose_operating_point(FRONTIER, 1.0)
    assert (p.recall, reason) == (0.90, "deadline_degraded")


def test_choose_floor_stops_degradation():
    # budget would want the 0.90 point, the floor forbids it
    p, reason = choose_operating_point(FRONTIER, 5.0, recall_floor=0.95)
    assert (p.recall, reason) == (0.95, "floor_clamped")


def test_choose_floor_above_entire_frontier_clamps_to_best():
    p, reason = choose_operating_point(FRONTIER, 5.0, recall_floor=0.999)
    assert (p.recall, reason) == (0.99, "floor_clamped")


def test_choose_scale_shifts_the_cutoff():
    # at scale 1 the 0.95 point (10 ms) fits a 12 ms budget...
    p, _ = choose_operating_point(FRONTIER, 12.0, scale=1.0)
    assert p.recall == 0.95
    # ...at scale 2 its calibrated cost is 20 ms and it no longer does
    p, reason = choose_operating_point(FRONTIER, 12.0, scale=2.0)
    assert (p.recall, reason) == (0.90, "deadline_degraded")


def test_choose_is_pure_and_reasons_are_closed():
    for budget in (None, 0.0, 1.0, 12.0, 1e6):
        first = choose_operating_point(FRONTIER, budget,
                                       recall_floor=0.9, scale=1.3)
        for _ in range(3):
            assert choose_operating_point(
                FRONTIER, budget, recall_floor=0.9, scale=1.3) == first
        assert first[1] in ADAPTIVE_REASONS


def test_adaptive_reasons_are_a_subset_of_explain_vocabulary():
    assert ADAPTIVE_REASONS <= obs_explain.REASONS


def test_choose_stays_pure_over_scan_mode_widened_cagra_grid():
    """The fused beam engine widened the cagra sweep grid with a
    ``scan_mode`` knob: frontiers can now carry both an XLA-routed and a
    Pallas-forced point at the same (itopk, width). The chooser must
    treat those as ordinary operating points — pure given (points,
    budget, floor, scale), closed reasons — or the committed artifact's
    replay would depend on dict order."""
    from raft_tpu.planner import sweep as planner_sweep

    grid = planner_sweep.default_grid("cagra")
    modes = {g["scan_mode"] for g in grid}
    assert modes == {"auto", "pallas"}
    # both modes appear at every (itopk, width) combo
    combos = {(g["itopk_size"], g["search_width"]) for g in grid}
    assert len(grid) == len(combos) * len(modes)
    # a frontier built over the widened grid: the forced-pallas twin of
    # each point is a hair faster at equal recall (the fused-wins case)
    pts = []
    for i, g in enumerate(sorted(grid, key=json.dumps)):
        fast = g["scan_mode"] == "pallas"
        pts.append(_pt(0.90 + 0.02 * (i // 2), 200.0 + 100.0 * i,
                       20.0 - 2.0 * i - (0.5 if fast else 0.0), g))
    frontier = pareto_prune(pts)
    assert frontier  # the widened grid still prunes to a real frontier
    for budget in (None, 0.0, 3.0, 15.0, 1e6):
        first = choose_operating_point(frontier, budget,
                                       recall_floor=0.9, scale=1.1)
        for _ in range(3):
            assert choose_operating_point(
                frontier, budget, recall_floor=0.9, scale=1.1) == first
        assert first[1] in ADAPTIVE_REASONS
        if first[0] is not None:
            assert first[0].params["scan_mode"] in ("auto", "pallas")


# --------------------------------------------------------- curve summaries
def test_hypervolume_staircase_area():
    pts = [_pt(1.0, 10.0, 1.0), _pt(0.5, 100.0, 1.0)]
    # area: recall 0→0.5 at qps 100, plus 0.5→1.0 at qps 10
    assert hypervolume(pts) == pytest.approx(0.5 * 100 + 0.5 * 10)
    # dominated points don't change the curve
    assert hypervolume([*pts, _pt(0.4, 50.0, 1.0)]) == \
        pytest.approx(hypervolume(pts))


def test_qps_at_recall_bands():
    assert qps_at_recall(FRONTIER, 0.90) == 900.0
    assert qps_at_recall(FRONTIER, 0.97) == 100.0
    assert qps_at_recall(FRONTIER, 0.999) is None


def test_frontier_metrics_names_and_values():
    m = frontier_metrics(_doc(FRONTIER))
    assert m["pareto.ivf_flat.k10.b8.n_points"] == 3.0
    assert m["pareto.ivf_flat.k10.b8.qps_at_r90"] == 900.0
    assert m["pareto.ivf_flat.k10.b8.qps_at_r95"] == 400.0
    assert m["pareto.ivf_flat.k10.b8.hypervolume"] == pytest.approx(
        hypervolume(FRONTIER), abs=1e-3)
    assert "pareto.ivf_flat.k10.b8.qps_at_r99" in m


# ------------------------------------------------------------ the artifact
def test_frontier_round_trip_and_bucket_scaling():
    doc = _doc(FRONTIER, bucket=8)
    f = Frontier(doc)
    assert f.families == ["ivf_flat"]
    assert f.ks("ivf_flat") == [10]
    pts = f.points("ivf_flat", 10, 8)
    assert [p.recall for p in pts] == [0.99, 0.95, 0.90]
    # nearest-bucket lookup scales predicted_ms linearly by row ratio
    scaled = f.points("ivf_flat", 10, 16)
    assert [p.predicted_ms for p in scaled] == [80.0, 20.0, 8.0]
    assert [p.bucket for p in scaled] == [8, 8, 8]  # provenance kept
    assert f.points("cagra", 10, 8) == []
    assert f.points("ivf_flat", 99, 8) == []


def test_frontier_rejects_foreign_schema():
    doc = _doc(FRONTIER)
    doc["schema"] = "raft_tpu.pareto/v999"
    with pytest.raises(ValueError, match="schema"):
        Frontier(doc)


def test_load_frontier_missing_file_raises_oserror(tmp_path):
    with pytest.raises(OSError):
        load_frontier(str(tmp_path / "nope.json"))


def test_committed_artifact_covers_all_families_and_checks_clean():
    path = REPO_ROOT / "PARETO_cpu.json"
    assert path.exists(), "commit PARETO_cpu.json via tools/autotune.py"
    f = load_frontier(str(path))
    assert f.families == ["brute_force", "cagra", "ivf_flat", "ivf_pq"]
    for fam in f.families:
        assert f.points(fam, 10, 8), fam
    assert autotune.check_artifact(str(path)) == 0


def test_check_artifact_rejects_non_monotone_curve(tmp_path):
    doc = _doc(FRONTIER)
    # sneak a dominated point into the committed list
    doc["families"]["ivf_flat"]["frontier"]["10"]["8"].append(
        _pt(0.5, 1.0, 99.0).to_dict())
    p = tmp_path / "PARETO_bad.json"
    p.write_text(json.dumps(doc))
    assert autotune.check_artifact(str(p)) == 1


# ------------------------------------------------------------- calibration
def test_calibration_ewma_converges_and_is_bounded():
    c = Calibration(alpha=0.5)
    assert c.scale == 1.0 and c.n_observed == 0
    for _ in range(20):
        c.observe(10.0, 20.0)  # device runs 2x slower than predicted
    assert c.scale == pytest.approx(2.0, rel=1e-3)
    assert c.n_observed == 20
    # one absurd sample is clamped before it enters the EWMA
    c.observe(1.0, 1e9)
    assert c.scale <= c.hi
    # non-positive samples are ignored
    n = c.n_observed
    c.observe(0.0, 5.0)
    c.observe(5.0, -1.0)
    assert c.n_observed == n


def test_calibration_single_sample_cannot_own_the_scale():
    c = Calibration(alpha=0.2)
    c.observe(10.0, 10_000.0)  # 1000x blowout, clamped to hi=4
    assert c.scale == pytest.approx(1.0 + 0.2 * (4.0 - 1.0))


# ------------------------------------------------------------- attribution
def test_record_choice_rejects_open_vocabulary():
    with pytest.raises(ValueError, match="vocabulary"):
        record_choice("ivf_flat", "because_reasons")


def test_record_choice_bumps_counter_and_rides_captures():
    before = adaptive_choice_counts().get(("ivf_flat", "deadline_degraded"),
                                          0)
    with obs_explain.capture() as cap:
        record_choice("ivf_flat", "deadline_degraded", point=FRONTIER[1],
                      budget_ms=12.0, predicted_ms=10.0)
    after = adaptive_choice_counts()[("ivf_flat", "deadline_degraded")]
    assert after == before + 1
    assert len(cap.records) == 1
    rec = cap.records[0]
    assert (rec.family, rec.requested, rec.engine) == (
        "ivf_flat", "adaptive", "planner")
    assert rec.reason == "deadline_degraded"
    assert rec.plan["budget_ms"] == 12.0


# ------------------------------------------------------------- the planner
def test_planner_from_missing_artifact_serves_static_params(tmp_path):
    planner = AdaptivePlanner.from_artifact(str(tmp_path / "nope.json"))
    choice = planner.choose("ivf_flat", 10, 8, 50.0)
    assert choice.point is None and choice.reason == "no_frontier"


def test_planner_choose_and_observe_close_the_loop():
    planner = AdaptivePlanner(Frontier(_doc(FRONTIER)), recall_floor=0.9)
    generous = planner.choose("ivf_flat", 10, 8, 1000.0)
    assert generous.reason == "pareto_default"
    assert generous.point.recall == 0.99
    tight = planner.choose("ivf_flat", 10, 8, 12.0)
    assert tight.reason == "deadline_degraded"
    assert tight.point.recall == 0.95
    # the device consistently runs 3x the prediction: the EWMA learns it
    for _ in range(30):
        choice = planner.choose("ivf_flat", 10, 8, 12.0)
        planner.observe(choice.predicted_ms,
                        3.0 * choice.point.predicted_ms)
    assert planner.calibration.scale == pytest.approx(3.0, rel=0.05)
    # and the same 12 ms budget now degrades one step further
    recal = planner.choose("ivf_flat", 10, 8, 12.0)
    assert recal.point.recall == 0.90


# ----------------------------------------------------- Request.remaining_ms
def test_request_remaining_ms_units_and_expiry():
    req = Request(np.zeros(4, np.float32), 10, Future(), t_submit=1.0,
                  t_deadline=1.250)
    assert req.remaining_ms(1.0) == pytest.approx(250.0)
    assert req.remaining_ms(1.2) == pytest.approx(50.0)
    assert not req.expired(1.2499)
    assert req.expired(1.2501)
    bare = Request(np.zeros(4, np.float32), 10, Future(), t_submit=1.0)
    assert bare.remaining_ms(99.0) is None
    assert not bare.expired(99.0)


# -------------------------------------------------------- Engine policy
HI_MS, LO_MS = 40.0, 2.0
STUB_DIM, STUB_K = 8, 5


def _stub_searcher(counts=None):
    """A Searcher whose device cost is the operating point: ``search``
    (the static path) costs HI_MS, ``search_with`` costs the point's
    ``cost_ms`` knob — so the policy's choices are directly observable
    as wall time."""
    counts = counts if counts is not None else {}

    def _result(n, k):
        return (np.zeros((n, k), np.float32),
                np.zeros((n, k), np.int32))

    def search_with(queries, k, overrides):
        cost = float(overrides.get("cost_ms", HI_MS))
        time.sleep(cost * 1e-3)
        counts[cost] = counts.get(cost, 0) + 1
        return _result(len(queries), k)

    def search(queries, k):
        return search_with(queries, k, {})

    return Searcher("ivf_flat", STUB_DIM, types.SimpleNamespace(),
                    search, search_with=search_with)


def _stub_frontier():
    return Frontier(_doc([
        _pt(1.0, 100.0, HI_MS, {"cost_ms": HI_MS}, bucket=4),
        _pt(0.90, 2000.0, LO_MS, {"cost_ms": LO_MS}, bucket=4),
    ], k=STUB_K, bucket=4))


class _ListSink:
    def __init__(self):
        self.records = []

    def emit(self, rec):
        self.records.append(rec)


def _engine(searcher, planner=None, sink=None, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_wait_us", 1000)
    kw.setdefault("warm_ks", (STUB_K,))
    kw.setdefault("warm_buckets", (4,))
    kw.setdefault("hang_timeout_s", None)
    return serving.Engine(searcher, serving.EngineConfig(
        planner=planner, span_sink=sink, **kw))


def test_engine_generous_deadline_serves_highest_recall_point():
    counts = {}
    sink = _ListSink()
    planner = AdaptivePlanner(_stub_frontier(), recall_floor=0.9)
    before = adaptive_choice_counts().get(("ivf_flat", "pareto_default"), 0)
    with _engine(_stub_searcher(counts), planner, sink) as eng:
        d, i = eng.search(np.zeros(STUB_DIM, np.float32), STUB_K,
                          deadline_ms=5000.0)
    assert d.shape == (STUB_K,)
    assert adaptive_choice_counts()[("ivf_flat", "pareto_default")] > before
    briefs = [r["adaptive"] for r in sink.records
              if r.get("kind") == "request" and "adaptive" in r]
    assert briefs and briefs[-1]["reason"] == "pareto_default"
    assert briefs[-1]["params"] == {"cost_ms": HI_MS}


def test_engine_tight_deadline_degrades_instead_of_shedding():
    counts = {}
    sink = _ListSink()
    planner = AdaptivePlanner(_stub_frontier(), recall_floor=0.9)
    with _engine(_stub_searcher(counts), planner, sink) as eng:
        # 25 ms budget < HI_MS: the static engine would serve this late
        # (or shed it under load); the planner drops to the LO point
        d, i = eng.search(np.zeros(STUB_DIM, np.float32), STUB_K,
                          deadline_ms=25.0)
    assert d.shape == (STUB_K,)
    briefs = [r["adaptive"] for r in sink.records
              if r.get("kind") == "request" and "adaptive" in r]
    assert briefs and briefs[-1]["reason"] == "deadline_degraded"
    assert briefs[-1]["params"] == {"cost_ms": LO_MS}
    # the LO program actually served (warmup used both)
    assert counts.get(LO_MS, 0) >= 1


def test_engine_without_frontier_serves_static_params_attributed():
    sink = _ListSink()
    planner = AdaptivePlanner(frontier=None)
    before = adaptive_choice_counts().get(("ivf_flat", "no_frontier"), 0)
    with _engine(_stub_searcher(), planner, sink) as eng:
        d, i = eng.search(np.zeros(STUB_DIM, np.float32), STUB_K)
    assert d.shape == (STUB_K,)
    assert adaptive_choice_counts()[("ivf_flat", "no_frontier")] > before
    briefs = [r["adaptive"] for r in sink.records
              if r.get("kind") == "request" and "adaptive" in r]
    assert briefs and briefs[-1]["reason"] == "no_frontier"
    assert "params" not in briefs[-1]


def _drive_overload(eng, n, deadline_ms):
    """Burst-submit ``n`` requests (2x+ the deadline-window capacity at
    the HI cost) and count served vs shed."""
    futures = []
    for _ in range(n):
        futures.append(eng.submit(np.zeros(STUB_DIM, np.float32), STUB_K,
                                  deadline_ms=deadline_ms))
    ok = shed = 0
    for f in futures:
        try:
            f.result(timeout=30.0)
            ok += 1
        except Exception:
            shed += 1
    return ok, shed


def test_engine_overload_goodput_degradation_beats_shedding():
    # 36 requests x HI_MS=40 ms at max_batch=4 is ~360 ms of device time
    # against a 150 ms deadline — ~2.4x overload. The shed-only engine
    # serves the first few batches and sheds the rest; the adaptive
    # engine degrades to the LO point (recall 0.90 = the floor) as the
    # budget tightens and serves (nearly) everything.
    n, deadline_ms = 36, 150.0

    with _engine(_stub_searcher()) as shed_eng:
        shed_ok, shed_shed = _drive_overload(shed_eng, n, deadline_ms)

    planner = AdaptivePlanner(_stub_frontier(), recall_floor=0.9)
    before = dict(adaptive_choice_counts())
    with _engine(_stub_searcher(), planner) as ada_eng:
        ada_ok, ada_shed = _drive_overload(ada_eng, n, deadline_ms)

    assert shed_shed > 0  # the baseline really was overloaded
    assert ada_ok > shed_ok  # degradation strictly beats shedding
    assert ada_ok >= int(0.6 * n)
    # the policy visibly degraded, and every reason stayed closed
    after = adaptive_choice_counts()
    degraded = after.get(("ivf_flat", "deadline_degraded"), 0) - \
        before.get(("ivf_flat", "deadline_degraded"), 0)
    assert degraded >= 1
    for (_, reason), _cnt in after.items():
        assert reason in ADAPTIVE_REASONS
    # degradation never went below the floor: the only points served
    # carry recall >= 0.9 by construction of the frontier
    assert planner.recall_floor == 0.9


def test_engine_calibration_observes_completed_batches():
    planner = AdaptivePlanner(_stub_frontier(), recall_floor=0.9)
    assert planner.calibration.n_observed == 0
    with _engine(_stub_searcher(), planner) as eng:
        for _ in range(3):
            eng.search(np.zeros(STUB_DIM, np.float32), STUB_K,
                       deadline_ms=5000.0)
    assert planner.calibration.n_observed >= 1
    assert 0.25 <= planner.calibration.scale <= 4.0
