"""dispersion + trustworthiness tests (reference: cpp/test/stats/
dispersion.cu, trustworthiness.cu)."""

import numpy as np

from raft_tpu.stats import dispersion, trustworthiness_score


def test_dispersion_zero_when_identical():
    c = np.ones((4, 3), np.float32)
    s = np.array([5, 5, 5, 5], np.float32)
    assert float(dispersion(c, s)) < 1e-6


def test_dispersion_scales_with_spread():
    s = np.array([10.0, 10.0], np.float32)
    near = np.array([[0.0, 0], [1, 0]], np.float32)
    far = np.array([[0.0, 0], [10, 0]], np.float32)
    assert float(dispersion(far, s)) > float(dispersion(near, s))


def test_trustworthiness_perfect_embedding(rng):
    x = rng.standard_normal((100, 8)).astype(np.float32)
    t = float(trustworthiness_score(x, x, n_neighbors=5))
    assert t >= 0.999


def test_trustworthiness_degrades_with_shuffle(rng):
    x = rng.standard_normal((100, 8)).astype(np.float32)
    bad = x[rng.permutation(100)]
    t_good = float(trustworthiness_score(x, x, n_neighbors=5))
    t_bad = float(trustworthiness_score(x, bad, n_neighbors=5))
    assert t_bad < t_good
