"""dispersion + trustworthiness tests (reference: cpp/test/stats/
dispersion.cu, trustworthiness.cu)."""

import numpy as np

from raft_tpu.stats import dispersion, trustworthiness_score


def test_dispersion_zero_when_identical():
    c = np.ones((4, 3), np.float32)
    s = np.array([5, 5, 5, 5], np.float32)
    assert float(dispersion(c, s)) < 1e-6


def test_dispersion_scales_with_spread():
    s = np.array([10.0, 10.0], np.float32)
    near = np.array([[0.0, 0], [1, 0]], np.float32)
    far = np.array([[0.0, 0], [10, 0]], np.float32)
    assert float(dispersion(far, s)) > float(dispersion(near, s))


def test_trustworthiness_perfect_embedding(rng):
    x = rng.standard_normal((100, 8)).astype(np.float32)
    t = float(trustworthiness_score(x, x, n_neighbors=5))
    assert t >= 0.999


def test_trustworthiness_degrades_with_shuffle(rng):
    x = rng.standard_normal((100, 8)).astype(np.float32)
    bad = x[rng.permutation(100)]
    t_good = float(trustworthiness_score(x, x, n_neighbors=5))
    t_bad = float(trustworthiness_score(x, bad, n_neighbors=5))
    assert t_bad < t_good


# ---------------------------------------------------------------------------
# breadth additions: sum/mean_center/meanvar/kl/regression/IC/contingency

def test_sum_mean_center_meanvar(rng):
    from raft_tpu import stats

    x = rng.standard_normal((20, 5)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(stats.sum(x)), x.sum(0), rtol=1e-5)
    centered, mu = stats.mean_center(x)
    np.testing.assert_allclose(np.asarray(mu), x.mean(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(centered), x - x.mean(0),
                               rtol=1e-5, atol=1e-6)
    m, v = stats.meanvar(x, sample=True)
    np.testing.assert_allclose(np.asarray(v), x.var(0, ddof=1), rtol=1e-4)


def test_kl_divergence_stat(rng):
    from raft_tpu import stats

    p = rng.random(32).astype(np.float32)
    q = rng.random(32).astype(np.float32)
    p /= p.sum(); q /= q.sum()
    got = float(stats.kl_divergence(p, q))
    ref = float((p * (np.log(p) - np.log(q))).sum())
    assert abs(got - ref) < 1e-4
    assert float(stats.kl_divergence(p, p)) < 1e-6


def test_regression_metrics(rng):
    from raft_tpu import stats

    yt = rng.standard_normal(50).astype(np.float32)
    yp = yt + rng.standard_normal(50).astype(np.float32) * 0.1
    mae, mse, medae = stats.regression_metrics(yt, yp)
    err = yp - yt
    np.testing.assert_allclose(float(mae), np.abs(err).mean(), rtol=1e-4)
    np.testing.assert_allclose(float(mse), (err ** 2).mean(), rtol=1e-4)
    np.testing.assert_allclose(float(medae), np.median(np.abs(err)), rtol=1e-4)


def test_information_criterion():
    from raft_tpu import stats

    ll = np.array([-100.0, -50.0], np.float32)
    aic = np.asarray(stats.information_criterion_batched(ll, 3, 100, "aic"))
    np.testing.assert_allclose(aic, -2 * ll + 6)
    bic = np.asarray(stats.information_criterion_batched(ll, 3, 100, "bic"))
    np.testing.assert_allclose(bic, -2 * ll + 3 * np.log(100), rtol=1e-6)
    aicc = np.asarray(stats.information_criterion_batched(ll, 3, 100, "aicc"))
    assert (aicc > aic).all()


def test_contingency_matrix():
    from raft_tpu import stats

    a = np.array([0, 0, 1, 2, 2], np.int32)
    b = np.array([1, 1, 0, 0, 1], np.int32)
    c = np.asarray(stats.contingency_matrix(a, b, 3, 2))
    ref = np.zeros((3, 2), np.int32)
    for i, j in zip(a, b):
        ref[i, j] += 1
    np.testing.assert_array_equal(c, ref)
