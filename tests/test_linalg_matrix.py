"""linalg + matrix prim tests — reference-vs-numpy pattern
(cpp/test/linalg/*, cpp/test/matrix/*)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.ops import linalg, matrix


@pytest.fixture()
def a(rng):
    return rng.standard_normal((40, 24)).astype(np.float32)


def test_gemm_gemv_axpy_dot(a, rng):
    b = rng.standard_normal((24, 8)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(linalg.gemm(a, b)), a @ b,
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(linalg.gemm(a, b.T, trans_b=True, alpha=2.0)),
        2.0 * (a @ b), rtol=1e-5, atol=1e-5)
    v = rng.standard_normal(24).astype(np.float32)
    np.testing.assert_allclose(np.asarray(linalg.gemv(a, v)), a @ v,
                               rtol=1e-5, atol=1e-5)
    y = rng.standard_normal(24).astype(np.float32)
    np.testing.assert_allclose(np.asarray(linalg.axpy(2.5, v, y)),
                               y + 2.5 * v, rtol=1e-6)
    np.testing.assert_allclose(float(linalg.dot(v, y)), float(v @ y),
                               rtol=1e-5)


def test_reductions_and_norms(a):
    np.testing.assert_allclose(np.asarray(linalg.coalesced_reduction(a)),
                               a.sum(-1), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(linalg.strided_reduction(a)),
                               a.sum(0), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(linalg.norm(a, "l2", sqrt=True)),
                               np.linalg.norm(a, axis=1), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(linalg.norm(a, "l1")),
                               np.abs(a).sum(1), rtol=1e-5)
    nz = np.asarray(linalg.normalize(a))
    np.testing.assert_allclose(np.linalg.norm(nz, axis=1), 1.0, rtol=1e-5)


def test_reduce_rows_by_key(rng):
    x = rng.standard_normal((30, 4)).astype(np.float32)
    keys = rng.integers(0, 5, 30)
    got = np.asarray(linalg.reduce_rows_by_key(x, keys, 5))
    want = np.zeros((5, 4), np.float32)
    np.add.at(want, keys, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_decompositions(a):
    q = np.asarray(linalg.qr_get_q(a))
    np.testing.assert_allclose(q.T @ q, np.eye(q.shape[1]), atol=1e-4)
    s = a.T @ a + 24 * np.eye(24, dtype=np.float32)
    c = np.asarray(linalg.cholesky(s))
    np.testing.assert_allclose(c @ c.T, s, rtol=1e-3, atol=1e-2)
    w, v = linalg.eig_dc(s)
    w, v = np.asarray(w), np.asarray(v)
    np.testing.assert_allclose(s @ v, v * w[None, :], rtol=1e-2, atol=1e-2)
    u, sv, vv = linalg.svd(a)
    recon = np.asarray(u) * np.asarray(sv)[None, :] @ np.asarray(vv).T
    np.testing.assert_allclose(recon, a, rtol=1e-3, atol=1e-3)


def test_rsvd_approximates_topk(rng):
    # low-rank + noise: rsvd should capture the dominant subspace
    u = rng.standard_normal((60, 5)).astype(np.float32)
    v = rng.standard_normal((5, 40)).astype(np.float32)
    a = u @ v + 0.01 * rng.standard_normal((60, 40)).astype(np.float32)
    uu, ss, vv = linalg.rsvd(jax.random.key(0), a, k=5)
    recon = np.asarray(uu) * np.asarray(ss)[None, :] @ np.asarray(vv).T
    rel = np.linalg.norm(recon - a) / np.linalg.norm(a)
    assert rel < 0.05, rel


def test_lanczos_extremal_eigs(rng):
    # symmetric with known spectrum
    n = 50
    q, _ = np.linalg.qr(rng.standard_normal((n, n)))
    w_true = np.linspace(1, 100, n).astype(np.float32)
    s = (q * w_true[None, :]) @ q.T
    s = ((s + s.T) / 2).astype(np.float32)
    sj = jnp.asarray(s)

    def matvec(v):
        return jnp.matmul(sj, v, precision=jax.lax.Precision.HIGHEST)

    w, v = linalg.lanczos(matvec, n, 3, key=jax.random.key(1), ncv=40)
    np.testing.assert_allclose(np.sort(np.asarray(w)), w_true[:3], rtol=0.05)
    w2, _ = linalg.lanczos(matvec, n, 2, key=jax.random.key(2), ncv=40,
                           which="largest")
    np.testing.assert_allclose(np.sort(np.asarray(w2)), w_true[-2:],
                               rtol=0.02)


def test_matrix_ops(a, rng):
    idx = rng.integers(0, 40, 10)
    np.testing.assert_array_equal(np.asarray(matrix.gather(a, idx)), a[idx])
    np.testing.assert_array_equal(
        np.asarray(matrix.argmax(a)), a.argmax(1))
    np.testing.assert_array_equal(
        np.asarray(matrix.argmin(a)), a.argmin(1))
    np.testing.assert_array_equal(
        np.asarray(matrix.slice(a, 5, 15, 2, 10)), a[5:15, 2:10])
    s = np.asarray(matrix.col_wise_sort(a))
    np.testing.assert_array_equal(s, np.sort(a, axis=0))
    v, k = matrix.row_wise_sort(a, return_keys=True)
    np.testing.assert_array_equal(np.asarray(v), np.sort(a, axis=1))
    r = np.asarray(matrix.reverse(a, axis=1))
    np.testing.assert_array_equal(r, a[:, ::-1])
    # select_k re-export sanity
    vals, ids = matrix.select_k(a, 3)
    np.testing.assert_allclose(np.asarray(vals), np.sort(a, 1)[:, :3],
                               rtol=1e-6)
