"""bench_gate tests: direction classification, artifact-shape flattening,
the best-of-N noise rule, and the three exit codes the CI/queue wiring
relies on (0 clean, 1 regressed/missing, 2 unusable input)."""

import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "tools"))
import bench_gate  # noqa: E402

pytestmark = pytest.mark.fast


# --------------------------------------------------------------- direction
@pytest.mark.parametrize("name,want", [
    ("brute_force_knn_qps_sift10k_k10", +1),
    ("mini_brute_force_qps_2000x32_k10", +1),
    ("ivf_flat_nprobe8.qps", +1),
    ("ivf_flat_nprobe8.recall", +1),
    ("select_k_256x8192.rows_per_s", +1),
    ("cagra.build_s", -1),
    ("ivf_pq.latency_ms_b1", -1),
    ("fused.p99_ms", -1),
    ("serving.wall_s", -1),
    ("some_random_counter", None),
    ("n_lists", None),
])
def test_metric_direction(name, want):
    assert bench_gate.metric_direction(name) == want


# -------------------------------------------------------------- flattening
def test_flatten_accepts_all_three_artifact_shapes():
    raw = {"metric": "knn_qps", "value": 100.0, "recall": 0.98,
           "extra": {"ivf_flat": {"qps": 50.0, "build_s": 2.0},
                     "notes": "not-a-dict-of-numbers"}}
    flat = bench_gate.flatten_metrics(raw)
    assert flat == {"knn_qps": 100.0, "knn_qps.recall": 0.98,
                    "ivf_flat.qps": 50.0, "ivf_flat.build_s": 2.0}
    # the tpu_queue wrapper unwraps to the same thing
    assert bench_gate.flatten_metrics({"parsed": raw}) == flat
    # a flat metrics document passes through
    assert bench_gate.flatten_metrics(
        {"metrics": {"a_qps": 1.0, "skip": "str"}}) == {"a_qps": 1.0}


def test_load_bench_scans_log_for_last_metric_line(tmp_path):
    log = tmp_path / "bench.log"
    log.write_text(
        "warmup chatter\n"
        '{"metric": "knn_qps", "value": 90.0}\n'
        "not json {\n"
        '{"metric": "knn_qps", "value": 110.0}\n')
    assert bench_gate.load_bench(str(log)) == {"knn_qps": 110.0}
    empty = tmp_path / "empty.log"
    empty.write_text("nothing here\n")
    with pytest.raises(ValueError, match="no JSON bench line"):
        bench_gate.load_bench(str(empty))


# -------------------------------------------------------------------- gate
def _verdict(verdicts, name):
    return next(v for v in verdicts if v.metric == name)


def test_gate_verdicts_are_direction_aware():
    base = {"a_qps": 100.0, "b.latency_ms": 10.0, "c_qps": 100.0,
            "d.build_s": 5.0, "mystery": 3.0, "gone_qps": 1.0}
    cand = {"a_qps": 90.0,        # -10% on higher-better: regressed
            "b.latency_ms": 9.0,  # -10% on lower-better: improved
            "c_qps": 103.0,       # +3% inside the band: flat
            "d.build_s": 5.1,     # +2% inside the band: flat
            "mystery": 9.9}       # unknown direction: ignored
    vs = bench_gate.gate(base, [cand], tolerance=0.05)
    got = {v.metric: v.verdict for v in vs}
    assert got == {"a_qps": "regressed", "b.latency_ms": "improved",
                   "c_qps": "flat", "d.build_s": "flat",
                   "mystery": "ignored", "gone_qps": "missing"}
    assert _verdict(vs, "a_qps").rel_change == pytest.approx(-0.10)
    # lower-better rel_change is direction-normalized: less is positive
    assert _verdict(vs, "b.latency_ms").rel_change == pytest.approx(+0.10)


def test_gate_best_of_n_forgives_one_noisy_repeat():
    """A one-off hiccup in one repeat must not gate; a loss sustained
    across every repeat must."""
    base = {"a_qps": 100.0}
    hiccup = [{"a_qps": 60.0}, {"a_qps": 99.0}]  # one bad, one fine
    assert bench_gate.gate(base, hiccup, 0.05)[0].verdict == "flat"
    sustained = [{"a_qps": 80.0}, {"a_qps": 82.0}]
    assert bench_gate.gate(base, sustained, 0.05)[0].verdict == "regressed"
    # lower-better best is the MIN across repeats
    base_ms = {"a.latency_ms": 10.0}
    vs = bench_gate.gate(base_ms, [{"a.latency_ms": 14.0},
                                   {"a.latency_ms": 10.1}], 0.05)
    assert vs[0].verdict == "flat" and vs[0].best == 10.1


def test_gate_zero_baseline_does_not_divide():
    vs = bench_gate.gate({"a_qps": 0.0}, [{"a_qps": 0.0}], 0.05)
    assert vs[0].verdict == "flat"
    vs = bench_gate.gate({"a_qps": 0.0}, [{"a_qps": 5.0}], 0.05)
    assert vs[0].verdict == "improved"


# -------------------------------------------------------------- exit codes
def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_main_exit_codes(tmp_path, capsys):
    base = _write(tmp_path, "base.json",
                  {"metrics": {"a_qps": 100.0, "b.latency_ms": 10.0}})
    same = _write(tmp_path, "same.json",
                  {"metrics": {"a_qps": 101.0, "b.latency_ms": 9.9}})
    worse = _write(tmp_path, "worse.json",
                   {"metrics": {"a_qps": 80.0, "b.latency_ms": 10.0}})
    partial = _write(tmp_path, "partial.json", {"metrics": {"a_qps": 99.0}})

    assert bench_gate.main([base, same]) == 0
    assert bench_gate.main([base, worse]) == 1
    # best-of-N: the clean repeat rescues the noisy one
    assert bench_gate.main([base, worse, same]) == 0
    # missing gates by default, --allow-missing waives it
    assert bench_gate.main([base, partial]) == 1
    assert bench_gate.main([base, partial, "--allow-missing"]) == 0
    # unusable inputs are exit 2, not a traceback
    assert bench_gate.main([str(tmp_path / "nope.json"), same]) == 2
    empty = _write(tmp_path, "empty.json", {"metrics": {}})
    assert bench_gate.main([empty, same]) == 2
    capsys.readouterr()


def test_main_writes_verdict_json(tmp_path, capsys):
    base = _write(tmp_path, "base.json", {"metrics": {"a_qps": 100.0}})
    cand = _write(tmp_path, "cand.json", {"metrics": {"a_qps": 120.0}})
    out = tmp_path / "verdicts.json"
    assert bench_gate.main([base, cand, "--json", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert doc["n_repeats"] == 1
    assert doc["verdicts"][0]["verdict"] == "improved"
    assert "1 improved" in capsys.readouterr().out


# ---------------------------------------------------------- frontier kind
def _pareto_doc(curves):
    """``curves``: {family: [(recall, qps), ...]} → a minimal
    raft_tpu.pareto/v1 doc (k=10, bucket=8)."""
    fams = {}
    for fam, pts in curves.items():
        fams[fam] = {"frontier": {"10": {"8": [
            {"params": {"n_probes": i}, "bucket": 8, "qps": q,
             "recall": r, "predicted_ms": 8.0 / q * 1e3}
            for i, (r, q) in enumerate(pts)]}}}
    return {"schema": "raft_tpu.pareto/v1", "platform": "cpu",
            "families": fams}


def test_flatten_frontier_yields_curve_summaries_not_points():
    flat = bench_gate.flatten_metrics(
        _pareto_doc({"ivf_flat": [(0.99, 100.0), (0.90, 900.0)]}))
    assert flat["pareto.ivf_flat.k10.b8.n_points"] == 2.0
    assert flat["pareto.ivf_flat.k10.b8.qps_at_r90"] == 900.0
    assert flat["pareto.ivf_flat.k10.b8.hypervolume"] > 0
    # no per-point metric leaks out — points may move freely on re-sweep
    assert not any("n_probes" in k or "predicted_ms" in k for k in flat)


def test_gate_frontier_pass_on_moved_points_same_curve(tmp_path):
    base = _write(tmp_path, "pareto_base.json",
                  _pareto_doc({"ivf_flat": [(0.99, 100.0), (0.90, 900.0)]}))
    # a re-sweep found a different but equivalent frontier: an extra
    # mid-curve point, slight point movement within tolerance
    cand = _write(tmp_path, "pareto_cand.json",
                  _pareto_doc({"ivf_flat": [(0.99, 101.0), (0.95, 400.0),
                                            (0.90, 905.0)]}))
    assert bench_gate.main([base, cand, "--allow-missing"]) == 0


def test_gate_frontier_fails_on_degraded_curve(tmp_path):
    base = _write(tmp_path, "pareto_base.json",
                  _pareto_doc({"ivf_flat": [(0.99, 100.0), (0.90, 900.0)]}))
    # the high-recall end got 40% slower: hypervolume + qps_at_r99 drop
    worse = _write(tmp_path, "pareto_worse.json",
                   _pareto_doc({"ivf_flat": [(0.99, 60.0), (0.90, 900.0)]}))
    assert bench_gate.main([base, worse]) == 1


def test_gate_frontier_recomputes_ignoring_stale_mirror(tmp_path):
    # an embedded metrics mirror claiming a better curve must not mask
    # the regression — the gate recomputes from the points
    doc = _pareto_doc({"ivf_flat": [(0.99, 60.0)]})
    doc["metrics"] = {"pareto.ivf_flat.k10.b8.qps_at_r99": 100.0}
    base = _write(tmp_path, "pareto_base.json",
                  _pareto_doc({"ivf_flat": [(0.99, 100.0)]}))
    lying = _write(tmp_path, "pareto_lying.json", doc)
    assert bench_gate.main([base, lying, "--allow-missing"]) == 1
