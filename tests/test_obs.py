"""obs/ — metrics registry, span sinks, scrape server (CPU-checked).

Every assertion here is against a private Registry instance (the global
one is shared with the serving stats and the p2p counters, so tests
never mutate it), except the device compile counters, which are
process-global by nature."""

import json
import math
import threading
import urllib.request

import pytest

from raft_tpu.obs import metrics as obm
from raft_tpu.obs import spans as obs
from raft_tpu.obs.httpd import MetricsServer

pytestmark = pytest.mark.fast


# ------------------------------------------------------------- registry

def test_counter_basics_and_monotonicity():
    reg = obm.Registry()
    c = reg.counter("x_total", "help text")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_get_or_create_is_idempotent_and_schema_checked():
    reg = obm.Registry()
    a = reg.counter("x_total", "h", ("peer",))
    b = reg.counter("x_total", "different help", ("peer",))
    assert a is b
    with pytest.raises(ValueError, match="labels"):
        reg.counter("x_total", "h", ("rank",))
    with pytest.raises(ValueError, match="counter"):
        reg.gauge("x_total")
    assert reg.get("x_total") is a
    assert reg.get("nope") is None


def test_labeled_children_are_distinct_series():
    reg = obm.Registry()
    c = reg.counter("msgs_total", "", ("peer",))
    c.labels(0).inc(5)
    c.labels("1").inc(7)
    assert c.labels("0").value == 5      # values stringify
    assert c.labels(1).value == 7
    with pytest.raises(ValueError, match="label"):
        c.labels("a", "b")


def test_gauge_set_inc_dec_and_callback():
    reg = obm.Registry()
    g = reg.gauge("depth")
    g.set(10)
    g.dec(3)
    assert g.value == 7.0
    g.set_function(lambda: 42.0)
    assert g.value == 42.0
    g.set(1.0)  # set clears the callback
    assert g.value == 1.0
    g.set_function(lambda: 1 / 0)  # a raising callback reads as NaN
    assert math.isnan(g.value)


def test_exponential_buckets():
    b = obm.exponential_buckets(1.0, 2.0, 4)
    assert b == (1.0, 2.0, 4.0, 8.0)
    with pytest.raises(ValueError):
        obm.exponential_buckets(0.0, 2.0, 4)
    with pytest.raises(ValueError):
        obm.exponential_buckets(1.0, 1.0, 4)
    assert len(obm.DEFAULT_LATENCY_BUCKETS) == 20
    assert obm.DEFAULT_LATENCY_BUCKETS[0] == pytest.approx(5e-5)


def test_histogram_observe_quantile_and_mean():
    reg = obm.Registry()
    h = reg.histogram("lat_seconds", "", buckets=(0.1, 0.2, 0.4, 0.8))
    for v in (0.05, 0.15, 0.15, 0.3):
        h.observe(v)
    snap = h.snapshot()
    assert snap.count == 4
    assert snap.mean == pytest.approx(0.1625)
    # rank-2 of 4 falls in the (0.1, 0.2] bucket holding 2 obs
    assert 0.1 <= snap.quantile(0.5) <= 0.2
    # p100 lands in (0.2, 0.4]
    assert 0.2 <= snap.quantile(1.0) <= 0.4
    assert snap.quantile(0.0) == 0.0 or snap.quantile(0.0) <= 0.1
    with pytest.raises(ValueError):
        snap.quantile(1.5)


def test_histogram_overflow_clamps_to_last_finite_bound():
    reg = obm.Registry()
    h = reg.histogram("big_seconds", "", buckets=(0.1, 0.2))
    h.observe(99.0)
    snap = h.snapshot()
    assert snap.counts[-1] == 1  # overflow bucket
    assert snap.quantile(0.99) == 0.2


def test_snapshot_diff_is_the_windowing_primitive():
    reg = obm.Registry()
    h = reg.histogram("w_seconds", "", buckets=(0.1, 0.2, 0.4))
    h.observe(0.05)
    before = h.snapshot()
    h.observe(0.3)
    h.observe(0.3)
    window = h.snapshot() - before
    assert window.count == 2
    assert window.mean == pytest.approx(0.3)
    assert 0.2 <= window.quantile(0.5) <= 0.4
    # empty window is all zeros, quantile 0.0
    empty = h.snapshot() - h.snapshot()
    assert empty.count == 0 and empty.quantile(0.99) == 0.0
    other = reg.histogram("other_seconds", "", buckets=(1.0,))
    with pytest.raises(ValueError):
        h.snapshot() - other.snapshot()


def test_histogram_threaded_observers_lose_nothing():
    reg = obm.Registry()
    h = reg.histogram("t_seconds", "", buckets=(0.5,))
    child = h.labels()

    def worker():
        for _ in range(1000):
            child.observe(0.1)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.snapshot().count == 4000


# ----------------------------------------------------------- exposition

def test_prometheus_text_format():
    reg = obm.Registry()
    reg.counter("req_total", "requests served", ("engine",)) \
       .labels("e0").inc(3)
    reg.gauge("cov", "coverage").set(0.5)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 0.2))
    h.observe(0.05)
    h.observe(0.15)
    h.observe(9.0)
    text = reg.to_prometheus_text()
    assert "# HELP req_total requests served" in text
    assert "# TYPE req_total counter" in text
    assert 'req_total{engine="e0"} 3' in text
    assert "# TYPE cov gauge" in text
    assert "cov 0.5" in text
    # buckets are CUMULATIVE and +Inf equals the count
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="0.2"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf"} 3' in text
    assert "lat_seconds_count 3" in text
    assert text.endswith("\n")


def test_prometheus_label_escaping():
    reg = obm.Registry()
    reg.counter("esc_total", "", ("path",)).labels('a"b\\c\nd').inc()
    text = reg.to_prometheus_text()
    assert 'path="a\\"b\\\\c\\nd"' in text


def test_json_dump_round_trips(tmp_path):
    reg = obm.Registry()
    reg.counter("c_total", "h").inc(2)
    h = reg.histogram("l_seconds", "", buckets=(0.1, 0.2))
    h.observe(0.15)
    doc = reg.to_json()
    assert doc["c_total"]["series"][0]["value"] == 2.0
    hs = doc["l_seconds"]["series"][0]
    assert hs["count"] == 1 and 100.0 <= hs["p50_ms"] <= 200.0
    p = tmp_path / "metrics.json"
    reg.dump_json(str(p))
    assert json.loads(p.read_text())["c_total"]["kind"] == "counter"


# ---------------------------------------------------------------- spans

def test_trace_ids_are_unique_16_hex():
    ids = {obs.new_trace_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(len(i) == 16 and int(i, 16) >= 0 for i in ids)


def test_list_sink_and_safe_emit():
    sink = obs.ListSink()
    obs.safe_emit(sink, {"kind": "request", "x": 1})
    obs.safe_emit(None, {"kind": "request"})  # no-op, no raise
    assert len(sink) == 1
    assert sink.by_kind("request")[0]["x"] == 1
    assert sink.by_kind("batch") == []
    sink.clear()
    assert len(sink) == 0

    class Exploding:
        def emit(self, record):
            raise RuntimeError("sink down")

    errors_before = obs._SINK_ERRORS.value
    obs.safe_emit(Exploding(), {"kind": "request"})  # silenced
    assert obs._SINK_ERRORS.value == errors_before + 1


def test_jsonl_sink_and_read_back(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    with obs.JsonlSink(path) as sink:
        sink.emit({"kind": "request", "trace_id": "aa", "total_ms": 1.5})
        sink.emit({"kind": "batch", "trace_ids": ["aa"]})
    with open(path, "a") as f:
        f.write('{"torn": ')  # crashed-writer tail must not break reads
    recs = obs.read_jsonl(path)
    assert len(recs) == 2
    assert obs.read_jsonl(path, kind="batch")[0]["trace_ids"] == ["aa"]
    # emit after close is a silent no-op
    sink2 = obs.JsonlSink(path)
    sink2.close()
    sink2.emit({"kind": "request"})
    assert len(obs.read_jsonl(path)) == 2


def test_timed_span_durations_and_errors():
    sink = obs.ListSink()
    with obs.timed_span(sink, "phase", step="warmup") as rec:
        rec["n"] = 7
    (r,) = sink.records
    assert r["kind"] == "phase" and r["step"] == "warmup" and r["n"] == 7
    assert r["duration_ms"] >= 0 and len(r["trace_id"]) == 16
    with pytest.raises(ValueError, match="boom"), \
            obs.timed_span(sink, "phase", trace_id="ff" * 8):
        raise ValueError("boom")
    failed = sink.records[-1]
    assert failed["trace_id"] == "ff" * 8
    assert failed["error"].startswith("ValueError")


# ---------------------------------------------------------------- httpd

def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_metrics_server_routes():
    reg = obm.Registry()
    reg.counter("served_total", "h").inc(9)
    health = {"status": "ok", "queue_depth": 0}
    with MetricsServer(port=0, registry=reg,
                       health_fn=lambda: health) as srv:
        code, body = _get(srv.url + "/metrics")
        assert code == 200 and "served_total 9" in body
        code, body = _get(srv.url + "/metrics.json")
        assert code == 200
        assert json.loads(body)["served_total"]["series"][0]["value"] == 9
        code, body = _get(srv.url + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        health["status"] = "degraded"  # alive-but-shedding is still 200
        assert _get(srv.url + "/healthz")[0] == 200
        health["status"] = "stopped"
        assert _get(srv.url + "/healthz")[0] == 503
        assert _get(srv.url + "/nope")[0] == 404


def test_metrics_server_503_when_health_fn_raises():
    def bad_health():
        raise RuntimeError("engine gone")

    with MetricsServer(port=0, health_fn=bad_health) as srv:
        code, body = _get(srv.url + "/healthz")
        assert code == 503 and "engine gone" in body


def test_metrics_server_counts_handler_failures():
    # graftcheck F003 regression: a handler failure must not vanish —
    # the 500 is sent AND the error lands in the scraped registry
    def bad_slo():
        raise RuntimeError("monitor gone")

    reg = obm.Registry()
    with MetricsServer(port=0, registry=reg, slo_fn=bad_slo) as srv:
        code, body = _get(srv.url + "/slo")
        assert code == 500 and "monitor gone" in body
        fam = reg.get("raft_tpu_http_errors_total")
        assert fam is not None
        counts = {labels: child.value for labels, child in fam.collect()}
        assert counts[("/slo", "RuntimeError")] == 1


def test_metrics_server_defaults_to_global_registry():
    from raft_tpu.obs.metrics import REGISTRY
    marker = REGISTRY.counter("obs_test_marker_total", "test only")
    marker.inc()
    with MetricsServer(port=0) as srv:
        code, body = _get(srv.url + "/metrics")
        assert code == 200 and "obs_test_marker_total" in body
        # no health_fn: healthz is an unconditional liveness 200
        assert _get(srv.url + "/healthz")[0] == 200


# --------------------------------------------------------------- device

def test_compile_counters_installed_and_monotonic():
    import jax
    import jax.numpy as jnp

    from raft_tpu.obs import device as obd

    obd.install_compile_metrics()
    obd.install_compile_metrics()  # idempotent
    before = obd.compile_count()

    @jax.jit
    def fresh(x):
        return x * 3.0 + 1.0

    fresh(jnp.ones(5)).block_until_ready()
    after = obd.compile_count()
    assert after >= before + 1
    assert obd.compile_seconds() >= 0.0
    # cached second call must not count a compile
    fresh(jnp.ones(5)).block_until_ready()
    assert obd.compile_count() == after
